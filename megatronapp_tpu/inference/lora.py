"""Multi-tenant batched-LoRA serving: adapter registry + HBM LRU cache.

The production-scale scenario from the ROADMAP: thousands of fine-tuned
tenants served from ONE fleet — one resident (optionally int8) base
model, per-request low-rank adapters batched into every decode step as
``base(x) + B_i A_i x``. This module owns the host-side half of that
subsystem:

- :class:`LoraAdapter` — one tenant's ``{A, B}`` pair per
  RESIDENT_KERNELS target (q/kv/out/fc1/fc2), stacked over layers.
  Loads from disk (``<lora_dir>/<adapter_id>.npz``, optionally stored
  PTQ-int8 via quantization.quantize_leaf) or from an in-memory
  registry (tests, programmatic serving).
- :class:`AdapterRegistry` — the fetch source the cache misses into.
- :class:`AdapterCache` — a fixed number of HBM-resident adapter slots
  per target, stacked into per-target BANK arrays
  ``A[L, slots, din, rank]`` / ``B[L, slots, rank, dout]`` so the
  decode jit gathers per-row adapter weights by bank slot (the same
  shape discipline as the paged KV pools: fixed allocation, functional
  row updates, per-row integer indirection). Slot 0 is the permanent
  NULL adapter (all zeros) — rows without an adapter index it and get
  an exactly-zero delta. Slots 1..R are managed with the SAME
  refcount / LRU-evict / audit discipline as ``PagedKVCache`` blocks:
  an in-use adapter can never be evicted, rc==0 residents park in LRU
  order and stay hittable, ``audit()`` proves the books are an exact
  partition after every step.

The device-side half — the segmented batched-LoRA GEMM with
scalar-prefetched per-row adapter ids, its jnp oracle, the eager
fallback, and the megakernel epilogues — lives in
ops/pallas/kernel_gen.py (``lora_delta`` and friends).

Chaos site ``lora-load`` fires between the registry fetch and the bank
commit: the drill (tests/test_resilience.py) proves a mid-load fault
leaves the cache books untouched and the engine admission rollback
requeues the request.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from collections import OrderedDict, deque
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from megatronapp_tpu.inference.quantization import (
    RESIDENT_KERNELS, dequantize_leaf, is_quantized_leaf, quantize_leaf,
)
from megatronapp_tpu.utils import chaos
from megatronapp_tpu.utils import metrics as telemetry

logger = logging.getLogger(__name__)

# The serving-LoRA targets are exactly the kernels that can stay
# int8-resident: the adapters ride on top of whatever form the base
# weights are in (bf16 or resident int8), which is what makes the
# one-resident-base + many-adapters HBM math work.
LORA_TARGETS = RESIDENT_KERNELS


def lora_target_dims(cfg) -> Dict[str, Tuple[int, int]]:
    """(din, dout) per LoRA target for this config — the A factor is
    [din, rank], the B factor [rank, dout], matching the base kernels'
    [din, dout] exactly (the delta adds into the SAME matmul output,
    before bias)."""
    if getattr(cfg, "multi_latent_attention", False):
        raise ValueError(
            "LoRA serving targets the standard GQA projection kernels "
            "(q/kv/out); multi-latent attention factors attention "
            "through latent kernels with no q_kernel/kv_kernel leaves "
            "— serve MLA models without --lora-dir")
    from megatronapp_tpu.ops.activations import is_gated
    h = cfg.hidden_size
    d = cfg.head_dim
    nq, nkv = cfg.num_attention_heads, cfg.num_query_groups
    f = cfg.ffn_hidden_size
    fc1_out = 2 * f if is_gated(cfg.activation) else f
    return {
        "q_kernel": (h, nq * d),
        "kv_kernel": (h, 2 * nkv * d),
        "out_kernel": (nq * d, h),
        "fc1_kernel": (h, fc1_out),
        "fc2_kernel": (f, h),
    }


def adapter_nbytes(cfg, rank: int, num_layers: Optional[int] = None,
                   itemsize: int = 4) -> int:
    """Rank-exact HBM bytes of ONE adapter: sum over targets of
    L*(din + dout)*rank*itemsize. This is the number /stats and the
    bench gate report — what an adapter actually costs, not the bank
    allocation granularity."""
    layers = num_layers if num_layers is not None else cfg.num_layers
    total = 0
    for din, dout in lora_target_dims(cfg).values():
        total += layers * (din + dout) * rank * itemsize
    return total


@dataclasses.dataclass
class LoraAdapter:
    """One tenant's adapter: per-target A [L, din, rank] and
    B [L, rank, dout] float32 stacks (layer-stacked like the base
    params pytree, so the cache banks scan with the layer scan)."""
    adapter_id: str
    rank: int
    a: Dict[str, np.ndarray]
    b: Dict[str, np.ndarray]

    @property
    def nbytes(self) -> int:
        """Rank-exact byte footprint of this adapter's factors."""
        return int(sum(v.nbytes for v in self.a.values())
                   + sum(v.nbytes for v in self.b.values()))

    @classmethod
    def random(cls, adapter_id: str, cfg, rank: int, *, seed: int = 0,
               num_layers: Optional[int] = None, scale: float = 0.05,
               zero_b: bool = False) -> "LoraAdapter":
        """A reproducible random adapter (tests, benchmarks). A is
        scaled ~1/sqrt(din) (standard LoRA init); B is small random —
        or exactly zero with zero_b=True, which makes the adapted
        stream provably identical to the base model (the zero-B parity
        gate)."""
        rng = np.random.default_rng(seed)
        layers = num_layers if num_layers is not None else cfg.num_layers
        a, b = {}, {}
        for t, (din, dout) in lora_target_dims(cfg).items():
            a[t] = (rng.standard_normal((layers, din, rank))
                    / np.sqrt(din)).astype(np.float32)
            if zero_b:
                b[t] = np.zeros((layers, rank, dout), np.float32)
            else:
                b[t] = (rng.standard_normal((layers, rank, dout))
                        * scale).astype(np.float32)
        return cls(adapter_id, rank, a, b)

    def save(self, lora_dir: str, *, quantize: bool = False) -> str:
        """Write ``<lora_dir>/<adapter_id>.npz``. quantize=True stores
        the factors PTQ-int8 (quantization.quantize_leaf per stack —
        half the disk/transfer bytes; load() dequantizes), mirroring
        how the base model ships."""
        os.makedirs(lora_dir, exist_ok=True)
        path = os.path.join(lora_dir, f"{self.adapter_id}.npz")
        payload = {"rank": np.int32(self.rank)}
        for t in LORA_TARGETS:
            for side, stack in (("a", self.a[t]), ("b", self.b[t])):
                key = f"{t}.{side}"
                if quantize:
                    q = quantize_leaf(stack)
                    payload[key + ".q"] = q["q"]
                    payload[key + ".scale"] = q["scale"]
                else:
                    payload[key] = stack
        np.savez(path, **payload)
        return path

    @classmethod
    def load(cls, lora_dir: str, adapter_id: str) -> "LoraAdapter":
        """Read an adapter saved by save() (plain or PTQ-int8)."""
        path = os.path.join(lora_dir, f"{adapter_id}.npz")
        with np.load(path) as z:
            rank = int(z["rank"])
            a, b = {}, {}
            for t in LORA_TARGETS:
                for side, dest in (("a", a), ("b", b)):
                    key = f"{t}.{side}"
                    if key in z:
                        dest[t] = np.asarray(z[key], np.float32)
                    else:
                        entry = {"__quant__": "int8", "q": z[key + ".q"],
                                 "scale": z[key + ".scale"],
                                 "dtype": "float32"}
                        assert is_quantized_leaf(entry)
                        dest[t] = np.asarray(dequantize_leaf(entry),
                                             np.float32)
        return cls(adapter_id, rank, a, b)


class AdapterRegistry:
    """Where cache misses fetch from: in-memory adapters registered by
    tests/benchmarks, plus an optional ``lora_dir`` of .npz files
    (in-memory wins on collision). Unknown ids raise KeyError with the
    known population — that is a PERMANENT error the engine rejects at
    submit time, never a retry loop."""

    def __init__(self, lora_dir: Optional[str] = None):
        self.lora_dir = lora_dir
        self._mem: Dict[str, LoraAdapter] = {}

    def register(self, adapter: LoraAdapter) -> None:
        self._mem[adapter.adapter_id] = adapter

    def ids(self):
        known = set(self._mem)
        if self.lora_dir and os.path.isdir(self.lora_dir):
            for fn in os.listdir(self.lora_dir):
                if fn.endswith(".npz"):
                    known.add(fn[:-4])
        return sorted(known)

    def __contains__(self, adapter_id: str) -> bool:
        if adapter_id in self._mem:
            return True
        return bool(
            self.lora_dir
            and os.path.exists(os.path.join(self.lora_dir,
                                            f"{adapter_id}.npz")))

    def get(self, adapter_id: str) -> LoraAdapter:
        if adapter_id in self._mem:
            return self._mem[adapter_id]
        if self.lora_dir:
            path = os.path.join(self.lora_dir, f"{adapter_id}.npz")
            if os.path.exists(path):
                return LoraAdapter.load(self.lora_dir, adapter_id)
        raise KeyError(
            f"unknown adapter {adapter_id!r}; registry knows "
            f"{self.ids() or '[] (empty)'}")


class AdapterSlotsPinned(RuntimeError):
    """Every resident slot is refcount-pinned by in-flight requests —
    a TRANSIENT capacity condition (the admission loop waits for a
    retirement to release one), unlike KeyError (unknown adapter,
    permanent)."""


class AdapterCache:
    """HBM-resident LoRA banks with PagedKVCache's pin/evict/audit
    discipline over ``max_resident`` adapter slots.

    Banks are per-target stacked arrays A[L, slots, din, rank] /
    B[L, slots, rank, dout] where slots = max_resident + 1 and slot 0
    is the permanent all-zero NULL adapter (rows without an adapter
    gather it and add an exactly-zero delta — the decode jit's shape
    never depends on which rows have adapters). acquire() returns the
    bank slot for an adapter id, loading it on miss (free slot first,
    then LRU-evicting an unpinned resident); release() unpins. The
    invariants audit() proves after every step:

    - slots 1..R are an exact partition: free ∪ resident,
    - every rc==0 resident is LRU-parked (and only those),
    - slot 0 is never free, never tabled, never refcounted.
    """

    def __init__(self, cfg, registry: AdapterRegistry, *,
                 max_resident: int = 8, rank: int = 8,
                 num_layers: Optional[int] = None, dtype=jnp.float32):
        if max_resident < 1:
            raise ValueError(
                f"max_resident must be >= 1, got {max_resident}")
        if rank < 1:
            raise ValueError(f"rank must be >= 1, got {rank}")
        self.cfg = cfg
        self.registry = registry
        self.rank = int(rank)
        self.max_resident = int(max_resident)
        self.slots = self.max_resident + 1            # + NULL slot 0
        self.num_layers = (num_layers if num_layers is not None
                           else cfg.num_layers)
        self.dtype = dtype
        self.dims = lora_target_dims(cfg)
        self.banks: Dict[str, Tuple[jnp.ndarray, jnp.ndarray]] = {
            t: (jnp.zeros((self.num_layers, self.slots, din, self.rank),
                          dtype),
                jnp.zeros((self.num_layers, self.slots, self.rank, dout),
                          dtype))
            for t, (din, dout) in self.dims.items()
        }
        self._free: deque = deque(range(1, self.slots))
        self._table: Dict[str, int] = {}              # adapter_id -> slot
        self._slot_id: Dict[int, str] = {}            # slot -> adapter_id
        self._refcount = np.zeros((self.slots,), np.int64)
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        self.stats = {"hits": 0, "misses": 0, "evictions": 0,
                      "load_faults": 0}

    # ---- byte accounting --------------------------------------------------
    @property
    def adapter_nbytes(self) -> int:
        """Rank-exact bytes of ONE resident adapter (what an adapter
        costs, independent of the bank allocation)."""
        return adapter_nbytes(self.cfg, self.rank,
                              num_layers=self.num_layers,
                              itemsize=jnp.dtype(self.dtype).itemsize)

    def resident_bytes(self) -> int:
        """Rank-exact bytes of the CURRENTLY resident adapters."""
        return len(self._table) * self.adapter_nbytes

    def bank_bytes(self) -> int:
        """Full HBM allocation of the banks (capacity, incl. slot 0)."""
        return int(sum(a.nbytes + b.nbytes
                       for a, b in self.banks.values()))

    # ---- lookup -----------------------------------------------------------
    def slot_of(self, adapter_id: str) -> Optional[int]:
        return self._table.get(adapter_id)

    def resident_ids(self):
        return sorted(self._table)

    # ---- acquire / release ------------------------------------------------
    def _validate(self, adapter: LoraAdapter) -> None:
        if adapter.rank != self.rank:
            raise ValueError(
                f"adapter {adapter.adapter_id!r} has rank "
                f"{adapter.rank} but the cache banks are sized for "
                f"rank {self.rank} (--lora-rank)")
        for t, (din, dout) in self.dims.items():
            want_a = (self.num_layers, din, self.rank)
            want_b = (self.num_layers, self.rank, dout)
            got_a = tuple(adapter.a[t].shape)
            got_b = tuple(adapter.b[t].shape)
            if got_a != want_a or got_b != want_b:
                raise ValueError(
                    f"adapter {adapter.adapter_id!r} target {t}: A/B "
                    f"shapes {got_a}/{got_b} do not match this model's "
                    f"{want_a}/{want_b}")

    def _take_free(self) -> int:
        if self._free:
            return self._free.popleft()
        if self._lru:
            slot, _ = self._lru.popitem(last=False)   # least recent
            evicted = self._slot_id.pop(slot)
            del self._table[evicted]
            self.stats["evictions"] += 1
            telemetry.inc("lora_cache_evictions")
            return slot
        raise AdapterSlotsPinned(
            f"all {self.max_resident} resident adapter slots are "
            f"pinned by in-flight requests — waiting for a retirement "
            f"(raise --max-resident-adapters to run more distinct "
            f"adapters concurrently)")

    def acquire(self, adapter_id: Optional[str]) -> int:
        """Pin an adapter resident and return its bank slot (0 for
        None). Miss path: fetch from the registry, take a slot (free
        first, else LRU-evict an unpinned resident), write the banks,
        commit the books. Exception-safe: a fault anywhere before the
        commit (the ``lora-load`` chaos site fires between fetch and
        commit) leaves every book untouched."""
        if adapter_id is None:
            return 0
        slot = self._table.get(adapter_id)
        if slot is not None:
            self.stats["hits"] += 1
            telemetry.inc("lora_cache_hits")
            self._refcount[slot] += 1
            self._lru.pop(slot, None)
            return slot
        self.stats["misses"] += 1
        telemetry.inc("lora_cache_misses")
        adapter = self.registry.get(adapter_id)       # may KeyError
        self._validate(adapter)
        try:
            # The drill window: the adapter bytes were fetched but
            # nothing is committed — a fault here must leave free/LRU/
            # refcount/table exactly as they were (no slot consumed, no
            # resident evicted for a load that never landed).
            chaos.fire("lora-load")
        except BaseException:
            self.stats["load_faults"] += 1
            raise
        slot = self._take_free()
        dt = self.dtype
        new_banks = {}
        for t, (a_bank, b_bank) in self.banks.items():
            new_banks[t] = (
                a_bank.at[:, slot].set(
                    jnp.asarray(adapter.a[t], dt)),
                b_bank.at[:, slot].set(
                    jnp.asarray(adapter.b[t], dt)),
            )
        # Commit point: banks + books move together.
        self.banks = new_banks
        self._table[adapter_id] = slot
        self._slot_id[slot] = adapter_id
        self._refcount[slot] = 1
        return slot

    def release(self, slot: int) -> None:
        """Unpin one reference to a bank slot (0 is a no-op — the NULL
        adapter is never refcounted). rc==0 residents park in the LRU
        (still hittable) rather than freeing — the next acquire of the
        same id is a hit."""
        slot = int(slot)
        if slot == 0:
            return
        assert slot in self._slot_id, f"release of untabled slot {slot}"
        self._refcount[slot] -= 1
        assert self._refcount[slot] >= 0, (
            f"negative refcount on adapter slot {slot}")
        if self._refcount[slot] == 0:
            self._lru[slot] = None

    # ---- invariants -------------------------------------------------------
    def audit(self) -> None:
        """Assert the exact-partition invariants (run after every step
        in tests — same discipline as PagedKVCache.audit)."""
        used = set(self._table.values())
        free = set(self._free)
        assert len(self._free) == len(free), "duplicate free slots"
        assert 0 not in used and 0 not in free, (
            "NULL slot 0 leaked into the managed books")
        assert not (used & free), f"slots both used and free: {used & free}"
        assert used | free == set(range(1, self.slots)), (
            f"slots 1..{self.slots - 1} are not an exact partition: "
            f"used={sorted(used)} free={sorted(free)}")
        assert used == set(self._slot_id), "table/slot_id out of sync"
        for aid, slot in self._table.items():
            assert self._slot_id[slot] == aid, (
                f"slot {slot} maps back to {self._slot_id[slot]!r}, "
                f"not {aid!r}")
        assert set(self._lru) <= used, "LRU entry for a non-resident slot"
        for slot in used:
            rc = int(self._refcount[slot])
            assert rc >= 0, f"negative refcount on slot {slot}"
            assert (slot in self._lru) == (rc == 0), (
                f"slot {slot} rc={rc} LRU-parked={slot in self._lru}")
        for slot in free:
            assert self._refcount[slot] == 0, (
                f"free slot {slot} still refcounted")
        assert self._refcount[0] == 0, "NULL slot 0 refcounted"

    def stats_snapshot(self) -> Dict:
        return {
            "rank": self.rank,
            "capacity": self.max_resident,
            "resident": len(self._table),
            "pinned": int(np.count_nonzero(self._refcount[1:])),
            "resident_ids": self.resident_ids(),
            "adapter_bytes": self.adapter_nbytes,
            "resident_bytes": self.resident_bytes(),
            "bank_bytes": self.bank_bytes(),
            **self.stats,
        }


# ---- per-tenant SLO classes ----------------------------------------------
# Composes with the PR-8 scheduler: the engine orders admission and
# preemption by (priority, request_id) — a tenant's SLO class shifts the
# priority every one of its requests carries and supplies a default
# deadline, WITHOUT a second scheduling mechanism.
SLO_CLASSES: Dict[str, Dict] = {
    "premium": {"priority_offset": -1, "deadline_s": None},
    "standard": {"priority_offset": 0, "deadline_s": None},
    "batch": {"priority_offset": 1, "deadline_s": None},
}


class TenantSLO:
    """tenant -> SLO class mapping with (priority, deadline)
    composition. Unknown tenants get ``default_class``."""

    def __init__(self, default_class: str = "standard"):
        if default_class not in SLO_CLASSES:
            raise ValueError(
                f"unknown SLO class {default_class!r}; known: "
                f"{sorted(SLO_CLASSES)}")
        self.default_class = default_class
        self._classes: Dict[str, str] = {}

    def assign(self, tenant: str, slo_class: str) -> None:
        if slo_class not in SLO_CLASSES:
            raise ValueError(
                f"unknown SLO class {slo_class!r}; known: "
                f"{sorted(SLO_CLASSES)}")
        self._classes[tenant] = slo_class

    def class_of(self, tenant: Optional[str]) -> str:
        if tenant is None:
            return self.default_class
        return self._classes.get(tenant, self.default_class)

    def compose(self, tenant: Optional[str], priority: int = 0,
                deadline_s: Optional[float] = None
                ) -> Tuple[int, Optional[float]]:
        """Effective (priority, deadline_s) for a request: the tenant
        class's priority offset ADDS to the caller's priority (lower =
        more important, so premium outranks same-priority standard in
        the (priority, rid) order), and the class deadline applies only
        when the caller set none."""
        cls = SLO_CLASSES[self.class_of(tenant)]
        eff_priority = priority + cls["priority_offset"]
        eff_deadline = deadline_s
        if eff_deadline is None and cls["deadline_s"] is not None:
            import time
            eff_deadline = time.monotonic() + cls["deadline_s"]
        return eff_priority, eff_deadline
