"""Fleet serving: multi-replica router with KV-affinity admission, live
session migration, and drain-aware replica lifecycle (ISSUE 14).

Everything below a single engine is built (paged quantized KV, SLO
admission, disagg handoff, telemetry percentiles); this module is the
layer ABOVE it: N engine replicas (each a `DynamicInferenceEngine` or
`DisaggServingEngine` on its own sub-mesh/device slice) behind ONE
router that presents the same stepping surface as a single engine — the
server's `DynamicBatchingDriver` and every /stats, /healthz, /metrics
endpoint serve a fleet unchanged. The reference's MegaFBD virtual-rank
coordinator (PAPER.md §MegaFBD) is the blueprint: the coordinator owns
PLACEMENT (admission, migration, drain order), the replicas own
EXECUTION (their step loops are untouched).

Admission scores every live replica and admits to the argmax of

    affinity_tokens                       (prefix-cache affinity)
  - queue_weight    * load                (queue depth + active slots)
  - pressure_weight * pool_pressure      (blocks_in_use / num_blocks)
  + slo_weight      * attainment          (histogram-backed SLO signal)

- **Affinity** comes from the pool's rolling full-block prefix hashes
  (`paged_cache.prefix_block_keys` — the SAME hashing the prefix cache
  uses, so router hits == pool hits by construction). Each replica's
  pool feeds prefix-INSERT events into a bounded hash→replica map; a new
  prompt's leading-block hash chain is walked against it and each
  matched block counts block_size affinity tokens. A replica whose pool
  flushes (rolling reload) fires its flush listener and the router drops
  its entries — a swapped replica can never be steered to for
  stale-weight "hits" (the ISSUE 14 small-fix satellite, made structural
  rather than call-site-dependent).
- **Load/pressure** read the engine facades directly (waiting + staged +
  active, pool occupancy) — the same numbers `stats_snapshot()` reports.
- **Attainment** reads each ENGINE'S own always-on decode-interval
  Histogram (utils/metrics.py, the PR-12 primitive — the disagg
  coordinator has carried one since PR 12, the plain engine grows one
  here): the fraction of back-to-back decode intervals within `slo_ms`
  (1.0 while no SLO is set). The router never times its own step loop
  for this — it steps replicas serially, so loop timing would measure
  the whole fleet round and inflate every replica's "interval" by the
  fleet size.

Rebalancing is LIVE SESSION MIGRATION — the PR-8/10 disagg handoff
generalized cross-pool: `PagedKVCache.export_slot` ships the stored
(possibly int8/fp8-quantized) KV rows + scales VERBATIM, the Request
object carries the sampler fold_in chain position, and
`import_slot` scatters the bytes into fresh blocks on the destination —
so a migrated greedy OR sampled stream continues token-exact (pinned in
tests/test_fleet.py for every KV dtype). Replica overload, replica
death, and fleet-wide rolling reloads all reduce to "export → re-admit
elsewhere":

- **Overload**: a replica with queued work and no free slots hands one
  running session to an underloaded same-params-version replica
  (bounded per step).
- **Death**: a replica whose step() raises is marked DEAD and every
  session it held fails over — running ones lose their KV (the pool
  died with the replica) and re-enter another replica's queue with
  prompt+generated intact, so they resume exactly like a preemption
  (the unified ragged prefill/decode step, arXiv 2604.15464, makes
  "resume anywhere" the same code path as admission). Zero sessions
  lost; greedy streams stay exact.
- **Rolling reload** (`begin_rolling_reload`): replicas drain ONE at a
  time — admission pauses on the draining replica, its running sessions
  migrate out (or finish), `set_params` swaps (flushing pool prefix
  cache AND router affinity), admission resumes, next replica. The
  fleet never stops admitting; migration only pairs replicas on the
  same params version so a half-rolled fleet cannot mix weights within
  one stream.

The policy layer on top is `MeshSplitAutoscaler`: per-replica EWMAs of
SLO attainment and prefill-queue depth recommend moving devices between
a disagg replica's prefill and decode sub-meshes
(`split_serving_meshes(prefill_devices=...)`); the router applies a
recommendation by draining the replica and rebuilding it through its
`engine_factory` with the new split — the same drain machinery as
reload and death-replacement.

The chaos site "fleet-migrate" fires between KV export and destination
import; because export is read-only and import is all-or-nothing, the
failed migration leaves BOTH pools audit-clean and the session decoding
on the source (drilled in tests/test_resilience.py).
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

import numpy as np

from megatronapp_tpu.inference.paged_cache import (
    FleetPrefixStore, cdiv, prefix_block_keys,
)
from megatronapp_tpu.trace.request_trace import (
    DECODE_PID, PREFILL_PID, get_request_tracer,
)
from megatronapp_tpu.utils import chaos
from megatronapp_tpu.utils import metrics as telemetry
from megatronapp_tpu.utils.metrics import Ewma, Histogram

logger = logging.getLogger(__name__)

# Replica lifecycle states.
ACTIVE = "active"        # admitting + stepping
DRAINING = "draining"    # stepping, admission paused (reload/rebuild)
DEAD = "dead"            # step() raised; sessions failed over


@dataclasses.dataclass
class Replica:
    """One engine replica + the router-side state attached to it."""
    idx: int
    engine: object
    state: str = ACTIVE
    params_version: int = 0
    reloads: int = 0
    steps: int = 0
    # Pending autoscale rebuild kwargs (engine_factory hints), applied
    # once the replica drains.
    rebuild_hints: Optional[dict] = None

    def attainment(self, slo_ms: Optional[float],
                   default: float = 1.0) -> float:
        """Histogram-backed SLO attainment (the PR-12 primitive) read
        from the ENGINE'S OWN decode-interval histogram — the router
        steps every replica serially, so timing its own loop would
        measure the whole fleet round, inflating every replica's
        'interval' by the fleet size. Both engine types keep a private
        always-on interval_hist (disagg coordinator since PR 12, the
        plain engine since this PR)."""
        hist = getattr(self.engine, "interval_hist", None)
        if hist is None or slo_ms is None or not hist.count:
            return default
        return hist.fraction_below(slo_ms)

    def interval_hist(self) -> Optional[Histogram]:
        return getattr(self.engine, "interval_hist", None)


class MeshSplitAutoscaler:
    """EWMA-attainment-driven prefill/decode mesh-split policy (the
    tentpole's policy layer). Consumes the router's per-replica signals
    — decode-SLO attainment and prefill-queue depth — as EWMAs and
    recommends a new prefill-device count for a disagg replica:

    - attainment below `target` with devices to spare on the prefill
      side → shrink prefill by one tp group (decode is the bottleneck);
    - attainment healthy but the prefill queue persistently deep →
      grow prefill by one tp group (TTFT is the bottleneck).

    Recommendations are rate-limited per replica (`cooldown` recommend
    calls) so one noisy window cannot thrash the split; applying one
    costs a full replica drain + rebuild."""

    def __init__(self, target_attainment: float = 0.9,
                 queue_high: float = 1.0, alpha: float = 0.3,
                 cooldown: int = 32, min_groups: int = 1):
        self.target = target_attainment
        self.queue_high = queue_high
        self.alpha = alpha
        self.cooldown = cooldown
        self.min_groups = min_groups
        self._att: Dict[int, Ewma] = {}
        self._queue: Dict[int, Ewma] = {}
        self._cool: Dict[int, int] = {}

    def observe(self, idx: int, attainment: float, prefill_waiting: int):
        self._att.setdefault(idx, Ewma(self.alpha)).observe(attainment)
        self._queue.setdefault(idx, Ewma(self.alpha)).observe(
            float(prefill_waiting))

    def recommend(self, idx: int, prefill_devices: int,
                  decode_devices: int, tp: int = 1) -> Optional[int]:
        """New prefill-device count, or None (keep the split)."""
        cool = self._cool.get(idx, 0)
        if cool > 0:
            self._cool[idx] = cool - 1
            return None
        att = self._att.get(idx)
        if att is None or att.value is None:
            return None
        q = self._queue.get(idx)
        q_depth = 0.0 if q is None or q.value is None else q.value
        if (att.value < self.target
                and prefill_devices - tp >= self.min_groups * tp):
            self._cool[idx] = self.cooldown
            return prefill_devices - tp
        if (att.value >= self.target and q_depth > self.queue_high
                and decode_devices - tp >= self.min_groups * tp):
            self._cool[idx] = self.cooldown
            return prefill_devices + tp
        return None


class FleetRouter:
    """Multi-replica serving router (module docstring). Drop-in for a
    single engine behind `DynamicBatchingDriver`: same
    add_request/step/has_work/abort/stats surface; one rid space spans
    the fleet (every replica draws from the router's shared counter, so
    the driver's per-rid bookkeeping never collides across replicas).

    Construct with ready-made `engines` or with an `engine_factory`
    (`factory(idx, **hints) -> engine`) — the factory additionally
    enables dead-replica replacement (`revive_replica`) and autoscale
    rebuilds. All replicas must share block_size and kv_cache_dtype
    (migration ships stored KV bytes verbatim between their pools)."""

    def __init__(self, engines: Optional[List] = None,
                 engine_factory: Optional[Callable] = None,
                 num_replicas: int = 2, policy: str = "affinity",
                 migrate: bool = True, autoscale: bool = False,
                 slo_ms: Optional[float] = None,
                 affinity_capacity: int = 8192,
                 max_migrations_per_step: int = 1,
                 queue_weight: Optional[float] = None,
                 pressure_weight: Optional[float] = None,
                 slo_weight: Optional[float] = None,
                 prefix_store_mb: float = 0.0):
        assert policy in ("affinity", "round_robin"), policy
        if engines is None:
            assert engine_factory is not None, (
                "FleetRouter needs engines or an engine_factory")
            engines = [engine_factory(i) for i in range(num_replicas)]
        assert engines, "FleetRouter needs at least one replica"
        self.engine_factory = engine_factory
        # ONE rid space across the fleet: every replica's engine draws
        # request ids from this shared counter.
        self._ids = itertools.count()
        self.replicas = [Replica(i, e) for i, e in enumerate(engines)]
        for rep in self.replicas:
            self._wire(rep)
        pools = [rep.engine.pool for rep in self.replicas]
        block_sizes = {p.block_size for p in pools}
        dtypes = {p.kv_cache_dtype for p in pools}
        if len(block_sizes) != 1 or len(dtypes) != 1:
            raise ValueError(
                "fleet replicas must share block_size and kv_cache_dtype "
                f"(got block sizes {sorted(block_sizes)}, dtypes "
                f"{sorted(dtypes)}): affinity hashes and migrated KV "
                "bytes cross pools verbatim")
        self.block_size = block_sizes.pop()
        self.kv_cache_dtype = dtypes.pop()
        self.policy = policy
        self.migrate = migrate
        self.slo_ms = slo_ms
        self.max_migrations_per_step = max_migrations_per_step
        # Scoring weights in affinity-token units: one queued/active
        # request outweighs ~2 cached blocks, a full pool ~4, a fully
        # attained SLO ~2 — affinity dominates only between comparably
        # loaded replicas.
        self.queue_weight = (2.0 * self.block_size if queue_weight is None
                             else queue_weight)
        self.pressure_weight = (4.0 * self.block_size
                                if pressure_weight is None
                                else pressure_weight)
        self.slo_weight = (2.0 * self.block_size if slo_weight is None
                           else slo_weight)
        self.tokenizer = self.replicas[0].engine.tokenizer
        self.max_batch = sum(r.engine.max_batch for r in self.replicas)
        self.paged = True
        self.pause_admission = False        # driver-facade compat
        # Bounded hash→replica affinity map (LRU past capacity).
        self.affinity_capacity = affinity_capacity
        self._affinity: OrderedDict = OrderedDict()
        # Bounded tenant/adapter→replica affinity (ISSUE 19): steering a
        # tenant's requests back to the replica whose AdapterCache
        # already holds its adapter avoids an HBM bank write (and a
        # possible eviction of someone else's pinned working set) per
        # admission. Same bounded-OrderedDict machinery as the prefix
        # map; an adapter reload costs far more than a prefix-block
        # re-prefill, so its weight defaults higher.
        self.tenant_affinity_capacity = 1024
        self._tenant_affinity: OrderedDict = OrderedDict()
        self.tenant_weight = 8.0 * self.block_size
        self._owner: Dict[int, int] = {}    # rid -> replica idx
        self._lock = threading.RLock()
        self._rr = 0                        # round-robin cursor
        self._version = 0                   # fleet params version target
        self._reload = None                 # rolling-reload state
        self._params = None                 # latest reloaded params
        self.autoscaler = MeshSplitAutoscaler() if autoscale else None
        # Fleet-global prefix store (ISSUE 20): exported prefix-block
        # payloads keyed by the same rolling hashes as the affinity
        # map — a replica that misses a hot prefix locally gathers the
        # blocks from the store at admission instead of recomputing the
        # prefill (in-process flavor; fleet_rpc.py ships the same
        # payloads over the prefix_put/prefix_get verbs).
        self.prefix_store = (FleetPrefixStore(int(prefix_store_mb
                                                  * (1 << 20)))
                             if prefix_store_mb else None)
        self.router_stats = {
            "migrations": 0, "migration_failures": 0,
            "migrated_kv_bytes": 0, "failovers": 0, "replica_deaths": 0,
            "reloads": 0, "replica_reloads": 0, "autoscale_rebuilds": 0,
            "autoscale_aborts": 0, "affinity_admissions": 0,
            "tenant_affinity_admissions": 0, "admissions": 0,
            "prefix_store_admission_hits": 0,
            "prefix_store_seeded_blocks": 0,
            "prefix_store_seeded_bytes": 0,
            "prefill_chunks_avoided": 0,
        }
        self._rt = get_request_tracer()
        # Fleet process rows aggregate every replica's events (spans
        # carry replica indices in their args; migrate-out/in instants
        # mark the hop) — label the rows so trace readers know.
        self._rt.set_process_name(DECODE_PID, "decode-mesh (fleet)")
        self._rt.set_process_name(PREFILL_PID, "prefill-mesh (fleet)")
        self._supervisor = None             # lazy (see .supervisor)

    # ---- replica wiring --------------------------------------------------
    def _wire(self, rep: Replica):
        """Attach a (new) engine to the router: shared rid counter +
        pool prefix/flush listeners feeding the affinity map."""
        eng = rep.engine
        inner = getattr(eng, "engine", eng)   # disagg facade → inner
        inner._ids = self._ids
        idx = rep.idx
        eng.pool.prefix_listener = (
            lambda keys, _i=idx: self._note_prefixes(_i, keys))
        eng.pool.flush_listener = lambda _i=idx: self._flush_replica(_i)

    def _note_prefixes(self, idx: int, keys: List[bytes]):
        with self._lock:
            for key in keys:
                self._affinity[key] = idx
                self._affinity.move_to_end(key)
            while len(self._affinity) > self.affinity_capacity:
                self._affinity.popitem(last=False)
        if self.prefix_store is not None:
            # Populate the fleet store from the same prefix-insert
            # events: export each NEW block once (host gather), after
            # which every replica serves it from host RAM.
            pool = self.replicas[idx].engine.pool
            for key in keys:
                if self.prefix_store.has(key):
                    continue
                payload = pool.export_prefix_block(key)
                if payload is not None:
                    self.prefix_store.put(key, payload)

    def _flush_replica(self, idx: int):
        """Drop every affinity entry pointing at replica `idx` (its
        prefix cache flushed, or it died)."""
        with self._lock:
            stale = [k for k, v in self._affinity.items() if v == idx]
            for k in stale:
                del self._affinity[k]
        if self.prefix_store is not None:
            # One replica's flush means a params reload is in flight (or
            # it died mid-anything): stored blocks are no longer
            # guaranteed to match the weights every replica will run, so
            # the WHOLE store drops — it repopulates from the next
            # prefix inserts, same as each pool's own prefix cache.
            self.prefix_store.clear()

    def _note_tenant(self, key: Optional[str], idx: int):
        if key is None:
            return
        with self._lock:
            self._tenant_affinity[key] = idx
            self._tenant_affinity.move_to_end(key)
            while (len(self._tenant_affinity)
                   > self.tenant_affinity_capacity):
                self._tenant_affinity.popitem(last=False)

    def _drop_tenant_replica(self, idx: int):
        """Drop tenant/adapter steering entries pointing at replica
        `idx` — its AdapterCache is gone (death) or fresh (rebuild), so
        steering there for residency "hits" would be stale. Prefix
        flushes do NOT call this: the adapter banks survive a params
        reload."""
        with self._lock:
            stale = [k for k, v in self._tenant_affinity.items()
                     if v == idx]
            for k in stale:
                del self._tenant_affinity[k]

    # ---- admission -------------------------------------------------------
    def _replica_load(self, eng) -> int:
        load = len(eng.waiting)
        load += sum(1 for s in eng.slots if s is not None)
        # Disagg facade: staged prefills count as load too.
        load += len(getattr(eng, "_inflight", ()))
        load += len(getattr(eng, "_parked", ()))
        return load

    def _admit_target(self, prompt: np.ndarray,
                      affinity_key: Optional[str] = None
                      ) -> Optional[Replica]:
        live = [r for r in self.replicas if r.state == ACTIVE]
        if not live:
            # Drain window (rolling reload / rebuild with every replica
            # DRAINING): queue on a draining replica rather than
            # erroring — queued work survives a reload in place (the
            # single-engine reload semantics) and rebuilds evacuate
            # their queue. Reload-draining replicas are preferred over
            # rebuild-draining ones (the latter's engine is replaced).
            # Only an all-DEAD fleet has nowhere to queue.
            live = [r for r in self.replicas if r.state == DRAINING
                    and r.rebuild_hints is None]
            live = live or [r for r in self.replicas
                            if r.state == DRAINING]
        if not live:
            return None
        if self.policy == "round_robin":
            rep = live[self._rr % len(live)]
            self._rr += 1
            return rep
        keys = prefix_block_keys(prompt, self.block_size, len(prompt))
        owners = [self._affinity.get(k) for k in keys]
        tenant_home = (None if affinity_key is None
                       else self._tenant_affinity.get(affinity_key))
        best = best_key = None
        best_aff = 0.0
        best_tenant = False
        for rep in live:
            aff = 0.0
            for o in owners:
                if o != rep.idx:
                    break
                aff += self.block_size
            taff = self.tenant_weight if tenant_home == rep.idx else 0.0
            eng = rep.engine
            load = self._replica_load(eng)
            pool = eng.pool
            pressure = pool.blocks_in_use() / pool.num_blocks
            score = (aff + taff
                     - self.queue_weight * load
                     - self.pressure_weight * pressure
                     + self.slo_weight * rep.attainment(self.slo_ms))
            # Deterministic tie-break: least loaded, then lowest index.
            key = (score, -load, -rep.idx)
            if best_key is None or key > best_key:
                best, best_key = rep, key
                best_aff, best_tenant = aff, taff > 0
        if best_aff > 0:
            self.router_stats["affinity_admissions"] += 1
        if best_tenant:
            self.router_stats["tenant_affinity_admissions"] += 1
        return best

    def add_request(self, prompt_tokens, max_new_tokens: int,
                    sampling=None, eod_id: Optional[int] = None,
                    priority: int = 0,
                    deadline_s: Optional[float] = None,
                    adapter_id: Optional[str] = None,
                    tenant: Optional[str] = None) -> int:
        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        # Steering key: the ADAPTER is what's expensive to move between
        # replicas (an HBM bank write on a miss), so it keys the
        # affinity map; a tenant without an adapter still benefits from
        # sticking to one replica (its prefix blocks live there).
        affinity_key = adapter_id if adapter_id is not None else tenant
        extra = {}
        if adapter_id is not None:
            extra["adapter_id"] = adapter_id
        if tenant is not None:
            extra["tenant"] = tenant
        # The WHOLE admission holds the router lock: _fail_replica (the
        # stepper thread) also holds it for its whole failover, so a
        # request can never land in a replica's books between the
        # death snapshot and the DEAD mark — the window that would
        # silently lose a session despite the zero-lost guarantee.
        # (Engine add_request is cheap — validation + a deque append —
        # and the driver already serializes submits under its own cv.)
        with self._lock:
            rep = self._admit_target(prompt, affinity_key)
            if rep is None:
                raise RuntimeError(
                    "fleet has no live replica to admit into (every "
                    "replica is dead — drain windows queue instead)")
            if self.prefix_store is not None:
                self._seed_from_store(rep, prompt)
            rid = rep.engine.add_request(
                prompt, max_new_tokens, sampling, eod_id=eod_id,
                priority=priority, deadline_s=deadline_s, **extra)
            self._owner[rid] = rep.idx
            self._note_tenant(affinity_key, rep.idx)
        self.router_stats["admissions"] += 1
        telemetry.inc("fleet_admissions")
        return rid

    def _seed_from_store(self, rep: Replica, prompt: np.ndarray):
        """Gather this prompt's missing leading prefix blocks from the
        fleet store into the target replica's pool (import_prefix_block
        — rc==0 LRU entries, exactly like a local insert) BEFORE
        admission, so pool.admit() hits them and the chunked prefill
        skips the covered tokens. Prefill-chunks-avoided is exact: the
        chunk counts before/after seeding follow admit()'s own
        cached-token arithmetic (len(leading hits) * block_size, capped
        at p_len - 1 for the CoW case)."""
        store = self.prefix_store
        keys = prefix_block_keys(prompt, self.block_size, len(prompt))
        if not keys:
            return
        eng = rep.engine
        inner = getattr(eng, "engine", eng)   # disagg facade → inner
        pool = eng.pool
        local = 0                  # leading blocks already present
        for k in keys:
            if not pool.has_prefix(k):
                break
            local += 1
        seeded = 0
        chain = local              # leading present-or-seeded blocks
        for k in keys[local:]:
            if pool.has_prefix(k):
                chain += 1
                continue
            payload = store.get(k)         # counts the hit/miss
            if payload is None or not pool.import_prefix_block(
                    k, payload):
                break                      # only a LEADING run helps
            chain += 1
            seeded += 1
            self.router_stats["prefix_store_seeded_blocks"] += 1
            self.router_stats["prefix_store_seeded_bytes"] += (
                payload["nbytes"])
        if not seeded:
            return
        p_len = len(prompt)
        chunk = int(getattr(inner, "prefill_chunk", 32))

        def chunks_at(blocks_cached: int) -> int:
            cached = min(blocks_cached * self.block_size, p_len - 1)
            return cdiv(p_len - cached, chunk)

        avoided = chunks_at(local) - chunks_at(chain)
        self.router_stats["prefix_store_admission_hits"] += 1
        self.router_stats["prefill_chunks_avoided"] += avoided
        telemetry.inc("fleet_prefill_chunks_avoided", avoided)

    # ---- per-request forwarding ------------------------------------------
    def _owner_engine(self, rid: int):
        with self._lock:
            idx = self._owner.get(rid)
        if idx is None:
            return None
        return self.replicas[idx].engine

    def pop_request(self, request_id: int):
        eng = self._owner_engine(request_id)
        req = None if eng is None else eng.pop_request(request_id)
        with self._lock:
            self._owner.pop(request_id, None)
        return req

    def abort_request(self, request_id: int) -> Optional[str]:
        eng = self._owner_engine(request_id)
        return None if eng is None else eng.abort_request(request_id)

    def park_request(self, request_id: int) -> bool:
        """Forward a client park (long-idle session) to the owning
        replica's spill tier; False when the owner has no spill tier
        (disagg facade / spill off) or the session isn't parkable."""
        eng = self._owner_engine(request_id)
        fn = getattr(eng, "park_request", None)
        return bool(fn and fn(request_id))

    def resume_request(self, request_id: int) -> bool:
        eng = self._owner_engine(request_id)
        fn = getattr(eng, "resume_request", None)
        return bool(fn and fn(request_id))

    def expire_overdue(self, now: Optional[float] = None) -> List[int]:
        expired: List[int] = []
        for rep in self.replicas:
            if rep.state != DEAD:
                expired += rep.engine.expire_overdue(now)
        return expired

    def abort_all(self):
        with self._lock:
            self._owner.clear()
        for rep in self.replicas:
            if rep.state == DEAD:
                continue
            try:
                rep.engine.abort_all()
            except Exception:  # noqa: BLE001 — best-effort reclaim
                logger.warning("abort_all failed on replica %d", rep.idx,
                               exc_info=True)

    # ---- facade surface (driver/server) ----------------------------------
    @property
    def has_work(self) -> bool:
        if self._reload is not None:
            return True
        if any(r.rebuild_hints is not None and r.state != DEAD
               for r in self.replicas):
            return True
        return any(r.state != DEAD and r.engine.has_work
                   for r in self.replicas)

    @property
    def slots(self) -> List:
        out: List = []
        for rep in self.replicas:
            if rep.state != DEAD:
                out += list(rep.engine.slots)
        return out

    @property
    def waiting(self) -> List:
        out: List = []
        for rep in self.replicas:
            if rep.state != DEAD:
                out += list(rep.engine.waiting)
        return out

    @property
    def requests(self) -> Dict:
        out: Dict = {}
        for rep in self.replicas:
            if rep.state != DEAD:
                out.update(rep.engine.requests)
        return out

    @property
    def reload_pending(self) -> bool:
        return self._reload is not None

    def reset_compilation(self):
        for rep in self.replicas:
            if rep.state != DEAD:
                rep.engine.reset_compilation()

    def free_decode_slots(self) -> int:
        return sum(rep.engine.free_decode_slots()
                   for rep in self.replicas if rep.state == ACTIVE)

    def drained_for_reload(self) -> bool:
        """Generic-driver compat: True when EVERY live replica is
        drained (the fleet-native path is begin_rolling_reload, which
        never requires this fleet-wide state)."""
        return all(rep.engine.drained_for_reload()
                   for rep in self.replicas if rep.state != DEAD)

    def set_params(self, params):
        """Immediate fleet-wide swap (generic-driver/test path; the
        production path is begin_rolling_reload). Each pool's prefix
        flush fires its listener, so the affinity map empties too."""
        self._version += 1
        self._params = params
        for rep in self.replicas:
            if rep.state == DEAD:
                continue
            rep.engine.set_params(params)
            rep.params_version = self._version
            rep.reloads += 1

    # ---- live session migration ------------------------------------------
    def migrate_request(self, rid: int,
                        dst_idx: Optional[int] = None) -> bool:
        """Move a RUNNING session from its replica to `dst_idx` (or the
        best eligible destination): export → ["fleet-migrate" chaos
        site] → import → source release. Exception-safe by
        construction: export is read-only and import is all-or-nothing,
        so ANY failure in the window leaves the session decoding on the
        source with both pools audit-clean — the retried stream is
        bit-identical because nothing moved."""
        with self._lock:
            src_idx = self._owner.get(rid)
        if src_idx is None:
            return False
        src = self.replicas[src_idx]
        dst = self._pick_destination(src, dst_idx)
        if dst is None:
            return False
        self._rt.begin("migrate", rid, src_replica=src.idx,
                       dst_replica=dst.idx)
        try:
            payload = src.engine.export_request(rid)
            if payload is None:
                return False
            # Chaos site: the worst point — KV exported, destination not
            # yet admitted (a destination death lands exactly here).
            chaos.fire("fleet-migrate")
            if not dst.engine.import_request(payload):
                self.router_stats["migration_failures"] += 1
                return False
        except Exception as e:  # noqa: BLE001 — rollback is "do nothing"
            self.router_stats["migration_failures"] += 1
            telemetry.inc("fleet_migration_failures")
            logger.warning(
                "migration of request %d (replica %d -> %d) failed — "
                "session stays on the source, pools untouched: %s",
                rid, src.idx, dst.idx, e)
            return False
        finally:
            self._rt.end("migrate", rid)
        src.engine.release_exported(rid)
        with self._lock:
            self._owner[rid] = dst.idx
        self.router_stats["migrations"] += 1
        self.router_stats["migrated_kv_bytes"] += payload["nbytes"]
        telemetry.inc("fleet_migrations")
        return True

    def _pick_destination(self, src: Replica,
                          dst_idx: Optional[int]) -> Optional[Replica]:
        """An ACTIVE same-params-version replica with a free decode slot
        and headroom (a half-rolled fleet must never continue a stream
        on different weights)."""
        def eligible(rep: Replica) -> bool:
            if rep is src or rep.state != ACTIVE:
                return False
            if rep.params_version != src.params_version:
                return False
            eng = rep.engine
            if eng.free_decode_slots() == 0:
                return False
            pool = eng.pool
            return pool.blocks_in_use() / pool.num_blocks < 0.9
        if dst_idx is not None:
            rep = self.replicas[dst_idx]
            return rep if eligible(rep) else None
        cands = [r for r in self.replicas if eligible(r)]
        if not cands:
            return None
        return min(cands, key=lambda r: (self._replica_load(r.engine),
                                         r.idx))

    def _migratable_rids(self, rep: Replica) -> List[int]:
        """Requests currently decoding in `rep`'s slots (the only ones
        owning exportable KV), most-remaining-work first."""
        inner = getattr(rep.engine, "engine", rep.engine)
        rids = []
        for req in inner.slots:
            if req is not None and not req.finished and req.generated:
                rids.append((req.max_new_tokens - len(req.generated),
                             req.request_id))
        return [rid for _, rid in sorted(rids, reverse=True)]

    def _rebalance(self) -> None:
        """One step's migration budget: drain DRAINING replicas first,
        then relieve an overloaded ACTIVE replica (queued work, no free
        slot) toward an underloaded one."""
        budget = self.max_migrations_per_step
        for rep in self.replicas:
            if budget <= 0:
                return
            if rep.state != DRAINING:
                continue
            # Evacuate queued work first (no KV — requeue is free).
            self._evacuate_waiting(rep)
            for rid in self._migratable_rids(rep):
                if budget <= 0:
                    return
                if self.migrate and self.migrate_request(rid):
                    budget -= 1
        if not self.migrate or budget <= 0:
            return
        for rep in self.replicas:
            if rep.state != ACTIVE:
                continue
            eng = rep.engine
            if not len(eng.waiting) or eng.free_decode_slots() > 0:
                continue
            for rid in self._migratable_rids(rep):
                if budget <= 0:
                    return
                if self.migrate_request(rid):
                    budget -= 1
                    break   # one relief migration per replica per step

    def _evacuate_waiting(self, rep: Replica):
        """Requeue a draining replica's QUEUED requests onto active
        replicas (they own no KV — a queue move, not a migration).
        Fresh requests (nothing generated yet) may go to any version —
        they run wholly on the destination's weights; preempted ones
        carrying generated tokens are fenced to SAME-params-version
        destinations and otherwise stay queued here (they drain with
        the reload's swap, never mixing weights in one stream). No-op
        when no destination exists; the reload then simply swaps with
        the queue in place (single-replica fleet)."""
        eng = rep.engine
        targets = [r for r in self.replicas
                   if r is not rep and r.state == ACTIVE]
        same_ver = [r for r in targets
                    if r.params_version == rep.params_version]
        if not targets or not len(eng.waiting):
            return
        moved, kept = [], []
        while True:
            try:
                req = eng.waiting.popleft()
            except IndexError:
                break
            if req.finished:
                continue
            if req.generated and not same_ver:
                kept.append(req)       # version-fenced: stays here
                continue
            moved.append(req)
        eng.waiting.extend(kept)
        for i, req in enumerate(moved):
            eng.requests.pop(req.request_id, None)
            pool = same_ver if (same_ver and req.generated) else targets
            self._requeue_on(pool[i % len(pool)], req)

    def _requeue_on(self, rep: Replica, req):
        """Hand a request (no KV) to another replica's queue: both
        engine types re-enter through their waiting deque — the disagg
        facade's is its prefill queue."""
        eng = rep.engine
        req.slot = -1
        req.queued_t = time.monotonic()
        eng.requests[req.request_id] = req
        eng.waiting.append(req)
        with self._lock:
            self._owner[req.request_id] = rep.idx

    # ---- replica failure / replacement -----------------------------------
    def _fail_replica(self, rep: Replica, err: Exception):
        """A replica's step() raised: mark it DEAD and fail every
        session it held over to the survivors. Running sessions lose
        their KV (the pool died with the replica) and resume by
        re-prefilling prompt+generated — the preemption-resume path, so
        greedy streams stay exact and nothing is lost. Holds the router
        lock for the WHOLE failover so a concurrent add_request cannot
        land a session in the dying replica's books mid-snapshot.
        Mid-stream sessions prefer a SAME-params-version survivor
        (tokens already emitted came from this version's weights);
        when a half-rolled fleet leaves none, availability wins over
        version purity — the session continues on a different version
        with a loud log rather than dropping. Raises only when NO live
        replica remains (the driver watchdog then owns it)."""
        logger.warning(
            "fleet replica %d DIED on step (%s) — failing its sessions "
            "over", rep.idx, err)
        with self._lock:
            rep.state = DEAD
            rep.rebuild_hints = None   # a dead engine cannot drain
            self.router_stats["replica_deaths"] += 1
            telemetry.inc("fleet_replica_deaths")
            self._flush_replica(rep.idx)
            self._drop_tenant_replica(rep.idx)
            eng = rep.engine
            orphans = list(eng.requests.items())
            # Failover targets: ACTIVE first, else DRAINING survivors
            # (alive — reload-draining preferred, same tiering as
            # admission; their queue survives the swap). Only an
            # all-DEAD fleet has nowhere to fail over to.
            live = [r for r in self.replicas if r.state == ACTIVE]
            if not live:
                live = [r for r in self.replicas if r.state == DRAINING
                        and r.rebuild_hints is None]
                live = live or [r for r in self.replicas
                                if r.state == DRAINING]
            if not live:
                raise err
            same_ver = [r for r in live
                        if r.params_version == rep.params_version]
            for i, (rid, req) in enumerate(orphans):
                if req.finished:
                    # Finished-but-unpopped results stay fetchable
                    # through the new owner's books.
                    tgt = live[0]
                    tgt.engine.requests[rid] = req
                    self._owner[rid] = tgt.idx
                    continue
                pool = same_ver if (same_ver and req.generated) else live
                if req.generated and not same_ver:
                    logger.warning(
                        "failover of mid-stream request %d crosses "
                        "params versions (no same-version survivor) — "
                        "continuing on v%d", rid,
                        live[0].params_version)
                self._requeue_on(pool[i % len(pool)], req)
                self.router_stats["failovers"] += 1
                telemetry.inc("fleet_failovers")
                self._rt.instant("failover", rid, dead_replica=rep.idx)
            eng.requests.clear()

    @property
    def supervisor(self):
        """The ONE supervisor code path (inference/supervisor.py):
        manual drills (`kill_replica`/`revive_replica`) and the
        threaded poll loop (`.supervisor.start()`) both run the same
        Supervisor policy over an in-process backend, so "playing
        supervisor by hand" and the real watcher cannot drift. The
        cross-process fleet wires the SAME Supervisor over a process
        backend (inference/fleet_rpc.py)."""
        if self._supervisor is None:
            from megatronapp_tpu.inference.supervisor import Supervisor
            self._supervisor = Supervisor(_InProcessBackend(self),
                                          interval=0.5)
        return self._supervisor

    def kill_replica(self, idx: int):
        """Operator/drill entry: treat replica `idx` as dead right now
        (same path a step() exception takes) — routed through the one
        supervisor code path."""
        self.supervisor.kill(idx)

    def revive_replica(self, idx: int, **hints):
        """Replace a DEAD (or rebuild a live, drained) replica —
        routed through the one supervisor code path (the backend's
        relaunch mechanism is `_revive_impl`)."""
        self.supervisor.revive(idx, **hints)

    def _kill_impl(self, idx: int):
        rep = self.replicas[idx]
        if rep.state == DEAD:
            return
        self._fail_replica(rep, RuntimeError("killed by operator"))

    def _revive_impl(self, idx: int, **hints):
        """Rebuild replica `idx` through the engine_factory. The
        factory builds with its captured (startup) params, so when the
        fleet has since rolled to newer weights the rebuilt engine is
        swapped onto them before it serves — a revived replica may
        never claim the current version while holding factory-stale
        weights."""
        assert self.engine_factory is not None, (
            "revive_replica needs an engine_factory")
        # Router lock across the swap: add_request could otherwise
        # admit into the OLD engine's queue between the drained check
        # and the replacement — an orphaned session in a discarded
        # engine (the same mutual exclusion _fail_replica holds).
        with self._lock:
            rep = self.replicas[idx]
            old = rep.engine
            rep.engine = self.engine_factory(idx, **hints)
            self._wire(rep)
            self._drop_tenant_replica(idx)   # fresh AdapterCache
            # Finished-but-unfetched results must survive the engine
            # swap (a client whose done event fired but who has not
            # yet called result_tokens would otherwise get None back)
            # — same transplant _fail_replica does.
            try:
                for rid, req in list(old.requests.items()):
                    if req.finished:
                        rep.engine.requests[rid] = req
            except Exception:  # noqa: BLE001 — a dead engine may refuse
                pass
            if self._params is not None:
                rep.engine.set_params(self._params)
            rep.state = ACTIVE
            rep.params_version = self._version
            rep.rebuild_hints = None

    # ---- drain-aware rolling reload --------------------------------------
    def begin_rolling_reload(self, params) -> threading.Event:
        """Schedule a FLEET-WIDE rolling params swap: replicas drain and
        swap one at a time inside step(), so fleet admission never
        pauses and zero requests drop. Returns an event that fires when
        the LAST replica has swapped. A second call before the roll
        completes supersedes the params and restarts the roll; all
        waiters fire when the latest roll lands."""
        ev = threading.Event()
        with self._lock:
            self._version += 1
            self._params = params
            if self._reload is None:
                self._reload = {"params": params, "events": [ev],
                                "idx": 0}
            else:
                self._reload["params"] = params
                self._reload["idx"] = 0
                self._reload["events"].append(ev)
        return ev

    def _advance_reload(self):
        # The whole advance holds the router lock: begin_rolling_reload
        # (request threads) mutates the same state — without mutual
        # exclusion a superseding reload could append its event in the
        # window between the roll finishing and self._reload clearing,
        # firing a waiter whose params were never applied. Reentrant
        # callbacks (set_params → pool flush → _flush_replica) take the
        # same RLock on this thread.
        with self._lock:
            r = self._reload
            if r is None:
                return
            while r["idx"] < len(self.replicas):
                rep = self.replicas[r["idx"]]
                if (rep.state == DEAD
                        or rep.params_version == self._version):
                    r["idx"] += 1
                    continue
                rep.engine.pause_admission = True
                if rep.state == ACTIVE:
                    rep.state = DRAINING   # _rebalance drains it empty
                if not rep.engine.drained_for_reload():
                    return              # keep stepping; drain continues
                # Preempted requests version-fenced into this queue
                # (no same-version survivor to evacuate to) inevitably
                # resume on the NEW weights after the swap — their
                # already-emitted tokens came from the old ones. Same
                # availability-over-purity tradeoff as the death
                # failover's cross-version path; log as loudly.
                for req in list(rep.engine.waiting):
                    if getattr(req, "generated", None):
                        logger.warning(
                            "reload of replica %d carries queued "
                            "mid-stream request %d across params "
                            "versions (no same-version survivor held "
                            "it)", rep.idx, req.request_id)
                rep.engine.set_params(r["params"])  # flush → affinity
                rep.params_version = self._version
                rep.reloads += 1
                rep.engine.pause_admission = False
                if rep.rebuild_hints is None:
                    rep.state = ACTIVE
                # else: stay DRAINING — a pending autoscale rebuild
                # still owns the drain (its hints would otherwise
                # strand: _advance_rebuilds only acts on DRAINING and
                # has_work would spin on the un-clearable hints).
                r["idx"] += 1
                self.router_stats["replica_reloads"] += 1
                telemetry.inc("fleet_replica_reloads")
            self.router_stats["reloads"] += 1
            events = r["events"]
            self._reload = None
        for ev in events:
            ev.set()

    # ---- autoscaling ------------------------------------------------------
    def _maybe_autoscale(self, rep: Replica):
        if (self.autoscaler is None or self.engine_factory is None
                or rep.state != ACTIVE or rep.rebuild_hints is not None):
            return
        eng = rep.engine
        if not hasattr(eng, "prefill_ctx"):
            return          # the split knob exists on disagg replicas
        self.autoscaler.observe(rep.idx, rep.attainment(self.slo_ms),
                                len(eng.waiting))
        tp = eng.decode_ctx.tp
        target = self.autoscaler.recommend(
            rep.idx, eng.prefill_ctx.num_devices,
            eng.decode_ctx.num_devices, tp=tp)
        if target is None:
            return
        logger.warning(
            "fleet autoscale: replica %d prefill devices %d -> %d "
            "(attainment %.3f, prefill queue %d) — draining for rebuild",
            rep.idx, eng.prefill_ctx.num_devices, target,
            rep.attainment(self.slo_ms), len(eng.waiting))
        rep.rebuild_hints = {"prefill_devices": target}
        rep.state = DRAINING
        rep.engine.pause_admission = True
        telemetry.inc("fleet_autoscale_decisions")

    def _advance_rebuilds(self):
        if self.engine_factory is None:
            return
        # Under the router lock: the drained/empty-queue check and the
        # engine swap must be atomic vs concurrent add_request (which
        # can queue on DRAINING replicas during an all-draining
        # window).
        with self._lock:
            self._advance_rebuilds_locked()

    def _advance_rebuilds_locked(self):
        for rep in self.replicas:
            if rep.rebuild_hints is None or rep.state != DRAINING:
                continue
            eng = rep.engine
            self._evacuate_waiting(rep)
            if len(eng.waiting) and not any(
                    r.state == ACTIVE for r in self.replicas
                    if r is not rep):
                # Queued work with nowhere to evacuate (e.g. a
                # single-replica fleet whose drain window admitted into
                # this queue): a rebuild that waits for an empty queue
                # while admission is paused would livelock. Abort the
                # rebuild — availability beats the split change; the
                # autoscaler will re-recommend once traffic allows.
                logger.warning(
                    "fleet autoscale: aborting replica %d rebuild — "
                    "queued work and no evacuation target", rep.idx)
                rep.rebuild_hints = None
                rep.state = ACTIVE
                eng.pause_admission = False
                self.router_stats["autoscale_aborts"] += 1
                continue
            if not eng.drained_for_reload() or len(eng.waiting):
                continue
            hints = rep.rebuild_hints
            self.revive_replica(rep.idx, **hints)
            self.router_stats["autoscale_rebuilds"] += 1
            telemetry.inc("fleet_autoscale_rebuilds")

    # ---- main loop --------------------------------------------------------
    def step(self) -> Dict[str, List]:
        """One fleet round: advance the rolling reload + pending
        rebuilds, rebalance (drain/overload migrations), then step every
        live replica once and merge their event dicts. A replica whose
        step raises is failed over inside the round — the fleet round
        only raises when no live replica remains."""
        events: Dict[str, List] = {"admitted": [], "tokens": [],
                                   "finished": [], "preempted": [],
                                   "expired": []}
        self._advance_reload()
        self._advance_rebuilds()
        self._rebalance()
        for rep in self.replicas:
            if rep.state == DEAD:
                continue
            eng = rep.engine
            if not eng.has_work:
                continue
            try:
                ev = eng.step()
            except Exception as e:  # noqa: BLE001 — replica fails over
                self._fail_replica(rep, e)
                continue
            rep.steps += 1
            for key in events:
                events[key] += ev.get(key, [])
            self._maybe_autoscale(rep)
        return events

    def run_to_completion(self, token_callback=None) -> Dict[int, np.ndarray]:
        results: Dict[int, np.ndarray] = {}
        finished: Dict[int, object] = {}
        while self.has_work:
            ev = self.step()
            if token_callback is not None:
                for rid, tok in ev["tokens"]:
                    token_callback(rid, tok)
            for rid in ev["finished"]:
                eng = self._owner_engine(rid)
                if eng is not None:
                    finished[rid] = eng.requests[rid]
        for rid, req in finished.items():
            results[rid] = req.tokens
            self.pop_request(rid)
        return results

    # ---- observability ----------------------------------------------------
    def stats_snapshot(self, include_dispatch: bool = False) -> Dict:
        """Fleet snapshot: aggregated pool + per-replica sections + the
        router's own accounting (the /stats payload; /healthz slims it).
        include_dispatch forwards to replica 0 only — the dispatch
        accounting is per-compiled-program, identical across replicas
        of one config."""
        live = [r for r in self.replicas if r.state != DEAD]
        agg_pool = {
            "num_blocks": 0, "blocks_in_use": 0, "blocks_free": 0,
            "blocks_evictable": 0, "pool_bytes_total": 0,
            "kv_cache_dtype": self.kv_cache_dtype,
            "block_size": self.block_size,
        }
        replicas = []
        for rep in self.replicas:
            entry = {
                "idx": rep.idx, "state": rep.state,
                "params_version": rep.params_version,
                "reloads": rep.reloads, "steps": rep.steps,
                "attainment": round(rep.attainment(self.slo_ms), 4),
            }
            hist = rep.interval_hist()
            if hist is not None and hist.count:
                entry["interval_p50_ms"] = round(hist.percentile(50), 3)
                entry["interval_p99_ms"] = round(hist.percentile(99), 3)
            if rep.state != DEAD:
                eng = rep.engine
                pool = eng.pool
                entry.update({
                    "active": sum(1 for s in eng.slots if s is not None),
                    "waiting": len(eng.waiting),
                    "parked": len(getattr(eng, "_parked", ())),
                    "blocks_in_use": pool.blocks_in_use(),
                    "prefix_hit_tokens":
                        pool.stats["prefix_hit_tokens"],
                    "prefill_tokens": pool.stats["prefill_tokens"],
                })
                agg_pool["num_blocks"] += pool.num_blocks
                agg_pool["blocks_in_use"] += pool.blocks_in_use()
                agg_pool["blocks_free"] += pool.free_blocks()
                agg_pool["blocks_evictable"] += pool.evictable_blocks()
                agg_pool["pool_bytes_total"] += pool.bytes_total
                if hasattr(eng, "prefill_ctx"):
                    entry["prefill_devices"] = eng.prefill_ctx.num_devices
                    entry["decode_devices"] = eng.decode_ctx.num_devices
            replicas.append(entry)
        hit = sum(r.get("prefix_hit_tokens", 0) for r in replicas)
        seen = hit + sum(r.get("prefill_tokens", 0) for r in replicas)
        out = {
            "engine": "fleet",
            "paged": True,
            "max_batch": self.max_batch,
            "active": sum(r.get("active", 0) for r in replicas),
            "waiting": sum(r.get("waiting", 0) for r in replicas),
            "pool": agg_pool,
            "fleet": {
                "replicas": replicas,
                "num_replicas": len(self.replicas),
                "live_replicas": len(live),
                "policy": self.policy,
                "migrate": self.migrate,
                "autoscale": self.autoscaler is not None,
                "slo_ms": self.slo_ms,
                "params_version": self._version,
                "reload_pending": self._reload is not None,
                "affinity_entries": len(self._affinity),
                "tenant_affinity_entries": len(self._tenant_affinity),
                "supervisor_restarts": (
                    self._supervisor.total_restarts
                    if self._supervisor is not None else 0),
                "prefix_hit_rate": (round(hit / seen, 4) if seen
                                    else 0.0),
                **self.router_stats,
            },
        }
        if self.prefix_store is not None:
            out["fleet"]["prefix_store"] = self.prefix_store.stats()
        if include_dispatch and live:
            try:
                out["decode_dispatch"] = (
                    live[0].engine.stats_snapshot(
                        include_dispatch=True).get("decode_dispatch"))
            except Exception:  # noqa: BLE001 — observability best-effort
                pass
        return out

    def generate_text(self, prompts, max_new_tokens: int, sampling=None,
                      token_callback=None):
        """String-level API (mirrors DynamicInferenceEngine)."""
        assert self.tokenizer is not None, "tokenizer required"
        eod = getattr(self.tokenizer, "eod", None)
        rids = []
        for prompt in prompts:
            ids = np.asarray(self.tokenizer.tokenize(prompt), np.int32)
            rids.append(self.add_request(ids, max_new_tokens, sampling,
                                         eod_id=eod))
        cb = None
        if token_callback is not None:
            def cb(rid, tok):
                token_callback(rid, np.asarray([tok]), None)
        results = self.run_to_completion(token_callback=cb)
        texts = []
        for prompt, rid in zip(prompts, rids):
            n_prompt = len(self.tokenizer.tokenize(prompt))
            new_ids = results[rid][n_prompt:].tolist()
            if eod is not None and eod in new_ids:
                new_ids = new_ids[: new_ids.index(eod)]
            texts.append(self.tokenizer.detokenize(new_ids))
        return texts


class _InProcessBackend:
    """Supervisor backend over an in-process FleetRouter: alive = the
    replica is not DEAD, kill = the step-exception failover path
    (`_fail_replica` — zero lost sessions), relaunch = the
    engine_factory rebuild. The cross-process twin lives in
    inference/fleet_rpc.py; both feed the SAME Supervisor policy
    (inference/supervisor.py), so thread mode and process mode cannot
    drift."""

    def __init__(self, router: "FleetRouter"):
        self.router = router

    def indices(self) -> List[int]:
        return [rep.idx for rep in self.router.replicas]

    def alive(self, idx: int) -> bool:
        return self.router.replicas[idx].state != DEAD

    def kill(self, idx: int):
        self.router._kill_impl(idx)

    def relaunch(self, idx: int, **hints):
        self.router._revive_impl(idx, **hints)
