"""Speculative decoding over the paged-KV engine (ISSUE 4).

Pluggable proposers + an EXACT rejection-sampling verifier for
DynamicInferenceEngine(paged=True, spec_method=...):

- ``NGramProposer`` ("ngram"): model-free prompt-lookup — the longest
  suffix n-gram of the request's token history is matched against its
  earlier occurrences and the continuation is proposed. Wins on
  repetitive / retrieval / code workloads; zero extra model cost.
- ``MTPProposer`` ("mtp"): self-drafting through the model's own
  multi-token-prediction depth modules (transformer/mtp.py, DeepSeek-V3
  recipe) — depth d predicts the token d+1 positions ahead from the
  previous depth's hidden state and the previous token's embedding. Needs
  ``params["mtp"]`` (cfg.mtp_num_layers > 0); K is capped at the depth.
- ``DraftModelProposer`` ("draft"): a small draft model sharing the
  target vocab/tokenizer (e.g. models/presets.py), with its own dense
  per-slot KV cache. Each round it catches up on tokens the target
  accepted since its last run (<= K+1 single-token steps), then drafts K
  tokens autoregressively; sampled requests draft from the draft's
  warped distribution and hand the verifier the full proposal
  probabilities q.

Verification: all K drafts (plus the mandatory next token) run through
the engine's ONE batched multi-query forward; acceptance is exact
rejection sampling (`_verify_and_sample`):

- greedy requests accept draft i while it equals argmax(target logits at
  its position) — the emitted stream is BIT-IDENTICAL to plain greedy
  decode for every proposer, by construction;
- sampled requests accept draft d with prob min(1, p(d)/q(d)) and on
  rejection sample the residual norm(max(p - q, 0)) — the classic
  speculative-sampling identity, so the emitted distribution equals the
  target's. Deterministic proposers (n-gram, greedy MTP heads) are
  point-mass q: accept with p(d), residual = p with d zeroed — also
  exact. p is warped through the SAME `_warp_logits`
  (temperature/top-k/top-p) the plain sampler uses, and all randomness
  comes from the engine's fold_in chains PRNGKey(seed) ∘ request_id ∘
  step (position i of a round uses step = generated_count + i), so
  streams stay reproducible and batch-composition independent; a fully
  accepted round's bonus token even uses the exact key plain decode
  would have used at that step.
"""

from __future__ import annotations

import functools
import warnings
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from megatronapp_tpu.inference.engine import (
    _forward_with_cache, init_kv_cache, mask_padded_vocab,
)
from megatronapp_tpu.models.gpt import gpt_embed, gpt_head
from megatronapp_tpu.ops.normalization import rms_norm
from megatronapp_tpu.transformer.block import layer_forward

# fold_in tags off the per-(request, step) chain key: acceptance uniform,
# residual categorical, and the draft model's own proposal sampling draw
# from distinct streams (the chain key itself is reserved for the
# plain-decode/bonus categorical).
_ACCEPT_FOLD = 1
_RESIDUAL_FOLD = 2
_DRAFT_FOLD = 3


# ---------------------------------------------------------------------------
# Exact rejection-sampling verifier
# ---------------------------------------------------------------------------


def _verify_and_sample(logits, drafts, q_lens, q_probs, seeds, rids,
                       base_steps, temps, top_ks, top_ps, greedys, *,
                       point_mass: bool):
    """Batched verification of one speculate round (jittable).

    logits [B, K+1, V] target logits (padded-vocab masked; row i sits at
    the position whose NEXT token is being decided — generated index
    base_steps + i); drafts [B, K]; q_lens [B] = 1 + per-row draft count
    (rows beyond are padding); q_probs [B, K, V] proposal probabilities
    (None when point_mass). Returns (accepted [B] ints in [0, K],
    out_token [B]) — the emitted window is drafts[:accepted] + [out].
    """
    from megatronapp_tpu.inference.dynamic_engine import (
        _request_keys, _warp_logits,
    )
    b, s, v = logits.shape
    k = s - 1
    flat = logits.reshape(b * s, v)
    rep = lambda a: jnp.repeat(a, s)  # noqa: E731
    warped = _warp_logits(flat, rep(temps), rep(top_ks),
                          rep(top_ps)).reshape(b, s, v)
    probs = jax.nn.softmax(warped, axis=-1)

    # Greedy acceptance: draft i == argmax of the target logits that
    # plain decode would have sampled from — bit-identical chains.
    g_acc = drafts == jnp.argmax(logits[:, :k], axis=-1)

    # Sampled acceptance: u * q(d) <= p(d), per-position chain keys.
    steps_i = base_steps[:, None] + jnp.arange(k)[None, :]      # [B, K]
    keys = jax.vmap(lambda sd, rd, st: _request_keys(
        jnp.full((k,), sd, jnp.int32), jnp.full((k,), rd, jnp.int32),
        st))(seeds, rids, steps_i)                              # [B, K, ·]
    u = jax.vmap(jax.vmap(lambda kk: jax.random.uniform(
        jax.random.fold_in(kk, _ACCEPT_FOLD))))(keys)           # [B, K]
    pd = jnp.take_along_axis(probs[:, :k], drafts[..., None],
                             axis=-1)[..., 0]
    if point_mass:
        qd = jnp.ones_like(pd)
    else:
        qd = jnp.take_along_axis(q_probs, drafts[..., None],
                                 axis=-1)[..., 0]
    s_acc = u * qd <= pd

    acc = jnp.where(greedys[:, None], g_acc, s_acc)
    acc = acc & (jnp.arange(k)[None, :] < (q_lens - 1)[:, None])
    a = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1)

    row_logits = jnp.take_along_axis(logits, a[:, None, None],
                                     axis=1)[:, 0]
    row_warped = jnp.take_along_axis(warped, a[:, None, None],
                                     axis=1)[:, 0]
    row_probs = jnp.take_along_axis(probs, a[:, None, None],
                                    axis=1)[:, 0]
    greedy_out = jnp.argmax(row_logits, axis=-1)

    base_key = _request_keys(seeds, rids, base_steps + a)
    # Fully-accepted bonus: the chain key plain decode would use at this
    # step, fed the same warped logits — the streams line up exactly.
    bonus = jax.vmap(jax.random.categorical)(base_key, row_warped)
    # Rejection: residual norm(max(p - q, 0)); p ≈ q underflow falls
    # back to p (acceptance prob was ~1 there anyway).
    d_a = jnp.take_along_axis(drafts, jnp.clip(a, 0, k - 1)[:, None],
                              axis=1)[:, 0]
    if point_mass:
        q_row = jax.nn.one_hot(d_a, v, dtype=row_probs.dtype)
    else:
        q_row = jnp.take_along_axis(
            q_probs, jnp.clip(a, 0, k - 1)[:, None, None], axis=1)[:, 0]
    resid = jnp.maximum(row_probs - q_row, 0.0)
    resid_sum = jnp.sum(resid, axis=-1, keepdims=True)
    resid = jnp.where(resid_sum > 1e-9, resid / resid_sum, row_probs)
    corr_key = jax.vmap(lambda kk: jax.random.fold_in(
        kk, _RESIDUAL_FOLD))(base_key)
    correction = jax.vmap(jax.random.categorical)(
        corr_key, jnp.log(jnp.maximum(resid, 1e-30)))
    rejected = a < (q_lens - 1)
    sampled_out = jnp.where(rejected, correction, bonus)
    out = jnp.where(greedys, greedy_out, sampled_out).astype(jnp.int32)
    return a.astype(jnp.int32), out


def build_verify_sampler(point_mass: bool):
    """Jitted `_verify_and_sample` with the proposer's point-mass mode
    baked in (point-mass engines pass q_probs=None)."""
    return jax.jit(functools.partial(_verify_and_sample,
                                     point_mass=point_mass))


# ---------------------------------------------------------------------------
# Proposers
# ---------------------------------------------------------------------------


class Proposer:
    """Engine-side proposer interface (one instance per engine).

    point_mass: the proposal is deterministic given the context (n-gram
    lookup, greedy MTP heads) — the verifier then treats q as a point
    mass, which keeps rejection sampling exact without materializing q.
    needs_hidden: the proposer consumes the engine's per-slot pre-head
    hidden state (engine._h_last, maintained by the verify rounds and
    chunked prefill)."""

    name = "base"
    point_mass = True
    needs_hidden = False

    def __init__(self, engine):
        self.engine = engine

    # Lifecycle hooks (engine calls these).
    def on_admit(self, slot: int, req):
        pass

    def on_release(self, slot: int):
        pass

    def on_verified(self, slot: int, accepted: int):
        pass

    def reset_compilation(self):
        pass

    def propose(self, k_caps: np.ndarray
                ) -> Tuple[np.ndarray, np.ndarray, Optional[jnp.ndarray]]:
        """k_caps [max_batch]: per-slot draft budget this round. Returns
        (drafts [B, spec_k] int32, counts [B] int32 with counts <=
        k_caps, q_probs [B, spec_k, V] or None for point-mass)."""
        raise NotImplementedError


def _ngram_lookup(tokens: np.ndarray, k: int, max_n: int,
                  min_n: int) -> np.ndarray:
    """Prompt-lookup: most recent earlier occurrence of the longest
    suffix n-gram; returns up to k continuation tokens (possibly 0)."""
    t = np.asarray(tokens)
    length = len(t)
    for n in range(min(max_n, length - 1), min_n - 1, -1):
        pat = t[length - n:]
        hay = t[:length - 1]            # continuation must exist
        if len(hay) < n:
            continue
        win = np.lib.stride_tricks.sliding_window_view(hay, n)
        hits = np.flatnonzero(np.all(win == pat[None], axis=1))
        # Exclude the suffix matching itself (start == length - n).
        hits = hits[hits < length - n]
        if len(hits):
            start = int(hits[-1]) + n   # most recent occurrence
            cont = t[start:start + k]
            if len(cont):
                return cont.astype(np.int32)
    return np.zeros((0,), np.int32)


class NGramProposer(Proposer):
    """Model-free prompt-lookup proposer (n-gram continuation)."""

    name = "ngram"
    point_mass = True

    def __init__(self, engine, max_n: int = 3, min_n: int = 1):
        super().__init__(engine)
        self.max_n = max_n
        self.min_n = min_n

    def propose(self, k_caps):
        eng = self.engine
        b, k = eng.max_batch, eng.spec_k
        drafts = np.zeros((b, k), np.int32)
        counts = np.zeros((b,), np.int32)
        for req in eng.slots:
            if req is None or req.finished:
                continue
            cap = int(k_caps[req.slot])
            if cap <= 0:
                continue
            cont = _ngram_lookup(req.tokens, cap, self.max_n, self.min_n)
            n = len(cont)
            drafts[req.slot, :n] = cont
            counts[req.slot] = n
        return drafts, counts, None


def _mtp_draft(params, h, toks, positions, cfg, k: int):
    """Greedy MTP self-draft chain: depth d combines the previous
    depth's hidden with the previous token's embedding (DeepSeek MTP
    recipe at inference) and scores with the SHARED head. The depth
    layer runs position-local here (S=1): single-token self-attention is
    rope-invariant and degenerate (out == v), so it acts as a learned
    head — proposal quality only; correctness comes from the verifier.
    h [B, H] pre-head hidden at the last verified position; toks [B] the
    pending token. Returns drafts [B, k]."""
    drafts = []
    h_cur = h.astype(cfg.compute_dtype)
    tok = toks
    pos = positions
    for d in range(k):
        dp = params["mtp"][d]
        e = gpt_embed(params, tok[:, None], cfg,
                      position_ids=pos[:, None])[:, 0]
        x = jnp.concatenate(
            [rms_norm(h_cur, dp["hnorm_scale"], cfg.layernorm_epsilon),
             rms_norm(e, dp["enorm_scale"], cfg.layernorm_epsilon)],
            axis=-1).astype(cfg.compute_dtype)
        x = x @ dp["proj"].astype(cfg.compute_dtype)
        (h2, _), _ = layer_forward(dp["layer"], x[:, None], cfg,
                                   None, None, None)
        h_cur = h2[:, 0]
        logits = mask_padded_vocab(
            gpt_head(params, h_cur[:, None], cfg)[:, 0], cfg)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        drafts.append(tok)
        pos = pos + 1
    return jnp.stack(drafts, axis=1)


class MTPProposer(Proposer):
    """Self-drafting through the model's own MTP depth modules."""

    name = "mtp"
    point_mass = True
    needs_hidden = True

    def __init__(self, engine):
        super().__init__(engine)
        self.depth = len(engine.params.get("mtp") or [])
        self._k = min(engine.spec_k, self.depth)
        self.reset_compilation()

    @staticmethod
    def available(engine) -> bool:
        return bool(engine.params.get("mtp"))

    def reset_compilation(self):
        cfg = self.engine.cfg
        k = self._k
        self._draft = jax.jit(
            lambda p, h, t, pos: _mtp_draft(p, h, t, pos, cfg, k))

    def propose(self, k_caps):
        eng = self.engine
        b, k = eng.max_batch, eng.spec_k
        drafts = np.zeros((b, k), np.int32)
        counts = np.zeros((b,), np.int32)
        caps = np.minimum(np.asarray(k_caps), self._k)
        rows = [r for r in eng.slots
                if r is not None and not r.finished
                and caps[r.slot] > 0 and eng._h_valid[r.slot]]
        if not rows or self._k == 0:
            return drafts, counts, None
        out = np.asarray(jax.device_get(self._draft(
            eng.params, jnp.asarray(eng._h_last),
            jnp.asarray(eng.last_tokens[:, 0].astype(np.int32)),
            jnp.asarray(eng.lengths.astype(np.int32)))))
        for r in rows:
            n = int(caps[r.slot])
            drafts[r.slot, :n] = out[r.slot, :n]
            counts[r.slot] = n
        return drafts, counts, None


def _draft_sample(logits, seeds, rids, steps, temps, top_ks, top_ps,
                  greedys):
    """One draft-chain sampling step: greedy rows argmax, sampled rows
    draw from the draft's warped distribution with the _DRAFT_FOLD
    stream (independent of the verifier's uniforms — a proposal that
    peeked at the acceptance randomness would bias the test). Returns
    (tokens [B], q [B, V] warped proposal probs)."""
    from megatronapp_tpu.inference.dynamic_engine import (
        _request_keys, _warp_logits,
    )
    warped = _warp_logits(logits, temps, top_ks, top_ps)
    q = jax.nn.softmax(warped, axis=-1)
    keys = jax.vmap(lambda kk: jax.random.fold_in(kk, _DRAFT_FOLD))(
        _request_keys(seeds, rids, steps))
    sampled = jax.vmap(jax.random.categorical)(keys, warped)
    toks = jnp.where(greedys, jnp.argmax(logits, axis=-1),
                     sampled).astype(jnp.int32)
    return toks, q


class DraftModelProposer(Proposer):
    """Small draft model with its own DENSE per-slot KV cache.

    The draft shares the target's (padded) vocab so its proposal
    distribution q lives in the same space as the target p. Per round it
    (1) catches up on tokens the target accepted since its last run —
    at most K+1 batched single-token steps, all through one jit — then
    (2) drafts K tokens autoregressively, recording q for the verifier.
    Draft KV for rejected tokens needs no rollback: the dense cache
    masks by per-row length and stale rows are overwritten on the next
    catch-up."""

    name = "draft"
    point_mass = False

    def __init__(self, engine, draft_params, draft_cfg):
        super().__init__(engine)
        if draft_cfg.vocab_size != engine.cfg.vocab_size:
            raise ValueError(
                f"draft vocab ({draft_cfg.vocab_size}) must match the "
                f"target vocab ({engine.cfg.vocab_size}) — the rejection "
                "sampler compares p and q over one distribution")
        self.params = draft_params
        self.cfg = draft_cfg
        b = engine.max_batch
        self.cache = init_kv_cache(draft_cfg, b, engine.max_seq_len)
        self.lens = np.zeros((b,), np.int32)
        self._round_base = np.zeros((b,), np.int32)
        self._round_fed = np.zeros((b,), np.int32)
        self._q_zero = None    # lazy [B, K, V] zeros for draft-less rounds
        self.reset_compilation()

    def reset_compilation(self):
        from megatronapp_tpu.inference.dynamic_engine import _decode_step
        dcfg = self.cfg
        self._prefill_jit = jax.jit(
            functools.partial(_forward_with_cache, cfg=dcfg))
        self._step = jax.jit(
            lambda p, t, c, l, a: _decode_step(p, t, c, l, a, dcfg),
            donate_argnums=(2,))
        self._sample = jax.jit(_draft_sample)

    def on_admit(self, slot, req):
        eng = self.engine
        valid = int(eng.lengths[slot])        # == len(req.tokens) - 1
        tokens = req.tokens[:valid]
        bucket = next((x for x in eng.prefill_buckets if x >= valid),
                      eng.max_seq_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :valid] = tokens
        tmp = init_kv_cache(self.cfg, 1, bucket)
        _, tmp = self._prefill_jit(self.params, jnp.asarray(padded), tmp, 0)
        self.cache = tuple(
            c.at[:, slot, :bucket].set(t[:, 0])
            for c, t in zip(self.cache, tmp))
        self.lens[slot] = valid

    def on_release(self, slot):
        self.lens[slot] = 0
        self._round_fed[slot] = 0

    def on_verified(self, slot, accepted):
        # Draft KV for the accepted prefix [pending, d1..da] is valid —
        # its rows were computed from all-accepted context. Rewind past
        # that (the first rejected draft's row gets overwritten on the
        # next catch-up).
        fed = int(self._round_fed[slot])
        if fed:
            self.lens[slot] = int(self._round_base[slot]) + min(
                accepted + 1, fed)
            self._round_fed[slot] = 0

    def propose(self, k_caps):
        eng = self.engine
        b, k = eng.max_batch, eng.spec_k
        drafts = np.zeros((b, k), np.int32)
        counts = np.zeros((b,), np.int32)
        self._round_fed[:] = 0
        rows = [r for r in eng.slots if r is not None and not r.finished
                and int(k_caps[r.slot]) > 0]
        if not rows:
            # point_mass is False for this proposer, so the verifier
            # still dereferences q — hand it an all-zeros (fully
            # masked-out by counts == 0) buffer.
            if self._q_zero is None:
                self._q_zero = jnp.zeros((b, k, eng.cfg.vocab_size),
                                         jnp.float32)
            return drafts, counts, self._q_zero

        # 1) Catch-up: feed the accepted tokens the draft hasn't seen.
        toks = {r.slot: r.tokens for r in rows}
        while True:
            behind = [s for s, t in toks.items()
                      if self.lens[s] < len(t) - 1]
            if not behind:
                break
            feed = np.zeros((b, 1), np.int32)
            act = np.zeros((b,), bool)
            for s in behind:
                feed[s, 0] = toks[s][self.lens[s]]
                act[s] = True
            _, self.cache = self._step(
                self.params, jnp.asarray(feed), self.cache,
                jnp.asarray(self.lens), jnp.asarray(act))
            for s in behind:
                self.lens[s] += 1

        # 2) Draft chain: K batched steps; per-row sampling params (the
        # engine's shared gather, so greedy rows draft greedily and
        # sampled rows draft from q on the right key chains).
        sp = eng._sampling_rows()
        seeds, rids, base = sp["seeds"], sp["rids"], sp["steps"]
        temps, top_ks = sp["temps"], sp["top_ks"]
        top_ps, greedys = sp["top_ps"], sp["greedys"]
        cur = np.zeros((b, 1), np.int32)
        for r in rows:
            slot = r.slot
            cur[slot, 0] = toks[slot][-1]
            self._round_base[slot] = self.lens[slot]
        k_max = int(max(k_caps[r.slot] for r in rows))
        q_cols = []
        for j in range(k_max):
            act = np.zeros((b,), bool)
            for r in rows:
                if int(k_caps[r.slot]) > j:
                    act[r.slot] = True
            logits, self.cache = self._step(
                self.params, jnp.asarray(cur), self.cache,
                jnp.asarray(self.lens), jnp.asarray(act))
            logits = mask_padded_vocab(logits, eng.cfg)
            tok_dev, q_dev = self._sample(
                logits, jnp.asarray(seeds), jnp.asarray(rids),
                jnp.asarray(base + j), jnp.asarray(temps),
                jnp.asarray(top_ks), jnp.asarray(top_ps),
                jnp.asarray(greedys))
            tok_np = np.asarray(jax.device_get(tok_dev))
            q_cols.append(q_dev)
            for r in rows:
                slot = r.slot
                if int(k_caps[slot]) > j:
                    drafts[slot, j] = tok_np[slot]
                    counts[slot] = j + 1
                    cur[slot, 0] = tok_np[slot]
                    self.lens[slot] += 1
                    self._round_fed[slot] += 1
        # Pad q to [B, K, V]; rows/columns beyond counts are ignored by
        # the verifier's acceptance mask.
        v = q_cols[0].shape[-1]
        while len(q_cols) < k:
            q_cols.append(jnp.zeros((b, v), q_cols[0].dtype))
        return drafts, counts, jnp.stack(q_cols, axis=1)


def make_proposer(method: str, engine, draft_params=None, draft_cfg=None,
                  **kwargs) -> Optional[Proposer]:
    """Build the requested proposer, or None (with a warning) when it is
    unavailable — the engine then falls back to plain decode."""
    from megatronapp_tpu.utils import metrics as telemetry
    if method == "ngram":
        return NGramProposer(engine, **kwargs)
    if method == "mtp":
        if not MTPProposer.available(engine):
            warnings.warn(
                "spec_method='mtp' requested but the model has no MTP "
                "depth modules (cfg.mtp_num_layers == 0 or params lack "
                "'mtp') — falling back to plain decode", stacklevel=2)
            telemetry.inc("spec_proposer_fallbacks")
            return None
        return MTPProposer(engine)
    if method == "draft":
        if draft_params is None or draft_cfg is None:
            warnings.warn(
                "spec_method='draft' requested without draft_params/"
                "draft_cfg — falling back to plain decode", stacklevel=2)
            telemetry.inc("spec_proposer_fallbacks")
            return None
        return DraftModelProposer(engine, draft_params, draft_cfg)
    raise ValueError(f"unknown spec_method {method!r} "
                     "(expected 'draft', 'mtp', or 'ngram')")
