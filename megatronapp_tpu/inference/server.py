"""Text-generation server: REST /api + WebSocket per-token streaming.

Parity with /root/reference/megatron/inference/text_generation_server.py
(MegatronServer Flask PUT /api :487, InferenceWSServer/InferenceGenerate
:29-298 — the MegaScope inference-mode streaming contract) and
tools/run_text_generation_server.py. aiohttp replaces Flask+ws (both in one
event loop; generation runs in a worker thread so the loop stays live).

REST:  PUT /api  {"prompts": [...], "tokens_to_generate": N,
                  "temperature": f, "top_k": i, "top_p": f, "greedy": b}
       → {"text": [...], "segments": [...]}
WS:    /ws — client sends the same JSON; server streams
       {"type": "token", "step": i, "token": id, "text": str} per token
       then {"type": "done", "text": full}.
"""

from __future__ import annotations

import asyncio
import json
from typing import Optional

from megatronapp_tpu.inference.engine import (
    SamplingParams, StaticInferenceEngine,
)


def _sampling_from_request(req: dict) -> SamplingParams:
    return SamplingParams(
        temperature=float(req.get("temperature", 1.0)),
        top_k=int(req.get("top_k", 0)),
        top_p=float(req.get("top_p", 0.0)),
        greedy=bool(req.get("greedy", False)),
        seed=int(req.get("random_seed", 0)),
    )


class TextGenerationServer:
    def __init__(self, engine: StaticInferenceEngine, host="0.0.0.0",
                 port=5000):
        self.engine = engine
        self.host = host
        self.port = port

    # ------------------------------------------------------------------
    async def handle_api(self, request):
        from aiohttp import web
        try:
            req = await request.json()
            prompts = req["prompts"]
            n = int(req.get("tokens_to_generate", 64))
            sampling = _sampling_from_request(req)
            loop = asyncio.get_running_loop()
            texts = await loop.run_in_executor(
                None, lambda: self.engine.generate_text(prompts, n,
                                                        sampling))
            return web.json_response({
                "text": [p + t for p, t in zip(prompts, texts)],
                "segments": texts,
            })
        except Exception as e:  # parity: reference returns 400 with message
            return web.json_response({"message": str(e)}, status=400)

    async def handle_ws(self, request):
        from aiohttp import web
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        loop = asyncio.get_running_loop()
        async for msg in ws:
            if msg.type != 1:  # TEXT
                continue
            req = json.loads(msg.data)
            prompts = req.get("prompts") or [req.get("prompt", "")]
            n = int(req.get("tokens_to_generate", 64))
            sampling = _sampling_from_request(req)
            queue: asyncio.Queue = asyncio.Queue()

            def cb(step, tokens, logits):
                text = self.engine.tokenizer.detokenize(
                    [int(tokens[0])]) if self.engine.tokenizer else ""
                loop.call_soon_threadsafe(queue.put_nowait, {
                    "type": "token", "step": int(step),
                    "token": int(tokens[0]), "text": text,
                })

            fut = loop.run_in_executor(
                None, lambda: self.engine.generate_text(
                    prompts[:1], n, sampling, token_callback=cb))
            done = False
            while not done:
                get = asyncio.create_task(queue.get())
                await asyncio.wait({get, fut},
                                   return_when=asyncio.FIRST_COMPLETED)
                while not queue.empty() or get.done():
                    payload = (get.result() if get.done()
                               else queue.get_nowait())
                    await ws.send_json(payload)
                    if queue.empty():
                        break
                    get = asyncio.create_task(queue.get())
                if fut.done() and queue.empty():
                    if not get.done():
                        get.cancel()
                    texts = fut.result()
                    await ws.send_json({"type": "done", "text": texts[0]})
                    done = True
        return ws

    # ------------------------------------------------------------------
    def build_app(self):
        from aiohttp import web
        app = web.Application()
        app.router.add_put("/api", self.handle_api)
        app.router.add_post("/api", self.handle_api)
        app.router.add_get("/ws", self.handle_ws)
        return app

    def run(self):
        from aiohttp import web
        web.run_app(self.build_app(), host=self.host, port=self.port)
