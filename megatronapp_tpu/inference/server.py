"""Text-generation server: REST /api + WebSocket per-token streaming.

Parity with /root/reference/megatron/inference/text_generation_server.py
(MegatronServer Flask PUT /api :487, InferenceWSServer/InferenceGenerate
:29-298 — the MegaScope inference-mode streaming contract) and
tools/run_text_generation_server.py. aiohttp replaces Flask+ws (both in one
event loop; generation runs in a worker thread so the loop stays live).

With a DynamicInferenceEngine (--engine dynamic), the server runs TRUE
continuous batching: every connection submits into one shared engine and
a single stepper thread (DynamicBatchingDriver) drives engine.step(), so
concurrent requests decode in the same batch instead of serializing
whole generations behind _gen_lock. Static/mamba engines keep the
serialized path (their caches are per-generation).

REST:  PUT /api  {"prompts": [...], "tokens_to_generate": N,
                  "temperature": f, "top_k": i, "top_p": f, "greedy": b}
       → {"text": [...], "segments": [...]}
       GET /stats → serving observability without log scraping: engine
       type, active batch size / waiting queue, paged-pool occupancy
       (blocks in use / free / evictable, prefix-cache hit rate,
       preemptions), and speculative-decoding acceptance rate +
       tokens/step (DynamicInferenceEngine.stats_snapshot).
WS:    /ws — client sends the same JSON; server streams
       {"type": "token", "step": i, "token": id, "text": str} per token
       then {"type": "done", "text": full}.

MegaScope inference mode (reference InferenceWSServer/InferenceGenerate,
text_generation_server.py:211-239): a WS request may add
"visualization" (FlagType→layers map), "compressor" {pixels, method} and
"disturbance" configs — the server then also streams per-token capture
payloads {update_type, site, layer_id, result} (same wire contract as
training mode) and attaches the top-20 candidate list (tik_result) to
each token message. Toggling captures re-traces the engine's jits —
the documented cost of dynamic reconfiguration under jit.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import Optional
from megatronapp_tpu.inference.dynamic_engine import DeadlineExceeded
from megatronapp_tpu.inference.engine import (
    SamplingParams, StaticInferenceEngine,
)
from megatronapp_tpu.trace.request_trace import get_request_tracer
from megatronapp_tpu.utils import chaos
from megatronapp_tpu.utils import metrics as telemetry


class _ClientGone(Exception):
    """Raised inside the generation worker when the WS client vanished
    mid-stream (cooperative cancellation via the token callback)."""


class DynamicBatchingDriver:
    """One stepper thread drives a shared DynamicInferenceEngine for ALL
    server connections (continuous batching across clients).

    submit() is thread-safe and returns (request_id, done_event); the
    optional token_cb(rid, token) fires from the stepper thread for every
    generated token. cancel() aborts a request (waiting requests complete
    immediately; running ones retire on the next step, releasing their
    cache). The stepper is a daemon thread started on first submit and
    parks on a condition variable whenever the engine has no work.

    Self-healing (ISSUE 6): per-request deadlines (submit timeout_s —
    expired work is rejected at admission, overdue in-flight work is
    aborted by the engine's expiry sweep and surfaces DeadlineExceeded);
    a stepper watchdog (a failing engine.step broadcasts clean error
    frames, reclaims the pool via abort_all, counts a restart, and backs
    off exponentially on consecutive failures so a persistent fault
    can't spin the thread hot); GET /healthz reports liveness, restart
    count, and pool pressure.

    Rolling engine reload (ISSUE 9): `request_reload(params)` swaps the
    model weights WITHOUT dropping the in-flight batch — admission
    pauses, running requests drain to completion, the swap lands on an
    empty batch (both sub-meshes for a disaggregated engine), and the
    still-waiting queue is then admitted against the new weights. The
    returned event fires when the swap is done; /healthz counts
    `reloads`."""

    def __init__(self, engine, crash_backoff_base: float = 0.25,
                 crash_backoff_cap: float = 5.0):
        self.engine = engine
        self._cv = threading.Condition()
        self._subs = {}     # rid -> {"cb": fn|None, "done": Event}
        self._errors = {}   # rid -> Exception from a failed step
        self._thread = None
        self.max_active = 0   # high-water concurrently-active slots
        # Watchdog / restart accounting.
        self.restarts = 0             # step failures survived
        self.thread_restarts = 0      # stepper threads found dead
        self.consecutive_failures = 0
        self.deadline_expired = 0     # requests aborted past deadline
        self.crash_backoff_base = crash_backoff_base
        self.crash_backoff_cap = crash_backoff_cap
        # Rolling reload state: (params, done_event) or None.
        self._reload = None
        self.reloads = 0

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            if self._thread is not None:
                # A dead stepper thread (BaseException escape) is a
                # restart-worthy event — account for it in /healthz.
                self.thread_restarts += 1
            self._thread = threading.Thread(
                target=self._loop, name="dynamic-engine-stepper",
                daemon=True)
            self._thread.start()

    def submit(self, prompt_ids, max_new_tokens, sampling, eod_id=None,
               token_cb=None, priority: int = 0,
               timeout_s: Optional[float] = None,
               adapter_id: Optional[str] = None,
               tenant: Optional[str] = None):
        """timeout_s: per-request deadline in seconds from now. Already-
        expired work (timeout_s <= 0) is rejected at admission with
        DeadlineExceeded — a clean error frame instead of queueing work
        the client has given up on.

        adapter_id/tenant: multi-tenant LoRA serving (ISSUE 19) —
        adapter_id picks the tenant's adapter from the engine's cache
        (unknown ids are rejected at submit), tenant labels per-tenant
        telemetry and composes the tenant's SLO class (TenantSLO on the
        engine, when configured) into (priority, deadline)."""
        deadline = None
        if timeout_s is not None:
            if timeout_s <= 0:
                self.deadline_expired += 1
                telemetry.inc("serving_deadline_expired")
                raise DeadlineExceeded(
                    "request deadline expired at admission "
                    f"(timeout_s={timeout_s})")
            deadline = time.monotonic() + timeout_s
        slo = getattr(self.engine, "tenant_slo", None)
        if slo is not None:
            priority, deadline = slo.compose(tenant, priority=priority,
                                             deadline_s=deadline)
        # Tenancy kwargs only when set: engines without the plumbing
        # (the disagg facade) keep their add_request signature.
        extra = {}
        if adapter_id is not None:
            extra["adapter_id"] = adapter_id
        if tenant is not None:
            extra["tenant"] = tenant
        with self._cv:
            rid = self.engine.add_request(prompt_ids, max_new_tokens,
                                          sampling, eod_id=eod_id,
                                          priority=priority,
                                          deadline_s=deadline, **extra)
            done = threading.Event()
            self._subs[rid] = {"cb": token_cb, "done": done}
            self._ensure_thread()
            self._cv.notify_all()
        return rid, done

    def request_reload(self, params) -> threading.Event:
        """Schedule a rolling params swap (checkpoint reload): pauses
        admission, lets running requests drain, swaps on the empty
        batch, then resumes admission for the waiting queue. Returns an
        event that fires once the new weights are live. Thread-safe; a
        second reload request before the first lands supersedes its
        params, and BOTH events fire when the (latest) swap lands — a
        superseded waiter must not block forever.

        Fleet engines (inference/fleet.FleetRouter) own a BETTER reload
        than the generic drain-the-whole-engine machinery: replicas
        drain and swap ONE AT A TIME inside their step loop, so fleet
        admission never pauses and zero requests drop — the driver
        delegates to `begin_rolling_reload` and only keeps the stepper
        awake (reload accounting lives in the fleet's own snapshot)."""
        if hasattr(self.engine, "begin_rolling_reload"):
            done = self.engine.begin_rolling_reload(params)
            with self._cv:
                self._ensure_thread()
                self._cv.notify_all()
            return done
        done = threading.Event()
        with self._cv:
            waiters = ([done] if self._reload is None
                       else self._reload[1] + [done])
            self._reload = (params, waiters)
            self._ensure_thread()
            self._cv.notify_all()
        return done

    def _maybe_reload_locked(self):
        """Advance the rolling reload state machine (caller holds _cv):
        pause admission while a reload is pending; perform the swap the
        moment the engine is drained of RUNNING work (waiting requests
        keep their queue position and decode on the new weights)."""
        if self._reload is None:
            return
        self.engine.pause_admission = True
        drained = (self.engine.drained_for_reload()
                   if hasattr(self.engine, "drained_for_reload")
                   else all(r is None for r in self.engine.slots))
        if not drained:
            return
        params, waiters = self._reload
        try:
            self.engine.set_params(params)
        finally:
            self.engine.pause_admission = False
            self._reload = None
        self.reloads += 1
        for done in waiters:
            done.set()

    def cancel(self, rid):
        with self._cv:
            state = self.engine.abort_request(rid)
            if state == "waiting":
                # Never ran: no finish event will fire — complete here.
                self.engine.pop_request(rid)
                sub = self._subs.pop(rid, None)
                if sub:
                    sub["done"].set()

    def result_tokens(self, rid):
        """Full token array of a finished request (pops it). Raises the
        stepper-side error if the request's step failed."""
        err = self._errors.pop(rid, None)
        if err is not None:
            # The request is dead either way: drop its engine-side
            # record too. The step-failure path already popped it via
            # abort_all (pop is a no-op then), but deadline-expired
            # requests are only RETIRED by the step — without this pop
            # every expiry would leak one Request in engine.requests.
            self.engine.pop_request(rid)
            raise err
        req = self.engine.pop_request(rid)
        return None if req is None else req.tokens

    def _loop(self):
        while True:
            with self._cv:
                while not (self.engine.has_work or
                           self._reload is not None):
                    self._cv.wait()
                self._maybe_reload_locked()
                if not self.engine.has_work:
                    continue
            try:
                chaos.fire("stepper-step")
                ev = self.engine.step()
                self.consecutive_failures = 0
            except Exception as e:  # noqa: BLE001 — broadcast & reset
                self.restarts += 1
                self.consecutive_failures += 1
                telemetry.inc("serving_step_failures")
                with self._cv:
                    for rid, sub in self._subs.items():
                        self._errors[rid] = e
                        sub["done"].set()
                    self._subs.clear()
                    # Drop ALL queued/running work: the engine state is
                    # suspect, and leaving occupied slots would spin this
                    # loop on the same exception forever. abort_all
                    # releases paged pool blocks too — clearing slots by
                    # hand would leak them and poison every later admit.
                    self.engine.abort_all()
                # Crash-loop backoff: repeated step failures (a wedged
                # compile cache, a persistent device fault) sleep
                # exponentially instead of spinning hot; one success
                # resets the clock.
                time.sleep(min(self.crash_backoff_cap,
                               self.crash_backoff_base *
                               2 ** (self.consecutive_failures - 1)))
                continue
            self.max_active = max(self.max_active, sum(
                1 for r in self.engine.slots if r is not None))
            with self._cv:
                # Deadline-expired requests get a clean error frame
                # BEFORE the generic finished handling pops their sub
                # (their pool blocks were reclaimed by the step's retire
                # pass).
                # (the engine's expiry sweep already counted these into
                # the telemetry registry — only driver bookkeeping here)
                for rid in ev.get("expired", ()):
                    if rid in self._subs:
                        self.deadline_expired += 1
                        self._errors[rid] = DeadlineExceeded(
                            f"request {rid} aborted: deadline exceeded")
                for rid, tok in ev["tokens"]:
                    sub = self._subs.get(rid)
                    if sub and sub["cb"] is not None:
                        try:
                            sub["cb"](rid, int(tok))
                        except Exception:  # noqa: BLE001 — dead sink
                            sub["cb"] = None
                for rid in ev["finished"]:
                    sub = self._subs.pop(rid, None)
                    if sub:
                        sub["done"].set()

    def stats(self) -> dict:
        """Stepper health for GET /healthz."""
        return {
            "started": self._thread is not None,
            "alive": self._thread is not None and self._thread.is_alive(),
            "restarts": self.restarts,
            "thread_restarts": self.thread_restarts,
            "consecutive_failures": self.consecutive_failures,
            "deadline_expired": self.deadline_expired,
            "subscribers": len(self._subs),
            "max_active": self.max_active,
            "reloads": self.reloads,
            "reload_pending": (self._reload is not None
                               or getattr(self.engine, "reload_pending",
                                          False)),
        }



def _sampling_from_request(req: dict) -> SamplingParams:
    return SamplingParams(
        temperature=float(req.get("temperature", 1.0)),
        top_k=int(req.get("top_k", 0)),
        top_p=float(req.get("top_p", 0.0)),
        greedy=bool(req.get("greedy", False)),
        seed=int(req.get("random_seed", 0)),
    )


class TextGenerationServer:
    def __init__(self, engine: StaticInferenceEngine, host="0.0.0.0",
                 port=5000):
        self.engine = engine
        self.host = host
        self.port = port
        # One generation at a time (static/mamba engines): the engine,
        # capture hooks, and disturbance are process-global, and viz
        # requests re-trace the engine's jits — concurrent generations
        # would cross-contaminate (the reference server serializes with a
        # lock too, text_generation_server.py MegatronServer).
        self._gen_lock = threading.Lock()
        # Continuous batching for DynamicInferenceEngine (and the
        # disaggregated coordinator, which exposes the same stepping
        # surface): connections share one engine through a single
        # stepper thread.
        from megatronapp_tpu.inference.disagg import DisaggServingEngine
        from megatronapp_tpu.inference.dynamic_engine import (
            DynamicInferenceEngine,
        )
        from megatronapp_tpu.inference.fleet import FleetRouter
        from megatronapp_tpu.inference.fleet_rpc import ProcessFleetRouter
        self._driver = (DynamicBatchingDriver(engine)
                        if isinstance(engine, (DynamicInferenceEngine,
                                               DisaggServingEngine,
                                               FleetRouter,
                                               ProcessFleetRouter))
                        else None)

    # ------------------------------------------------------------------
    def _submit_and_wait(self, prompts, n, sampling,
                         cancel: Optional[threading.Event] = None,
                         token_cb=None, timeout_s: Optional[float] = None,
                         adapter_id: Optional[str] = None,
                         tenant: Optional[str] = None):
        """Driver path (dynamic engine): submit every prompt into the
        shared batch, wait for completion, detokenize. token_cb(rid, tok)
        streams tokens of the FIRST prompt (WS contract). timeout_s:
        per-request deadline (expired work is rejected/aborted with a
        clean error surfaced through the normal error paths).
        adapter_id/tenant: multi-tenant LoRA fields forwarded to
        submit() (ISSUE 19)."""
        import numpy as np
        tok = self.engine.tokenizer
        assert tok is not None, "tokenizer required"
        eod = getattr(tok, "eod", None)
        subs = []
        for i, prompt in enumerate(prompts):
            ids = np.asarray(tok.tokenize(prompt), np.int32)
            rid, done = self._driver.submit(
                ids, n, sampling, eod_id=eod,
                token_cb=token_cb if i == 0 else None,
                timeout_s=timeout_s, adapter_id=adapter_id,
                tenant=tenant)
            subs.append((ids, rid, done))
        texts = []
        first_err = None
        for ids, rid, done in subs:
            while not done.wait(timeout=0.1):
                if cancel is not None and cancel.is_set():
                    self._driver.cancel(rid)
                    done.wait(timeout=60)   # retires on the next step
                    break
            try:
                toks = self._driver.result_tokens(rid)
            except Exception as e:  # noqa: BLE001 — re-raised after drain
                # Drain EVERY rid before surfacing the error: bailing on
                # the first failed prompt would leave the later prompts'
                # results/errors in the driver and engine forever (each
                # timed-out multi-prompt call would leak them all).
                if first_err is None:
                    first_err = e
                continue
            if cancel is not None and cancel.is_set():
                raise _ClientGone()
            new_ids = [] if toks is None else toks[len(ids):].tolist()
            if eod is not None and eod in new_ids:
                new_ids = new_ids[: new_ids.index(eod)]
            texts.append(tok.detokenize(new_ids))
        if first_err is not None:
            raise first_err
        return texts

    # ------------------------------------------------------------------
    async def handle_api(self, request):
        from aiohttp import web
        try:
            req = await request.json()
            prompts = req["prompts"]
            n = int(req.get("tokens_to_generate", 64))
            sampling = _sampling_from_request(req)
            timeout_s = req.get("timeout_s")
            timeout_s = None if timeout_s is None else float(timeout_s)
            adapter_id = req.get("adapter_id")
            tenant = req.get("tenant")
            loop = asyncio.get_running_loop()

            def run_api():
                if self._driver is not None:
                    # Continuous batching: concurrent /api calls share
                    # the decode batch instead of queueing on the lock.
                    return self._submit_and_wait(prompts, n, sampling,
                                                 timeout_s=timeout_s,
                                                 adapter_id=adapter_id,
                                                 tenant=tenant)
                with self._gen_lock:
                    return self.engine.generate_text(prompts, n, sampling)

            texts = await loop.run_in_executor(None, run_api)
            return web.json_response({
                "text": [p + t for p, t in zip(prompts, texts)],
                "segments": texts,
            })
        except Exception as e:  # parity: reference returns 400 with message
            return web.json_response({"message": str(e)}, status=400)

    async def handle_ws(self, request):
        from aiohttp import web
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        loop = asyncio.get_running_loop()
        # One persistent receive task doubles as the mid-generation
        # disconnect watcher: cancelling a ws.receive() mid-flight can
        # drop frames, so the SAME pending task is awaited between
        # requests and select()-ed against the payload queue during one.
        # TEXT frames that arrive mid-generation are buffered in
        # `pending` and served in order once the current one finishes
        # (sequential pipelining, matching the old async-for semantics).
        # Bounded: each buffered request later holds _gen_lock serially,
        # so an unbounded queue lets one client grow memory and head-of-
        # line latency without limit. Past the cap the socket is closed
        # with a policy-violation code (client should await replies).
        MAX_PENDING = 32
        import collections
        pending: collections.deque = collections.deque()
        recv_task = asyncio.ensure_future(ws.receive())
        while True:
            if len(pending) > MAX_PENDING:
                await ws.close(
                    code=1008,
                    message=b"too many pipelined requests; await replies")
                break
            if pending:
                msg = pending.popleft()
            else:
                msg = await recv_task
                if msg.type == 1:
                    recv_task = asyncio.ensure_future(ws.receive())
            if msg.type != 1:  # not TEXT → close/closing/error: done
                break
            req = json.loads(msg.data)
            prompts = req.get("prompts") or [req.get("prompt", "")]
            n = int(req.get("tokens_to_generate", 64))
            sampling = _sampling_from_request(req)
            viz = req.get("visualization")
            if viz and self._driver is not None:
                await ws.send_json({
                    "type": "error",
                    "message": "visualization requires --engine static "
                               "(the continuous-batching backend shares "
                               "one step loop across connections)"})
                continue
            queue: asyncio.Queue = asyncio.Queue()
            # Client-gone cancellation: a disconnect mid-stream must not
            # leave the generation running to completion while holding
            # _gen_lock (round-2 advisor finding) — the per-token
            # callback aborts the executor job at the next token.
            cancel = threading.Event()

            def cb(step, tokens, logits):
                if cancel.is_set():
                    raise _ClientGone()
                payload = {
                    "type": "token", "step": int(step),
                    "token": int(tokens[0]),
                    "text": (self.engine.tokenizer.detokenize(
                        [int(tokens[0])]) if self.engine.tokenizer
                        else ""),
                }
                if viz and logits is not None:
                    # Reference tik_result: sampled token + top-20
                    # candidates with decoded text.
                    from megatronapp_tpu.scope.tensor_tracer import (
                        get_tensor_tracer,
                    )
                    payload["candidates"] = get_tensor_tracer(
                    ).report_result(logits[0], int(tokens[0]),
                                    self.engine.tokenizer)["candidates"]
                loop.call_soon_threadsafe(queue.put_nowait, payload)

            def run_generation():
                if self._driver is not None:
                    # Dynamic engine: stream through the shared stepper
                    # (no lock — other connections keep decoding in the
                    # same batch). The driver callback must never raise
                    # in the stepper thread; disconnects abort via
                    # driver.cancel inside _submit_and_wait.
                    state = {"step": 0}

                    def driver_cb(rid, token):
                        if cancel.is_set():
                            return
                        payload = {
                            "type": "token", "step": state["step"],
                            "token": int(token),
                            "text": (self.engine.tokenizer.detokenize(
                                [int(token)]) if self.engine.tokenizer
                                else ""),
                        }
                        state["step"] += 1
                        loop.call_soon_threadsafe(queue.put_nowait,
                                                  payload)

                    return self._submit_and_wait(
                        prompts[:1], n, sampling, cancel=cancel,
                        token_cb=driver_cb,
                        timeout_s=(float(req["timeout_s"])
                                   if req.get("timeout_s") is not None
                                   else None),
                        adapter_id=req.get("adapter_id"),
                        tenant=req.get("tenant"))
                # Capture hooks are thread-local and baked in at trace
                # time: activate in THIS worker thread and re-trace the
                # engine around the toggle. The lock serializes against
                # every other generation (shared engine/global hooks).
                with self._gen_lock:
                    if not viz:
                        return self.engine.generate_text(
                            prompts[:1], n, sampling, token_callback=cb)
                    import jax

                    from megatronapp_tpu.scope.disturbance import (
                        get_disturbance,
                    )
                    from megatronapp_tpu.scope.hooks import (
                        capture_payload,
                    )
                    from megatronapp_tpu.scope.tensor_tracer import (
                        get_tensor_tracer,
                    )
                    comp = req.get("compressor") or {}
                    tt = get_tensor_tracer()

                    def report(site, layer_id, arr):
                        loop.call_soon_threadsafe(
                            queue.put_nowait,
                            capture_payload(site, layer_id, arr))

                    # Config application sits INSIDE the try: a malformed
                    # client config must not leave hooks globally active.
                    try:
                        tt.set_flags_from_config(viz)
                        tt.activate(report,
                                    pixels=int(comp.get("pixels", 16)),
                                    method=comp.get("method", "mean"))
                        if req.get("disturbance") is not None:
                            get_disturbance().configure(
                                req["disturbance"],
                                seed=int(req.get("random_seed", 0)))
                        self.engine.reset_compilation()
                        return self.engine.generate_text(
                            prompts[:1], n, sampling, token_callback=cb)
                    finally:
                        jax.effects_barrier()
                        tt.deactivate()
                        tt.clear_records()
                        get_disturbance().clear()
                        self.engine.reset_compilation()

            fut = loop.run_in_executor(None, run_generation)
            # Sentinel-terminated drain: per-token callbacks enqueue via
            # call_soon_threadsafe BEFORE the executor job finishes, and
            # the done-callback fires on the loop after those are
            # scheduled, so FIFO order guarantees every payload precedes
            # the sentinel (no racy cancel of an in-flight queue.get).
            _DONE = object()
            fut.add_done_callback(lambda _: queue.put_nowait(_DONE))
            # Drain payloads while WATCHING the socket: a close frame (or
            # any mid-stream client traffic) must abort the in-flight
            # generation — the token callback raises _ClientGone at the
            # next token, releasing _gen_lock instead of running to
            # completion (round-2 advisor finding). A bare queue.get()
            # would never see the disconnect. recv_task is the
            # persistent watcher; on a mid-stream fire it stays
            # completed and the top of the outer loop consumes it.
            completed = False
            get_task = asyncio.ensure_future(queue.get())
            try:
                while True:
                    done, _ = await asyncio.wait(
                        {get_task, recv_task},
                        return_when=asyncio.FIRST_COMPLETED)
                    if recv_task in done:
                        m = recv_task.result()
                        if m.type == 1 and len(pending) < MAX_PENDING:
                            # Pipelined request: buffer it, keep
                            # streaming the current generation.
                            pending.append(m)
                            recv_task = asyncio.ensure_future(
                                ws.receive())
                            continue
                        if m.type == 1:
                            pending.append(m)  # outer loop closes 1008
                        break           # disconnect/flood → abort
                    payload = get_task.result()
                    if payload is _DONE:
                        completed = True
                        break
                    await ws.send_json(payload)
                    get_task = asyncio.ensure_future(queue.get())
            except (ConnectionResetError, RuntimeError):
                pass                    # TCP reset mid-send → abort
            finally:
                if not completed:
                    cancel.set()
                if not get_task.done():
                    get_task.cancel()   # queue.get cancel is loss-free
            if not completed:
                try:
                    await fut      # worker aborts at the next token
                except _ClientGone:
                    pass
                except Exception:  # noqa: BLE001 — client already gone
                    pass
                continue           # outer loop handles the fired recv
            try:
                texts = fut.result()
            except _ClientGone:
                continue
            except Exception as e:
                # Client-input-driven failures (bad flag names, malformed
                # disturbance configs) surface as an error frame, matching
                # the REST handler's 400-with-message behavior.
                await ws.send_json({"type": "error", "message": str(e)})
                continue
            await ws.send_json({"type": "done", "text": texts[0]})
        if not recv_task.done():
            recv_task.cancel()     # connection is closing anyway
        return ws

    # ------------------------------------------------------------------
    def stats_snapshot(self) -> dict:
        """Serving stats for GET /stats. Dynamic engines report their
        full snapshot (pool / speculation / batch occupancy — plus the
        compiled decode-step dispatch accounting, ISSUE 11: /stats opts
        into include_dispatch, whose FIRST call pays one AOT compile and
        is cached after; /healthz keeps the cheap snapshot); static and
        mamba engines report what exists for them."""
        eng = self.engine
        if hasattr(eng, "stats_snapshot"):
            # Both the plain engine and the disagg facade accept
            # include_dispatch (ISSUE 12 satellite: the facade used to
            # TypeError here, silently dropping dispatch stats).
            out = eng.stats_snapshot(include_dispatch=True)
        else:
            out = {"engine": type(eng).__name__.replace(
                "InferenceEngine", "").lower()}
        if self._driver is not None:
            out["driver_max_active"] = self._driver.max_active
        return out

    async def handle_stats(self, request):
        from aiohttp import web
        return web.json_response(self.stats_snapshot())

    # ------------------------------------------------------------------
    def health_snapshot(self) -> dict:
        """GET /healthz payload: stepper liveness + restart accounting
        (DynamicBatchingDriver watchdog) and pool pressure, so an
        external orchestrator can probe the server without scraping
        logs. status: 'ok' (healthy / static engine), 'degraded'
        (stepper currently failing steps but self-healing), 'unhealthy'
        (stepper thread dead — probe should restart the server)."""
        out = {"status": "ok",
               "engine": type(self.engine).__name__.replace(
                   "InferenceEngine", "").lower()}
        if self._driver is not None:
            st = self._driver.stats()
            out["stepper"] = st
            out["restarts"] = st["restarts"] + st["thread_restarts"]
            eng = self.engine
            out["active"] = sum(1 for r in eng.slots if r is not None)
            out["waiting"] = len(eng.waiting)
            snap = (eng.stats_snapshot()
                    if hasattr(eng, "stats_snapshot") else {})
            if "disagg" in snap:
                # Per-queue depth + SLO attainment ride into /healthz so
                # an orchestrator can rotate on SLO pressure without
                # scraping /stats.
                out["disagg"] = {
                    "queues": snap["disagg"]["queues"],
                    "slo": snap["disagg"]["slo"],
                }
            if "fleet" in snap:
                # Aggregated fleet health: replica states + attainment
                # so an orchestrator sees a degraded fleet (dead
                # replica, reduced capacity) without scraping /stats.
                f = snap["fleet"]
                out["fleet"] = {
                    "num_replicas": f["num_replicas"],
                    "live_replicas": f["live_replicas"],
                    "reload_pending": f["reload_pending"],
                    "migrations": f["migrations"],
                    "failovers": f["failovers"],
                    # Cross-process fleets (inference/fleet_rpc.py)
                    # report supervisor restart accounting; in-process
                    # fleets report 0 until their supervisor runs.
                    "supervisor_restarts": f.get(
                        "supervisor_restarts", 0),
                    "replicas": [
                        {k: r.get(k) for k in
                         ("idx", "state", "active", "waiting",
                          "attainment", "params_version")}
                        for r in f["replicas"]],
                }
                if f["live_replicas"] < f["num_replicas"]:
                    out["status"] = "degraded"
            pool_stats = snap.get("pool")
            if pool_stats is not None:
                # One source of truth for the pool fields (the engine's
                # /stats payload); only the pressure ratio is derived
                # here.
                pool_stats["pressure"] = round(
                    pool_stats["blocks_in_use"] / pool_stats["num_blocks"],
                    4)
                out["pool"] = pool_stats
            if st["started"] and not st["alive"]:
                out["status"] = "unhealthy"
            elif st["consecutive_failures"] > 0 and self.engine.has_work:
                # Degraded = actively struggling. After a crash drains
                # the queue (abort_all) the stepper is parked with
                # nothing to fail on — an idle server must not stay
                # 'degraded' forever and get pulled from rotation; the
                # restart counters still record that it happened.
                out["status"] = "degraded"
        return out

    async def handle_healthz(self, request):
        from aiohttp import web
        payload = self.health_snapshot()
        return web.json_response(
            payload, status=503 if payload["status"] == "unhealthy"
            else 200)

    # ------------------------------------------------------------------
    def _export_live_gauges(self):
        """Point-in-time gauges refreshed at scrape time (counters and
        histograms accumulate at the instrumented sites; queue depths
        and pool occupancy are state, not events)."""
        eng = self.engine
        if hasattr(eng, "slots"):
            telemetry.set_gauge("serving_active_slots", sum(
                1 for r in eng.slots if r is not None))
        if hasattr(eng, "waiting"):
            telemetry.set_gauge("serving_waiting", len(eng.waiting))
        pool = getattr(eng, "pool", None)
        if pool is not None:
            telemetry.set_gauge("paged_blocks_in_use",
                                pool.blocks_in_use())
            telemetry.set_gauge("paged_blocks_free", pool.free_blocks())
            telemetry.set_gauge("paged_blocks_evictable",
                                pool.evictable_blocks())
        adapters = getattr(eng, "adapters", None)
        if adapters is not None:
            # LoRA adapter cache occupancy: resident/pinned counts and
            # rank-exact resident bytes. Hit/miss/eviction COUNTERS
            # accumulate at the cache's instrumented sites.
            lstats = adapters.stats_snapshot()
            telemetry.set_gauge("lora_adapters_resident",
                                lstats["resident"])
            telemetry.set_gauge("lora_adapters_pinned", lstats["pinned"])
            telemetry.set_gauge("lora_resident_bytes",
                                adapters.resident_bytes())
        spill = getattr(eng, "spill", None)
        if spill is not None:
            # Host-RAM KV spill tier (ISSUE 20): occupancy is state
            # (parked sessions, exact resident bytes vs budget); the
            # park/unpark COUNTERS accumulate at the tier's
            # instrumented sites.
            sstats = spill.stats()
            telemetry.set_gauge("kv_spill_parked", sstats["parked"])
            telemetry.set_gauge("kv_spill_bytes_used",
                                sstats["bytes_used"])
            telemetry.set_gauge("kv_spill_budget_bytes",
                                sstats["budget_bytes"])
        store = getattr(eng, "prefix_store", None)
        if store is not None:
            # Fleet-global prefix store (ISSUE 20): entry count and
            # exact resident bytes; hit/miss/eviction counters
            # accumulate inside the store.
            pstats = store.stats()
            telemetry.set_gauge("fleet_prefix_store_entries",
                                pstats["entries"])
            telemetry.set_gauge("fleet_prefix_store_bytes",
                                pstats["bytes_used"])
            telemetry.set_gauge("fleet_prefix_store_hit_total",
                                pstats["hits"])
        tstats = getattr(eng, "_tenant_stats", None)
        if tstats:
            # Per-tenant SLO attainment gauges (bounded cardinality —
            # the engine folds tenants past its label cap into
            # "_other"); per-tenant request/token COUNTERS accumulate
            # at the engine's _tenant_inc sites.
            lab = telemetry.labeled
            for t, st in list(tstats.items()):
                closed = st["finished"] + st["expired"]
                telemetry.set_gauge(
                    lab("serving_tenant_slo_attainment", tenant=t),
                    round(st["finished"] / closed, 4) if closed else 1.0)
        if hasattr(eng, "export_fleet_gauges"):
            # Cross-process fleet (inference/fleet_rpc.py): the router
            # exports its own per-replica labeled gauges + supervisor
            # restart counts — the replica engines live in OTHER
            # processes, so their state is only reachable through the
            # router's last step replies. One scrape covers the fleet.
            eng.export_fleet_gauges(telemetry)
        reps = getattr(eng, "replicas", None)
        if reps is not None:
            # Per-replica labeled series (one metric family, N labeled
            # series — the fleet dashboard shape).
            lab = telemetry.labeled
            for rep in reps:
                r = str(rep.idx)
                telemetry.set_gauge(
                    lab("fleet_replica_up", replica=r),
                    int(rep.state != "dead"))
                telemetry.set_gauge(
                    lab("fleet_replica_attainment", replica=r),
                    round(rep.attainment(getattr(eng, "slo_ms", None)),
                          4))
                if rep.state == "dead":
                    # Zero the capacity series: frozen last-alive
                    # values would over-count live capacity on
                    # dashboards forever.
                    for g in ("fleet_replica_active_slots",
                              "fleet_replica_waiting",
                              "fleet_replica_blocks_in_use"):
                        telemetry.set_gauge(lab(g, replica=r), 0)
                    continue
                reng = rep.engine
                telemetry.set_gauge(
                    lab("fleet_replica_active_slots", replica=r),
                    sum(1 for s in reng.slots if s is not None))
                telemetry.set_gauge(
                    lab("fleet_replica_waiting", replica=r),
                    len(reng.waiting))
                telemetry.set_gauge(
                    lab("fleet_replica_blocks_in_use", replica=r),
                    reng.pool.blocks_in_use())
            sup = getattr(eng, "_supervisor", None)
            if sup is not None:
                # Same restart-accounting series the cross-process
                # router exports — kill/revive drills route through the
                # one Supervisor, so the counters exist in-process too.
                for idx, n in sup.restarts.items():
                    telemetry.set_gauge(
                        lab("fleet_supervisor_restarts",
                            replica=str(idx)), n)
                telemetry.set_gauge("fleet_supervisor_restarts_total",
                                    sup.total_restarts)
        if self._driver is not None:
            st = self._driver.stats()
            telemetry.set_gauge("serving_stepper_alive",
                                int(st["alive"]))
            telemetry.set_gauge("serving_stepper_restarts",
                                st["restarts"] + st["thread_restarts"])

    def metrics_text(self) -> str:
        """Prometheus text for GET /metrics (also the driver-side dump
        hook — callers can scrape without an HTTP round-trip)."""
        if telemetry.enabled():
            self._export_live_gauges()
        return telemetry.render_prometheus()

    async def handle_metrics(self, request):
        """GET /metrics: Prometheus text exposition of the telemetry
        registry (enable with --serving-metrics / MEGATRON_METRICS=1;
        a disabled registry serves a one-line comment, not a 404, so
        scrapers keep a stable target)."""
        from aiohttp import web
        return web.Response(text=self.metrics_text(),
                            content_type="text/plain")

    # ------------------------------------------------------------------
    def dump_request_trace(self, path: Optional[str] = None) -> dict:
        """Driver hook: render the request-trace ring as one merged
        Chrome trace (prefill + decode mesh rows); optionally write it
        to `path` for chrome://tracing / Perfetto. A process-backed
        fleet merges every replica worker's ring over RPC into the
        same trace (one pid row per process)."""
        if hasattr(self.engine, "merged_trace"):
            trace = self.engine.merged_trace()
        else:
            trace = get_request_tracer().chrome_trace()
        if path is not None:
            with open(path, "w") as f:
                json.dump(trace, f)
        return trace

    async def handle_trace(self, request):
        """GET /trace: the per-request lifecycle ring as a Chrome trace
        JSON (enable with --request-trace / MEGATRON_REQUEST_TRACE=1).
        Server-side file dumps go through the dump_request_trace driver
        hook — a client-supplied path here would be an arbitrary-file-
        write primitive on an unauthenticated endpoint."""
        from aiohttp import web
        rt = get_request_tracer()
        if not rt.enabled:
            return web.json_response(
                {"message": "request tracing disabled — enable with "
                            "--request-trace or MEGATRON_REQUEST_TRACE=1"},
                status=404)
        return web.json_response(self.dump_request_trace())

    # ------------------------------------------------------------------
    def build_app(self):
        from aiohttp import web
        app = web.Application()
        app.router.add_put("/api", self.handle_api)
        app.router.add_post("/api", self.handle_api)
        app.router.add_get("/stats", self.handle_stats)
        app.router.add_get("/healthz", self.handle_healthz)
        app.router.add_get("/metrics", self.handle_metrics)
        app.router.add_get("/trace", self.handle_trace)
        app.router.add_get("/ws", self.handle_ws)
        return app

    def run(self):
        from aiohttp import web
        web.run_app(self.build_app(), host=self.host, port=self.port)
