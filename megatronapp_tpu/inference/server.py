"""Text-generation server: REST /api + WebSocket per-token streaming.

Parity with /root/reference/megatron/inference/text_generation_server.py
(MegatronServer Flask PUT /api :487, InferenceWSServer/InferenceGenerate
:29-298 — the MegaScope inference-mode streaming contract) and
tools/run_text_generation_server.py. aiohttp replaces Flask+ws (both in one
event loop; generation runs in a worker thread so the loop stays live).

REST:  PUT /api  {"prompts": [...], "tokens_to_generate": N,
                  "temperature": f, "top_k": i, "top_p": f, "greedy": b}
       → {"text": [...], "segments": [...]}
WS:    /ws — client sends the same JSON; server streams
       {"type": "token", "step": i, "token": id, "text": str} per token
       then {"type": "done", "text": full}.

MegaScope inference mode (reference InferenceWSServer/InferenceGenerate,
text_generation_server.py:211-239): a WS request may add
"visualization" (FlagType→layers map), "compressor" {pixels, method} and
"disturbance" configs — the server then also streams per-token capture
payloads {update_type, site, layer_id, result} (same wire contract as
training mode) and attaches the top-20 candidate list (tik_result) to
each token message. Toggling captures re-traces the engine's jits —
the documented cost of dynamic reconfiguration under jit.
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Optional
from megatronapp_tpu.inference.engine import (
    SamplingParams, StaticInferenceEngine,
)


class _ClientGone(Exception):
    """Raised inside the generation worker when the WS client vanished
    mid-stream (cooperative cancellation via the token callback)."""



def _sampling_from_request(req: dict) -> SamplingParams:
    return SamplingParams(
        temperature=float(req.get("temperature", 1.0)),
        top_k=int(req.get("top_k", 0)),
        top_p=float(req.get("top_p", 0.0)),
        greedy=bool(req.get("greedy", False)),
        seed=int(req.get("random_seed", 0)),
    )


class TextGenerationServer:
    def __init__(self, engine: StaticInferenceEngine, host="0.0.0.0",
                 port=5000):
        self.engine = engine
        self.host = host
        self.port = port
        # One generation at a time: the engine, capture hooks, and
        # disturbance are process-global, and viz requests re-trace the
        # engine's jits — concurrent generations would cross-contaminate
        # (the reference server serializes with a lock too,
        # text_generation_server.py MegatronServer).
        self._gen_lock = threading.Lock()

    # ------------------------------------------------------------------
    async def handle_api(self, request):
        from aiohttp import web
        try:
            req = await request.json()
            prompts = req["prompts"]
            n = int(req.get("tokens_to_generate", 64))
            sampling = _sampling_from_request(req)
            loop = asyncio.get_running_loop()

            def run_api():
                with self._gen_lock:
                    return self.engine.generate_text(prompts, n, sampling)

            texts = await loop.run_in_executor(None, run_api)
            return web.json_response({
                "text": [p + t for p, t in zip(prompts, texts)],
                "segments": texts,
            })
        except Exception as e:  # parity: reference returns 400 with message
            return web.json_response({"message": str(e)}, status=400)

    async def handle_ws(self, request):
        from aiohttp import web
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        loop = asyncio.get_running_loop()
        # One persistent receive task doubles as the mid-generation
        # disconnect watcher: cancelling a ws.receive() mid-flight can
        # drop frames, so the SAME pending task is awaited between
        # requests and select()-ed against the payload queue during one.
        # TEXT frames that arrive mid-generation are buffered in
        # `pending` and served in order once the current one finishes
        # (sequential pipelining, matching the old async-for semantics).
        # Bounded: each buffered request later holds _gen_lock serially,
        # so an unbounded queue lets one client grow memory and head-of-
        # line latency without limit. Past the cap the socket is closed
        # with a policy-violation code (client should await replies).
        MAX_PENDING = 32
        import collections
        pending: collections.deque = collections.deque()
        recv_task = asyncio.ensure_future(ws.receive())
        while True:
            if len(pending) > MAX_PENDING:
                await ws.close(
                    code=1008,
                    message=b"too many pipelined requests; await replies")
                break
            if pending:
                msg = pending.popleft()
            else:
                msg = await recv_task
                if msg.type == 1:
                    recv_task = asyncio.ensure_future(ws.receive())
            if msg.type != 1:  # not TEXT → close/closing/error: done
                break
            req = json.loads(msg.data)
            prompts = req.get("prompts") or [req.get("prompt", "")]
            n = int(req.get("tokens_to_generate", 64))
            sampling = _sampling_from_request(req)
            viz = req.get("visualization")
            queue: asyncio.Queue = asyncio.Queue()
            # Client-gone cancellation: a disconnect mid-stream must not
            # leave the generation running to completion while holding
            # _gen_lock (round-2 advisor finding) — the per-token
            # callback aborts the executor job at the next token.
            cancel = threading.Event()

            def cb(step, tokens, logits):
                if cancel.is_set():
                    raise _ClientGone()
                payload = {
                    "type": "token", "step": int(step),
                    "token": int(tokens[0]),
                    "text": (self.engine.tokenizer.detokenize(
                        [int(tokens[0])]) if self.engine.tokenizer
                        else ""),
                }
                if viz and logits is not None:
                    # Reference tik_result: sampled token + top-20
                    # candidates with decoded text.
                    from megatronapp_tpu.scope.tensor_tracer import (
                        get_tensor_tracer,
                    )
                    payload["candidates"] = get_tensor_tracer(
                    ).report_result(logits[0], int(tokens[0]),
                                    self.engine.tokenizer)["candidates"]
                loop.call_soon_threadsafe(queue.put_nowait, payload)

            def run_generation():
                # Capture hooks are thread-local and baked in at trace
                # time: activate in THIS worker thread and re-trace the
                # engine around the toggle. The lock serializes against
                # every other generation (shared engine/global hooks).
                with self._gen_lock:
                    if not viz:
                        return self.engine.generate_text(
                            prompts[:1], n, sampling, token_callback=cb)
                    import jax

                    from megatronapp_tpu.scope.disturbance import (
                        get_disturbance,
                    )
                    from megatronapp_tpu.scope.hooks import (
                        capture_payload,
                    )
                    from megatronapp_tpu.scope.tensor_tracer import (
                        get_tensor_tracer,
                    )
                    comp = req.get("compressor") or {}
                    tt = get_tensor_tracer()

                    def report(site, layer_id, arr):
                        loop.call_soon_threadsafe(
                            queue.put_nowait,
                            capture_payload(site, layer_id, arr))

                    # Config application sits INSIDE the try: a malformed
                    # client config must not leave hooks globally active.
                    try:
                        tt.set_flags_from_config(viz)
                        tt.activate(report,
                                    pixels=int(comp.get("pixels", 16)),
                                    method=comp.get("method", "mean"))
                        if req.get("disturbance") is not None:
                            get_disturbance().configure(
                                req["disturbance"],
                                seed=int(req.get("random_seed", 0)))
                        self.engine.reset_compilation()
                        return self.engine.generate_text(
                            prompts[:1], n, sampling, token_callback=cb)
                    finally:
                        jax.effects_barrier()
                        tt.deactivate()
                        tt.clear_records()
                        get_disturbance().clear()
                        self.engine.reset_compilation()

            fut = loop.run_in_executor(None, run_generation)
            # Sentinel-terminated drain: per-token callbacks enqueue via
            # call_soon_threadsafe BEFORE the executor job finishes, and
            # the done-callback fires on the loop after those are
            # scheduled, so FIFO order guarantees every payload precedes
            # the sentinel (no racy cancel of an in-flight queue.get).
            _DONE = object()
            fut.add_done_callback(lambda _: queue.put_nowait(_DONE))
            # Drain payloads while WATCHING the socket: a close frame (or
            # any mid-stream client traffic) must abort the in-flight
            # generation — the token callback raises _ClientGone at the
            # next token, releasing _gen_lock instead of running to
            # completion (round-2 advisor finding). A bare queue.get()
            # would never see the disconnect. recv_task is the
            # persistent watcher; on a mid-stream fire it stays
            # completed and the top of the outer loop consumes it.
            completed = False
            get_task = asyncio.ensure_future(queue.get())
            try:
                while True:
                    done, _ = await asyncio.wait(
                        {get_task, recv_task},
                        return_when=asyncio.FIRST_COMPLETED)
                    if recv_task in done:
                        m = recv_task.result()
                        if m.type == 1 and len(pending) < MAX_PENDING:
                            # Pipelined request: buffer it, keep
                            # streaming the current generation.
                            pending.append(m)
                            recv_task = asyncio.ensure_future(
                                ws.receive())
                            continue
                        if m.type == 1:
                            pending.append(m)  # outer loop closes 1008
                        break           # disconnect/flood → abort
                    payload = get_task.result()
                    if payload is _DONE:
                        completed = True
                        break
                    await ws.send_json(payload)
                    get_task = asyncio.ensure_future(queue.get())
            except (ConnectionResetError, RuntimeError):
                pass                    # TCP reset mid-send → abort
            finally:
                if not completed:
                    cancel.set()
                if not get_task.done():
                    get_task.cancel()   # queue.get cancel is loss-free
            if not completed:
                try:
                    await fut      # worker aborts at the next token
                except _ClientGone:
                    pass
                except Exception:  # noqa: BLE001 — client already gone
                    pass
                continue           # outer loop handles the fired recv
            try:
                texts = fut.result()
            except _ClientGone:
                continue
            except Exception as e:
                # Client-input-driven failures (bad flag names, malformed
                # disturbance configs) surface as an error frame, matching
                # the REST handler's 400-with-message behavior.
                await ws.send_json({"type": "error", "message": str(e)})
                continue
            await ws.send_json({"type": "done", "text": texts[0]})
        if not recv_task.done():
            recv_task.cancel()     # connection is closing anyway
        return ws

    # ------------------------------------------------------------------
    def build_app(self):
        from aiohttp import web
        app = web.Application()
        app.router.add_put("/api", self.handle_api)
        app.router.add_post("/api", self.handle_api)
        app.router.add_get("/ws", self.handle_ws)
        return app

    def run(self):
        from aiohttp import web
        web.run_app(self.build_app(), host=self.host, port=self.port)
