"""Disaggregated serving: prefill/decode sub-meshes with KV handoff.

The serving-side analogue of the reference's MegaFBD forward/backward
disaggregation (MegatronApp §4, virtual ranks on device halves —
`parallel/fbd.py` models the half-mesh construction this module reuses):
the device set splits into a PREFILL sub-mesh and a DECODE sub-mesh, so
a long prompt's prefill never occupies the decode devices and decode
token intervals stop being hostage to whoever else just connected.

Architecture (one process, one stepper thread — the
`DynamicBatchingDriver` drives `DisaggServingEngine.step()` exactly like
a plain engine):

- **Shared refcounted block pool.** One `PagedKVCache` owns all KV
  bookkeeping; its page DATA lives on the decode sub-mesh (tp > 1
  shards it over KV heads — the per-shard pools of the tp-sharded paged
  kernels). The pool carries `prefill_slots` extra page-table rows as
  prefill STAGING slots.
- **Prefill worker** (prefill sub-mesh): admits a request into a staging
  slot, runs CHUNKED prefill — fixed-size chunks through one
  `_forward_with_cache` trace against a bucket-sized dense temp cache on
  the prefill mesh — and after each chunk ships ONLY that chunk's new KV
  rows to the decode mesh, scattering them page-table-aware into the
  shared pool (`write_prompt_pages`). Prefix-cache hits are gathered
  from the pool once instead of recomputed. Chunking is the prefill-side
  scheduler: between chunks the coordinator can preempt in favor of the
  decode SLO.
- **KV handoff = page-table transfer.** When the prompt completes (first
  token sampled prefill-side on the engine's exact fold_in chain), the
  request parks until the decode engine has a free slot, then
  `PagedKVCache.transfer_slot` moves block OWNERSHIP to the decode slot:
  refcounts and page data untouched — KV is written once by prefill and
  adopted by decode with no dense copy (pinned by tests/test_disagg.py).
- **SLO-aware two-queue scheduler.** The prefill queue and the parked
  (handoff) queue are both served in (priority, request_id) order;
  over-deadline work is rejected at admission and swept while queued,
  in-flight, or parked (their staged blocks are reclaimed — the handoff
  state is a first-class lifecycle stage for `expire_overdue` /
  `abort_all`). A decode-latency budget gates prefill chunks: when the
  next chunk's EWMA-predicted cost would push the decode token interval
  past `decode_slo_ms`, the chunk is deferred (a counted
  `chunk_preemption`) and decode steps first. `/stats` and `/healthz`
  expose per-queue depth and SLO attainment.

MTP speculative decoding degrades to plain decode for adopted requests
(the proposer's pre-head hidden state is not shipped across the meshes);
ngram/draft proposers are unaffected.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from megatronapp_tpu.config.parallel_config import ParallelConfig
from megatronapp_tpu.config.transformer_config import TransformerConfig
from megatronapp_tpu.inference.dynamic_engine import (
    DeadlineExceeded, DynamicInferenceEngine, Request, _sample_batched,
    validate_admission,
)
from megatronapp_tpu.inference.engine import (
    SamplingParams, _forward_with_cache, init_kv_cache, mask_padded_vocab,
)
from megatronapp_tpu.inference.paged_cache import PagedKVCache, cdiv
from megatronapp_tpu.parallel.fbd import build_half_meshes
from megatronapp_tpu.parallel.mesh import MeshContext
from megatronapp_tpu.trace.request_trace import (
    PREFILL_PID, get_request_tracer,
)
from megatronapp_tpu.utils import metrics as telemetry
from megatronapp_tpu.utils.metrics import Histogram


def split_serving_meshes(tp: int = 1, devices=None,
                         prefill_devices: Optional[int] = None
                         ) -> Tuple[MeshContext, MeshContext]:
    """(prefill_ctx, decode_ctx) on disjoint device subsets, each a tp
    mesh — the serving analogue of `split_fbd_meshes` (same half-mesh
    construction, no DP bookkeeping: serving replicates params).

    prefill_devices=None keeps the historical even split on the first
    2*tp devices. An explicit count gives the prefill sub-mesh that many
    devices and the decode sub-mesh the REST of `devices` — the knob the
    fleet autoscaler turns (inference/fleet.py MeshSplitAutoscaler):
    EWMA decode-SLO attainment shrinks the prefill side, prefill-queue
    pressure grows it. Both sides must hold at least one whole tp
    group."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    par = ParallelConfig(tensor_parallel=tp)
    if prefill_devices is None:
        need = 2 * tp
        if len(devices) < need:
            raise ValueError(
                f"prefill/decode disaggregation at tp={tp} needs {need} "
                f"devices, have {len(devices)}")
        return build_half_meshes(par, par, devices[:need])
    n_pre = int(prefill_devices)
    n_dec = len(devices) - n_pre
    if (n_pre < tp or n_dec < tp or n_pre % tp or n_dec % tp):
        raise ValueError(
            f"uneven prefill/decode split {n_pre}/{n_dec} over "
            f"{len(devices)} devices is invalid at tp={tp}: both "
            "sub-meshes need a positive multiple of tp devices")
    from megatronapp_tpu.parallel.mesh import build_mesh
    return (build_mesh(par, devices=devices[:n_pre]),
            build_mesh(par, devices=devices[n_pre:]))


def _split2(four):
    """((k, v, k_scales, v_scales)) → ((k, v), (k_scales, v_scales))."""
    return tuple(four[:2]), tuple(four[2:])


@dataclasses.dataclass
class PrefillState:
    """One in-flight (or parked) prefill on the prefill sub-mesh."""
    req: Request
    pslot: int                    # pool STAGING slot owning the blocks
    tokens: np.ndarray            # prompt + pre-preemption generated
    p_len: int
    pos: int                      # next uncomputed position
    tmp: tuple                    # dense temp cache on the prefill mesh
    bucket: int
    done: bool = False            # all chunks computed, first token out


class PrefillWorker:
    """Chunked prefill on the prefill sub-mesh, writing KV blocks into
    the shared pool on the decode sub-mesh (see module docstring)."""

    def __init__(self, params, cfg: TransformerConfig, pool: PagedKVCache,
                 ctx: MeshContext, decode_ctx: MeshContext,
                 prefill_chunk: int, prefill_buckets, max_seq_len: int):
        import functools
        from jax.sharding import NamedSharding, PartitionSpec as P
        from megatronapp_tpu.ops.pallas.paged_attention import (
            gather_prefix_pages, quantize_kv_rows, write_prompt_pages,
        )
        self.cfg = cfg
        self.pool = pool
        self.ctx = ctx
        self.chunk = prefill_chunk
        # Buckets rounded UP to chunk multiples: every chunk — including
        # the last — then slices a full chunk-shaped KV run out of the
        # temp cache, so the ship/scatter path has ONE trace per bucket.
        # manual-ok: mesh-level placement outside any manual region.
        self._params_sharding = NamedSharding(ctx.mesh, P())
        self._decode_rep = NamedSharding(decode_ctx.mesh, P())  # manual-ok: see above
        self.params = jax.device_put(params, self._params_sharding)  # manual-ok: see above
        self.buckets = tuple(sorted({
            cdiv(max(b, prefill_chunk), prefill_chunk) * prefill_chunk
            for b in (*prefill_buckets, max_seq_len)}))
        self._prefill = jax.jit(
            functools.partial(_forward_with_cache, cfg=cfg))
        self._sample = jax.jit(_sample_batched)
        # ONE fused scatter for both pool tensors per chunk (halves the
        # per-chunk dispatch overhead), with the OUTPUT sharding pinned
        # to the pool's committed placement (tp-sharded over Hkv or
        # replicated on the decode mesh): the engine's decode jit and
        # this write alternate on the same buffers, and a sharding flip
        # between them would force a retrace every handoff.
        # manual-ok: mesh-level placement outside any manual region.
        if pool.quantized:
            # int8 pool: rows quantize ON THE PREFILL MESH (one jit) so
            # the cross-mesh handoff ships int8 rows + fp32 scales —
            # (D + 4) / (2 D) of the bf16 row bytes — and the fused
            # scatter commits all four pool tensors.
            self._quantize = jax.jit(functools.partial(
                quantize_kv_rows, dtype=pool.pages[0].dtype))

            def _write_quant(pk, pv, sk, sv, rk, rv, rsk, rsv,
                             table_row, start, count):
                w = write_prompt_pages
                return (w(pk, rk, table_row, start, count),
                        w(pv, rv, table_row, start, count),
                        w(sk, rsk, table_row, start, count),
                        w(sv, rsv, table_row, start, count))

            self._write = jax.jit(
                _write_quant, donate_argnums=(0, 1, 2, 3),
                out_shardings=(pool.pages[0].sharding,
                               pool.pages[1].sharding,
                               pool.scales[0].sharding,
                               pool.scales[1].sharding))
        else:
            def _write_both(pk, pv, rk, rv, table_row, start, count):
                return (write_prompt_pages(pk, rk, table_row, start,
                                           count),
                        write_prompt_pages(pv, rv, table_row, start,
                                           count))

            self._write = jax.jit(
                _write_both, donate_argnums=(0, 1),
                out_shardings=(pool.pages[0].sharding,
                               pool.pages[1].sharding))
        self._gather = jax.jit(gather_prefix_pages, static_argnums=(2,))
        self.stats = {"prefills_started": 0, "prefills_finished": 0,
                      "chunks": 0, "kv_shipped_bytes": 0,
                      "prefix_hit_tokens": 0}
        # Prefill-mesh events land on their own pid row of the merged
        # request trace (ISSUE 12).
        self._rt = get_request_tracer()

    def set_params(self, params):
        """Rolling reload: mirror the new weights onto the prefill mesh
        (shapes unchanged, traces stay valid)."""
        # manual-ok: host-side reload path, no manual region
        self.params = jax.device_put(params, self._params_sharding)

    # ------------------------------------------------------------------
    def start(self, req: Request, pslot: int) -> Optional[PrefillState]:
        """Admit `req` into staging slot `pslot` and set up its chunked
        prefill. Returns None (nothing mutated) when the pool cannot
        host the prompt right now."""
        tokens = req.tokens
        p_len = len(tokens)
        plan = self.pool.admit(pslot, tokens)
        if plan is None:
            return None
        # Temp cache = bucket + one spare chunk: a prefix-cache hit can
        # start chunking at pos = cached (any block multiple), so the
        # fixed-width chunk window [pos, pos + chunk) may extend past
        # p_len — without the spare row range, _forward_with_cache's
        # dynamic_update_slice/dynamic_slice would CLAMP the start index
        # (silently overwriting the gathered prefix and mis-rotating
        # rope) instead of erroring. The spare rows only ever hold
        # padding-token garbage that nothing attends causally.
        bucket = next(b for b in self.buckets if b >= p_len) + self.chunk
        tmp_np = [np.zeros(c.shape, np.float32)
                  for c in init_kv_cache(self.cfg, 1, bucket)]
        cached = plan.cached_tokens
        if cached:
            # Prefix hit: gather the cached blocks' KV out of the shared
            # pool once (decode mesh) and seed the temp cache with it —
            # the cached prefix is neither recomputed nor re-shipped.
            # int8 pools dequantize the gathered rows here (the dense
            # temp cache on the prefill mesh is compute-dtype).
            nblocks = cdiv(cached, self.pool.block_size)
            table_row = jnp.asarray(self.pool.page_table[pslot])
            if self.pool.quantized:
                for t, p, sc in zip(tmp_np, self.pool.pages,
                                    self.pool.scales):
                    rows = np.asarray(jax.device_get(
                        self._gather(p, table_row, nblocks)))[:, :cached]
                    rsc = np.asarray(jax.device_get(
                        self._gather(sc, table_row,
                                     nblocks)))[:, :cached]
                    t[:, 0, :cached] = (rows.astype(np.float32)
                                        * rsc[..., None])
            else:
                for t, p in zip(tmp_np, self.pool.pages):
                    rows = np.asarray(jax.device_get(
                        self._gather(p, table_row, nblocks)))[:, :cached]
                    t[:, 0, :cached] = rows
            self.stats["prefix_hit_tokens"] += cached
        tmp = tuple(
            # manual-ok: temp-cache placement onto the prefill mesh,
            # host-side admission path, no manual region
            jax.device_put(jnp.asarray(t, self.cfg.compute_dtype),
                           self._params_sharding)
            for t in tmp_np)
        self.stats["prefills_started"] += 1
        return PrefillState(req=req, pslot=pslot, tokens=tokens,
                            p_len=p_len, pos=cached, tmp=tmp,
                            bucket=bucket)

    def advance(self, state: PrefillState, sync: bool = True) -> bool:
        """Run ONE chunk of `state`'s prefill and ship its KV rows into
        the shared pool. Returns True when the whole prompt is computed
        (state.req then carries its first generated token). With `sync`
        the call blocks until the chunk is done — the coordinator needs
        the real chunk latency for its decode-SLO budget EWMA; without
        an SLO the chunks pipeline asynchronously against the decode
        mesh."""
        self._rt.begin("prefill-chunk", state.req.request_id,
                       pid=PREFILL_PID, pos=state.pos)
        c = min(self.chunk, state.p_len - state.pos)
        padded = np.zeros((1, self.chunk), np.int32)
        padded[0, :c] = state.tokens[state.pos:state.pos + c]
        logits, state.tmp = self._prefill(
            self.params, jnp.asarray(padded), state.tmp, state.pos)
        # Ship ONLY this chunk's rows (fixed chunk shape, count-masked
        # padding) to the decode mesh and scatter them page-table-aware
        # in one fused write. int8 pools quantize ON THE PREFILL MESH
        # first, so the handoff ships int8 rows + fp32 scales instead of
        # bf16 rows (the shipped-bytes accounting below reads the actual
        # transferred arrays either way).
        from megatronapp_tpu.utils import chaos
        table_row = jnp.asarray(self.pool.page_table[state.pslot])
        rows = []
        for t in state.tmp:
            r = t[:, 0, state.pos:state.pos + self.chunk]
            if self.pool.quantized:
                r_q, r_s = self._quantize(r)
                # manual-ok: cross-mesh handoff transfer (prefill →
                # decode), outside any manual region — the one data
                # movement of the handoff (quantized chunk rows +
                # scales, never the pool).
                rows.append((jax.device_put(r_q, self._decode_rep),
                             # manual-ok: cross-mesh handoff, see above
                             jax.device_put(r_s, self._decode_rep)))
                self.stats["kv_shipped_bytes"] += sum(
                    int(x.size) * x.dtype.itemsize for x in rows[-1])
            else:
                # manual-ok: cross-mesh handoff transfer (prefill →
                # decode), outside any manual region — the one data
                # movement of the handoff (block-granular chunk rows,
                # never the pool).
                rows.append(jax.device_put(r, self._decode_rep))
                self.stats["kv_shipped_bytes"] += int(
                    r.size) * r.dtype.itemsize
        if self.pool.quantized:
            # Chaos site "kv-quant-write": fires between quantize and
            # the page-table commit of the shipped rows — the pool is
            # untouched, state.pos unchanged, so the retry (or the
            # release path on abort) leaves the allocator audit-clean.
            chaos.fire("kv-quant-write")
            (self.pool.pages,
             self.pool.scales) = _split2(self._write(
                 self.pool.pages[0], self.pool.pages[1],
                 self.pool.scales[0], self.pool.scales[1],
                 rows[0][0], rows[1][0], rows[0][1], rows[1][1],
                 table_row, state.pos, c))
        else:
            self.pool.pages = self._write(
                self.pool.pages[0], self.pool.pages[1], rows[0], rows[1],
                table_row, state.pos, c)
        state.pos += c
        self.stats["chunks"] += 1
        telemetry.inc("disagg_prefill_chunks")
        if state.pos < state.p_len:
            if sync:
                jax.block_until_ready(logits)
            self._rt.end("prefill-chunk", state.req.request_id,
                         pid=PREFILL_PID)
            return False
        # Prompt complete: register its blocks for followers and sample
        # the first generated token with the engine's exact key chain
        # (PRNGKey(seed) ∘ request_id ∘ step) — streams are independent
        # of WHERE the prefill ran.
        self.pool.register_prefix(state.pslot, state.tokens, state.p_len)
        req = state.req
        s = req.sampling
        last = mask_padded_vocab(logits[0, c - 1], self.cfg)
        tok = int(jax.device_get(self._sample(
            last[None], jnp.asarray([s.seed], jnp.int32),
            jnp.asarray([req.request_id], jnp.int32),
            jnp.asarray([len(req.generated)], jnp.int32),
            jnp.asarray([s.temperature], jnp.float32),
            jnp.asarray([s.top_k], jnp.int32),
            jnp.asarray([s.top_p], jnp.float32),
            jnp.asarray([s.greedy], bool)))[0])
        req.generated.append(tok)
        if (tok == req.eod_id
                or len(req.generated) >= req.max_new_tokens):
            req.finished = True
        state.done = True
        self.stats["prefills_finished"] += 1
        self._rt.end("prefill-chunk", req.request_id, pid=PREFILL_PID)
        return True

    def release(self, state: PrefillState):
        """Return a staged prefill's blocks to the pool (abort/expiry
        while in flight or parked) — the handoff lifecycle stage leaks
        nothing."""
        self.pool.release(state.pslot, state.tokens,
                          min(state.pos, state.p_len))


class DisaggServingEngine:
    """Prefill/decode-disaggregated serving engine (module docstring).

    Drop-in for `DynamicInferenceEngine` behind the server's
    `DynamicBatchingDriver`: same add_request/step/has_work/abort/stats
    surface, but prompts prefill on their own sub-mesh and enter the
    decode batch by block handoff."""

    def __init__(self, params, cfg: TransformerConfig, tokenizer=None,
                 max_batch: int = 4, max_seq_len: Optional[int] = None,
                 prefill_buckets: Tuple[int, ...] = (32, 128, 512),
                 block_size: int = 16, num_blocks: Optional[int] = None,
                 enable_prefix_caching: bool = True,
                 prefill_chunk: int = 32, prefill_slots: int = 2,
                 decode_slo_ms: Optional[float] = None, tp: int = 1,
                 devices=None, spec_method: Optional[str] = None,
                 spec_k: int = 4, draft_params=None, draft_cfg=None,
                 idle_chunks_per_step: int = 4,
                 kv_cache_dtype: str = "bf16",
                 prefill_devices: Optional[int] = None,
                 fused_decode: bool = False):
        self.prefill_ctx, self.decode_ctx = split_serving_meshes(
            tp=tp, devices=devices, prefill_devices=prefill_devices)
        max_seq_len = max_seq_len or cfg.max_position_embeddings
        pool = PagedKVCache(
            cfg, max_batch, max_seq_len, num_blocks=num_blocks,
            block_size=block_size,
            enable_prefix_caching=enable_prefix_caching,
            extra_slots=prefill_slots, kv_cache_dtype=kv_cache_dtype)
        # fused_decode (--megakernel-decode) threads into the DECODE
        # engine only — eligibility is re-checked per jit build there
        # (a tp>1 decode sub-mesh keeps the unfused body with a logged
        # reason); the prefill worker's bucketed dense prefill is not a
        # decode-step shape and stays unfused.
        self.engine = DynamicInferenceEngine(
            params, cfg, tokenizer=tokenizer, max_batch=max_batch,
            max_seq_len=max_seq_len, prefill_buckets=prefill_buckets,
            paged=True, prefill_chunk=prefill_chunk,
            spec_method=spec_method, spec_k=spec_k,
            draft_params=draft_params, draft_cfg=draft_cfg,
            ctx=self.decode_ctx, pool=pool, fused_decode=fused_decode)
        self.worker = PrefillWorker(
            params, cfg, pool, self.prefill_ctx, self.decode_ctx,
            prefill_chunk, prefill_buckets, max_seq_len)
        self.cfg = cfg
        self.tokenizer = tokenizer
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len
        self.prefill_slots = prefill_slots
        self.decode_slo_s = (None if decode_slo_ms is None
                             else decode_slo_ms / 1e3)
        self.idle_chunks_per_step = idle_chunks_per_step
        self.pause_admission = False

        self.waiting: deque = deque()        # prefill queue (priority)
        self._inflight: List[PrefillState] = []
        self._parked: List[PrefillState] = []  # done, awaiting handoff
        self._aborted: List[Request] = []
        self.requests: Dict[int, Request] = self.engine.requests
        self._last_decode_t: Optional[float] = None
        self._chunk_ewma_s: Optional[float] = None
        self.slo_stats = {"decode_intervals": 0, "attained": 0,
                          "worst_interval_ms": 0.0,
                          "chunk_preemptions": 0,
                          "rejected_at_admission": 0}
        # Histogram-backed SLO accounting (ISSUE 12): token-interval and
        # TTFT percentiles replace the single worst-interval scalar as
        # the attainment signal. Private Histogram instances — live even
        # when the global metrics registry is off (the fleet router will
        # score replicas off these).
        self.interval_hist = Histogram(lo=1e-2, hi=1e6, growth=1.25)
        self.ttft_hist = Histogram(lo=1e-2, hi=1e7, growth=1.25)
        self._rt = get_request_tracer()

    # ---- engine-facade surface ------------------------------------------
    @property
    def pool(self) -> PagedKVCache:
        return self.engine.pool

    @property
    def slots(self):
        return self.engine.slots

    @property
    def paged(self) -> bool:
        return True

    @property
    def megakernel(self) -> bool:
        """Whether the decode engine's fused (megakernel) step is live
        (re-gated on every decode-jit build)."""
        return self.engine.megakernel

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self._inflight or self._parked
                    or self.engine.has_work)

    def add_request(self, prompt_tokens, max_new_tokens: int,
                    sampling: Optional[SamplingParams] = None,
                    eod_id: Optional[int] = None, priority: int = 0,
                    deadline_s: Optional[float] = None,
                    request_id: Optional[int] = None) -> int:
        """Same contract/validation as the engine's add_request (the
        shared `validate_admission`); requests enter the PREFILL queue
        (served in (priority, request_id) order — SLO-aware admission)
        instead of the decode waiting queue. `request_id` lets the
        cross-process fleet router (inference/fleet_rpc.py) mint the id."""
        try:
            prompt = validate_admission(prompt_tokens, max_new_tokens,
                                        self.max_seq_len, pool=self.pool,
                                        deadline_s=deadline_s)
        except DeadlineExceeded:
            self.slo_stats["rejected_at_admission"] += 1
            raise
        now = time.monotonic()
        if request_id is None:
            request_id = next(self.engine._ids)
        elif request_id in self.requests:
            raise ValueError(f"request id {request_id} already admitted")
        req = Request(request_id, prompt, max_new_tokens,
                      sampling or SamplingParams(), eod_id=eod_id,
                      priority=priority, deadline_s=deadline_s,
                      admit_t=now, queued_t=now)
        self.waiting.append(req)
        self.requests[req.request_id] = req
        telemetry.inc("serving_requests_admitted")
        rt = self._rt
        if rt.enabled:
            rt.instant("admit", req.request_id,
                       prompt_tokens=len(prompt), priority=priority)
            rt.begin("request", req.request_id)
            rt.begin("queue-wait", req.request_id)
        return req.request_id

    def pop_request(self, request_id: int) -> Optional[Request]:
        return self.engine.pop_request(request_id)

    def abort_request(self, request_id: int) -> Optional[str]:
        req = self.requests.get(request_id)
        if req is None:
            return None
        if req in self.waiting:
            try:
                self.waiting.remove(req)
            except ValueError:
                pass        # raced with prefill start: running below
            else:
                req.finished = True
                self._rt.finish(request_id, "abort")
                return "waiting"
        if not req.finished:
            # In-flight prefill, parked, or decoding: the next step's
            # sweep releases its blocks (staging or decode slot alike).
            req.finished = True
            self._rt.instant("abort", request_id)
            return "running"
        return None

    def expire_overdue(self, now: Optional[float] = None) -> List[int]:
        """Deadline sweep across ALL lifecycle stages — queued,
        in-flight prefill, PARKED IN HANDOFF, and decoding. Marking here;
        block reclaim happens in the same step's sweep pass, so no leak
        path opens between the sub-meshes."""
        if now is None:
            now = time.monotonic()
        expired: List[int] = []

        def overdue(r: Request) -> bool:
            return (r.deadline_s is not None and not r.finished
                    and now >= r.deadline_s)

        for _ in range(4):
            try:
                overdue_waiting = [r for r in self.waiting if overdue(r)]
                break
            except RuntimeError:
                continue
        else:
            overdue_waiting = []
        for req in overdue_waiting:
            try:
                self.waiting.remove(req)
            except ValueError:
                continue
            req.finished = True
            self._aborted.append(req)
            expired.append(req.request_id)
            self._rt.finish(req.request_id, "expire")
        for state in self._inflight + self._parked:
            if overdue(state.req):
                state.req.finished = True     # reclaimed by _sweep_staged
                expired.append(state.req.request_id)
                self._rt.instant("expire", state.req.request_id)
        if expired:
            telemetry.inc("serving_deadline_expired", len(expired))
        expired += self.engine.expire_overdue(now)
        return expired

    def abort_all(self):
        """Drop everything (server error recovery): queued, staged
        (in-flight + parked — their pool blocks are released), and the
        decode engine's own state."""
        for req in list(self.waiting):
            self.requests.pop(req.request_id, None)
            self._rt.finish(req.request_id, "abort")
        self.waiting.clear()
        for state in self._inflight + self._parked:
            try:
                self.worker.release(state)
            except Exception:  # noqa: BLE001 — best-effort reclaim
                pass
            self.requests.pop(state.req.request_id, None)
            self._rt.finish(state.req.request_id, "abort")
        self._inflight = []
        self._parked = []
        self.engine.abort_all()

    def set_params(self, params):
        """Rolling reload: swap weights on BOTH sub-meshes (the raw
        host-side pytree is placed onto each mesh independently)."""
        self.engine.set_params(params)
        self.worker.set_params(params)

    # ---- live session migration (ISSUE 14, inference/fleet.py) ----------
    # Only sessions already in a DECODE slot are exportable (their KV is
    # complete in the shared pool); in-flight/parked prefills and queued
    # requests return None from export and migrate by requeue instead —
    # the engine-level export checks slot occupancy, so the delegation
    # is safe for every lifecycle stage.
    def export_request(self, rid: int) -> Optional[dict]:
        return self.engine.export_request(rid)

    def import_request(self, payload: dict) -> bool:
        return self.engine.import_request(payload)

    def release_exported(self, rid: int):
        return self.engine.release_exported(rid)

    def free_decode_slots(self) -> int:
        return self.engine.free_decode_slots()

    def drained_for_reload(self) -> bool:
        """True when a params swap is safe: no decode slot occupied, no
        prefill mid-flight, and nothing PARKED in handoff — a parked
        request's prompt KV was computed with the old weights, so it
        must adopt and finish on them (adoption keeps running while
        admission is paused) before the swap lands. Queued work holds
        its position and prefills on the new weights."""
        return (not self._inflight and not self._parked
                and all(r is None for r in self.engine.slots))

    # ---- scheduling internals -------------------------------------------
    def _pop_priority(self) -> Optional[Request]:
        """Pop the highest-priority (lowest (priority, request_id))
        waiting request; tolerant of concurrent submit/abort mutation
        like the engine's expiry sweep."""
        for _ in range(4):
            try:
                snapshot = sorted(self.waiting,
                                  key=lambda r: (r.priority,
                                                 r.request_id))
                break
            except RuntimeError:
                continue
        else:
            return None
        for req in snapshot:
            try:
                self.waiting.remove(req)
            except ValueError:
                continue
            return req
        return None

    def _free_pslot(self) -> Optional[int]:
        used = {s.pslot for s in self._inflight + self._parked}
        for i in range(self.max_batch,
                       self.max_batch + self.prefill_slots):
            if i not in used:
                return i
        return None

    def _sweep_staged(self, events):
        """Release staged (in-flight/parked) requests aborted or expired
        since the last step — the handoff lifecycle stage reclaims its
        blocks exactly like a decode slot does."""
        for lst in (self._inflight, self._parked):
            for state in list(lst):
                if state.req.finished:
                    self.worker.release(state)
                    lst.remove(state)
                    events["finished"].append(state.req.request_id)
                    # abort/expire instants already fired at the mark
                    # site; this closes every span still open (prefill
                    # on the prefill pid, handoff-parked, request).
                    self._rt.finish(state.req.request_id)

    def _adopt_parked(self, events):
        """Hand finished prefills to the decode side in (priority, rid)
        order while it has free slots: pure page-table/refcount
        transfer, no KV movement."""
        for state in sorted(self._parked,
                            key=lambda s: (s.req.priority,
                                           s.req.request_id)):
            if self.engine.free_decode_slots() == 0:
                break
            self._parked.remove(state)
            self._rt.end("handoff-parked", state.req.request_id)
            self.engine.adopt_request(state.req, state.pslot,
                                      state.p_len)
            events["admitted"].append(state.req.request_id)

    def _start_prefills(self, events):
        while not self.pause_admission:
            pslot = self._free_pslot()
            if pslot is None:
                return
            req = self._pop_priority()
            if req is None:
                return
            if req.finished:               # aborted while queued
                self._aborted.append(req)
                continue
            state = self.worker.start(req, pslot)
            if state is None:
                # Pool pressure: strict priority — the head of the queue
                # waits for blocks rather than letting lower-priority
                # work overtake it.
                self.waiting.appendleft(req)
                return
            self._inflight.append(state)
            rt = self._rt
            rt.end("queue-wait", req.request_id)
            telemetry.observe("serving_queue_wait_ms",
                              (time.monotonic() - req.queued_t) * 1e3)
            rt.begin("prefill", req.request_id, pid=PREFILL_PID,
                     prompt_tokens=state.p_len,
                     cached_tokens=state.pos)

    def _prefill_budget_chunks(self, t_decode_done: float,
                               decode_active: bool) -> None:
        """Run prefill chunks under the decode-latency budget: chunks
        keep running while the EWMA-predicted next-chunk cost fits
        inside the decode SLO window; the first deferred chunk counts as
        a preemption. With no active decode the budget is a simple
        per-step chunk cap (keep TTFT moving, return control to the
        stepper regularly)."""
        ran = 0
        cap = 1 if decode_active else self.idle_chunks_per_step
        while self._inflight:
            state = min(self._inflight,
                        key=lambda s: (s.req.priority, s.req.request_id))
            if decode_active and self.decode_slo_s is not None:
                est = self._chunk_ewma_s or 0.0
                elapsed = time.monotonic() - t_decode_done
                if elapsed + est > 0.8 * self.decode_slo_s:
                    if state.pos < state.p_len:
                        self.slo_stats["chunk_preemptions"] += 1
                    return
            elif ran >= cap:
                return
            t0 = time.monotonic()
            done = self.worker.advance(
                state, sync=self.decode_slo_s is not None)
            dt = time.monotonic() - t0
            self._chunk_ewma_s = (dt if self._chunk_ewma_s is None
                                  else 0.5 * self._chunk_ewma_s
                                  + 0.5 * dt)
            ran += 1
            if done:
                self._inflight.remove(state)
                self._finish_prefill(state)

    def _finish_prefill(self, state: PrefillState):
        """Prompt fully computed: emit the first token (next step's
        events) and park for handoff — or finish outright when the
        request is already done (max_new_tokens == 1 / immediate eod /
        aborted mid-prompt)."""
        rid = state.req.request_id
        self._rt.end("prefill", rid, pid=PREFILL_PID)
        if len(state.req.generated) == 1:
            # First completion only: a preempted request resumes through
            # the prefill queue with generated tokens already recorded —
            # its Nth token is not a TTFT sample (duplicate, oversized
            # observations would inflate the replica-scoring
            # percentiles).
            ttft_ms = (time.monotonic() - state.req.admit_t) * 1e3
            self.ttft_hist.observe(ttft_ms)
            telemetry.observe("serving_ttft_ms", ttft_ms)
        self._first_tokens.append((state.req.request_id,
                                   state.req.generated[-1]))
        if state.req.finished:
            self.worker.release(state)
            self._finished_staged.append(state.req.request_id)
            telemetry.inc("serving_requests_retired")
            self._rt.finish(rid, "retire",
                            generated=len(state.req.generated))
        else:
            self._parked.append(state)
            self._rt.begin("handoff-parked", rid)

    # ---- main loop -------------------------------------------------------
    def step(self) -> Dict[str, List]:
        """One coordinator round: sweep deadlines → reclaim staged
        aborts → adopt parked prefills → decode step (decode sub-mesh) →
        budgeted prefill chunks (prefill sub-mesh). Event dict matches
        the plain engine's contract."""
        self._first_tokens: List = []
        self._finished_staged: List[int] = []
        expired = self.expire_overdue()
        events = {"admitted": [], "tokens": [], "finished": [],
                  "preempted": [], "expired": expired}
        self._sweep_staged(events)
        self._adopt_parked(events)
        self._start_prefills(events)

        decode_active = any(
            r is not None and not r.finished for r in self.engine.slots)
        if not decode_active:
            # Idle gap: a stale timestamp would charge the whole gap to
            # the first post-idle decode interval and poison worst/
            # attainment — intervals only measure back-to-back decodes.
            self._last_decode_t = None
        if decode_active or self.engine.waiting:
            t0 = time.monotonic()
            if decode_active and self._last_decode_t is not None:
                interval = t0 - self._last_decode_t
                self.slo_stats["decode_intervals"] += 1
                self.slo_stats["worst_interval_ms"] = max(
                    self.slo_stats["worst_interval_ms"], interval * 1e3)
                self.interval_hist.observe(interval * 1e3)
                if (self.decode_slo_s is None
                        or interval <= self.decode_slo_s):
                    self.slo_stats["attained"] += 1
            ev = self.engine.step()
            if decode_active:
                self._last_decode_t = time.monotonic()
            for key in ("tokens", "finished", "preempted", "expired"):
                events[key] += ev[key]
            # Decode-side preemptions re-enter through the PREFILL queue
            # (they re-prefill prompt+generated on the prefill mesh,
            # usually re-hitting their own cached blocks) — the decode
            # mesh never runs a prefill.
            for rid in ev["preempted"]:
                req = self.requests.get(rid)
                if req is not None and req in self.engine.waiting:
                    try:
                        self.engine.waiting.remove(req)
                    except ValueError:
                        continue
                    # (queued_t was already stamped by the engine's
                    # _preempt; the move between queues is instant.)
                    self.waiting.append(req)
        t_decode_done = time.monotonic()

        self._prefill_budget_chunks(t_decode_done, decode_active)

        events["tokens"] += self._first_tokens
        events["finished"] += self._finished_staged
        events["finished"] += [r.request_id for r in self._aborted]
        self._aborted = []
        return events

    def run_to_completion(self, token_callback=None
                          ) -> Dict[int, np.ndarray]:
        results: Dict[int, np.ndarray] = {}
        finished: Dict[int, Request] = {}
        while self.has_work:
            ev = self.step()
            if token_callback is not None:
                for rid, tok in ev["tokens"]:
                    token_callback(rid, tok)
            for rid in ev["finished"]:
                finished[rid] = self.requests[rid]
        for rid, req in finished.items():
            results[rid] = req.tokens
            self.requests.pop(rid, None)
        return results

    # ---- observability ---------------------------------------------------
    def reset_compilation(self):
        self.engine.reset_compilation()

    def stats_snapshot(self, include_dispatch: bool = False) -> Dict:
        """Engine snapshot + the disagg section: per-queue depths, SLO
        attainment (histogram-backed percentiles, ISSUE 12), handoff
        accounting (the /stats payload). include_dispatch forwards to
        the decode engine's compiled-dispatch accounting (ISSUE 11) —
        the facade accepts the same kwarg as the plain engine, so the
        server no longer TypeError-falls-back to a dispatch-less
        snapshot."""
        out = self.engine.stats_snapshot(include_dispatch=include_dispatch)
        out["engine"] = "disagg"
        s = dict(self.slo_stats)
        n = s["decode_intervals"]
        ih, th = self.interval_hist, self.ttft_hist
        if n:
            # Percentiles estimated FROM the log-bucket histogram — the
            # fleet-scale signal the single worst-interval scalar could
            # not provide (worst_interval_ms stays for compatibility).
            s["interval_p50_ms"] = round(ih.percentile(50), 3)
            s["interval_p90_ms"] = round(ih.percentile(90), 3)
            s["interval_p99_ms"] = round(ih.percentile(99), 3)
        if th.count:
            s["ttft_p50_ms"] = round(th.percentile(50), 3)
            s["ttft_p99_ms"] = round(th.percentile(99), 3)
        out["disagg"] = {
            "prefill_devices": self.prefill_ctx.num_devices,
            "decode_devices": self.decode_ctx.num_devices,
            "tp": self.decode_ctx.tp,
            "queues": {
                "prefill_waiting": len(self.waiting),
                "prefill_inflight": len(self._inflight),
                "handoff_parked": len(self._parked),
                "decode_active": sum(
                    1 for r in self.engine.slots if r is not None),
            },
            "slo": {
                "decode_slo_ms": (None if self.decode_slo_s is None
                                  else self.decode_slo_s * 1e3),
                "attainment": (round(s["attained"] / n, 4) if n
                               else 1.0),
                **s,
            },
            "handoff": {
                "transfers": self.pool.stats["handoff_transfers"],
                # Actual transferred bytes (int8 rows + fp32 scales on a
                # quantized pool — ~(D+4)/2D of the bf16 rows), read off
                # the shipped arrays, never assumed from the param
                # dtype.
                "kv_shipped_bytes":
                    self.worker.stats["kv_shipped_bytes"],
                "kv_cache_dtype": self.pool.kv_cache_dtype,
                "dense_copies": 0,     # by construction: transfer_slot
            },
            "prefill_worker": dict(self.worker.stats),
        }
        return out

    def generate_text(self, prompts, max_new_tokens: int,
                      sampling: Optional[SamplingParams] = None,
                      token_callback=None):
        """String-level API (mirrors DynamicInferenceEngine)."""
        assert self.tokenizer is not None, "tokenizer required"
        eod = getattr(self.tokenizer, "eod", None)
        rids = []
        for prompt in prompts:
            ids = np.asarray(self.tokenizer.tokenize(prompt), np.int32)
            rids.append(self.add_request(ids, max_new_tokens, sampling,
                                         eod_id=eod))
        cb = None
        if token_callback is not None:
            def cb(rid, tok):
                token_callback(rid, np.asarray([tok]), None)
        results = self.run_to_completion(token_callback=cb)
        texts = []
        for prompt, rid in zip(prompts, rids):
            n_prompt = len(self.tokenizer.tokenize(prompt))
            new_ids = results[rid][n_prompt:].tolist()
            if eod is not None and eod in new_ids:
                new_ids = new_ids[: new_ids.index(eod)]
            texts.append(self.tokenizer.detokenize(new_ids))
        return texts
