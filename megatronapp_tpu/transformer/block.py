"""Transformer layer + block (scan-over-layers).

Parity with /root/reference/megatron/core/transformer/transformer_layer.py:237
(TransformerLayer) and transformer_block.py:220 (TransformerBlock). The
reference builds a Python list of layer modules and loops; here per-layer
params are *stacked* along a leading 'layers' axis and the block runs
``jax.lax.scan`` over them — one compiled layer body regardless of depth
(TPU-first: fast compiles, natural fit for pipeline chunking and remat).

Pre-LN residual structure (reference: input_layernorm → attn → +residual →
pre_mlp_layernorm → mlp → +residual).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from megatronapp_tpu.config.transformer_config import (
    NormKind, TransformerConfig,
)
from megatronapp_tpu.ops.normalization import apply_norm
from megatronapp_tpu.transformer.attention import (
    attention_forward, init_attention_params,
)
from megatronapp_tpu.transformer.mlp import init_mlp_params, mlp_forward
from megatronapp_tpu.transformer.moe import init_moe_params, moe_forward
from megatronapp_tpu.scope.hooks import scope_capture


def init_layer_params(rng, cfg: TransformerConfig, force_dense: bool = False):
    """One layer's params + logical axes (unstacked)."""
    # Scaled init for residual-out projections: std/sqrt(2*num_layers)
    # (reference scaled_init_method_normal, training/utils).
    out_std = cfg.init_method_std / jnp.sqrt(2.0 * cfg.num_layers)
    k_attn, k_mlp = jax.random.split(rng)
    if cfg.multi_latent_attention:
        from megatronapp_tpu.transformer.mla import init_mla_params
        attn_p, attn_ax = init_mla_params(k_attn, cfg, out_std)
    else:
        attn_p, attn_ax = init_attention_params(k_attn, cfg, out_std)
    p = {
        "ln1_scale": jnp.ones((cfg.hidden_size,), cfg.params_dtype),
        "ln2_scale": jnp.ones((cfg.hidden_size,), cfg.params_dtype),
        "attention": attn_p,
    }
    ax = {
        "ln1_scale": ("embed",),
        "ln2_scale": ("embed",),
        "attention": attn_ax,
    }
    if cfg.normalization == NormKind.layernorm:
        p["ln1_bias"] = jnp.zeros((cfg.hidden_size,), cfg.params_dtype)
        p["ln2_bias"] = jnp.zeros((cfg.hidden_size,), cfg.params_dtype)
        ax["ln1_bias"] = ("embed",)
        ax["ln2_bias"] = ("embed",)
    if cfg.is_moe and not force_dense:
        p["moe"], ax["moe"] = init_moe_params(k_mlp, cfg, out_std)
    else:
        p["mlp"], ax["mlp"] = init_mlp_params(k_mlp, cfg, out_std)
    return p, ax


def layer_forward(p, x: jnp.ndarray, cfg: TransformerConfig,
                  rope_cos=None, rope_sin=None, attention_mask=None,
                  layer_id=None, kv_cache=None, cache_index=None,
                  cache_positions=None, ctx=None,
                  zigzag: bool = False, segment_ids=None,
                  page_table=None, active=None, chunk_counts=None,
                  tp_sharded: bool = False, kv_scales=None,
                  fused_decode: bool = False, fp8=None, lora=None):
    """One transformer layer. x: [B,S,H] → ((out, new_cache), aux_losses).

    page_table/active: paged-KV decode (inference/paged_cache.py) —
    kv_cache is then the per-layer block pool and each batch row appends
    at its own page-table position (see attention.py / mla.py).
    kv_scales: per-layer fp32 scale pools marking a quantized paged pool
    (see attention.py; MLA: per-row scalar scales on the latent/pe
    pools, see mla.py); new_cache then carries four pools.

    tp_sharded: ambient-manual tp-sharded stage body (pp pipeline) — x is
    the local [B, S/tp, H] seq chunk; norms/residuals run on it directly
    (elementwise over seq) and the sublayers take their ring paths.

    fused_decode: megakernel decode body (ISSUE 11) — the s == 1 paged
    decode layer runs as the three fused Pallas kernels around the
    generated paged-attention kernel (ops/pallas/kernel_gen.py
    fused_layer_decode) instead of the ~15-fusion unfused tail. Callers
    (DynamicInferenceEngine fused_decode=True) gate eligibility via
    kernel_gen.megakernel_ineligible_reason; streams stay token-exact.

    fp8: this layer's delayed-scaling amax state (training/fp8.py,
    ISSUE 13) — {"attention": {"qkv", "out"}, "mlp": {"fc1", "fc2"}}
    sub-dicts threaded into the tp-overlap ring GEMMs; the updated
    histories travel out through their cotangents.

    lora: batched per-row adapter deltas (inference/lora.py, ISSUE 19) —
    {"row_adapter": [B] int32 bank slots, "banks": {target: (a, b)}}
    with THIS layer's factor banks a [slots, din, r] / b [slots, r, dout]
    per RESIDENT_KERNELS target. Serving paths only: each projection
    matmul grows a ``base(x) + B_i A_i x`` delta (unfused via
    kernel_gen.apply_lora_delta, fused via the megakernel LoRA
    epilogues); slot 0 is the all-zero null adapter."""
    if fused_decode:
        if page_table is None or kv_cache is None or "moe" in p:
            raise ValueError(
                "fused_decode covers the dense-MLP paged "
                "decode/multiquery bodies only — gate callers on "
                "kernel_gen.megakernel_ineligible_reason")
        from megatronapp_tpu.ops.pallas.kernel_gen import (
            fused_layer_decode, fused_layer_multiquery,
        )
        if chunk_counts is not None:
            # Ragged multi-token rows (speculative verify / chunked
            # prefill): the fused kernels run on the flattened B·S rows
            # around the ragged paged-attention kernel.
            return fused_layer_multiquery(
                p, x, cfg, rope_cos, rope_sin, kv_cache,
                cache_positions, chunk_counts, page_table, active,
                kv_scales=kv_scales, lora=lora)
        if x.shape[1] != 1:
            raise ValueError(
                "fused_decode without chunk_counts is the s == 1 "
                "decode body — pass chunk_counts for ragged "
                "multi-token steps")
        return fused_layer_decode(p, x, cfg, rope_cos, rope_sin, kv_cache,
                                  cache_positions, page_table, active,
                                  kv_scales=kv_scales, lora=lora)
    residual = x
    h = apply_norm(cfg.normalization, x, p["ln1_scale"], p.get("ln1_bias"),
                   cfg.layernorm_epsilon)
    if cfg.multi_latent_attention:
        if lora is not None:
            raise ValueError(
                "lora serving targets the GQA projection kernels — MLA "
                "has no q_kernel/kv_kernel (lora.AdapterCache rejects "
                "MLA configs at construction)")
        from megatronapp_tpu.transformer.mla import mla_forward
        if segment_ids is not None:
            # MLA routes through the reference attention impl — packed
            # segments densify into the mask here.
            seg_mask = (segment_ids[:, None, :, None]
                        == segment_ids[:, None, None, :])
            attention_mask = (seg_mask if attention_mask is None
                              else attention_mask & seg_mask)
        if kv_cache is not None:
            attn_out, new_cache = mla_forward(
                p["attention"], h, cfg, rope_cos, rope_sin, attention_mask,
                layer_id=layer_id, ctx=ctx, kv_cache=kv_cache,
                cache_index=cache_index, cache_positions=cache_positions,
                page_table=page_table, active=active,
                chunk_counts=chunk_counts, kv_scales=kv_scales)
        else:
            attn_out = mla_forward(
                p["attention"], h, cfg, rope_cos, rope_sin, attention_mask,
                layer_id=layer_id, ctx=ctx, tp_sharded=tp_sharded)
            new_cache = None
    else:
        attn_out, new_cache = attention_forward(
            p["attention"], h, cfg, rope_cos, rope_sin, attention_mask,
            kv_cache=kv_cache, cache_index=cache_index,
            cache_positions=cache_positions, layer_id=layer_id,
            ctx=ctx, zigzag=zigzag, segment_ids=segment_ids,
            page_table=page_table, active=active,
            chunk_counts=chunk_counts, tp_sharded=tp_sharded,
            kv_scales=kv_scales,
            fp8=None if fp8 is None else fp8["attention"],
            lora=lora)
    # Tag for the 'selective_attn' remat policy (a no-op otherwise).
    attn_out = checkpoint_name(attn_out, "attn_out")
    x = residual + attn_out.astype(residual.dtype)

    residual = x
    h = apply_norm(cfg.normalization, x, p["ln2_scale"], p.get("ln2_bias"),
                   cfg.layernorm_epsilon)
    aux = None
    if "moe" in p:
        if fp8 is not None:
            raise ValueError("fp8 does not support MoE layers "
                             "(fp8_ineligible_reason gates this off)")
        if lora is not None:
            raise ValueError("lora serving targets the dense fc1/fc2 "
                             "kernels — MoE layers are unsupported")
        mlp_out, aux = moe_forward(p["moe"], h, cfg, layer_id=layer_id,
                                   ctx=ctx, tp_sharded=tp_sharded)
    else:
        mlp_out = mlp_forward(p["mlp"], h, cfg, layer_id=layer_id, ctx=ctx,
                              tp_sharded=tp_sharded,
                              fp8=None if fp8 is None else fp8["mlp"],
                              lora=lora)
    x = residual + mlp_out.astype(residual.dtype)
    # MegaScope 'system' perturbation + capture site between layers
    # (transformer_block.py:542-544).
    from megatronapp_tpu.scope.disturbance import get_disturbance
    x = get_disturbance().apply("system", x, layer_id)
    x = scope_capture("between_layers", x, layer_id)
    return (x, new_cache), aux


def _remat_wrap(fn, policy: str):
    if policy == "full":
        return jax.checkpoint(fn, policy=jax.checkpoint_policies.nothing_saveable)
    if policy == "selective":
        # Save matmul outputs, recompute the rest (attention softmax etc.) —
        # semantics of the reference --recompute-activations selective mode.
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    if policy == "selective_attn":
        # Selective + the tagged attention outputs: skips the flash-kernel
        # forward recompute in the backward pass for one [B,S,H] bf16
        # residual per layer (~6 MB/layer at GPT-2 125M shapes) — trades a
        # little HBM for the kernel re-execution.
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                jax.checkpoint_policies.save_only_these_names("attn_out")))
    return fn


from megatronapp_tpu.parallel.sharding import is_logical_axes as _is_axes


def _stack_layers(per_layer, extra_axis: str = "layers"):
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs),
                           *[p for p, _ in per_layer])
    ax = jax.tree.map(lambda axes: (extra_axis,) + axes, per_layer[0][1],
                      is_leaf=_is_axes)
    return stacked, ax


def init_block_params(rng, cfg: TransformerConfig, num_layers: int = None):
    """Stacked layer params for lax.scan.

    Uniform case: every leaf gains a leading [L] 'layers' axis.
    moe_layer_freq > 1 (reference transformer_config moe_layer_freq int
    pattern — layer i is MoE iff i % freq == 0): layers are grouped into
    L/freq scan units of {1 MoE layer + (freq-1) dense layers}, stacked as
    {'moe': [G,...], 'dense': [G, freq-1, ...]} so the scan body stays
    uniform (TPU-first: one compiled group body).
    """
    n = num_layers or cfg.num_layers
    if getattr(cfg, "hetero_block_specs", None):
        from megatronapp_tpu.transformer.heterogeneous import (
            init_hetero_block_params,
        )
        return init_hetero_block_params(rng, cfg)
    freq = cfg.moe_layer_freq if cfg.is_moe else 1
    if freq == 1:
        keys = jax.random.split(rng, n)
        return _stack_layers([init_layer_params(k, cfg) for k in keys])

    if n % freq != 0:
        raise ValueError(f"num_layers={n} not divisible by "
                         f"moe_layer_freq={freq}")
    groups = n // freq
    keys = jax.random.split(rng, n)
    moe_layers, dense_groups = [], []
    for g in range(groups):
        moe_layers.append(init_layer_params(keys[g * freq], cfg))
        dense = [init_layer_params(keys[g * freq + 1 + j], cfg,
                                   force_dense=True)
                 for j in range(freq - 1)]
        dense_groups.append(_stack_layers(dense, extra_axis="stage_layers"))
    moe_p, moe_ax = _stack_layers(moe_layers)
    dense_p, dense_ax = _stack_layers(dense_groups, extra_axis="layers")
    return ({"moe": moe_p, "dense": dense_p},
            {"moe": moe_ax, "dense": dense_ax})


def block_forward(stacked_p, x: jnp.ndarray, cfg: TransformerConfig,
                  rope_cos=None, rope_sin=None, attention_mask=None,
                  layer_offset: int = 0, ctx=None, zigzag: bool = False,
                  segment_ids=None, tp_sharded: bool = False, fp8=None):
    """Run all stacked layers via lax.scan. Returns (x, moe_aux_sum).

    tp_sharded: thread the ambient-manual tp-sharded stage-body path
    through every layer (pp pipeline; see layer_forward).

    fp8: layer-stacked delayed-scaling amax state (training/fp8.py,
    leaves [L, n_tensors, H]) — rides the SAME layer scan as the
    stacked params, so each layer's ring GEMMs see their own history
    slice and the scan's xs-cotangent stacks the updated histories
    back to [L, ...] for the train step."""
    if fp8 is not None and (getattr(cfg, "hetero_block_specs", None)
                            or (isinstance(stacked_p, dict)
                                and "dense" in stacked_p)):
        raise ValueError("fp8 does not support heterogeneous / "
                         "MoE-interleaved layer stacks "
                         "(fp8_ineligible_reason gates this off)")
    if getattr(cfg, "hetero_block_specs", None):
        if segment_ids is not None or zigzag:
            raise NotImplementedError(
                "heterogeneous per-layer configs do not compose with "
                "packed sequences or zigzag CP yet")
        from megatronapp_tpu.transformer.heterogeneous import (
            hetero_block_forward,
        )
        return hetero_block_forward(
            stacked_p, x, cfg, rope_cos, rope_sin, attention_mask,
            layer_offset=layer_offset, ctx=ctx)
    hetero = isinstance(stacked_p, dict) and "dense" in stacked_p

    def run_layer(layer_p, h, lid, fp8_l=None):
        (h2, _), aux = layer_forward(
            layer_p, h, cfg, rope_cos, rope_sin, attention_mask,
            layer_id=lid, ctx=ctx, zigzag=zigzag,
            segment_ids=segment_ids, tp_sharded=tp_sharded, fp8=fp8_l)
        return h2, (aux if aux is not None
                    else jnp.zeros((), jnp.float32))

    if not hetero:
        def body(carry, layer_in):
            h, lid = carry
            if fp8 is not None:
                layer_p, fp8_l = layer_in
            else:
                layer_p, fp8_l = layer_in, None
            h2, aux = run_layer(layer_p, h, lid, fp8_l)
            return (h2, lid + 1), aux

        body = _remat_wrap(body, cfg.remat_policy)
        xs = stacked_p if fp8 is None else (stacked_p, fp8)
        (x, _), aux = jax.lax.scan(
            body, (x, jnp.int32(layer_offset)), xs,
            unroll=cfg.scan_unroll)
        return x, jnp.sum(aux)

    freq = cfg.moe_layer_freq

    def group_body(carry, group_p):
        h, lid = carry
        h, aux_moe = run_layer(group_p["moe"], h, lid)

        def dense_body(inner, layer_p):
            hh, l = inner
            hh, a = run_layer(layer_p, hh, l)
            return (hh, l + 1), a

        (h, _), aux_dense = jax.lax.scan(
            dense_body, (h, lid + 1), group_p["dense"],
            unroll=cfg.scan_unroll)
        return (h, lid + freq), aux_moe + jnp.sum(aux_dense)

    group_body = _remat_wrap(group_body, cfg.remat_policy)
    (x, _), aux = jax.lax.scan(
        group_body, (x, jnp.int32(layer_offset)), stacked_p,
        unroll=cfg.scan_unroll)
    return x, jnp.sum(aux)
