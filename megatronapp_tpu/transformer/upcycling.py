"""Dense → MoE checkpoint upcycling.

Parity with /root/reference/megatron/core/transformer/moe/
upcycling_utils.py (upcycle_state_dict / load_and_upcycle_model): a
pretrained dense model seeds a MoE model — every expert starts as a copy
of the dense MLP (fc1/fc2 replicated across the expert axis), the router
is freshly initialized, and all non-MLP parameters carry over unchanged.

Works on the stacked [L, ...] parameter layout (transformer/block.py):
dense p["block"]["mlp"] {fc1_kernel [L,H,F], fc2_kernel [L,F,H]} maps to
moe {fc1_kernel [L,E,H,F], fc2_kernel [L,E,F,H]}. Targets the uniform
MoE stack only (moe_layer_freq=1); grouped stacks raise.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import TransformerConfig


def _broadcast_expert(kernel: jnp.ndarray, num_experts: int) -> jnp.ndarray:
    """[L, a, b] → [L, E, a, b] (every expert = the dense MLP)."""
    return jnp.broadcast_to(
        kernel[:, None], (kernel.shape[0], num_experts) + kernel.shape[1:]
    ).copy()


def upcycle_params(dense_params, dense_cfg: TransformerConfig,
                   moe_cfg: TransformerConfig, rng=None):
    """Dense GPT/BERT params → MoE params for `moe_cfg`.

    moe_cfg must match dense_cfg in every architecture dim except the MoE
    fields; moe_ffn_hidden_size must equal the dense ffn_hidden_size
    (expert = copy of the dense MLP, upcycling_utils.py:115-136).
    Returns a NEW params pytree; `rng` seeds the fresh routers.
    """
    if moe_cfg.num_moe_experts is None:
        raise ValueError("moe_cfg has no experts — nothing to upcycle to")
    if moe_cfg.moe_ffn_hidden_size != dense_cfg.ffn_hidden_size:
        raise ValueError(
            f"moe_ffn_hidden_size ({moe_cfg.moe_ffn_hidden_size}) must "
            f"equal the dense ffn_hidden_size "
            f"({dense_cfg.ffn_hidden_size}) for weight-copy upcycling")
    if moe_cfg.moe_layer_freq != 1:
        raise NotImplementedError(
            "upcycling targets the uniform MoE stack (moe_layer_freq=1); "
            "grouped stacks would need per-slot mapping")
    for f in ("num_layers", "hidden_size", "num_attention_heads",
              "ffn_hidden_size", "vocab_size"):
        if getattr(moe_cfg, f) != getattr(dense_cfg, f):
            raise ValueError(f"cfg mismatch on {f}")

    rng = jax.random.PRNGKey(0) if rng is None else rng
    e = moe_cfg.num_moe_experts
    h = moe_cfg.hidden_size
    std = moe_cfg.init_method_std

    new = dict(dense_params)
    block = dict(dense_params["block"])
    mlp = block.pop("mlp")
    L = mlp["fc1_kernel"].shape[0]
    moe = {
        "router_kernel": jax.random.normal(
            rng, (L, h, e), jnp.float32) * std,
        "fc1_kernel": _broadcast_expert(mlp["fc1_kernel"], e),
        "fc2_kernel": _broadcast_expert(mlp["fc2_kernel"], e),
    }
    if moe_cfg.moe_shared_expert_intermediate_size:
        raise NotImplementedError(
            "dense checkpoints carry no shared-expert weights; upcycle "
            "into a config without shared experts")
    block["moe"] = moe
    new["block"] = block
    return new


def moe_config_from_dense(dense_cfg: TransformerConfig, *,
                          num_experts: int, topk: int = 2,
                          aux_loss_coeff: float = 1e-2,
                          **overrides) -> TransformerConfig:
    """The canonical upcycling target config: same dims, experts added
    (reference examples: --moe-use-upcycling with num_experts)."""
    return dataclasses.replace(
        dense_cfg, num_moe_experts=num_experts, moe_router_topk=topk,
        moe_aux_loss_coeff=aux_loss_coeff,
        moe_ffn_hidden_size=dense_cfg.ffn_hidden_size, **overrides)
