"""Mixture-of-Experts layer (top-k router, EP-sharded experts).

Parity with /root/reference/megatron/core/transformer/moe/ — TopKRouter
(router.py:102), token dispatchers (token_dispatcher.py:114,248,909), grouped
experts (experts.py:90 GroupedMLP), shared experts, aux-loss balancing
(moe_utils.py). The reference dispatches tokens with explicit
allgather/all-to-all collectives; TPU-first, we build GShard-style dispatch/
combine einsums against experts stacked on an 'experts'-sharded leading axis —
XLA lowers the token exchange to a ragged all-to-all over the 'ep' mesh axis.

Two dispatch modes, matching the reference's semantics:
- moe_capacity_factor=None (the reference DEFAULT): exact dropless —
  token copies are sorted by expert and run through ``lax.ragged_dot``
  grouped GEMMs (static shapes, no capacity buffer, no token dropping;
  the reference's allgather/a2a dispatchers with no capacity).
- moe_capacity_factor=F: GShard capacity dispatch (tokens beyond
  F*T*k/E per expert dropped, prob-weighted combine) — the reference's
  --moe-expert-capacity-factor path; the GroupedMLP becomes one batched
  einsum over the expert axis (MXU-friendly).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import TransformerConfig
from megatronapp_tpu.ops.activations import apply_activation, is_gated


def init_moe_params(rng, cfg: TransformerConfig, out_std: float):
    h = cfg.hidden_size
    f = cfg.moe_ffn_hidden_size
    e = cfg.num_moe_experts
    k_router, k1, k2, k_shared = jax.random.split(rng, 4)
    std = cfg.init_method_std
    fc1_out = 2 * f if is_gated(cfg.activation) else f
    p = {
        # Router in fp32 (reference router.py keeps router params fp32).
        "router_kernel": jax.random.normal(k_router, (h, e), jnp.float32) * std,
        "fc1_kernel": jax.random.normal(k1, (e, h, fc1_out), cfg.params_dtype) * std,
        "fc2_kernel": jax.random.normal(k2, (e, f, h), cfg.params_dtype) * out_std,
    }
    ax = {
        "router_kernel": ("embed", None),
        "fc1_kernel": ("experts", "embed", "mlp"),
        "fc2_kernel": ("experts", "mlp", "embed"),
    }
    if cfg.moe_shared_expert_intermediate_size:
        fs = cfg.moe_shared_expert_intermediate_size
        shared_out = 2 * fs if is_gated(cfg.activation) else fs
        ks1, ks2 = jax.random.split(k_shared)
        p["shared_fc1"] = jax.random.normal(ks1, (h, shared_out), cfg.params_dtype) * std
        p["shared_fc2"] = jax.random.normal(ks2, (fs, h), cfg.params_dtype) * out_std
        ax["shared_fc1"] = ("embed", "mlp")
        ax["shared_fc2"] = ("mlp", "embed")
    return p, ax


def _router(p, x_flat: jnp.ndarray, cfg: TransformerConfig,
            stats_mean=None):
    """Top-k softmax router with load-balance + z losses.

    x_flat: [T, H]. Returns (topk_idx [T,K], topk_probs [T,K], aux_loss).
    Softmax-then-topk with prob renormalization — reference TopKRouter
    (router.py:102) default scoring.

    stats_mean: optional reducer applied to the per-expert token-mean
    statistics (frac, mean_prob, z² mean) BEFORE the nonlinear aux-loss
    combination. The manual-ep dispatch passes a pmean over the
    token-splitting mesh axes so the aux loss is computed from GLOBAL
    stats — bit-matching the single-shard router instead of averaging
    per-shard products (which differs whenever shards see different
    routing mixes).
    """
    e = cfg.num_moe_experts
    logits = x_flat.astype(jnp.float32) @ p["router_kernel"]
    probs = jax.nn.softmax(logits, axis=-1)
    topk_probs, topk_idx = jax.lax.top_k(probs, cfg.moe_router_topk)
    topk_probs = topk_probs / jnp.maximum(
        jnp.sum(topk_probs, -1, keepdims=True), 1e-9)

    if stats_mean is None:
        stats_mean = lambda s: s  # noqa: E731 — identity reducer
    aux = jnp.zeros((), jnp.float32)
    if cfg.moe_aux_loss_coeff:
        # Switch/GShard load-balancing loss (moe_utils.py switch_load_balancing
        # _loss_func): sum(probs_pe * tokens_pe) * E * coeff / (T^2 * topk) —
        # the 1/topk keeps the loss scale invariant in k (reference
        # normalization; advisor finding r1).
        onehot = jax.nn.one_hot(topk_idx, e, dtype=jnp.float32)  # [T,K,E]
        frac = stats_mean(
            jnp.mean(jnp.sum(onehot, axis=1), axis=0) / cfg.moe_router_topk)
        mean_prob = stats_mean(jnp.mean(probs, axis=0))
        aux = aux + cfg.moe_aux_loss_coeff * e * jnp.sum(frac * mean_prob)
    if cfg.moe_z_loss_coeff:
        z = jax.nn.logsumexp(logits, axis=-1)
        aux = aux + cfg.moe_z_loss_coeff * stats_mean(
            jnp.mean(jnp.square(z)))
    return topk_idx, topk_probs, aux


def _apply_act(cfg: TransformerConfig, y: jnp.ndarray) -> jnp.ndarray:
    """Apply the configured activation, splitting gate‖value for gated
    kinds (the fc1 kernels emit 2F columns when gated)."""
    if is_gated(cfg.activation):
        gate, val = jnp.split(y, 2, axis=-1)
        return apply_activation(cfg.activation, val, gate)
    return apply_activation(cfg.activation, y)


def _expert_ffn(p, x: jnp.ndarray, cfg: TransformerConfig) -> jnp.ndarray:
    """Batched expert MLP: x [E, C, H] → [E, C, H] (GroupedMLP analogue).

    Expert kernels resolve at matmul entry (inference/quantization.py
    resolve_param — a no-op on plain arrays): serving-resident int8
    expert stacks stay int8 in HBM with the per-channel dequant fused
    into the expert GEMMs, exactly like the dense fc1/fc2 path."""
    from megatronapp_tpu.inference.quantization import resolve_param
    dt = cfg.compute_dtype
    y = jnp.einsum("ech,ehf->ecf", x.astype(dt),
                   resolve_param(p["fc1_kernel"], dt))
    return jnp.einsum("ecf,efh->ech", _apply_act(cfg, y),
                      resolve_param(p["fc2_kernel"], dt))


def _dropless_experts(p, x_flat, topk_idx, topk_probs,
                      cfg: TransformerConfig) -> jnp.ndarray:
    """Exact dropless dispatch: sort the T*k token copies by expert id and
    run grouped GEMMs (``lax.ragged_dot``) over the contiguous per-expert
    row groups — static shapes, no capacity buffer, zero drops. This is
    the reference's default behavior (no --moe-expert-capacity-factor ⇒
    dispatchers never drop; experts.py GroupedMLP runs ragged groups)."""
    from megatronapp_tpu.inference.quantization import resolve_param
    t, h = x_flat.shape
    k = cfg.moe_router_topk
    e = cfg.num_moe_experts
    dt = cfg.compute_dtype
    flat_expert = topk_idx.reshape(t * k)
    order = jnp.argsort(flat_expert)
    token_of = order // k
    group_sizes = jnp.bincount(flat_expert, length=e).astype(jnp.int32)

    # Resident int8 expert stacks dequantize here, at matmul entry
    # (resolve_param is a no-op on plain arrays) — the ragged grouped
    # GEMM consumes the dequant directly, so the int8 stack is what
    # lives in HBM.
    x_sorted = jnp.take(x_flat.astype(dt), token_of, axis=0)
    y = jax.lax.ragged_dot(x_sorted, resolve_param(p["fc1_kernel"], dt),
                           group_sizes)
    y = jax.lax.ragged_dot(_apply_act(cfg, y),
                           resolve_param(p["fc2_kernel"], dt),
                           group_sizes)

    w_sorted = jnp.take(topk_probs.reshape(t * k), order).astype(
        jnp.float32)
    return jnp.zeros((t, h), jnp.float32).at[token_of].add(
        y.astype(jnp.float32) * w_sorted[:, None])


def moe_forward(p, x: jnp.ndarray, cfg: TransformerConfig, layer_id=None,
                ctx=None, tp_sharded: bool = False
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x: [B,S,H] → ([B,S,H], aux_loss scalar).

    ctx with ep > 1 selects the explicit all-to-all dispatch
    (_a2a_expert_forward): expert weights stay home on their ep shard and
    token activations travel, the reference MoEAlltoAllTokenDispatcher
    (core/transformer/moe/token_dispatcher.py). Without it, XLA's SPMD
    partitioner faces token-sharded ⇄ expert-sharded layout transitions
    it can only solve by full rematerialization (replicate + repartition
    — the 'Involuntary full rematerialization' warnings).

    tp_sharded: the ambient manual region (pp pipeline stage body) runs
    with the residual stream tp-SHARDED along the sequence — x is this
    shard's [B, S/tp, H] chunk, each shard routes only its local tokens
    (FLOPs cut tp×), and tp joins the token-splitting axes of the router
    aux-stat pmean so the load-balance loss still matches the global
    router exactly."""
    b, s, h = x.shape
    t = b * s
    e = cfg.num_moe_experts
    k = cfg.moe_router_topk

    from megatronapp_tpu.parallel.collectives import current_manual_axes
    if (ctx is not None and getattr(ctx, "ep", 1) > 1
            and not current_manual_axes()
            and e % ctx.ep == 0
            and b % (ctx.dp * ctx.ep) == 0
            and (ctx.cp == 1 or s % ctx.cp == 0)):
        # Explicit ep all-to-all dispatch (full-manual shard_map — the
        # partial-auto manual regions of this jax build abort XLA:CPU,
        # parallel/overlap.py docstring). Unavailable inside an ambient
        # manual region (the pp/cp pipeline body): nesting shard_maps is
        # unsupported in this JAX build, so moe+pp falls through to the
        # local dense dispatch below (each manual shard routes its own
        # tokens against the full expert stack). Ineligible layouts
        # (indivisible batch/experts) keep the compiler-sharded GSPMD
        # fallback.
        out, aux = _a2a_expert_forward(p, x, cfg, ctx)
        x_flat = x.reshape(t, h)
        return _with_shared(p, x_flat, out.reshape(t, h), cfg).reshape(
            b, s, h).astype(x.dtype), aux

    x_flat = x.reshape(t, h)
    # Inside an ambient manual region (the pp/cp pipeline body) each shard
    # routes only its local tokens; pmean the router stats over the
    # token-splitting manual axes BEFORE the nonlinear aux combination so
    # the load-balance loss matches the global router exactly — the same
    # global-stats discipline as the _a2a dispatch path above.
    stats_mean = None
    manual = current_manual_axes()
    if manual:
        from megatronapp_tpu.config.parallel_config import (
            CP_AXIS, DP_AXIS, EP_AXIS, TP_AXIS,
        )
        token_axes = tuple(a for a in (DP_AXIS, EP_AXIS, CP_AXIS)
                           if a in manual)
        if tp_sharded:
            # tp-sharded stage body: the sequence (hence tokens) splits
            # over tp too — without this entry each shard's aux loss
            # would combine LOCAL routing stats nonlinearly and drift
            # from the global router.
            token_axes = token_axes + (TP_AXIS,)
        if token_axes:
            stats_mean = lambda st: jax.lax.pmean(st, token_axes)  # noqa: E731
    topk_idx, topk_probs, aux = _router(p, x_flat, cfg,
                                        stats_mean=stats_mean)

    if cfg.moe_capacity_factor is None:
        out = _dropless_experts(p, x_flat, topk_idx, topk_probs, cfg)
    else:
        out = _capacity_experts(p, x_flat, topk_idx, topk_probs, cfg)
    return _with_shared(p, x_flat, out, cfg).reshape(
        b, s, h).astype(x.dtype), aux


def _chunked_a2a_ffn(send, fc1, fc2, cfg: TransformerConfig, ep: int):
    """Decomposed, latency-hiding all-to-all → expert FFN → all-to-all.

    send [ep, e_loc, cap, h]: send[j] = this shard's capacity buffer bound
    for the experts on shard j. Instead of one bulk ``lax.all_to_all``
    followed by one big grouped GEMM (exposed exchange, then exposed
    compute), the exchange is decomposed into ep-1 ``ppermute`` hops —
    hop s delivers the chunk from shard me-s — and each hop is issued
    BEFORE the expert GEMMs on the previously-arrived chunk, so on
    hardware with an async collective engine the token exchange rides
    under expert compute (T3-style, arXiv:2401.16677). Results return the
    same way: the return hop for chunk s is issued while chunk s+1's FFN
    runs. Returns y [ep, e_loc, cap, h] with y[j] = the FFN outputs of
    this shard's tokens that were dispatched to shard j.
    """
    from megatronapp_tpu.config.parallel_config import EP_AXIS
    from megatronapp_tpu.parallel.collectives import ring_span

    me = jax.lax.axis_index(EP_AXIS)
    params = {"fc1_kernel": fc1, "fc2_kernel": fc2}

    def chunk_for_shift(s):
        # What I must hand to the shard s hops ahead: send[(me + s) % ep].
        return jax.lax.dynamic_index_in_dim(send, (me + s) % ep,
                                            keepdims=False)

    y = jnp.zeros_like(send)
    # Own chunk needs no comm; hop 1 is issued first so it flies under it.
    # Hop s delivers chunk(i, i+s) from every source i to its dest i+s —
    # each shard receives the chunk from shard me-s bound for its experts.
    nxt = None
    if ep > 1:
        ring_span("moe-a2a-permute", "B", send, EP_AXIS, step=0, op="fwd")
        nxt = jax.lax.ppermute(
            chunk_for_shift(1), EP_AXIS,
            [(i, (i + 1) % ep) for i in range(ep)])
        ring_span("moe-a2a-permute", "E", nxt, EP_AXIS, step=0, op="fwd")
    ring_span("moe-a2a-compute", "B", send, EP_AXIS, step=0, op="fwd")
    y = jax.lax.dynamic_update_index_in_dim(
        y, _expert_ffn(params, chunk_for_shift(0), cfg), me, 0)
    ring_span("moe-a2a-compute", "E", y, EP_AXIS, step=0, op="fwd")
    for s in range(1, ep):
        arrived = nxt
        nxt = None
        if s + 1 < ep:
            # Pre-issue the next inbound hop under this chunk's GEMMs.
            ring_span("moe-a2a-permute", "B", arrived, EP_AXIS, step=s,
                      op="fwd")
            nxt = jax.lax.ppermute(
                chunk_for_shift(s + 1), EP_AXIS,
                [(i, (i + s + 1) % ep) for i in range(ep)])
            ring_span("moe-a2a-permute", "E", nxt, EP_AXIS, step=s,
                      op="fwd")
        ring_span("moe-a2a-compute", "B", arrived, EP_AXIS, step=s,
                  op="fwd")
        ys = _expert_ffn(params, arrived, cfg)
        ring_span("moe-a2a-compute", "E", ys, EP_AXIS, step=s, op="fwd")
        # Return the results to the tokens' home shard (dest i-s); what
        # arrives here is MY chunk's result from shard me+s. The receive
        # side of this hop overlaps the next iteration's FFN.
        ring_span("moe-a2a-permute", "B", ys, EP_AXIS, step=s, op="ret")
        back = jax.lax.ppermute(
            ys, EP_AXIS, [(i, (i - s) % ep) for i in range(ep)])
        ring_span("moe-a2a-permute", "E", back, EP_AXIS, step=s, op="ret")
        y = jax.lax.dynamic_update_index_in_dim(y, back, (me + s) % ep, 0)
    return y


def _a2a_expert_forward(p, x: jnp.ndarray, cfg: TransformerConfig, ctx
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Expert-parallel dispatch as explicit ICI collectives.

    FULL-MANUAL shard_map over every mesh axis (the partial-auto regions
    of this jax build abort XLA:CPU — parallel/overlap.py design notes):
    token batch threads over (dp, ep), sequence over cp, expert weights
    over ep; tp rides replicated inside the region (the expert GEMMs
    compute redundantly per tp rank — the GSPMD mlp-dim sharding of the
    old partial-auto region needed exactly the mode this build aborts
    on). Each (dp, ep, cp) shard routes its own tokens, packs per-expert
    capacity buffers, exchanges them with the experts' home ep shards,
    runs the local expert FFNs, and sends results back — the reference's
    MoEAlltoAllTokenDispatcher. With ``cfg.moe_comm_overlap`` (default)
    the exchange is the chunked, latency-hiding ``_chunked_a2a_ffn``
    above; otherwise one bulk lax.all_to_all each way.

    Capacity: moe_capacity_factor when set (GShard drop semantics);
    otherwise T_local*k — every copy provably fits, keeping the default
    dropless-exact semantics at the cost of a fatter buffer (the
    reference pads to capacity on this path too,
    --moe-pad-expert-input-to-capacity).
    """
    from megatronapp_tpu.config.parallel_config import (
        CP_AXIS, DP_AXIS, EP_AXIS,
    )
    from megatronapp_tpu.parallel.collectives import shard_map_compat

    e = cfg.num_moe_experts
    k = cfg.moe_router_topk
    ep = ctx.ep
    cp = ctx.cp
    e_loc = e // ep
    dt = cfg.compute_dtype
    if cfg.moe_capacity_factor is not None and cfg.moe_capacity_factor <= 0:
        raise ValueError(
            f"moe_capacity_factor must be > 0 (got "
            f"{cfg.moe_capacity_factor}); omit it (None) for dropless "
            "dispatch")
    # Token-splitting axes of the manual region: aux stats pmean over them
    # so the load-balance loss is computed from GLOBAL per-expert stats
    # (exact parity with the single-shard router).
    token_axes = (DP_AXIS, EP_AXIS) + ((CP_AXIS,) if cp > 1 else ())

    def body(router_kernel, fc1, fc2, x_loc):
        bl, sl, h = x_loc.shape
        t_loc = bl * sl
        xf = x_loc.reshape(t_loc, h)
        topk_idx, topk_probs, aux = _router(
            {"router_kernel": router_kernel}, xf, cfg,
            stats_mean=lambda st: jax.lax.pmean(st, token_axes))

        if cfg.moe_capacity_factor is not None:
            cap = max(int(cfg.moe_capacity_factor * t_loc * k / e), 1)
        else:
            # top_k indices are distinct per token, so an expert receives
            # at most one copy per token: cap = t_loc is provably
            # dropless.
            cap = t_loc
        flat_e = topk_idx.reshape(t_loc * k)
        pos = _position_in_expert(flat_e, e)                  # [T*k]
        valid = pos < cap
        idx_e = jnp.where(valid, flat_e, 0)
        idx_p = jnp.where(valid, pos, 0)
        token_of = jnp.arange(t_loc * k) // k

        vals = (xf[token_of].astype(dt) *
                valid[:, None].astype(dt))                    # [T*k, H]
        send = jnp.zeros((e, cap, h), dt).at[idx_e, idx_p].add(vals)

        # tokens → expert home shards (experts live contiguously:
        # shard i holds [i*e_loc, (i+1)*e_loc), the fc1/fc2 'experts'
        # axis sharding).
        send = send.reshape(ep, e_loc, cap, h)
        if getattr(cfg, "moe_comm_overlap", True):
            y = _chunked_a2a_ffn(send, fc1, fc2, cfg, ep)
            y = y.reshape(e, cap, h)
        else:
            recv = jax.lax.all_to_all(send, EP_AXIS, split_axis=0,
                                      concat_axis=0)          # [ep_src,...]
            xin = recv.transpose(1, 0, 2, 3).reshape(e_loc, ep * cap, h)
            y = _expert_ffn({"fc1_kernel": fc1, "fc2_kernel": fc2}, xin,
                            cfg)
            y = y.reshape(e_loc, ep, cap, h).transpose(1, 0, 2, 3)
            y = jax.lax.all_to_all(y, EP_AXIS, split_axis=0,
                                   concat_axis=0)             # back home
            y = y.reshape(e, cap, h)

        w = (topk_probs.reshape(t_loc * k) *
             valid.astype(topk_probs.dtype))
        contrib = y[idx_e, idx_p].astype(jnp.float32) * w[:, None]
        out = contrib.reshape(t_loc, k, h).sum(axis=1)        # [T_loc, H]
        return out.reshape(bl, sl, h), aux

    from jax.sharding import PartitionSpec as P
    batch_axes = (DP_AXIS, EP_AXIS)
    x_spec = P(batch_axes, CP_AXIS if cp > 1 else None, None)
    # manual-ok: _a2a_expert_forward is gated on `not current_manual_axes()`
    sm = shard_map_compat(
        body, ctx.shard_map_mesh,
        in_specs=(P(), P(EP_AXIS), P(EP_AXIS), x_spec),
        out_specs=(x_spec, P()))
    return sm(p["router_kernel"], p["fc1_kernel"], p["fc2_kernel"], x)


def _position_in_expert(flat_expert: jnp.ndarray, e: int) -> jnp.ndarray:
    """Arrival-order slot of each (token, choice) copy within its
    expert's capacity buffer (GShard position accounting, shared by the
    capacity and a2a dispatchers). flat_expert: [T*k] → pos [T*k]."""
    onehot = jax.nn.one_hot(flat_expert, e, dtype=jnp.int32)  # [T*k, E]
    before = jnp.cumsum(onehot, axis=0) - onehot
    return jnp.sum(before * onehot, axis=1)


def _capacity_experts(p, x_flat, topk_idx, topk_probs,
                      cfg: TransformerConfig) -> jnp.ndarray:
    """GShard capacity dispatch (reference --moe-expert-capacity-factor
    path): tokens beyond F*T*k/E per expert are dropped."""
    t, _h = x_flat.shape
    e = cfg.num_moe_experts
    k = cfg.moe_router_topk
    if cfg.moe_capacity_factor <= 0:
        raise ValueError(
            f"moe_capacity_factor must be > 0 (got "
            f"{cfg.moe_capacity_factor}); omit it (None) for dropless "
            "dispatch")
    capacity = max(int(cfg.moe_capacity_factor * t * k / e), 1)

    onehot = jax.nn.one_hot(topk_idx, e, dtype=jnp.int32)  # [T,K,E]
    pos = _position_in_expert(topk_idx.reshape(t * k), e).reshape(t, k)
    keep = pos < capacity

    # Dispatch tensor [T, E, C] (GShard combine/dispatch einsum pattern).
    probs_masked = topk_probs * keep.astype(topk_probs.dtype)
    pos_oh = jax.nn.one_hot(jnp.where(keep, pos, capacity), capacity,
                            dtype=jnp.float32)  # [T,K,C] (dropped → all-zero)
    combine = jnp.einsum("tke,tkc,tk->tec", onehot.astype(jnp.float32),
                         pos_oh, probs_masked)  # [T,E,C]
    dispatch = (combine > 0).astype(cfg.compute_dtype)

    expert_in = jnp.einsum("tec,th->ech", dispatch,
                           x_flat.astype(cfg.compute_dtype))
    expert_out = _expert_ffn(p, expert_in, cfg)
    return jnp.einsum("tec,ech->th", combine.astype(jnp.float32),
                      expert_out.astype(jnp.float32))


def _with_shared(p, x_flat, out, cfg: TransformerConfig):
    """Add the always-on shared expert(s) (reference shared_experts.py)."""
    if "shared_fc1" not in p:
        return out
    dt = cfg.compute_dtype
    y = _apply_act(cfg, x_flat.astype(dt) @ p["shared_fc1"].astype(dt))
    return out + (y @ p["shared_fc2"].astype(dt)).astype(jnp.float32)
