"""MLP sublayer (dense FFN, gated variants).

Parity with /root/reference/megatron/core/transformer/mlp.py:32 (MLP with
ColumnParallelLinear fc1 → activation → RowParallelLinear fc2). TP falls out
of the 'mlp' logical axis; gated activations fuse gate+value into one fc1
matmul exactly like the reference's ``gated_linear_unit`` path.

Param leaf layout:
  fc1_kernel [H, F] or [H, 2F] (gated)   logical ('embed','mlp')
  fc1_bias   [F] / [2F]                  logical ('mlp',)
  fc2_kernel [F, H]                      logical ('mlp','embed')
  fc2_bias   [H]                         logical ('embed',)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import TransformerConfig
from megatronapp_tpu.ops.activations import apply_activation, is_gated
from megatronapp_tpu.scope.hooks import scope_capture


def init_mlp_params(rng, cfg: TransformerConfig, out_std: float,
                    ffn_hidden: int = None):
    h = cfg.hidden_size
    f = ffn_hidden or cfg.ffn_hidden_size
    k1, k2 = jax.random.split(rng)
    std = cfg.init_method_std
    fc1_out = 2 * f if is_gated(cfg.activation) else f
    p = {
        "fc1_kernel": jax.random.normal(k1, (h, fc1_out), cfg.params_dtype) * std,
        "fc2_kernel": jax.random.normal(k2, (f, h), cfg.params_dtype) * out_std,
    }
    ax = {"fc1_kernel": ("embed", "mlp"), "fc2_kernel": ("mlp", "embed")}
    if cfg.add_bias_linear:
        p["fc1_bias"] = jnp.zeros((fc1_out,), cfg.params_dtype)
        p["fc2_bias"] = jnp.zeros((h,), cfg.params_dtype)
        ax["fc1_bias"] = ("mlp",)
        ax["fc2_bias"] = ("embed",)
    return p, ax


def mlp_forward(p, x: jnp.ndarray, cfg: TransformerConfig, layer_id=None,
                ctx=None):
    from megatronapp_tpu.scope.disturbance import get_disturbance
    from megatronapp_tpu.parallel.overlap import (
        all_gather_matmul, matmul_reduce_scatter, tp_overlap_eligible,
    )
    _dist = get_disturbance()
    # Latency-hiding tp path (--tp-comm-overlap): fc1 column-parallel via
    # ring all-gather-matmul, fc2 row-parallel via matmul-reduce-scatter.
    # One eligibility decision covers the pair (both weight dims must
    # shard evenly) so the intermediate layout stays consistent.
    overlap = tp_overlap_eligible(cfg, ctx, p["fc1_kernel"].shape[1],
                                  p["fc2_kernel"].shape[0],
                                  batch=x.shape[0])
    x = x.astype(cfg.compute_dtype)
    fc1_kernel = _dist.apply("weight", p["fc1_kernel"], layer_id)
    fc1_kernel = fc1_kernel.astype(cfg.compute_dtype)
    if overlap:
        y = all_gather_matmul(x, fc1_kernel, ctx.shard_map_mesh)
    else:
        y = x @ fc1_kernel
    if "fc1_bias" in p:
        y = y + p["fc1_bias"].astype(cfg.compute_dtype)
    y = scope_capture("mlp1", y, layer_id)
    # MegaScope 'calculation' perturbation site (reference mlp.py).
    from megatronapp_tpu.scope.disturbance import get_disturbance
    y = get_disturbance().apply("calculation", y, layer_id)
    if is_gated(cfg.activation):
        gate, val = jnp.split(y, 2, axis=-1)
        y = apply_activation(cfg.activation, val, gate)
    else:
        y = apply_activation(cfg.activation, y)
    fc2_kernel = _dist.apply("weight", p["fc2_kernel"], layer_id)
    fc2_kernel = fc2_kernel.astype(cfg.compute_dtype)
    if overlap:
        out = matmul_reduce_scatter(y, fc2_kernel, ctx.shard_map_mesh)
    else:
        out = y @ fc2_kernel
    if "fc2_bias" in p:
        out = out + p["fc2_bias"].astype(cfg.compute_dtype)
    out = scope_capture("mlp2", out, layer_id)
    return out
