"""MLP sublayer (dense FFN, gated variants).

Parity with /root/reference/megatron/core/transformer/mlp.py:32 (MLP with
ColumnParallelLinear fc1 → activation → RowParallelLinear fc2). TP falls out
of the 'mlp' logical axis; gated activations fuse gate+value into one fc1
matmul exactly like the reference's ``gated_linear_unit`` path.

Param leaf layout:
  fc1_kernel [H, F] or [H, 2F] (gated)   logical ('embed','mlp')
  fc1_bias   [F] / [2F]                  logical ('mlp',)
  fc2_kernel [F, H]                      logical ('mlp','embed')
  fc2_bias   [H]                         logical ('embed',)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import TransformerConfig
from megatronapp_tpu.ops.activations import apply_activation, is_gated
from megatronapp_tpu.scope.hooks import scope_capture


def init_mlp_params(rng, cfg: TransformerConfig, out_std: float,
                    ffn_hidden: int = None):
    h = cfg.hidden_size
    f = ffn_hidden or cfg.ffn_hidden_size
    k1, k2 = jax.random.split(rng)
    std = cfg.init_method_std
    fc1_out = 2 * f if is_gated(cfg.activation) else f
    p = {
        "fc1_kernel": jax.random.normal(k1, (h, fc1_out), cfg.params_dtype) * std,
        "fc2_kernel": jax.random.normal(k2, (f, h), cfg.params_dtype) * out_std,
    }
    ax = {"fc1_kernel": ("embed", "mlp"), "fc2_kernel": ("mlp", "embed")}
    if cfg.add_bias_linear:
        p["fc1_bias"] = jnp.zeros((fc1_out,), cfg.params_dtype)
        p["fc2_bias"] = jnp.zeros((h,), cfg.params_dtype)
        ax["fc1_bias"] = ("mlp",)
        ax["fc2_bias"] = ("embed",)
    return p, ax


def mlp_forward(p, x: jnp.ndarray, cfg: TransformerConfig, layer_id=None,
                ctx=None, tp_sharded: bool = False, fp8=None, lora=None):
    """fp8: this layer's delayed-scaling state for the fc1/fc2 ring
    sites ({"fc1": {hist, sat}, "fc2": ...} — training/fp8.py). Only
    legal when the tp-overlap rings actually run (fp8_ineligible_reason
    gates callers); raising here instead of silently ignoring keeps the
    amax history from rotting."""
    from megatronapp_tpu.scope.disturbance import get_disturbance
    from megatronapp_tpu.parallel.overlap import (
        all_gather_matmul, matmul_reduce_scatter, tp_overlap_eligible,
    )
    if tp_sharded:
        if lora is not None:
            raise ValueError(
                "lora deltas are not composable with the tp-sharded "
                "stage body — serving paths only")
        if fp8 is not None:
            raise ValueError(
                "fp8 is not supported on the tp-sharded pipeline stage "
                "body (ambient-manual rings keep bf16) — "
                "fp8_ineligible_reason gates this off")
        # Ambient-manual tp-sharded stage body (pp pipeline): x is this
        # shard's [b, S/tp, H] seq chunk; fc1 runs as a ring all-gather-
        # matmul on a local column slice, fc2 as a matmul-reduce-scatter
        # on the matching row slice (parallel/overlap.py *_manual).
        return _mlp_forward_tp_sharded(p, x, cfg, layer_id, ctx)
    _dist = get_disturbance()
    # Serving-resident int8 weights dequantize at matmul entry
    # (inference/quantization.py resolve_param — a no-op on plain
    # arrays).
    from megatronapp_tpu.inference.quantization import resolve_param
    fc1_res = resolve_param(p["fc1_kernel"])
    fc2_res = resolve_param(p["fc2_kernel"])
    # Latency-hiding tp path (--tp-comm-overlap): fc1 column-parallel via
    # ring all-gather-matmul, fc2 row-parallel via matmul-reduce-scatter.
    # One eligibility decision covers the pair (both weight dims must
    # shard evenly) so the intermediate layout stays consistent.
    overlap = tp_overlap_eligible(cfg, ctx, fc1_res.shape[1],
                                  fc2_res.shape[0],
                                  batch=x.shape[0])
    if fp8 is not None and not overlap:
        raise ValueError(
            "fp8 state passed but the tp-overlap rings are not "
            "eligible here (tp_overlap_eligible is False) — the fp8 "
            "GEMMs live inside the ring bodies; check "
            "fp8_ineligible_reason at wiring time")
    margin = int(getattr(cfg, "fp8_margin", 0))
    # Batched-LoRA serving (inference/lora.py): per-row deltas compose
    # with the plain matmuls only, not the ring-decomposed overlap path.
    if lora is not None and overlap:
        raise ValueError(
            "lora deltas are not composable with the tp-overlap rings "
            "— serving paths only")
    x = x.astype(cfg.compute_dtype)
    fc1_kernel = _dist.apply("weight", fc1_res, layer_id)
    fc1_kernel = fc1_kernel.astype(cfg.compute_dtype)
    if overlap:
        # manual-ok: overlap gated by tp_overlap_eligible (False inside
        # ambient manual regions; the pipeline takes the tp_sharded path)
        y = all_gather_matmul(x, fc1_kernel, ctx.shard_map_mesh,
                              fp8=None if fp8 is None else fp8["fc1"],
                              fp8_margin=margin)
    else:
        y = x @ fc1_kernel
        if lora is not None:
            from megatronapp_tpu.ops.pallas.kernel_gen import (
                apply_lora_delta)
            y = apply_lora_delta(y, x, lora, "fc1_kernel")
    if "fc1_bias" in p:
        y = y + p["fc1_bias"].astype(cfg.compute_dtype)
    y = scope_capture("mlp1", y, layer_id)
    # MegaScope 'calculation' perturbation site (reference mlp.py).
    from megatronapp_tpu.scope.disturbance import get_disturbance
    y = get_disturbance().apply("calculation", y, layer_id)
    if is_gated(cfg.activation):
        gate, val = jnp.split(y, 2, axis=-1)
        y = apply_activation(cfg.activation, val, gate)
    else:
        y = apply_activation(cfg.activation, y)
    fc2_kernel = _dist.apply("weight", fc2_res, layer_id)
    fc2_kernel = fc2_kernel.astype(cfg.compute_dtype)
    if overlap:
        # manual-ok: same tp_overlap_eligible gate as fc1 above
        out = matmul_reduce_scatter(
            y, fc2_kernel, ctx.shard_map_mesh,
            fp8=None if fp8 is None else fp8["fc2"], fp8_margin=margin)
    else:
        out = y @ fc2_kernel
        if lora is not None:
            from megatronapp_tpu.ops.pallas.kernel_gen import (
                apply_lora_delta)
            out = apply_lora_delta(out, y, lora, "fc2_kernel")
    if "fc2_bias" in p:
        out = out + p["fc2_bias"].astype(cfg.compute_dtype)
    out = scope_capture("mlp2", out, layer_id)
    return out


def _mlp_forward_tp_sharded(p, x: jnp.ndarray, cfg: TransformerConfig,
                            layer_id, ctx):
    """MLP with a tp-SHARDED residual stream inside an ambient full-manual
    region (the pp pipeline stage body).

    Weights enter replicated (pipeline in_specs mention only pp) and each
    tp shard slices its column/row block locally — the slice transpose
    scatters the local wgrad into a zero full-size cotangent, which the
    enclosing shard_map's transpose psums across tp into the full grad
    (pipeline.py grad-axes bookkeeping). Gated activations shard the gate
    and value halves SEPARATELY so each shard owns matching (gate, value)
    column pairs — a contiguous slice of the packed [gate | value] fc1
    would hand shard 0 only gate columns."""
    from jax import lax
    from megatronapp_tpu.config.parallel_config import TP_AXIS
    from megatronapp_tpu.parallel.overlap import (
        all_gather_matmul_manual, matmul_reduce_scatter_manual,
    )
    from megatronapp_tpu.scope.disturbance import get_disturbance
    _dist = get_disturbance()
    tp = ctx.tp
    me = lax.axis_index(TP_AXIS)
    overlap = bool(getattr(cfg, "tp_comm_overlap", False))
    dt = cfg.compute_dtype
    x = x.astype(dt)
    fc1_kernel = _dist.apply("weight", p["fc1_kernel"], layer_id).astype(dt)
    gated = is_gated(cfg.activation)
    f = p["fc2_kernel"].shape[0]
    fl = f // tp

    def colslice(w, start):
        return lax.dynamic_slice_in_dim(w, start, fl, axis=1)

    if gated:
        wg = colslice(fc1_kernel, me * fl)
        wv = colslice(fc1_kernel, f + me * fl)
        yg, yv = all_gather_matmul_manual(x, (wg, wv), tp, overlap)
        if "fc1_bias" in p:
            b1 = p["fc1_bias"].astype(dt)
            yg = yg + lax.dynamic_slice_in_dim(b1, me * fl, fl)
            yv = yv + lax.dynamic_slice_in_dim(b1, f + me * fl, fl)
        # Repack this shard's halves into the baseline's [gate | value]
        # layout so 'mlp1' captures both halves and 'calculation' draws
        # ONE disturbance per (site, layer), like every other path.
        y = jnp.concatenate([yg, yv], axis=-1)
        y = scope_capture("mlp1", y, layer_id)
        y = _dist.apply("calculation", y, layer_id)
        yg, yv = jnp.split(y, 2, axis=-1)
        y = apply_activation(cfg.activation, yv, yg)
    else:
        w1 = colslice(fc1_kernel, me * fl)
        y = all_gather_matmul_manual(x, w1, tp, overlap)
        if "fc1_bias" in p:
            y = y + lax.dynamic_slice_in_dim(p["fc1_bias"].astype(dt),
                                             me * fl, fl)
        y = scope_capture("mlp1", y, layer_id)
        y = _dist.apply("calculation", y, layer_id)
        y = apply_activation(cfg.activation, y)

    fc2_kernel = _dist.apply("weight", p["fc2_kernel"], layer_id).astype(dt)
    w2 = lax.dynamic_slice_in_dim(fc2_kernel, me * fl, fl, axis=0)
    out = matmul_reduce_scatter_manual(y, w2, tp, overlap)
    if "fc2_bias" in p:
        out = out + p["fc2_bias"].astype(dt)
    out = scope_capture("mlp2", out, layer_id)
    return out
