"""Multi-token prediction (MTP).

Parity with /root/reference/megatron/core/transformer/
multi_token_prediction.py (MultiTokenPredictionLayer, DeepSeek-V3 recipe):
D sequential depth modules each predict one additional future token while
keeping the causal chain — depth k combines RMSNorm(h^{k-1}) with
RMSNorm(emb(t_{i+k})) through a linear projection, runs one shared-spec
transformer layer, and scores with the SHARED output head; the auxiliary
loss is mtp_loss_scaling_factor × mean over depths.

TPU-first: depth modules are a Python loop over D (D is small and static);
each depth is the same scan-free layer body the main stack uses, so XLA
fuses it into the step program.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import TransformerConfig
from megatronapp_tpu.ops.cross_entropy import cross_entropy_loss
from megatronapp_tpu.ops.normalization import rms_norm
from megatronapp_tpu.transformer.block import (
    init_layer_params, layer_forward,
)


def init_mtp_params(rng, cfg: TransformerConfig):
    """[D] list of depth modules: input norms + 2H→H projection + one
    transformer layer (embedding/head are SHARED with the main model)."""
    h = cfg.hidden_size
    depths = []
    axes = []
    for k in range(cfg.mtp_num_layers or 0):
        kp, kl = jax.random.split(jax.random.fold_in(rng, k))
        layer_p, layer_ax = init_layer_params(kl, cfg)
        depths.append({
            "hnorm_scale": jnp.ones((h,), cfg.params_dtype),
            "enorm_scale": jnp.ones((h,), cfg.params_dtype),
            "proj": jax.random.normal(kp, (2 * h, h), cfg.params_dtype)
            * cfg.init_method_std,
            "layer": layer_p,
        })
        axes.append({
            "hnorm_scale": ("embed",), "enorm_scale": ("embed",),
            "proj": (None, "embed"), "layer": layer_ax,
        })
    return depths, axes


def mtp_loss(mtp_params, h: jnp.ndarray, embed_fn, head_fn,
             tokens: jnp.ndarray, labels: jnp.ndarray,
             loss_mask: Optional[jnp.ndarray], cfg: TransformerConfig,
             rope_cos=None, rope_sin=None, ctx=None
             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Auxiliary MTP loss (reference MTPLossAutoScaler path).

    h: [B,S,H] main-stack output (pre final-norm/head); embed_fn(tokens) →
    [B,S,H]; head_fn(h) → logits. Depth k (1-based) predicts labels rolled
    left by k; the trailing k positions are masked out (roll_tensor
    semantics, multi_token_prediction.py:119).

    Returns (scaled_total, per_depth_mean, layer_aux) — add scaled_total
    AND layer_aux (the depth layers' own MoE router losses, unscaled like
    the main stack's) to the LM loss; log per_depth_mean
    (track_mtp_metrics analogue).
    """
    d_depths = len(mtp_params)
    if d_depths == 0:
        z = jnp.zeros((), jnp.float32)
        return z, z, z
    b, s = tokens.shape
    if loss_mask is None:
        loss_mask = jnp.ones((b, s), jnp.float32)

    total = jnp.zeros((), jnp.float32)
    layer_aux = jnp.zeros((), jnp.float32)
    for k, dp in enumerate(mtp_params, start=1):
        # Embedding of token t_{i+k} at position i.
        toks_k = jnp.roll(tokens, -k, axis=1)
        emb_k = embed_fn(toks_k)
        x = jnp.concatenate(
            [rms_norm(h, dp["hnorm_scale"], cfg.layernorm_epsilon),
             rms_norm(emb_k, dp["enorm_scale"], cfg.layernorm_epsilon)],
            axis=-1).astype(cfg.compute_dtype)
        x = x @ dp["proj"].astype(cfg.compute_dtype)
        (h, _), l_aux = layer_forward(dp["layer"], x, cfg, rope_cos,
                                      rope_sin, None, layer_id=None,
                                      ctx=ctx)
        if l_aux is not None:
            layer_aux = layer_aux + l_aux
        logits = head_fn(h)
        labels_k = jnp.roll(labels, -k, axis=1)
        # Positions whose target rolled past the end contribute nothing.
        valid = (jnp.arange(s) < s - k).astype(jnp.float32)[None, :]
        mask_k = jnp.roll(loss_mask, -k, axis=1) * valid
        loss_k, _ = cross_entropy_loss(logits, labels_k, mask_k)
        total = total + loss_k
    mean = total / d_depths
    scale = cfg.mtp_loss_scaling_factor
    return scale * mean, mean, layer_aux
