"""Multi-latent attention (MLA, DeepSeek-style).

Parity with /root/reference/megatron/core/transformer/
multi_latent_attention.py:44 (MLASelfAttention) and MLATransformerConfig
(transformer_config.py:1072): queries (optionally) and keys/values project
through low-rank latents; position information flows only through small
decoupled rope heads (qk_pos_emb_head_dim) — the KV cache compresses to the
latent + shared rope key.

Shapes (per layer):
  q path:   x[H] → (q_lora_rank → ln →)? nq*(dqk + dpe)
  kv path:  x[H] → kv_lora_rank + dpe   (latent ‖ shared k_pe)
            latent → ln → nq*(dqk + dv) (k_nope ‖ v)
  attn:     q = [q_nope ‖ rope(q_pe)], k = [k_nope ‖ rope(k_pe)] with the
            shared k_pe broadcast across heads; softmax scale
            1/sqrt(dqk + dpe); out: nq*dv → H.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import TransformerConfig
from megatronapp_tpu.ops import rotary
from megatronapp_tpu.ops.attention import dot_product_attention
from megatronapp_tpu.ops.normalization import rms_norm


def init_mla_params(rng, cfg: TransformerConfig, out_std: float):
    h = cfg.hidden_size
    nq = cfg.num_attention_heads
    dqk, dpe, dv = cfg.qk_head_dim, cfg.qk_pos_emb_head_dim, cfg.v_head_dim
    klat = cfg.kv_lora_rank
    keys = jax.random.split(rng, 6)
    std = cfg.init_method_std
    p = {}
    ax = {}
    if cfg.q_lora_rank:
        p["q_down"] = jax.random.normal(
            keys[0], (h, cfg.q_lora_rank), cfg.params_dtype) * std
        p["q_ln_scale"] = jnp.ones((cfg.q_lora_rank,), cfg.params_dtype)
        p["q_up"] = jax.random.normal(
            keys[1], (cfg.q_lora_rank, nq * (dqk + dpe)),
            cfg.params_dtype) * std
        ax["q_down"] = ("embed", None)
        ax["q_ln_scale"] = (None,)
        ax["q_up"] = (None, "qkv")
    else:
        p["q_proj"] = jax.random.normal(
            keys[0], (h, nq * (dqk + dpe)), cfg.params_dtype) * std
        ax["q_proj"] = ("embed", "qkv")
    # Compressed KV latent + shared rope key (one dpe-wide head).
    p["kv_down"] = jax.random.normal(
        keys[2], (h, klat + dpe), cfg.params_dtype) * std
    p["kv_ln_scale"] = jnp.ones((klat,), cfg.params_dtype)
    p["kv_up"] = jax.random.normal(
        keys[3], (klat, nq * (dqk + dv)), cfg.params_dtype) * std
    p["out_kernel"] = jax.random.normal(
        keys[4], (nq * dv, h), cfg.params_dtype) * out_std
    ax.update({
        "kv_down": ("embed", None), "kv_ln_scale": (None,),
        "kv_up": (None, "qkv"), "out_kernel": ("qkv", "embed"),
    })
    return p, ax


def mla_forward(p, x: jnp.ndarray, cfg: TransformerConfig,
                rope_cos=None, rope_sin=None,
                attention_mask: Optional[jnp.ndarray] = None,
                layer_id=None, ctx=None, kv_cache=None, cache_index=None,
                cache_positions=None, page_table=None, active=None,
                chunk_counts=None, tp_sharded: bool = False,
                kv_scales=None):
    """kv_cache: optional (latent_cache [B, Smax, kv_lora_rank],
    kpe_cache [B, Smax, dpe]) — the COMPRESSED decode cache (the latent +
    shared roped key; reference MLA's defining cache shape). Returns
    (out, new_cache) when caching, else out.

    cache_positions: optional [B] int32 per-row write positions for
    continuous-batching decode (dynamic_context.py analogue) — each row
    appends its latent/k_pe at ITS OWN position; causality must then come
    from the caller's per-row attention_mask.

    Dense-cache decode recomputes k_nope/v from the cached latent via
    kv_up each step (the storage-optimal variant). The PAGED path
    (page_table is not None) instead absorbs kv_up's k_nope columns into
    the query and attends IN LATENT SPACE through the generated ragged
    paged kernel (ops/pallas/kernel_gen.paged_attention_latent,
    ISSUE 17): scores are q_lat·latentᵀ + q_pe·k_peᵀ over the page
    table, values re-expand per-tile in-register — no dense gather and
    no per-step kv_up over the whole history.

    kv_scales: optional (lat_scales, pe_scales) per-row scalar fp32
    scale pools [NB, bs] marking a QUANTIZED latent/pe pool (paged path
    only); new rows quantize on insert (quantize_kv_rows) and new_cache
    then carries four pools.

    tp_sharded: ambient-manual tp-sharded stage body (see
    transformer/attention.py docstring) — training path only."""
    from megatronapp_tpu.scope.disturbance import get_disturbance
    from megatronapp_tpu.scope.hooks import scope_capture
    if tp_sharded:
        if kv_cache is not None or attention_mask is not None:
            raise NotImplementedError(
                "tp-sharded MLA supports the plain training path only")
        return _mla_forward_tp_sharded(p, x, cfg, rope_cos, rope_sin,
                                       layer_id, ctx)
    _dist = get_disturbance()

    b, s, h = x.shape
    nq = cfg.num_attention_heads
    dqk, dpe, dv = cfg.qk_head_dim, cfg.qk_pos_emb_head_dim, cfg.v_head_dim
    klat = cfg.kv_lora_rank
    dt = cfg.compute_dtype
    x = x.astype(dt)

    if "q_proj" in p:
        q = x @ _dist.apply("weight", p["q_proj"], layer_id).astype(dt)
    else:
        q_lat = x @ p["q_down"].astype(dt)
        q_lat = rms_norm(q_lat, p["q_ln_scale"], cfg.layernorm_epsilon)
        q = q_lat @ p["q_up"].astype(dt)
    q = q.reshape(b, s, nq, dqk + dpe)
    q_nope, q_pe = q[..., :dqk], q[..., dqk:]

    kv = x @ _dist.apply("weight", p["kv_down"],
                         layer_id).astype(dt)  # [B,S,klat+dpe]
    latent, k_pe = kv[..., :klat], kv[..., klat:]
    latent = rms_norm(latent, p["kv_ln_scale"], cfg.layernorm_epsilon)

    if rope_cos is not None:
        q_pe = rotary.apply_rope(q_pe, rope_cos, rope_sin)
        k_pe = rotary.apply_rope(k_pe[:, :, None, :], rope_cos,
                                 rope_sin)[:, :, 0]

    from megatronapp_tpu.config.transformer_config import AttnMaskType
    new_cache = None
    s_kv = s
    mask_type = cfg.attn_mask_type
    q_offset = 0
    if kv_cache is not None:
        if ctx is not None and ctx.cp > 1:
            raise NotImplementedError(
                "MLA decode with a KV cache under context parallelism is "
                "not supported (each shard would attend only local KV)")
        c_lat, c_pe = kv_cache
        if page_table is not None:
            # Paged continuous-batching decode (ISSUE 17): kv_cache is
            # the shared latent/k_pe block pool ([num_blocks, block_size,
            # klat/dpe], inference/paged_cache.py). Each row appends at
            # its own (block, offset); attention then runs IN LATENT
            # SPACE through the generated ragged paged kernel — q
            # absorbed through kv_up's k_nope columns, values
            # re-expanded per-tile in-register — so the history is never
            # gathered dense nor re-expanded through kv_up per step.
            from megatronapp_tpu.config.transformer_config import (
                PositionEmbeddingKind,
            )
            from megatronapp_tpu.inference.quantization import (
                resolve_param,
            )
            from megatronapp_tpu.ops.pallas.kernel_gen import (
                paged_attention_latent,
            )
            from megatronapp_tpu.ops.pallas.paged_attention import (
                append_chunk_pages, append_token_pages, quantize_kv_rows,
                tp_paged_eligible,
            )
            from megatronapp_tpu.scope import hooks as scope_hooks
            if active is None:
                active = jnp.ones((b,), bool)
            new_scales = None
            ragged = s > 1 or chunk_counts is not None
            if ragged:
                # Multi-token paged append (speculative verify / chunked
                # prefill): ragged per-row chunk starting at
                # cache_positions; the kernel's scalar-prefetched q_lens
                # carries the causal tail mask.
                counts = (chunk_counts if chunk_counts is not None
                          else jnp.full((b,), s, jnp.int32))
                if kv_scales is not None:
                    # Quantized latent/pe pools: per-row SCALAR scales
                    # (the rows have no kv-head axis) quantized on
                    # insert and scattered through the same page table.
                    c_ls, c_ps = kv_scales
                    lat_q, lat_s = quantize_kv_rows(latent,
                                                    dtype=c_lat.dtype)
                    pe_q, pe_s = quantize_kv_rows(k_pe, dtype=c_pe.dtype)
                    c_lat = append_chunk_pages(c_lat, lat_q, page_table,
                                               cache_positions, counts,
                                               active)
                    c_pe = append_chunk_pages(c_pe, pe_q, page_table,
                                              cache_positions, counts,
                                              active)
                    c_ls = append_chunk_pages(c_ls, lat_s, page_table,
                                              cache_positions, counts,
                                              active)
                    c_ps = append_chunk_pages(c_ps, pe_s, page_table,
                                              cache_positions, counts,
                                              active)
                    new_scales = (c_ls, c_ps)
                    sc_kw = {"lat_scales": c_ls, "pe_scales": c_ps}
                else:
                    c_lat = append_chunk_pages(
                        c_lat, latent.astype(c_lat.dtype), page_table,
                        cache_positions, counts, active)
                    c_pe = append_chunk_pages(
                        c_pe, k_pe.astype(c_pe.dtype), page_table,
                        cache_positions, counts, active)
                    sc_kw = {}
                kv_lens = cache_positions + counts
            else:
                if kv_scales is not None:
                    c_ls, c_ps = kv_scales
                    lat_q, lat_s = quantize_kv_rows(latent[:, 0],
                                                    dtype=c_lat.dtype)
                    pe_q, pe_s = quantize_kv_rows(k_pe[:, 0],
                                                  dtype=c_pe.dtype)
                    c_lat = append_token_pages(c_lat, lat_q, page_table,
                                               cache_positions, active)
                    c_pe = append_token_pages(c_pe, pe_q, page_table,
                                              cache_positions, active)
                    c_ls = append_token_pages(c_ls, lat_s, page_table,
                                              cache_positions, active)
                    c_ps = append_token_pages(c_ps, pe_s, page_table,
                                              cache_positions, active)
                    new_scales = (c_ls, c_ps)
                    sc_kw = {"lat_scales": c_ls, "pe_scales": c_ps}
                else:
                    c_lat = append_token_pages(
                        c_lat, latent[:, 0].astype(c_lat.dtype),
                        page_table, cache_positions, active)
                    c_pe = append_token_pages(
                        c_pe, k_pe[:, 0].astype(c_pe.dtype), page_table,
                        cache_positions, active)
                    sc_kw = {}
                kv_lens = cache_positions + 1
            new_cache = ((c_lat, c_pe) if new_scales is None
                         else (c_lat, c_pe) + new_scales)

            # YaRN: the rope tables already carry mscale, so the pe
            # logits get mscale² for free; the cached latent is
            # UNSCALED, so the absorbed query must carry the whole m²
            # the dense path splits as (q_nope·m)·(k_nope·m).
            m = 1.0
            if cfg.position_embedding == PositionEmbeddingKind.yarn:
                m = rotary.yarn_mscale(cfg.rope_scaling_factor,
                                       cfg.yarn_mscale_coeff)
            q_full = jnp.concatenate(
                [q_nope * m if m != 1.0 else q_nope, q_pe], axis=-1)
            q_full = scope_capture("qkv_q", q_full, layer_id)
            q_nope_y, q_pe = q_full[..., :dqk], q_full[..., dqk:]

            kvu = p["kv_up"].astype(dt).reshape(klat, nq, dqk + dv)
            wk, w_v = kvu[..., :dqk], kvu[..., dqk:]
            rows = q_nope_y.reshape(b * s, nq, dqk)
            if m != 1.0:
                rows = rows * m                    # second m factor
            q_abs = jnp.einsum("bnd,knd->bnk", rows, wk)
            q_abs = q_abs.reshape(b, s, nq, klat)

            scale = 1.0 / float((dqk + dpe) ** 0.5)
            tp_paged = False
            if ctx is not None:
                from megatronapp_tpu.parallel.collectives import (
                    current_manual_axes,
                )
                tp_paged = (tp_paged_eligible(cfg, ctx)
                            and not current_manual_axes())
            mesh = ctx.shard_map_mesh if tp_paged else None
            if ragged:
                attn = paged_attention_latent(
                    q_abs, q_pe, c_lat, c_pe, page_table, kv_lens, w_v,
                    q_lens=counts, softmax_scale=scale, mesh=mesh,
                    **sc_kw)
            else:
                attn = paged_attention_latent(
                    q_abs[:, 0], q_pe[:, 0], c_lat, c_pe, page_table,
                    kv_lens, w_v, softmax_scale=scale, mesh=mesh,
                    **sc_kw)[:, None]
            if tp_paged:
                from jax.sharding import NamedSharding, PartitionSpec
                # manual-ok: replicate the kernel output so the
                # out-projection runs identically on every device (the
                # latent shard_map already emits replicated output; the
                # constraint pins it for GSPMD).
                attn = jax.lax.with_sharding_constraint(
                    attn, NamedSharding(ctx.mesh, PartitionSpec()))  # manual-ok: see above

            if (scope_hooks.is_enabled("qkv_k")
                    or scope_hooks.is_enabled("qkv_v")):
                # MegaScope parity (debug-only, gated off the hot path):
                # reconstitute the dense k/v views the pre-kernel path
                # captured — gather the history and expand through
                # kv_up, exactly the work the kernel path avoids.
                from megatronapp_tpu.ops.pallas.paged_attention import (
                    gather_pages_batched,
                )
                g_lat = gather_pages_batched(c_lat, page_table)
                g_pe = gather_pages_batched(c_pe, page_table)
                if new_scales is not None:
                    g_ls = gather_pages_batched(new_scales[0], page_table)
                    g_ps = gather_pages_batched(new_scales[1], page_table)
                    g_lat = g_lat.astype(jnp.float32) * g_ls[..., None]
                    g_pe = g_pe.astype(jnp.float32) * g_ps[..., None]
                g_lat, g_pe = g_lat.astype(dt), g_pe.astype(dt)
                s_g = g_lat.shape[1]
                kvu_g = (g_lat @ p["kv_up"].astype(dt)).reshape(
                    b, s_g, nq, dqk + dv)
                k_nope_g, v_g = kvu_g[..., :dqk], kvu_g[..., dqk:]
                if m != 1.0:
                    k_nope_g = k_nope_g * m
                k_full_g = jnp.concatenate(
                    [k_nope_g, jnp.broadcast_to(g_pe[:, :, None, :],
                                                (b, s_g, nq, dpe))],
                    axis=-1)
                scope_capture("qkv_k", k_full_g, layer_id)
                scope_capture("qkv_v", v_g, layer_id)

            attn = scope_capture("context", attn, layer_id)
            out = attn.reshape(b, s, nq * dv) @ _dist.apply(
                "weight", resolve_param(p["out_kernel"]),
                layer_id).astype(dt)
            return out, new_cache
        elif cache_positions is not None:
            # Continuous-batching decode: per-row append positions.
            # Causality MUST come from the caller's per-row mask — the
            # scalar-offset causal mask cannot express per-row history
            # lengths, so an absent mask would silently attend to stale/
            # future cache slots (round-2 advisor finding).
            if attention_mask is None:
                raise ValueError(
                    "per-row decode (cache_positions) requires an "
                    "explicit per-row attention_mask; see "
                    "inference/dynamic_engine.py's attend mask")
            c_lat = c_lat.at[jnp.arange(b), cache_positions].set(
                latent[:, 0].astype(c_lat.dtype))
            c_pe = c_pe.at[jnp.arange(b), cache_positions].set(
                k_pe[:, 0].astype(c_pe.dtype))
            mask_type = AttnMaskType.bidirectional
        else:
            # Append the normed latent + roped shared key at cache_index;
            # the whole cached history reconstitutes k_nope/v below.
            c_lat = jax.lax.dynamic_update_slice_in_dim(
                c_lat, latent.astype(c_lat.dtype), cache_index, axis=1)
            c_pe = jax.lax.dynamic_update_slice_in_dim(
                c_pe, k_pe.astype(c_pe.dtype), cache_index, axis=1)
            q_offset = cache_index
        new_cache = (c_lat, c_pe)
        latent, k_pe = c_lat.astype(dt), c_pe.astype(dt)
        s_kv = latent.shape[1]

    kv_up = (latent @ p["kv_up"].astype(dt)).reshape(b, s_kv, nq, dqk + dv)
    k_nope, v = kv_up[..., :dqk], kv_up[..., dqk:]
    k_pe = jnp.broadcast_to(k_pe[:, :, None, :], (b, s_kv, nq, dpe))

    # YaRN: the rope tables already carry mscale (models/gpt.py), which
    # gives the pe logits the reference's mscale² factor; the nope logits
    # need the same factor explicitly (reference multi_latent_attention.py
    # :83-84 applies mscale²/sqrt(d) to ALL logits).
    from megatronapp_tpu.config.transformer_config import (
        PositionEmbeddingKind,
    )
    if cfg.position_embedding == PositionEmbeddingKind.yarn:
        m = rotary.yarn_mscale(cfg.rope_scaling_factor,
                               cfg.yarn_mscale_coeff)
        q_nope = q_nope * m
        k_nope = k_nope * m

    q_full = jnp.concatenate([q_nope, q_pe], axis=-1)
    k_full = jnp.concatenate([k_nope, k_pe], axis=-1)
    q_full = scope_capture("qkv_q", q_full, layer_id)
    k_full = scope_capture("qkv_k", k_full, layer_id)
    v = scope_capture("qkv_v", v, layer_id)
    scale = 1.0 / jnp.sqrt(jnp.float32(dqk + dpe))
    if ctx is not None and ctx.cp > 1 and kv_cache is None:
        # Context parallelism over the concatenated nope+rope heads
        # (values have a different head dim — the cp impls handle
        # d_v != d_qk). Contiguous modes only: MLA is excluded from the
        # zigzag layout (zigzag_active).
        from megatronapp_tpu.config.transformer_config import AttnMaskType
        from megatronapp_tpu.ops.context_parallel import context_attention
        if attention_mask is not None:
            raise NotImplementedError(
                "MLA + explicit attention mask under cp is unsupported")
        # manual-ok: context_attention detects the ambient manual cp axis
        out = context_attention(
            q_full, k_full, v, ctx.shard_map_mesh, cfg.cp_comm_type,
            causal=cfg.attn_mask_type == AttnMaskType.causal,
            softmax_scale=float(1.0 / (dqk + dpe) ** 0.5),
            a2a_size=cfg.hierarchical_cp_a2a_size,
            overlap_ring=getattr(cfg, "cp_comm_overlap", True))
    else:
        out = dot_product_attention(
            q_full, k_full, v, mask_type=mask_type,
            attention_mask=attention_mask, softmax_scale=scale,
            softmax_in_fp32=cfg.attention_softmax_in_fp32,
            q_offset=q_offset)
    out = scope_capture("context", out, layer_id)
    from megatronapp_tpu.inference.quantization import resolve_param
    out = out.reshape(b, s, nq * dv) @ _dist.apply(
        "weight", resolve_param(p["out_kernel"]), layer_id).astype(dt)
    return (out, new_cache) if kv_cache is not None else out


def _mla_forward_tp_sharded(p, x, cfg: TransformerConfig, rope_cos,
                            rope_sin, layer_id, ctx):
    """MLA with a tp-sharded residual stream inside the ambient full-manual
    pipeline stage body (training path, no cache).

    x: [B, S/tp, H] local seq chunk. The low-rank DOWN projections (q_down,
    kv_down) have small replicated-output widths: each shard computes them
    on its LOCAL rows only (FLOPs still cut tp×; wgrads are per-seq-chunk
    partials the enclosing transpose psums). The UP projections carry the
    head structure: q_up / kv_up run as ring all-gather-matmuls over
    per-shard head slices, producing full-sequence activations with nq/tp
    local heads. The tiny shared rope key k_pe is gathered explicitly
    (collectives.all_gather_seq) and roped with full tables; the out-proj
    ring reduce-scatters back to the local chunk."""
    from jax import lax
    from megatronapp_tpu.config.parallel_config import TP_AXIS
    from megatronapp_tpu.parallel.collectives import all_gather_seq
    from megatronapp_tpu.parallel.overlap import (
        all_gather_matmul_manual, matmul_reduce_scatter_manual,
    )
    from megatronapp_tpu.scope.disturbance import get_disturbance
    from megatronapp_tpu.scope.hooks import scope_capture
    from megatronapp_tpu.config.transformer_config import (
        PositionEmbeddingKind,
    )
    _dist = get_disturbance()

    b, s, h = x.shape
    nq = cfg.num_attention_heads
    dqk, dpe, dv = cfg.qk_head_dim, cfg.qk_pos_emb_head_dim, cfg.v_head_dim
    klat = cfg.kv_lora_rank
    dt = cfg.compute_dtype
    tp = ctx.tp
    me = lax.axis_index(TP_AXIS)
    ov = bool(getattr(cfg, "tp_comm_overlap", False))
    nql = nq // tp
    x = x.astype(dt)
    sf = s * tp

    dq = dqk + dpe
    if "q_proj" in p:
        qw = lax.dynamic_slice_in_dim(
            _dist.apply("weight", p["q_proj"], layer_id).astype(dt),
            me * nql * dq, nql * dq, axis=1)
        q = all_gather_matmul_manual(x, qw, tp, ov)      # [B, Sf, nql*dq]
    else:
        q_lat = x @ p["q_down"].astype(dt)               # local rows
        q_lat = rms_norm(q_lat, p["q_ln_scale"], cfg.layernorm_epsilon)
        quw = lax.dynamic_slice_in_dim(p["q_up"].astype(dt),
                                       me * nql * dq, nql * dq, axis=1)
        q = all_gather_matmul_manual(q_lat, quw, tp, ov)
    q = q.reshape(b, sf, nql, dq)
    q_nope, q_pe = q[..., :dqk], q[..., dqk:]

    kv = x @ _dist.apply("weight", p["kv_down"],
                         layer_id).astype(dt)            # [B, S/tp, klat+dpe]
    latent, k_pe = kv[..., :klat], kv[..., klat:]
    latent = rms_norm(latent, p["kv_ln_scale"], cfg.layernorm_epsilon)

    # kv_up rides a ring all-gather of the latent seq chunks; the shared
    # rope key gathers explicitly (dpe-wide — negligible traffic).
    kuw = lax.dynamic_slice_in_dim(p["kv_up"].astype(dt),
                                   me * nql * (dqk + dv),
                                   nql * (dqk + dv), axis=1)
    kv_up = all_gather_matmul_manual(latent, kuw, tp, ov)
    kv_up = kv_up.reshape(b, sf, nql, dqk + dv)
    k_nope, v = kv_up[..., :dqk], kv_up[..., dqk:]
    k_pe = all_gather_seq(k_pe, TP_AXIS, axis=1)         # [B, Sf, dpe]

    if rope_cos is not None:
        q_pe = rotary.apply_rope(q_pe, rope_cos, rope_sin)
        k_pe = rotary.apply_rope(k_pe[:, :, None, :], rope_cos,
                                 rope_sin)[:, :, 0]
    k_pe = jnp.broadcast_to(k_pe[:, :, None, :], (b, sf, nql, dpe))

    if cfg.position_embedding == PositionEmbeddingKind.yarn:
        m = rotary.yarn_mscale(cfg.rope_scaling_factor,
                               cfg.yarn_mscale_coeff)
        q_nope = q_nope * m
        k_nope = k_nope * m

    q_full = jnp.concatenate([q_nope, q_pe], axis=-1)
    k_full = jnp.concatenate([k_nope, k_pe], axis=-1)
    q_full = scope_capture("qkv_q", q_full, layer_id)
    k_full = scope_capture("qkv_k", k_full, layer_id)
    v = scope_capture("qkv_v", v, layer_id)
    scale = 1.0 / jnp.sqrt(jnp.float32(dqk + dpe))
    out = dot_product_attention(
        q_full, k_full, v, mask_type=cfg.attn_mask_type,
        attention_mask=None, softmax_scale=scale,
        softmax_in_fp32=cfg.attention_softmax_in_fp32)
    out = scope_capture("context", out, layer_id)
    ow = lax.dynamic_slice_in_dim(
        _dist.apply("weight", p["out_kernel"], layer_id).astype(dt),
        me * nql * dv, nql * dv, axis=0)
    return matmul_reduce_scatter_manual(out.reshape(b, sf, nql * dv), ow,
                                        tp, ov)
