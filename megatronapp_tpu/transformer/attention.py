"""Self-attention sublayer (GQA, RoPE, optional QK-layernorm).

Parity with /root/reference/megatron/core/transformer/attention.py:88
(Attention / SelfAttention :845). The reference splits weights across TP
ranks explicitly via ColumnParallelLinear/RowParallelLinear; here the kernels
carry logical axes ('heads'/'kv_heads' → tp) and XLA partitions the matmuls.

Param leaf layout (per layer, unstacked):
  q_kernel   [H, n_heads*D]        logical ('embed', 'qkv')
  kv_kernel  [H, 2*n_kv*D]         logical ('embed', 'qkv')
  q_bias     [n_heads*D]           logical ('qkv',)
  kv_bias    [2*n_kv*D]            logical ('qkv',)
  out_kernel [n_heads*D, H]        logical ('qkv', 'embed')
  out_bias   [H]                   logical ('embed',)
  (optional) q_ln_scale, k_ln_scale [D]
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import (
    AttnMaskType, TransformerConfig,
)
from megatronapp_tpu.ops.attention import dot_product_attention
from megatronapp_tpu.ops.normalization import rms_norm
from megatronapp_tpu.ops import rotary
from megatronapp_tpu.scope.hooks import scope_capture


def init_attention_params(rng, cfg: TransformerConfig, out_std: float):
    h = cfg.hidden_size
    d = cfg.head_dim
    nq, nkv = cfg.num_attention_heads, cfg.num_query_groups
    keys = jax.random.split(rng, 3)
    std = cfg.init_method_std
    p = {
        "q_kernel": jax.random.normal(keys[0], (h, nq * d), cfg.params_dtype) * std,
        "kv_kernel": jax.random.normal(keys[1], (h, 2 * nkv * d), cfg.params_dtype) * std,
        "out_kernel": jax.random.normal(keys[2], (nq * d, h), cfg.params_dtype) * out_std,
    }
    ax = {
        "q_kernel": ("embed", "qkv"),
        "kv_kernel": ("embed", "qkv"),
        "out_kernel": ("qkv", "embed"),
    }
    if cfg.add_qkv_bias:
        p["q_bias"] = jnp.zeros((nq * d,), cfg.params_dtype)
        p["kv_bias"] = jnp.zeros((2 * nkv * d,), cfg.params_dtype)
        ax["q_bias"] = ("qkv",)
        ax["kv_bias"] = ("qkv",)
    if cfg.add_bias_linear:
        p["out_bias"] = jnp.zeros((h,), cfg.params_dtype)
        ax["out_bias"] = ("embed",)
    if cfg.qk_layernorm:
        p["q_ln_scale"] = jnp.ones((d,), cfg.params_dtype)
        p["k_ln_scale"] = jnp.ones((d,), cfg.params_dtype)
        ax["q_ln_scale"] = ("head_dim",)
        ax["k_ln_scale"] = ("head_dim",)
    return p, ax


def _replicate_heads(attn_out: jnp.ndarray, ctx) -> jnp.ndarray:
    """Gather a head-sharded paged-attention output back to replicated
    before the out-projection. Keeping the out-proj matmul replicated
    (instead of a partial-contraction + all-reduce) costs one small
    [B, S, Hq, D] all-gather per layer but makes the summation order —
    and therefore the sampled greedy stream — bit-identical to the
    single-device engine."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    # manual-ok: tp serving path only — callers gate on tp_paged, which
    # requires no ambient manual axes (GSPMD constraint is legal here).
    return jax.lax.with_sharding_constraint(
        attn_out, NamedSharding(ctx.mesh, P()))  # manual-ok: see above


def attention_forward(
    p, x: jnp.ndarray, cfg: TransformerConfig,
    rope_cos: Optional[jnp.ndarray] = None,
    rope_sin: Optional[jnp.ndarray] = None,
    attention_mask: Optional[jnp.ndarray] = None,
    kv_cache=None, cache_index=None, cache_positions=None,
    layer_id=None, ctx=None, zigzag: bool = False,
    segment_ids: Optional[jnp.ndarray] = None,
    page_table: Optional[jnp.ndarray] = None,
    active: Optional[jnp.ndarray] = None,
    chunk_counts: Optional[jnp.ndarray] = None,
    tp_sharded: bool = False,
    kv_scales=None,
    fp8=None,
    lora=None,
) -> jnp.ndarray:
    """x: [B, S, H] → [B, S, H]. Returns (out, new_kv_cache).

    page_table: [B, max_blocks_per_seq] int32 — marks kv_cache as PAGED
    block-pool storage [num_blocks, block_size, Hkv, D]
    (inference/paged_cache.py): each row appends its token at its own
    (block, offset) and attends through the ragged paged-attention
    kernel, which masks by per-row kv length (no caller mask needed).
    active: [B] bool — inactive rows' writes are dropped (their page
    tables may reference blocks re-allocated to other requests).
    chunk_counts: [B] int32 — multi-token paged append (speculative
    verify / chunked prefill): row b's first chunk_counts[b] positions
    are real tokens starting at cache_positions[b]; attention runs
    through the multi-query ragged kernel (causal within the new tail,
    full attention to the paged context). Rows past a row's count are
    padding whose outputs are garbage (callers discard them).
    kv_scales: (k_scales, v_scales) fp32 [NB, bs, Hkv] — marks the paged
    pools as int8 (PagedKVCache kv_cache_dtype="int8"): new rows
    quantize per (row, head) in this jit before the scatter, the ragged
    kernels dequantize each DMA'd block in-register, and new_cache grows
    to (k, v, k_scales, v_scales). Paged paths only.

    zigzag: the CALLER laid the sequence out in zigzag cp order (model-side
    permutation, models/gpt.py) — required before the zigzag ring kernel may
    be dispatched; models that don't permute keep the contiguous ring.

    segment_ids: [B, S] packed-sequence map; the flash kernel masks
    in-block (O(S) memory), the reference impl builds the dense
    block-diagonal mask, and the cp impls thread segments through their
    collectives.

    tp_sharded: the caller (the pp pipeline stage body) runs inside an
    ambient FULL-MANUAL region with the residual stream tp-sharded along
    the sequence: x is this shard's [B, S/tp, H] chunk. QKV then runs as
    one fused ring all-gather-matmul over per-shard HEAD slices (q, k and
    v sliced separately so each shard owns matched GQA groups), attention
    runs on the full sequence with nq/tp local heads, and the out-proj
    ring reduce-scatters back to the local seq chunk
    (parallel/overlap.py *_manual; tp_stage_eligible gates callers)."""
    b, s, h = x.shape
    d = cfg.head_dim
    nq, nkv = cfg.num_attention_heads, cfg.num_query_groups
    x = x.astype(cfg.compute_dtype)

    # MegaScope 'weight' perturbation site (reference
    # tensor_parallel/layers.py:944-951 applies it to every parallel
    # linear's weights).
    from megatronapp_tpu.scope.disturbance import get_disturbance
    from megatronapp_tpu.parallel.overlap import (
        all_gather_matmul, matmul_reduce_scatter, tp_overlap_eligible,
    )
    _dist = get_disturbance()
    # Latency-hiding tp path (--tp-comm-overlap, parallel/overlap.py):
    # QKV column-parallel via ring all-gather-matmul, out-proj row-parallel
    # via matmul-reduce-scatter. The flat projection dims (not head counts)
    # must shard evenly over tp — the ring reproduces the global layout, so
    # GQA head counts indivisible by tp still work when nq*d / 2*nkv*d do.
    # (kv_cache = decode: S∈{1,prefill} matmuls are tiny and latency-bound,
    # the ring would be pure overhead — keep GSPMD there.)
    overlap = (kv_cache is None and not tp_sharded
               and tp_overlap_eligible(cfg, ctx, nq * d, 2 * nkv * d,
                                       batch=b))
    # fp8 (ISSUE 13): this layer's delayed-scaling state for the
    # qkv/out-proj ring sites — only legal when the rings actually run
    # (the amax history would silently rot otherwise).
    if fp8 is not None and not overlap:
        raise ValueError(
            "fp8 state passed but the tp-overlap rings are not "
            "eligible here (tp_overlap_eligible is False / decode "
            "path) — check fp8_ineligible_reason at wiring time")
    fp8_margin = int(getattr(cfg, "fp8_margin", 0))
    # Batched-LoRA serving (inference/lora.py): per-row adapter deltas
    # compose with the plain projection matmuls only — the tp-overlap
    # rings and the tp-sharded stage body slice weights per shard and
    # would need the delta ring-decomposed too.
    if lora is not None and (overlap or tp_sharded):
        raise ValueError(
            "lora deltas are not composable with the tp-overlap rings "
            "or the tp-sharded stage body — serving paths only")
    # Serving-resident int8 weights (inference/quantization.py
    # residentize_params): resolve_param dequantizes at matmul entry —
    # int8 stays in HBM, XLA fuses the per-channel scale multiply.
    from megatronapp_tpu.inference.quantization import resolve_param
    q_kernel = _dist.apply("weight", resolve_param(p["q_kernel"]),
                           layer_id)
    kv_kernel = _dist.apply("weight", resolve_param(p["kv_kernel"]),
                            layer_id)
    if tp_sharded:
        # Ambient-manual tp-sharded stage body: see docstring. Local head
        # counts; s stays the LOCAL seq chunk length, sf the full length.
        if (kv_cache is not None or attention_mask is not None
                or segment_ids is not None or zigzag):
            raise NotImplementedError(
                "tp-sharded stage body supports the plain training path "
                "only (no kv cache / explicit mask / packing / zigzag) — "
                "tp_stage_eligible callers gate these off")
        from jax import lax
        from megatronapp_tpu.config.parallel_config import TP_AXIS
        from megatronapp_tpu.parallel.overlap import (
            all_gather_matmul_manual, matmul_reduce_scatter_manual,
        )
        tp = ctx.tp
        me = lax.axis_index(TP_AXIS)
        nql, nkvl = nq // tp, nkv // tp
        dt = cfg.compute_dtype
        qw = lax.dynamic_slice_in_dim(q_kernel.astype(dt),
                                      me * nql * d, nql * d, axis=1)
        kw = lax.dynamic_slice_in_dim(kv_kernel.astype(dt),
                                      me * nkvl * d, nkvl * d, axis=1)
        vw = lax.dynamic_slice_in_dim(kv_kernel.astype(dt),
                                      nkv * d + me * nkvl * d, nkvl * d,
                                      axis=1)
        ov = bool(getattr(cfg, "tp_comm_overlap", False))
        q, k, v = all_gather_matmul_manual(x, (qw, kw, vw), tp, ov)
        if "q_bias" in p:
            qb = p["q_bias"].astype(dt)
            kvb = p["kv_bias"].astype(dt)
            q = q + lax.dynamic_slice_in_dim(qb, me * nql * d, nql * d)
            k = k + lax.dynamic_slice_in_dim(kvb, me * nkvl * d, nkvl * d)
            v = v + lax.dynamic_slice_in_dim(
                kvb, nkv * d + me * nkvl * d, nkvl * d)
        sf = s * tp
        q = q.reshape(b, sf, nql, d)
        k = k.reshape(b, sf, nkvl, d)
        v = v.reshape(b, sf, nkvl, d)
        q = scope_capture("qkv_q", q, layer_id)
        k = scope_capture("qkv_k", k, layer_id)
        v = scope_capture("qkv_v", v, layer_id)
        if cfg.qk_layernorm:
            q = rms_norm(q, p["q_ln_scale"], cfg.layernorm_epsilon)
            k = rms_norm(k, p["k_ln_scale"], cfg.layernorm_epsilon)
        if rope_cos is not None:
            # Post-ring tables: q/k carry the full sequence (cp == 1) or
            # this cp rank's full LOCAL chunk (cp > 1 — the caller
            # sliced the tables to the chunk, models/gpt.py stage_fn).
            q = rotary.apply_rope(q, rope_cos, rope_sin)
            k = rotary.apply_rope(k, rope_cos, rope_sin)
        if ctx.cp > 1:
            # pp x cp x tp composition (ISSUE 15): after the tp ring
            # gather the sequence is still the cp-LOCAL chunk — run the
            # contiguous cp ring attention per tp head shard instead of
            # treating the chunk as the whole sequence.
            # tp_stage_eligible restricts this path to dense
            # contiguous-p2p layouts (no zigzag — the caller skipped the
            # permutation).
            from megatronapp_tpu.ops.context_parallel import (
                context_attention,
            )
            # manual-ok: context_attention detects the ambient manual cp
            # axis and runs its ring body directly (no nested shard_map)
            attn_out = context_attention(
                q, k, v, ctx.shard_map_mesh, "p2p",
                causal=cfg.attn_mask_type == AttnMaskType.causal,
                overlap_ring=getattr(cfg, "cp_comm_overlap", True))
        else:
            attn_out = dot_product_attention(
                q, k, v, mask_type=cfg.attn_mask_type,
                attention_mask=None, softmax_scale=None,
                softmax_in_fp32=cfg.attention_softmax_in_fp32,
                layer_id=layer_id)
        attn_out = scope_capture("context", attn_out, layer_id)
        out_kernel = _dist.apply("weight", resolve_param(p["out_kernel"]),
                                 layer_id).astype(dt)
        ow = lax.dynamic_slice_in_dim(out_kernel, me * nql * d, nql * d,
                                      axis=0)
        out = matmul_reduce_scatter_manual(
            attn_out.reshape(b, sf, nql * d), ow, tp, ov)
        if "out_bias" in p:
            out = out + p["out_bias"].astype(dt)
        return out, None
    if overlap:
        # Fused call: one ring all-gather of x feeds both column-parallel
        # projections (two calls would move x around the ring twice).
        # manual-ok: overlap gated by tp_overlap_eligible (False inside
        # ambient manual regions; the pipeline takes tp_sharded above)
        q, kv = all_gather_matmul(
            x, (q_kernel.astype(cfg.compute_dtype),
                kv_kernel.astype(cfg.compute_dtype)), ctx.shard_map_mesh,
            fp8=None if fp8 is None else fp8["qkv"],
            fp8_margin=fp8_margin)
    else:
        q = x @ q_kernel.astype(cfg.compute_dtype)
        kv = x @ kv_kernel.astype(cfg.compute_dtype)
    if lora is not None:
        from megatronapp_tpu.ops.pallas.kernel_gen import apply_lora_delta
        q = apply_lora_delta(q, x, lora, "q_kernel")
        kv = apply_lora_delta(kv, x, lora, "kv_kernel")
    if "q_bias" in p:
        q = q + p["q_bias"].astype(cfg.compute_dtype)
        kv = kv + p["kv_bias"].astype(cfg.compute_dtype)
    q = q.reshape(b, s, nq, d)
    k, v = jnp.split(kv.reshape(b, s, 2 * nkv, d), 2, axis=2)

    # MegaScope QKV capture site (reference attention.py:979-981).
    q = scope_capture("qkv_q", q, layer_id)
    k = scope_capture("qkv_k", k, layer_id)
    v = scope_capture("qkv_v", v, layer_id)

    if cfg.qk_layernorm:
        q = rms_norm(q, p["q_ln_scale"], cfg.layernorm_epsilon)
        k = rms_norm(k, p["k_ln_scale"], cfg.layernorm_epsilon)

    q_offset = 0
    if rope_cos is not None:
        q = rotary.apply_rope(q, rope_cos, rope_sin)
        k = rotary.apply_rope(k, rope_cos, rope_sin)

    new_cache = None
    new_scales = None
    paged_out = None
    mask_type = cfg.attn_mask_type
    if kv_cache is not None:
        ck, cv = kv_cache
        if page_table is not None:
            # TP serving mesh (ISSUE 9): head-shard the paged kernels
            # over ctx's tp axis — the pool is sharded on Hkv (1/tp of
            # the KV bytes and attention FLOPs per device) and the
            # kernel is placed with a full-manual shard_map, exactly
            # like the flash wrapper above. The output is constrained
            # back to REPLICATED before the out-projection so every
            # device runs the identical dense matmul — per-request
            # greedy streams stay bit-identical to the single-device
            # engine (the tp2 parity pin in tests/test_disagg.py).
            from megatronapp_tpu.ops.pallas.paged_attention import (
                tp_paged_eligible,
            )
            from megatronapp_tpu.parallel.collectives import (
                current_manual_axes,
            )
            tp_paged = (tp_paged_eligible(cfg, ctx)
                        and not current_manual_axes())
        if page_table is not None and (s > 1 or chunk_counts is not None):
            # Multi-token paged append (speculative verify / chunked
            # prefill): write the ragged chunk then attend through the
            # multi-query kernel.
            from megatronapp_tpu.ops.pallas.paged_attention import (
                append_chunk_pages, paged_attention_multiquery,
                paged_attention_multiquery_tp, quantize_kv_rows,
            )
            if active is None:
                active = jnp.ones((b,), bool)
            counts = (chunk_counts if chunk_counts is not None
                      else jnp.full((b,), s, jnp.int32))
            if kv_scales is not None:
                # int8 pool: quantize the new rows per (row, head) right
                # here — ONE fused jit covers quantize + scatter +
                # attend — and scatter the scales through the same page
                # table.
                cks, cvs = kv_scales
                k_q, k_s = quantize_kv_rows(k, dtype=ck.dtype)
                v_q, v_s = quantize_kv_rows(v, dtype=cv.dtype)
                ck = append_chunk_pages(ck, k_q, page_table,
                                        cache_positions, counts, active)
                cv = append_chunk_pages(cv, v_q, page_table,
                                        cache_positions, counts, active)
                cks = append_chunk_pages(cks, k_s, page_table,
                                         cache_positions, counts, active)
                cvs = append_chunk_pages(cvs, v_s, page_table,
                                         cache_positions, counts, active)
                new_scales = (cks, cvs)
                sc_kw = {"k_scales": cks, "v_scales": cvs}
            else:
                ck = append_chunk_pages(ck, k, page_table,
                                        cache_positions, counts, active)
                cv = append_chunk_pages(cv, v, page_table,
                                        cache_positions, counts, active)
                sc_kw = {}
            new_cache = (ck, cv)
            if tp_paged:
                # manual-ok: tp_paged requires no ambient manual axes
                paged_out = paged_attention_multiquery_tp(
                    q, ck, cv, page_table, cache_positions + counts,
                    counts, ctx.shard_map_mesh, **sc_kw)
                paged_out = _replicate_heads(paged_out, ctx)
            else:
                paged_out = paged_attention_multiquery(
                    q, ck, cv, page_table, cache_positions + counts,
                    counts, **sc_kw)
        elif page_table is not None:
            # Paged continuous-batching decode: kv_cache is the shared
            # block pool; cache_positions[b] is row b's append position.
            from megatronapp_tpu.ops.pallas.paged_attention import (
                append_token_pages, paged_attention_decode,
                paged_attention_decode_tp, quantize_kv_rows,
            )
            if active is None:
                active = jnp.ones((b,), bool)
            if kv_scales is not None:
                cks, cvs = kv_scales
                k_q, k_s = quantize_kv_rows(k[:, 0], dtype=ck.dtype)
                v_q, v_s = quantize_kv_rows(v[:, 0], dtype=cv.dtype)
                ck = append_token_pages(ck, k_q, page_table,
                                        cache_positions, active)
                cv = append_token_pages(cv, v_q, page_table,
                                        cache_positions, active)
                cks = append_token_pages(cks, k_s, page_table,
                                         cache_positions, active)
                cvs = append_token_pages(cvs, v_s, page_table,
                                         cache_positions, active)
                new_scales = (cks, cvs)
                sc_kw = {"k_scales": cks, "v_scales": cvs}
            else:
                ck = append_token_pages(ck, k[:, 0], page_table,
                                        cache_positions, active)
                cv = append_token_pages(cv, v[:, 0], page_table,
                                        cache_positions, active)
                sc_kw = {}
            new_cache = (ck, cv)
            if tp_paged:
                # manual-ok: tp_paged requires no ambient manual axes
                paged_out = paged_attention_decode_tp(
                    q[:, 0], ck, cv, page_table, cache_positions + 1,
                    ctx.shard_map_mesh, **sc_kw)[:, None]
                paged_out = _replicate_heads(paged_out, ctx)
            else:
                paged_out = paged_attention_decode(
                    q[:, 0], ck, cv, page_table,
                    cache_positions + 1, **sc_kw)[:, None]  # [B,1,Hq,D]
        elif cache_positions is not None:
            # Continuous-batching decode (dynamic_context.py analogue):
            # each row appends at ITS OWN position; causality MUST come
            # from the caller's per-row attention_mask — fail fast if it
            # is missing rather than silently attending to stale/future
            # cache slots (round-2 advisor finding).
            if attention_mask is None:
                raise ValueError(
                    "per-row decode (cache_positions) requires an "
                    "explicit per-row attention_mask; see "
                    "inference/dynamic_engine.py's attend mask")
            ck = ck.at[jnp.arange(b), cache_positions].set(k[:, 0])
            cv = cv.at[jnp.arange(b), cache_positions].set(v[:, 0])
            mask_type = AttnMaskType.bidirectional
        else:
            # Static decode: append k,v at cache_index (static_context.py).
            ck = jax.lax.dynamic_update_slice_in_dim(ck, k, cache_index,
                                                     axis=1)
            cv = jax.lax.dynamic_update_slice_in_dim(cv, v, cache_index,
                                                     axis=1)
            q_offset = cache_index
        k, v = ck, cv
        # Quantized paged paths return the scale pools alongside so the
        # engine's lax.scan carries all four updated pools per layer.
        new_cache = ((ck, cv) if new_scales is None
                     else (ck, cv) + new_scales)

    # Note: the reference's apply_query_key_layer_scaling is numerically
    # neutral (it divides QK by layer_number for fp16 range safety and
    # multiplies it back inside the fused softmax). We always softmax in
    # fp32, so no scaling is needed — the flag is accepted for config parity
    # and intentionally has no effect on the math.
    if paged_out is not None:
        attn_out = paged_out
    elif ctx is not None and ctx.cp > 1 and kv_cache is None:
        # Context-parallel attention over the cp axis (seq sharded).
        from megatronapp_tpu.ops.context_parallel import (
            context_attention, zigzag_active,
        )
        if attention_mask is not None:
            raise NotImplementedError(
                "explicit attention_mask is not supported under context "
                "parallelism yet (only causal/bidirectional); run with "
                "context_parallel=1 or drop the mask")
        comm = ("p2p_zigzag" if zigzag and zigzag_active(cfg, ctx)
                else cfg.cp_comm_type)
        # manual-ok: context_attention detects the ambient manual cp axis
        # and runs its ring bodies directly there (no nested shard_map)
        attn_out = context_attention(
            q, k, v, ctx.shard_map_mesh, comm,
            causal=cfg.attn_mask_type == AttnMaskType.causal,
            segment_ids=segment_ids,
            a2a_size=cfg.hierarchical_cp_a2a_size,
            overlap_ring=getattr(cfg, "cp_comm_overlap", True))
    else:
        from megatronapp_tpu.parallel.collectives import current_manual_axes

        impl = cfg.attention_impl
        if impl == "auto":
            # Crossover: dense XLA attention below flash_min_seq (the
            # flash bwd kernels lose to the fused dense backward at
            # short S with D=64 — PERF.md), flash above it. The dense
            # fallback is memory-guarded: it materializes fp32
            # [B, H, S, S] scores+probs, so configs whose score tensors
            # exceed ~1 GB per device keep the O(S)-memory flash kernel
            # regardless of S.
            dense_bytes = 2 * 4 * b * nq * s * s
            if ctx is not None and ctx.num_devices > 1:
                # The [B,H,S,S] score tensor shards only over dp/ep/tp
                # (batch and heads) — pp/cp devices each hold a full
                # copy, so dividing by the whole mesh would undercount
                # per-device memory by up to pp*cp x and OOM a config
                # just below flash_min_seq.
                dense_bytes //= max(1, ctx.dp * ctx.ep * ctx.tp)
            impl = ("pallas" if jax.default_backend() == "tpu"
                    and (s >= cfg.flash_min_seq or dense_bytes > 1 << 30)
                    else "reference")
        # GSPMD cannot partition a pallas_call (it would replicate full
        # attention on every device), so the kernel must be placed
        # explicitly: on a multi-device mesh we shard_map it manually over
        # (dp, ep, tp) — attention is embarrassingly parallel over
        # batch/heads. Inside an existing manual region (the pp/cp pipeline
        # body) nesting shard_maps is unsupported in this JAX build, so fall
        # back to the reference impl there.
        in_manual = bool(current_manual_axes())
        use_flash = (
            impl == "pallas" and attention_mask is None
            and kv_cache is None and not in_manual
            and cfg.attn_mask_type in (AttnMaskType.causal,
                                       AttnMaskType.bidirectional))
        multi_device = ctx is not None and ctx.num_devices > 1
        if use_flash and multi_device:
            dp_ep = ctx.dp * ctx.ep
            use_flash = (b % dp_ep == 0 and nq % ctx.tp == 0
                         and nkv % ctx.tp == 0)
        if use_flash:
            from megatronapp_tpu.ops.pallas.flash_attention import (
                flash_attention,
            )
            causal = cfg.attn_mask_type == AttnMaskType.causal
            if multi_device:
                from jax.sharding import PartitionSpec as P
                from megatronapp_tpu.config.parallel_config import (
                    DP_AXIS, EP_AXIS, TP_AXIS,
                )
                from megatronapp_tpu.parallel.collectives import (
                    shard_map_compat,
                )
                # Full-manual region (shard_map_compat): the kernel is
                # purely local over (dp, ep, tp) shards; pp/cp ride
                # replicated (eligibility requires cp == 1 here).
                spec = P((DP_AXIS, EP_AXIS), None, TP_AXIS, None)
                seg_spec = P((DP_AXIS, EP_AXIS), None)
                if segment_ids is None:
                    # manual-ok: use_flash requires `not in_manual` above
                    flash = jax.jit(shard_map_compat(
                        lambda q_, k_, v_: flash_attention(
                            q_, k_, v_, causal=causal,
                            block_q=cfg.flash_block_q,
                            block_kv=cfg.flash_block_kv,
                            head_fold=getattr(cfg, "flash_head_fold",
                                              False)),
                        ctx.shard_map_mesh,
                        in_specs=(spec, spec, spec),
                        out_specs=spec))
                    attn_out = flash(q, k, v)
                else:
                    # manual-ok: use_flash requires `not in_manual` above
                    flash = jax.jit(shard_map_compat(
                        lambda q_, k_, v_, s_: flash_attention(
                            q_, k_, v_, causal=causal,
                            block_q=cfg.flash_block_q,
                            block_kv=cfg.flash_block_kv, segment_ids=s_),
                        ctx.shard_map_mesh,
                        in_specs=(spec, spec, spec, seg_spec),
                        out_specs=spec))
                    attn_out = flash(q, k, v, segment_ids)
            else:
                attn_out = flash_attention(
                    q, k, v, causal=causal,
                    block_q=cfg.flash_block_q, block_kv=cfg.flash_block_kv,
                    segment_ids=segment_ids,
                    head_fold=getattr(cfg, "flash_head_fold", False))
        else:
            if segment_ids is not None:
                seg_mask = (segment_ids[:, None, :, None]
                            == segment_ids[:, None, None, :])
                attention_mask = (seg_mask if attention_mask is None
                                  else attention_mask & seg_mask)
            attn_out = dot_product_attention(
                q, k, v, mask_type=mask_type,
                attention_mask=attention_mask, softmax_scale=None,
                softmax_in_fp32=cfg.attention_softmax_in_fp32,
                q_offset=q_offset, layer_id=layer_id)
    attn_out = scope_capture("context", attn_out, layer_id)

    out_kernel = _dist.apply("weight", resolve_param(p["out_kernel"]),
                             layer_id)
    out_kernel = out_kernel.astype(cfg.compute_dtype)
    if overlap:
        # manual-ok: same tp_overlap_eligible gate as the QKV ring above
        out = matmul_reduce_scatter(
            attn_out.reshape(b, s, nq * d), out_kernel,
            ctx.shard_map_mesh,
            fp8=None if fp8 is None else fp8["out"],
            fp8_margin=fp8_margin)
    else:
        out = attn_out.reshape(b, s, nq * d) @ out_kernel
        if lora is not None:
            from megatronapp_tpu.ops.pallas.kernel_gen import (
                apply_lora_delta)
            out = apply_lora_delta(out, attn_out.reshape(b, s, nq * d),
                                   lora, "out_kernel")
    if "out_bias" in p:
        out = out + p["out_bias"].astype(cfg.compute_dtype)
    return (out, new_cache) if kv_cache is not None else (out, None)
