"""Heterogeneous per-layer transformer configs (Llama-Nemotron style).

Parity with /root/reference/megatron/core/transformer/heterogeneous/
heterogeneous_config.py (HeterogeneousTransformerConfig): individual layers
may differ — attention or MLP can be a no-op or replaced with a single
linear layer (linear_replacements.py), GQA group counts and MLP
intermediate sizes can vary per layer. The config format is the
HuggingFace Nemotron "block_configs" JSON list
(heterogeneous_config.py:166-189).

TPU-first design: the uniform stack compiles as one scanned layer body
(transformer/block.py); heterogeneous stacks can't share one body, so the
block unrolls — a Python loop over per-layer params at trace time, each
layer under the same remat policy. Compile time grows with depth, but each
layer body is exactly the shape XLA already optimizes, and no_op halves
vanish entirely instead of being masked.
"""

from __future__ import annotations

import dataclasses
import json
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from megatronapp_tpu.config.transformer_config import (
    NormKind, TransformerConfig,
)
from megatronapp_tpu.ops.normalization import apply_norm
from megatronapp_tpu.transformer.attention import (
    attention_forward, init_attention_params,
)
from megatronapp_tpu.transformer.mlp import init_mlp_params, mlp_forward

OP_NORMAL = "normal"
OP_NOOP = "noop"
OP_LINEAR = "linear"


@dataclasses.dataclass(frozen=True)
class HeteroBlockSpec:
    """Resolved per-layer structure."""
    attention: str = OP_NORMAL          # normal | noop | linear
    num_query_groups: Optional[int] = None
    mlp: str = OP_NORMAL                # normal | noop | linear
    ffn_hidden_size: Optional[int] = None


def _ffn_mult_to_intermediate_size(ffn_mult: float, hidden: int) -> int:
    """2/3 rule + round up to a multiple of 256
    (heterogeneous_config.py:101-130)."""
    size = int(2 * ffn_mult * hidden / 3)
    return size if size % 256 == 0 else size + 256 - (size % 256)


def parse_block_configs(encoded_json: str, *, num_attention_heads: int,
                        hidden_size: int) -> Tuple[HeteroBlockSpec, ...]:
    """HF Nemotron config JSON (or a bare block_configs list) →
    HeteroBlockSpec tuple. Accepts both `num_query_groups` and the HF
    `n_heads_in_group` spelling (heterogeneous_config.py:38-51)."""
    doc = json.loads(encoded_json)
    blocks = doc["block_configs"] if isinstance(doc, dict) else doc
    specs = []
    for block in blocks:
        attn = block.get("attention", {})
        if attn.get("no_op"):
            a_op, nqg = OP_NOOP, None
        elif attn.get("replace_with_linear"):
            a_op, nqg = OP_LINEAR, None
        else:
            a_op = OP_NORMAL
            nqg = attn.get("num_query_groups")
            if nqg is None:
                nhg = attn.get("n_heads_in_group")
                if nhg:
                    if num_attention_heads % nhg:
                        raise ValueError(
                            f"num_attention_heads ({num_attention_heads}) "
                            f"not a multiple of n_heads_in_group ({nhg})")
                    nqg = num_attention_heads // nhg
        mlp = block.get("ffn", block.get("mlp", {}))
        if mlp.get("no_op"):
            m_op, ffn = OP_NOOP, None
        elif mlp.get("replace_with_linear"):
            m_op, ffn = OP_LINEAR, None
        else:
            m_op = OP_NORMAL
            ffn = mlp.get("ffn_hidden_size")
            if ffn is None and mlp.get("ffn_mult") is not None:
                ffn = _ffn_mult_to_intermediate_size(
                    float(mlp["ffn_mult"]), hidden_size)
        specs.append(HeteroBlockSpec(a_op, nqg, m_op, ffn))
    return tuple(specs)


def layer_cfg_for_spec(cfg: TransformerConfig,
                       spec: HeteroBlockSpec) -> TransformerConfig:
    """Per-layer TransformerConfig with the spec's overrides
    (reference get_config_for_layer, heterogeneous_config.py:229)."""
    over = {}
    if spec.num_query_groups is not None:
        over["num_query_groups"] = spec.num_query_groups
    if spec.ffn_hidden_size is not None:
        over["ffn_hidden_size"] = spec.ffn_hidden_size
    if not over:
        return cfg
    # Drop the block-configs JSON from the per-layer copy: replace()
    # re-runs __post_init__, and re-parsing the L-entry JSON per layer
    # would be O(L²); the per-layer cfg only feeds attention/MLP shapes.
    return dataclasses.replace(cfg, heterogeneous_layers_config_json=None,
                               **over)


def layer_relative_cost(spec: HeteroBlockSpec,
                        cfg: TransformerConfig) -> float:
    """Relative per-layer FLOP weight for the pipeline planner's
    heterogeneous stage table (parallel/schedule.stage_cost_model).

    Counts the projection GEMM work (the seq-independent part — the
    attention-score term scales every NORMAL-attention layer identically
    and cancels in the relative comparison the planner makes): no_op
    halves cost 0, linear replacements one [H, H] matmul, normal
    attention its QKV + out projections at the spec's GQA group count,
    normal MLP its fc1/fc2 (plus the gate half for gated activations)
    at the spec's ffn size."""
    from megatronapp_tpu.config.transformer_config import ActivationKind
    h = cfg.hidden_size
    d = cfg.head_dim
    nq = cfg.num_attention_heads
    cost = 0.0
    if spec.attention == OP_LINEAR:
        cost += h * h
    elif spec.attention == OP_NORMAL:
        nkv = spec.num_query_groups or cfg.num_query_groups
        cost += h * (nq + 2 * nkv) * d      # fused QKV projection
        cost += h * nq * d                  # out projection
    if spec.mlp == OP_LINEAR:
        cost += h * h
    elif spec.mlp == OP_NORMAL:
        ffn = spec.ffn_hidden_size or cfg.ffn_hidden_size
        gated = cfg.activation in (ActivationKind.swiglu,
                                   ActivationKind.geglu)
        cost += (3 if gated else 2) * h * ffn
    return cost


def init_hetero_block_params(rng, cfg: TransformerConfig):
    """Per-layer (unstacked) params + logical axes; layer i follows
    cfg.hetero_block_specs[i]."""
    specs = cfg.hetero_block_specs
    if len(specs) != cfg.num_layers:
        raise ValueError(
            f"heterogeneous block_configs has {len(specs)} entries for "
            f"num_layers={cfg.num_layers}")
    out_std = cfg.init_method_std / jnp.sqrt(2.0 * cfg.num_layers)
    h = cfg.hidden_size
    params: List[dict] = []
    axes: List[dict] = []
    keys = jax.random.split(rng, len(specs))
    for key, spec in zip(keys, specs):
        k_attn, k_mlp = jax.random.split(key)
        lcfg = layer_cfg_for_spec(cfg, spec)
        p, ax = {}, {}

        def add_norm(name):
            p[f"{name}_scale"] = jnp.ones((h,), cfg.params_dtype)
            ax[f"{name}_scale"] = ("embed",)
            if cfg.normalization == NormKind.layernorm:
                p[f"{name}_bias"] = jnp.zeros((h,), cfg.params_dtype)
                ax[f"{name}_bias"] = ("embed",)

        if spec.attention == OP_NORMAL:
            add_norm("ln1")
            p["attention"], ax["attention"] = init_attention_params(
                k_attn, lcfg, out_std)
        elif spec.attention == OP_LINEAR:
            add_norm("ln1")
            p["attn_linear"] = jax.random.normal(
                k_attn, (h, h), cfg.params_dtype) * out_std
            ax["attn_linear"] = ("embed", "embed")

        if spec.mlp == OP_NORMAL:
            add_norm("ln2")
            p["mlp"], ax["mlp"] = init_mlp_params(k_mlp, lcfg, out_std)
        elif spec.mlp == OP_LINEAR:
            add_norm("ln2")
            p["mlp_linear"] = jax.random.normal(
                k_mlp, (h, h), cfg.params_dtype) * out_std
            ax["mlp_linear"] = ("embed", "embed")

        params.append(p)
        axes.append(ax)
    return params, axes


def hetero_block_forward(per_layer_params, x: jnp.ndarray,
                         cfg: TransformerConfig, rope_cos=None,
                         rope_sin=None, attention_mask=None,
                         layer_offset: int = 0, ctx=None):
    """Unrolled heterogeneous stack. Returns (x, aux=0.0)."""
    from megatronapp_tpu.transformer.block import _remat_wrap

    specs = cfg.hetero_block_specs

    def one_layer(p, x, spec: HeteroBlockSpec, lid: int):
        lcfg = layer_cfg_for_spec(cfg, spec)
        if spec.attention != OP_NOOP:
            residual = x
            hdn = apply_norm(cfg.normalization, x, p["ln1_scale"],
                             p.get("ln1_bias"), cfg.layernorm_epsilon)
            if spec.attention == OP_LINEAR:
                out = hdn.astype(cfg.compute_dtype) @ \
                    p["attn_linear"].astype(cfg.compute_dtype)
            else:
                out, _ = attention_forward(
                    p["attention"], hdn, lcfg, rope_cos, rope_sin,
                    attention_mask, layer_id=lid, ctx=ctx)
            x = residual + out.astype(residual.dtype)
        if spec.mlp != OP_NOOP:
            residual = x
            hdn = apply_norm(cfg.normalization, x, p["ln2_scale"],
                             p.get("ln2_bias"), cfg.layernorm_epsilon)
            if spec.mlp == OP_LINEAR:
                out = hdn.astype(cfg.compute_dtype) @ \
                    p["mlp_linear"].astype(cfg.compute_dtype)
            else:
                out = mlp_forward(p["mlp"], hdn, lcfg, layer_id=lid,
                                  ctx=ctx)
            x = residual + out.astype(residual.dtype)
        return x

    for i, (p, spec) in enumerate(zip(per_layer_params, specs)):
        body = _remat_wrap(
            lambda p_, x_, s=spec, l=layer_offset + i: one_layer(
                p_, x_, s, l),
            cfg.remat_policy)
        x = body(p, x)
    return x, jnp.zeros((), jnp.float32)
