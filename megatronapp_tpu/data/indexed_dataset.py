"""Indexed binary dataset (.bin/.idx), format-compatible with Megatron.

Parity with /root/reference/megatron/core/datasets/indexed_dataset.py:506
(IndexedDataset) and its writer — same on-disk layout, fresh implementation:

.idx layout (little-endian):
  9s  magic  b"MMIDIDX\\x00\\x00"
  Q   version (1)
  B   dtype code (1=u8 2=i8 3=i16 4=i32 5=i64 6=f64 7=f32 8=u16)
  Q   sequence_count
  Q   document_count
  i32[sequence_count]  sequence lengths (tokens)
  i64[sequence_count]  sequence byte pointers into .bin
  i64[document_count]  sequence indices marking document ends
.bin: raw token arrays back to back.

Reads are zero-copy via np.memmap — a Megatron-preprocessed corpus drops in
unchanged.
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional, Type

import numpy as np

_INDEX_HEADER = b"MMIDIDX\x00\x00"
_DTYPE_CODES = {
    np.uint8: 1, np.int8: 2, np.int16: 3, np.int32: 4, np.int64: 5,
    np.float64: 6, np.float32: 7, np.uint16: 8,
}
_CODE_DTYPES = {v: k for k, v in _DTYPE_CODES.items()}


def best_dtype(vocab_size: int):
    """Smallest integer dtype holding token ids (reference
    DType.optimal_dtype)."""
    return np.uint16 if vocab_size < 65500 else np.int32


class IndexedDatasetWriter:
    """Streaming writer: add_document(tokens) per doc, finalize() at end."""

    def __init__(self, path_prefix: str, dtype: Type[np.number] = np.int32):
        self.path_prefix = path_prefix
        self.dtype = np.dtype(dtype).type
        if self.dtype not in _DTYPE_CODES:
            raise ValueError(f"unsupported dtype {dtype}")
        self._bin = open(path_prefix + ".bin", "wb")
        self._lengths: List[int] = []
        self._doc_indices: List[int] = [0]

    def add_document(self, tokens: np.ndarray,
                     sequence_lengths: Optional[List[int]] = None):
        """Append one document. By default the document is one sequence;
        pass sequence_lengths to split it (sentence-level datasets)."""
        tokens = np.asarray(tokens, dtype=self.dtype)
        self._bin.write(tokens.tobytes(order="C"))
        if sequence_lengths is None:
            self._lengths.append(len(tokens))
        else:
            assert sum(sequence_lengths) == len(tokens)
            self._lengths.extend(sequence_lengths)
        self._doc_indices.append(len(self._lengths))

    def finalize(self):
        self._bin.close()
        itemsize = np.dtype(self.dtype).itemsize
        pointers = np.zeros(len(self._lengths), dtype=np.int64)
        if len(self._lengths) > 1:
            np.cumsum(np.asarray(self._lengths[:-1], dtype=np.int64)
                      * itemsize, out=pointers[1:])
        with open(self.path_prefix + ".idx", "wb") as f:
            f.write(_INDEX_HEADER)
            f.write(struct.pack("<Q", 1))
            f.write(struct.pack("<B", _DTYPE_CODES[self.dtype]))
            f.write(struct.pack("<Q", len(self._lengths)))
            f.write(struct.pack("<Q", len(self._doc_indices)))
            f.write(np.asarray(self._lengths, dtype=np.int32)
                    .tobytes(order="C"))
            f.write(pointers.tobytes(order="C"))
            f.write(np.asarray(self._doc_indices, dtype=np.int64)
                    .tobytes(order="C"))

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            # Don't leave a valid-looking .idx behind a mid-stream failure.
            self._bin.close()
            for suffix in (".bin", ".idx"):
                try:
                    os.unlink(self.path_prefix + suffix)
                except OSError:
                    pass
            return False
        self.finalize()


class IndexedDataset:
    """mmap reader. ds[i] → np array of sequence i; ds.document_indices
    gives doc boundaries (reference IndexedDataset API)."""

    def __init__(self, path_prefix: str):
        self.path_prefix = path_prefix
        idx_path = path_prefix + ".idx"
        bin_path = path_prefix + ".bin"
        if not (os.path.exists(idx_path) and os.path.exists(bin_path)):
            raise FileNotFoundError(f"missing {idx_path} or {bin_path}")
        with open(idx_path, "rb") as f:
            header = f.read(9)
            if header != _INDEX_HEADER:
                raise ValueError(f"bad index header in {idx_path}")
            (version,) = struct.unpack("<Q", f.read(8))
            if version != 1:
                raise ValueError(f"unsupported index version {version}")
            (code,) = struct.unpack("<B", f.read(1))
            self.dtype = _CODE_DTYPES[code]
            (seq_count,) = struct.unpack("<Q", f.read(8))
            (doc_count,) = struct.unpack("<Q", f.read(8))
            offset = f.tell()
        idx_buf = np.memmap(idx_path, mode="r", order="C")
        o = offset
        self.sequence_lengths = np.frombuffer(
            idx_buf, dtype=np.int32, count=seq_count, offset=o)
        o += seq_count * 4
        self.sequence_pointers = np.frombuffer(
            idx_buf, dtype=np.int64, count=seq_count, offset=o)
        o += seq_count * 8
        self.document_indices = np.frombuffer(
            idx_buf, dtype=np.int64, count=doc_count, offset=o)
        self._bin = np.memmap(bin_path, mode="r", order="C")
        self._itemsize = np.dtype(self.dtype).itemsize

    def __len__(self) -> int:
        return len(self.sequence_lengths)

    def __getitem__(self, idx: int) -> np.ndarray:
        ptr = self.sequence_pointers[idx]
        length = self.sequence_lengths[idx]
        return np.frombuffer(self._bin, dtype=self.dtype, count=length,
                             offset=ptr)

    def get(self, idx: int, offset: int = 0,
            length: Optional[int] = None) -> np.ndarray:
        """Partial sequence read (reference IndexedDataset.get)."""
        seq_len = int(self.sequence_lengths[idx])
        if not 0 <= offset <= seq_len:
            raise IndexError(
                f"offset {offset} out of range for sequence {idx} "
                f"(length {seq_len})")
        ptr = self.sequence_pointers[idx] + offset * self._itemsize
        max_len = seq_len - offset
        length = max_len if length is None else min(length, max_len)
        # np.frombuffer treats ANY negative count as "read to the end" —
        # never let one through.
        length = max(int(length), 0)
        return np.frombuffer(self._bin, dtype=self.dtype, count=length,
                             offset=int(ptr))

    @property
    def num_tokens(self) -> int:
        return int(self.sequence_lengths.sum())
