"""Tokenizer wrappers.

Parity with /root/reference/megatron/training/tokenizer/tokenizer.py
(build_tokenizer: GPT2BPETokenizer, HuggingFaceTokenizer, NullTokenizer,
with vocab padding to a multiple for TP divisibility).
"""

from __future__ import annotations

from typing import List, Optional


class NullTokenizer:
    """Integer-string passthrough (reference NullTokenizer) — for synthetic
    and pre-tokenized data."""

    def __init__(self, vocab_size: int):
        self._vocab_size = vocab_size
        self.eod = vocab_size - 1

    @property
    def vocab_size(self) -> int:
        return self._vocab_size

    def tokenize(self, text: str) -> List[int]:
        return [int(t) for t in text.split()]

    def detokenize(self, ids: List[int]) -> str:
        return " ".join(str(i) for i in ids)


class HuggingFaceTokenizer:
    """Any HF tokenizer by name/path (reference HuggingFaceTokenizer)."""

    def __init__(self, name_or_path: str):
        from transformers import AutoTokenizer
        self._tok = AutoTokenizer.from_pretrained(name_or_path)
        self.eod = self._tok.eos_token_id

    @property
    def vocab_size(self) -> int:
        return len(self._tok)

    def tokenize(self, text: str) -> List[int]:
        return self._tok.encode(text)

    def detokenize(self, ids: List[int]) -> str:
        return self._tok.decode(ids)


class BertWordPieceTokenizer(HuggingFaceTokenizer):
    """WordPiece tokenizer exposing the special ids the BERT dataset needs
    (reference BertWordPieceTokenizer: cls/sep/mask/pad)."""

    def __init__(self, name_or_path: str = "bert-base-uncased"):
        super().__init__(name_or_path)
        self.cls = self._tok.cls_token_id
        self.sep = self._tok.sep_token_id
        self.mask = self._tok.mask_token_id
        self.pad = self._tok.pad_token_id
        missing = [n for n in ("cls", "sep", "mask", "pad")
                   if getattr(self, n) is None]
        if missing:
            raise ValueError(
                f"tokenizer {name_or_path!r} lacks special tokens "
                f"{missing} required for BERT pretraining — use a "
                f"WordPiece tokenizer (e.g. bert-base-uncased)")

    def tokenize(self, text: str) -> List[int]:
        # Raw wordpieces without [CLS]/[SEP] — the dataset assembles those.
        return self._tok.encode(text, add_special_tokens=False)


class GPT2BPETokenizer(HuggingFaceTokenizer):
    """GPT-2 byte-level BPE (reference GPT2BPETokenizer; vocab/merges come
    from the HF hub or a local path)."""

    def __init__(self, name_or_path: str = "gpt2"):
        super().__init__(name_or_path)


def pad_vocab_size(orig_vocab_size: int, multiple: int = 128,
                   tp: int = 1) -> int:
    """Pad vocab to a multiple divisible by TP (reference
    _vocab_size_with_padding)."""
    unit = multiple * tp
    return ((orig_vocab_size + unit - 1) // unit) * unit


def build_tokenizer(tokenizer_type: str, name_or_path: Optional[str] = None,
                    vocab_size: Optional[int] = None):
    """Factory (reference build_tokenizer)."""
    if tokenizer_type == "NullTokenizer":
        assert vocab_size is not None
        return NullTokenizer(vocab_size)
    if tokenizer_type == "GPT2BPETokenizer":
        return GPT2BPETokenizer(name_or_path or "gpt2")
    if tokenizer_type == "BertWordPieceTokenizer":
        return BertWordPieceTokenizer(name_or_path or "bert-base-uncased")
    if tokenizer_type == "HuggingFaceTokenizer":
        assert name_or_path
        return HuggingFaceTokenizer(name_or_path)
    raise ValueError(f"unknown tokenizer_type {tokenizer_type}")
