"""ctypes bindings for the C++ dataset index builders (+ numpy fallback).

Replaces the reference's pybind11 `helpers_cpp` module
(/root/reference/megatron/core/datasets/helpers.cpp) — built on demand with
g++ into libdata_helpers.so next to the source; a pure-numpy fallback keeps
everything working where no compiler exists.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libdata_helpers.so")
_LIB = None
_LOAD_FAILED = False
_LOCK = threading.Lock()


def _load_native() -> Optional[ctypes.CDLL]:
    global _LIB, _LOAD_FAILED
    with _LOCK:
        if _LIB is not None:
            return _LIB
        if _LOAD_FAILED:
            return None
        src = os.path.join(_NATIVE_DIR, "helpers.cpp")
        have_src = os.path.exists(src)
        stale = (have_src and os.path.exists(_SO_PATH) and
                 os.path.getmtime(_SO_PATH) < os.path.getmtime(src))
        if (not os.path.exists(_SO_PATH) or stale) and have_src:
            # Build to a temp path and rename atomically: concurrent
            # processes must never dlopen a half-written .so.
            tmp = _SO_PATH + f".tmp.{os.getpid()}"
            try:
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, src],
                    check=True, capture_output=True)
                os.replace(tmp, _SO_PATH)
            except Exception:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                _LOAD_FAILED = True
                return None
        if not os.path.exists(_SO_PATH):
            _LOAD_FAILED = True
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            _LOAD_FAILED = True
            return None
        lib.build_sample_idx.restype = ctypes.c_int64
        lib.build_sample_idx.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.build_blending_indices.restype = None
        lib.build_blending_indices.argtypes = [
            ctypes.POINTER(ctypes.c_int16), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_double), ctypes.c_int32, ctypes.c_int64,
        ]
        lib.build_mapping.restype = ctypes.c_int64
        lib.build_mapping.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_double, ctypes.c_uint64,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ]
        lib.build_exhaustive_blending_indices.restype = None
        lib.build_exhaustive_blending_indices.argtypes = [
            ctypes.POINTER(ctypes.c_int16), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
        ]
        lib.build_blocks_mapping.restype = ctypes.c_int64
        lib.build_blocks_mapping.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_uint64, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ]
        _LIB = lib
        return _LIB


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def build_sample_idx(sizes: np.ndarray, doc_idx: np.ndarray,
                     seq_length: int, num_samples: int) -> np.ndarray:
    """[num_samples+1, 2] (doc_pos, offset) pairs; sample i spans tokens
    from sample_idx[i] to sample_idx[i+1] (+1 label token overlap)."""
    sizes = np.ascontiguousarray(sizes, dtype=np.int32)
    doc_idx = np.ascontiguousarray(doc_idx, dtype=np.int64)
    lib = _load_native()
    if lib is not None:
        out = np.zeros((num_samples + 1, 2), dtype=np.int64)
        rc = lib.build_sample_idx(
            _ptr(sizes, ctypes.c_int32), _ptr(doc_idx, ctypes.c_int64),
            len(doc_idx), seq_length, num_samples,
            _ptr(out, ctypes.c_int64))
        if rc != 0:
            raise ValueError(
                "document stream exhausted before num_samples; add epochs")
        return out
    return _build_sample_idx_np(sizes, doc_idx, seq_length, num_samples)


def _build_sample_idx_np(sizes, doc_idx, seq_length, num_samples):
    out = np.zeros((num_samples + 1, 2), dtype=np.int64)
    doc_pos, doc_offset = 0, 0
    for i in range(1, num_samples + 1):
        remaining = seq_length
        while remaining > 0:
            if doc_pos >= len(doc_idx):
                raise ValueError(
                    "document stream exhausted before num_samples; "
                    "add epochs")
            doc_len = sizes[doc_idx[doc_pos]] - doc_offset
            if doc_len > remaining:
                doc_offset += remaining
                remaining = 0
            else:
                remaining -= doc_len
                doc_offset = 0
                doc_pos += 1
        out[i] = (doc_pos, doc_offset)
    return out


def build_blending_indices(weights: np.ndarray, size: int
                           ) -> Tuple[np.ndarray, np.ndarray]:
    """(dataset_index[size] int16, dataset_sample_index[size] int64)."""
    weights = np.ascontiguousarray(weights, dtype=np.float64)
    weights = weights / weights.sum()
    lib = _load_native()
    ds_idx = np.zeros(size, dtype=np.int16)
    ds_sample = np.zeros(size, dtype=np.int64)
    if lib is not None:
        lib.build_blending_indices(
            _ptr(ds_idx, ctypes.c_int16), _ptr(ds_sample, ctypes.c_int64),
            _ptr(weights, ctypes.c_double), len(weights), size)
        return ds_idx, ds_sample
    consumed = np.zeros(len(weights), dtype=np.int64)
    for i in range(size):
        err = weights * (i + 1) - consumed
        best = int(np.argmax(err))
        ds_idx[i] = best
        ds_sample[i] = consumed[best]
        consumed[best] += 1
    return ds_idx, ds_sample


def build_mapping_native(document_indices: np.ndarray,
                         sentence_lengths: np.ndarray,
                         num_epochs: int, max_num_samples: int,
                         max_seq_length: int, short_seq_prob: float,
                         seed: int, min_num_sent: int
                         ) -> Optional[np.ndarray]:
    """Native sentence-span sample mapping → int64 [N,3], or None when the
    native library is unavailable (masked_dataset.py falls back to the
    bit-identical numpy implementation — shared splitmix64 stream)."""
    lib = _load_native()
    if lib is None:
        return None
    docs = np.ascontiguousarray(document_indices, dtype=np.int64)
    sizes = np.ascontiguousarray(sentence_lengths, dtype=np.int32)
    n_docs = len(docs) - 1
    args = (_ptr(docs, ctypes.c_int64), n_docs,
            _ptr(sizes, ctypes.c_int32), num_epochs, max_num_samples,
            max_seq_length, short_seq_prob, seed, min_num_sent)
    count = lib.build_mapping(*args, None, 0)
    if count < 0:
        raise ValueError("build_mapping: invalid arguments")
    out = np.zeros((count, 3), dtype=np.int64)
    filled = lib.build_mapping(*args, _ptr(out, ctypes.c_int64), count)
    if filled != count:
        raise RuntimeError(
            f"build_mapping pass disagreement: {count} vs {filled}")
    return out


def build_exhaustive_blending_indices(sizes: np.ndarray
                                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Blend datasets drawing EXACTLY sizes[d] samples from dataset d
    (reference build_exhaustive_blending_indices, helpers.cpp:21-74):
    largest-deficit-first with size-proportional weights, datasets drop
    out of contention once exhausted. Deterministic."""
    sizes = np.ascontiguousarray(sizes, dtype=np.int64)
    total = int(sizes.sum())
    ds_idx = np.zeros(total, dtype=np.int16)
    ds_sample = np.zeros(total, dtype=np.int64)
    lib = _load_native()
    if lib is not None:
        lib.build_exhaustive_blending_indices(
            _ptr(ds_idx, ctypes.c_int16), _ptr(ds_sample, ctypes.c_int64),
            _ptr(sizes, ctypes.c_int64), len(sizes))
        return ds_idx, ds_sample
    weights = sizes / total if total else sizes.astype(np.float64)
    consumed = np.zeros(len(sizes), dtype=np.int64)
    spent = sizes == 0
    for i in range(total):
        err = weights * max(float(i), 1.0) - consumed
        err[spent] = -np.inf
        best = int(np.argmax(err))
        ds_idx[i] = best
        ds_sample[i] = consumed[best]
        consumed[best] += 1
        if consumed[best] >= sizes[best]:
            spent[best] = True
    return ds_idx, ds_sample


def _splitmix64(state: np.ndarray) -> int:
    """One splitmix64 draw; `state` is a 1-element uint64 array (shared
    stream with the C++ implementation)."""
    with np.errstate(over="ignore"):
        state[0] += np.uint64(0x9E3779B97F4A7C15)
        z = state[0]
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return int(z ^ (z >> np.uint64(31)))


def build_blocks_mapping(document_indices: np.ndarray,
                         sentence_lengths: np.ndarray,
                         title_lengths: np.ndarray,
                         num_epochs: int, max_num_samples: int,
                         max_seq_length: int, seed: int,
                         use_one_sent_blocks: bool = False) -> np.ndarray:
    """Block sample map for ICT/REALM retrieval pretraining → int64 [N,4]
    (first_sentence, end_sentence, doc, block_id). Reference semantics:
    build_blocks_mapping_impl (helpers.cpp:564-804); per-doc target length
    is max_seq_length - title_len so the title can be prepended. Native
    path with bit-identical numpy fallback (shared shuffle stream)."""
    docs = np.ascontiguousarray(document_indices, dtype=np.int64)
    sizes = np.ascontiguousarray(sentence_lengths, dtype=np.int32)
    titles = np.ascontiguousarray(title_lengths, dtype=np.int32)
    n_docs = len(docs) - 1
    if len(titles) < n_docs:
        raise ValueError(
            f"title_lengths has {len(titles)} entries but the block "
            f"dataset has {n_docs} documents — wrong titles companion?")
    min_num_sent = 1 if use_one_sent_blocks else 2
    lib = _load_native()
    if lib is not None:
        args = (_ptr(docs, ctypes.c_int64), n_docs,
                _ptr(sizes, ctypes.c_int32), _ptr(titles, ctypes.c_int32),
                num_epochs, max_num_samples, max_seq_length, seed,
                min_num_sent)
        count = lib.build_blocks_mapping(*args, None, 0)
        if count < 0:
            raise ValueError("build_blocks_mapping: invalid arguments")
        out = np.zeros((count, 4), dtype=np.int64)
        filled = lib.build_blocks_mapping(
            *args, _ptr(out, ctypes.c_int64), count)
        if filled != count:
            raise RuntimeError(
                f"build_blocks_mapping pass disagreement: {count} vs "
                f"{filled}")
        return out
    # numpy fallback — same traversal, same shuffle stream.
    rows = []
    long_sent = 512  # kLongSentenceLen
    for epoch in range(num_epochs):
        if max_num_samples > 0 and len(rows) >= max_num_samples:
            break
        block_id = 0
        for doc in range(n_docs):
            first, last = int(docs[doc]), int(docs[doc + 1])
            remain = last - first
            if remain < min_num_sent:
                continue
            if np.any(sizes[first:last] > long_sent):
                continue
            tgt = max_seq_length - int(titles[doc])
            start, seq_len, num_sent = first, 0, 0
            for s in range(first, last):
                seq_len += int(sizes[s])
                num_sent += 1
                remain -= 1
                if ((seq_len >= tgt and remain >= min_num_sent and
                     num_sent >= min_num_sent) or remain == 0):
                    rows.append((start, s + 1, doc, block_id))
                    block_id += 1
                    start = s + 1
                    seq_len, num_sent = 0, 0
    if max_num_samples > 0:
        rows = rows[:max_num_samples]
    out = np.asarray(rows, dtype=np.int64).reshape(-1, 4)
    state = np.array([np.uint64(seed + 1)], dtype=np.uint64)
    for i in range(len(out) - 1, 0, -1):
        j = _splitmix64(state) % (i + 1)
        out[[i, j]] = out[[j, i]]
    return out


def native_available() -> bool:
    return _load_native() is not None
