"""Mock GPT dataset for tests/benchmarks without preprocessed data.

Parity with /root/reference/megatron/core/datasets/gpt_dataset.py:753
(MockGPTDataset / MockGPTLowLevelDataset: deterministic pseudo-random token
sequences keyed by index). Batches carry the same fields the reference
get_batch produces (pretrain_gpt.py:139): tokens, labels, loss_mask,
position_ids (attention_mask is implicit causal).
"""

from __future__ import annotations

from typing import Dict, Iterator

import numpy as np


class MockGPTDataset:
    def __init__(self, seq_length: int, vocab_size: int, seed: int = 0,
                 size: int = 10**9):
        self.seq_length = seq_length
        self.vocab_size = vocab_size
        self.seed = seed
        self.size = size

    def __len__(self):
        return self.size

    def __getitem__(self, idx: int) -> np.ndarray:
        rng = np.random.default_rng((self.seed, idx))
        return rng.integers(0, self.vocab_size,
                            size=self.seq_length + 1).astype(np.int32)


def mock_batches(seq_length: int, vocab_size: int, batch_size: int,
                 seed: int = 0, start_idx: int = 0) -> Iterator[Dict[str, np.ndarray]]:
    """Infinite iterator of global batches (caller shards over dp).

    Delegates batch assembly to gpt_batches so the get_batch field contract
    lives in one place."""
    from megatronapp_tpu.data.gpt_dataset import gpt_batches
    ds = MockGPTDataset(seq_length, vocab_size, seed)
    return gpt_batches(ds, batch_size, start_idx=start_idx)
