"""Masked-workpiece sample construction over sentence-split corpora.

The base layer for BERT and T5 pretraining data. Parity targets (fresh
implementation, algorithm-level only):
- /root/reference/megatron/core/datasets/masked_dataset.py
  (MaskedWordPieceDataset: sentence-span sample index + masked-LM
  prediction construction with n-gram spans and 80/10/10 replacement)
- /root/reference/megatron/core/datasets/helpers.cpp:266 build_mapping
  (the two-pass sentence-span index builder; native variant in
  data/native/helpers.cpp, numpy fallback here).

A "sentence-split" corpus is an IndexedDataset written with one SEQUENCE
per sentence and document boundaries marking sentence runs
(tools/preprocess_data.py --split-sentences).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np


def masked_batches(dataset, batch_size: int, start_idx: int = 0
                   ) -> Iterator[Dict[str, np.ndarray]]:
    """Global-batch iterator over an indexable sample dataset (wraps
    around; resume via start_idx = consumed samples — the reference
    consumed_train_samples bookkeeping). Shared by the BERT and T5
    datasets."""
    i = start_idx
    while True:
        samples = [dataset[j % len(dataset)]
                   for j in range(i, i + batch_size)]
        i += batch_size
        yield {k: np.stack([s[k] for s in samples]) for k in samples[0]}


def build_sentence_sample_mapping(
    document_indices: np.ndarray,
    sentence_lengths: np.ndarray,
    num_epochs: int,
    max_num_samples: int,
    max_seq_length: int,
    short_seq_prob: float,
    seed: int,
    min_num_sent: int = 2,
) -> np.ndarray:
    """Map of (first_sentence, end_sentence, target_seq_length) triples.

    Walks documents sentence by sentence, emitting a sample whenever the
    accumulated token count reaches a target length (occasionally shortened
    with probability short_seq_prob), then shuffles the map — the semantics
    of the reference build_mapping (helpers.cpp:266-524). Documents with
    fewer than min_num_sent sentences or any sentence longer than 512
    tokens are skipped (reference LONG_SENTENCE_LEN).

    Returns int64 [N, 3].
    """
    from megatronapp_tpu.data.helpers import build_mapping_native

    native = build_mapping_native(
        document_indices, sentence_lengths, num_epochs, max_num_samples,
        max_seq_length, short_seq_prob, seed, min_num_sent)
    if native is not None:
        return native
    return _build_mapping_np(
        document_indices, sentence_lengths, num_epochs, max_num_samples,
        max_seq_length, short_seq_prob, seed, min_num_sent)


_LONG_SENTENCE_LEN = 512
_U64 = 0xFFFFFFFFFFFFFFFF


def _splitmix64(state: int) -> Tuple[int, int]:
    """(new_state, value) — bit-identical to the C++ splitmix64 in
    data/native/helpers.cpp, so the numpy fallback and the native builder
    produce the SAME sample map for the same seed."""
    state = (state + 0x9E3779B97F4A7C15) & _U64
    z = state
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _U64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _U64
    return state, z ^ (z >> 31)


def _build_mapping_np(docs, sizes, num_epochs, max_num_samples,
                      max_seq_length, short_seq_prob, seed, min_num_sent):
    state = int(seed) & _U64

    def target_len(state):
        state, r = _splitmix64(state)
        if short_seq_prob > 0 and \
                (r >> 11) * (1.0 / 9007199254740992.0) < short_seq_prob:
            state, r2 = _splitmix64(state)
            return state, 2 + int(r2 % (max_seq_length - 1))
        return state, max_seq_length

    triples: List[Tuple[int, int, int]] = []
    for _epoch in range(num_epochs):
        if max_num_samples > 0 and len(triples) >= max_num_samples:
            break
        for doc in range(len(docs) - 1):
            first, last = int(docs[doc]), int(docs[doc + 1])
            if last - first < min_num_sent:
                continue
            if np.any(sizes[first:last] > _LONG_SENTENCE_LEN):
                continue
            start = first
            seq_len = 0
            num_sent = 0
            state, tgt = target_len(state)
            for s in range(first, last):
                seq_len += int(sizes[s])
                num_sent += 1
                remain = last - s - 1
                if (seq_len >= tgt and remain > 1 and
                        num_sent >= min_num_sent) or remain == 0:
                    triples.append((start, s + 1, tgt))
                    start = s + 1
                    seq_len = 0
                    num_sent = 0
                    state, tgt = target_len(state)
    if max_num_samples > 0:
        triples = triples[:max_num_samples]
    out = np.asarray(triples, dtype=np.int64).reshape(-1, 3)
    # Fisher-Yates with the shared RNG (seed + 1 stream) — matches C++.
    sstate = (int(seed) + 1) & _U64
    for i in range(len(out) - 1, 0, -1):
        sstate, r = _splitmix64(sstate)
        j = int(r % (i + 1))
        out[[i, j]] = out[[j, i]]
    return out


@dataclasses.dataclass
class MaskingConfig:
    """Masked-LM replacement policy (reference masked_dataset.py fields)."""
    masked_lm_prob: float = 0.15
    max_ngram: int = 1              # SpanBERT-style n-gram masking when > 1
    mask_token_prob: float = 0.8    # replace with [MASK]
    random_token_prob: float = 0.1  # replace with random token
    # remaining probability: keep the original token


def create_masked_lm_predictions(
    tokens: Sequence[int],
    vocab_size: int,
    mask_id: int,
    special_ids: Sequence[int],
    rng: np.random.RandomState,
    cfg: Optional[MaskingConfig] = None,
    max_predictions: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(masked_tokens, masked_positions, masked_labels).

    Selects ~masked_lm_prob of non-special positions (in shuffled n-gram
    spans), replacing each with [MASK] (80%), a random token (10%), or the
    original (10%) — reference _create_masked_lm_predictions
    (masked_dataset.py:231).
    """
    cfg = cfg or MaskingConfig()
    tokens = np.asarray(tokens, dtype=np.int64)
    special = set(int(x) for x in special_ids)
    candidates = [i for i, t in enumerate(tokens) if int(t) not in special]
    n_pred = max(1, int(round(len(candidates) * cfg.masked_lm_prob)))
    if max_predictions is not None:
        n_pred = min(n_pred, max_predictions)

    # Build candidate n-gram spans starting at shuffled positions; favor
    # short spans (probability ∝ 1/n, the reference's ngram weighting).
    order = list(candidates)
    rng.shuffle(order)
    if cfg.max_ngram > 1:
        ngram_p = 1.0 / np.arange(1, cfg.max_ngram + 1)
        ngram_p = ngram_p / ngram_p.sum()

    covered = set()
    positions: List[int] = []
    for start in order:
        if len(positions) >= n_pred:
            break
        if start in covered:
            continue
        n = 1
        if cfg.max_ngram > 1:
            n = 1 + rng.choice(cfg.max_ngram, p=ngram_p)
        span = []
        for i in range(start, min(start + n, len(tokens))):
            if int(tokens[i]) in special or i in covered:
                break
            span.append(i)
        if not span or len(positions) + len(span) > n_pred:
            span = span[: n_pred - len(positions)]
        for i in span:
            covered.add(i)
            positions.append(i)

    positions.sort()
    positions = np.asarray(positions, dtype=np.int64)
    labels = tokens[positions].copy()
    out = tokens.copy()
    for pos in positions:
        roll = rng.random_sample()
        if roll < cfg.mask_token_prob:
            out[pos] = mask_id
        elif roll < cfg.mask_token_prob + cfg.random_token_prob:
            out[pos] = rng.randint(0, vocab_size)
        # else: keep original
    return out, positions, labels
