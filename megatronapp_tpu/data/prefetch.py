"""Cross-process batch prefetching over the C++ shared-memory ring.

This is where MegaDPP's shm transport earns its keep on TPU (SURVEY §2.7:
"keep a C++ shm ring for host-side staging"): inter-CHIP activation traffic
belongs to XLA collectives, but host-side BATCH PREPARATION (tokenization,
masking, sample-index gathers) is Python work that otherwise serializes
with step dispatch. A producer PROCESS builds batches and pushes each
field's array through `runtime/shm_ring.ShmRing` (zero-copy writes into
/dev/shm, SPSC lock-free); the trainer pops ready batches — data prep
overlaps device execution across a process boundary, the same
producer/consumer structure as the reference's background sender/receiver
threads (shm_tensor_new_rdma.cpp:1478-1646).

Wire protocol per batch: one uint8 JSON header (field names) then one
array per field in header order (ShmRing frames carry dtype/shape).
"""

from __future__ import annotations

import json
import multiprocessing as mp
import time
from typing import Callable, Dict, Iterator, Optional

import numpy as np

from megatronapp_tpu.runtime.shm_ring import ShmRing


def _producer(name: str, factory: Callable[[], Iterator],
              num_batches: int, capacity: int):
    ring = ShmRing(name, create=False)
    it = factory()
    try:
        for _ in range(num_batches):
            batch = next(it)
            keys = sorted(batch)
            header = json.dumps({"keys": keys}).encode()
            payloads = [np.frombuffer(header, np.uint8)] + [
                np.ascontiguousarray(batch[k]) for k in keys]
            for arr in payloads:
                # Fail fast on frames that can never fit (push would
                # otherwise spin forever and the consumer would time out
                # with a misleading error). ~64B covers frame framing +
                # dtype/shape metadata.
                if arr.nbytes + 64 > capacity:
                    raise ValueError(
                        f"batch field of {arr.nbytes} bytes exceeds ring "
                        f"capacity {capacity}; raise ShmPrefetcher("
                        f"capacity=...)")
                while not ring.push_array(arr):
                    time.sleep(0.0005)
    finally:
        ring.close()


class ShmPrefetcher:
    """Iterator over batches produced in a separate process.

    factory() must be picklable (a module-level function or partial) and
    return the batch iterator when called INSIDE the producer process.
    """

    def __init__(self, factory: Callable[[], Iterator],
                 num_batches: int, capacity: int = 1 << 26,
                 name: Optional[str] = None):
        self.name = name or f"/mta_prefetch_{time.time_ns() & 0xFFFFFF}"
        self.capacity = capacity
        self.ring = ShmRing(self.name, capacity=capacity)
        self.num_batches = num_batches
        self._served = 0
        ctx = mp.get_context("spawn")
        self.proc = ctx.Process(
            target=_producer,
            args=(self.name, factory, num_batches, capacity), daemon=True)
        self.proc.start()

    def _pop(self, timeout: float = 300.0) -> np.ndarray:
        deadline = time.monotonic() + timeout
        while True:
            # Receive buffer must admit anything the ring can hold — the
            # pop-side default (64MB) is smaller than large capacities.
            arr = self.ring.pop_array(max_len=self.capacity)
            if arr is not None:
                return arr
            if not self.proc.is_alive():
                # Drain: the producer may have pushed its final frames
                # right before exiting.
                arr = self.ring.pop_array(max_len=self.capacity)
                if arr is not None:
                    return arr
                raise RuntimeError(
                    "prefetch producer died before finishing")
            if time.monotonic() > deadline:
                raise TimeoutError("prefetch pop timed out")
            time.sleep(0.0005)

    def __iter__(self):
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        if self._served >= self.num_batches:
            raise StopIteration
        header = json.loads(self._pop().tobytes().decode())
        batch = {key: self._pop() for key in header["keys"]}
        self._served += 1
        return batch

    def close(self):
        if self.proc.is_alive():
            self.proc.terminate()
        self.proc.join(timeout=5)
        self.ring.close()
        self.ring.unlink()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
