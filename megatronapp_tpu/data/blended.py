"""Weighted blend of multiple datasets.

Parity with /root/reference/megatron/core/datasets/blended_dataset.py:25
(BlendedDataset): samples are drawn from constituent datasets in proportion
to weights using the deficit-tracking index built by the C++ helper
(build_blending_indices), deterministic and stable across runs.
weights=None activates the exhaustive mode (reference
build_exhaustive_blending_indices, used when blends give sizes instead of
weights): every constituent is consumed exactly once, interleaved
size-proportionally.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from megatronapp_tpu.data.helpers import (
    build_blending_indices, build_exhaustive_blending_indices,
)


class BlendedDataset:
    def __init__(self, datasets: Sequence,
                 weights: Optional[Sequence[float]],
                 num_samples: Optional[int] = None):
        if weights is None:
            # Exhaustive: draw exactly len(d) samples from each d.
            self.datasets = list(datasets)
            sizes = np.asarray([len(d) for d in datasets], np.int64)
            self.dataset_index, self.dataset_sample_index = \
                build_exhaustive_blending_indices(sizes)
            self.num_samples = int(sizes.sum())
            if num_samples is not None and num_samples != self.num_samples:
                raise ValueError(
                    f"exhaustive blend yields {self.num_samples} samples; "
                    f"num_samples={num_samples} conflicts")
            return
        if len(datasets) != len(weights):
            raise ValueError("datasets and weights length mismatch")
        if num_samples is None:
            raise ValueError("num_samples required with explicit weights")
        self.datasets = list(datasets)
        self.num_samples = num_samples
        self.dataset_index, self.dataset_sample_index = \
            build_blending_indices(np.asarray(weights, dtype=np.float64),
                                   num_samples)
        # Validate each constituent can supply its weighted share
        # (reference BlendedDataset size check).
        counts = np.bincount(self.dataset_index, minlength=len(datasets))
        for d, need in enumerate(counts):
            if need > len(self.datasets[d]):
                raise ValueError(
                    f"dataset {d} supplies {need} samples under these "
                    f"weights but only has {len(self.datasets[d])}; reduce "
                    f"num_samples or its weight")

    def __len__(self) -> int:
        return self.num_samples

    def __getitem__(self, idx: int):
        d = self.dataset_index[idx]
        s = self.dataset_sample_index[idx]
        return self.datasets[d][int(s)]

    @property
    def seq_length(self):
        return self.datasets[0].seq_length
