"""BERT pretraining dataset: masked-LM + next-sentence prediction samples.

Parity with /root/reference/megatron/core/datasets/bert_dataset.py
(BERTMaskedWordPieceDataset.__getitem__: sentence-span sample → A/B split
with 50% random swap (NSP), center-out truncation to the target length,
[CLS] A [SEP] B [SEP] assembly with tokentype assignments, masked-LM
prediction, padding) — fresh implementation over our sentence-split
IndexedDataset.

Batch fields match models/bert.py bert_loss:
  tokens, labels, loss_mask, padding_mask, tokentype_ids, is_random.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, Optional

import numpy as np

from megatronapp_tpu.data.indexed_dataset import IndexedDataset
from megatronapp_tpu.data.masked_dataset import (
    MaskingConfig, build_sentence_sample_mapping,
    create_masked_lm_predictions, masked_batches,
)


@dataclasses.dataclass
class BertTokenIds:
    """Special token ids the dataset needs (reference reads them off the
    BertWordPieceTokenizer: cls/sep/mask/pad)."""
    cls: int
    sep: int
    mask: int
    pad: int


class BertDataset:
    """Masked-LM + NSP samples from a sentence-split .bin/.idx corpus."""

    def __init__(self, indexed: IndexedDataset, *, seq_length: int,
                 vocab_size: int, token_ids: BertTokenIds,
                 num_samples: int, seed: int = 1234,
                 masked_lm_prob: float = 0.15, short_seq_prob: float = 0.1,
                 max_ngram: int = 1, classification_head: bool = True,
                 num_epochs: int = 100):
        self.ds = indexed
        self.seq_length = seq_length
        self.vocab_size = vocab_size
        self.ids = token_ids
        self.seed = seed
        self.classification_head = classification_head
        self.masking = MaskingConfig(masked_lm_prob=masked_lm_prob,
                                     max_ngram=max_ngram)
        self.sample_index = build_sentence_sample_mapping(
            indexed.document_indices, indexed.sequence_lengths,
            num_epochs=num_epochs, max_num_samples=num_samples,
            # -3 head-room for [CLS] and 2×[SEP] (reference passes
            # sequence_length - 3 for the classification-head case).
            max_seq_length=seq_length - 3, short_seq_prob=short_seq_prob,
            seed=seed, min_num_sent=2 if classification_head else 1)
        if len(self.sample_index) == 0:
            raise ValueError(
                "no BERT samples could be built — is the corpus "
                "sentence-split (tools/preprocess_data.py "
                "--split-sentences)?")

    def __len__(self) -> int:
        return len(self.sample_index)

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        first, end, target_len = self.sample_index[idx % len(self)]
        rng = np.random.RandomState((self.seed + idx) % 2**32)
        sents = [np.asarray(self.ds[i], np.int64)
                 for i in range(first, end)]

        # NSP: split sentences into contiguous segments A/B; 50% swapped.
        pivot = len(sents)
        is_random = 0
        if self.classification_head:
            pivot = 1 if len(sents) < 3 else rng.randint(1, len(sents))
            is_random = int(rng.random_sample() < 0.5)
        a = [t for s in sents[:pivot] for t in s]
        b = [t for s in sents[pivot:] for t in s]
        if is_random:
            a, b = b, a

        # Trim the pair from random ends to the target length (reference
        # end-biased truncation).
        while len(a) + len(b) > target_len:
            longer = a if len(a) > len(b) else b
            if rng.random_sample() < 0.5:
                del longer[0]
            else:
                del longer[-1]

        ids = self.ids
        tokens = [ids.cls, *a, ids.sep]
        types = [0] * len(tokens)
        if b:
            tokens += [*b, ids.sep]
            types += [1] * (len(b) + 1)

        masked, positions, mlm_labels = create_masked_lm_predictions(
            tokens, self.vocab_size, ids.mask,
            special_ids=(ids.cls, ids.sep, ids.pad), rng=rng,
            cfg=self.masking)

        s = self.seq_length
        n = len(masked)
        out_tokens = np.full((s,), ids.pad, np.int32)
        out_tokens[:n] = masked
        out_types = np.zeros((s,), np.int32)
        out_types[:n] = types
        padding_mask = np.zeros((s,), np.float32)
        padding_mask[:n] = 1.0
        # Unmasked positions carry label 0 (excluded via loss_mask); a -1
        # sentinel would index out of bounds in take_along_axis CE.
        labels = np.zeros((s,), np.int32)
        labels[positions] = mlm_labels
        loss_mask = np.zeros((s,), np.float32)
        loss_mask[positions] = 1.0
        return {
            "tokens": out_tokens,
            "labels": labels,
            "loss_mask": loss_mask,
            "padding_mask": padding_mask,
            "tokentype_ids": out_types,
            "is_random": np.int32(is_random),
        }


bert_batches = masked_batches
