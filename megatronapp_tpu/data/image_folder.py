"""Image-folder dataset + vision transforms for the vision entries.

Parity with /root/reference/megatron/legacy/data/image_folder.py
(class-per-subdirectory layout, classes_fraction /
data_per_class_fraction subsampling :67-109) and
legacy/data/vit_dataset.py (ClassificationTransform :50 — train
RandomResizedCrop+flip / eval resize+center-crop, ImageNet
normalization; DinoTransform :148 — global/local multi-crop). TPU-first:
transforms are numpy (PIL for decode/resize only, no torchvision), and
batches arrive as [B, H, W, C] float32 host arrays ready for the
sharded train step.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".webp", ".npy")
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def _load_image(path: str) -> np.ndarray:
    """Decode to float32 [H, W, 3] in [0, 1]."""
    if path.endswith(".npy"):
        arr = np.asarray(np.load(path), np.float32)
        if arr.ndim == 2:
            arr = np.repeat(arr[..., None], 3, -1)
        if arr.max() > 1.5:   # stored in the 0-255 convention
            arr = arr / 255.0
        return np.clip(arr, 0.0, 1.0)
    from PIL import Image
    with Image.open(path) as im:
        return np.asarray(im.convert("RGB"), np.float32) / 255.0


def _resize(img: np.ndarray, size_hw) -> np.ndarray:
    """Resize to (h, w) — or to (size, size) for an int size."""
    from PIL import Image
    h, w = (size_hw, size_hw) if isinstance(size_hw, int) else size_hw
    im = Image.fromarray((np.clip(img, 0, 1) * 255).astype(np.uint8))
    return np.asarray(im.resize((w, h), Image.BILINEAR),
                      np.float32) / 255.0


class ImageFolder:
    """Class-per-subdirectory image dataset (reference ImageFolder).

    root/
      class_a/ img0.png ...
      class_b/ ...
    """

    def __init__(self, root: str, classes_fraction: float = 1.0,
                 data_per_class_fraction: float = 1.0):
        self.root = root
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        classes = classes[: max(int(len(classes) * classes_fraction), 1)]
        self.classes: List[str] = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples: List[Tuple[str, int]] = []
        for c in classes:
            files = sorted(
                f for f in os.listdir(os.path.join(root, c))
                if f.lower().endswith(_EXTS))
            keep = max(int(len(files) * data_per_class_fraction), 1)
            self.samples.extend(
                (os.path.join(root, c, f), self.class_to_idx[c])
                for f in files[:keep])
        if not self.samples:
            raise FileNotFoundError(f"no images under {root}")

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i) -> Tuple[np.ndarray, int]:
        path, label = self.samples[i]
        return _load_image(path), label


# ---------------------------------------------------------------------------
# Transforms (numpy; reference vit_dataset.py)


def _random_resized_crop(img: np.ndarray, size: int, rng,
                         scale=(0.08, 1.0)) -> np.ndarray:
    h, w = img.shape[:2]
    area = h * w
    for _ in range(10):
        target = rng.uniform(*scale) * area
        ar = np.exp(rng.uniform(np.log(3 / 4), np.log(4 / 3)))
        ch = int(round(np.sqrt(target / ar)))
        cw = int(round(np.sqrt(target * ar)))
        if ch <= h and cw <= w:
            y = rng.integers(0, h - ch + 1)
            x = rng.integers(0, w - cw + 1)
            return _resize(img[y:y + ch, x:x + cw], size)
    return _center_crop(img, size)


def _center_crop(img: np.ndarray, size: int) -> np.ndarray:
    """Aspect-preserving short-side resize (reference Resize(size*1.143
    ≈ 256/224)) then center crop — no squash-to-square."""
    h, w = img.shape[:2]
    scale_size = max(int(size * 1.143), size)
    if min(h, w) != scale_size:
        if h < w:
            new_h, new_w = scale_size, int(round(w * scale_size / h))
        else:
            new_h, new_w = int(round(h * scale_size / w)), scale_size
        img = _resize(img, (new_h, new_w))
        h, w = new_h, new_w
    y, x = (h - size) // 2, (w - size) // 2
    return img[y:y + size, x:x + size]


def _normalize(img: np.ndarray) -> np.ndarray:
    return (img - IMAGENET_MEAN) / IMAGENET_STD


class ClassificationTransform:
    """train: RandomResizedCrop + horizontal flip; eval: resize +
    center-crop; both ImageNet-normalized (vit_dataset.py:50-71)."""

    def __init__(self, image_size: int, train: bool = True,
                 seed: int = 0):
        self.image_size = image_size
        self.train = train
        self.rng = np.random.default_rng(seed)

    def __call__(self, img: np.ndarray) -> np.ndarray:
        if self.train:
            img = _random_resized_crop(img, self.image_size, self.rng)
            if self.rng.random() < 0.5:
                img = img[:, ::-1]
        else:
            img = _center_crop(img, self.image_size)
        return _normalize(np.ascontiguousarray(img)).astype(np.float32)


class DinoTransform:
    """2 global crops (scale 0.4-1) + N local crops (scale 0.05-0.4,
    smaller size), flips, ImageNet normalization (vit_dataset.py:148-205;
    color jitter/blur omitted — augmentation-strength knobs, not wire
    contract)."""

    def __init__(self, image_size: int, local_size: int,
                 n_local: int, seed: int = 0):
        self.image_size = image_size
        self.local_size = local_size
        self.n_local = n_local
        self.rng = np.random.default_rng(seed)

    def _crop(self, img, size, scale):
        out = _random_resized_crop(img, size, self.rng, scale=scale)
        if self.rng.random() < 0.5:
            out = out[:, ::-1]
        return _normalize(np.ascontiguousarray(out)).astype(np.float32)

    def __call__(self, img: np.ndarray
                 ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """→ (global [2, S, S, 3], local [n, s, s, 3] or None)."""
        g = np.stack([self._crop(img, self.image_size, (0.4, 1.0))
                      for _ in range(2)])
        if self.n_local == 0:
            return g, None
        loc = np.stack([self._crop(img, self.local_size, (0.05, 0.4))
                        for _ in range(self.n_local)])
        return g, loc


# ---------------------------------------------------------------------------
# Batch iterators


def _epoch_batches(dataset: ImageFolder, batch_size: int, seed: int
                   ) -> Iterator[np.ndarray]:
    """Endless shuffled epochs of index batches (shared epoch loop)."""
    if batch_size > len(dataset):
        raise ValueError(
            f"batch_size={batch_size} exceeds dataset size "
            f"{len(dataset)} ({dataset.root}); the epoch loop would "
            "spin forever yielding nothing")
    rng = np.random.default_rng(seed)
    while True:
        order = rng.permutation(len(dataset))
        for i in range(0, len(order) - batch_size + 1, batch_size):
            yield order[i:i + batch_size]


def image_batches(dataset: ImageFolder, batch_size: int,
                  transform: ClassificationTransform,
                  seed: int = 0) -> Iterator[Dict[str, np.ndarray]]:
    """Shuffled epochs of {'images' [B,S,S,3], 'labels' [B]}."""
    for idx in _epoch_batches(dataset, batch_size, seed):
        imgs, labels = zip(*(dataset[j] for j in idx))
        yield {"images": np.stack([transform(im) for im in imgs]),
               "labels": np.asarray(labels, np.int32)}


def dino_batches(dataset: ImageFolder, batch_size: int,
                 transform: DinoTransform,
                 seed: int = 0) -> Iterator[Dict[str, np.ndarray]]:
    """Shuffled epochs of multi-crop batches
    {'global_crops' [B,2,S,S,3], 'local_crops' [B,n,s,s,3]}."""
    for idx in _epoch_batches(dataset, batch_size, seed):
        crops = [transform(dataset[j][0]) for j in idx]
        batch = {"global_crops": np.stack([c[0] for c in crops])}
        if crops[0][1] is not None:
            batch["local_crops"] = np.stack([c[1] for c in crops])
        yield batch


def load_folder(data_path: str, log_fn=print) -> ImageFolder:
    """Open + announce an image corpus (shared entry-point wiring)."""
    ds = ImageFolder(data_path)
    log_fn(f"image corpus: {len(ds)} images / {len(ds.classes)} "
           f"classes from {data_path}")
    return ds
