"""ICT (Inverse Cloze Task) dataset for biencoder retrieval pretraining.

Parity with /root/reference/megatron/legacy/data/ict_dataset.py
(ICTDataset): the corpus is a sentence-split IndexedDataset plus a titles
IndexedDataset (one title per document); blocks come from the native
build_blocks_mapping (sentence spans closed at max_seq_length -
title_len); each sample draws one sentence as the pseudo-query and keeps
it in the context block query_in_block_prob of the time
(ict_dataset.py:92-99).

Layout of the emitted pairs (concat_and_pad_tokens semantics):
  query:   [CLS] sentence [SEP]                    (padded to seq_length)
  context: [CLS] title [SEP] block [SEP]           (padded to seq_length)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional

import numpy as np

from megatronapp_tpu.data.helpers import build_blocks_mapping
from megatronapp_tpu.data.indexed_dataset import IndexedDataset


@dataclass
class IctTokenIds:
    cls: int = 1
    sep: int = 2
    pad: int = 0


class ICTDataset:
    """len() = number of blocks; [i] → dict of query/context arrays."""

    def __init__(self, block_dataset: IndexedDataset,
                 title_dataset: IndexedDataset, *, seq_length: int,
                 token_ids: Optional[IctTokenIds] = None,
                 num_epochs: int = 1, max_num_samples: int = 0,
                 query_in_block_prob: float = 0.1, seed: int = 1,
                 use_one_sent_blocks: bool = False):
        self.block = block_dataset
        self.titles = title_dataset
        self.seq_length = seq_length
        self.ids = token_ids or IctTokenIds()
        self.query_in_block_prob = query_in_block_prob
        self.rng = np.random.default_rng(seed)
        docs = np.asarray(block_dataset.document_indices)
        # Lengths come straight from the .idx — no data reads.
        title_lengths = np.asarray(title_dataset.sequence_lengths,
                                   dtype=np.int32)[:len(docs) - 1]
        self.mapping = build_blocks_mapping(
            docs, np.asarray(block_dataset.sequence_lengths),
            title_lengths, num_epochs, max_num_samples,
            # Reserve [CLS] .. [SEP] .. [SEP] like the reference's
            # title_pad_offset=3.
            seq_length - 3, seed,
            use_one_sent_blocks=use_one_sent_blocks)

    def __len__(self) -> int:
        return len(self.mapping)

    def _pad(self, pieces: List[np.ndarray]) -> Dict[str, np.ndarray]:
        toks = np.concatenate(pieces)[:self.seq_length]
        out = np.full(self.seq_length, self.ids.pad, dtype=np.int32)
        out[:len(toks)] = toks
        mask = np.zeros(self.seq_length, dtype=np.int32)
        mask[:len(toks)] = 1
        return out, mask

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        start, end, doc, block_id = (int(v) for v in self.mapping[idx])
        sentences = [np.asarray(self.block[i], dtype=np.int32)
                     for i in range(start, end)]
        q_idx = int(self.rng.integers(0, len(sentences)))
        if self.rng.random() < self.query_in_block_prob or \
                len(sentences) == 1:
            query = sentences[q_idx].copy()
        else:
            query = sentences.pop(q_idx)
        title = np.asarray(self.titles[doc], dtype=np.int32)
        cls_ = np.array([self.ids.cls], dtype=np.int32)
        sep = np.array([self.ids.sep], dtype=np.int32)
        block_body = (np.concatenate(sentences)
                      [:self.seq_length - 3 - len(title)])
        q_tokens, q_mask = self._pad([cls_, query[:self.seq_length - 2],
                                      sep])
        c_tokens, c_mask = self._pad([cls_, title, sep, block_body, sep])
        return {
            "query_tokens": q_tokens, "query_pad_mask": q_mask,
            "context_tokens": c_tokens, "context_pad_mask": c_mask,
            "block_data": np.array([start, end, doc, block_id],
                                   dtype=np.int64),
        }


def ict_batches(dataset: ICTDataset, batch_size: int,
                start_idx: int = 0) -> Iterator[Dict[str, np.ndarray]]:
    """Cyclic batch iterator (block_data excluded — train fields only)."""
    i = start_idx
    n = len(dataset)
    if n == 0:
        raise ValueError("ICT dataset is empty (corpus too small for "
                         "the block size)")
    while True:
        rows = [dataset[(i + j) % n] for j in range(batch_size)]
        i = (i + batch_size) % n
        yield {k: np.stack([r[k] for r in rows])
               for k in ("query_tokens", "query_pad_mask",
                         "context_tokens", "context_pad_mask")}


def mock_ict_batch(seed: int, batch_size: int, seq_length: int,
                   vocab_size: int) -> Dict[str, np.ndarray]:
    """Synthetic ICT batch: each context is a bag of tokens, the query is
    a subset of them — learnable by lexical overlap."""
    r = np.random.default_rng(seed)
    ctx = r.integers(5, vocab_size, size=(batch_size, seq_length),
                     dtype=np.int64).astype(np.int32)
    q = np.full((batch_size, seq_length), 0, dtype=np.int32)
    q_len = max(4, seq_length // 4)
    for b in range(batch_size):
        sel = r.choice(seq_length, size=q_len, replace=False)
        q[b, :q_len] = ctx[b, np.sort(sel)]
    ones = np.ones((batch_size, seq_length), dtype=np.int32)
    q_mask = np.zeros_like(ones)
    q_mask[:, :q_len] = 1
    return {"query_tokens": q, "query_pad_mask": q_mask,
            "context_tokens": ctx, "context_pad_mask": ones}
