// Dataset index-building kernels (C++), ctypes ABI.
//
// TPU-native replacement for /root/reference/megatron/core/datasets/
// helpers.cpp (846 LoC, pybind11): same algorithms (sample-index and
// blending-index construction are backend-agnostic), fresh implementation
// with a plain C ABI so Python binds via ctypes (no pybind11 in this image).
//
// Build: g++ -O3 -shared -fPIC -o libdata_helpers.so helpers.cpp

#include <cstdint>
#include <cstring>

extern "C" {

// Build the GPT sample index: for `num_samples` samples of `seq_length`+1
// tokens drawn from the document stream (documents concatenated in doc_idx
// order), record for each sample the (document-stream position, offset
// within that document) where it starts. Mirrors the semantics of the
// reference build_sample_idx (helpers.cpp:838-845 export).
//
// sizes:        token count per sequence in the underlying dataset
// doc_idx:      epoch-expanded, shuffled document order (len = num_docs_total)
// sample_idx:   out, shape [num_samples + 1, 2] int64 (doc_pos, offset)
// Returns 0 on success, -1 if the document stream is exhausted early.
int64_t build_sample_idx(const int32_t* sizes,
                         const int64_t* doc_idx,
                         int64_t doc_idx_len,
                         int64_t seq_length,
                         int64_t num_samples,
                         int64_t* sample_idx /* [(n+1)*2] */) {
    int64_t doc_pos = 0;     // position in doc_idx
    int64_t doc_offset = 0;  // token offset within current document
    sample_idx[0] = doc_pos;
    sample_idx[1] = doc_offset;
    for (int64_t i = 1; i <= num_samples; ++i) {
        int64_t remaining = seq_length;  // +1 handled by overlap convention:
        // each sample takes seq_length tokens and the next sample starts
        // seq_length later (the trailing label token overlaps the next
        // sample's first token, reference GPTDataset convention).
        while (remaining > 0) {
            if (doc_pos >= doc_idx_len) return -1;
            int64_t doc_len = sizes[doc_idx[doc_pos]] - doc_offset;
            if (doc_len > remaining) {
                doc_offset += remaining;
                remaining = 0;
            } else {
                remaining -= doc_len;
                doc_offset = 0;
                ++doc_pos;
            }
        }
        sample_idx[i * 2] = doc_pos;
        sample_idx[i * 2 + 1] = doc_offset;
    }
    return 0;
}

// Weighted blending: distribute `size` samples over `num_datasets` datasets
// proportionally to weights, tracking the running deficit (reference
// build_blending_indices): at each step pick the dataset with the largest
// (weight * i - consumed) error.
void build_blending_indices(int16_t* dataset_index,  // out [size]
                            int64_t* dataset_sample_index,  // out [size]
                            const double* weights,
                            int32_t num_datasets,
                            int64_t size) {
    int64_t* consumed = new int64_t[num_datasets];
    std::memset(consumed, 0, sizeof(int64_t) * num_datasets);
    for (int64_t i = 0; i < size; ++i) {
        double sample_count = static_cast<double>(i + 1);
        int32_t best = 0;
        double best_err = weights[0] * sample_count -
                          static_cast<double>(consumed[0]);
        for (int32_t d = 1; d < num_datasets; ++d) {
            double err = weights[d] * sample_count -
                         static_cast<double>(consumed[d]);
            if (err > best_err) {
                best_err = err;
                best = d;
            }
        }
        dataset_index[i] = static_cast<int16_t>(best);
        dataset_sample_index[i] = consumed[best];
        ++consumed[best];
    }
    delete[] consumed;
}

// --------------------------------------------------------------------------
// Sentence-span sample mapping (BERT/T5 masked datasets).
//
// Semantics of the reference build_mapping (helpers.cpp:266-561): walk
// documents (runs of sentence-level sequences), accumulate sentences until
// a target length (occasionally shortened with probability short_seq_prob)
// is reached, emit (first_sentence, end_sentence, target_len) triples,
// Fisher-Yates shuffle the map. Deterministic across the C++ and numpy
// implementations via a shared splitmix64 RNG (not the reference's
// std::mt19937 — bitwise parity with libstdc++ is not a goal; parity
// between OUR two implementations is).

static inline uint64_t splitmix64(uint64_t* state) {
    uint64_t z = (*state += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
}

static const int32_t kLongSentenceLen = 512;

// Pass 1 (out == NULL): return the number of samples.
// Pass 2 (out != NULL, capacity = value from pass 1): fill [N,3] int64
// triples and shuffle. Returns the sample count, or -1 on bad args.
int64_t build_mapping(const int64_t* docs,       // [n_docs + 1]
                      int64_t n_docs,
                      const int32_t* sizes,      // per-sentence token counts
                      int32_t num_epochs,
                      int64_t max_num_samples,
                      int32_t max_seq_length,
                      double short_seq_prob,
                      uint64_t seed,
                      int32_t min_num_sent,
                      int64_t* out,              // NULL or [capacity * 3]
                      int64_t capacity) {
    if (num_epochs <= 0 || max_seq_length <= 1) return -1;
    uint64_t rng = seed;
    int64_t count = 0;
    for (int32_t epoch = 0; epoch < num_epochs; ++epoch) {
        if (max_num_samples > 0 && count >= max_num_samples) break;
        for (int64_t doc = 0; doc < n_docs; ++doc) {
            int64_t first = docs[doc];
            int64_t last = docs[doc + 1];
            if (last - first < min_num_sent) continue;
            bool has_long = false;
            for (int64_t s = first; s < last; ++s) {
                if (sizes[s] > kLongSentenceLen) { has_long = true; break; }
            }
            if (has_long) continue;

            int64_t start = first;
            int64_t seq_len = 0;
            int64_t num_sent = 0;
            // Target-length draw: consumes one RNG value per draw in both
            // implementations (keep in lock-step with helpers.py).
            uint64_t r = splitmix64(&rng);
            int64_t tgt = max_seq_length;
            if (short_seq_prob > 0.0 &&
                (double)(r >> 11) * (1.0 / 9007199254740992.0) <
                    short_seq_prob) {
                tgt = 2 + (int64_t)(splitmix64(&rng) %
                                    (uint64_t)(max_seq_length - 1));
            }
            for (int64_t s = first; s < last; ++s) {
                seq_len += sizes[s];
                ++num_sent;
                int64_t remain = last - s - 1;
                if ((seq_len >= tgt && remain > 1 &&
                     num_sent >= min_num_sent) || remain == 0) {
                    // Writes past `capacity` are dropped (the final epoch
                    // overshoots max_num_samples; pass 1's return is
                    // already clamped) — but the RNG stream still advances
                    // so both passes stay in lock-step.
                    if (out != NULL && count < capacity) {
                        out[count * 3] = start;
                        out[count * 3 + 1] = s + 1;
                        out[count * 3 + 2] = tgt;
                    }
                    ++count;
                    start = s + 1;
                    seq_len = 0;
                    num_sent = 0;
                    r = splitmix64(&rng);
                    tgt = max_seq_length;
                    if (short_seq_prob > 0.0 &&
                        (double)(r >> 11) * (1.0 / 9007199254740992.0) <
                            short_seq_prob) {
                        tgt = 2 + (int64_t)(splitmix64(&rng) %
                                            (uint64_t)(max_seq_length - 1));
                    }
                }
            }
        }
    }
    if (max_num_samples > 0 && count > max_num_samples)
        count = max_num_samples;
    if (out != NULL) {
        if (count > capacity) count = capacity;
        // Fisher-Yates with the shared RNG (seed + 1 stream).
        uint64_t srng = seed + 1;
        for (int64_t i = count - 1; i > 0; --i) {
            int64_t j = (int64_t)(splitmix64(&srng) % (uint64_t)(i + 1));
            for (int k = 0; k < 3; ++k) {
                int64_t t = out[i * 3 + k];
                out[i * 3 + k] = out[j * 3 + k];
                out[j * 3 + k] = t;
            }
        }
    }
    return count;
}

// Exhaustive blending: draw EXACTLY sizes[d] samples from dataset d (the
// reference build_exhaustive_blending_indices, helpers.cpp:21-74 semantics):
// weights derive from sizes, the largest-deficit dataset wins each step,
// and a dataset leaves the candidate set once exhausted. Total output
// length = sum(sizes). Deterministic, no RNG.
void build_exhaustive_blending_indices(
        int16_t* dataset_index,        // out [sum(sizes)]
        int64_t* dataset_sample_index, // out [sum(sizes)]
        const int64_t* sizes,
        int32_t num_datasets) {
    int64_t total = 0;
    for (int32_t d = 0; d < num_datasets; ++d) total += sizes[d];
    int64_t* consumed = new int64_t[num_datasets];
    bool* spent = new bool[num_datasets];
    double* weights = new double[num_datasets];
    for (int32_t d = 0; d < num_datasets; ++d) {
        consumed[d] = 0;
        spent[d] = (sizes[d] == 0);
        weights[d] = total > 0
            ? static_cast<double>(sizes[d]) / static_cast<double>(total)
            : 0.0;
    }
    for (int64_t i = 0; i < total; ++i) {
        double step = i > 0 ? static_cast<double>(i) : 1.0;
        int32_t best = -1;
        double best_err = 0.0;
        for (int32_t d = 0; d < num_datasets; ++d) {
            if (spent[d]) continue;
            double err = weights[d] * step -
                         static_cast<double>(consumed[d]);
            if (best < 0 || err > best_err) {
                best_err = err;
                best = d;
            }
        }
        dataset_index[i] = static_cast<int16_t>(best);
        dataset_sample_index[i] = consumed[best];
        if (++consumed[best] >= sizes[best]) spent[best] = true;
    }
    delete[] weights;
    delete[] spent;
    delete[] consumed;
}

// Block sample mapping for ICT/REALM-style retrieval pretraining
// (reference build_blocks_mapping_impl, helpers.cpp:564-804 semantics):
// walk each document's sentences, close a block when the accumulated
// length reaches max_seq_length - title_len(doc) (leaving at least
// min_num_sent sentences for the next block), and record
// (first_sentence, end_sentence, doc, block_id) quadruples; block_id is
// unique within an epoch. Fisher-Yates shuffle at the end.
//
// Two-pass contract like build_mapping above: out == NULL returns the
// count; second call fills [capacity, 4] int64.
int64_t build_blocks_mapping(const int64_t* docs,      // [n_docs + 1]
                             int64_t n_docs,
                             const int32_t* sizes,     // per-sentence tokens
                             const int32_t* title_sizes,  // [n_docs]
                             int32_t num_epochs,
                             int64_t max_num_samples,
                             int32_t max_seq_length,
                             uint64_t seed,
                             int32_t min_num_sent,  // 1 = one-sent blocks
                             int64_t* out,          // NULL or [capacity*4]
                             int64_t capacity) {
    if (num_epochs <= 0 || max_seq_length <= 1 || min_num_sent < 1)
        return -1;
    int64_t count = 0;
    for (int32_t epoch = 0; epoch < num_epochs; ++epoch) {
        if (max_num_samples > 0 && count >= max_num_samples) break;
        int64_t block_id = 0;
        for (int64_t doc = 0; doc < n_docs; ++doc) {
            int64_t first = docs[doc];
            int64_t last = docs[doc + 1];
            int64_t remain = last - first;
            if (remain < min_num_sent) continue;
            bool has_long = false;
            for (int64_t s = first; s < last; ++s) {
                if (sizes[s] > kLongSentenceLen) { has_long = true; break; }
            }
            if (has_long) continue;
            int64_t tgt = max_seq_length - title_sizes[doc];
            int64_t start = first;
            int64_t seq_len = 0;
            int64_t num_sent = 0;
            for (int64_t s = first; s < last; ++s) {
                seq_len += sizes[s];
                ++num_sent;
                --remain;
                if ((seq_len >= tgt && remain >= min_num_sent &&
                     num_sent >= min_num_sent) || remain == 0) {
                    if (out != NULL && count < capacity) {
                        out[count * 4] = start;
                        out[count * 4 + 1] = s + 1;
                        out[count * 4 + 2] = doc;
                        out[count * 4 + 3] = block_id;
                    }
                    ++count;
                    ++block_id;
                    start = s + 1;
                    seq_len = 0;
                    num_sent = 0;
                }
            }
        }
    }
    if (max_num_samples > 0 && count > max_num_samples)
        count = max_num_samples;
    if (out != NULL) {
        if (count > capacity) count = capacity;
        uint64_t srng = seed + 1;
        for (int64_t i = count - 1; i > 0; --i) {
            int64_t j = (int64_t)(splitmix64(&srng) % (uint64_t)(i + 1));
            for (int k = 0; k < 4; ++k) {
                int64_t t = out[i * 4 + k];
                out[i * 4 + k] = out[j * 4 + k];
                out[j * 4 + k] = t;
            }
        }
    }
    return count;
}

}  // extern "C"
