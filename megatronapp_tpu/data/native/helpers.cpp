// Dataset index-building kernels (C++), ctypes ABI.
//
// TPU-native replacement for /root/reference/megatron/core/datasets/
// helpers.cpp (846 LoC, pybind11): same algorithms (sample-index and
// blending-index construction are backend-agnostic), fresh implementation
// with a plain C ABI so Python binds via ctypes (no pybind11 in this image).
//
// Build: g++ -O3 -shared -fPIC -o libdata_helpers.so helpers.cpp

#include <cstdint>
#include <cstring>

extern "C" {

// Build the GPT sample index: for `num_samples` samples of `seq_length`+1
// tokens drawn from the document stream (documents concatenated in doc_idx
// order), record for each sample the (document-stream position, offset
// within that document) where it starts. Mirrors the semantics of the
// reference build_sample_idx (helpers.cpp:838-845 export).
//
// sizes:        token count per sequence in the underlying dataset
// doc_idx:      epoch-expanded, shuffled document order (len = num_docs_total)
// sample_idx:   out, shape [num_samples + 1, 2] int64 (doc_pos, offset)
// Returns 0 on success, -1 if the document stream is exhausted early.
int64_t build_sample_idx(const int32_t* sizes,
                         const int64_t* doc_idx,
                         int64_t doc_idx_len,
                         int64_t seq_length,
                         int64_t num_samples,
                         int64_t* sample_idx /* [(n+1)*2] */) {
    int64_t doc_pos = 0;     // position in doc_idx
    int64_t doc_offset = 0;  // token offset within current document
    sample_idx[0] = doc_pos;
    sample_idx[1] = doc_offset;
    for (int64_t i = 1; i <= num_samples; ++i) {
        int64_t remaining = seq_length;  // +1 handled by overlap convention:
        // each sample takes seq_length tokens and the next sample starts
        // seq_length later (the trailing label token overlaps the next
        // sample's first token, reference GPTDataset convention).
        while (remaining > 0) {
            if (doc_pos >= doc_idx_len) return -1;
            int64_t doc_len = sizes[doc_idx[doc_pos]] - doc_offset;
            if (doc_len > remaining) {
                doc_offset += remaining;
                remaining = 0;
            } else {
                remaining -= doc_len;
                doc_offset = 0;
                ++doc_pos;
            }
        }
        sample_idx[i * 2] = doc_pos;
        sample_idx[i * 2 + 1] = doc_offset;
    }
    return 0;
}

// Weighted blending: distribute `size` samples over `num_datasets` datasets
// proportionally to weights, tracking the running deficit (reference
// build_blending_indices): at each step pick the dataset with the largest
// (weight * i - consumed) error.
void build_blending_indices(int16_t* dataset_index,  // out [size]
                            int64_t* dataset_sample_index,  // out [size]
                            const double* weights,
                            int32_t num_datasets,
                            int64_t size) {
    int64_t* consumed = new int64_t[num_datasets];
    std::memset(consumed, 0, sizeof(int64_t) * num_datasets);
    for (int64_t i = 0; i < size; ++i) {
        double sample_count = static_cast<double>(i + 1);
        int32_t best = 0;
        double best_err = weights[0] * sample_count -
                          static_cast<double>(consumed[0]);
        for (int32_t d = 1; d < num_datasets; ++d) {
            double err = weights[d] * sample_count -
                         static_cast<double>(consumed[d]);
            if (err > best_err) {
                best_err = err;
                best = d;
            }
        }
        dataset_index[i] = static_cast<int16_t>(best);
        dataset_sample_index[i] = consumed[best];
        ++consumed[best];
    }
    delete[] consumed;
}

}  // extern "C"
