"""GPT training dataset: epoch-aware shuffled sampling over IndexedDataset.

Parity with /root/reference/megatron/core/datasets/gpt_dataset.py:66
(GPTDataset): documents are concatenated in a shuffled order, cut into
seq_length-token samples (label = next token, overlapping by one), with a
second-level shuffle over samples; all three indices (doc/sample/shuffle)
are deterministic in the seed and cached in memory.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np

from megatronapp_tpu.data.helpers import build_sample_idx
from megatronapp_tpu.data.indexed_dataset import IndexedDataset


class GPTDataset:
    def __init__(self, indexed: IndexedDataset, seq_length: int,
                 num_samples: int, seed: int = 1234,
                 documents: Optional[np.ndarray] = None,
                 shuffle: bool = True):
        """documents: subset of document ids to draw from (split support —
        reference passes per-split document ranges)."""
        self.indexed = indexed
        self.seq_length = seq_length
        self.num_samples = num_samples
        self.seed = seed

        if documents is None:
            documents = np.arange(len(indexed.document_indices) - 1,
                                  dtype=np.int64)
        # Sequences belonging to the chosen documents.
        seq_ids = np.concatenate([
            np.arange(indexed.document_indices[d],
                      indexed.document_indices[d + 1], dtype=np.int64)
            for d in documents]) if len(documents) else np.zeros(
                0, dtype=np.int64)
        sizes = indexed.sequence_lengths

        tokens_per_epoch = int(sizes[seq_ids].sum())
        if tokens_per_epoch == 0:
            raise ValueError("empty document selection")
        # The sample stream needs num_samples*seq_length + 1 tokens (each
        # sample spans seq_length+1 tokens, overlapping the next by one) —
        # reference _get_num_epochs semantics.
        tokens_needed = num_samples * seq_length + 1
        num_epochs = int(np.ceil(tokens_needed / tokens_per_epoch))

        rng = np.random.default_rng(seed)
        # Epoch-expanded shuffled document-stream (reference
        # _build_document_index: each epoch is an independent shuffle).
        chunks = []
        for _ in range(num_epochs):
            order = seq_ids.copy()
            if shuffle:
                rng.shuffle(order)
            chunks.append(order)
        self.doc_idx = np.concatenate(chunks)

        self.sample_idx = build_sample_idx(
            sizes, self.doc_idx, seq_length, num_samples)

        self.shuffle_idx = np.arange(num_samples, dtype=np.int64)
        if shuffle:
            rng.shuffle(self.shuffle_idx)

    def __len__(self) -> int:
        return self.num_samples

    def __getitem__(self, idx: int) -> np.ndarray:
        """seq_length+1 tokens (inputs + final label token)."""
        idx = self.shuffle_idx[idx % self.num_samples]
        doc_pos0, offset0 = self.sample_idx[idx]
        parts = []
        need = self.seq_length + 1
        pos, off = int(doc_pos0), int(offset0)
        while need > 0 and pos < len(self.doc_idx):
            seq_id = int(self.doc_idx[pos])
            chunk = self.indexed.get(seq_id, offset=off, length=need)
            parts.append(chunk)
            need -= len(chunk)
            pos += 1
            off = 0
        out = np.concatenate(parts).astype(np.int64)
        # The epoch provisioning above guarantees full coverage; a short
        # read here would be a bug, not a tail condition.
        assert len(out) == self.seq_length + 1, (len(out), self.seq_length)
        return out


def gpt_batches(dataset, batch_size: int, start_idx: int = 0
                ) -> Iterator[Dict[str, np.ndarray]]:
    """Batch iterator with the pretrain_gpt.py get_batch field contract
    (tokens/labels/loss_mask/position_ids)."""
    idx = start_idx
    seq_length = dataset.seq_length
    while True:
        samples = np.stack([dataset[(idx + i) % len(dataset)]
                            for i in range(batch_size)])
        idx += batch_size
        tokens = samples[:, :-1].astype(np.int32)
        labels = samples[:, 1:].astype(np.int32)
        yield {
            "tokens": tokens,
            "labels": labels,
            "loss_mask": np.ones_like(tokens, dtype=np.float32),
            "position_ids": np.tile(
                np.arange(seq_length, dtype=np.int32),
                (batch_size, 1)),
        }
