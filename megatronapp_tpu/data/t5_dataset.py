"""T5 pretraining dataset: span corruption with sentinel tokens.

Parity with /root/reference/megatron/core/datasets/t5_dataset.py
(T5MaskedWordPieceDataset.__getitem__: sentence-span sample → n-gram span
masking where each span collapses to one sentinel in the encoder stream and
expands to sentinel+original tokens in the decoder stream; [BOS] decoder
shift; padding) — fresh implementation over our sentence-split
IndexedDataset.

Batch fields match models/t5.py t5_loss (reference pretrain_t5.py names):
  text_enc, text_dec, labels, loss_mask, enc_mask, dec_mask.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List

import numpy as np

from megatronapp_tpu.data.indexed_dataset import IndexedDataset
from megatronapp_tpu.data.masked_dataset import (
    MaskingConfig, build_sentence_sample_mapping,
    create_masked_lm_predictions, masked_batches,
)


@dataclasses.dataclass
class T5TokenIds:
    """Special ids (reference reads bos/eos/pad/sentinel ids off the
    tokenizer; sentinels are the trailing vocab ids in T5 convention)."""
    bos: int
    eos: int
    pad: int
    sentinels: List[int]            # e.g. <extra_id_0..99>


class T5Dataset:
    """Span-corruption encoder/decoder samples from a sentence-split
    .bin/.idx corpus."""

    def __init__(self, indexed: IndexedDataset, *, enc_seq_length: int,
                 dec_seq_length: int, vocab_size: int, token_ids: T5TokenIds,
                 num_samples: int, seed: int = 1234,
                 masked_lm_prob: float = 0.15, short_seq_prob: float = 0.1,
                 max_ngram: int = 3, num_epochs: int = 100):
        self.ds = indexed
        self.enc_len = enc_seq_length
        self.dec_len = dec_seq_length
        self.vocab_size = vocab_size
        self.ids = token_ids
        self.seed = seed
        self.masking = MaskingConfig(masked_lm_prob=masked_lm_prob,
                                     max_ngram=max_ngram,
                                     # Spans always become sentinels —
                                     # no random/keep replacement in T5.
                                     mask_token_prob=1.0,
                                     random_token_prob=0.0)
        self.sample_index = build_sentence_sample_mapping(
            indexed.document_indices, indexed.sequence_lengths,
            num_epochs=num_epochs, max_num_samples=num_samples,
            # Head-room for sentinel insertion + [EOS].
            max_seq_length=enc_seq_length - 1,
            short_seq_prob=short_seq_prob, seed=seed, min_num_sent=1)
        if len(self.sample_index) == 0:
            raise ValueError(
                "no T5 samples could be built — is the corpus "
                "sentence-split (tools/preprocess_data.py "
                "--split-sentences)?")

    def __len__(self) -> int:
        return len(self.sample_index)

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        first, end, target_len = self.sample_index[idx % len(self)]
        rng = np.random.RandomState((self.seed + idx) % 2**32)
        tokens = [int(t) for i in range(first, end)
                  for t in self.ds[i]][:target_len]

        # Select span positions with the shared n-gram masker; a dedicated
        # mask id marks selected positions, then contiguous runs collapse
        # into sentinels.
        marker = -1
        masked, positions, labels_at = create_masked_lm_predictions(
            tokens, self.vocab_size, marker, special_ids=(), rng=rng,
            cfg=self.masking)
        selected = set(int(p) for p in positions)
        orig = np.asarray(tokens, np.int64)

        enc: List[int] = []
        dec: List[int] = [self.ids.bos]
        tgt: List[int] = []
        sentinel_i = 0
        i = 0
        n = len(tokens)
        while i < n:
            if i in selected:
                sent = self.ids.sentinels[
                    min(sentinel_i, len(self.ids.sentinels) - 1)]
                sentinel_i += 1
                enc.append(sent)
                dec.append(sent)
                tgt.append(sent)
                while i < n and i in selected:
                    dec.append(int(orig[i]))
                    tgt.append(int(orig[i]))
                    i += 1
            else:
                enc.append(int(orig[i]))
                i += 1
        tgt.append(self.ids.eos)
        # The encoder stream terminates with EOS too (reference t5_dataset
        # appends eos to the corrupted input) — the -1 head-room in the
        # sample mapping reserves its slot.
        enc.append(self.ids.eos)

        enc = enc[: self.enc_len]
        dec = dec[: self.dec_len]
        tgt = tgt[: self.dec_len]

        def pad_to(x, length, value):
            out = np.full((length,), value, np.int32)
            out[: len(x)] = x
            return out

        enc_mask = np.zeros((self.enc_len,), np.float32)
        enc_mask[: len(enc)] = 1.0
        dec_mask = np.zeros((self.dec_len,), np.float32)
        dec_mask[: len(dec)] = 1.0
        loss_mask = np.zeros((self.dec_len,), np.float32)
        loss_mask[: len(tgt)] = 1.0
        return {
            "text_enc": pad_to(enc, self.enc_len, self.ids.pad),
            "text_dec": pad_to(dec, self.dec_len, self.ids.pad),
            "labels": pad_to(tgt, self.dec_len, self.ids.pad),
            "loss_mask": loss_mask,
            "enc_mask": enc_mask,
            "dec_mask": dec_mask,
        }


t5_batches = masked_batches
