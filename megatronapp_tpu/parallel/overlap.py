"""Latency-hiding tensor-parallel matmuls (ring overlap).

The reference hides tensor-parallel collective latency behind the dependent
GEMMs (`--tp-comm-overlap`, delegating to TE's bulk/ring overlap; T3
arXiv:2401.16677 makes the general case for fine-grained compute-collective
fusion). Our GSPMD path instead lets XLA insert one blocking collective per
column->row projection pair, which serializes a full all-gather /
reduce-scatter (or all-reduce) against the matmuls it feeds.

This module implements the manual alternative behind
``TransformerConfig.tp_comm_overlap``:

``all_gather_matmul(x, w, mesh)``
    Column-parallel ``x @ w`` with ``w`` sharded on its OUTPUT dim over tp.
    Inside a shard_map manual over tp only, the sequence dim of ``x`` is
    ring-all-gathered via ``lax.ppermute`` in tp chunks; every received
    chunk is immediately multiplied into its rows of the accumulator, so
    each permute hop rides under the previous chunk's GEMM.

``matmul_reduce_scatter(y, w, mesh)``
    Row-parallel ``y @ w`` with ``w`` sharded on its INPUT dim over tp.
    The partial products are ring-reduce-scattered along the sequence dim:
    each step permutes the running partial sum while the next sequence
    chunk's local GEMM is computed.

Both carry a ``jax.custom_vjp`` whose backward overlaps symmetrically and
FUSED: one ring pass per primitive serves the dgrad (all-gather /
reduce-scatter of cotangents) and the wgrad accumulation together.

Design notes:
- The chunk count is the ring length and is auto-derived from the tp mesh
  degree (tp chunks of S/tp sequence rows each); sequence lengths not
  divisible by tp are zero-padded outside the custom_vjp boundary.
- Output layouts match the GSPMD path exactly: ``all_gather_matmul``
  returns [B, S, N] sharded over tp on the last dim, so downstream
  bias/activation/split code is unchanged; ``matmul_reduce_scatter``
  returns the full [B, S, H] (sequence manually sharded over tp — the
  consumer's residual add re-gathers it, total comm volume identical to
  the all-reduce GSPMD emits).
- The shard_map is FULLY manual (every mesh axis): on the jax 0.4.x
  builds this image ships, partial-auto regions lower ppermute/axis_index
  through an SPMD path that XLA:CPU aborts on (spmd_partitioner
  IsManualSubgroup check / unsupported PartitionId) — the batch dim is
  therefore threaded explicitly over (dp, ep) and pp/cp ride along
  replicated (eligibility requires cp == 1 and a non-manual context).
- MegaScan: when tracing is enabled at trace time, per-chunk
  ``tp-overlap-compute`` / ``tp-overlap-permute`` spans are emitted (one
  timeline per tp rank, tid = rank + 1) so the overlap is visible in the
  merged trace.
- This module and ``parallel/collectives.py`` are the approved homes for
  raw manual collectives — ``tools/check_vma.py`` enforces that new
  shard_map code routes through them.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from megatronapp_tpu.config.parallel_config import DP_AXIS, EP_AXIS, TP_AXIS
from megatronapp_tpu.parallel.collectives import (
    ring_span, shard_map_compat as _shard_map, zeros_like_vma,
)

# MegaScan span names (trace/tracer.py GRANULARITY_EVENTS 'collective').
OVERLAP_COMPUTE_EVENT = "tp-overlap-compute"
OVERLAP_PERMUTE_EVENT = "tp-overlap-permute"
# The generic ring (ring_all_gather) serves the ZeRO-1 dp param return —
# its spans must not book into the tp-overlap category (one permute
# event name per axis domain, like cp-overlap-*/pp-overlap-*).
DP_OVERLAP_PERMUTE_EVENT = "dp-overlap-permute"

# Activation batch dims shard over (dp, ep) — mesh.py batch_spec.
_BATCH = (DP_AXIS, EP_AXIS)


def _ring_perm(tp: int):
    """Ring permutation: rank r sends to r-1, i.e. after one hop rank r
    holds what r+1 held — at step s every rank holds chunk (r + s) % tp."""
    return [(r, (r - 1) % tp) for r in range(tp)]


def _mark(name: str, ph: str, dep, *, op: str, step: int):
    """Per-chunk MegaScan record (collectives.ring_span over tp)."""
    ring_span(name, ph, dep, TP_AXIS, op=op, step=step)


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


# ---------------------------------------------------------------------------
# all_gather_matmul: ring AG of x's sequence chunks, overlapped with the
# column-parallel GEMM.
# ---------------------------------------------------------------------------

def _ag_mm_body(tp, op_name, xl, wls):
    """xl [b, S/tp, H] (this rank's batch rows + seq chunk), wls: tuple of
    [H, N_j/tp] column-parallel weights sharing ONE ring all-gather of x
    (the fused-QKV case: gathering x once instead of once per projection
    halves the permute traffic). Returns a tuple of y_j [b, S, N_j/tp]."""
    me = lax.axis_index(TP_AXIS)
    b, sc, _ = xl.shape
    ys = [zeros_like_vma((b, sc * tp, wl.shape[1]),
                         jnp.result_type(xl.dtype, wl.dtype), xl)
          for wl in wls]
    perm = _ring_perm(tp)
    chunk = xl
    for step in range(tp):
        nxt = None
        if step + 1 < tp:
            # Issue the permute BEFORE the dependent GEMM so the hop rides
            # under it (TPU async collectives; XLA:CPU runs it serially).
            _mark(OVERLAP_PERMUTE_EVENT, "B", chunk, op=op_name, step=step)
            nxt = lax.ppermute(chunk, TP_AXIS, perm)
        owner = (me + step) % tp  # global chunk index currently held
        _mark(OVERLAP_COMPUTE_EVENT, "B", chunk, op=op_name, step=step)
        last = None
        for j, wl in enumerate(wls):
            piece = chunk @ wl
            ys[j] = lax.dynamic_update_slice_in_dim(ys[j], piece,
                                                    owner * sc, axis=1)
            last = piece
        _mark(OVERLAP_COMPUTE_EVENT, "E", last, op=op_name, step=step)
        if nxt is not None:
            _mark(OVERLAP_PERMUTE_EVENT, "E", nxt, op=op_name, step=step)
            chunk = nxt
    return tuple(ys)


def _ag_mm_bwd_body(tp, xl, wls, dyls, reduce_batch=True):
    """Fused backward ring for all_gather_matmul.

    xl [b, S/tp, H], wls: tuple of [H, N_j/tp], dyls: matching cotangents
    [b, S, N_j/tp]. One ring pass of x chunks accumulates EVERY weight's
    wgrad; the dgrad is the symmetric matmul-reduce-scatter of the summed
    dy_j @ w_j^T. Returns (dx_local [b, S/tp, H], tuple of dw_j).

    reduce_batch=False (the ambient-manual pipeline path): skip the
    (dp, ep) wgrad psum — there the weights are replicated INPUTS of the
    enclosing shard_map, whose transpose already psums their cotangents
    over every unmentioned axis; an explicit psum here would double-count
    (collectives.shard_map_compat autodiff note)."""
    me = lax.axis_index(TP_AXIS)
    b, sc, h = xl.shape
    perm = _ring_perm(tp)
    op = "all-gather-matmul-bwd"

    # wgrad: dw_j = sum over seq chunks  x_c^T @ dy_j_c  (ring AG of x
    # chunks; fp32 accumulators — chunked serial adds would otherwise
    # round in bf16 where one big GEMM accumulates wide).
    dws = [zeros_like_vma((h, wl.shape[1]), jnp.float32, xl) for wl in wls]
    chunk = xl
    for step in range(tp):
        nxt = None
        if step + 1 < tp:
            _mark(OVERLAP_PERMUTE_EVENT, "B", chunk, op=op, step=step)
            nxt = lax.ppermute(chunk, TP_AXIS, perm)
        owner = (me + step) % tp
        _mark(OVERLAP_COMPUTE_EVENT, "B", chunk, op=op, step=step)
        pm = None
        for j, (wl, dyl) in enumerate(zip(wls, dyls)):
            dyc = lax.dynamic_slice_in_dim(dyl, owner * sc, sc, axis=1)
            pm = (chunk.reshape(b * sc, h).T
                  @ dyc.reshape(b * sc, wl.shape[1]))
            dws[j] = dws[j] + pm.astype(jnp.float32)
        _mark(OVERLAP_COMPUTE_EVENT, "E", pm, op=op, step=step)
        if nxt is not None:
            _mark(OVERLAP_PERMUTE_EVENT, "E", nxt, op=op, step=step)
            chunk = nxt

    # dgrad: dx = reduce-scatter over seq of  sum_j dy_j @ w_j^T  (ring
    # RS; the per-chunk partials of every projection sum before the hop).
    dx = _mm_rs_rings(tp, dyls, tuple(wl.T for wl in wls), op_name=op)
    # The batch dim is manually sharded over (dp, ep); the weights are
    # replicated there, so their grads must be reduced across the batch
    # shards — the all-reduce GSPMD would have inserted for us. fp32
    # reduction (bf16 manual all-reduces crash XLA:CPU — README).
    if reduce_batch:
        dws = [lax.psum(dw, (DP_AXIS, EP_AXIS)) for dw in dws]
    return (dx.astype(xl.dtype),
            tuple(dw.astype(wl.dtype) for dw, wl in zip(dws, wls)))


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _ag_mm(mesh, x, ws):
    return _ag_mm_fwd(mesh, x, ws)[0]


def _ag_mm_fwd(mesh, x, ws):
    tp = mesh.shape[TP_AXIS]
    n = len(ws)
    ys = _shard_map(
        functools.partial(_ag_mm_body, tp, "all-gather-matmul"), mesh,
        in_specs=(P(_BATCH, TP_AXIS, None), (P(None, TP_AXIS),) * n),
        out_specs=(P(_BATCH, None, TP_AXIS),) * n)(x, ws)
    return ys, (x, ws)


def _ag_mm_bwd(mesh, res, dys):
    x, ws = res
    tp = mesh.shape[TP_AXIS]
    n = len(ws)
    dx, dws = _shard_map(
        functools.partial(_ag_mm_bwd_body, tp), mesh,
        in_specs=(P(_BATCH, TP_AXIS, None), (P(None, TP_AXIS),) * n,
                  (P(_BATCH, None, TP_AXIS),) * n),
        out_specs=(P(_BATCH, TP_AXIS, None),
                   (P(None, TP_AXIS),) * n))(x, ws, dys)
    return dx, dws


_ag_mm.defvjp(_ag_mm_fwd, _ag_mm_bwd)


# ---------------------------------------------------------------------------
# matmul_reduce_scatter: row-parallel GEMM whose partial-product reduction
# is a ring reduce-scatter along the sequence dim.
# ---------------------------------------------------------------------------

def _mm_rs_rings(tp, yls, wls, op_name="matmul-reduce-scatter"):
    """yls: tuple of [b, S, N_j/tp]; wls: matching [N_j/tp, H] →
    this rank's reduced seq chunk [b, S/tp, H] of sum_j y_j @ w_j.
    Each step's local chunk GEMMs are issued while the running partial
    sum permutes around the ring."""
    if not isinstance(yls, tuple):
        yls, wls = (yls,), (wls,)
    me = lax.axis_index(TP_AXIS)
    sc = yls[0].shape[1] // tp
    perm = _ring_perm(tp)

    def piece(c, step):
        _mark(OVERLAP_COMPUTE_EVENT, "B", yls[0], op=op_name, step=step)
        out = None
        for yl, wl in zip(yls, wls):
            yc = lax.dynamic_slice_in_dim(yl, c * sc, sc, axis=1)
            out = yc @ wl if out is None else out + yc @ wl
        _mark(OVERLAP_COMPUTE_EVENT, "E", out, op=op_name, step=step)
        return out

    # acc_r after step s = sum_{j=r..r+s} partial_j[chunk (r+s+1) % tp];
    # after tp-1 hops rank r holds the fully reduced chunk r.
    acc = piece((me + 1) % tp, 0)
    for step in range(1, tp):
        _mark(OVERLAP_PERMUTE_EVENT, "B", acc, op=op_name, step=step)
        moving = lax.ppermute(acc, TP_AXIS, perm)
        nxt = piece((me + 1 + step) % tp, step)
        _mark(OVERLAP_PERMUTE_EVENT, "E", moving, op=op_name, step=step)
        acc = moving + nxt
    return acc


def _mm_rs_bwd_body(tp, yl, wl, dol, reduce_batch=True):
    """Fused backward ring for matmul_reduce_scatter.

    yl [b, S, N/tp], wl [N/tp, H], dol [b, S/tp, H] (this rank's cotangent
    seq chunk). ONE ring all-gather of the dout chunks feeds both the dgrad
    (dy = dout @ w^T, written rows-at-a-time) and the wgrad accumulation
    (dw = sum_c y_c^T @ dout_c). Returns (dy [b,S,N/tp], dw [N/tp,H])."""
    me = lax.axis_index(TP_AXIS)
    b, sc, h = dol.shape
    nl = wl.shape[0]
    perm = _ring_perm(tp)
    op = "matmul-reduce-scatter-bwd"

    dy = zeros_like_vma((b, sc * tp, nl),
                        jnp.result_type(dol.dtype, wl.dtype), dol)
    dw = zeros_like_vma((nl, h), jnp.float32, dol)
    chunk = dol
    for step in range(tp):
        nxt = None
        if step + 1 < tp:
            _mark(OVERLAP_PERMUTE_EVENT, "B", chunk, op=op, step=step)
            nxt = lax.ppermute(chunk, TP_AXIS, perm)
        owner = (me + step) % tp
        _mark(OVERLAP_COMPUTE_EVENT, "B", chunk, op=op, step=step)
        dyc = chunk @ wl.T
        yc = lax.dynamic_slice_in_dim(yl, owner * sc, sc, axis=1)
        pm = yc.reshape(b * sc, nl).T @ chunk.reshape(b * sc, h)
        _mark(OVERLAP_COMPUTE_EVENT, "E", dyc, op=op, step=step)
        dy = lax.dynamic_update_slice_in_dim(dy, dyc, owner * sc, axis=1)
        dw = dw + pm.astype(jnp.float32)
        if nxt is not None:
            _mark(OVERLAP_PERMUTE_EVENT, "E", nxt, op=op, step=step)
            chunk = nxt
    # Weight grad: reduce across the manual (dp, ep) batch shards (see
    # _ag_mm_bwd_body) — fp32 before the cast. Skipped on the ambient
    # pipeline path, where the enclosing shard_map's transpose owns it.
    if reduce_batch:
        dw = lax.psum(dw, (DP_AXIS, EP_AXIS))
    return dy.astype(yl.dtype), dw.astype(wl.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _mm_rs(mesh, y, w):
    return _mm_rs_fwd(mesh, y, w)[0]


def _mm_rs_fwd(mesh, y, w):
    tp = mesh.shape[TP_AXIS]
    out = _shard_map(
        functools.partial(_mm_rs_rings, tp), mesh,
        in_specs=(P(_BATCH, None, TP_AXIS), P(TP_AXIS, None)),
        out_specs=P(_BATCH, TP_AXIS, None))(y, w)
    return out, (y, w)


def _mm_rs_bwd(mesh, res, dout):
    y, w = res
    tp = mesh.shape[TP_AXIS]
    dy, dw = _shard_map(
        functools.partial(_mm_rs_bwd_body, tp), mesh,
        in_specs=(P(_BATCH, None, TP_AXIS), P(TP_AXIS, None),
                  P(_BATCH, TP_AXIS, None)),
        out_specs=(P(_BATCH, None, TP_AXIS), P(TP_AXIS, None)))(y, w, dout)
    return dy, dw


_mm_rs.defvjp(_mm_rs_fwd, _mm_rs_bwd)


# ---------------------------------------------------------------------------
# fp8 (e4m3) ring variants with delayed-scaling amax history (ISSUE 13).
#
# MAINTENANCE NOTE: these four bodies are deliberate twins of the bf16
# bodies above (same ring/permute/span structure, plus the fp32 upcast
# + per-projection descale). A structural fix to a bf16 body (permute
# ordering, span placement, accumulator dtype) must be mirrored here —
# unifying them behind an optional (upcast, invs) parameterization is a
# recorded follow-up, deferred because the bf16 bodies are the most
# bitwise-pinned code in the repo.
#
# Same ring structure as the bf16 bodies above, but both GEMM operands
# are quantized to fp8 with per-tensor delayed scales derived from an
# amax HISTORY (training/fp8.py): forward tensors (x, every w_j) are
# observed in the fwd, the cotangents in the bwd, and the updated
# history travels OUT through the custom_vjp cotangent of the ``fp8``
# input — the train step installs it into state["fp8"] directly, never
# through the optimizer. The fp8 chunks are what the ppermute ring
# moves (half the bf16 hop bytes — the deterministic byte-count
# evidence of tools/fp8_benchmark.py); the GEMMs upcast fp8 → fp32 in
# register (e4m3 values are exact in fp32, so this is the fp8-input
# matmul with fp32 accumulation an MXU would run) and apply the
# combined 1/(s_a * s_b) descale on the product.
# ---------------------------------------------------------------------------


def _fp8_quant_global(x, scale):
    """Quantize a GLOBAL (GSPMD-sharded) array outside the shard_map:
    the amax/saturation reductions are global by construction, so no
    in-body pmax is needed. Returns (x_fp8, amax, sat_count)."""
    from megatronapp_tpu.training.fp8 import fp8_quantize
    return fp8_quantize(x, scale)


def _ag_mm_fp8_body(tp, op_name, out_dtype, xl, wls, invs):
    """fp8 twin of _ag_mm_body: xl fp8 [b, S/tp, H] chunks ring around,
    each GEMM upcasts in register and applies its projection's combined
    descale inv_j = 1/(s_x * s_w_j)."""
    me = lax.axis_index(TP_AXIS)
    b, sc, _ = xl.shape
    ys = [zeros_like_vma((b, sc * tp, wl.shape[1]), out_dtype, xl)
          for wl in wls]
    perm = _ring_perm(tp)
    chunk = xl
    for step in range(tp):
        nxt = None
        if step + 1 < tp:
            _mark(OVERLAP_PERMUTE_EVENT, "B", chunk, op=op_name, step=step)
            nxt = lax.ppermute(chunk, TP_AXIS, perm)
        owner = (me + step) % tp
        _mark(OVERLAP_COMPUTE_EVENT, "B", chunk, op=op_name, step=step)
        cf = chunk.astype(jnp.float32)
        last = None
        for j, wl in enumerate(wls):
            piece = ((cf @ wl.astype(jnp.float32))
                     * invs[j]).astype(out_dtype)
            ys[j] = lax.dynamic_update_slice_in_dim(ys[j], piece,
                                                    owner * sc, axis=1)
            last = piece
        _mark(OVERLAP_COMPUTE_EVENT, "E", last, op=op_name, step=step)
        if nxt is not None:
            _mark(OVERLAP_PERMUTE_EVENT, "E", nxt, op=op_name, step=step)
            chunk = nxt
    return tuple(ys)


def _mm_rs_fp8_rings(tp, out_dtype, yls, wls, invs,
                     op_name="matmul-reduce-scatter-fp8"):
    """fp8 twin of _mm_rs_rings: per-chunk partial products descale with
    their own inv_j before the sum; the running partial permutes in
    out_dtype (same hop bytes as the baseline — the fp8 win here is the
    operand side, not the partial-sum side)."""
    me = lax.axis_index(TP_AXIS)
    sc = yls[0].shape[1] // tp
    perm = _ring_perm(tp)

    def piece(c, step):
        _mark(OVERLAP_COMPUTE_EVENT, "B", yls[0], op=op_name, step=step)
        out = None
        for yl, wl, inv in zip(yls, wls, invs):
            yc = lax.dynamic_slice_in_dim(yl, c * sc, sc,
                                          axis=1).astype(jnp.float32)
            t = (yc @ wl.astype(jnp.float32)) * inv
            out = t if out is None else out + t
        out = out.astype(out_dtype)
        _mark(OVERLAP_COMPUTE_EVENT, "E", out, op=op_name, step=step)
        return out

    acc = piece((me + 1) % tp, 0)
    for step in range(1, tp):
        _mark(OVERLAP_PERMUTE_EVENT, "B", acc, op=op_name, step=step)
        moving = lax.ppermute(acc, TP_AXIS, perm)
        nxt = piece((me + 1 + step) % tp, step)
        _mark(OVERLAP_PERMUTE_EVENT, "E", moving, op=op_name, step=step)
        acc = moving + nxt
    return acc


def _ag_mm_fp8_bwd_body(tp, out_dtype, w_dtypes, xl, wls, dyls, inv_dws,
                        inv_dxs):
    """Fused fp8 backward ring for all_gather_matmul: ONE ring pass of
    the fp8 x chunks accumulates every wgrad (descaled per projection by
    inv_dw_j = 1/(s_x s_g_j)); the dgrad is the fp8 reduce-scatter of
    the quantized cotangents against the transposed fp8 weights
    (inv_dx_j = 1/(s_g_j s_w_j)). All operands are fp8; accumulators
    fp32."""
    me = lax.axis_index(TP_AXIS)
    b, sc, h = xl.shape
    perm = _ring_perm(tp)
    op = "all-gather-matmul-fp8-bwd"

    dws = [zeros_like_vma((h, wl.shape[1]), jnp.float32, xl) for wl in wls]
    chunk = xl
    for step in range(tp):
        nxt = None
        if step + 1 < tp:
            _mark(OVERLAP_PERMUTE_EVENT, "B", chunk, op=op, step=step)
            nxt = lax.ppermute(chunk, TP_AXIS, perm)
        owner = (me + step) % tp
        _mark(OVERLAP_COMPUTE_EVENT, "B", chunk, op=op, step=step)
        cf = chunk.astype(jnp.float32)
        pm = None
        for j, (wl, dyl) in enumerate(zip(wls, dyls)):
            dyc = lax.dynamic_slice_in_dim(
                dyl, owner * sc, sc, axis=1).astype(jnp.float32)
            pm = (cf.reshape(b * sc, h).T
                  @ dyc.reshape(b * sc, wl.shape[1])) * inv_dws[j]
            dws[j] = dws[j] + pm
        _mark(OVERLAP_COMPUTE_EVENT, "E", pm, op=op, step=step)
        if nxt is not None:
            _mark(OVERLAP_PERMUTE_EVENT, "E", nxt, op=op, step=step)
            chunk = nxt

    dx = _mm_rs_fp8_rings(tp, out_dtype, dyls,
                          tuple(wl.T for wl in wls), inv_dxs, op_name=op)
    dws = [lax.psum(dw, (DP_AXIS, EP_AXIS)) for dw in dws]
    return (dx,
            tuple(dw.astype(dt) for dw, dt in zip(dws, w_dtypes)))


def _mm_rs_fp8_bwd_body(tp, y_dtype, w_dtype, yl, wl, dol, inv_dy,
                        inv_dw):
    """Fused fp8 backward ring for matmul_reduce_scatter: one ring
    all-gather of the fp8 cotangent chunks feeds dgrad
    (dy = (do @ w^T) / (s_do s_w)) and wgrad
    (dw = sum_c y_c^T @ do_c / (s_y s_do)) together."""
    me = lax.axis_index(TP_AXIS)
    b, sc, h = dol.shape
    nl = wl.shape[0]
    perm = _ring_perm(tp)
    op = "matmul-reduce-scatter-fp8-bwd"

    dy = zeros_like_vma((b, sc * tp, nl), y_dtype, dol)
    dw = zeros_like_vma((nl, h), jnp.float32, dol)
    wt = wl.astype(jnp.float32).T
    chunk = dol
    for step in range(tp):
        nxt = None
        if step + 1 < tp:
            _mark(OVERLAP_PERMUTE_EVENT, "B", chunk, op=op, step=step)
            nxt = lax.ppermute(chunk, TP_AXIS, perm)
        owner = (me + step) % tp
        _mark(OVERLAP_COMPUTE_EVENT, "B", chunk, op=op, step=step)
        cf = chunk.astype(jnp.float32)
        dyc = ((cf @ wt) * inv_dy).astype(y_dtype)
        yc = lax.dynamic_slice_in_dim(
            yl, owner * sc, sc, axis=1).astype(jnp.float32)
        pm = (yc.reshape(b * sc, nl).T @ cf.reshape(b * sc, h)) * inv_dw
        _mark(OVERLAP_COMPUTE_EVENT, "E", dyc, op=op, step=step)
        dy = lax.dynamic_update_slice_in_dim(dy, dyc, owner * sc, axis=1)
        dw = dw + pm
        if nxt is not None:
            _mark(OVERLAP_PERMUTE_EVENT, "E", nxt, op=op, step=step)
            chunk = nxt
    dw = lax.psum(dw, (DP_AXIS, EP_AXIS))
    return dy, dw.astype(w_dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _ag_mm_fp8(mesh, margin, x, ws, fp8):
    return _ag_mm_fp8_fwd(mesh, margin, x, ws, fp8)[0]


def _ag_mm_fp8_fwd(mesh, margin, x, ws, fp8):
    from megatronapp_tpu.training.fp8 import fp8_scale_from_hist
    tp = mesh.shape[TP_AXIS]
    n = len(ws)
    out_dtype = jnp.result_type(x.dtype, *(w.dtype for w in ws))
    hist = fp8["hist"]                       # [1 + 2n, H]
    scales = fp8_scale_from_hist(hist, margin)
    xq, ax, sx_cnt = _fp8_quant_global(x, scales[0])
    wqs, aws, sws = [], [], []
    for j, w in enumerate(ws):
        wq, aw, sw_cnt = _fp8_quant_global(w, scales[1 + j])
        wqs.append(wq)
        aws.append(aw)
        sws.append(sw_cnt)
    wqs = tuple(wqs)
    invs = tuple(1.0 / (scales[0] * scales[1 + j]) for j in range(n))
    ys = _shard_map(
        functools.partial(_ag_mm_fp8_body, tp, "all-gather-matmul-fp8",
                          out_dtype), mesh,
        in_specs=(P(_BATCH, TP_AXIS, None), (P(None, TP_AXIS),) * n,
                  (P(),) * n),
        out_specs=(P(_BATCH, None, TP_AXIS),) * n)(xq, wqs, invs)
    # Dtype witnesses: residual leaves must be jax types, so original
    # dtypes travel as zero-size arrays (xq/wqs are fp8 — the primal
    # dtypes are otherwise lost by quantization).
    wit = (tuple(jnp.zeros((0,), w.dtype) for w in ws),
           jnp.zeros((0,), x.dtype), jnp.zeros((0,), out_dtype))
    res = (xq, wqs, hist, scales, (ax, tuple(aws)),
           (sx_cnt, tuple(sws)), wit)
    return ys, res


def _ag_mm_fp8_bwd(mesh, margin, res, dys):
    from megatronapp_tpu.training.fp8 import rolled_hist
    (xq, wqs, hist, scales, (ax, aws), (sx_cnt, sws), wit) = res
    w_wits, x_wit, out_wit = wit
    w_dtypes = tuple(w.dtype for w in w_wits)
    x_dtype, out_dtype = x_wit.dtype, out_wit.dtype
    tp = mesh.shape[TP_AXIS]
    n = len(wqs)
    dyqs, ags, sgs = [], [], []
    for j, dy in enumerate(dys):
        dq, ag, sg_cnt = _fp8_quant_global(dy, scales[1 + n + j])
        dyqs.append(dq)
        ags.append(ag)
        sgs.append(sg_cnt)
    dyqs = tuple(dyqs)
    inv_dws = tuple(1.0 / (scales[0] * scales[1 + n + j])
                    for j in range(n))
    inv_dxs = tuple(1.0 / (scales[1 + n + j] * scales[1 + j])
                    for j in range(n))
    dx, dws = _shard_map(
        functools.partial(_ag_mm_fp8_bwd_body, tp, out_dtype, w_dtypes),
        mesh,
        in_specs=(P(_BATCH, TP_AXIS, None), (P(None, TP_AXIS),) * n,
                  (P(_BATCH, None, TP_AXIS),) * n,
                  (P(),) * n, (P(),) * n),
        out_specs=(P(_BATCH, TP_AXIS, None),
                   (P(None, TP_AXIS),) * n))(
        xq, wqs, dyqs, inv_dws, inv_dxs)
    amaxes = jnp.stack([ax, *aws, *ags])
    sats = jnp.stack([sx_cnt, *sws, *sgs])
    dfp8 = {"hist": rolled_hist(hist, amaxes), "sat": sats}
    return dx.astype(x_dtype), dws, dfp8


_ag_mm_fp8.defvjp(_ag_mm_fp8_fwd, _ag_mm_fp8_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _mm_rs_fp8(mesh, margin, y, w, fp8):
    return _mm_rs_fp8_fwd(mesh, margin, y, w, fp8)[0]


def _mm_rs_fp8_fwd(mesh, margin, y, w, fp8):
    from megatronapp_tpu.training.fp8 import fp8_scale_from_hist
    tp = mesh.shape[TP_AXIS]
    out_dtype = jnp.result_type(y.dtype, w.dtype)
    hist = fp8["hist"]                       # [3, H]: y, w, dout
    scales = fp8_scale_from_hist(hist, margin)
    yq, ay, sy_cnt = _fp8_quant_global(y, scales[0])
    wq, aw, sw_cnt = _fp8_quant_global(w, scales[1])
    inv = 1.0 / (scales[0] * scales[1])
    out = _shard_map(
        functools.partial(_mm_rs_fp8_rings, tp, out_dtype), mesh,
        in_specs=(P(_BATCH, None, TP_AXIS), P(TP_AXIS, None), P()),
        out_specs=P(_BATCH, TP_AXIS, None))((yq,), (wq,), (inv,))
    wit = (jnp.zeros((0,), y.dtype), jnp.zeros((0,), w.dtype))
    res = (yq, wq, hist, scales, (ay, aw), (sy_cnt, sw_cnt), wit)
    return out, res


def _mm_rs_fp8_bwd(mesh, margin, res, dout):
    from megatronapp_tpu.training.fp8 import rolled_hist
    yq, wq, hist, scales, (ay, aw), (sy_cnt, sw_cnt), wit = res
    y_dtype, w_dtype = wit[0].dtype, wit[1].dtype
    tp = mesh.shape[TP_AXIS]
    doq, ag, sg_cnt = _fp8_quant_global(dout, scales[2])
    inv_dy = 1.0 / (scales[2] * scales[1])
    inv_dw = 1.0 / (scales[0] * scales[2])
    dy, dw = _shard_map(
        functools.partial(_mm_rs_fp8_bwd_body, tp, y_dtype, w_dtype),
        mesh,
        in_specs=(P(_BATCH, None, TP_AXIS), P(TP_AXIS, None),
                  P(_BATCH, TP_AXIS, None), P(), P()),
        out_specs=(P(_BATCH, None, TP_AXIS), P(TP_AXIS, None)))(
        yq, wq, doq, inv_dy, inv_dw)
    amaxes = jnp.stack([ay, aw, ag])
    sats = jnp.stack([sy_cnt, sw_cnt, sg_cnt])
    dfp8 = {"hist": rolled_hist(hist, amaxes), "sat": sats}
    return dy, dw, dfp8


_mm_rs_fp8.defvjp(_mm_rs_fp8_fwd, _mm_rs_fp8_bwd)


# ---------------------------------------------------------------------------
# Ambient-manual variants: the same fused rings, callable from INSIDE an
# existing full-manual shard_map (the pp pipeline stage body). No shard_map
# wrapper (nested shard_maps are unsupported on this jax build) and no
# (dp, ep) wgrad psum (the enclosing region's transpose owns that
# reduction for replicated params). ``overlap=False`` swaps the latency-
# hiding ring forward for bulk collectives (one tiled all-gather / an
# unfused reduce-scatter ring) — the A/B baseline — while keeping the
# fused ring backward, which is correct either way.
# ---------------------------------------------------------------------------


def _bulk_ag_mm(tp, xl, wls):
    """Bulk forward: one tiled all-gather of x, then the plain GEMMs
    (exposed comm — the tp_comm_overlap=False baseline)."""
    from megatronapp_tpu.parallel.collectives import all_gather_seq
    x_full = all_gather_seq(xl, TP_AXIS, axis=1)
    return tuple(x_full @ wl for wl in wls)


def _bulk_mm_rs(tp, yls, wls):
    """Bulk forward: full partial product first, then an unfused
    reduce-scatter ring over the seq chunks (no GEMM to hide hops under)."""
    me = lax.axis_index(TP_AXIS)
    full = None
    for yl, wl in zip(yls, wls):
        full = yl @ wl if full is None else full + yl @ wl
    sc = full.shape[1] // tp
    perm = _ring_perm(tp)

    def chunk(c):
        return lax.dynamic_slice_in_dim(full, c * sc, sc, axis=1)

    acc = chunk((me + 1) % tp)
    for step in range(1, tp):
        acc = lax.ppermute(acc, TP_AXIS, perm) + chunk((me + 1 + step) % tp)
    return acc


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _ag_mm_ambient(tp, overlap, x, ws):
    return _ag_mm_ambient_fwd(tp, overlap, x, ws)[0]


def _ag_mm_ambient_fwd(tp, overlap, x, ws):
    if overlap:
        ys = _ag_mm_body(tp, "all-gather-matmul", x, ws)
    else:
        ys = _bulk_ag_mm(tp, x, ws)
    return ys, (x, ws)


def _ag_mm_ambient_bwd(tp, overlap, res, dys):
    x, ws = res
    dx, dws = _ag_mm_bwd_body(tp, x, ws, dys, reduce_batch=False)
    return dx, dws


_ag_mm_ambient.defvjp(_ag_mm_ambient_fwd, _ag_mm_ambient_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _mm_rs_ambient(tp, overlap, y, w):
    return _mm_rs_ambient_fwd(tp, overlap, y, w)[0]


def _mm_rs_ambient_fwd(tp, overlap, y, w):
    if overlap:
        out = _mm_rs_rings(tp, (y,), (w,))
    else:
        out = _bulk_mm_rs(tp, (y,), (w,))
    return out, (y, w)


def _mm_rs_ambient_bwd(tp, overlap, res, dout):
    y, w = res
    dy, dw = _mm_rs_bwd_body(tp, y, w, dout, reduce_batch=False)
    return dy, dw


_mm_rs_ambient.defvjp(_mm_rs_ambient_fwd, _mm_rs_ambient_bwd)


def all_gather_matmul_manual(x, w, tp, overlap=True):
    """Column-parallel matmul from inside an ambient full-manual region.

    x: [b, S/tp, H] — this shard's seq chunk of the tp-sharded residual
    stream; w: [H, N/tp] — this shard's output slice (or a tuple sharing
    ONE ring all-gather of x, the fused-QKV case). Returns [b, S, N/tp]
    per weight: full sequence, local output shard. The caller guarantees
    the ambient region is manual over tp (and that S divided evenly when
    the stream was sharded — tp_stage_eligible)."""
    fused = isinstance(w, (tuple, list))
    ws = tuple(w) if fused else (w,)
    ys = _ag_mm_ambient(tp, overlap, x, ws)
    return ys if fused else ys[0]


def matmul_reduce_scatter_manual(y, w, tp, overlap=True):
    """Row-parallel matmul from inside an ambient full-manual region.

    y: [b, S, N/tp] (full seq, local inner shard); w: [N/tp, H] — this
    shard's row slice. Returns [b, S/tp, H]: the fully-reduced local seq
    chunk of the tp-sharded residual stream."""
    return _mm_rs_ambient(tp, overlap, y, w)


def tp_stage_eligible(cfg, ctx, seq_len: int) -> bool:
    """Whether the full-manual pipeline may run its stage body tp-SHARDED
    (activations [mb, S/tp, H] between stages — [mb, S/(cp*tp), H] under
    the pp x cp x tp composition — projections through the ambient rings
    above) instead of tp-replicated.

    Requirements: tp > 1 inside a pp > 1 manual region, the kill-switch
    ``cfg.tp_sharded_stage`` on, S divisible by the seq shard degree
    (tp, or cp*tp when cp > 1), whole heads per shard (nq — and nkv for
    GQA — divisible by tp; the manual path slices head groups, unlike
    the GSPMD-overlap path which only needs flat dims), and dense-MLP
    ffn divisible by tp (gate/value halves shard separately for gated
    activations). MoE layers dispatch locally per shard (any expert
    count); heterogeneous stacks are excluded (the pipeline rejects them
    anyway). Under cp > 1 (ISSUE 15) the composition is restricted to
    dense non-MLA, non-MoE stacks on the contiguous p2p cp ring: heads
    shard over tp, the QKV ring gathers only the cp-local seq chunk,
    and attention runs the cp ring per head shard."""
    return tp_stage_ineligible_reason(cfg, ctx, seq_len) is None


def tp_stage_cp_excluded_reason(cfg, cp: int):
    """Config-only predicates excluding the pp x cp x tp composition
    (ISSUE 15): the residual stream between stages shards the sequence
    over (cp, tp) jointly and attention runs the contiguous cp ring per
    tp head shard — restricted to dense non-MLA, non-MoE stacks on the
    p2p cp ring for now. Shared by the runtime eligibility check below
    and the parse-time validation in config/arguments.py so the two
    sites cannot drift; returns the first failed predicate or None."""
    if cfg.multi_latent_attention:
        return (f"cp == {cp} > 1 with MLA (the latent "
                "attention's shared-rope gather is not composed "
                "with the cp ring under tp-sharded stage bodies "
                "yet — the replicated body handles MLA + cp)")
    if cfg.is_moe:
        return (f"cp == {cp} > 1 with MoE (expert dispatch "
                "under the joint cp x tp token split is not "
                "validated yet — the replicated body handles "
                "MoE + cp)")
    if cfg.cp_comm_type != "p2p":
        return (f"cp_comm_type {cfg.cp_comm_type!r} (the tp-sharded "
                "stage body composes with the contiguous p2p cp "
                "ring only; a2a-family comms redistribute heads, "
                "which are already tp-sliced here)")
    return None


def tp_stage_ineligible_reason(cfg, ctx, seq_len: int):
    """Why the stage body may NOT run tp-sharded — None when eligible,
    otherwise the FIRST failed predicate by name, so the replicated-body
    fallback log says what to fix instead of a generic "ineligible"
    (ISSUE 11 satellite; same contract as tp_paged_ineligible_reason)."""
    if ctx is None:
        return "no mesh context (ctx is None)"
    if ctx.tp <= 1:
        return f"tp == {ctx.tp} (nothing to shard)"
    if ctx.pp <= 1:
        return (f"pp == {ctx.pp} (the sharded body lives inside the "
                f"manual pp pipeline region)")
    if ctx.cp > 1:
        reason = tp_stage_cp_excluded_reason(cfg, ctx.cp)
        if reason is not None:
            return reason
    # FBD abstract half-meshes keep the proven tp-replicated body (same
    # exclusion as tp_overlap_eligible: abstract-mesh manual collectives
    # over tp are unvalidated there).
    if getattr(ctx, "abstract_collectives", False):
        return "FBD abstract half-mesh (manual tp collectives " \
               "unvalidated on abstract meshes)"
    if not getattr(cfg, "tp_sharded_stage", True):
        return "kill-switch: cfg.tp_sharded_stage off " \
               "(--no-tp-sharded-stage)"
    if getattr(cfg, "hetero_block_specs", None):
        return "heterogeneous per-layer configs (pipeline rejects them)"
    tp = ctx.tp
    seq_shard = tp * ctx.cp
    if seq_len % seq_shard:
        return (f"seq_len ({seq_len}) % tp ({tp}) != 0" if ctx.cp == 1
                else f"seq_len ({seq_len}) % (cp*tp) ({seq_shard}) != 0 "
                     f"(the stream shards the sequence over cp AND tp)")
    if cfg.num_attention_heads % tp:
        return (f"num_attention_heads ({cfg.num_attention_heads}) % tp "
                f"({tp}) != 0")
    if not cfg.multi_latent_attention and cfg.num_query_groups % tp:
        return (f"num_query_groups ({cfg.num_query_groups}) % tp "
                f"({tp}) != 0 (shards must own whole GQA groups)")
    has_dense_mlp = (not cfg.is_moe) or cfg.moe_layer_freq > 1
    if has_dense_mlp and cfg.ffn_hidden_size % tp:
        return (f"ffn_hidden_size ({cfg.ffn_hidden_size}) % tp ({tp}) "
                f"!= 0 (gate/value halves shard separately)")
    return None


# ---------------------------------------------------------------------------
# Generic ring all-gather: the ZeRO-1 distributed optimizer's param-return
# path (training/distributed_optimizer.py manual_apply) rings updated
# param shards around the dp axis the same way the tp rings move sequence
# chunks — each hop is issued before the chunk lands in the accumulator so
# hops ride under the writes (TPU async collectives; serial on XLA:CPU).
# ---------------------------------------------------------------------------

def ring_all_gather(x, axis_name: str, n: int, axis: int = 0,
                    op_name: str = "ring-allgather"):
    """[..., D/n, ...] shard → full [..., D, ...] via an n-hop ppermute
    ring over ``axis_name``, rank-major chunk order (identical layout to
    ``lax.all_gather(..., tiled=True)``). Callable from any full-manual
    region whose mesh binds ``axis_name``; n == 1 is a no-op."""
    if n == 1:
        return x
    me = lax.axis_index(axis_name)
    chunk_len = x.shape[axis]
    shape = list(x.shape)
    shape[axis] = chunk_len * n
    out = zeros_like_vma(tuple(shape), x.dtype, x)
    perm = [(r, (r - 1) % n) for r in range(n)]
    chunk = x
    for step in range(n):
        nxt = None
        if step + 1 < n:
            ring_span(DP_OVERLAP_PERMUTE_EVENT, "B", chunk, axis_name,
                      op=op_name, step=step)
            nxt = lax.ppermute(chunk, axis_name, perm)
        owner = (me + step) % n     # global chunk index currently held
        out = lax.dynamic_update_slice_in_dim(out, chunk,
                                              owner * chunk_len, axis)
        if nxt is not None:
            ring_span(DP_OVERLAP_PERMUTE_EVENT, "E", nxt, axis_name,
                      op=op_name, step=step)
            chunk = nxt
    return out


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def all_gather_matmul(x, w, mesh, fp8=None, fp8_margin=0):
    """Column-parallel ``x @ w`` with ring-overlapped sequence all-gather.

    x: [B, S, H]; w: [H, N] with N % tp == 0 (sharded over tp on N) — or
    a tuple of such weights, in which case ONE ring all-gather of x
    feeds every projection (fused QKV: half the permute traffic of two
    separate calls) and a tuple of outputs is returned.
    Each output is [B, S, N_j] sharded over tp on the last dim —
    layout-identical to the GSPMD column-parallel matmul. S not divisible
    by tp is zero-padded internally (outside the custom_vjp, so gradients
    of the pad/slice are automatic).

    fp8 (ISSUE 13): the site's delayed-scaling state
    {"hist" [1+2n, H], "sat" [1+2n]} — both GEMM operands quantize to
    e4m3 with scales from the history, the ring moves fp8 chunks, and
    the UPDATED history travels out as this input's cotangent
    (training/fp8.py; the train step installs it into state["fp8"])."""
    tp = mesh.shape[TP_AXIS]
    fused = isinstance(w, (tuple, list))
    ws = tuple(w) if fused else (w,)
    for wj in ws:
        if wj.shape[-1] % tp:
            raise ValueError(
                f"all_gather_matmul: output dim {wj.shape[-1]} not "
                f"divisible by tp={tp} (gate callers on "
                "tp_overlap_eligible)")
    s = x.shape[1]
    sp = _round_up(s, tp)
    if sp != s:
        x = jnp.pad(x, ((0, 0), (0, sp - s), (0, 0)))
    if fp8 is not None:
        ys = _ag_mm_fp8(mesh, int(fp8_margin), x, ws, fp8)
    else:
        ys = _ag_mm(mesh, x, ws)
    if sp != s:
        ys = tuple(y[:, :s] for y in ys)
    return ys if fused else ys[0]


def matmul_reduce_scatter(y, w, mesh, fp8=None, fp8_margin=0):
    """Row-parallel ``y @ w`` with ring-overlapped partial-sum
    reduce-scatter along the sequence dim.

    y: [B, S, N] with N % tp == 0 (sharded over tp on N); w: [N, H].
    Returns the full [B, S, H] (manually sharded over tp along S; a
    replicated consumer triggers the trailing all-gather — same total
    volume as the GSPMD all-reduce, with the RS half overlapped).

    fp8: the site's delayed-scaling state {"hist" [3, H], "sat" [3]}
    (input, weight, cotangent) — see all_gather_matmul."""
    tp = mesh.shape[TP_AXIS]
    if y.shape[-1] % tp or y.shape[-1] != w.shape[0]:
        raise ValueError(
            f"matmul_reduce_scatter: inner dim {y.shape[-1]} must match "
            f"w rows {w.shape[0]} and divide by tp={tp}")
    s = y.shape[1]
    sp = _round_up(s, tp)
    if sp != s:
        y = jnp.pad(y, ((0, 0), (0, sp - s), (0, 0)))
    if fp8 is not None:
        out = _mm_rs_fp8(mesh, int(fp8_margin), y, w, fp8)
    else:
        out = _mm_rs(mesh, y, w)
    return out[:, :s] if sp != s else out


def tp_overlap_eligible(cfg, ctx, *tp_dims, batch=None) -> bool:
    """Whether the manual overlap path may replace the GSPMD matmuls here.

    tp_dims: every weight dim that must shard evenly over tp (column
    output dims and row input dims of the projection pair — one decision
    per pair keeps fwd layouts consistent). batch: the activation batch
    dim, which the full-manual region shards over (dp, ep).

    Falls back to GSPMD when: the flag is off; no mesh context; tp == 1
    (nothing to overlap); cp > 1 (seq already compiler-sharded over cp);
    inside an existing manual region (nested shard_map unsupported —
    README known constraints); FBD abstract meshes (eager abstract-mesh
    shard_maps unsupported); or any dim indivisible."""
    if not getattr(cfg, "tp_comm_overlap", False):
        return False
    if ctx is None:
        return False
    if getattr(ctx, "abstract_collectives", False):
        return False
    tp = ctx.tp
    if tp <= 1 or ctx.cp > 1:
        return False
    if batch is not None and batch % (ctx.dp * ctx.ep) != 0:
        return False
    from megatronapp_tpu.parallel.collectives import current_manual_axes
    if current_manual_axes():
        return False
    return all(d % tp == 0 for d in tp_dims)
