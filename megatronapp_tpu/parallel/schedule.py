"""Pipeline schedule layer: explicit per-stage instruction programs, a
simulated-timeline bubble model, and the trace-driven planner that closes
the MegaScan → MegaDPP loop (ISSUE 15).

The reference ships MegaScan (tracing + slow-chip detection) and MegaDPP
(dynamic pipeline planning) as separate modules that never talk; here the
tracer's per-stage signal feeds an actual scheduling decision:

  programs   ``forward_tables`` emits the clocked (active, microbatch,
             chunk) tables the SPMD executor in ``parallel/pipeline.py``
             consumes for 1F1B / interleaved-VPP forwards (identical to
             the closed-form schedule the scan used to compute inline —
             pinned in tests), and ``zb_backward_tables`` emits the
             zero-bubble backward program: B = dgrad (activation
             cotangent, rides the reverse stage ring), W = wgrad (weight
             cotangent, DEFERRED into bubble slots). The weight update is
             fenced on ALL W done — the optimizer / ZeRO-1 sees grads
             identical to the fused backward.
  model      ``simulate_timeline``: event-driven per-stage timeline off
             the combined instruction programs + a per-stage cost table —
             the deterministic bubble evidence while the TPU tunnel is
             down (PAPERS.md: arXiv 2412.14374 MPMD per-stage programs;
             the zero-bubble split follows the ZB-H1 family).
  planner    ``Planner``: per-(stage, vstage) step-time EWMAs fed by the
             MegaScan ring-hop spans (trace/detect.stage_step_gaps) and
             the whole-step straggler signal, static relative costs from
             the heterogeneous stage table (transformer/heterogeneous.py),
             modeled bubble per candidate schedule, and hysteresis
             re-planning with loud logs + /metrics gauges keyed
             (stage, vstage).

Program/timing conventions: one instruction per stage per clock slot;
an instruction executed at slot t is consumable by another stage at slot
t+1 (one ring hop per slot — exactly the executor's ppermute cadence).
The executed SPMD program realizes the combined zero-bubble timeline as a
forward F-scan plus a backward B/W-scan with the same instruction sets
and dependencies (validated here); the combined timeline is what an MPMD
runtime would execute and what the bubble model measures.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from megatronapp_tpu.utils import metrics as telemetry

logger = logging.getLogger(__name__)

F, B, W, BW = "F", "B", "W", "BW"

# NOP/B/W encoding of the backward tables (lax.switch branch index).
KIND_NOP, KIND_B, KIND_W = 0, 1, 2

SCHEDULES = ("1f1b", "vpp", "zero-bubble")


@dataclasses.dataclass(frozen=True)
class Instr:
    kind: str
    mb: int
    chunk: int = 0


# ---------------------------------------------------------------------------
# Forward program tables (1F1B / interleaved VPP)
# ---------------------------------------------------------------------------

def forward_tables(pp: int, num_microbatches: int, vpp: int = 1
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Clocked forward program: (active[T, pp] bool, mb[T, pp] i32,
    chunk[T, pp] i32) with T = M*vpp + pp - 1.

    Entry [t, s] is the instruction stage s executes at slot t (masked
    when inactive). Matches the unified closed-form schedule bit-for-bit
    (u = t - s, round r = u // (pp*vpp), chunk = (u % (pp*vpp)) // pp,
    m = r*pp + u % pp) — the scan body now *executes this table* instead
    of computing the formula inline, which is what lets zero-bubble (and
    future schedules) swap in as data."""
    M = num_microbatches
    T = M * vpp + pp - 1
    cycle = pp * vpp
    active = np.zeros((T, pp), np.bool_)
    mb_t = np.zeros((T, pp), np.int32)
    ck_t = np.zeros((T, pp), np.int32)
    for t in range(T):
        for s in range(pp):
            u = t - s
            r, w = divmod(u, cycle)          # floor semantics == jnp i32
            c = w // pp
            m = r * pp + (w % pp)
            active[t, s] = (u >= 0) and (0 <= m < M)
            mb_t[t, s] = min(max(m, 0), M - 1)
            ck_t[t, s] = min(max(c, 0), vpp - 1)
    return active, mb_t, ck_t


# ---------------------------------------------------------------------------
# Zero-bubble backward program tables
# ---------------------------------------------------------------------------

def zb_backward_tables(pp: int, num_microbatches: int, vpp: int = 1
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Clocked zero-bubble backward program: (kind[T2, pp] i32 in
    {KIND_NOP, KIND_B, KIND_W}, mb[T2, pp], chunk[T2, pp]).

    B's form cotangent WAVEFRONTS: microbatch m's backward visits
    (chunk vpp-1 .. 0) x (stage pp-1 .. 0) on consecutive slots, so each
    B consumes exactly what the ring delivered from its producer one slot
    earlier (B_(m,c,s) is one slot after B_(m,c,s+1); at s == pp-1 and
    c < vpp-1 one slot after B_(m,c+1,0) — the reversed chunk hand-off).
    Wavefront start slots are chosen greedily earliest-first without
    per-stage slot collisions. W's then fill every remaining idle slot
    after their same-stage B (FIFO by B time) — the deferral that turns
    1F1B's cooldown bubble into wgrad work. All W's complete inside the
    program: the optimizer fence is structural."""
    M = num_microbatches

    def slot(tau, c, s):
        return tau + (vpp - 1 - c) * pp + (pp - 1 - s)

    occupied: List[Dict[int, Tuple[int, int]]] = [dict() for _ in range(pp)]
    taus = []
    tau = 0
    for m in range(M):
        while any(slot(tau, c, s) in occupied[s]
                  for c in range(vpp) for s in range(pp)):
            tau += 1
        taus.append(tau)
        for c in range(vpp):
            for s in range(pp):
                occupied[s][slot(tau, c, s)] = (m, c)
        tau += 1

    b_end = max(max(o) for o in occupied)
    # W fill: walk slots; at each idle slot run the earliest-ready wgrad.
    w_sched: List[Dict[int, Tuple[int, int]]] = [dict() for _ in range(pp)]
    for s in range(pp):
        ready = sorted(occupied[s].items())     # [(slot, (m, c))...]
        pending: List[Tuple[int, Tuple[int, int]]] = []
        nxt = 0
        t = 0
        while nxt < len(ready) or pending:
            while nxt < len(ready) and ready[nxt][0] < t:
                pending.append(ready[nxt])
                nxt += 1
            if t not in occupied[s] and pending:
                w_sched[s][t] = pending.pop(0)[1]
            t += 1

    T2 = 1 + max(b_end,
                 max((max(w) for w in w_sched if w), default=0))
    kind = np.zeros((T2, pp), np.int32)
    mb_t = np.zeros((T2, pp), np.int32)
    ck_t = np.zeros((T2, pp), np.int32)
    for s in range(pp):
        for t, (m, c) in occupied[s].items():
            kind[t, s], mb_t[t, s], ck_t[t, s] = KIND_B, m, c
        for t, (m, c) in w_sched[s].items():
            kind[t, s], mb_t[t, s], ck_t[t, s] = KIND_W, m, c
    return kind, mb_t, ck_t


# ---------------------------------------------------------------------------
# Program validation (dependency / ring-alignment / fence checks)
# ---------------------------------------------------------------------------

def validate_programs(pp: int, num_microbatches: int, vpp: int,
                      fwd: Tuple[np.ndarray, np.ndarray, np.ndarray],
                      bwd: Optional[Tuple[np.ndarray, np.ndarray,
                                          np.ndarray]] = None) -> None:
    """Raise ValueError on any dependency, ring-alignment, duplicate, or
    fence violation. The executor runs programs blindly — this is the
    gate that keeps a planner-emitted program from silently consuming a
    stale ring value or dropping a wgrad before the optimizer fence."""
    M = num_microbatches
    active, mb_t, ck_t = fwd
    T = active.shape[0]
    f_slot: Dict[Tuple[int, int, int], int] = {}
    for t in range(T):
        for s in range(pp):
            if not active[t, s]:
                continue
            key = (int(mb_t[t, s]), int(ck_t[t, s]), s)
            if key in f_slot:
                raise ValueError(f"duplicate F for (m, chunk, stage)={key}")
            f_slot[key] = t
    if len(f_slot) != M * vpp * pp:
        raise ValueError(
            f"forward program has {len(f_slot)} F instructions, expected "
            f"{M * vpp * pp} (every (microbatch, chunk) on every stage)")
    for (m, c, s), t in f_slot.items():
        if s > 0:
            dep = (m, c, s - 1)
        elif c > 0:
            dep = (m, c - 1, pp - 1)
        else:
            continue                       # stage-0 chunk-0 injects fresh
        if f_slot.get(dep) != t - 1:
            raise ValueError(
                f"F{(m, c, s)} at slot {t} misaligned with its ring "
                f"producer F{dep} (need slot {t - 1}, got "
                f"{f_slot.get(dep)})")

    if bwd is None:
        return
    kind, bmb, bck = bwd
    T2 = kind.shape[0]
    b_slot: Dict[Tuple[int, int, int], int] = {}
    w_slot: Dict[Tuple[int, int, int], int] = {}
    for t in range(T2):
        for s in range(pp):
            k = int(kind[t, s])
            if k == KIND_NOP:
                continue
            key = (int(bmb[t, s]), int(bck[t, s]), s)
            table = b_slot if k == KIND_B else w_slot
            if key in table:
                raise ValueError(
                    f"duplicate {'B' if k == KIND_B else 'W'} for "
                    f"(m, chunk, stage)={key}")
            table[key] = t
    if len(b_slot) != M * vpp * pp or len(w_slot) != M * vpp * pp:
        raise ValueError(
            f"backward program has {len(b_slot)} B / {len(w_slot)} W "
            f"instructions, expected {M * vpp * pp} each — a missing W "
            "would drop a wgrad before the optimizer fence")
    for (m, c, s), t in b_slot.items():
        if s == pp - 1 and c == vpp - 1:
            continue                    # consumes the output cotangent
        dep = (m, c, s + 1) if s < pp - 1 else (m, c + 1, 0)
        if b_slot.get(dep) != t - 1:
            raise ValueError(
                f"B{(m, c, s)} at slot {t} misaligned with its reverse-"
                f"ring producer B{dep} (need slot {t - 1}, got "
                f"{b_slot.get(dep)})")
    for (m, c, s), t in w_slot.items():
        tb = b_slot.get((m, c, s))
        if tb is None or tb >= t:
            raise ValueError(
                f"W{(m, c, s)} at slot {t} runs before its dgrad "
                f"B at slot {tb} — wgrad needs the saved output "
                "cotangent")


# ---------------------------------------------------------------------------
# Combined (modeled) per-stage programs + the bubble simulator
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=128)
def combined_programs(schedule: str, pp: int, num_microbatches: int
                      ) -> List[List[Instr]]:
    """Ordered per-stage instruction lists of the COMBINED timeline an
    MPMD runtime would execute (vpp == 1): '1f1b' uses the fused BW
    backward; 'zero-bubble' splits B/W with greedy B > F > W priority
    under the 1F1B in-flight cap (ZB-H1-style, same activation memory).

    Cached per (schedule, pp, M) — the planner re-simulates every
    candidate each log interval and only the cost-dependent event
    simulation varies; callers must treat the returned lists as
    read-only."""
    M = num_microbatches
    if schedule in ("1f1b", "vpp"):
        progs = []
        for s in range(pp):
            warm = min(pp - 1 - s, M)
            order = [Instr(F, m) for m in range(warm)]
            for i in range(M - warm):
                order.append(Instr(F, warm + i))
                order.append(Instr(BW, i))
            for m in range(M - warm, M):
                order.append(Instr(BW, m))
            progs.append(order)
        return progs
    if schedule != "zero-bubble":
        raise ValueError(f"unknown schedule {schedule!r} (one of "
                         f"{SCHEDULES})")

    # Greedy unit-cost construction. done-slot semantics: an instruction
    # run at slot t is visible to OTHER stages at t+1 and to its OWN
    # stage's later slots.
    f_at: Dict[Tuple[int, int], int] = {}
    b_at: Dict[Tuple[int, int], int] = {}
    f_next = [0] * pp
    b_next = [0] * pp
    w_done = [0] * pp
    w_pool: List[List[int]] = [[] for _ in range(pp)]
    progs: List[List[Instr]] = [[] for _ in range(pp)]
    t = 0
    while any(w_done[s] < M for s in range(pp)):
        for s in range(pp):
            m = b_next[s]
            can_b = (m < M and f_at.get((m, s), t) < t
                     and (s == pp - 1 or b_at.get((m, s + 1), t) < t))
            if can_b:
                b_at[(m, s)] = t
                b_next[s] += 1
                w_pool[s].append(m)
                progs[s].append(Instr(B, m))
                continue
            m = f_next[s]
            can_f = (m < M and (s == 0 or f_at.get((m, s - 1), t) < t)
                     and f_next[s] - b_next[s] < pp - s)
            if can_f:
                f_at[(m, s)] = t
                f_next[s] += 1
                progs[s].append(Instr(F, m))
                continue
            if w_pool[s] and b_at[(w_pool[s][0], s)] < t:
                progs[s].append(Instr(W, w_pool[s].pop(0)))
                w_done[s] += 1
        t += 1
        if t > 10 * (3 * M + pp) + 100:
            raise RuntimeError("zero-bubble greedy scheduler failed to "
                               "converge (internal bug)")
    # The loop's only normal exit (w_done == M on every stage) implies
    # every W already drained — a leftover would violate the ZB-H1
    # in-flight invariant the loop encodes.
    assert not any(w_pool), "zero-bubble greedy left W pending"
    return progs


def simulate_timeline(schedule: str, pp: int, num_microbatches: int,
                      stage_costs: Optional[Sequence[float]] = None,
                      comm: float = 0.0, bwd_ratio: float = 1.0,
                      wgrad_ratio: float = 1.0) -> Dict:
    """Event-driven simulation of the combined per-stage programs.

    stage_costs: relative per-stage forward cost (per microbatch);
    B costs bwd_ratio x F, W costs wgrad_ratio x F, the fused BW their
    sum. Returns {makespan, bubble_fraction, per_stage_busy,
    per_stage_idle} — the deterministic evidence the bench gate consumes
    (zero-bubble bubble strictly < 1F1B at the bench shapes)."""
    M = num_microbatches
    costs = list(stage_costs) if stage_costs is not None else [1.0] * pp
    if len(costs) != pp:
        raise ValueError(f"stage_costs must have pp={pp} entries")
    progs = combined_programs(schedule, pp, M)
    done: Dict[Tuple[str, int, int], float] = {}
    t_free = [0.0] * pp
    busy = [0.0] * pp
    idx = [0] * pp

    def ready_time(ins: Instr, s: int) -> Optional[float]:
        if ins.kind == F:
            if s == 0:
                return 0.0
            dep = (F, ins.mb, s - 1)
            return None if dep not in done else done[dep] + comm
        if ins.kind in (B, BW):
            fdep = (F, ins.mb, s)
            if fdep not in done:
                return None
            if s == pp - 1:
                return done[fdep]
            dep = (ins.kind, ins.mb, s + 1)
            if dep not in done:
                return None
            return max(done[dep] + comm, done[fdep])
        dep = (B, ins.mb, s)                      # W
        return done.get(dep)

    def cost_of(ins: Instr, s: int) -> float:
        if ins.kind == F:
            return costs[s]
        if ins.kind == B:
            return costs[s] * bwd_ratio
        if ins.kind == W:
            return costs[s] * wgrad_ratio
        return costs[s] * (bwd_ratio + wgrad_ratio)

    progressed = True
    while any(idx[s] < len(progs[s]) for s in range(pp)):
        if not progressed:
            raise RuntimeError(
                f"deadlock simulating {schedule!r} program (stuck at "
                f"{[(s, idx[s]) for s in range(pp)]})")
        progressed = False
        for s in range(pp):
            while idx[s] < len(progs[s]):
                ins = progs[s][idx[s]]
                ready = ready_time(ins, s)
                if ready is None:
                    break
                start = max(t_free[s], ready)
                dur = cost_of(ins, s)
                done[(ins.kind, ins.mb, s)] = start + dur
                t_free[s] = start + dur
                busy[s] += dur
                idx[s] += 1
                progressed = True
    makespan = max(t_free)
    return {
        "makespan": makespan,
        "bubble_fraction": 1.0 - sum(busy) / (pp * makespan),
        "per_stage_busy": busy,
        "per_stage_idle": [makespan - b for b in busy],
    }


def analytic_vpp_bubble(pp: int, num_microbatches: int, vpp: int,
                        stage_costs: Sequence[float]) -> float:
    """Closed-form interleaved-VPP bubble estimate: the fill fraction
    (M*vpp)/(M*vpp + pp - 1) scaled by the heterogeneous imbalance
    (mean/max stage cost — the slowest stage dictates the clock)."""
    imb = (sum(stage_costs) / len(stage_costs)) / max(stage_costs)
    fill = (num_microbatches * vpp) / (num_microbatches * vpp + pp - 1)
    return 1.0 - imb * fill


# ---------------------------------------------------------------------------
# Stage cost model (heterogeneous stage table) + the planner
# ---------------------------------------------------------------------------

def stage_cost_model(cfg, pp: int, vpp: int = 1) -> List[float]:
    """Relative per-stage forward cost table, normalized to mean 1.0.

    Uniform stacks → all ones. Heterogeneous stacks (Nemotron-style
    block_configs, transformer/heterogeneous.py) → per-layer projection
    FLOPs summed per stage through the interleaved chunk placement
    (global layer (c*pp + s)*Lc + i). The pipeline executor rejects
    unstacked hetero params, so this table is the PLANNER's view of
    unequal stages — exactly the signal MegaDPP sizes stages with."""
    specs = getattr(cfg, "hetero_block_specs", None) if cfg else None
    if not specs:
        return [1.0] * pp
    from megatronapp_tpu.transformer.heterogeneous import (
        layer_relative_cost,
    )
    L = len(specs)
    if L % (pp * vpp):
        return [1.0] * pp
    lc = L // (pp * vpp)
    costs = [0.0] * pp
    for s in range(pp):
        for c in range(vpp):
            base = (c * pp + s) * lc
            for i in range(lc):
                costs[s] += layer_relative_cost(specs[base + i], cfg)
    mean = sum(costs) / pp
    return [c / mean for c in costs] if mean > 0 else [1.0] * pp


@dataclasses.dataclass
class PipelinePlan:
    schedule: str
    num_microbatches: int
    vpp: int
    bubble_fraction: float
    candidates: Dict[str, float]
    stage_costs: List[float]


class Planner:
    """Turns MegaScan's detection signal into scheduling decisions.

    Per-(stage, vstage) step-time EWMAs are fed three ways: real
    per-stage samples from the pipeline's ring-hop trace spans
    (``ingest_trace_events`` → trace/detect.stage_step_gaps), whole-step
    samples distributed by the current relative weights
    (``observe_step`` — keeps the signal alive when tracing is off), or
    direct ``observe_stage_time`` calls (tests, external probes). The
    static fallback is the heterogeneous stage table. ``plan`` simulates
    every candidate schedule's bubble under the current costs and picks
    the minimum; ``maybe_replan`` adds hysteresis and logs loudly.
    """

    def __init__(self, pp: int, vpp: int = 1, model_cfg=None,
                 alpha: float = 0.2, replan_margin: float = 0.02,
                 z_window: int = 64, allow_zero_bubble: bool = True):
        from megatronapp_tpu.utils.straggler import RollingZ
        self.pp = pp
        self.vpp = vpp
        self.alpha = alpha
        self.replan_margin = replan_margin
        # The caller gates this on the executor's dispatch mode: where
        # the zero-bubble backward runs as masked dual-vjp compute
        # (tp-sharded / cp-ring / moe stage bodies), the bubble the
        # model saves is paid back ~2x in redundant backward FLOPs, so
        # the planner must not auto-apply it there.
        self.allow_zero_bubble = allow_zero_bubble
        self.base_costs = stage_cost_model(model_cfg, pp, vpp)
        self._ewma: Dict[Tuple[int, int], float] = {}
        self._z: Dict[Tuple[int, int], RollingZ] = {}
        self._z_window = z_window
        self._make_z = RollingZ
        self.current: Optional[PipelinePlan] = None
        self.replans = 0
        self._trace_seen = False
        self._validated: set = set()  # (schedule, M) already validated

    # -- signal ingestion --------------------------------------------------
    def observe_stage_time(self, stage: int, seconds: float,
                           vstage: int = 0):
        key = (int(stage), int(vstage))
        prev = self._ewma.get(key)
        self._ewma[key] = (seconds if prev is None
                           else self.alpha * seconds
                           + (1 - self.alpha) * prev)
        z = self._z.get(key)
        if z is None:
            z = self._z[key] = self._make_z(window=self._z_window)
        z.observe(seconds)

    def observe_step(self, step_seconds: float):
        """Whole-pipeline step sample (the straggler detector's view):
        distributed over stages by the current relative weights, so the
        EWMAs stay alive — and the plan stays stable — when tracing is
        off. A no-op once ring-hop trace samples have been ingested:
        those are per-SLOT stage-body times (~step/(M*vpp+pp-1)), a
        different unit from this per-step split (~step/pp) — mixing the
        two in one EWMA/RollingZ window would oscillate the exported
        gauges and flag phantom stragglers on uniform stages."""
        if self._trace_seen:
            return
        w = self.stage_costs()
        total = sum(w)
        for s in range(self.pp):
            self.observe_stage_time(s, step_seconds * w[s] / total)

    def ingest_trace_events(self, events) -> int:
        """Feed per-stage compute-time gaps mined from the pipeline's
        ring-hop spans (MegaScan → planner). Returns samples ingested."""
        from megatronapp_tpu.trace.detect import stage_step_gaps
        n = 0
        by_stage = {s: g for s, g in stage_step_gaps(events).items()
                    if 0 <= s < self.pp}
        if any(by_stage.values()) and not self._trace_seen:
            # Real per-slot samples supersede the synthetic whole-step
            # split for the rest of the run (see observe_step) — drop
            # the synthetic history so this window is not judged
            # against the wrong unit.
            self._trace_seen = True
            self._ewma.clear()
            self._z.clear()
        for stage, gaps in by_stage.items():
            for g in gaps:
                self.observe_stage_time(stage, g)
                n += 1
        return n

    # -- planning ----------------------------------------------------------
    def stage_costs(self) -> List[float]:
        """Current relative per-stage costs: measured EWMAs (summed over
        vstages) when every stage has one, else the static table."""
        per_stage = [0.0] * self.pp
        seen = [False] * self.pp
        for (s, _v), val in self._ewma.items():
            per_stage[s] += val
            seen[s] = True
        if not all(seen):
            return list(self.base_costs)
        mean = sum(per_stage) / self.pp
        return ([c / mean for c in per_stage] if mean > 0
                else list(self.base_costs))

    def plan(self, num_microbatches: int) -> PipelinePlan:
        costs = self.stage_costs()
        cands: Dict[str, float] = {}
        if self.vpp > 1:
            cands["vpp"] = analytic_vpp_bubble(
                self.pp, num_microbatches, self.vpp, costs)
        else:
            scheds = (("1f1b", "zero-bubble") if self.allow_zero_bubble
                      else ("1f1b",))
            for sch in scheds:
                cands[sch] = simulate_timeline(
                    sch, self.pp, num_microbatches,
                    stage_costs=costs)["bubble_fraction"]
        best = min(cands, key=lambda k: cands[k])
        # Emit + validate the executable program for the winner before
        # recommending it (a planner must never hand the executor an
        # unvalidated program). Tables are deterministic in
        # (schedule, pp, M, vpp) and plan() runs every log interval
        # from the training hot loop, so each key is validated once.
        key = (best, num_microbatches)
        if key not in self._validated:
            fwd = forward_tables(self.pp, num_microbatches, self.vpp)
            bwd = (zb_backward_tables(self.pp, num_microbatches,
                                      self.vpp)
                   if best == "zero-bubble" else None)
            validate_programs(self.pp, num_microbatches, self.vpp, fwd,
                              bwd)
            self._validated.add(key)
        plan = PipelinePlan(schedule=best,
                            num_microbatches=num_microbatches,
                            vpp=self.vpp, bubble_fraction=cands[best],
                            candidates=cands, stage_costs=costs)
        if self.current is None:
            self.current = plan
        return plan

    def maybe_replan(self, num_microbatches: int
                     ) -> Optional[PipelinePlan]:
        """Re-plan with hysteresis: switch only when the winner differs
        from the current schedule AND the modeled bubble improves by more
        than replan_margin (absolute). Loud log + counter on switch."""
        new = self.plan(num_microbatches)
        cur = self.current
        if cur is None or cur.schedule == new.schedule:
            self.current = new
            return None
        if cur.schedule not in new.candidates:
            # The running schedule has no modeled bubble under this
            # planner configuration (e.g. zero-bubble under vpp > 1,
            # which the combined-timeline model does not cover yet) —
            # a fabricated comparison would force-switch away from a
            # user-configured schedule on no real measurement. Stay put.
            return None
        cur_bubble = new.candidates[cur.schedule]
        if cur_bubble - new.bubble_fraction <= self.replan_margin:
            # No switch — but adopt the just-computed costs/candidates
            # under the RUNNING schedule so the exported gauges track
            # the live signal instead of the startup snapshot.
            self.current = dataclasses.replace(
                new, schedule=cur.schedule, bubble_fraction=cur_bubble)
            return None
        self.replans += 1
        logger.warning(
            "pp-planner RE-PLAN: schedule %r -> %r (modeled bubble "
            "%.4f -> %.4f at M=%d, stage costs %s)", cur.schedule,
            new.schedule, cur_bubble, new.bubble_fraction,
            num_microbatches,
            [round(c, 3) for c in new.stage_costs])
        self.current = new
        return new

    # -- observability -----------------------------------------------------
    def export_metrics(self):
        """Per-(stage, vstage) EWMA + straggler-z gauges into the shared
        telemetry registry (/metrics), plus the current plan's modeled
        bubble — the planner's input signal made observable (ISSUE 15
        satellite)."""
        for (s, v), val in sorted(self._ewma.items()):
            telemetry.set_gauge(
                telemetry.labeled("pp_stage_step_time_ewma_ms",
                                  stage=s, vstage=v),
                round(val * 1e3, 4))
            z = self._z.get((s, v))
            if z is not None and z.last_z is not None:
                telemetry.set_gauge(
                    telemetry.labeled("pp_stage_straggler_z",
                                      stage=s, vstage=v),
                    round(z.last_z, 4))
        if self.current is not None:
            telemetry.set_gauge("pp_plan_bubble_fraction",
                                round(self.current.bubble_fraction, 4))
            telemetry.set_gauge("pp_plan_schedule_index",
                                SCHEDULES.index(self.current.schedule))
        telemetry.set_gauge("pp_planner_replans_total", self.replans)
