"""MegaFBD analogue: forward/backward disaggregation onto disjoint sub-meshes.

Parity with the reference MegaFBD module (SURVEY §2.2): the reference splits
each pipeline stage into a forward instance and a backward instance on
different GPUs (rank parity picks fwd vs bwd, parallel_state.py:444-452; DP
is halved :453), forward ranks run grad-free forward
(forward_step_no_grad, schedules.py:355) and ship each input activation to
the paired backward rank (send_corresponding_forward :1866), which
recomputes forward WITH grad and runs backward
(forward_or_backward_pipelining_without_interleaving, schedules.py:2208).
A thread/bitvector coordinator arbitrates collectives
(virtual_tensor_parallel_communication.py:165-403).

TPU-native re-design (SURVEY §7: "forward-only meshes feeding backward
meshes ... the coordinator problem disappears (XLA schedules collectives)
but the placement policy remains"):

- The device set splits into a FORWARD mesh and a BACKWARD mesh (DP halved
  on each, exactly the reference's rank accounting).
- The forward mesh runs the grad-free forward (loss/metrics/MegaScope
  captures, NaN validation — everything the reference fwd instance
  produces); the backward mesh recomputes forward with grad and applies the
  update (the reference bwd instance's recompute-with-grad).
- The two dispatches are asynchronous: while the backward mesh grinds
  through grads for batch i, the forward mesh is already validating batch
  i+1 — the overlap MegaFBD buys, without controller ranks or thread-level
  collective emulation (the XLA runtime owns scheduling).
- Updated params stream back to the forward mesh each step
  (device_put across meshes rides ICI/DCN; the reference ships params
  implicitly by running both instances from the same checkpoint).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from megatronapp_tpu.config.parallel_config import ParallelConfig
from megatronapp_tpu.parallel.mesh import MeshContext, build_mesh


def split_fbd_meshes(parallel: ParallelConfig, devices=None
                     ) -> Tuple[MeshContext, MeshContext]:
    """Split devices into forward/backward halves (DP halved on each —
    reference assert parallel_state.py:453: DP must be even)."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    dp = parallel.infer_data_parallel(n)
    if dp % 2 != 0:
        raise ValueError(
            f"forward/backward disaggregation requires even data-parallel "
            f"degree (got dp={dp}) — reference parallel_state.py:453")
    half_cfg = dataclasses.replace(parallel, data_parallel=dp // 2,
                                   forward_backward_disaggregating=False)
    fwd_ctx = build_mesh(half_cfg, devices=devices[: n // 2])
    bwd_ctx = build_mesh(half_cfg, devices=devices[n // 2:])
    return fwd_ctx, bwd_ctx


class FBDExecutor:
    """Runs training with forward and backward on disjoint meshes.

    loss_fn(params, microbatch) -> (loss, metrics) as in make_train_step.
    """

    def __init__(self, loss_fn: Callable, optimizer, fwd_ctx: MeshContext,
                 bwd_ctx: MeshContext, state, state_shardings):
        self.fwd_ctx = fwd_ctx
        self.bwd_ctx = bwd_ctx
        self.optimizer = optimizer

        # Master state lives on the backward mesh.
        self.state = jax.device_put(
            jax.device_get(state),
            jax.tree.map(lambda s: _retarget(s, bwd_ctx), state_shardings))
        self._params_shardings_bwd = jax.tree.map(
            lambda s: _retarget(s, bwd_ctx), state_shardings)["params"]
        self._params_shardings_fwd = jax.tree.map(
            lambda s: _retarget(s, fwd_ctx), state_shardings)["params"]
        # Mirror of params on the forward mesh.
        self.params_fwd = jax.device_put(
            jax.device_get(self.state["params"]), self._params_shardings_fwd)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def fwd_only(params, batch_mb):
            # Grad-free forward over the microbatches (reference
            # forward_step_no_grad).
            def body(acc, micro):
                loss, _ = loss_fn(params, micro)
                return acc + loss, None
            total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                    batch_mb)
            return total / batch_mb["tokens"].shape[0]

        def bwd_step(state, batch_mb):
            # Microbatched grad accumulation (same math as the main path's
            # make_train_step scan).
            params = state["params"]

            def accum(carry, micro):
                g_acc, loss_acc = carry
                (loss, _), g = grad_fn(params, micro)
                return (jax.tree.map(lambda a, b: a + b.astype(a.dtype),
                                     g_acc, g), loss_acc + loss), None

            zeros = jax.tree.map(lambda q: jnp.zeros(q.shape, jnp.float32),
                                 params)
            (g_sum, loss_sum), _ = jax.lax.scan(
                accum, (zeros, jnp.zeros((), jnp.float32)), batch_mb)
            num_micro = batch_mb["tokens"].shape[0]
            grads = jax.tree.map(lambda g: g / num_micro, g_sum)
            updates, new_opt = optimizer.update(
                grads, state["opt_state"], params)
            new_params = jax.tree.map(
                lambda p, u: p + u.astype(p.dtype), params, updates)
            return ({"step": state["step"] + 1, "params": new_params,
                     "opt_state": new_opt}, loss_sum / num_micro)

        self._fwd_only = jax.jit(fwd_only)
        self._bwd_step = jax.jit(bwd_step, donate_argnums=(0,))

    def step(self, batch_mb: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """One disaggregated step over a microbatched batch
        [num_micro, mb, S]: dispatch grad-free forward on the fwd mesh and
        recompute+backward on the bwd mesh; both run concurrently (async
        dispatch — losses are returned as DEVICE arrays so steps pipeline;
        callers device_get only when logging), then params stream back to
        the fwd mesh."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        fwd_sh = NamedSharding(
            self.fwd_ctx.mesh,
            P(None, *self.fwd_ctx.batch_spec(seq_sharded=False)))
        bwd_sh = NamedSharding(
            self.bwd_ctx.mesh,
            P(None, *self.bwd_ctx.batch_spec(seq_sharded=False)))
        micro_fwd = jax.device_put(batch_mb, fwd_sh)
        micro_bwd = jax.device_put(batch_mb, bwd_sh)

        with self.fwd_ctx.mesh:
            fwd_loss = self._fwd_only(self.params_fwd, micro_fwd)
        with self.bwd_ctx.mesh:
            self.state, bwd_loss = self._bwd_step(self.state, micro_bwd)
        # Stream updated params to the forward mesh (the reference's fwd
        # instances likewise track their bwd twin's weights).
        self.params_fwd = jax.device_put(self.state["params"],
                                         self._params_shardings_fwd)
        return {"loss": bwd_loss, "fwd_loss": fwd_loss}


def _retarget(sharding, ctx: MeshContext):
    """Rebuild a NamedSharding against another mesh (same spec)."""
    from jax.sharding import NamedSharding
    return NamedSharding(ctx.mesh, sharding.spec)
