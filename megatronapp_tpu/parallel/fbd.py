"""MegaFBD analogue: forward/backward disaggregation onto disjoint sub-meshes.

Parity with the reference MegaFBD module (SURVEY §2.2): the reference splits
each pipeline stage into a forward instance and a backward instance on
different GPUs (rank parity picks fwd vs bwd, parallel_state.py:444-452; DP
is halved :453). Forward ranks run the grad-free forward
(forward_step_no_grad, schedules.py:355) and SHIP activations to the paired
backward rank (send_corresponding_forward, schedules.py:1866 →
p2p_communication.py:723), which completes the gradient computation
(forward_or_backward_pipelining_without_interleaving, schedules.py:2208).
A thread/bitvector coordinator arbitrates collectives
(virtual_tensor_parallel_communication.py:165-403).

TPU-native re-design (SURVEY §7: "forward-only meshes feeding backward
meshes ... the coordinator problem disappears (XLA schedules collectives)
but the placement policy remains"):

- The device set splits into a FORWARD mesh and a BACKWARD mesh (DP halved
  on each — the reference's rank accounting).
- Per microbatch, the forward mesh runs the vjp FORWARD pass and ships the
  pullback's residuals (the saved activations) to the backward mesh — the
  analogue of send_corresponding_forward, except the backward mesh applies
  the transposed computation DIRECTLY instead of recomputing the forward
  with grad (XLA autodiff makes the handoff exact: residuals + cotangent
  in, parameter grads out; nothing is computed twice).
- The two dispatch queues overlap WITHIN an optimizer step: while the
  backward mesh grinds through the pullback of microbatch m, the forward
  mesh is already computing microbatch m+1 — MegaFBD's overlap, without
  controller ranks or thread-level collective emulation (the XLA runtime
  owns scheduling, and the host loop never blocks between dispatches).
- Gradients accumulate on the backward mesh; the optimizer update runs
  there once per step and the new params stream back to the forward mesh
  (the reference ships params implicitly by running both instances from
  the same checkpoint).
- Composes with tp/pp/cp: the loss_fn (including the pipelined
  gpt_pipeline_loss) runs under each half-mesh's own compiler sharding; the
  vjp residual transfer retargets each leaf's NamedSharding spec onto the
  twin mesh (same axis names, disjoint devices).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from megatronapp_tpu.config.parallel_config import ParallelConfig
from megatronapp_tpu.parallel.mesh import MeshContext, build_mesh


def build_half_meshes(parallel_a: ParallelConfig, parallel_b: ParallelConfig,
                      devices) -> Tuple[MeshContext, MeshContext]:
    """Split a device list into two disjoint half-meshes (first half →
    parallel_a, second half → parallel_b). The shared sub-mesh
    construction behind both disaggregation subsystems: MegaFBD's
    forward/backward split here, and the serving-side prefill/decode
    split (inference/disagg.py, ISSUE 9)."""
    n = len(devices)
    ctx_a = build_mesh(parallel_a, devices=devices[: n // 2])
    ctx_b = build_mesh(parallel_b, devices=devices[n // 2:])
    return ctx_a, ctx_b


def split_fbd_meshes(parallel: ParallelConfig, devices=None
                     ) -> Tuple[MeshContext, MeshContext]:
    """Split devices into forward/backward halves (DP halved on each —
    reference assert parallel_state.py:453: DP must be even)."""
    if devices is None:
        devices = jax.devices()
    dp = parallel.infer_data_parallel(len(devices))
    if dp % 2 != 0:
        raise ValueError(
            f"forward/backward disaggregation requires even data-parallel "
            f"degree (got dp={dp}) — reference parallel_state.py:453")
    half_cfg = dataclasses.replace(parallel, data_parallel=dp // 2,
                                   forward_backward_disaggregating=False)
    fwd_ctx, bwd_ctx = build_half_meshes(half_cfg, half_cfg, devices)
    # Abstract-mesh collectives: the fwd pass's pullback must be executable
    # on the twin mesh (see MeshContext.shard_map_mesh).
    fwd_ctx.abstract_collectives = True
    bwd_ctx.abstract_collectives = True
    return fwd_ctx, bwd_ctx


def _retarget(sharding, ctx: MeshContext):
    """Rebuild a NamedSharding against another mesh (same spec)."""
    from jax.sharding import NamedSharding
    return NamedSharding(ctx.mesh, sharding.spec)


class FBDExecutor:
    """Runs training with forward and backward on disjoint meshes.

    loss_fn(params, batch, ctx) -> (loss, metrics); ctx is the half-mesh
    the call executes on (fwd mesh for the forward pass — its transposed
    pullback then runs on the bwd mesh).

    pipeline=True: loss_fn consumes the whole microbatched batch at once
    (the SPMD pipeline schedules microbatches internally), so one
    fwd/ship/bwd handoff happens per optimizer step.
    """

    def __init__(self, loss_fn: Callable, optimizer, fwd_ctx: MeshContext,
                 bwd_ctx: MeshContext, state, state_shardings,
                 pipeline: bool = False):
        self.fwd_ctx = fwd_ctx
        self.bwd_ctx = bwd_ctx
        self.optimizer = optimizer
        self.pipeline = pipeline
        self.shipped_bytes = 0

        # Master state lives on the backward mesh.
        self.state = jax.device_put(
            jax.device_get(state),
            jax.tree.map(lambda s: _retarget(s, bwd_ctx), state_shardings))
        self._params_shardings_fwd = jax.tree.map(
            lambda s: _retarget(s, fwd_ctx), state_shardings)["params"]
        # Mirror of params on the forward mesh.
        self.params_fwd = jax.device_put(
            jax.device_get(self.state["params"]), self._params_shardings_fwd)

        def fwd_one(params, micro):
            # vjp forward pass only (reference forward_step_no_grad, plus
            # residual stashing): loss + metrics + the pullback whose
            # pytree leaves are the saved activations.
            loss, pullback, aux = jax.vjp(
                lambda p: loss_fn(p, micro, fwd_ctx), params, has_aux=True)
            return loss, aux, pullback

        def bwd_accum(g_acc, loss_acc, pullback, loss):
            # Transposed pass on the shipped residuals: cotangent 1.0 on
            # the loss → parameter grads; accumulate in fp32.
            (g,) = pullback(jnp.ones((), jnp.float32))
            g_acc = jax.tree.map(lambda a, b: a + b.astype(a.dtype),
                                 g_acc, g)
            return g_acc, loss_acc + loss

        def apply_update(state, g_sum, loss_sum, inv_num_micro):
            params = state["params"]
            grads = jax.tree.map(lambda g: g * inv_num_micro, g_sum)
            import optax
            grad_norm = optax.global_norm(grads)
            updates, new_opt = optimizer.update(
                grads, state["opt_state"], params)
            new_params = jax.tree.map(
                lambda p, u: p + u.astype(p.dtype), params, updates)
            new_state = {"step": state["step"] + 1, "params": new_params,
                         "opt_state": new_opt}
            return new_state, loss_sum * inv_num_micro, grad_norm

        self._fwd_one = jax.jit(fwd_one)
        self._bwd_accum = jax.jit(bwd_accum, donate_argnums=(0, 1))
        self._apply = jax.jit(apply_update, donate_argnums=(0, 1))
        self._zeros = jax.jit(
            lambda p: jax.tree.map(
                lambda q: jnp.zeros(q.shape, jnp.float32), p))

    def _ship(self, pullback):
        """Move the pullback's residual leaves fwd→bwd mesh, preserving
        each leaf's partitioning (same axis names on the twin mesh). This
        is the activation handoff (reference p2p_communication.py:723).
        Shipped bytes accumulate in ``shipped_bytes`` for per-step
        accounting (DCN-budget visibility on real pods)."""
        leaves, treedef = jax.tree.flatten(pullback)
        self.shipped_bytes += sum(
            int(leaf.size) * leaf.dtype.itemsize for leaf in leaves
            if hasattr(leaf, "size"))
        moved = [jax.device_put(
            leaf, _retarget(leaf.sharding, self.bwd_ctx))
            for leaf in leaves]
        return jax.tree.unflatten(treedef, moved)

    def step(self, batch_mb: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """One disaggregated step over a microbatched batch [M, mb, S].

        The host loop dispatches fwd(m) and bwd(m-1) without blocking, so
        the forward mesh computes microbatch m while the backward mesh
        transposes microbatch m-1 (MegaFBD's overlap). Losses return as
        DEVICE arrays; callers device_get only when logging."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        num_micro = jax.tree.leaves(batch_mb)[0].shape[0]
        bwd_rep = NamedSharding(self.bwd_ctx.mesh, P())
        self.shipped_bytes = 0

        g_acc = self._zeros(self.state["params"])
        loss_acc = jax.device_put(jnp.zeros((), jnp.float32), bwd_rep)
        fwd_loss_sum = None
        if self.pipeline:
            # The pipeline loss consumes [M, mb, S] whole; one handoff.
            fwd_sh = NamedSharding(
                self.fwd_ctx.mesh,
                P(None, *self.fwd_ctx.batch_spec(seq_sharded=False)))
            micros = [jax.device_put(batch_mb, fwd_sh)]
            num_micro = 1
        else:
            fwd_sh = NamedSharding(
                self.fwd_ctx.mesh,
                P(*self.fwd_ctx.batch_spec(seq_sharded=False)))
            micros = [jax.device_put(
                jax.tree.map(lambda x: x[m], batch_mb), fwd_sh)
                for m in range(num_micro)]
        for micro in micros:
            loss, aux, pullback = self._fwd_one(self.params_fwd, micro)
            # Mean over microbatches (stays on the fwd mesh) so the
            # fwd/bwd loss cross-check compares like with like.
            fwd_loss_sum = (loss if fwd_loss_sum is None
                            else fwd_loss_sum + loss)
            # Ship residuals + per-microbatch loss to the backward mesh.
            pb_b = self._ship(pullback)
            loss_b = jax.device_put(loss, bwd_rep)
            g_acc, loss_acc = self._bwd_accum(g_acc, loss_acc, pb_b, loss_b)

        self.state, mean_loss, grad_norm = self._apply(
            self.state, g_acc, loss_acc, 1.0 / num_micro)
        # Stream updated params to the forward mesh for the next step.
        self.params_fwd = jax.device_put(self.state["params"],
                                         self._params_shardings_fwd)
        return {"loss": mean_loss,
                "fwd_loss": fwd_loss_sum / len(micros),
                "grad_norm": grad_norm,
                "shipped_bytes": self.shipped_bytes}

    def set_state(self, state):
        """Install a restored checkpoint state (bwd-mesh master + fwd
        params mirror)."""
        self.state = state
        self.params_fwd = jax.device_put(
            jax.device_get(state["params"]), self._params_shardings_fwd)
