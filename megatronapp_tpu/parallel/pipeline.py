"""SPMD pipeline parallelism over the 'pp' mesh axis.

Parity with /root/reference/megatron/core/pipeline_parallel/schedules.py
(1F1B :1918, interleaved VPP :856, no-pipelining :618) and
p2p_communication.py (:303 _communicate) — re-designed TPU-first:

Instead of imperative per-rank send/recv schedules, the whole pipeline is ONE
jitted SPMD program: a FULL-MANUAL ``shard_map`` over every mesh axis
(parallel/collectives.shard_map_compat), with a ``lax.scan`` over schedule
steps and a ring ``ppermute`` carrying activations stage→stage.
Differentiating the scan yields the reverse (backward) pipeline
automatically — the transpose of ppermute is the reverse ppermute — so XLA
schedules and overlaps what Megatron encodes by hand, and the 1F1B memory
profile is recovered with per-stage rematerialization (stage inputs are the
only per-step residuals).

Full manual (vs the earlier partial-auto region manual only over pp/cp):
on the jax 0.4.x builds this image ships, partial-auto manual regions
lower ppermute/axis_index through an SPMD path XLA:CPU aborts on
(parallel/overlap.py design notes), and nested shard_maps are unsupported
— so the body owns EVERY axis. The microbatch dim threads over (dp, ep)
when it divides evenly, sequence over cp (attention dispatches to the cp
ring impls directly via the ambient-manual check). tp has two modes:
``tp_shard=True`` (layouts passing overlap.tp_stage_eligible) shards the
activations along the SEQUENCE over tp between stages — [mb, S/tp, H]
residual streams (and, composing with cp > 1, [mb, S/(cp*tp), H]: the
pp x cp x tp composition, ISSUE 15), tp× smaller pp ppermute hops, stage
bodies running the parallel/overlap.py ring all-gather-matmul /
matmul-reduce-scatter primitives on per-shard weight slices (tp× fewer
stage FLOPs, collectives hidden under the GEMM chunks). Under cp > 1 the
QKV ring gathers only the cp-LOCAL sequence chunk and attention runs the
contiguous cp ring per tp head shard. Otherwise tp rides replicated
inside the body (each tp rank redundantly computes the stage — kept for
ineligible layouts; the tp-GSPMD sharding of the old partial-auto region
needed exactly the partial-auto mode this build aborts on). Stage
hand-offs emit per-step ``pp-overlap-permute`` MegaScan spans so the
schedule's comm is visible in the merged trace — and those spans are the
per-stage step-time signal the trace-driven planner
(parallel/schedule.Planner) mines for scheduling decisions.

The schedule is a per-stage instruction PROGRAM (parallel/schedule.py,
ISSUE 15), not a hard-coded loop: the scan body indexes clocked
(active, microbatch, chunk) tables at [step, stage]. For '1f1b'/'vpp'
the tables reproduce the unified closed-form schedule exactly
(u = t - stage, round r = u // (pp*vpp), chunk c = (u % (pp*vpp)) // pp,
m = r*pp + u % pp; bubble (pp-1)/(M*vpp) — reference
schedules.py:856-1780) and the backward stays the scan's autodiff
transpose. The activation emitted by the last stage at step t is
consumed by stage 0 at t+1 via the same ring ppermute, which is exactly
the chunk hand-off the reference implements with batched p2p ops.

schedule='zero-bubble' splits the backward into B = dgrad and W = wgrad
instructions (the ZB-H1 family): a custom_vjp wraps the stage program —
the forward scan additionally saves each (chunk, microbatch) stage INPUT,
and the hand-written backward scan executes the validated B/W program:
B recomputes the stage forward and pulls ONLY the activation cotangent
(the wgrad path is dead code in that vjp), sending it down the reverse
ring one hop per slot; W recomputes and pulls ONLY the weight cotangent
from the saved (input, output-cotangent) pair, accumulated into the grad
buffers at the program's deferred slots. All W's complete inside the
program, so the optimizer fence is structural and ZeRO-1 sees grads
identical to the fused backward (parity pinned ≤1e-6).

Virtual-stage layer placement matches the reference interleaved convention:
chunk c on stage s holds global layers [(c*pp + s) * Lc, ...) where
Lc = num_layers / (pp*vpp) (schedules.py chunk bookkeeping :1057-1098).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatronapp_tpu.config.parallel_config import (
    CP_AXIS, DP_AXIS, EP_AXIS, PP_AXIS, TP_AXIS,
)
from megatronapp_tpu.parallel.mesh import MeshContext


from megatronapp_tpu.parallel.collectives import (
    pvary, ring_span, shard_map_compat, span_tags, zeros_like_vma,
)

# MegaScan span name for the stage→stage ring hop (tracer GRANULARITY
# 'collective' set).
PP_OVERLAP_PERMUTE_EVENT = "pp-overlap-permute"


def reshape_params_for_pipeline(stacked_params, pp: int, vpp: int = 1):
    """[L, ...]-stacked layer params → [pp, vpp, L/(pp*vpp), ...] with the
    interleaved chunk→stage assignment (global layer (c*pp+s)*Lc + i ↦
    position [s, c, i])."""
    if isinstance(stacked_params, list):
        raise NotImplementedError(
            "heterogeneous per-layer configs (unstacked params) do not "
            "compose with pipeline parallelism; run hetero models with "
            "pp=1 (reference get_config_for_layer builds per-layer specs "
            "on one pipeline too)")

    def r(x):
        L = x.shape[0]
        Lc = L // (pp * vpp)
        # [L, ...] → [vpp, pp, Lc, ...] (chunk-major) → transpose to
        # [pp, vpp, Lc, ...].
        y = x.reshape(vpp, pp, Lc, *x.shape[1:])
        return jnp.swapaxes(y, 0, 1)

    return jax.tree.map(r, stacked_params)


def spmd_pipeline(
    stage_fn: Callable[[Any, jnp.ndarray, jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]],
    pipe_params: Any,
    h_mb: jnp.ndarray,
    ctx: MeshContext,
    num_microbatches: int,
    vpp: int = 1,
    compute_dtype=jnp.bfloat16,
    order_policy: str = "dfc",
    aux_mb: Any = None,
    tp_shard: bool = False,
    schedule: str = "1f1b",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Run the pipelined layer stack.

    order_policy — the MegaDPP scheduling policy (reference paper §5.2,
    shm_tensor_new_rdma.cpp:1478-1646 send-order traversal of the
    (chunk, microbatch) matrix), reinterpreted for the SPMD schedule:
      'dfc' (depth-first-chunk): the interleaved schedule — a round of pp
            microbatches traverses ALL vpp chunks before the next round.
            Bubble (pp-1)/(M*vpp); pp activations in flight per stage.
      'bfc' (breadth-first-chunk): all M microbatches pass through chunk c
            before chunk c+1 (sequential GPipe passes). Bubble
            vpp*(pp-1)/(M*vpp + vpp*(pp-1)); M boundary activations
            materialize between passes (cheaper steady-state VMEM, more HBM).

    stage_fn(chunk_params, h, layer_offset) -> (h, aux) processes one chunk
    (Lc layers) of one microbatch; it runs under compiler sharding for
    tp/dp/cp/ep. Rematerialization is stage_fn's responsibility (the block's
    remat_policy wraps each layer, so the schedule stores only per-layer
    inputs per in-flight microbatch — the 1F1B memory profile).

    aux_mb: optional pytree of [M, ...] per-microbatch side inputs (packed
    segment ids, per-token rope tables). Unlike activations these do NOT
    ride the stage ring — every stage indexes the microbatch it is
    currently processing directly (the schedule makes m a pure function of
    (step, stage)); stage_fn then takes a 4th argument with the indexed
    pytree. Leaves with a sequence axis (dim 2 of [M, mb, S, ...]) are
    cp-sharded like the activations.
    pipe_params: [pp, vpp, Lc, ...] pytree (leading axis sharded over pp).
    h_mb: [M, mb, S, H] microbatched hidden states (e.g. embeddings) — must
    be fp32 when pp > 1 (cast to compute_dtype happens inside; see body).
    tp_shard: run the stage body tp-SHARDED — activations enter/leave the
    region with the sequence dim sharded over tp ([mb, S/tp, H] inside;
    composing with cp > 1, over (cp, tp): [mb, S/(cp*tp), H]), stage_fn
    must thread tp_sharded=True into the transformer stack, and params
    gain a real tp entry in the grad-axes bookkeeping (each shard
    contributes a slice-local partial wgrad the transpose psums). Caller
    gates on overlap.tp_stage_eligible (divisible S/heads/ffn; under
    cp > 1 dense non-MLA stacks on the contiguous p2p ring).

    schedule — the instruction program the manual region executes
    (parallel/schedule.py): '1f1b' (interleaved automatically when
    vpp > 1), 'vpp' (alias that REQUIRES vpp > 1), or 'zero-bubble'
    (backward split into B=dgrad / W=wgrad steps via a custom_vjp whose
    hand-written backward scan executes the validated B/W program;
    grads match the fused backward, the weight update fences on all W).
    Returns (out_mb [M, mb, S, H] from the last stage, summed aux losses).
    """
    pp = ctx.pp
    M = num_microbatches
    if pp == 1:
        # No-pipelining fallback (reference schedules.py:618): plain scan
        # over microbatches with all layers merged back into one stack.
        merged = jax.tree.map(lambda x: x.reshape(-1, *x.shape[3:]),
                              pipe_params)

        if aux_mb is None:
            def body(acc, h):
                out, a = stage_fn(merged, h, 0)
                return acc + a, out

            aux, outs = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                     h_mb)
        else:
            def body(acc, inp):
                h, aux_m = inp
                out, a = stage_fn(merged, h, 0, aux_m)
                return acc + a, out

            aux, outs = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                     (h_mb, aux_mb))
        return outs, aux
    if order_policy not in ("dfc", "bfc"):
        raise ValueError(f"order_policy must be 'dfc' or 'bfc', got "
                         f"{order_policy!r}")
    from megatronapp_tpu.parallel import schedule as schedlib
    if schedule not in schedlib.SCHEDULES:
        raise ValueError(f"schedule must be one of {schedlib.SCHEDULES}, "
                         f"got {schedule!r}")
    if schedule == "vpp" and vpp <= 1:
        raise ValueError("schedule 'vpp' requires vpp > 1 (it is the "
                         "interleaved schedule; plain 1F1B is '1f1b')")
    zero_bubble = schedule == "zero-bubble"
    if zero_bubble and aux_mb:
        raise NotImplementedError(
            "zero-bubble does not compose with per-microbatch aux "
            "inputs (packed sequences) yet — run --pp-schedule 1f1b "
            "there")
    if vpp > 1 and order_policy == "dfc" and M % pp != 0:
        raise ValueError(
            f"interleaved (dfc) pipeline requires num_microbatches ({M}) "
            f"divisible by pipeline_parallel ({pp}); 'bfc' has no such "
            f"constraint")

    if vpp > 1 and order_policy == "bfc":
        # Breadth-first chunks: vpp sequential single-chunk pipeline passes;
        # the M boundary activations materialize (fp32, the shard_map
        # boundary dtype) between passes.
        lc = jax.tree.leaves(pipe_params)[0].shape[2]
        h = h_mb
        aux_total = jnp.zeros((), jnp.float32)
        out = None
        for c in range(vpp):
            chunk_params = jax.tree.map(lambda x, c=c: x[:, c:c + 1],
                                        pipe_params)

            def shifted(p_, x, off, *rest, _c=c):
                # Global layer index = (c*pp + stage)*Lc; the inner vpp=1
                # schedule supplies stage*Lc. *rest forwards the optional
                # per-microbatch aux pytree.
                return stage_fn(p_, x, off + _c * pp * lc, *rest)

            out, aux = spmd_pipeline(
                shifted, chunk_params, h, ctx, M, vpp=1,
                compute_dtype=compute_dtype, order_policy="dfc",
                aux_mb=aux_mb, tp_shard=tp_shard,
                schedule="1f1b" if schedule == "vpp" else schedule)
            aux_total = aux_total + aux
            h = out.astype(jnp.float32)
        return out, aux_total

    mesh = ctx.mesh
    total_steps = M * vpp + pp - 1
    # Clocked instruction tables (parallel/schedule.py): the scan indexes
    # [step, stage] instead of computing the closed-form schedule inline
    # — identical entries for '1f1b'/'vpp' (pinned in tests), and the
    # hook that lets zero-bubble (and planner-emitted programs) swap in
    # as data. Validated before anything executes them.
    f_tables = schedlib.forward_tables(pp, M, vpp)
    b_tables = schedlib.zb_backward_tables(pp, M, vpp) if zero_bubble \
        else None
    schedlib.validate_programs(pp, M, vpp, f_tables, b_tables)
    f_act_np, f_mb_np, f_ck_np = f_tables
    # Context parallelism composes INSIDE this (full-)manual region (nested
    # shard_maps are unreliable in this JAX build): with cp > 1 the body is
    # manual over cp too, sequence enters pre-sharded [.., S/cp, ..],
    # and attention calls the ring/a2a impls directly (context_attention
    # detects the ambient manual cp). The microbatch dim threads over
    # (dp, ep) when it divides evenly; otherwise it rides replicated
    # (identical math, redundant compute).
    cp = ctx.cp
    mb_size = h_mb.shape[1]
    dpep = ctx.dp * ctx.ep
    batch_axes = (DP_AXIS, EP_AXIS) if mb_size % dpep == 0 else None

    def body(params_local, h_mb_in, aux_mb_in):
        # params_local: [1, vpp, Lc, ...]; h_mb_in: [M, mb(/dp/ep), S(/cp), H].
        # h_mb_in MUST be fp32 at this boundary: its transpose-psum (the
        # pvary below) must not be a bf16 manual all-reduce (XLA:CPU bug —
        # see collectives.zeros_like_vma). Casting to the compute dtype
        # happens per injection, after the pvary.
        h_mb_in = pvary(h_mb_in, (PP_AXIS,))
        aux_mb_in = jax.tree.map(
            lambda a: pvary(a, (PP_AXIS,)), aux_mb_in)
        params_s = jax.tree.map(lambda x: x[0], params_local)
        # Params enter replicated over the token-splitting axes (cp seq
        # chunks; (dp, ep) microbatch shards) but every shard contributes a
        # partial wgrad: pvary's backward is the single fp32 psum per param
        # that IS the data-parallel/cp grad reduction. With the tp-sharded
        # stage body tp is a REAL entry too: each shard's wgrad covers only
        # its weight slice / seq chunk, and the psum assembles the full
        # grad. Replicated-tp bodies need no entry — they compute
        # redundantly, so per-tp-shard cotangents are already complete.
        grad_axes = (batch_axes or ()) + ((CP_AXIS,) if cp > 1 else ()) \
            + ((TP_AXIS,) if tp_shard else ())
        if grad_axes:
            params_s = jax.tree.map(
                lambda p: pvary(p, grad_axes), params_s)
        layers_per_chunk = jax.tree.leaves(params_s)[0].shape[1]
        mb_shape = h_mb_in.shape[1:]

        def chunk_slice(params_s_, chunk):
            return jax.tree.map(
                lambda p: jax.lax.dynamic_index_in_dim(p, chunk,
                                                       keepdims=False),
                params_s_)

        def forward_scan(params_s_, h_mb_, save_inputs, consts=None):
            """Execute the forward instruction program. save_inputs
            (zero-bubble) additionally records each (chunk, microbatch)
            stage INPUT — the residual the hand-written B/W backward
            rematerializes from. consts: the closure-converted stage
            callable's hoisted values (zero-bubble path — inside the
            custom_vjp every captured tracer must be an explicit arg on
            this jax build)."""
            # Tables as numpy → real jit constants wherever this traces.
            f_act = jnp.asarray(f_act_np)
            f_mbt = jnp.asarray(f_mb_np)
            f_ckt = jnp.asarray(f_ck_np)
            state = zeros_like_vma(mb_shape, compute_dtype, h_mb_)
            outputs = zeros_like_vma(h_mb_.shape, compute_dtype, h_mb_)
            aux = zeros_like_vma((), jnp.float32, h_mb_)
            carry = (state, outputs, aux)
            if save_inputs:
                xs_buf = zeros_like_vma((vpp, M) + mb_shape,
                                        compute_dtype, h_mb_)
                carry = carry + (xs_buf,)
            stage = jax.lax.axis_index(PP_AXIS)

            def step(carry, t):
                if save_inputs:
                    state, outputs, aux, xs_buf = carry
                else:
                    state, outputs, aux = carry
                active = f_act[t, stage]
                m_safe = f_mbt[t, stage]
                chunk = f_ckt[t, stage]

                # Stage 0 injects a fresh microbatch while running chunk
                # 0; otherwise consume the ring state.
                inject = jax.lax.dynamic_index_in_dim(h_mb_, m_safe,
                                                      keepdims=False)
                inject = inject.astype(compute_dtype)
                x = jnp.where((stage == 0) & (chunk == 0), inject, state)
                if save_inputs:
                    zi = (0,) * len(mb_shape)
                    prev_x = jax.lax.dynamic_slice(
                        xs_buf, (chunk, m_safe) + zi,
                        (1, 1) + mb_shape)[0, 0]
                    xs_buf = jax.lax.dynamic_update_slice(
                        xs_buf, jnp.where(active, x, prev_x)[None, None],
                        (chunk, m_safe) + zi)

                chunk_params = chunk_slice(params_s_, chunk)
                layer_offset = (chunk * pp + stage) * layers_per_chunk
                if consts is not None:
                    y, a = closed_stage(chunk_params, x, layer_offset,
                                        *consts)
                else:
                    # Tag every ring span the stage body emits (the
                    # tp-sharded body's tp-overlap-* rings) so
                    # in-pipeline hops are distinguishable from
                    # top-level tp overlap in merged traces.
                    with span_tags(region="pp-stage"):
                        if aux_mb_in:
                            aux_m = jax.tree.map(
                                lambda a: jax.lax.dynamic_index_in_dim(
                                    a, m_safe, keepdims=False),
                                aux_mb_in)
                            y, a = stage_fn(chunk_params, x,
                                            layer_offset, aux_m)
                        else:
                            y, a = stage_fn(chunk_params, x,
                                            layer_offset)
                aux = aux + jnp.where(active, a, 0.0)

                # Last stage, last chunk → collect output.
                collect = active & (stage == pp - 1) & (chunk == vpp - 1)
                prev = jax.lax.dynamic_index_in_dim(outputs, m_safe,
                                                    keepdims=False)
                outputs = jax.lax.dynamic_update_index_in_dim(
                    outputs, jnp.where(collect, y, prev), m_safe, 0)

                # Stage hand-off: one ring hop per schedule step. The
                # span makes the exposed hop visible per pp rank in
                # MegaScan traces (t is traced — ring_span threads it
                # into the callback); trace/detect.stage_step_gaps mines
                # the inter-hop gaps as the planner's per-stage signal.
                # Caveat (this jax build): scan linearization under
                # jax.grad drops in-scan debug callbacks, so these spans
                # appear in forward/eval executions; the cp/moe spans
                # inside the remat'd layer bodies survive training steps
                # too.
                ring_span(PP_OVERLAP_PERMUTE_EVENT, "B", y, PP_AXIS,
                          step=t, op="pp-schedule")
                state = jax.lax.ppermute(
                    y, PP_AXIS, [(i, (i + 1) % pp) for i in range(pp)])
                ring_span(PP_OVERLAP_PERMUTE_EVENT, "E", state, PP_AXIS,
                          step=t, op="pp-schedule")
                new_carry = (state, outputs, aux)
                if save_inputs:
                    new_carry = new_carry + (xs_buf,)
                return new_carry, None

            carry, _ = jax.lax.scan(step, carry,
                                    jnp.arange(total_steps))
            if save_inputs:
                state, outputs, aux, xs_buf = carry
                return outputs, aux, xs_buf
            state, outputs, aux = carry
            return outputs, aux, None

        if not zero_bubble:
            outputs, aux, _ = forward_scan(params_s, h_mb_in, False)
        else:
            # Hoist whatever the caller's stage_fn closed over (rope
            # tables etc.) into explicit custom_vjp inputs — tracer
            # consts inside a custom_vjp jaxpr fail to lower on this
            # jax build, and the closure-free callable is what lets the
            # hand-written backward pull dgrad and wgrad separately.
            # CONTRACT: hoisted consts receive ZERO cotangents from
            # zb_bwd — everything differentiable (learned tables,
            # adapters) MUST ride chunk_params, never the stage_fn
            # closure, or its gradients silently vanish under
            # zero-bubble while 1f1b trains them.
            def _stage(chunk_params, xx, off):
                with span_tags(region="pp-stage"):
                    return stage_fn(chunk_params, xx, off)

            closed_stage, stage_consts = jax.closure_convert(
                _stage, chunk_slice(params_s, 0),
                jnp.zeros(mb_shape, compute_dtype),
                jnp.asarray(0, jnp.int32))
            # Per-slot dispatch mode: when the stage BODY is
            # collective-free (no tp-sharded rings, no cp ring, no moe
            # ep a2a — dp only shards the microbatch dim and its grad
            # psum lives at the region transpose, outside the branches)
            # each backward slot runs exactly its program instruction
            # via lax.switch: stages taking different branches cannot
            # diverge on a collective because there are none inside.
            # With collectives in the body, XLA:CPU's rendezvous spans
            # EVERY device listed in the instruction's groups —
            # diverging branches deadlock — so both vjps run
            # unconditionally and the program masks which one lands
            # (redundant masked compute; an MPMD runtime has no such
            # constraint — the bubble model carries the perf claim,
            # parity carries correctness).
            zb_switch = (not tp_shard) and ctx.cp == 1 and ctx.ep == 1
            if not zb_switch:
                # Trace-time log (once per compiled shape): the user
                # asked for zero-bubble on a mesh where the SPMD
                # realization costs ~2x backward compute — say so
                # instead of silently regressing step time (the
                # planner refuses to auto-apply it here; a static
                # --pp-schedule zero-bubble is honored for parity/
                # MPMD-model work but is not a CPU/SPMD perf win).
                import logging
                logging.getLogger(__name__).warning(
                    "zero-bubble runs in MASKED dual-vjp dispatch on "
                    "this mesh (collectives inside the stage body): "
                    "both backward vjps execute every slot — ~2x "
                    "backward compute vs the fused transpose; the "
                    "modeled bubble win applies to MPMD runtimes, not "
                    "this SPMD realization")
            outputs, aux = _make_zb_core(
                forward_scan, chunk_slice, closed_stage,
                layers_per_chunk, mb_shape, b_tables, zb_switch)(
                    params_s, h_mb_in, tuple(stage_consts))
        # Sum aux losses across stages; average over the token-splitting
        # shards (cp seq chunks, (dp, ep) microbatch shards), whose aux
        # terms are per-local-token means. Outputs live on the last stage.
        red_axes = (PP_AXIS,) + ((CP_AXIS,) if cp > 1 else ()) \
            + (batch_axes or ())
        denom = cp * (dpep if batch_axes else 1)
        aux = jax.lax.psum(aux, red_axes) / denom
        return outputs[None], aux[None]

    def _make_zb_core(forward_scan, chunk_slice, closed_stage,
                      layers_per_chunk, mb_shape, b_tables, zb_switch):
        """Zero-bubble executor: custom_vjp around the stage program.

        fwd — the forward instruction scan, additionally saving every
        (chunk, microbatch) stage input (the only residual besides the
        params). bwd — a hand-written scan over the VALIDATED B/W
        program: B rematerializes the stage forward and pulls ONLY the
        activation cotangent (closing over the params makes the wgrad
        path dead code in that vjp), ships it down the reverse ring one
        hop per slot, and parks the incoming output-cotangent for its W;
        W rematerializes and pulls ONLY the weight cotangent from the
        saved (input, cotangent) pair, accumulating into the grad
        buffers at the program's deferred slot. Grads therefore equal
        the fused backward (fp32-accumulation order aside — parity
        pinned ≤1e-6); the scan ends only after every W, so the weight
        update is fenced on all W done and ZeRO-1 slices identical
        grads. The ppermute runs unconditionally every slot (outside
        the lax.switch) — stages in different branches never diverge on
        the pp collective, and a stage's tp/cp ring collectives stay
        within its own (same-branch) shard group."""
        kind_np, bmb_np, bck_np = b_tables

        @jax.custom_vjp
        def zb_core(params_s_, h_mb_, consts):
            outputs, aux, _ = forward_scan(params_s_, h_mb_, False,
                                           consts)
            return outputs, aux

        def zb_fwd(params_s_, h_mb_, consts):
            outputs, aux, xs_buf = forward_scan(params_s_, h_mb_, True,
                                                consts)
            return (outputs, aux), (params_s_, xs_buf, consts)

        def zb_bwd(res, cot):
            params_s_, xs_buf, consts = res
            d_out, d_aux = cot
            stage = jax.lax.axis_index(PP_AXIS)
            kind_t = jnp.asarray(kind_np)
            bmb_t = jnp.asarray(bmb_np)
            bck_t = jnp.asarray(bck_np)
            zi = (0,) * len(mb_shape)

            d_state0 = zeros_like_vma(mb_shape, compute_dtype, d_out)
            dy_buf0 = zeros_like_vma((vpp, M) + mb_shape, compute_dtype,
                                     d_out)
            d_h0 = zeros_like_vma((M,) + mb_shape, jnp.float32, d_out)
            d_params0 = jax.tree.map(
                lambda p: zeros_like_vma(p.shape, p.dtype, d_out),
                params_s_)

            def bstep(carry, t):
                d_state, dy_buf, d_params, d_h = carry
                kind = kind_t[t, stage]
                m = bmb_t[t, stage]
                c = bck_t[t, stage]
                x_m = jax.lax.dynamic_slice(
                    xs_buf, (c, m) + zi, (1, 1) + mb_shape)[0, 0]
                chunk_params = chunk_slice(params_s_, c)
                layer_offset = (c * pp + stage) * layers_per_chunk
                # The top of each cotangent wavefront consumes the
                # OUTPUT cotangent; every other B consumes what the
                # reverse ring delivered last slot (program-validated).
                top = (stage == pp - 1) & (c == vpp - 1)
                dy_in = jnp.where(
                    top,
                    jax.lax.dynamic_index_in_dim(d_out, m,
                                                 keepdims=False),
                    d_state)

                def f_of_x(xx):
                    return closed_stage(chunk_params, xx, layer_offset,
                                        *consts)

                def f_of_p(p_):
                    return closed_stage(p_, x_m, layer_offset, *consts)

                def nop_branch(_):
                    emit = zeros_like_vma(mb_shape, compute_dtype,
                                          d_state)
                    return emit, dy_buf, d_params, d_h

                def b_branch(_):
                    _, pull = jax.vjp(f_of_x, x_m)
                    (dx,) = pull((dy_in, d_aux))
                    dy_buf2 = jax.lax.dynamic_update_slice(
                        dy_buf, dy_in[None, None], (c, m) + zi)
                    # Stage 0 / chunk 0 closes the chain: its dx is the
                    # injected microbatch's cotangent (fp32 boundary).
                    first = (stage == 0) & (c == 0)
                    prev = jax.lax.dynamic_index_in_dim(
                        d_h, m, keepdims=False)
                    d_h2 = jax.lax.dynamic_update_index_in_dim(
                        d_h,
                        jnp.where(first,
                                  prev + dx.astype(jnp.float32), prev),
                        m, 0)
                    return dx, dy_buf2, d_params, d_h2

                def w_branch(_):
                    dy_m = jax.lax.dynamic_slice(
                        dy_buf, (c, m) + zi, (1, 1) + mb_shape)[0, 0]
                    _, pull = jax.vjp(f_of_p, chunk_params)
                    (dp,) = pull((dy_m, d_aux))

                    def acc(a_, g_):
                        cur = jax.lax.dynamic_index_in_dim(
                            a_, c, keepdims=False)
                        return jax.lax.dynamic_update_index_in_dim(
                            a_, cur + g_.astype(a_.dtype), c, 0)

                    d_params2 = jax.tree.map(acc, d_params, dp)
                    emit = zeros_like_vma(mb_shape, compute_dtype,
                                          d_state)
                    return emit, dy_buf, d_params2, d_h

                if zb_switch:
                    emit, dy_buf2, d_params2, d_h2 = jax.lax.switch(
                        kind, [nop_branch, b_branch, w_branch], 0)
                else:
                    # Masked (uniform) dispatch: every device runs both
                    # vjps in the same order — no collective divergence
                    # — and the program's kind masks which one lands.
                    is_b = kind == schedlib.KIND_B
                    is_w = kind == schedlib.KIND_W
                    _, pull_x = jax.vjp(f_of_x, x_m)
                    (dx,) = pull_x((dy_in, d_aux))
                    dy_m = jax.lax.dynamic_slice(
                        dy_buf, (c, m) + zi, (1, 1) + mb_shape)[0, 0]
                    _, pull_p = jax.vjp(f_of_p, chunk_params)
                    (dp,) = pull_p((dy_m, d_aux))
                    zero_emit = zeros_like_vma(mb_shape, compute_dtype,
                                               d_state)
                    emit = jnp.where(is_b, dx, zero_emit)
                    dy_buf2 = jax.lax.dynamic_update_slice(
                        dy_buf,
                        jnp.where(is_b, dy_in, dy_m)[None, None],
                        (c, m) + zi)
                    first = (stage == 0) & (c == 0)
                    prev_h = jax.lax.dynamic_index_in_dim(
                        d_h, m, keepdims=False)
                    d_h2 = jax.lax.dynamic_update_index_in_dim(
                        d_h,
                        jnp.where(is_b & first,
                                  prev_h + dx.astype(jnp.float32),
                                  prev_h), m, 0)

                    def acc_masked(a_, g_):
                        cur = jax.lax.dynamic_index_in_dim(
                            a_, c, keepdims=False)
                        return jax.lax.dynamic_update_index_in_dim(
                            a_,
                            jnp.where(is_w, cur + g_.astype(a_.dtype),
                                      cur), c, 0)

                    d_params2 = jax.tree.map(acc_masked, d_params, dp)
                ring_span(PP_OVERLAP_PERMUTE_EVENT, "B", emit, PP_AXIS,
                          step=t, op="pp-zb-bwd")
                d_state = jax.lax.ppermute(
                    emit, PP_AXIS,
                    [(i, (i - 1) % pp) for i in range(pp)])
                ring_span(PP_OVERLAP_PERMUTE_EVENT, "E", d_state,
                          PP_AXIS, step=t, op="pp-zb-bwd")
                return (d_state, dy_buf2, d_params2, d_h2), None

            (d_state, dy_buf, d_params, d_h), _ = jax.lax.scan(
                bstep, (d_state0, dy_buf0, d_params0, d_h0),
                jnp.arange(kind_np.shape[0]))
            # The hoisted stage consts (rope tables) take zero
            # cotangents — nothing in the stack differentiates them.
            d_consts = tuple(
                zeros_like_vma(cst.shape, cst.dtype, d_out)
                for cst in consts)
            return d_params, d_h, d_consts

        zb_core.defvjp(zb_fwd, zb_bwd)
        return zb_core

    if tp_shard and aux_mb:
        raise NotImplementedError(
            "tp_shard does not compose with per-microbatch aux inputs "
            "(packed sequences) yet — callers keep tp-replicated there")
    # With the tp-sharded stage body the seq dim shards over tp at the
    # region boundary ((cp, tp) jointly when cp > 1 — the pp x cp x tp
    # composition): each shard receives/returns its [.., S/tp, H] (or
    # [.., S/(cp*tp), H]) chunk, the transpose delivers REAL per-shard
    # output cotangents, and the pp ring hops inside carry tp× less data.
    seq_axes = (() if cp <= 1 else (CP_AXIS,)) \
        + ((TP_AXIS,) if tp_shard else ())
    cp_spec = (seq_axes if len(seq_axes) > 1
               else (seq_axes[0] if seq_axes else None))
    h_spec = P(None, batch_axes, cp_spec)
    out_spec = P(PP_AXIS, None, batch_axes, cp_spec)
    aux_mb = {} if aux_mb is None else aux_mb

    # Leaves [M, mb, S, ...]: microbatch axis (dim 1) over (dp, ep),
    # sequence axis (dim 2) cp-sharded. Lower-rank leaves (e.g. a per-
    # microbatch [M, mb] scalar input) take the prefix of the spec.
    def _aux_spec(a):
        dims = [None, batch_axes, cp_spec] + [None] * max(0, a.ndim - 3)
        return P(*dims[:a.ndim])

    aux_specs = jax.tree.map(_aux_spec, aux_mb)
    # manual-ok: this call CREATES the pipeline's manual region (the one
    # the stage-body modules execute inside) — it is not nested
    sm = jax.jit(shard_map_compat(
        body, ctx.shard_map_mesh,
        in_specs=(P(PP_AXIS), h_spec, aux_specs),
        out_specs=(out_spec, P(PP_AXIS))))
    outputs_all, aux_all = sm(pipe_params, h_mb, aux_mb)
    return outputs_all[-1], aux_all[0]
