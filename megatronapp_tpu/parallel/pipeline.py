"""SPMD pipeline parallelism over the 'pp' mesh axis.

Parity with /root/reference/megatron/core/pipeline_parallel/schedules.py
(1F1B :1918, interleaved VPP :856, no-pipelining :618) and
p2p_communication.py (:303 _communicate) — re-designed TPU-first:

Instead of imperative per-rank send/recv schedules, the whole pipeline is ONE
jitted SPMD program: a ``shard_map`` manual only over 'pp'
(axis_names={'pp'}; tp/dp/cp/ep stay compiler-sharded inside the body), with
a ``lax.scan`` over schedule steps and a ring ``ppermute`` carrying
activations stage→stage. Differentiating the scan yields the reverse
(backward) pipeline automatically — the transpose of ppermute is the reverse
ppermute — so XLA schedules and overlaps what Megatron encodes by hand, and
the 1F1B memory profile is recovered with per-stage rematerialization
(stage inputs are the only per-step residuals).

Unified schedule (steps t = 0..M*vpp + pp - 2), u = t - stage:
  round r = u // (pp*vpp), within-round w = u % (pp*vpp),
  chunk c = w // pp, microbatch m = r*pp + (w % pp).
vpp=1 degenerates to the non-interleaved schedule (inject every step,
chunk 0); vpp>1 is the interleaved/circular schedule with the familiar
bubble reduction (pp-1)/(M*vpp) — reference schedules.py:856-1780. The
activation emitted by the last stage at step t is consumed by stage 0 at
t+1 via the same ring ppermute, which is exactly the chunk hand-off the
reference implements with batched p2p ops.

Virtual-stage layer placement matches the reference interleaved convention:
chunk c on stage s holds global layers [(c*pp + s) * Lc, ...) where
Lc = num_layers / (pp*vpp) (schedules.py chunk bookkeeping :1057-1098).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatronapp_tpu.config.parallel_config import CP_AXIS, PP_AXIS
from megatronapp_tpu.parallel.mesh import MeshContext


from megatronapp_tpu.parallel.collectives import zeros_like_vma


def reshape_params_for_pipeline(stacked_params, pp: int, vpp: int = 1):
    """[L, ...]-stacked layer params → [pp, vpp, L/(pp*vpp), ...] with the
    interleaved chunk→stage assignment (global layer (c*pp+s)*Lc + i ↦
    position [s, c, i])."""
    if isinstance(stacked_params, list):
        raise NotImplementedError(
            "heterogeneous per-layer configs (unstacked params) do not "
            "compose with pipeline parallelism; run hetero models with "
            "pp=1 (reference get_config_for_layer builds per-layer specs "
            "on one pipeline too)")

    def r(x):
        L = x.shape[0]
        Lc = L // (pp * vpp)
        # [L, ...] → [vpp, pp, Lc, ...] (chunk-major) → transpose to
        # [pp, vpp, Lc, ...].
        y = x.reshape(vpp, pp, Lc, *x.shape[1:])
        return jnp.swapaxes(y, 0, 1)

    return jax.tree.map(r, stacked_params)


def spmd_pipeline(
    stage_fn: Callable[[Any, jnp.ndarray, jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]],
    pipe_params: Any,
    h_mb: jnp.ndarray,
    ctx: MeshContext,
    num_microbatches: int,
    vpp: int = 1,
    compute_dtype=jnp.bfloat16,
    order_policy: str = "dfc",
    aux_mb: Any = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Run the pipelined layer stack.

    order_policy — the MegaDPP scheduling policy (reference paper §5.2,
    shm_tensor_new_rdma.cpp:1478-1646 send-order traversal of the
    (chunk, microbatch) matrix), reinterpreted for the SPMD schedule:
      'dfc' (depth-first-chunk): the interleaved schedule — a round of pp
            microbatches traverses ALL vpp chunks before the next round.
            Bubble (pp-1)/(M*vpp); pp activations in flight per stage.
      'bfc' (breadth-first-chunk): all M microbatches pass through chunk c
            before chunk c+1 (sequential GPipe passes). Bubble
            vpp*(pp-1)/(M*vpp + vpp*(pp-1)); M boundary activations
            materialize between passes (cheaper steady-state VMEM, more HBM).

    stage_fn(chunk_params, h, layer_offset) -> (h, aux) processes one chunk
    (Lc layers) of one microbatch; it runs under compiler sharding for
    tp/dp/cp/ep. Rematerialization is stage_fn's responsibility (the block's
    remat_policy wraps each layer, so the schedule stores only per-layer
    inputs per in-flight microbatch — the 1F1B memory profile).

    aux_mb: optional pytree of [M, ...] per-microbatch side inputs (packed
    segment ids, per-token rope tables). Unlike activations these do NOT
    ride the stage ring — every stage indexes the microbatch it is
    currently processing directly (the schedule makes m a pure function of
    (step, stage)); stage_fn then takes a 4th argument with the indexed
    pytree. Leaves with a sequence axis (dim 2 of [M, mb, S, ...]) are
    cp-sharded like the activations.
    pipe_params: [pp, vpp, Lc, ...] pytree (leading axis sharded over pp).
    h_mb: [M, mb, S, H] microbatched hidden states (e.g. embeddings) — must
    be fp32 when pp > 1 (cast to compute_dtype happens inside; see body).
    Returns (out_mb [M, mb, S, H] from the last stage, summed aux losses).
    """
    pp = ctx.pp
    M = num_microbatches
    if pp == 1:
        # No-pipelining fallback (reference schedules.py:618): plain scan
        # over microbatches with all layers merged back into one stack.
        merged = jax.tree.map(lambda x: x.reshape(-1, *x.shape[3:]),
                              pipe_params)

        if aux_mb is None:
            def body(acc, h):
                out, a = stage_fn(merged, h, 0)
                return acc + a, out

            aux, outs = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                     h_mb)
        else:
            def body(acc, inp):
                h, aux_m = inp
                out, a = stage_fn(merged, h, 0, aux_m)
                return acc + a, out

            aux, outs = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                     (h_mb, aux_mb))
        return outs, aux
    if order_policy not in ("dfc", "bfc"):
        raise ValueError(f"order_policy must be 'dfc' or 'bfc', got "
                         f"{order_policy!r}")
    if vpp > 1 and order_policy == "dfc" and M % pp != 0:
        raise ValueError(
            f"interleaved (dfc) pipeline requires num_microbatches ({M}) "
            f"divisible by pipeline_parallel ({pp}); 'bfc' has no such "
            f"constraint")

    if vpp > 1 and order_policy == "bfc":
        # Breadth-first chunks: vpp sequential single-chunk pipeline passes;
        # the M boundary activations materialize (fp32, the shard_map
        # boundary dtype) between passes.
        lc = jax.tree.leaves(pipe_params)[0].shape[2]
        h = h_mb
        aux_total = jnp.zeros((), jnp.float32)
        out = None
        for c in range(vpp):
            chunk_params = jax.tree.map(lambda x, c=c: x[:, c:c + 1],
                                        pipe_params)

            def shifted(p_, x, off, *rest, _c=c):
                # Global layer index = (c*pp + stage)*Lc; the inner vpp=1
                # schedule supplies stage*Lc. *rest forwards the optional
                # per-microbatch aux pytree.
                return stage_fn(p_, x, off + _c * pp * lc, *rest)

            out, aux = spmd_pipeline(
                shifted, chunk_params, h, ctx, M, vpp=1,
                compute_dtype=compute_dtype, order_policy="dfc",
                aux_mb=aux_mb)
            aux_total = aux_total + aux
            h = out.astype(jnp.float32)
        return out, aux_total

    mesh = ctx.mesh
    total_steps = M * vpp + pp - 1
    cycle = pp * vpp
    # Context parallelism composes by WIDENING this manual region (nested
    # shard_maps are unreliable in this JAX build): with cp > 1 the body is
    # manual over both pp and cp, sequence enters pre-sharded [.., S/cp, ..],
    # and attention calls the ring/a2a impls directly (context_attention
    # detects the ambient manual cp).
    cp = ctx.cp
    manual_axes = {PP_AXIS} | ({CP_AXIS} if cp > 1 else set())

    def body(params_local, h_mb_in, aux_mb_in):
        # params_local: [1, vpp, Lc, ...]; h_mb_in: [M, mb, S(/cp), H].
        # h_mb_in MUST be fp32 at this boundary: its transpose-psum (and the
        # pcast below) must not be a bf16 manual all-reduce (XLA:CPU bug —
        # see collectives.zeros_like_vma). Casting to the compute dtype
        # happens per injection, after the pcast.
        h_mb_in = jax.lax.pcast(h_mb_in, (PP_AXIS,), to="varying")
        aux_mb_in = jax.tree.map(
            lambda a: jax.lax.pcast(a, (PP_AXIS,), to="varying"), aux_mb_in)
        stage = jax.lax.axis_index(PP_AXIS)
        params_s = jax.tree.map(lambda x: x[0], params_local)
        if cp > 1:
            # Make params cp-varying up front: otherwise every bf16 use of a
            # cp-invariant param inside the stage transposes to a bf16
            # psum_invariant over cp (the XLA:CPU crash). Params are fp32
            # here, so this pcast's transpose is a single fp32 psum per
            # param — which is also exactly the cp grad reduction.
            params_s = jax.tree.map(
                lambda p: jax.lax.pcast(p, (CP_AXIS,), to="varying"),
                params_s)
        layers_per_chunk = jax.tree.leaves(params_s)[0].shape[1]
        mb_shape = h_mb_in.shape[1:]

        state = zeros_like_vma(mb_shape, compute_dtype, h_mb_in)
        outputs = zeros_like_vma(h_mb_in.shape, compute_dtype, h_mb_in)
        aux = zeros_like_vma((), jnp.float32, h_mb_in)

        def step(carry, t):
            state, outputs, aux = carry
            u = t - stage
            r = u // cycle
            w = u % cycle
            chunk = w // pp
            m = r * pp + (w % pp)
            active = (u >= 0) & (m >= 0) & (m < M)
            m_safe = jnp.clip(m, 0, M - 1)

            # Stage 0 injects a fresh microbatch while running chunk 0;
            # otherwise consume the ring state.
            inject = jax.lax.dynamic_index_in_dim(h_mb_in, m_safe,
                                                  keepdims=False)
            inject = inject.astype(compute_dtype)
            x = jnp.where((stage == 0) & (chunk == 0), inject, state)

            chunk_params = jax.tree.map(
                lambda p: jax.lax.dynamic_index_in_dim(p, chunk,
                                                       keepdims=False),
                params_s)
            layer_offset = (chunk * pp + stage) * layers_per_chunk
            if aux_mb_in:
                aux_m = jax.tree.map(
                    lambda a: jax.lax.dynamic_index_in_dim(
                        a, m_safe, keepdims=False), aux_mb_in)
                y, a = stage_fn(chunk_params, x, layer_offset, aux_m)
            else:
                y, a = stage_fn(chunk_params, x, layer_offset)
            aux = aux + jnp.where(active, a, 0.0)

            # Last stage, last chunk → collect output.
            collect = active & (stage == pp - 1) & (chunk == vpp - 1)
            prev = jax.lax.dynamic_index_in_dim(outputs, m_safe,
                                                keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(collect, y, prev), m_safe, 0)

            state = jax.lax.ppermute(
                y, PP_AXIS, [(i, (i + 1) % pp) for i in range(pp)])
            return (state, outputs, aux), None

        (state, outputs, aux), _ = jax.lax.scan(
            step, (state, outputs, aux), jnp.arange(total_steps))
        # Sum aux losses across stages (and average over cp shards, whose
        # aux terms are per-local-token means); outputs live on the last
        # stage.
        if cp > 1:
            aux = jax.lax.psum(aux, (PP_AXIS, CP_AXIS)) / cp
        else:
            aux = jax.lax.psum(aux, PP_AXIS)
        return outputs[None], aux[None]

    h_spec = P(None, None, CP_AXIS) if cp > 1 else P(None)
    out_spec = (P(PP_AXIS, None, None, CP_AXIS) if cp > 1
                else P(PP_AXIS))
    aux_mb = {} if aux_mb is None else aux_mb
    if cp > 1:
        # Leaves [M, mb, S, ...]: sequence axis (dim 2) cp-sharded.
        aux_specs = jax.tree.map(
            lambda a: P(*([None, None, CP_AXIS]
                          + [None] * (a.ndim - 3))), aux_mb)
    else:
        aux_specs = jax.tree.map(lambda a: P(None), aux_mb)
    sm = jax.jit(jax.shard_map(
        body, mesh=ctx.shard_map_mesh,
        in_specs=(P(PP_AXIS), h_spec, aux_specs),
        out_specs=(out_spec, P(PP_AXIS)),
        axis_names=manual_axes))
    outputs_all, aux_all = sm(pipe_params, h_mb, aux_mb)
    return outputs_all[-1], aux_all[0]
