"""SPMD pipeline parallelism over the 'pp' mesh axis.

Parity with /root/reference/megatron/core/pipeline_parallel/schedules.py
(1F1B :1918, interleaved VPP :856, no-pipelining :618) and
p2p_communication.py (:303 _communicate) — re-designed TPU-first:

Instead of imperative per-rank send/recv schedules, the whole pipeline is ONE
jitted SPMD program: a FULL-MANUAL ``shard_map`` over every mesh axis
(parallel/collectives.shard_map_compat), with a ``lax.scan`` over schedule
steps and a ring ``ppermute`` carrying activations stage→stage.
Differentiating the scan yields the reverse (backward) pipeline
automatically — the transpose of ppermute is the reverse ppermute — so XLA
schedules and overlaps what Megatron encodes by hand, and the 1F1B memory
profile is recovered with per-stage rematerialization (stage inputs are the
only per-step residuals).

Full manual (vs the earlier partial-auto region manual only over pp/cp):
on the jax 0.4.x builds this image ships, partial-auto manual regions
lower ppermute/axis_index through an SPMD path XLA:CPU aborts on
(parallel/overlap.py design notes), and nested shard_maps are unsupported
— so the body owns EVERY axis. The microbatch dim threads over (dp, ep)
when it divides evenly, sequence over cp (attention dispatches to the cp
ring impls directly via the ambient-manual check). tp has two modes:
``tp_shard=True`` (cp == 1 layouts passing overlap.tp_stage_eligible)
shards the activations along the SEQUENCE over tp between stages —
[mb, S/tp, H] residual streams, tp× smaller pp ppermute hops, stage
bodies running the parallel/overlap.py ring all-gather-matmul /
matmul-reduce-scatter primitives on per-shard weight slices (tp× fewer
stage FLOPs, collectives hidden under the GEMM chunks). Otherwise tp
rides replicated inside the body (each tp rank redundantly computes the
stage — kept for ineligible layouts; the tp-GSPMD sharding of the old
partial-auto region needed exactly the partial-auto mode this build
aborts on). Stage hand-offs emit per-step
``pp-overlap-permute`` MegaScan spans so the schedule's comm is visible in
the merged trace.

Unified schedule (steps t = 0..M*vpp + pp - 2), u = t - stage:
  round r = u // (pp*vpp), within-round w = u % (pp*vpp),
  chunk c = w // pp, microbatch m = r*pp + (w % pp).
vpp=1 degenerates to the non-interleaved schedule (inject every step,
chunk 0); vpp>1 is the interleaved/circular schedule with the familiar
bubble reduction (pp-1)/(M*vpp) — reference schedules.py:856-1780. The
activation emitted by the last stage at step t is consumed by stage 0 at
t+1 via the same ring ppermute, which is exactly the chunk hand-off the
reference implements with batched p2p ops.

Virtual-stage layer placement matches the reference interleaved convention:
chunk c on stage s holds global layers [(c*pp + s) * Lc, ...) where
Lc = num_layers / (pp*vpp) (schedules.py chunk bookkeeping :1057-1098).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatronapp_tpu.config.parallel_config import (
    CP_AXIS, DP_AXIS, EP_AXIS, PP_AXIS, TP_AXIS,
)
from megatronapp_tpu.parallel.mesh import MeshContext


from megatronapp_tpu.parallel.collectives import (
    pvary, ring_span, shard_map_compat, span_tags, zeros_like_vma,
)

# MegaScan span name for the stage→stage ring hop (tracer GRANULARITY
# 'collective' set).
PP_OVERLAP_PERMUTE_EVENT = "pp-overlap-permute"


def reshape_params_for_pipeline(stacked_params, pp: int, vpp: int = 1):
    """[L, ...]-stacked layer params → [pp, vpp, L/(pp*vpp), ...] with the
    interleaved chunk→stage assignment (global layer (c*pp+s)*Lc + i ↦
    position [s, c, i])."""
    if isinstance(stacked_params, list):
        raise NotImplementedError(
            "heterogeneous per-layer configs (unstacked params) do not "
            "compose with pipeline parallelism; run hetero models with "
            "pp=1 (reference get_config_for_layer builds per-layer specs "
            "on one pipeline too)")

    def r(x):
        L = x.shape[0]
        Lc = L // (pp * vpp)
        # [L, ...] → [vpp, pp, Lc, ...] (chunk-major) → transpose to
        # [pp, vpp, Lc, ...].
        y = x.reshape(vpp, pp, Lc, *x.shape[1:])
        return jnp.swapaxes(y, 0, 1)

    return jax.tree.map(r, stacked_params)


def spmd_pipeline(
    stage_fn: Callable[[Any, jnp.ndarray, jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]],
    pipe_params: Any,
    h_mb: jnp.ndarray,
    ctx: MeshContext,
    num_microbatches: int,
    vpp: int = 1,
    compute_dtype=jnp.bfloat16,
    order_policy: str = "dfc",
    aux_mb: Any = None,
    tp_shard: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Run the pipelined layer stack.

    order_policy — the MegaDPP scheduling policy (reference paper §5.2,
    shm_tensor_new_rdma.cpp:1478-1646 send-order traversal of the
    (chunk, microbatch) matrix), reinterpreted for the SPMD schedule:
      'dfc' (depth-first-chunk): the interleaved schedule — a round of pp
            microbatches traverses ALL vpp chunks before the next round.
            Bubble (pp-1)/(M*vpp); pp activations in flight per stage.
      'bfc' (breadth-first-chunk): all M microbatches pass through chunk c
            before chunk c+1 (sequential GPipe passes). Bubble
            vpp*(pp-1)/(M*vpp + vpp*(pp-1)); M boundary activations
            materialize between passes (cheaper steady-state VMEM, more HBM).

    stage_fn(chunk_params, h, layer_offset) -> (h, aux) processes one chunk
    (Lc layers) of one microbatch; it runs under compiler sharding for
    tp/dp/cp/ep. Rematerialization is stage_fn's responsibility (the block's
    remat_policy wraps each layer, so the schedule stores only per-layer
    inputs per in-flight microbatch — the 1F1B memory profile).

    aux_mb: optional pytree of [M, ...] per-microbatch side inputs (packed
    segment ids, per-token rope tables). Unlike activations these do NOT
    ride the stage ring — every stage indexes the microbatch it is
    currently processing directly (the schedule makes m a pure function of
    (step, stage)); stage_fn then takes a 4th argument with the indexed
    pytree. Leaves with a sequence axis (dim 2 of [M, mb, S, ...]) are
    cp-sharded like the activations.
    pipe_params: [pp, vpp, Lc, ...] pytree (leading axis sharded over pp).
    h_mb: [M, mb, S, H] microbatched hidden states (e.g. embeddings) — must
    be fp32 when pp > 1 (cast to compute_dtype happens inside; see body).
    tp_shard: run the stage body tp-SHARDED — activations enter/leave the
    region with the sequence dim sharded over tp ([mb, S/tp, H] inside),
    stage_fn must thread tp_sharded=True into the transformer stack, and
    params gain a real tp entry in the grad-axes bookkeeping (each shard
    contributes a slice-local partial wgrad the transpose psums). Caller
    gates on overlap.tp_stage_eligible (cp == 1, divisible S/heads/ffn).
    Returns (out_mb [M, mb, S, H] from the last stage, summed aux losses).
    """
    pp = ctx.pp
    M = num_microbatches
    if pp == 1:
        # No-pipelining fallback (reference schedules.py:618): plain scan
        # over microbatches with all layers merged back into one stack.
        merged = jax.tree.map(lambda x: x.reshape(-1, *x.shape[3:]),
                              pipe_params)

        if aux_mb is None:
            def body(acc, h):
                out, a = stage_fn(merged, h, 0)
                return acc + a, out

            aux, outs = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                     h_mb)
        else:
            def body(acc, inp):
                h, aux_m = inp
                out, a = stage_fn(merged, h, 0, aux_m)
                return acc + a, out

            aux, outs = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                     (h_mb, aux_mb))
        return outs, aux
    if order_policy not in ("dfc", "bfc"):
        raise ValueError(f"order_policy must be 'dfc' or 'bfc', got "
                         f"{order_policy!r}")
    if vpp > 1 and order_policy == "dfc" and M % pp != 0:
        raise ValueError(
            f"interleaved (dfc) pipeline requires num_microbatches ({M}) "
            f"divisible by pipeline_parallel ({pp}); 'bfc' has no such "
            f"constraint")

    if vpp > 1 and order_policy == "bfc":
        # Breadth-first chunks: vpp sequential single-chunk pipeline passes;
        # the M boundary activations materialize (fp32, the shard_map
        # boundary dtype) between passes.
        lc = jax.tree.leaves(pipe_params)[0].shape[2]
        h = h_mb
        aux_total = jnp.zeros((), jnp.float32)
        out = None
        for c in range(vpp):
            chunk_params = jax.tree.map(lambda x, c=c: x[:, c:c + 1],
                                        pipe_params)

            def shifted(p_, x, off, *rest, _c=c):
                # Global layer index = (c*pp + stage)*Lc; the inner vpp=1
                # schedule supplies stage*Lc. *rest forwards the optional
                # per-microbatch aux pytree.
                return stage_fn(p_, x, off + _c * pp * lc, *rest)

            out, aux = spmd_pipeline(
                shifted, chunk_params, h, ctx, M, vpp=1,
                compute_dtype=compute_dtype, order_policy="dfc",
                aux_mb=aux_mb, tp_shard=tp_shard)
            aux_total = aux_total + aux
            h = out.astype(jnp.float32)
        return out, aux_total

    mesh = ctx.mesh
    total_steps = M * vpp + pp - 1
    cycle = pp * vpp
    # Context parallelism composes INSIDE this (full-)manual region (nested
    # shard_maps are unreliable in this JAX build): with cp > 1 the body is
    # manual over cp too, sequence enters pre-sharded [.., S/cp, ..],
    # and attention calls the ring/a2a impls directly (context_attention
    # detects the ambient manual cp). The microbatch dim threads over
    # (dp, ep) when it divides evenly; otherwise it rides replicated
    # (identical math, redundant compute).
    cp = ctx.cp
    mb_size = h_mb.shape[1]
    dpep = ctx.dp * ctx.ep
    batch_axes = (DP_AXIS, EP_AXIS) if mb_size % dpep == 0 else None

    def body(params_local, h_mb_in, aux_mb_in):
        # params_local: [1, vpp, Lc, ...]; h_mb_in: [M, mb(/dp/ep), S(/cp), H].
        # h_mb_in MUST be fp32 at this boundary: its transpose-psum (the
        # pvary below) must not be a bf16 manual all-reduce (XLA:CPU bug —
        # see collectives.zeros_like_vma). Casting to the compute dtype
        # happens per injection, after the pvary.
        h_mb_in = pvary(h_mb_in, (PP_AXIS,))
        aux_mb_in = jax.tree.map(
            lambda a: pvary(a, (PP_AXIS,)), aux_mb_in)
        stage = jax.lax.axis_index(PP_AXIS)
        params_s = jax.tree.map(lambda x: x[0], params_local)
        # Params enter replicated over the token-splitting axes (cp seq
        # chunks; (dp, ep) microbatch shards) but every shard contributes a
        # partial wgrad: pvary's backward is the single fp32 psum per param
        # that IS the data-parallel/cp grad reduction. With the tp-sharded
        # stage body tp is a REAL entry too: each shard's wgrad covers only
        # its weight slice / seq chunk, and the psum assembles the full
        # grad. Replicated-tp bodies need no entry — they compute
        # redundantly, so per-tp-shard cotangents are already complete.
        grad_axes = (batch_axes or ()) + ((CP_AXIS,) if cp > 1 else ()) \
            + ((TP_AXIS,) if tp_shard else ())
        if grad_axes:
            params_s = jax.tree.map(
                lambda p: pvary(p, grad_axes), params_s)
        layers_per_chunk = jax.tree.leaves(params_s)[0].shape[1]
        mb_shape = h_mb_in.shape[1:]

        state = zeros_like_vma(mb_shape, compute_dtype, h_mb_in)
        outputs = zeros_like_vma(h_mb_in.shape, compute_dtype, h_mb_in)
        aux = zeros_like_vma((), jnp.float32, h_mb_in)

        def step(carry, t):
            state, outputs, aux = carry
            u = t - stage
            r = u // cycle
            w = u % cycle
            chunk = w // pp
            m = r * pp + (w % pp)
            active = (u >= 0) & (m >= 0) & (m < M)
            m_safe = jnp.clip(m, 0, M - 1)

            # Stage 0 injects a fresh microbatch while running chunk 0;
            # otherwise consume the ring state.
            inject = jax.lax.dynamic_index_in_dim(h_mb_in, m_safe,
                                                  keepdims=False)
            inject = inject.astype(compute_dtype)
            x = jnp.where((stage == 0) & (chunk == 0), inject, state)

            chunk_params = jax.tree.map(
                lambda p: jax.lax.dynamic_index_in_dim(p, chunk,
                                                       keepdims=False),
                params_s)
            layer_offset = (chunk * pp + stage) * layers_per_chunk
            # Tag every ring span the stage body emits (the tp-sharded
            # body's tp-overlap-* rings) so in-pipeline hops are
            # distinguishable from top-level tp overlap in merged traces.
            with span_tags(region="pp-stage"):
                if aux_mb_in:
                    aux_m = jax.tree.map(
                        lambda a: jax.lax.dynamic_index_in_dim(
                            a, m_safe, keepdims=False), aux_mb_in)
                    y, a = stage_fn(chunk_params, x, layer_offset, aux_m)
                else:
                    y, a = stage_fn(chunk_params, x, layer_offset)
            aux = aux + jnp.where(active, a, 0.0)

            # Last stage, last chunk → collect output.
            collect = active & (stage == pp - 1) & (chunk == vpp - 1)
            prev = jax.lax.dynamic_index_in_dim(outputs, m_safe,
                                                keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(collect, y, prev), m_safe, 0)

            # Stage hand-off: one ring hop per schedule step. The span
            # makes the exposed hop visible per pp rank in MegaScan traces
            # (t is traced — ring_span threads it into the callback).
            # Caveat (this jax build): scan linearization under jax.grad
            # drops in-scan debug callbacks, so these spans appear in
            # forward/eval executions; the cp/moe spans inside the
            # remat'd layer bodies survive training steps too.
            ring_span(PP_OVERLAP_PERMUTE_EVENT, "B", y, PP_AXIS, step=t,
                      op="pp-schedule")
            state = jax.lax.ppermute(
                y, PP_AXIS, [(i, (i + 1) % pp) for i in range(pp)])
            ring_span(PP_OVERLAP_PERMUTE_EVENT, "E", state, PP_AXIS, step=t,
                      op="pp-schedule")
            return (state, outputs, aux), None

        (state, outputs, aux), _ = jax.lax.scan(
            step, (state, outputs, aux), jnp.arange(total_steps))
        # Sum aux losses across stages; average over the token-splitting
        # shards (cp seq chunks, (dp, ep) microbatch shards), whose aux
        # terms are per-local-token means. Outputs live on the last stage.
        red_axes = (PP_AXIS,) + ((CP_AXIS,) if cp > 1 else ()) \
            + (batch_axes or ())
        denom = cp * (dpep if batch_axes else 1)
        aux = jax.lax.psum(aux, red_axes) / denom
        return outputs[None], aux[None]

    if tp_shard and cp > 1:
        raise ValueError("tp_shard requires cp == 1 (the sequence is the "
                         "tp shard dim); gate callers on tp_stage_eligible")
    if tp_shard and aux_mb:
        raise NotImplementedError(
            "tp_shard does not compose with per-microbatch aux inputs "
            "(packed sequences) yet — callers keep tp-replicated there")
    # With the tp-sharded stage body the seq dim shards over tp at the
    # region boundary: each shard receives/returns its [.., S/tp, H]
    # chunk, the transpose delivers REAL per-shard output cotangents,
    # and the pp ring hops inside carry tp× less data.
    cp_spec = (CP_AXIS if cp > 1 else (TP_AXIS if tp_shard else None))
    h_spec = P(None, batch_axes, cp_spec)
    out_spec = P(PP_AXIS, None, batch_axes, cp_spec)
    aux_mb = {} if aux_mb is None else aux_mb

    # Leaves [M, mb, S, ...]: microbatch axis (dim 1) over (dp, ep),
    # sequence axis (dim 2) cp-sharded. Lower-rank leaves (e.g. a per-
    # microbatch [M, mb] scalar input) take the prefix of the spec.
    def _aux_spec(a):
        dims = [None, batch_axes, cp_spec] + [None] * max(0, a.ndim - 3)
        return P(*dims[:a.ndim])

    aux_specs = jax.tree.map(_aux_spec, aux_mb)
    # manual-ok: this call CREATES the pipeline's manual region (the one
    # the stage-body modules execute inside) — it is not nested
    sm = jax.jit(shard_map_compat(
        body, ctx.shard_map_mesh,
        in_specs=(P(PP_AXIS), h_spec, aux_specs),
        out_specs=(out_spec, P(PP_AXIS))))
    outputs_all, aux_all = sm(pipe_params, h_mb, aux_mb)
    return outputs_all[-1], aux_all[0]
