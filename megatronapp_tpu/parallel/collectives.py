"""Helpers for manual-collective (shard_map) code.

The reference wraps torch.distributed in
virtual_tensor_parallel_communication.py; here the collectives themselves are
jax.lax primitives — this module only holds small shared utilities for code
running inside shard_map manual regions.
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp


def current_manual_axes() -> Tuple[str, ...]:
    """Mesh axes that are Manual in the ambient context (nested shard_maps
    accumulate them)."""
    m = jax.sharding.get_abstract_mesh()
    if m is None or not m.shape:
        return ()
    Manual = jax.sharding.AxisType.Manual
    return tuple(name for name, t in zip(m.axis_names, m.axis_types)
                 if t == Manual)


def _axes_tuple(axis) -> Tuple[str, ...]:
    if axis is None:
        return current_manual_axes()
    if isinstance(axis, str):
        return (axis,)
    return tuple(axis)


def varying_zeros(shape, dtype, axis: Union[str, Sequence[str], None] = None):
    """Zeros with 'varying' VMA over the given axes (default: every manual
    axis in scope) WITHOUT lax.pcast.

    pcast's transpose is a psum, and the current XLA build crashes on bf16
    manual all-reduces ("Invalid binary instruction opcode copy" — reducer
    regions containing converts). axis_index is varying and
    non-differentiable, so adding 0*axis_index yields a varying value with no
    collective in the backward pass.
    """
    z = jnp.zeros((), jnp.int32)
    for a in _axes_tuple(axis):
        z = z + jax.lax.axis_index(a) * 0
    return jnp.zeros(shape, dtype) + z.astype(dtype)


def varying_full(shape, fill, dtype,
                 axis: Union[str, Sequence[str], None] = None):
    z = jnp.zeros((), jnp.int32)
    for a in _axes_tuple(axis):
        z = z + jax.lax.axis_index(a) * 0
    return jnp.full(shape, fill, dtype) + z.astype(dtype)


def _anchor(like: jnp.ndarray) -> jnp.ndarray:
    """Scalar zero inheriting `like`'s varying-manual-axes type, with no
    backward edge (stop_gradient) and no axis_index — safe inside nested
    shard_maps where parent-bound axis names cannot be referenced."""
    flat = jax.lax.stop_gradient(like).ravel()
    return (flat[0] * 0).astype(jnp.float32)


def zeros_like_vma(shape, dtype, like: jnp.ndarray):
    """Zeros of (shape, dtype) whose varying-manual-axes match `like`."""
    return jnp.zeros(shape, dtype) + _anchor(like).astype(dtype)


def full_like_vma(shape, fill, dtype, like: jnp.ndarray):
    return jnp.full(shape, fill, dtype) + _anchor(like).astype(dtype)
