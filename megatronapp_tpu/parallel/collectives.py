"""Helpers for manual-collective (shard_map) code.

The reference wraps torch.distributed in
virtual_tensor_parallel_communication.py; here the collectives themselves are
jax.lax primitives — this module only holds small shared utilities for code
running inside shard_map manual regions.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def current_manual_axes() -> Tuple[str, ...]:
    """Mesh axes that are Manual in the ambient context (nested shard_maps
    accumulate them).

    Newer jax exposes this via the abstract mesh's axis types; on the
    jax 0.4.x builds this image ships (no get_abstract_mesh/AxisType) the
    manual axes are exactly the names shard_map bound into the tracing
    axis env — same mechanism pmap/ppermute name resolution uses."""
    if hasattr(jax.sharding, "get_abstract_mesh"):
        m = jax.sharding.get_abstract_mesh()
        if m is None or not m.shape:
            return ()
        Manual = jax.sharding.AxisType.Manual
        return tuple(name for name, t in zip(m.axis_names, m.axis_types)
                     if t == Manual)
    try:
        from jax._src.core import trace_ctx
        return tuple(trace_ctx.axis_env.axis_names())
    except (ImportError, AttributeError):
        return ()


def _anchor(like: jnp.ndarray) -> jnp.ndarray:
    """Scalar zero inheriting `like`'s varying-manual-axes type, with no
    backward edge (stop_gradient) and no axis_index — safe inside nested
    shard_maps where parent-bound axis names cannot be referenced.

    Why not lax.pcast for making carries varying: pcast's transpose is a
    psum, and the current XLA build crashes on bf16 manual all-reduces
    ("Invalid binary instruction opcode copy" — reducer regions containing
    converts). This anchor adds no collective in either direction."""
    flat = jax.lax.stop_gradient(like).ravel()
    return (flat[0] * 0).astype(jnp.float32)


def zeros_like_vma(shape, dtype, like: jnp.ndarray):
    """Zeros of (shape, dtype) whose varying-manual-axes match `like`."""
    return jnp.zeros(shape, dtype) + _anchor(like).astype(dtype)


def full_like_vma(shape, fill, dtype, like: jnp.ndarray):
    return jnp.full(shape, fill, dtype) + _anchor(like).astype(dtype)
