"""Helpers for manual-collective (shard_map) code.

The reference wraps torch.distributed in
virtual_tensor_parallel_communication.py; here the collectives themselves are
jax.lax primitives — this module only holds small shared utilities for code
running inside shard_map manual regions.

This module and ``parallel/overlap.py`` are the designated homes for raw
manual collectives (tools/check_vma.py); every full-manual subsystem
(tp overlap, cp ring attention, ep all-to-all dispatch, the pp pipeline)
builds on the compat wrappers here.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def shard_map_compat(body, mesh, in_specs, out_specs):
    """FULL-MANUAL shard_map across jax versions.

    Newer jax: ``jax.shard_map(..., check_vma=False)`` (the bodies are
    plain ring code; vma annotation adds nothing under full manual).
    jax 0.4.x (this image): ``jax.experimental.shard_map.shard_map`` with
    ``check_rep=False`` — the old rep checker predates varying-manual-axes
    types and rejects valid ring accumulations.

    Full manual (every mesh axis) is load-bearing on this stack: the jax
    0.4.x partial-auto manual regions lower ppermute/axis_index through an
    SPMD path XLA:CPU aborts on (spmd_partitioner IsManualSubgroup check /
    unsupported PartitionId) — see parallel/overlap.py design notes. Axes a
    body does not communicate over are simply threaded through the specs
    (split batch dims) or replicated (unmentioned spec dims).

    Autodiff note (verified on jax 0.4.37): grads of inputs whose spec
    leaves axes unmentioned come out correct — the transpose feeds output
    cotangents to a single shard along unmentioned out-spec axes and sums
    input cotangents across unmentioned in-spec axes — so replicated
    params (split batch) and redundantly-computed axes both transpose
    right without explicit psums. Explicit psums are still required for
    reductions the MATH needs inside custom_vjp bodies (e.g. wgrads
    across manual batch shards in overlap.py)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(body, mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def axis_size(axis_name) -> int:
    """Static size of a bound mesh axis, across jax versions.

    jax 0.4.x has no ``lax.axis_size``; ``lax.psum(1, name)`` is the
    canonical spelling there and constant-folds to a Python int."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def psum(x, axis_name):
    """All-reduce sum over a bound manual mesh axis — the designated
    entry point for shard-partial reductions in full-manual bodies
    (tools/check_vma.py gate 1), e.g. the latent-column score/value
    partials of kernel_gen._tp_place_latent. Keep operands fp32 at the
    call sites: bf16 manual all-reduces crash this XLA:CPU build
    (README known constraints)."""
    return lax.psum(x, axis_name)


def pvary(x, axes: Tuple[str, ...]):
    """Mark a replicated-over-``axes`` input as varying inside a manual
    region, so its cotangent is psummed over ``axes`` exactly once.

    Version-portable replacement for ``lax.pcast(x, axes, to="varying")``
    at full-manual shard_map boundaries (pipeline stage params over cp and
    the (dp, ep) microbatch shards; microbatch inputs over pp). On jax
    0.4.x there is no pcast AND none is needed: the shard_map transpose
    already psums input cotangents over every axis the in_spec leaves
    unmentioned (verified on 0.4.37 — an explicit extra psum here would
    double-count). Keep inputs fp32 at these call sites — bf16 manual
    all-reduces crash this XLA:CPU build (README known constraints)."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, tuple(axes), to="varying")
    return x


def current_manual_axes() -> Tuple[str, ...]:
    """Mesh axes that are Manual in the ambient context (nested shard_maps
    accumulate them).

    Newer jax exposes this via the abstract mesh's axis types; on the
    jax 0.4.x builds this image ships (no get_abstract_mesh/AxisType) the
    manual axes are exactly the names shard_map bound into the tracing
    axis env — same mechanism pmap/ppermute name resolution uses."""
    if hasattr(jax.sharding, "get_abstract_mesh"):
        m = jax.sharding.get_abstract_mesh()
        if m is None or not m.shape:
            return ()
        Manual = jax.sharding.AxisType.Manual
        return tuple(name for name, t in zip(m.axis_names, m.axis_types)
                     if t == Manual)
    try:
        from jax._src.core import trace_ctx
        return tuple(trace_ctx.axis_env.axis_names())
    except (ImportError, AttributeError):
        return ()


def ambient_manual(*axes: str) -> bool:
    """True iff every named mesh axis is Manual in the ambient context —
    the shared detection gate for code that must switch between GSPMD
    wrappers (outside any manual region) and ambient ring bodies (inside
    the full-manual pipeline/cp regions, where a nested shard_map or a
    GSPMD collective would abort this XLA:CPU build)."""
    manual = current_manual_axes()
    return all(a in manual for a in axes)


def all_gather_seq(x: jnp.ndarray, axis_name: str, axis: int = 1):
    """Tiled all-gather of a manually-sharded axis inside an ambient
    manual region ([..., S/n, ...] → [..., S, ...], rank-major order —
    matching the contiguous seq-chunk layout the tp/cp rings use).

    The audited home for the bulk (non-overlapped) gathers of the
    tp-sharded pipeline stage body: small side tensors (MLA's shared
    rope key) and the ``tp_comm_overlap=False`` bulk fallback both route
    through here rather than sprinkling raw lax.all_gather calls."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=True)


# Python-level attrs merged into every ring_span record while active —
# lets an enclosing region (the pp pipeline's tp-sharded stage body) tag
# the spans its inner rings emit without threading arguments through
# every ring body. Trace-time state: the tag captures at trace time like
# the enabled check itself.
_SPAN_TAGS: dict = {}


@contextlib.contextmanager
def span_tags(**tags):
    """Tag all ring_span records emitted while tracing under this context
    (e.g. ``span_tags(region="pp-stage")`` around the pipeline stage body
    marks the in-pipeline tp rings apart from top-level tp overlap).

    Scope caveat: custom_vjp BACKWARD ring bodies are traced during
    transposition — outside any forward-side ``with`` — so only
    forward-pass spans carry the tag (same jax-0.4.x boundary as the
    "pp hop spans appear on forward/eval only" scan-linearization
    note)."""
    global _SPAN_TAGS
    prev = _SPAN_TAGS
    _SPAN_TAGS = {**prev, **tags}
    try:
        yield
    finally:
        _SPAN_TAGS = prev


def ring_span(name: str, ph: str, dep, axis_name: str, *, step=None,
              **attrs):
    """Per-hop MegaScan record from inside a jitted manual ring body.

    Shared emission helper behind the tp/cp/ep overlap spans
    (tp-overlap-*, cp-overlap-*, moe-a2a-*, pp-overlap-*). Inserted only
    when tracing is enabled at trace time (zero overhead otherwise). Uses
    ``jax.debug.callback`` — the only callback flavor supported inside
    shard_map manual regions in this build (ordered io_callback is
    rejected there); the data dependency on ``dep`` anchors the record
    near the op it brackets. One timeline per rank along ``axis_name``
    (tid = rank + 1; tid 0 stays the host-scope timeline).

    The timeline id is the shard's linearized rank over EVERY ambient
    manual axis (not just ``axis_name``): two shards that share a ring
    rank but differ on another axis (e.g. the dp shards of one cp rank)
    must not interleave B/E pairs onto one Chrome-trace tid, whose pairing
    is a per-tid stack. On single-ring meshes this degenerates to
    ring-rank + 1 exactly as before.

    step may be a Python int (unrolled rings) or a traced scalar (the pp
    schedule's scanned step) — it rides into the callback as an operand."""
    from megatronapp_tpu.trace.tracer import callbacks_supported, get_tracer

    tracer = get_tracer()
    if not (tracer.enabled and callbacks_supported()):
        return
    if _SPAN_TAGS:
        attrs = {**_SPAN_TAGS, **attrs}

    rank = lax.axis_index(axis_name)
    tid = jnp.zeros((), jnp.int32)
    for n in sorted(current_manual_axes()):
        tid = tid * axis_size(n) + lax.axis_index(n)

    def _cb(rank_, tid_, step_, _):
        a = dict(attrs, rank=int(rank_))
        if int(step_) >= 0:
            a["step"] = int(step_)
        tracer.phase_event(name, ph, tid=int(tid_) + 1, **a)

    anchor = lax.stop_gradient(dep).ravel()[0]
    jax.debug.callback(_cb, rank, tid,
                       jnp.asarray(-1 if step is None else step, jnp.int32),
                       anchor)


def _anchor(like: jnp.ndarray) -> jnp.ndarray:
    """Scalar zero inheriting `like`'s varying-manual-axes type, with no
    backward edge (stop_gradient) and no axis_index — safe inside nested
    shard_maps where parent-bound axis names cannot be referenced.

    Why not lax.pcast for making carries varying: pcast's transpose is a
    psum, and the current XLA build crashes on bf16 manual all-reduces
    ("Invalid binary instruction opcode copy" — reducer regions containing
    converts). This anchor adds no collective in either direction."""
    flat = jax.lax.stop_gradient(like).ravel()
    return (flat[0] * 0).astype(jnp.float32)


def zeros_like_vma(shape, dtype, like: jnp.ndarray):
    """Zeros of (shape, dtype) whose varying-manual-axes match `like`."""
    return jnp.zeros(shape, dtype) + _anchor(like).astype(dtype)


def full_like_vma(shape, fill, dtype, like: jnp.ndarray):
    return jnp.full(shape, fill, dtype) + _anchor(like).astype(dtype)
