"""Logical-axis sharding rules.

TPU-native replacement for the reference's explicit tensor-parallel layer
classes (ColumnParallelLinear /root/reference/megatron/core/tensor_parallel/
layers.py:675, RowParallelLinear :1019, VocabParallelEmbedding :172). Instead
of hand-splitting weights and inserting collectives via autograd functions
(mappings.py:27-353), every parameter carries a tuple of *logical* axis names;
a rule table maps logical names to mesh axes and XLA inserts the matching
all-gather / reduce-scatter / all-reduce.

Column-parallel == output-feature axis mapped to 'tp';
row-parallel == input-feature axis mapped to 'tp';
vocab-parallel embedding == vocab axis mapped to 'tp'.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from megatronapp_tpu.config.parallel_config import (
    DP_AXIS, EP_AXIS, CP_AXIS, TP_AXIS, PP_AXIS,
)

# Logical axis vocabulary used by model code.
#   'vocab'        — vocabulary dim (tp-sharded: vocab-parallel embedding/CE)
#   'embed'        — hidden/residual dim (replicated across tp; fsdp-shardable)
#   'mlp'          — FFN intermediate dim (tp-sharded: column→row parallel pair)
#   'heads'        — attention heads dim (tp-sharded)
#   'kv_heads'     — GQA KV-heads dim (tp-sharded)
#   'head_dim'     — per-head feature dim (unsharded)
#   'qkv'          — fused QKV output dim (tp-sharded)
#   'experts'      — MoE expert dim (ep-sharded)
#   'layers'       — stacked-layer leading axis from scan (pp-sharded when
#                    pipelining, else unsharded)
#   'stage_layers' — layers within one pipeline stage (unsharded)
#   'batch','seq'  — activation dims
DEFAULT_RULES: Tuple[Tuple[str, Any], ...] = (
    ("vocab", TP_AXIS),
    ("embed", None),
    ("mlp", TP_AXIS),
    ("heads", TP_AXIS),
    ("kv_heads", TP_AXIS),
    ("head_dim", None),
    ("qkv", TP_AXIS),
    ("experts", EP_AXIS),
    # 'layers' is the scan-stacked leading axis. It is NOT pp-sharded here:
    # the pipeline module owns pp placement explicitly (parallel/pipeline.py
    # reshapes to [pp, layers/pp, ...] inside shard_map); in the non-pipelined
    # path layers live whole on every pp group (pp=1).
    ("layers", None),
    # Pipeline param layout [pp, vpp, Lc, ...] (parallel/pipeline.py
    # reshape_params_for_pipeline): stage axis sharded over pp.
    ("pp_stage", PP_AXIS),
    ("vpp_chunk", None),
    ("stage_layers", None),
    ("batch", (DP_AXIS, EP_AXIS)),
    ("seq", CP_AXIS),
    ("pos", None),
)

# FSDP variant: shard the residual/hidden dim of weights across dp as well
# (reference custom_fsdp,
# core/distributed/custom_fsdp/fully_sharded_data_parallel.py). ZeRO-1
# (--use-distributed-optimizer) is NOT this: params keep DEFAULT_RULES and
# only the optimizer-state pytree gains a dp shard dim, via the regex spec
# map in training/distributed_optimizer.py.
FSDP_RULES: Tuple[Tuple[str, Any], ...] = tuple(
    (name, (DP_AXIS,) if name == "embed" else axis)
    for name, axis in DEFAULT_RULES
)


def rules_dict(rules=DEFAULT_RULES) -> Dict[str, Any]:
    return dict(rules)


def is_logical_axes(x) -> bool:
    """Leaf predicate for logical-axes pytrees: a tuple of axis names/None.
    The single canonical copy — jax.tree.map over axes trees must use this
    as is_leaf everywhere or the tuples get flattened into strings."""
    return isinstance(x, tuple) and all(
        a is None or isinstance(a, str) for a in x)


def logical_to_spec(logical_axes: Tuple[Optional[str], ...],
                    rules=DEFAULT_RULES) -> P:
    """Map a tuple of logical axis names to a PartitionSpec."""
    table = dict(rules)
    spec = []
    used = set()
    for name in logical_axes:
        if name is None:
            spec.append(None)
            continue
        axis = table.get(name)
        # A mesh axis may appear only once in a PartitionSpec; later
        # occurrences degrade to replication (matters for e.g. ('embed','mlp')
        # under fsdp rules where two dims could both want dp).
        key = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
        if axis is None or any(k in used for k in key):
            spec.append(None)
        else:
            used.update(key)
            spec.append(axis)
    return P(*spec)


def tree_logical_to_sharding(logical_tree, mesh: Mesh, rules=DEFAULT_RULES):
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, logical_to_spec(axes, rules)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x),
    )


def shard_params(params, logical_tree, mesh: Mesh, rules=DEFAULT_RULES):
    """Device-put a param pytree according to its logical axes."""
    shardings = tree_logical_to_sharding(logical_tree, mesh, rules)
    return jax.device_put(params, shardings)
