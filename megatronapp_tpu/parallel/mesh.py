"""Device mesh construction and accessors.

TPU-native analogue of ``parallel_state.py`` group construction
(/root/reference/megatron/core/parallel_state.py:1272 and accessors :18-124).
Where the reference builds ~20 NCCL/Gloo process groups and stores them in
module globals, here a single ``MeshContext`` owns a ``jax.sharding.Mesh`` with
named axes (pp, dp, ep, cp, tp); "groups" are just axis names, and collectives
are either emitted by XLA from shardings or written explicitly with
``shard_map`` + ``psum``/``ppermute`` over an axis name.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from megatronapp_tpu.config.parallel_config import (
    MESH_AXES, ParallelConfig, DP_AXIS, TP_AXIS, PP_AXIS, CP_AXIS, EP_AXIS,
)


@dataclasses.dataclass
class MeshContext:
    """Owns the device mesh and the parallel config that shaped it."""

    mesh: Mesh
    parallel: ParallelConfig
    # FBD half-meshes set this: shard_maps then bind the ABSTRACT mesh
    # (axis names only) and resolve devices from argument shardings, so a
    # vjp pullback traced on the forward mesh can execute on the backward
    # mesh. Default False — eager abstract-mesh shard_maps on unsharded
    # args are not supported by this XLA build.
    abstract_collectives: bool = False

    @property
    def shard_map_mesh(self):
        """The mesh object to pass to jax.shard_map."""
        return (self.mesh.abstract_mesh if self.abstract_collectives
                else self.mesh)

    # --- degree accessors (parity with parallel_state get_*_world_size) ---
    @property
    def tp(self) -> int:
        return self.mesh.shape[TP_AXIS]

    @property
    def pp(self) -> int:
        return self.mesh.shape[PP_AXIS]

    @property
    def dp(self) -> int:
        return self.mesh.shape[DP_AXIS]

    @property
    def cp(self) -> int:
        return self.mesh.shape[CP_AXIS]

    @property
    def ep(self) -> int:
        return self.mesh.shape[EP_AXIS]

    @property
    def num_devices(self) -> int:
        return int(np.prod([self.mesh.shape[a] for a in MESH_AXES]))

    # --- sharding helpers ---
    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_spec(self, seq_sharded: bool = True) -> P:
        """PartitionSpec for a [batch, seq, ...] activation/token array.

        Batch is sharded over dp (and ep, which subdivides the data-parallel
        world exactly as in the reference where EP ranks hold distinct data;
        parallel_state.py:43-52). Sequence is sharded over cp (context
        parallelism, §5.7 of SURVEY) when seq_sharded.
        """
        batch_axes = (DP_AXIS, EP_AXIS)
        if seq_sharded and self.cp > 1:
            return P(batch_axes, CP_AXIS)
        return P(batch_axes)

    @contextlib.contextmanager
    def use(self):
        with self.mesh:
            yield self


def build_mesh(parallel: ParallelConfig,
               devices: Optional[Sequence[jax.Device]] = None) -> MeshContext:
    """Build the mesh with axis order pp, dp, ep, cp, tp (outer→inner).

    TP innermost keeps tensor-parallel collectives on nearest-neighbor ICI
    links; PP outermost lets pipeline stages span slices over DCN — the
    reference encodes the same locality preference via RankGenerator order
    tp-cp-ep-dp-pp (parallel_state.py).
    """
    if devices is None:
        devices = jax.devices()
    shape = parallel.mesh_shape(len(devices))
    dev_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, MESH_AXES)
    return MeshContext(mesh=mesh, parallel=parallel)


def single_device_mesh() -> MeshContext:
    """Trivial 1-device mesh (all axes size 1) for single-chip runs/tests."""
    return build_mesh(ParallelConfig(), devices=jax.devices()[:1])
