"""Device mesh construction and accessors.

TPU-native analogue of ``parallel_state.py`` group construction
(/root/reference/megatron/core/parallel_state.py:1272 and accessors :18-124).
Where the reference builds ~20 NCCL/Gloo process groups and stores them in
module globals, here a single ``MeshContext`` owns a ``jax.sharding.Mesh`` with
named axes (pp, dp, ep, cp, tp); "groups" are just axis names, and collectives
are either emitted by XLA from shardings or written explicitly with
``shard_map`` + ``psum``/``ppermute`` over an axis name.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from megatronapp_tpu.config.parallel_config import (
    MESH_AXES, ParallelConfig, DP_AXIS, TP_AXIS, PP_AXIS, CP_AXIS, EP_AXIS,
)


@dataclasses.dataclass
class MeshContext:
    """Owns the device mesh and the parallel config that shaped it."""

    mesh: Mesh
    parallel: ParallelConfig
    # FBD half-meshes set this: shard_maps then bind the ABSTRACT mesh
    # (axis names only) and resolve devices from argument shardings, so a
    # vjp pullback traced on the forward mesh can execute on the backward
    # mesh. Default False — eager abstract-mesh shard_maps on unsharded
    # args are not supported by this XLA build.
    abstract_collectives: bool = False

    @property
    def shard_map_mesh(self):
        """The mesh object to pass to jax.shard_map."""
        return (self.mesh.abstract_mesh if self.abstract_collectives
                else self.mesh)

    # --- degree accessors (parity with parallel_state get_*_world_size) ---
    @property
    def tp(self) -> int:
        return self.mesh.shape[TP_AXIS]

    @property
    def pp(self) -> int:
        return self.mesh.shape[PP_AXIS]

    @property
    def dp(self) -> int:
        return self.mesh.shape[DP_AXIS]

    @property
    def cp(self) -> int:
        return self.mesh.shape[CP_AXIS]

    @property
    def ep(self) -> int:
        return self.mesh.shape[EP_AXIS]

    @property
    def num_devices(self) -> int:
        return int(np.prod([self.mesh.shape[a] for a in MESH_AXES]))

    # --- sharding helpers ---
    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_spec(self, seq_sharded: bool = True) -> P:
        """PartitionSpec for a [batch, seq, ...] activation/token array.

        Batch is sharded over dp (and ep, which subdivides the data-parallel
        world exactly as in the reference where EP ranks hold distinct data;
        parallel_state.py:43-52). Sequence is sharded over cp (context
        parallelism, §5.7 of SURVEY) when seq_sharded.
        """
        batch_axes = (DP_AXIS, EP_AXIS)
        if seq_sharded and self.cp > 1:
            return P(batch_axes, CP_AXIS)
        return P(batch_axes)

    @contextlib.contextmanager
    def use(self):
        with self.mesh:
            yield self


_distributed_initialized = False


def initialize_multi_host(coordinator_address: Optional[str] = None,
                          num_processes: Optional[int] = None,
                          process_id: Optional[int] = None) -> None:
    """Join the multi-host runtime (reference
    torch.distributed.init_process_group, training/initialize.py:330-335;
    here ``jax.distributed.initialize`` — the JAX runtime then exposes one
    global ``jax.devices()`` list spanning all hosts, and XLA routes
    inter-slice collectives over DCN).

    On TPU pods (GKE/queued resources) all three arguments auto-detect from
    the metadata server; pass them explicitly for manual launches
    (reference MASTER_ADDR/RANK/WORLD_SIZE env). Idempotent: a second call
    in the same process (repeated parse_args in tests/notebooks) is a
    no-op instead of a double-initialize error."""
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    global _distributed_initialized
    if _distributed_initialized:
        return
    try:
        jax.distributed.initialize(**kwargs)
        _distributed_initialized = True
    except RuntimeError as e:
        # jax.distributed exposes no public already-initialized query
        # (global_state lives under jax._src); the flag above handles
        # re-entry within this process, and the error-string match below
        # is only a fallback for initializes done outside this helper.
        if "only be called once" not in str(e):
            raise
        _distributed_initialized = True


def _dcn_slice_axis(shape: Sequence[int], n_slices: int) -> int:
    """Pick the mesh axis to split across DCN slices: the OUTERMOST of
    pp/dp/ep whose degree n_slices divides (axis order pp, dp, ep, cp, tp
    — pipeline stages or data-parallel replicas span slices; cp/tp
    collectives are latency-critical and must stay on intra-slice ICI,
    the reference's NCCL-topology preference)."""
    for i, extent in enumerate(shape[:3]):  # pp, dp, ep only
        if extent > 1 and extent % n_slices == 0:
            return i
    raise ValueError(
        f"no pp/dp/ep mesh axis in {tuple(shape)} divisible by {n_slices} "
        "DCN slices; choose pp/dp degrees that factor across slices")


def build_mesh(parallel: ParallelConfig,
               devices: Optional[Sequence[jax.Device]] = None) -> MeshContext:
    """Build the mesh with axis order pp, dp, ep, cp, tp (outer→inner).

    TP innermost keeps tensor-parallel collectives on nearest-neighbor ICI
    links; PP outermost lets pipeline stages span slices over DCN — the
    reference encodes the same locality preference via RankGenerator order
    tp-cp-ep-dp-pp (parallel_state.py).

    On real TPU the device array is laid out topology-aware: within one
    slice via ``mesh_utils.create_device_mesh`` (ICI torus assignment), and
    across slices via ``create_hybrid_device_mesh`` with the slice count on
    the outermost divisible axis (DCN traffic rides pp/dp, never tp).
    Virtual/CPU devices keep the plain deterministic reshape (tests)."""
    if devices is None:
        devices = jax.devices()
    shape = parallel.mesh_shape(len(devices))
    if getattr(devices[0], "platform", None) == "tpu":
        from jax.experimental import mesh_utils
        slice_ids = {getattr(d, "slice_index", 0) for d in devices}
        if len(slice_ids) > 1:
            # Raises (with a config suggestion) when no pp/dp/ep axis
            # factors across the slices — a misconfigured multi-slice job
            # must fail loudly, not silently put tp/cp on DCN.
            dcn = [1] * len(shape)
            dcn[_dcn_slice_axis(shape, len(slice_ids))] = len(slice_ids)
            per_slice = [s // d for s, d in zip(shape, dcn)]
            dev_array = mesh_utils.create_hybrid_device_mesh(
                per_slice, dcn, devices=devices)
        else:
            try:
                dev_array = mesh_utils.create_device_mesh(
                    shape, devices=devices)
            except (ValueError, NotImplementedError):
                # Unusual topologies (e.g. subset meshes) — fall back to
                # the enumeration order, which jax topology-sorts.
                dev_array = np.asarray(devices).reshape(shape)
    else:
        dev_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, MESH_AXES)
    return MeshContext(mesh=mesh, parallel=parallel)


def single_device_mesh() -> MeshContext:
    """Trivial 1-device mesh (all axes size 1) for single-chip runs/tests."""
    return build_mesh(ParallelConfig(), devices=jax.devices()[:1])
