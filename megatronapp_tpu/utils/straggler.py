"""Host-side straggler detector.

Parity with /root/reference/megatron/core/utils.py:1030 (StragglerDetector,
docs core/README_STRAGGLER.md): collects per-step timings and flags outlier
steps/processes. The reference reads GPU power/temp/clocks via pynvml; on
TPU those counters aren't host-visible, so this detector works purely from
step-time statistics (MegaScan's trace-based detector — trace/detect.py —
is the op-granularity complement, exactly as in the reference).

Toggleable at runtime (reference: curl port on/off) via enable()/disable().
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Deque, List, Optional

from megatronapp_tpu.utils import metrics as telemetry


@dataclasses.dataclass
class StepRecord:
    step: int
    elapsed_s: float


def _window_z(values, x: float) -> Optional[float]:
    """z-score of x against the trailing window (population std); None
    when the window is degenerate (zero spread)."""
    n = len(values)
    mean = sum(values) / n
    var = sum((t - mean) ** 2 for t in values) / n
    std = var ** 0.5
    if std <= 0:
        return None
    return (x - mean) / std


class StragglerDetector:
    def __init__(self, window: int = 64, z_threshold: float = 3.0,
                 min_samples: int = 8):
        self.window: Deque[StepRecord] = deque(maxlen=window)
        self.z_threshold = z_threshold
        self.min_samples = min_samples
        self.enabled = False
        self.flagged: List[StepRecord] = []
        self._t0: Optional[float] = None
        self._step = 0

    def enable(self):
        self.enabled = True

    def disable(self):
        self.enabled = False

    def start(self):
        # Keep a running span open: start() fires every iteration but the
        # sample closes only at the next sync point (stop()).
        if self.enabled and self._t0 is None:
            self._t0 = time.perf_counter()

    def stop(self, steps: int = 1) -> Optional[StepRecord]:
        """Record a sample normalized to per-step time (a sync span may
        cover several pipelined steps); returns the record if it is an
        outlier."""
        if not self.enabled or self._t0 is None:
            return None
        elapsed = (time.perf_counter() - self._t0) / max(steps, 1)
        self._t0 = None
        self._step += 1
        rec = StepRecord(self._step, elapsed)
        outlier = None
        if len(self.window) >= self.min_samples:
            z = _window_z([r.elapsed_s for r in self.window], elapsed)
            if z is not None:
                # z-score into the shared telemetry registry (ISSUE 12):
                # the straggler signal becomes scrapeable at /metrics
                # alongside the step-time histogram, instead of living
                # only in the log line.
                telemetry.set_gauge("train_straggler_z", round(z, 4))
                if z > self.z_threshold:
                    telemetry.inc("train_straggler_flags")
                    self.flagged.append(rec)
                    outlier = rec
        # Outliers are excluded from the baseline window.
        if outlier is None:
            self.window.append(rec)
        return outlier


class RollingZ:
    """Windowed z-score of the latest sample against the trailing window
    — the per-(stage, vstage) complement of StragglerDetector's per-step
    z. The pipeline planner (parallel/schedule.Planner) keys one per
    stage timeline so per-stage slowdowns are visible at /metrics even
    when the aggregate step time hides them."""

    def __init__(self, window: int = 64, min_samples: int = 8,
                 z_threshold: float = 3.0):
        self.window: Deque[float] = deque(maxlen=window)
        self.min_samples = min_samples
        self.z_threshold = z_threshold
        self.last_z: Optional[float] = None

    def observe(self, x: float) -> Optional[float]:
        z = None
        if len(self.window) >= self.min_samples:
            z = _window_z(self.window, x)
        # Outliers stay out of the baseline window (same discipline as
        # StragglerDetector.stop).
        if z is None or z <= self.z_threshold:
            self.window.append(x)
        self.last_z = z
        return z


def probe_chip_rtts(devices=None, size: int = 256, repeats: int = 3,
                    warmup: int = 1):
    """Per-chip round-trip probe: dispatch a small matmul to EACH device
    and time put→compute→get individually.

    The per-chip complement the reference gets from pynvml telemetry
    (core/utils.py:1030 collects per-GPU power/temp/clock): TPU counters
    are not host-visible, but a per-device RTT isolates a slow/hung chip
    the aggregate step time can't attribute. Returns
    [{'device', 'rtt_ms'}...] sorted worst-first.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    if devices is None:
        devices = jax.devices()
    x = np.ones((size, size), np.float32)
    f = jax.jit(lambda a: a @ a)
    results = []
    for d in devices:
        xs = jax.device_put(jnp.asarray(x), d)
        for _ in range(warmup):
            jax.device_get(f(xs))
        t0 = time.perf_counter()
        for _ in range(repeats):
            jax.device_get(f(xs))
        results.append({"device": str(d),
                        "rtt_ms": (time.perf_counter() - t0) / repeats
                        * 1e3})
    return sorted(results, key=lambda r: -r["rtt_ms"])


def detect_slow_chips(rtts, ratio_threshold: float = 2.0):
    """Flag devices whose probe RTT exceeds ratio_threshold × the median
    (the per-chip stage of straggler localization; MegaScan's trace
    detector — trace/detect.py — is the op-granularity stage)."""
    if not rtts:
        return []
    times = sorted(r["rtt_ms"] for r in rtts)
    median = times[len(times) // 2]
    return [r for r in rtts if r["rtt_ms"] > ratio_threshold * median]


_DETECTOR = StragglerDetector()


def get_straggler_detector() -> StragglerDetector:
    return _DETECTOR
