"""Serving/training telemetry registry (ISSUE 12).

One process-wide registry of NAMED counters, gauges, EWMAs, and
log-bucketed latency histograms, plus a Prometheus-text renderer for the
server's ``GET /metrics``. Production code calls the module-level
``inc``/``set_gauge``/``observe``/``observe_ewma`` at the instrumented
sites (paged allocator evictions, speculative acceptance, decode token
intervals, train step times, …); the registry aggregates and the server
exports.

Design constraints (mirrors utils/chaos.py):

- **Zero-cost when disabled.** Every module-level recording function
  starts with a single truthiness check of a module-level dict
  (``if not _ACTIVE: return``) — no lookup, no lock, no allocation — so
  the sites can live inside the serving stepper and the train loop
  without a measurable change (tests/test_metrics.py pins the disabled
  path like the chaos registry's).
- **Bounded memory.** Histograms hold fixed bucket arrays (no raw
  samples); counters/gauges are one float per name.
- **Percentiles from buckets.** ``Histogram.percentile`` estimates
  p50/p90/p99 by geometric interpolation inside the covering log
  bucket — relative error is bounded by the bucket growth factor
  (accuracy pinned against numpy in tests/test_metrics.py).
- **Subprocess-friendly.** ``MEGATRON_METRICS=1`` enables the registry
  at import time, so soak/bench children and drills opt in without code
  hooks.

The classes are also usable standalone (the disaggregated coordinator
owns a private ``Histogram`` for its SLO token-interval/TTFT
percentiles, live even when the global registry is off).
"""

from __future__ import annotations

import math
import os
import re
import threading
from bisect import bisect_left
from typing import Dict, List, Optional

__all__ = [
    "Histogram", "Ewma", "MetricsRegistry", "enable", "disable",
    "enabled", "registry", "inc", "set_gauge", "observe", "observe_ewma",
    "render_prometheus", "snapshot", "counter_value", "labeled",
]


def labeled(name: str, **labels) -> str:
    """Attach Prometheus labels to a metric name:
    ``labeled("fleet_replica_active", replica=0)`` →
    ``fleet_replica_active{replica="0"}``. The renderer keeps the label
    block verbatim (only the base name is sanitized) and merges
    histogram ``le`` labels into it — the fleet server exports
    per-replica gauges this way (one metric family, N labeled series,
    the Prometheus-native shape for per-replica dashboards)."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


class Histogram:
    """Log-bucketed histogram: bucket i covers
    (lo*growth^(i-1), lo*growth^i]; values ≤ lo land in bucket 0, values
    past hi in the overflow (+Inf) bucket. Thread-safe."""

    def __init__(self, lo: float = 1e-3, hi: float = 1e5,
                 growth: float = 1.25):
        assert lo > 0 and hi > lo and growth > 1.0
        n = int(math.ceil(math.log(hi / lo) / math.log(growth)))
        # Upper bucket edges; +Inf overflow is counts[-1].
        self.bounds: List[float] = [lo * growth ** i for i in range(n + 1)]
        self.growth = growth
        self.counts: List[int] = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float):
        idx = bisect_left(self.bounds, value)
        with self._lock:
            self.counts[idx] += 1
            self.count += 1
            self.sum += value

    def percentile(self, q: float) -> float:
        """Estimate the q-th percentile (q in [0, 100]) from the bucket
        counts, interpolating geometrically inside the covering bucket
        (log buckets → geometric interpolation keeps the relative error
        within one growth factor)."""
        with self._lock:
            counts = list(self.counts)
            total = self.count
        if total == 0:
            return 0.0
        rank = max(q / 100.0 * total, 1e-12)
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= rank:
                frac = (rank - cum) / c
                if i >= len(self.bounds):       # overflow bucket
                    return self.bounds[-1] * self.growth
                upper = self.bounds[i]
                lower = self.bounds[i - 1] if i > 0 else upper / self.growth
                return lower * (upper / lower) ** frac
            cum += c
        return self.bounds[-1] * self.growth    # unreachable if total>0

    def fraction_below(self, x: float) -> float:
        """Fraction of observations <= x, estimated from the bucket
        counts (geometric interpolation inside the covering bucket —
        the SLO-attainment read: fraction of latencies within budget).
        Returns 1.0 on an empty histogram (no evidence of violation)."""
        with self._lock:
            counts = list(self.counts)
            total = self.count
        if total == 0:
            return 1.0
        below = 0.0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if i >= len(self.bounds):            # overflow bucket
                upper = self.bounds[-1] * self.growth
                lower = self.bounds[-1]
            else:
                upper = self.bounds[i]
                lower = upper / self.growth
            if x >= upper:
                below += c
            elif x > lower:
                below += c * (math.log(x / lower)
                              / math.log(upper / lower))
        return min(1.0, below / total)

    def stats(self) -> Dict[str, float]:
        return {
            "count": self.count,
            "sum": round(self.sum, 6),
            "mean": round(self.sum / self.count, 6) if self.count else 0.0,
            "p50": round(self.percentile(50), 6),
            "p90": round(self.percentile(90), 6),
            "p99": round(self.percentile(99), 6),
        }

    # -- cross-process transport (inference/fleet_rpc.py) -----------------
    # A Histogram carries a lock, so the object itself cannot cross a
    # process boundary; its STATE can. Replica workers ship state dicts
    # in step/stats replies and the router reconstructs or merges —
    # percentiles and attainment then read identically on either side.
    def state(self) -> Dict:
        """Picklable full state (bounds + counts + sum)."""
        with self._lock:
            return {"bounds": list(self.bounds), "growth": self.growth,
                    "counts": list(self.counts), "count": self.count,
                    "sum": self.sum}

    @classmethod
    def from_state(cls, st: Dict) -> "Histogram":
        h = cls.__new__(cls)
        h.bounds = list(st["bounds"])
        h.growth = st["growth"]
        h.counts = list(st["counts"])
        h.count = st["count"]
        h.sum = st["sum"]
        h._lock = threading.Lock()
        return h

    def merge_state(self, st: Dict):
        """Accumulate another histogram's state into this one (the
        router's fleet-wide attainment view). Bucket layouts must match
        — both sides build from the same (lo, hi, growth)."""
        if list(st["bounds"]) != list(self.bounds):
            raise ValueError("histogram bucket layouts differ; cannot "
                             "merge")
        with self._lock:
            for i, c in enumerate(st["counts"]):
                self.counts[i] += c
            self.count += st["count"]
            self.sum += st["sum"]


class Ewma:
    """Exponentially-weighted moving average (the SLO-budget smoothing
    primitive, promoted into the registry so /metrics can export it)."""

    def __init__(self, alpha: float = 0.2):
        self.alpha = alpha
        self.value: Optional[float] = None

    def observe(self, x: float):
        self.value = (x if self.value is None
                      else self.alpha * x + (1 - self.alpha) * self.value)


class MetricsRegistry:
    """Named counters / gauges / EWMAs / histograms behind one lock
    (histograms additionally carry their own — they are handed out and
    observed lock-free of the registry)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.ewmas: Dict[str, Ewma] = {}
        self.histograms: Dict[str, Histogram] = {}

    # -- recording ---------------------------------------------------------
    def inc(self, name: str, value: float = 1):
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + value

    def set_gauge(self, name: str, value: float):
        with self._lock:
            self.gauges[name] = value

    def observe_ewma(self, name: str, value: float, alpha: float = 0.2):
        with self._lock:
            e = self.ewmas.get(name)
            if e is None:
                e = self.ewmas[name] = Ewma(alpha)
        e.observe(value)

    def histogram(self, name: str, lo: float = 1e-3, hi: float = 1e5,
                  growth: float = 1.25) -> Histogram:
        """Get-or-create a named histogram (bucket layout is fixed by
        the FIRST declaration; later calls reuse it)."""
        with self._lock:
            h = self.histograms.get(name)
            if h is None:
                h = self.histograms[name] = Histogram(lo, hi, growth)
        return h

    def observe(self, name: str, value: float, lo: float = 1e-3,
                hi: float = 1e5, growth: float = 1.25):
        self.histogram(name, lo, hi, growth).observe(value)

    # -- export ------------------------------------------------------------
    @staticmethod
    def _sanitize(name: str) -> str:
        """Sanitize the metric name; a ``labeled()`` suffix (the first
        '{' onward) is preserved verbatim."""
        base, brace, rest = name.partition("{")
        return re.sub(r"[^a-zA-Z0-9_:]", "_", base) + brace + rest

    @staticmethod
    def _series(name: str, suffix: str = "",
                extra_label: Optional[str] = None) -> str:
        """Compose a series line head for a possibly-labeled name:
        the suffix lands on the BASE name and extra labels merge into
        the existing label block (``h{replica="0"}`` + ``_bucket`` +
        ``le="1"`` → ``h_bucket{replica="0",le="1"}``)."""
        base, brace, rest = name.partition("{")
        if not brace:
            labels = f"{{{extra_label}}}" if extra_label else ""
            return base + suffix + labels
        inner = rest[:-1] if rest.endswith("}") else rest
        if extra_label:
            inner = f"{inner},{extra_label}"
        return f"{base}{suffix}{{{inner}}}"

    @staticmethod
    def _fmt(v: float) -> str:
        if isinstance(v, int):
            return str(v)
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(float(v))

    def render_prometheus(self) -> str:
        """Prometheus text exposition (counters, gauges, EWMAs-as-gauges,
        histograms with cumulative le buckets + _sum/_count)."""
        lines: List[str] = []
        with self._lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
            ewmas = {k: e.value for k, e in self.ewmas.items()
                     if e.value is not None}
            hists = dict(self.histograms)
        # TYPE lines carry the BASE family name (a labeled() name's
        # series share one family); emit each family's TYPE once.
        typed = set()

        def _type_line(n: str, kind: str):
            base = n.partition("{")[0]
            if base not in typed:
                typed.add(base)
                lines.append(f"# TYPE {base} {kind}")

        for name in sorted(counters):
            n = self._sanitize(name)
            _type_line(n, "counter")
            lines.append(f"{self._series(n)} {self._fmt(counters[name])}")
        for name in sorted(gauges):
            n = self._sanitize(name)
            _type_line(n, "gauge")
            lines.append(f"{self._series(n)} {self._fmt(gauges[name])}")
        for name in sorted(ewmas):
            n = self._series(self._sanitize(name), "_ewma")
            _type_line(n, "gauge")
            lines.append(f"{n} {self._fmt(ewmas[name])}")
        for name in sorted(hists):
            h = hists[name]
            n = self._sanitize(name)
            _type_line(n, "histogram")
            with h._lock:
                counts = list(h.counts)
                total, s = h.count, h.sum
            cum = 0
            for bound, c in zip(h.bounds, counts):
                cum += c
                # Suppress interior all-zero prefixes? No — Prometheus
                # expects the full cumulative series, but emitting every
                # log bucket is noisy; emit only buckets that change the
                # cumulative count, plus +Inf (cumulative semantics stay
                # exact for any quantile query).
                if c:
                    lines.append(self._series(
                        n, "_bucket", f'le="{bound:g}"') + f" {cum}")
            lines.append(self._series(n, "_bucket", 'le="+Inf"')
                         + f" {total}")
            lines.append(f"{self._series(n, '_sum')} {self._fmt(s)}")
            lines.append(f"{self._series(n, '_count')} {total}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict:
        """JSON-ready view (histograms as count/sum/percentiles)."""
        with self._lock:
            out = {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "ewmas": {k: e.value for k, e in self.ewmas.items()},
                "histograms": {},
            }
            hists = dict(self.histograms)
        for name, h in hists.items():
            out["histograms"][name] = h.stats()
        return out


# ---------------------------------------------------------------------------
# Module-level front door. _ACTIVE is the one-dict-truthiness disabled
# gate (chaos.py pattern): empty dict == disabled == every recording
# call returns after one check.
# ---------------------------------------------------------------------------

_ACTIVE: Dict[str, MetricsRegistry] = {}


def enable() -> MetricsRegistry:
    """Turn recording on (idempotent; keeps accumulated values)."""
    reg = _ACTIVE.get("registry")
    if reg is None:
        reg = MetricsRegistry()
        _ACTIVE["registry"] = reg
    return reg


def disable():
    """Turn recording off AND drop accumulated values (tests isolate
    through this; a paused-but-kept registry would be a new feature)."""
    _ACTIVE.clear()


def enabled() -> bool:
    return bool(_ACTIVE)


def registry() -> Optional[MetricsRegistry]:
    return _ACTIVE.get("registry")


def inc(name: str, value: float = 1):
    if not _ACTIVE:
        return
    # Atomic re-read: disable() can clear the dict between the
    # truthiness check and the index on another thread — a KeyError
    # here would surface as a serving step failure.
    reg = _ACTIVE.get("registry")
    if reg is not None:
        reg.inc(name, value)


def set_gauge(name: str, value: float):
    if not _ACTIVE:
        return
    reg = _ACTIVE.get("registry")
    if reg is not None:
        reg.set_gauge(name, value)


def observe(name: str, value: float, lo: float = 1e-3, hi: float = 1e5,
            growth: float = 1.25):
    if not _ACTIVE:
        return
    reg = _ACTIVE.get("registry")
    if reg is not None:
        reg.observe(name, value, lo, hi, growth)


def observe_ewma(name: str, value: float, alpha: float = 0.2):
    if not _ACTIVE:
        return
    reg = _ACTIVE.get("registry")
    if reg is not None:
        reg.observe_ewma(name, value, alpha)


def counter_value(name: str) -> float:
    """Current counter value (0 when disabled/absent) — test helper and
    /stats convenience."""
    reg = _ACTIVE.get("registry")
    if reg is None:
        return 0.0
    return reg.counters.get(name, 0.0)


def render_prometheus() -> str:
    reg = _ACTIVE.get("registry")
    if reg is None:
        return "# metrics registry disabled\n"
    return reg.render_prometheus()


def snapshot() -> Dict:
    reg = _ACTIVE.get("registry")
    if reg is None:
        return {"enabled": False}
    out = reg.snapshot()
    out["enabled"] = True
    return out


if os.environ.get("MEGATRON_METRICS"):
    enable()
