"""Theoretical memory usage report.

Parity with /root/reference/megatron/training/theoretical_memory_usage.py:
estimates per-chip parameter, optimizer-state, gradient, and activation
memory for a config + parallel layout, so OOMs are predictable before
compile.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from megatronapp_tpu.config.parallel_config import ParallelConfig
from megatronapp_tpu.config.transformer_config import TransformerConfig


def report_theoretical_memory(cfg: TransformerConfig,
                              parallel: ParallelConfig,
                              micro_batch_size: int, seq_length: int,
                              num_devices: int,
                              distributed_optimizer: bool = True
                              ) -> Dict[str, float]:
    """Per-chip GiB estimates (fp32 params + adam, compute-dtype
    activations)."""
    n_params = cfg.num_parameters()
    tp = parallel.tensor_parallel
    pp = parallel.pipeline_parallel
    dp = max(num_devices // max(parallel.model_parallel_size *
                                parallel.expert_parallel, 1), 1)

    params_per_chip = n_params / (tp * pp)
    param_bytes = params_per_chip * 4                      # fp32 master
    grad_bytes = params_per_chip * 4                       # fp32 grads
    # Adam m+v; sharded over dp with the distributed optimizer (ZeRO-1 —
    # reference distrib_optimizer docs).
    opt_bytes = params_per_chip * 8 / (dp if distributed_optimizer else 1)

    # Activation estimate per microbatch per layer (selective recompute):
    # residual stream + per-layer checkpointed inputs, compute dtype (2B).
    h = cfg.hidden_size
    s = seq_length // max(parallel.context_parallel, 1)
    b = micro_batch_size
    act_per_layer = s * b * h * 2 * 4  # ln inputs, attn out, mlp in/out
    layers_per_chip = cfg.num_layers / pp
    act_bytes = act_per_layer * layers_per_chip / tp
    # Logits buffer dominates small models.
    logit_bytes = b * s * cfg.vocab_size * 4 / tp

    gib = 1 << 30
    report = {
        "params_gib": param_bytes / gib,
        "grads_gib": grad_bytes / gib,
        "optimizer_gib": opt_bytes / gib,
        "activations_gib": act_bytes / gib,
        "logits_gib": logit_bytes / gib,
    }
    report["total_gib"] = float(sum(report.values()))
    report["num_parameters"] = float(n_params)
    return report


def format_report(report: Dict[str, float]) -> str:
    return (f"theoretical memory/chip: params {report['params_gib']:.2f} + "
            f"grads {report['grads_gib']:.2f} + "
            f"opt {report['optimizer_gib']:.2f} + "
            f"acts {report['activations_gib']:.2f} + "
            f"logits {report['logits_gib']:.2f} = "
            f"{report['total_gib']:.2f} GiB "
            f"({report['num_parameters']/1e6:.0f}M params)")
