"""FLOPs accounting for throughput/MFU logging.

Parity with /root/reference/megatron/training/training.py:142
(num_floating_point_operations): counts dense matmul + attention + logit
FLOPs per token for the standard transformer; used by training_log to report
TFLOP/s/device and by bench.py for MFU.
"""

from __future__ import annotations

from megatronapp_tpu.config.transformer_config import (
    ActivationKind, TransformerConfig,
)

# Peak bf16 FLOP/s per chip for MFU math (TPU v5e = 197 TFLOP/s bf16 —
# the oft-quoted 394 is the int8 TOPS figure; v5p ≈ 459 bf16; override
# with the actual platform at call sites if known).
TPU_PEAK_FLOPS = {
    "v5litepod": 197e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6e": 918e12,
    "v6 lite": 918e12,
    "cpu": 1e12,
}


def flops_per_token(cfg: TransformerConfig, seq_len: int) -> float:
    """Forward+backward FLOPs per token (3x forward matmul FLOPs)."""
    h = cfg.hidden_size
    d = cfg.head_dim
    nq, nkv = cfg.num_attention_heads, cfg.num_query_groups
    l = cfg.num_layers

    # Attention projections: Q + KV + out.
    proj = 2 * h * (nq * d) + 2 * h * (2 * nkv * d) + 2 * (nq * d) * h
    # Attention scores + context: 2 * S * (nq*d) each (per token, seq_len kv).
    attn = 2 * 2 * seq_len * nq * d
    # MLP.
    f = cfg.ffn_hidden_size
    if cfg.is_moe:
        f_active = cfg.moe_ffn_hidden_size * cfg.moe_router_topk
        if cfg.moe_shared_expert_intermediate_size:
            f_active += cfg.moe_shared_expert_intermediate_size
        f = f_active
    gated = cfg.activation in (ActivationKind.swiglu, ActivationKind.geglu)
    mlp = (3 if gated else 2) * 2 * h * f
    per_layer = proj + attn + mlp
    logits = 2 * h * cfg.vocab_size
    fwd = l * per_layer + logits
    return 3.0 * fwd  # fwd + bwd (2x fwd)


def mfu(tokens_per_sec_per_chip: float, cfg: TransformerConfig,
        seq_len: int, peak_flops: float) -> float:
    return tokens_per_sec_per_chip * flops_per_token(cfg, seq_len) / peak_flops
