"""E2E training-run metrics (one-logger parity).

Parity with /root/reference/megatron/training/one_logger_utils.py
(on_train_start :18, _produce_e2e_metrics :76, track_e2e_metrics :209,
on_save_checkpoint_start/success/end :226-443, finish :463): a process-
wide tracker accumulating end-to-end run health metrics — train-loop
time, per-iteration averages, consumed samples/tokens, throughput,
checkpoint save counts and sync time — flushed through the standard
metrics sinks (training/metrics.py jsonl/tensorboard/wandb) instead of
the reference's proprietary one-logger service.
"""

from __future__ import annotations

import time
from typing import Dict, Optional


class E2EMetricsTracker:
    """Accumulates E2E metrics across a training run."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._start_time: Optional[float] = None
        self._start_iteration = 0
        self._samples_start = 0
        self._train_iters_target = 0
        self._seq_length = 0
        self._iter_time_total_s = 0.0
        self._tracked_iterations = 0
        self._validation_time_total_s = 0.0
        self._validation_count = 0
        self._save_count = 0
        self._save_time_total_s = 0.0
        self._consumed_samples = 0

    # -- lifecycle ------------------------------------------------------
    def on_train_start(self, start_iteration: int, consumed_samples: int,
                       train_iters: int, seq_length: int):
        """reference on_train_start: records loop start + targets."""
        self._start_time = time.perf_counter()
        self._start_iteration = start_iteration
        self._samples_start = consumed_samples
        self._consumed_samples = consumed_samples
        self._train_iters_target = train_iters
        self._seq_length = seq_length

    def track_iterations(self, n: int, duration_s: float, samples: int):
        """Accumulate a window of n completed iterations (the loop's
        sync-point cadence; reference track_e2e_metrics per-iteration)."""
        self._iter_time_total_s += duration_s
        self._tracked_iterations += n
        self._consumed_samples += samples

    def track_validation(self, duration_s: float):
        self._validation_time_total_s += duration_s
        self._validation_count += 1

    def on_save_checkpoint(self, duration_s: float):
        """reference on_save_checkpoint_start/end: count + sync time."""
        self._save_count += 1
        self._save_time_total_s += duration_s

    # -- reporting ------------------------------------------------------
    def metrics(self) -> Dict[str, float]:
        """The reference's e2e_metrics dict (names kept for familiarity,
        msecs units as in _produce_e2e_metrics)."""
        if self._start_time is None:
            return {}
        elapsed = time.perf_counter() - self._start_time
        n = max(self._tracked_iterations, 1)
        samples = self._consumed_samples - self._samples_start
        tokens = samples * self._seq_length
        out = {
            "app_train_loop_time_msecs": round(elapsed * 1e3, 1),
            "train_iterations_time_msecs_total":
                round(self._iter_time_total_s * 1e3, 1),
            "train_iterations_time_msecs_avg":
                round(self._iter_time_total_s * 1e3 / n, 3),
            "tracked_train_iterations": self._tracked_iterations,
            "iteration_start": self._start_iteration,
            "train_iterations_target": self._train_iters_target,
            "train_samples_start": self._samples_start,
            "train_samples": samples,
            "train_tokens": tokens,
            "validation_iterations_time_msecs_total":
                round(self._validation_time_total_s * 1e3, 1),
            "tracked_validation_iterations": self._validation_count,
            "save_checkpoint_count": self._save_count,
            "save_checkpoint_sync_time_total_secs":
                round(self._save_time_total_s, 3),
        }
        if self._iter_time_total_s > 0:
            out["train_throughput_tokens_per_sec"] = round(
                tokens / self._iter_time_total_s, 1)
        return out

    def finish(self, metrics_logger=None, log_fn=None, step: int = 0):
        """reference finish(): emit the final E2E summary through the
        metrics sinks and/or the run log."""
        m = self.metrics()
        if not m:
            return m
        if metrics_logger is not None:
            metrics_logger.log(step, {f"e2e/{k}": v for k, v in m.items()})
        if log_fn is not None:
            log_fn("e2e: " + " ".join(f"{k}={v}" for k, v in sorted(
                m.items())))
        return m


_TRACKER = E2EMetricsTracker()


def get_e2e_tracker() -> E2EMetricsTracker:
    return _TRACKER
