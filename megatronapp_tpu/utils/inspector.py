"""Workload-inspector HTTP server.

Parity with /root/reference/megatron/training/arguments.py:1346-1351
(--run-workload-inspector-server, started training.py:2026-2032) and the
StragglerDetector's curl on/off port (core/utils.py:1030, toggled via
`curl host:port/...`): a tiny stdlib HTTP endpoint on the trainer host
that exposes live run state as JSON and lets an operator flip the
straggler detector at runtime without touching the process.

Endpoints:
  GET /status              — step, losses, throughput, timers, straggler
  GET /straggler/enable    — turn the step-time detector on
  GET /straggler/disable   — off
  GET /probe               — per-chip RTT probe (slow-chip localization)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional


class WorkloadInspector:
    """Shared mutable run state + HTTP server."""

    def __init__(self):
        self._state: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    def update(self, **fields):
        with self._lock:
            self._state.update(fields)

    def snapshot(self) -> Dict[str, Any]:
        from megatronapp_tpu.utils.straggler import get_straggler_detector
        from megatronapp_tpu.utils.timers import get_timers
        det = get_straggler_detector()
        with self._lock:
            snap = dict(self._state)
        snap["straggler"] = {
            "enabled": det.enabled,
            "flagged_steps": [r.step for r in det.flagged[-16:]],
            "window_samples": len(det.window),
        }
        try:
            snap["timers_s"] = get_timers().elapsed_all(reset=False)
        except Exception:
            pass
        return snap

    def start(self, port: int = 0, host: str = "127.0.0.1") -> int:
        """Start serving; returns the bound port (0 = ephemeral)."""
        inspector = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence request logging
                pass

            def do_GET(self):
                from megatronapp_tpu.utils.straggler import (
                    detect_slow_chips, get_straggler_detector,
                    probe_chip_rtts,
                )
                det = get_straggler_detector()
                if self.path.startswith("/straggler/enable"):
                    det.enable()
                    body = {"straggler": "enabled"}
                elif self.path.startswith("/straggler/disable"):
                    det.disable()
                    body = {"straggler": "disabled"}
                elif self.path.startswith("/probe"):
                    rtts = probe_chip_rtts()
                    body = {"rtts": rtts,
                            "slow": detect_slow_chips(rtts)}
                elif self.path.startswith("/status"):
                    body = inspector.snapshot()
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                payload = json.dumps(body).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


_INSPECTOR = WorkloadInspector()


def get_inspector() -> WorkloadInspector:
    return _INSPECTOR
