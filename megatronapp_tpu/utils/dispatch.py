"""Per-decode-step dispatch accounting (ISSUE 11 observability).

At decode batch sizes the per-token step is dispatch-dominated, not
FLOP-dominated (PERF.md round-2: 35.7% MFU for the full step vs 63.6%
for one layer body) — so the megakernel work's figure of merit is "how
many kernels does one decode step launch", measured deterministically
(no wall clock, works while the TPU tunnel is down).

Two probes, both off the traced/compiled module:

``jaxpr_launch_stats`` — the GATE metric. Walks the closed jaxpr of the
decode step and estimates kernel launches per executed step: each
``pallas_call`` is exactly ONE launch (a TPU custom call — on CPU the
interpret-mode expansion is a simulation detail, which is why the CPU
HLO text is NOT the gate: it inlines the kernels and inverts the
comparison), a ``scan`` contributes length × its body's launches plus
ceil(length / unroll) loop steps (the while-iteration overhead the
scan-unroll lever removes), and ordinary equations count one launch
apiece minus a small free-op set (reshape & friends never dispatch).
Pre-fusion op counts overestimate both A/B legs the same way, so the
REDUCTION is sound; tests and tools/megakernel_benchmark.py gate on it.

``module_dispatch_stats`` / ``compiled_stats`` — the RECORD metrics:
optimized-HLO fusion/custom-call/while counts plus the XLA cost-model
totals (flops, bytes accessed) of the actually-compiled module, reported
alongside for the round tables and re-validated on-chip when the tunnel
returns.
"""

from __future__ import annotations

import math
import re
from typing import Dict, Optional

# Equations that never become their own kernel launch (pure
# layout/metadata in XLA).
_FREE_PRIMS = frozenset({
    "reshape", "squeeze", "expand_dims", "broadcast_in_dim",
    "stop_gradient", "copy",
})

# Call-like primitives whose sub-jaxpr executes inline exactly once.
_CALL_PARAM_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr")


def _sub_jaxpr(v):
    return v.jaxpr if hasattr(v, "jaxpr") else v


def jaxpr_launch_stats(jaxpr) -> Dict[str, float]:
    """Estimated kernel launches for one execution of `jaxpr`
    (jax.make_jaxpr output or an inner jaxpr). Returns
    {launches, kernels (pallas calls), loop_steps, eqns}."""
    launches = kernels = loop_steps = eqns = 0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        eqns += 1
        if name == "pallas_call":
            kernels += 1
            launches += 1
            continue
        if name == "scan":
            length = int(eqn.params.get("length", 1))
            unroll = int(eqn.params.get("unroll", 1) or 1)
            inner = jaxpr_launch_stats(_sub_jaxpr(eqn.params["jaxpr"]))
            launches += length * inner["launches"]
            kernels += length * inner["kernels"]
            loop_steps += (math.ceil(length / max(1, unroll))
                           + length * inner["loop_steps"])
            continue
        if name == "while":
            # Trip count is data-dependent: count the body once and one
            # loop step (decode steps built here carry no bare whiles;
            # scans are the loop of record).
            inner = jaxpr_launch_stats(_sub_jaxpr(eqn.params["body_jaxpr"]))
            launches += inner["launches"]
            kernels += inner["kernels"]
            loop_steps += 1 + inner["loop_steps"]
            continue
        if name == "cond":
            branches = [jaxpr_launch_stats(_sub_jaxpr(b))
                        for b in eqn.params["branches"]]
            worst = max(branches, key=lambda s: s["launches"])
            launches += worst["launches"]
            kernels += worst["kernels"]
            loop_steps += worst["loop_steps"]
            continue
        handled = False
        for key in _CALL_PARAM_KEYS:
            if key in eqn.params:
                inner = jaxpr_launch_stats(_sub_jaxpr(eqn.params[key]))
                launches += inner["launches"]
                kernels += inner["kernels"]
                loop_steps += inner["loop_steps"]
                handled = True
                break
        if handled:
            continue
        if name not in _FREE_PRIMS:
            launches += 1
    return {"launches": launches, "kernels": kernels,
            "loop_steps": loop_steps, "eqns": eqns}


def launch_stats(fn, *args, **kwargs) -> Dict[str, float]:
    """jaxpr_launch_stats of `fn` traced at the given (abstract or
    concrete) arguments. `fn` may be jitted (the pjit wrapper is
    recursed through) — nothing is compiled or executed."""
    import jax
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    stats = jaxpr_launch_stats(closed.jaxpr)
    stats["dispatches_per_step"] = stats["launches"] + stats["loop_steps"]
    return stats


# ---------------------------------------------------------------------------
# Compiled-module record metrics (optimized HLO text + XLA cost model)
# ---------------------------------------------------------------------------

_HDR = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*(?:\(.*)?\{\s*$")
_WHILE_BODY = re.compile(r"\bbody=%?([\w.\-]+)")


def _split_computations(hlo_text: str) -> Dict[str, str]:
    """{computation name: body text} from HLO long text. Line-based:
    computation headers end with '{' and bodies close with a bare '}'
    (inline one-line metadata braces never span lines)."""
    comps: Dict[str, str] = {}
    name = None
    buf: list = []
    for line in hlo_text.splitlines():
        if name is None:
            m = _HDR.match(line.strip())
            if m and "=" not in line.split("{")[0]:
                name = m.group(2)
                buf = []
        else:
            if line.strip() == "}":
                comps[name] = "\n".join(buf)
                name = None
            else:
                buf.append(line)
    return comps


def module_dispatch_stats(hlo_text: str) -> Dict:
    """Fusion / custom-call / while counts of one optimized HLO module,
    split into while-loop bodies vs the rest. NOTE: on CPU the
    interpret-mode Pallas kernels are inlined into ordinary HLO here —
    these counts are the record of what THIS backend compiled, not the
    TPU launch count (jaxpr_launch_stats is the gate)."""
    comps = _split_computations(hlo_text)
    body_names = set(_WHILE_BODY.findall(hlo_text))
    in_loop = {"fusions": 0, "custom_calls": 0}
    out_loop = {"fusions": 0, "custom_calls": 0}
    for name, body in comps.items():
        # Fusion computations' insides execute as ONE kernel — count
        # only the call sites.
        if name.startswith("fused_computation"):
            continue
        tgt = in_loop if name in body_names else out_loop
        tgt["fusions"] += len(re.findall(r"=\s*\S+\s+fusion\(", body))
        tgt["custom_calls"] += len(
            re.findall(r"=\s*\S+\s+custom-call\(", body))
    return {"computations": len(comps),
            "while_loops": len(body_names),
            "in_loop": in_loop, "out_of_loop": out_loop}


def compiled_stats(jitted, *args, **kwargs) -> Dict:
    """Lower + compile `jitted` at the given (abstract or concrete)
    arguments: module_dispatch_stats of the optimized HLO plus the XLA
    cost-model totals (flops / bytes accessed) when the backend exposes
    them. This is an AOT compile — one extra compilation at these
    shapes; callers cache."""
    compiled = jitted.lower(*args, **kwargs).compile()
    stats = module_dispatch_stats(compiled.as_text())
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        stats["cost"] = {k: float(cost[k])
                         for k in ("flops", "bytes accessed")
                         if k in cost}
    except Exception:  # noqa: BLE001 — cost model is backend-optional
        pass
    return stats
