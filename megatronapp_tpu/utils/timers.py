"""Named timers with log-level gating and cross-process reduction.

Parity with /root/reference/megatron/core/timers.py (450 LoC): `Timers` is
a registry of named `Timer` objects with start/stop/elapsed, a log-level
gate (timers above the configured level are no-ops), and `log()` /
`get_all_timers_string()` that reduce elapsed times across ranks
(min/max/mean) before printing.

TPU-native notes: the reference's `barrier=True` issues a
torch.distributed.barrier before each start/stop so GPU ranks measure the
same region. Under JAX the host dispatches asynchronously, so a barrier
means forcing pending device work instead: pass `barrier_fn` (typically a
``lambda: jax.device_get(token)`` on a live array, or
``jax.effects_barrier``). Cross-"rank" reduction uses
jax.process_index/process_count when multi-host, degrading to a single
entry locally.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional


class Timer:
    """One named timer (reference core/timers.py Timer)."""

    def __init__(self, name: str):
        self.name = name
        self._elapsed = 0.0
        self._count = 0
        self._started = False
        self._start_time = 0.0

    def start(self, barrier_fn: Optional[Callable] = None):
        if self._started:
            raise RuntimeError(f"timer {self.name} already started")
        if barrier_fn is not None:
            barrier_fn()
        self._start_time = time.perf_counter()
        self._started = True

    def stop(self, barrier_fn: Optional[Callable] = None):
        if not self._started:
            raise RuntimeError(f"timer {self.name} was not started")
        if barrier_fn is not None:
            barrier_fn()
        self._elapsed += time.perf_counter() - self._start_time
        self._count += 1
        self._started = False

    def elapsed(self, reset: bool = True) -> float:
        """Total elapsed seconds (optionally resetting, reference
        semantics: elapsed() resets by default)."""
        running = self._started
        if running:
            self.stop()
        out = self._elapsed
        if reset:
            self._elapsed = 0.0
            self._count = 0
        if running:
            self.start()
        return out

    @property
    def count(self) -> int:
        return self._count

    def reset(self):
        self._elapsed = 0.0
        self._count = 0


class _NullTimer:
    """No-op stand-in for timers above the log level."""

    def start(self, *a, **k):
        pass

    def stop(self, *a, **k):
        pass

    def elapsed(self, *a, **k):
        return 0.0

    def reset(self):
        pass


_NULL = _NullTimer()


class _BarrierTimer:
    """View over a Timer that applies the registry's barrier_fn on
    start/stop (reference Timer(barrier=True) semantics: all ranks /
    pending device work synchronize before the measurement edges)."""

    def __init__(self, timer: Timer, barrier_fn: Callable):
        self._t = timer
        self._b = barrier_fn

    def start(self):
        self._t.start(barrier_fn=self._b)

    def stop(self):
        self._t.stop(barrier_fn=self._b)

    def elapsed(self, reset: bool = True) -> float:
        return self._t.elapsed(reset=reset)

    def reset(self):
        self._t.reset()


class Timers:
    """Registry with log-level gating (reference Timers.__call__).

    timers = Timers(log_level=1)
    timers("forward", log_level=0).start()
    ...
    timers("forward").stop()
    print(timers.get_all_timers_string(normalizer=steps))
    """

    def __init__(self, log_level: int = 2,
                 barrier_fn: Optional[Callable] = None):
        self.log_level = log_level
        self.barrier_fn = barrier_fn
        self._timers: Dict[str, Timer] = {}
        self._levels: Dict[str, int] = {}

    def __call__(self, name: str, log_level: int = 0, barrier: bool = False):
        if name in self._timers:
            t = self._timers[name]
        elif log_level > self.log_level:
            return _NULL
        else:
            t = self._timers.setdefault(name, Timer(name))
            self._levels[name] = log_level
        if barrier and self.barrier_fn is not None:
            return _BarrierTimer(t, self.barrier_fn)
        return t

    def elapsed_all(self, reset: bool = True) -> Dict[str, float]:
        return {n: t.elapsed(reset=reset)
                for n, t in self._timers.items()}

    def get_all_timers_string(self, names: Optional[List[str]] = None,
                              normalizer: float = 1.0,
                              reset: bool = True) -> str:
        """'(min, max) time across ranks (ms)'-style line (reference
        log())."""
        assert normalizer > 0
        names = names or sorted(self._timers)
        parts = []
        for n in names:
            if n not in self._timers:
                continue
            e = self._timers[n].elapsed(reset=reset) * 1e3 / normalizer
            lo, hi = self._reduce(e)
            parts.append(f"{n}: ({lo:.2f}, {hi:.2f})")
        return ("time across ranks (ms) | " + " | ".join(parts)
                if parts else "")

    def log(self, names: Optional[List[str]] = None,
            normalizer: float = 1.0, reset: bool = True,
            write_fn: Callable[[str], None] = print):
        s = self.get_all_timers_string(names, normalizer, reset)
        if s:
            write_fn(s)

    @staticmethod
    def _reduce(value: float):
        """(min, max) across processes: all-gather the scalar via
        multihost_utils when multi-host, identity on a single process."""
        import jax
        if jax.process_count() == 1:
            return value, value
        import numpy as np
        from jax.experimental import multihost_utils
        allv = np.asarray(multihost_utils.process_allgather(
            np.asarray([value])))
        return float(allv.min()), float(allv.max())


_GLOBAL_TIMERS: Optional[Timers] = None


def get_timers(log_level: int = 2) -> Timers:
    """Global registry (reference global_vars.get_timers)."""
    global _GLOBAL_TIMERS
    if _GLOBAL_TIMERS is None:
        _GLOBAL_TIMERS = Timers(log_level=log_level)
    return _GLOBAL_TIMERS
