"""Chaos fault-injection registry (ISSUE 6).

A small registry of NAMED fault-injection sites threaded through the
hot paths that matter for resilience drills. Production code calls
``fire(site)`` (raising sites) or ``should_fire(site)`` (boolean sites)
at the injection point; tests ``arm()`` a site to make it misbehave a
bounded number of times. The reference achieves the same ends with
scattered mechanisms (RerunErrorInjector's --error-injection-rate,
ft_integration's maybe_setup_simulated_fault); this registry gives them
one front door and makes "every failure mode has a drill" testable.

Design constraints:

- **Zero-cost when disabled.** The disabled path is a single truthiness
  check of a module-level dict (``if not _ARMED: return``) — no lookup,
  no lock, no allocation — so the sites can live inside the train step
  loop and the serving stepper without a measurable step-time change.
- **Bounded.** An armed fault fires ``times`` times (after skipping the
  first ``after`` hits) and then disarms itself: drills test recovery,
  not permanent outage.
- **Subprocess-friendly.** ``MEGATRON_CHAOS="site[:times[:after]],..."``
  arms sites at import time, so subprocess drills (SIGTERM + resume,
  crash-loop) need no code hooks in the child.

Sites (each must be exercised by at least one test —
tests/test_resilience.py pins this registry against its drill list):

- ``checkpoint-save``        durable (Orbax) checkpoint write fails —
                             exercises CheckpointManager's bounded
                             retry-with-backoff.
- ``local-checkpoint-save``  fast local .npz checkpoint write fails —
                             exercises the train loop's warn-and-continue
                             (local checkpoints are best-effort).
- ``step-nan``               the step's loss is replaced with NaN at the
                             validation point — same injection point as
                             --error-injection-rate (the rerun state
                             machine), armable deterministically.
- ``stepper-step``           the serving stepper thread's engine.step()
                             raises — exercises the DynamicBatchingDriver
                             watchdog (error frames, pool reclaim,
                             crash-loop backoff, restart accounting).
- ``paged-evict``            the paged KV block allocator's LRU eviction
                             fails (inference/paged_cache.py _take_free)
                             — exercises admit/ensure_capacity rollback:
                             no leaked refcounts, audit() passes, the
                             next request succeeds.
- ``paged-cow``              the copy-on-write block copy of a fully
                             cached prompt fails (_copy_block) —
                             exercises the admit rollback path with
                             cached-prefix refs already acquired.
- ``spec-verify``            a speculative verify round fails AFTER the
                             multi-query step wrote the draft tokens' KV
                             but before acceptance was applied
                             (dynamic_engine._spec_round) — exercises
                             the round's rollback: every slot rewinds to
                             its last verified length, pool audit()
                             passes, and the retried round leaves the
                             emitted stream unchanged.
- ``kv-quant-write``         an int8-pool chunk write fails between
                             quantize and the page-table commit — in the
                             engine's chunk-scatter prefill
                             (dynamic_engine._paged_prefill_chunked) and
                             the disagg prefill worker's shipped-chunk
                             write (disagg.PrefillWorker.advance) —
                             exercises the admit rollback (blocks
                             released, request requeued, audit clean)
                             and the worker's untouched-pool retry.
- ``fleet-migrate``          a live session migration dies between the
                             source pool's KV export and the
                             destination's import
                             (inference/fleet.FleetRouter
                             .migrate_request) — the replica-death-mid-
                             migration point: exercises the
                             exception-safe rollback (export is
                             read-only, import all-or-nothing, so the
                             source slot stays intact, both pools
                             audit() clean, and the retried stream is
                             bit-identical).
- ``fleet-rpc``              a cross-process fleet RPC reply is lost
                             AFTER the replica serialized + sent it and
                             the router deserialized it, but BEFORE the
                             router commits it (inference/fleet_rpc
                             .ReplicaClient.call) — the lost-
                             acknowledgement window: exercises the
                             router's rollback verbs (idempotent evict
                             + resubmit for admission, destination
                             evict for migration, sessions-resync for a
                             lost step reply) — zero sessions lost,
                             pools audit() clean, streams unchanged.
- ``kv-spill``               a host-RAM KV spill transfer dies in the
                             worst window (dynamic_engine park/unpark,
                             ISSUE 20): parking, between the read-only
                             host copy (export_slot) and the page-table
                             release — nothing has mutated, so the
                             rollback is "do nothing" and the session
                             keeps decoding in its slot; unparking (the
                             mirror), between the destination
                             import_slot and the spill-entry release —
                             the imported blocks return to the pool and
                             the session stays parked. Either way
                             audit() passes and the resumed stream is
                             token-exact.
- ``lora-load``              a LoRA adapter fetch dies between reading
                             the adapter's weights from the registry
                             and committing them into the HBM bank
                             (inference/lora.AdapterCache.acquire) —
                             exercises the cache's exception-safe
                             rollback (no slot taken, no resident
                             evicted, refcounts/LRU books unchanged,
                             audit() clean) and the engine admission
                             rollback (pool blocks released, request
                             requeued, retry succeeds).

Simulated whole-process faults (hang / exit) are flag-driven rather than
registry-driven: --simulated-fault KIND:DELAY routes through
training/ft_integration.maybe_setup_simulated_fault.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Dict, Optional

SITES = (
    "checkpoint-save",
    "local-checkpoint-save",
    "step-nan",
    "stepper-step",
    "paged-evict",
    "paged-cow",
    "spec-verify",
    "kv-quant-write",
    "fleet-migrate",
    "fleet-rpc",
    "kv-spill",
    "lora-load",
)


class ChaosFault(RuntimeError):
    """The exception raised by an armed raising site."""


@dataclasses.dataclass
class _Fault:
    times: int = 1      # remaining fires (then auto-disarm)
    after: int = 0      # skip this many hits before the first fire
    hits: int = 0


_ARMED: Dict[str, _Fault] = {}
_LOCK = threading.Lock()


def arm(site: str, times: int = 1, after: int = 0) -> None:
    """Arm `site` to fire `times` times, skipping the first `after`
    hits. Raising sites raise ChaosFault; boolean sites return True."""
    if site not in SITES:
        raise ValueError(f"unknown chaos site {site!r}; known: {SITES}")
    if times < 1 or after < 0:
        raise ValueError("times must be >= 1 and after >= 0")
    with _LOCK:
        _ARMED[site] = _Fault(times=times, after=after)


def disarm(site: Optional[str] = None) -> None:
    """Disarm one site (or all when site is None)."""
    with _LOCK:
        if site is None:
            _ARMED.clear()
        else:
            _ARMED.pop(site, None)


def active() -> bool:
    return bool(_ARMED)


def _consume(site: str) -> bool:
    with _LOCK:
        f = _ARMED.get(site)
        if f is None:
            return False
        f.hits += 1
        if f.hits <= f.after:
            return False
        f.times -= 1
        if f.times <= 0:
            del _ARMED[site]
        return True


def should_fire(site: str) -> bool:
    """Boolean sites (e.g. step-nan): True when the armed fault fires.
    The disabled path is one dict truthiness check."""
    if not _ARMED:
        return False
    return _consume(site)


def fire(site: str) -> None:
    """Raising sites (e.g. checkpoint-save): raises ChaosFault when the
    armed fault fires. The disabled path is one dict truthiness check."""
    if not _ARMED:
        return
    if _consume(site):
        raise ChaosFault(f"chaos: injected fault at site {site!r}")


def configure_from_env(value: Optional[str] = None) -> None:
    """Arm sites from a spec string "site[:times[:after]],..." —
    defaults to the MEGATRON_CHAOS environment variable, so subprocess
    drills arm the child without code hooks."""
    spec = value if value is not None else os.environ.get("MEGATRON_CHAOS")
    if not spec:
        return
    for part in spec.split(","):
        fields = part.strip().split(":")
        if not fields[0]:
            continue
        times = int(fields[1]) if len(fields) > 1 else 1
        after = int(fields[2]) if len(fields) > 2 else 0
        arm(fields[0], times=times, after=after)


configure_from_env()
