"""MegaScope training-mode WebSocket server.

Parity with /root/reference/megatron/training/training_wsserver.py:39-146 +
the training-loop integration (training.py:1975-2024): the frontend sends
``run_training_step`` with visualization / disturbance / compressor configs;
training executes one step with those configs applied and streams captured
tensor payloads back, then a step summary.

Wire contract (reference :46-52): per capture the server sends
  {"update_type": <FlagType value>, "layer_id": int, "site": str,
   "result": [[...]]}
then {"type": "step_done", "iteration": i, "loss": f}.

Config changes that alter which sites/disturbances are traced in trigger a
recompile of the step (documented hard part, SURVEY §7) — the session keys
its jit cache on the scope/disturbance config versions.
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Dict, List, Optional

import jax
import numpy as np

from megatronapp_tpu.scope.disturbance import get_disturbance
from megatronapp_tpu.scope.hooks import _SITE_TO_FLAG, capture_payload
from megatronapp_tpu.scope.tensor_tracer import get_tensor_tracer


class TrainingScopeSession:
    """Owns train state + a rebuildable step function; one step per
    run_step() call with the requested scope configs applied."""

    def __init__(self, model_cfg, parallel_cfg, train_cfg, opt_cfg,
                 batch_iter=None, ctx=None):
        from megatronapp_tpu.data.mock import mock_batches
        from megatronapp_tpu.models.gpt import init_gpt_params
        from megatronapp_tpu.parallel.mesh import build_mesh
        from megatronapp_tpu.training.optimizer import get_optimizer
        from megatronapp_tpu.training.train_state import setup_train_state

        self.model_cfg = model_cfg
        self.train_cfg = train_cfg
        self.opt_cfg = opt_cfg
        self.ctx = ctx or build_mesh(parallel_cfg)
        self.optimizer = get_optimizer(opt_cfg, train_cfg.train_iters)
        rng = jax.random.PRNGKey(train_cfg.seed)
        self.state, self.shardings, _ = setup_train_state(
            rng, lambda k: init_gpt_params(k, model_cfg), self.optimizer,
            self.ctx)
        self.batch_iter = batch_iter or mock_batches(
            train_cfg.seq_length, model_cfg.vocab_size,
            train_cfg.global_batch_size, seed=train_cfg.seed)
        self.iteration = 0
        self._step_cache = {}
        self._lock = threading.Lock()

    def _build_step(self):
        from megatronapp_tpu.training.train import gpt_microbatch_loss
        from megatronapp_tpu.training.train_step import make_train_step
        dist = get_disturbance()
        # Key on canonical config CONTENT (not a monotonic version counter,
        # which would force a recompile every step and leak executables).
        dist_key = tuple(sorted(
            (site, c.kind, c.scale, c.layers)
            for site, c in dist.sites.items()))
        key = (dist_key,
               get_tensor_tracer().enabled,
               tuple(sorted((lid, tuple(sorted(f.value for f in flags)))
                            for lid, flags in
                            get_tensor_tracer().flags.items())))
        if key not in self._step_cache:
            loss_fn = gpt_microbatch_loss(self.model_cfg, ctx=self.ctx)
            self._step_cache[key] = make_train_step(
                loss_fn, self.optimizer, self.opt_cfg, self.ctx,
                self.shardings, self.train_cfg.train_iters)
        return self._step_cache[key]

    def run_step(self, visualization: Optional[Dict] = None,
                 disturbance: Optional[Dict] = None,
                 compressor: Optional[Dict] = None) -> List[dict]:
        """Apply configs, run one training step, return streamed payloads
        (captures + step summary)."""
        with self._lock:
            payloads: List[dict] = []
            tt = get_tensor_tracer()

            def report(site, layer_id, arr):
                payloads.append(capture_payload(site, layer_id, arr))

            comp = compressor or {}
            if visualization:
                tt.set_flags_from_config(visualization)
                tt.activate(report, pixels=int(comp.get("pixels", 16)),
                            method=comp.get("method", "mean"))
            else:
                tt.deactivate()
            if disturbance is not None:
                get_disturbance().configure(disturbance,
                                            seed=self.iteration)
            else:
                get_disturbance().clear()

            from megatronapp_tpu.training.train import reshape_global_batch
            num_micro = self.train_cfg.num_microbatches(
                self.ctx.dp * self.ctx.ep)
            batch = reshape_global_batch(next(self.batch_iter), num_micro)
            step_fn = self._build_step()
            with self.ctx.mesh:
                self.state, metrics = step_fn(self.state, batch)
                metrics = jax.device_get(metrics)
            # Flush async debug callbacks before deactivating, or late
            # captures are dropped / race the payload list.
            jax.effects_barrier()
            tt.deactivate()
            # PCA of this step's accumulated MLP2 records (reference
            # tik_end, tensor_tracer.py:212-223 → frontend PCAPlot). Never
            # let a PCA failure turn a completed step into an error payload
            # — the optimizer state has already advanced.
            try:
                pca = tt.pca_mlp2()
            except Exception:
                pca = None
            if pca is not None:
                payloads.append({"type": "pca",
                                 "points": pca.tolist()})
            tt.clear_records()
            self.iteration += 1
            payloads.append({
                "type": "step_done",
                "iteration": self.iteration,
                "loss": float(metrics["loss"]),
                "grad_norm": float(metrics["grad_norm"]),
            })
            return payloads


class TrainingScopeServer:
    """WS endpoint /ws driving a TrainingScopeSession (rank-0 semantics)."""

    def __init__(self, session: TrainingScopeSession, host="0.0.0.0",
                 port=5656):
        self.session = session
        self.host = host
        self.port = port

    async def handle_ws(self, request):
        from aiohttp import web
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        loop = asyncio.get_running_loop()
        async for msg in ws:
            if msg.type != 1:
                continue
            req = json.loads(msg.data)
            if req.get("type") != "run_training_step":
                await ws.send_json({"type": "error",
                                    "message": "unknown message type"})
                continue
            try:
                payloads = await loop.run_in_executor(
                    None, lambda: self.session.run_step(
                        req.get("visualization"),
                        req.get("disturbance"),
                        req.get("compressor")))
                for p in payloads:
                    await ws.send_json(p)
            except Exception as e:
                await ws.send_json({"type": "error", "message": str(e)})
        return ws

    async def handle_index(self, request):
        import os

        from aiohttp import web
        path = os.path.join(os.path.dirname(__file__), "frontend",
                            "index.html")
        return web.FileResponse(path)

    def build_app(self):
        import os

        from aiohttp import web
        app = web.Application()
        app.router.add_get("/", self.handle_index)
        app.router.add_get("/ws", self.handle_ws)
        # Component modules (frontend/components/*.js + app.js) — the
        # counterpart of the reference SPA's src/ tree, served directly
        # (no build step).
        app.router.add_static(
            "/frontend", os.path.join(os.path.dirname(__file__),
                                      "frontend"))
        return app

    def run(self):
        from aiohttp import web
        web.run_app(self.build_app(), host=self.host, port=self.port)
