"""MegaScope capture hooks (identity unless enabled).

Parity with the reference capture sites (tik_tensor calls at
/root/reference/megatron/core/transformer/attention.py:979-981,
dot_product_attention.py:168-170, mlp.py:116-118) and the TensorTracer flag
system (/root/reference/megatron/core/tensor_tracer.py:66-74).

Under jit, captures must be traced in: when enabled, `scope_capture` routes
the (compressed) tensor to the host via ``jax.debug.callback`` (async, does
not block the device). When disabled (default) it is the identity and has
zero cost — XLA elides it entirely.
"""

from __future__ import annotations

import enum
import threading
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp


class FlagType(enum.IntEnum):
    """Reference tensor_tracer.py:66-74 FlagType values (wire contract with
    the frontend)."""
    QKV_mat_mul = 0
    RawAttentionScore = 1
    ContextLayer = 2
    MLP1 = 3
    MLP2 = 4
    Result = 5
    MLP2_Plot = 6


_SITE_TO_FLAG = {
    "qkv_q": FlagType.QKV_mat_mul,
    "qkv_k": FlagType.QKV_mat_mul,
    "qkv_v": FlagType.QKV_mat_mul,
    "attention_probs": FlagType.RawAttentionScore,
    "context": FlagType.ContextLayer,
    "mlp1": FlagType.MLP1,
    "mlp2": FlagType.MLP2,
    "result": FlagType.Result,
}


class _ScopeState(threading.local):
    def __init__(self):
        self.enabled = False
        self.sites: Dict[str, bool] = {}
        self.sink: Optional[Callable] = None
        self.compress_pixels: int = 0


_state = _ScopeState()


def configure(enabled: bool, sites: Optional[Dict[str, bool]] = None,
              sink: Optional[Callable] = None, compress_pixels: int = 64):
    """Enable/disable capture. `sink(site, layer_id, array)` is called on host.

    NOTE: toggling changes trace-time behavior → triggers recompilation, the
    documented cost of dynamic reconfiguration under jit (SURVEY §7 hard
    parts). The WS server therefore batches config changes between steps.
    """
    _state.enabled = enabled
    _state.sites = sites or {}
    _state.sink = sink
    _state.compress_pixels = compress_pixels


def is_enabled(site: str) -> bool:
    return _state.enabled and _state.sites.get(site, False) and _state.sink is not None


def _compress(x: jnp.ndarray, pixels: int) -> jnp.ndarray:
    """Bucket the feature dim to `pixels` means (tensor_tracer.py:76-122
    Compressor with default method data.mean(dim=-1))."""
    if pixels <= 0 or x.shape[-1] <= pixels:
        return x.astype(jnp.float32)
    feat = x.shape[-1]
    chunk = feat // pixels
    trimmed = x[..., : pixels * chunk].astype(jnp.float32)
    return trimmed.reshape(*x.shape[:-1], pixels, chunk).mean(-1)


def capture_payload(site: str, layer_id, arr) -> dict:
    """The capture wire payload (reference training_wsserver.py:46-52
    contract: update_type = FlagType value, layer_id, result) — shared by
    the training WS server and the inference server so the frontend
    contract lives in ONE place."""
    import numpy as np
    flag = _SITE_TO_FLAG.get(site)
    return {
        "update_type": int(flag) if flag is not None else -1,
        "site": site,
        "layer_id": int(layer_id) if layer_id is not None else -1,
        "result": np.asarray(arr, np.float64).tolist(),
    }


def scope_capture(site: str, x: jnp.ndarray, layer_id=None) -> jnp.ndarray:
    """Identity passthrough that optionally mirrors a compressed copy of x to
    the host sink. Safe to call inside jit/scan."""
    if not is_enabled(site):
        return x
    compressed = _compress(x, _state.compress_pixels)
    sink = _state.sink

    def _emit(arr, lid):
        sink(site, None if lid is None else int(lid), arr)

    lid = layer_id if layer_id is not None else -1
    jax.debug.callback(_emit, compressed, lid)
    return x
