"""MegaScope perturbation injection.

Parity with /root/reference/megatron/core/tensor_disturbance.py:27-75
(Disturbance with NOISE_REGISTRY: 'noise1' additive Gaussian, 'noise2'
multiplicative uniform) applied at three sites:
  weight       — linear-layer weights (reference tensor_parallel/layers.py
                 :944-951),
  calculation  — MLP activations (mlp.py),
  system       — hidden states between layers (transformer_block.py:542-544).

Under jit the noise must be traced in (SURVEY §7 hard parts): the config is
read at trace time, so toggling a site or changing its kind recompiles the
step — scale/seed changes ride through as array inputs via the global
disturbance state refreshed per step by the WS server.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Optional

import jax
import jax.numpy as jnp

SITES = ("weight", "calculation", "system")


def noise1(x, rng, scale):
    """Additive Gaussian (reference NOISE_REGISTRY['noise1'])."""
    return x + (scale * jax.random.normal(rng, x.shape)).astype(x.dtype)


def noise2(x, rng, scale):
    """Multiplicative uniform in [1-scale, 1+scale] (reference 'noise2')."""
    factor = 1.0 + scale * (2.0 * jax.random.uniform(rng, x.shape) - 1.0)
    return x * factor.astype(x.dtype)


NOISE_REGISTRY = {"noise1": noise1, "noise2": noise2}


@dataclasses.dataclass
class SiteConfig:
    kind: str = "noise1"
    scale: float = 0.0
    # Restrict to specific layers; None = all layers.
    layers: Optional[tuple] = None


class Disturbance:
    """Global (per-process) perturbation state, read at trace time."""

    def __init__(self):
        self._lock = threading.Lock()
        self.sites: Dict[str, SiteConfig] = {}
        self.seed = 0
        # Bumped every configure() call: step builders key their jit cache
        # on this so stale compilations are not reused.
        self.version = 0

    def configure(self, config: Dict[str, dict], seed: int = 0):
        """config: {site: {kind, scale, layers}} (WS wire format)."""
        with self._lock:
            self.sites = {}
            for site, c in config.items():
                if site not in SITES:
                    raise ValueError(
                        f"unknown disturbance site {site!r}; valid: {SITES}")
                kind = c.get("kind", "noise1")
                if kind not in NOISE_REGISTRY:
                    raise ValueError(
                        f"unknown noise kind {kind!r}; valid: "
                        f"{sorted(NOISE_REGISTRY)}")
                layers = c.get("layers")
                self.sites[site] = SiteConfig(
                    kind=kind, scale=float(c.get("scale", 0.0)),
                    layers=tuple(layers) if layers is not None else None)
            self.seed = seed
            self.version += 1

    def clear(self):
        with self._lock:
            self.sites = {}
            self.version += 1

    def active(self, site: str) -> bool:
        c = self.sites.get(site)
        return c is not None and c.scale != 0.0

    def apply(self, site: str, x: jnp.ndarray, layer_id=None) -> jnp.ndarray:
        """Traced-in application; identity when the site is inactive at
        trace time."""
        c = self.sites.get(site)
        if c is None or c.scale == 0.0:
            return x
        import zlib
        rng = jax.random.fold_in(
            jax.random.PRNGKey(self.seed),
            zlib.crc32(site.encode()) % (2 ** 31))
        if layer_id is not None:
            rng = jax.random.fold_in(rng, jnp.asarray(layer_id, jnp.uint32))
            if c.layers is not None:
                # Per-layer gating with a traced layer_id: apply noise, then
                # select (both branches traced; scan-compatible).
                noisy = NOISE_REGISTRY[c.kind](x, rng, c.scale)
                in_set = jnp.isin(jnp.asarray(layer_id),
                                  jnp.asarray(c.layers))
                return jnp.where(in_set, noisy, x)
        return NOISE_REGISTRY[c.kind](x, rng, c.scale)


_DISTURBANCE = Disturbance()


def get_disturbance() -> Disturbance:
    return _DISTURBANCE
