// MegaScope application shell — counterpart of the reference SPA's
// src/App.vue + src/AppContent.vue (transformer-visualize): owns the
// two WebSocket contracts (training scope/ws_server.py, inference
// inference/server.py), the visualization/disturbance/compressor
// controls, and composes the component tree in components/ (named 1:1
// after the reference's src/components/*.vue).
import { AttentionMatrix } from "./components/AttentionMatrix.js";
import { ColoredVector } from "./components/ColoredVector.js";
import { HelloWorld } from "./components/HelloWorld.js";
import { MLPVectors } from "./components/MLPVectors.js";
import { OutputProbs } from "./components/OutputProbs.js";
import { PCAPlot } from "./components/PCAPlot.js";
import { QKVMatrix } from "./components/QKVMatrix.js";
import { QKVVectors } from "./components/QKVVectors.js";
import { dimColors, flat2d } from "./components/util.js";

"use strict";
const $ = id => document.getElementById(id);

// ---- tabs ----------------------------------------------------------------
$("tab_train").onclick = () => setTab(true);
$("tab_infer").onclick = () => setTab(false);
function setTab(train) {
  $("train_view").classList.toggle("hidden", !train);
  $("infer_view").classList.toggle("hidden", train);
  $("tab_train").classList.toggle("on", train);
  $("tab_infer").classList.toggle("on", !train);
}

// ---- training mode -------------------------------------------------------
let ws = null, losses = [], gnorms = [], autoTimer = null;
// site -> layer_id -> payload (per-layer retention so the layer selector
// can flip between traced layers, reference per-layer batched stores).
const latest = {};

function connect() {
  ws = new WebSocket(`ws://${location.host}/ws`);
  ws.onopen = () => $("status").textContent = "connected";
  ws.onclose = () => { $("status").textContent = "disconnected";
                       setTimeout(connect, 1500); };
  ws.onmessage = ev => {
    const msg = JSON.parse(ev.data);
    if (msg.type === "step_done") {
      losses.push(msg.loss); gnorms.push(msg.grad_norm);
      $("status").textContent =
        `iter ${msg.iteration}  loss ${msg.loss.toFixed(4)}  ` +
        `gnorm ${msg.grad_norm.toFixed(3)}`;
      refreshLayerChoices();
      drawAll();
      if (autoTimer) requestStep();
    } else if (msg.type === "error") {
      $("status").textContent = "error: " + msg.message;
      stopAuto();
    } else if (msg.type === "pca") {
      latest["pca"] = msg;
    } else if (msg.site) {
      (latest[msg.site] = latest[msg.site] || {})[msg.layer_id] = msg;
    }
  };
}

function tracedLayers() {
  return $("layers").value.split(",")
    .map(s => parseInt(s.trim())).filter(Number.isFinite);
}

function visualizationConfig() {
  const layers = tracedLayers();
  const cfg = {};
  if ($("f_qkv").checked) cfg["QKV_mat_mul"] = layers;
  if ($("f_attn").checked) { cfg["RawAttentionScore"] = layers;
                             cfg["ContextLayer"] = layers; }
  if ($("f_mlp").checked) { cfg["MLP1"] = layers; cfg["MLP2"] = layers; }
  if ($("f_result").checked) cfg["Result"] = [0];
  return cfg;
}

function disturbanceConfig() {
  const cfg = {};
  const rows = [["dw", "weight"], ["dc", "calculation"], ["ds", "system"]];
  for (const [p, site] of rows)
    if ($(p + "_on").checked)
      cfg[site] = { kind: $(p + "_kind").value,
                    scale: parseFloat($(p + "_scale").value) || 0.01,
                    layers: null };
  return cfg;
}

function requestStep() {
  if (!ws || ws.readyState !== 1) return;
  const req = { type: "run_training_step",
                visualization: visualizationConfig(),
                compressor: { pixels: parseInt($("pixels").value) || 16,
                              method: "mean" } };
  const dist = disturbanceConfig();
  if (Object.keys(dist).length) req.disturbance = dist;
  ws.send(JSON.stringify(req));
}

function stopAuto() { if (autoTimer) { autoTimer = null;
                      $("auto").textContent = "auto"; } }
$("step").onclick = requestStep;
$("auto").onclick = () => {
  if (autoTimer) stopAuto();
  else { autoTimer = true; $("auto").textContent = "stop"; requestStep(); }
};
$("sel_layer").onchange = drawAll;
$("sel_head").onchange = drawAll;

function refreshLayerChoices() {
  const ids = new Set();
  for (const site of Object.keys(latest))
    if (site !== "pca")
      Object.keys(latest[site]).forEach(l => ids.add(parseInt(l)));
  const sel = $("sel_layer"), cur = sel.value;
  sel.innerHTML = "";
  [...ids].filter(i => i >= 0).sort((a, b) => a - b).forEach(i => {
    const o = document.createElement("option"); o.value = i;
    o.textContent = i; sel.appendChild(o);
  });
  if ([...sel.options].some(o => o.value === cur)) sel.value = cur;
  const att = sitePayload("attention_probs");
  const heads = att ? countHeads(att.result) : 0;
  const hs = $("sel_head"), hcur = hs.value;
  hs.innerHTML = "";
  const all = document.createElement("option");
  all.value = "all"; all.textContent = "all";
  hs.appendChild(all);
  for (let h = 0; h < heads; h++) {
    const o = document.createElement("option"); o.value = h;
    o.textContent = h; hs.appendChild(o);
  }
  if ([...hs.options].some(o => o.value === hcur)) hs.value = hcur;
}

function sitePayload(site) {
  const per = latest[site];
  if (!per) return null;
  const want = $("sel_layer").value;
  if (want !== "" && per[want]) return per[want];
  const ks = Object.keys(per);
  return ks.length ? per[ks[0]] : null;
}

function countHeads(x) {
  let depth = 0, a = x;
  while (Array.isArray(a)) { depth++; a = a[0]; }
  if (depth < 3) return 0;
  a = x;
  for (let i = 0; i < depth - 3; i++) a = a[0];
  return a.length;
}

function headSlice(x) {
  // Reduce an attention payload to 2-D honoring the head selector:
  // 'all' stacks heads vertically, otherwise one head's [q][k].
  let depth = 0, a = x;
  while (Array.isArray(a)) { depth++; a = a[0]; }
  if (depth < 3) return flat2d(x);
  let arr = x;
  for (let i = 0; i < depth - 3; i++) arr = arr[0];
  const want = $("sel_head").value;
  if (want === "all" || !(want in arr)) return flat2d(arr);
  return flat2d(arr[parseInt(want)]);
}

// ---- composition helpers -------------------------------------------------
function mount(id, node) {
  const host = $(id);
  host.innerHTML = "";
  host.appendChild(node);
}

function normalize01(rows) {
  let lo = Infinity, hi = -Infinity;
  rows.forEach(r => r.forEach(v => { lo = Math.min(lo, v);
                                     hi = Math.max(hi, v); }));
  const rng = hi - lo + 1e-9;
  return rows.map(r => r.map(v => (v - lo) / rng));
}

function drawSeriesChart(canvas, series, colors) {
  const ctx = canvas.getContext("2d");
  canvas.width = canvas.clientWidth; canvas.height = 90;
  ctx.clearRect(0, 0, canvas.width, canvas.height);
  series.forEach((data, si) => {
    if (data.length < 2) return;
    const lo = Math.min(...data), hi = Math.max(...data);
    ctx.strokeStyle = colors[si]; ctx.beginPath();
    data.forEach((l, i) => {
      const x = i / (data.length - 1) * (canvas.width - 8) + 4;
      const y = canvas.height - 6 -
        (l - lo) / (hi - lo + 1e-9) * (canvas.height - 12);
      i ? ctx.lineTo(x, y) : ctx.moveTo(x, y);
    });
    ctx.stroke();
    ctx.fillStyle = colors[si]; ctx.font = "10px monospace";
    ctx.fillText(data[data.length - 1].toFixed(3),
                 canvas.width - 48, 12 + si * 12);
  });
}

function drawAll() {
  drawSeriesChart($("loss"), [losses, gnorms], ["#8ecbff", "#c98"]);
  // QKV: per-token strips (QKVVectors) + the raw matrix (QKVMatrix).
  const qkvRows = ["qkv_q", "qkv_k", "qkv_v"].map(sitePayload)
    .filter(Boolean).map(m => flat2d(m.result));
  if (qkvRows.length) {
    const rows = [].concat(...qkvRows);
    const dim = rows[0].length;
    mount("qkv_vectors", QKVVectors({
      colors: dimColors(dim), values: rows.flat(), dim }));
    const norm = normalize01(rows);
    mount("qkv_matrix", QKVMatrix({
      rows: norm.length, cols: dim,
      colors: norm.flat().map(() => [0.2, 0.45, 0.95]),
      values: norm.flat() }));
  }
  const att = sitePayload("attention_probs");
  if (att) {
    const rows = headSlice(att.result);
    mount("attn", AttentionMatrix({
      size: rows.length, color: [0.18, 0.44, 0.92],
      values: rows.map(r => r.slice(0, rows.length)),
      tokens: null, layer_id: att.layer_id }));
  }
  const ctxp = sitePayload("context");
  if (ctxp) {
    const rows = normalize01(flat2d(ctxp.result));
    mount("ctx", QKVMatrix({
      rows: rows.length, cols: rows[0].length,
      colors: rows.flat().map(() => [0.85, 0.45, 0.2]),
      values: rows.flat() }));
  }
  const mlpPanels = [["mlp1", [0.2, 0.7, 0.4]], ["mlp2", [0.7, 0.3, 0.7]]];
  const mlpBox = document.createElement("div");
  for (const [site, color] of mlpPanels) {
    const m = sitePayload(site);
    if (!m) continue;
    const rows = flat2d(m.result);
    mlpBox.appendChild(MLPVectors({
      color, values: rows.flat(), dim: rows[0].length }));
  }
  if (mlpBox.childNodes.length) mount("mlp", mlpBox);
  const res = sitePayload("result");
  if (res) {
    const rows = flat2d(res.result);
    const last = rows[rows.length - 1];
    mount("probs", ColoredVector({
      length: last.length,
      colors: last.map((_, i) => dimColors(last.length)[i]),
      values: last }));
  }
  if (latest["pca"]) {
    // Training server emits {"type": "pca", points: [[x, y], ...]} for
    // one flattened batch; PCAPlot takes [batch][token][2].
    mount("pca", PCAPlot({
      values: [latest["pca"].points], layerId: $("sel_layer").value || 0,
      tokens: null }));
  }
}

// ---- inference mode ------------------------------------------------------
let genTokens = [], selectedTok = -1, iws = null;
const ilatest = {};

$("gen").onclick = () => {
  const url = $("iws").value ||
              `ws://${location.hostname}:5000/ws`;
  // One live generation socket at a time: a second click aborts the
  // stream in flight instead of interleaving two runs' tokens.
  if (iws && iws.readyState <= 1) { try { iws.close(); } catch (e) {} }
  try { iws = new WebSocket(url); }
  catch (e) { $("istatus").textContent = "bad ws url"; return; }
  const sock = iws;   // handlers ignore events from superseded sockets
  genTokens = []; selectedTok = -1;
  renderGenText(); renderCandidates();
  $("istatus").textContent = "connecting...";
  sock.onopen = () => {
    $("istatus").textContent = "generating...";
    const layers = $("ilayers").value.split(",")
      .map(s => parseInt(s.trim())).filter(Number.isFinite);
    const vis = {};
    if ($("if_qkv").checked) vis["QKV_mat_mul"] = layers;
    if ($("if_attn").checked) vis["RawAttentionScore"] = layers;
    if ($("if_cands").checked) vis["Result"] = [0]; // top-20 candidates
    const req = {
      prompts: [$("prompt").value],
      tokens_to_generate: parseInt($("ntok").value) || 16,
      temperature: parseFloat($("temp").value) || 0,
      top_k: parseInt($("topk").value) || 0,
    };
    // Omit visualization entirely when nothing is requested so the
    // server takes the fast no-retrace path.
    if (Object.keys(vis).length) req.visualization = vis;
    sock.send(JSON.stringify(req));
  };
  sock.onerror = () => { if (sock === iws)
    $("istatus").textContent = "connection failed"; };
  sock.onmessage = ev => {
    if (sock !== iws) return;   // superseded by a newer generation
    const msg = JSON.parse(ev.data);
    if (msg.type === "token") {
      genTokens.push(msg);
      if (selectedTok < 0) { selectedTok = 0; }
      renderGenText();
      renderCandidates();
    } else if (msg.type === "done") {
      $("istatus").textContent = `done (${genTokens.length} tokens)`;
      sock.close();
    } else if (msg.type === "error") {
      $("istatus").textContent = "error: " + msg.message;
      sock.close();
    } else if (msg.site) {
      ilatest[msg.site] = msg;
      drawInferPanels();
    }
  };
};

function renderGenText() {
  const el = $("gen_text");
  el.innerHTML = "";
  const pr = document.createElement("span");
  pr.className = "prompt"; pr.textContent = $("prompt").value;
  el.appendChild(pr);
  genTokens.forEach((t, i) => {
    const s = document.createElement("span");
    s.className = "tok" + (i === selectedTok ? " sel" : "");
    s.textContent = t.text ?? String(t.token);
    s.title = `step ${t.step} id ${t.token}`;
    s.onclick = () => { selectedTok = i; renderGenText();
                        renderCandidates(); };
    el.appendChild(s);
  });
}

function renderCandidates() {
  // Reference OutputProbs: top-k candidates with the sampled token
  // highlighted — rendered by the named component counterpart.
  const t = genTokens[selectedTok];
  $("cand_tok").textContent = t
    ? `— step ${t.step}: "${t.text ?? t.token}"` : "";
  if (!t || !t.candidates) { mount("cands", HelloWorld({})); return; }
  mount("cands", OutputProbs({ data: {
    probs: t.candidates.map(c => ({
      logit: 0, id: c.token, token: c.text ?? String(c.token),
      probability: c.prob })),
    sampled: { logit: 0, id: t.token, token: t.text ?? String(t.token),
               probability: (t.candidates.find(c => c.token === t.token)
                             || { prob: 0 }).prob },
  } }));
}

function drawInferPanels() {
  const q = ["qkv_q", "qkv_k", "qkv_v"].map(s => ilatest[s])
    .filter(Boolean).map(m => flat2d(m.result));
  if (q.length) {
    const rows = [].concat(...q);
    mount("iqkv", QKVVectors({
      colors: dimColors(rows[0].length), values: rows.flat(),
      dim: rows[0].length }));
  }
  if (ilatest["attention_probs"]) {
    const rows = flat2d(ilatest["attention_probs"].result);
    mount("iattn", AttentionMatrix({
      size: rows.length, color: [0.18, 0.44, 0.92],
      values: rows.map(r => r.slice(0, rows.length)),
      tokens: genTokens.map((t, i) => ({ id: t.token,
                                         token: t.text ?? String(t.token) })),
      layer_id: ilatest["attention_probs"].layer_id }));
  }
}

connect();
