// Shared helpers for the component tree (the reference repeats tohex in
// every component; here it is one module).

// [r,g,b] in 0..1 + intensity in 0..1 → '#rrggbb' (white → full color),
// the reference's tohex.
export function tohex(baseColor, value) {
  const v = Math.max(0, Math.min(1, value));
  return "#" + baseColor
    .map(c => Math.round(255 * (c * v + (1 - v)))
      .toString(16).padStart(2, "0"))
    .join("");
}

// Default per-dimension rainbow used by the reference AppContent for
// QKV vectors: stable hue per dimension index.
export function dimColors(n) {
  return Array.from({ length: n }, (_, i) => {
    const h = (i / Math.max(1, n)) * 300;
    return hsl2rgb(h, 0.75, 0.5);
  });
}

export function hsl2rgb(h, s, l) {
  const a = s * Math.min(l, 1 - l);
  const f = k => {
    const x = (k + h / 30) % 12;
    return l - a * Math.max(-1, Math.min(x - 3, 9 - x, 1));
  };
  return [f(0), f(8), f(4)];
}

// Flatten an arbitrarily-nested numeric array to 2-D rows (batched
// payloads stack vertically) — shared by matrix-shaped components.
export function flat2d(x) {
  if (!Array.isArray(x)) return [[x]];
  if (!Array.isArray(x[0])) return [x];
  const rows = [];
  const rec = a => {
    if (!Array.isArray(a[0])) { rows.push(a); return; }
    a.forEach(rec);
  };
  rec(x);
  return rows;
}

export function card(title) {
  const box = document.createElement("div");
  box.className = "ncard";
  const h = document.createElement("h3");
  h.textContent = title;
  h.style.cssText = "font-size:12px;margin:0 0 6px;color:#aac;";
  box.appendChild(h);
  return box;
}
