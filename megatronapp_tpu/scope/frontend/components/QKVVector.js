// Counterpart of transformer-visualize/src/components/QKVVector.vue:
// one token's Q/K/V projection as an SVG strip, each dimension a 2px
// rect colored by its per-dimension hue scaled by the normalized value.
import { tohex } from "./util.js";

const SVG = "http://www.w3.org/2000/svg";

export function QKVVector({ length, colors, values }) {
  const svg = document.createElementNS(SVG, "svg");
  const w = 2 * length, h = 10;
  svg.setAttribute("width", w);
  svg.setAttribute("height", h);
  svg.setAttribute("viewBox", `0 0 ${w} ${h}`);
  if (!values || !values.length) return svg;
  const min = Math.min(...values), max = Math.max(...values);
  for (let i = 0; i < length; i++) {
    const rect = document.createElementNS(SVG, "rect");
    rect.setAttribute("x", 2 * i);
    rect.setAttribute("y", 0);
    rect.setAttribute("width", 2);
    rect.setAttribute("height", h);
    const norm = (values[i] - min) / (max - min + 1e-9);
    rect.setAttribute("fill", tohex(colors[i] || [0.5, 0.5, 0.5], norm));
    const t = document.createElementNS(SVG, "title");
    t.textContent = `dim ${i}: ${values[i]?.toFixed(4)}`;
    rect.appendChild(t);
    svg.appendChild(rect);
  }
  return svg;
}
