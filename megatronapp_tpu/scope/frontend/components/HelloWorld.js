// Counterpart of transformer-visualize/src/components/HelloWorld.vue
// (the reference keeps its Vite scaffold demo component in the tree) —
// a connectivity smoke card used when no data has arrived yet.
import { card } from "./util.js";

export function HelloWorld({ msg } = {}) {
  const box = card(msg || "MegaScope");
  const p = document.createElement("p");
  p.style.cssText = "font-size:12px;color:#889;";
  p.textContent =
    "Connected component tree is live. Run a training step or a " +
    "generation to populate the panels.";
  box.appendChild(p);
  return box;
}
