// Counterpart of transformer-visualize/src/components/ColoredVector.vue:
// one token's vector as a horizontal strip of per-dimension color
// segments, min/max-normalized, hover tooltip with the raw value.
import { tohex } from "./util.js";

export function ColoredVector({ length, colors, values }) {
  const el = document.createElement("div");
  el.className = "colored-vector";
  el.style.cssText = "display:flex;height:25px;width:100%;";
  if (!values || !values.length) return el;
  const min = Math.min(...values), max = Math.max(...values);
  const range = max - min, flat = range < 1e-6;
  for (let i = 0; i < length; i++) {
    const seg = document.createElement("div");
    const v = values[i];
    const norm = flat ? 0.5 : (v - min) / range;
    const color = (i < values.length && colors && colors[i])
      ? tohex(colors[i], norm) : "#CCCCCC";
    seg.style.cssText =
      `flex-grow:1;background-color:${color};min-width:1px;`;
    seg.title = `Value: ${v?.toFixed(4)}`;
    el.appendChild(seg);
  }
  return el;
}
