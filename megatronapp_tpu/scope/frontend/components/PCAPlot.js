// Counterpart of transformer-visualize/src/components/PCAPlot.vue: a
// 2-D scatter of PCA-projected activations, one color per batch with a
// legend and hover tooltip showing the point's token — canvas instead
// of chart.js (no external deps), same dataset semantics.
import { card } from "./util.js";

const BATCH_COLORS = [
  "rgba(75,192,192,", "rgba(255,99,132,", "rgba(54,162,235,",
  "rgba(255,206,86,", "rgba(153,102,255,", "rgba(255,159,64,",
  "rgba(100,100,100,", "rgba(200,100,50,",
];

export function batchColor(i, alpha = 1) {
  return BATCH_COLORS[i % BATCH_COLORS.length] + alpha + ")";
}

export function PCAPlot({ values, layerId, tokens }) {
  const box = card(`Layer ${layerId} PCA`);
  const canvas = document.createElement("canvas");
  canvas.width = 340; canvas.height = 200;
  canvas.style.cssText = "width:100%;background:#15151d;border-radius:4px;";
  box.appendChild(canvas);
  const ctx = canvas.getContext("2d");
  if (!values || !values.length) return box;

  const pts = [];   // {x, y, batch, token}
  values.forEach((batchData, b) => (batchData || []).forEach((p, i) =>
    pts.push({ x: p[0], y: p[1], batch: b,
               token: tokens?.[b]?.[i]?.token ?? `[Token ${i + 1}]` })));
  if (!pts.length) return box;
  const xs = pts.map(p => p.x), ys = pts.map(p => p.y);
  const xlo = Math.min(...xs), xhi = Math.max(...xs);
  const ylo = Math.min(...ys), yhi = Math.max(...ys);
  const px = p => 10 + (p.x - xlo) / (xhi - xlo + 1e-9) *
    (canvas.width - 20);
  const py = p => canvas.height - 10 -
    (p.y - ylo) / (yhi - ylo + 1e-9) * (canvas.height - 20);

  function draw(hover) {
    ctx.clearRect(0, 0, canvas.width, canvas.height);
    for (const p of pts) {
      ctx.fillStyle = batchColor(p.batch, p === hover ? 1 : 0.7);
      ctx.beginPath();
      ctx.arc(px(p), py(p), p === hover ? 6 : 4, 0, 7);
      ctx.fill();
    }
    // Legend: one entry per batch.
    const nb = values.length;
    for (let b = 0; b < nb; b++) {
      ctx.fillStyle = batchColor(b);
      ctx.fillRect(8, 8 + 14 * b, 10, 10);
      ctx.fillStyle = "#aab";
      ctx.font = "10px monospace";
      ctx.fillText(`Batch ${b + 1}`, 22, 17 + 14 * b);
    }
    if (hover) {
      ctx.fillStyle = "#fff";
      ctx.font = "11px monospace";
      ctx.fillText(
        `${hover.token} (${hover.x.toFixed(3)}, ${hover.y.toFixed(3)})`,
        Math.min(px(hover) + 8, canvas.width - 130), py(hover) - 8);
    }
  }
  canvas.onmousemove = ev => {
    const r = canvas.getBoundingClientRect();
    const mx = (ev.clientX - r.left) * canvas.width / r.width;
    const my = (ev.clientY - r.top) * canvas.height / r.height;
    let best = null, bd = 100;
    for (const p of pts) {
      const d = (px(p) - mx) ** 2 + (py(p) - my) ** 2;
      if (d < bd) { bd = d; best = p; }
    }
    draw(best);
  };
  canvas.onmouseleave = () => draw(null);
  draw(null);
  return box;
}
