// Counterpart of transformer-visualize/src/components/QKVMatrix.vue:
// a rows×cols grid of 10px SVG cells, each colored by its per-cell base
// color scaled by the cell value.
import { tohex } from "./util.js";

const SVG = "http://www.w3.org/2000/svg";

export function QKVMatrix({ rows, cols, colors, values }) {
  const svg = document.createElementNS(SVG, "svg");
  const w = 10 * cols, h = 10 * rows;
  svg.setAttribute("width", w);
  svg.setAttribute("height", h);
  svg.setAttribute("viewBox", `0 0 ${w} ${h}`);
  svg.style.maxWidth = "100%";
  if (!values || !values.length) return svg;
  for (let i = 0; i < rows; i++) {
    for (let j = 0; j < cols; j++) {
      const idx = i * cols + j;
      const rect = document.createElementNS(SVG, "rect");
      rect.setAttribute("x", 10 * j);
      rect.setAttribute("y", 10 * i);
      rect.setAttribute("width", 10);
      rect.setAttribute("height", 10);
      rect.setAttribute(
        "fill", tohex(colors?.[idx] || [0.2, 0.4, 0.9],
                      values[idx] ?? 0));
      const t = document.createElementNS(SVG, "title");
      t.textContent = `[${i},${j}] ${Number(values[idx] ?? 0).toFixed(4)}`;
      rect.appendChild(t);
      svg.appendChild(rect);
    }
  }
  return svg;
}
