// Counterpart of transformer-visualize/src/components/MLPVector.vue:
// one token's MLP activations as an SVG strip with a single base color
// scaled by the min/max-normalized value.
import { tohex } from "./util.js";

const SVG = "http://www.w3.org/2000/svg";

export function MLPVector({ length, color, values }) {
  const svg = document.createElementNS(SVG, "svg");
  const w = 2 * length, h = 10;
  svg.setAttribute("width", w);
  svg.setAttribute("height", h);
  svg.setAttribute("viewBox", `0 0 ${w} ${h}`);
  if (!values || !values.length) return svg;
  const min = Math.min(...values), max = Math.max(...values);
  for (let i = 0; i < length; i++) {
    const rect = document.createElementNS(SVG, "rect");
    rect.setAttribute("x", 2 * i);
    rect.setAttribute("y", 0);
    rect.setAttribute("width", 2);
    rect.setAttribute("height", h);
    const norm = (values[i] - min) / (max - min + 1e-9);
    rect.setAttribute("fill", tohex(color, norm));
    const t = document.createElementNS(SVG, "title");
    t.textContent = `dim ${i}: ${values[i]?.toFixed(4)}`;
    rect.appendChild(t);
    svg.appendChild(rect);
  }
  return svg;
}
