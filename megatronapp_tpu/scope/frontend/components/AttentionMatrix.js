// Counterpart of transformer-visualize/src/components/AttentionMatrix.vue:
// an S×S attention-weight grid, cells colored by weight, hover popover
// with query/key token and attention %. DOM grid (faithful to the
// reference) up to 64 tokens; canvas heatmap beyond that so long
// sequences stay responsive.
import { card, tohex } from "./util.js";

const DOM_LIMIT = 64;

function tokenString(tokens, i) {
  return tokens?.[i]?.token ?? `[Token ${i + 1}]`;
}

export function AttentionMatrix({ size, color, values, tokens, layer_id }) {
  const box = card(`Layer ${layer_id} Attention Matrix`);
  const valid = values && values.length === size &&
    values.every(r => r && r.length === size);
  if (!valid) {
    const empty = document.createElement("div");
    empty.style.cssText = "color:#778;font-size:12px;";
    empty.textContent =
      `Layer ${layer_id} attention data not available or mismatched ` +
      "dimensions.";
    box.appendChild(empty);
    return box;
  }
  if (size > DOM_LIMIT) {
    const canvas = document.createElement("canvas");
    canvas.width = size; canvas.height = size;
    canvas.style.cssText =
      "width:100%;image-rendering:pixelated;border-radius:4px;";
    const ctx = canvas.getContext("2d");
    const img = ctx.createImageData(size, size);
    for (let i = 0; i < size; i++)
      for (let j = 0; j < size; j++) {
        const v = Math.max(0, Math.min(1, values[i][j]));
        const o = (i * size + j) * 4;
        img.data[o] = 255 * (color[0] * v + (1 - v));
        img.data[o + 1] = 255 * (color[1] * v + (1 - v));
        img.data[o + 2] = 255 * (color[2] * v + (1 - v));
        img.data[o + 3] = 255;
      }
    ctx.putImageData(img, 0, 0);
    canvas.title = `attention ${size}×${size} (hover grid shown below ` +
      `${DOM_LIMIT} tokens)`;
    box.appendChild(canvas);
    return box;
  }
  const grid = document.createElement("div");
  grid.style.cssText =
    `display:grid;grid-template-columns:repeat(${size},1fr);` +
    "border:1px solid #333;aspect-ratio:1;";
  for (let i = 0; i < size; i++) {
    for (let j = 0; j < size; j++) {
      const cellWrap = document.createElement("div");
      cellWrap.style.cssText =
        "aspect-ratio:1;display:flex;align-items:center;" +
        "justify-content:center;";
      const cell = document.createElement("div");
      cell.style.cssText =
        "width:90%;height:90%;border-radius:2px;" +
        `background-color:${tohex(color, values[i][j])};`;
      cell.title =
        `Query: ${tokenString(tokens, i)} (idx ${i})\n` +
        `Key: ${tokenString(tokens, j)} (idx ${j})\n` +
        `Attention: ${(values[i][j] * 100).toFixed(2)}%`;
      cellWrap.appendChild(cell);
      grid.appendChild(cellWrap);
    }
  }
  box.appendChild(grid);
  return box;
}
