// Counterpart of transformer-visualize/src/components/MLPVectors.vue:
// a flex row of per-token MLPVector strips. The reference hardcodes its
// model's 64-dim hidden; here the dimension comes from the payload.
import { MLPVector } from "./MLPVector.js";

export function MLPVectors({ color, values, dim }) {
  const el = document.createElement("div");
  el.style.cssText = "display:flex;flex-wrap:wrap;gap:4px;";
  if (!values || !values.length || !dim) return el;
  const nTokens = Math.floor(values.length / dim);
  for (let i = 0; i < nTokens; i++) {
    el.appendChild(MLPVector({
      length: dim,
      color,
      values: values.slice(i * dim, (i + 1) * dim),
    }));
  }
  return el;
}
