// Counterpart of transformer-visualize/src/components/OutputProbs.vue:
// the sampled token highlighted, then the top-k candidates sorted by
// probability as rounded tags (with probability bars — the inference
// server supplies up to top-20 candidates per step).
export function OutputProbs({ data }) {
  const el = document.createElement("div");
  el.className = "output-probs";
  const valid = data && data.probs && data.probs.length && data.sampled;
  if (!valid) {
    el.style.cssText = "color:#778;font-size:12px;";
    el.textContent = "waiting for output probabilities…";
    return el;
  }
  const head = document.createElement("div");
  head.style.cssText = "margin-bottom:6px;font-size:13px;";
  const headTag = document.createElement("span");
  headTag.style.cssText =
    "background:#2fb36f;color:#fff;border-radius:10px;padding:2px 10px;";
  headTag.textContent =
    `${JSON.stringify(data.sampled.token)}: ` +
    `${(data.sampled.probability * 100).toFixed(2)}% 🎯`;
  head.append("Sampled token: ", headTag);
  el.appendChild(head);

  const list = document.createElement("div");
  list.style.cssText = "display:flex;flex-wrap:wrap;gap:4px;";
  const sorted = [...data.probs].sort(
    (a, b) => b.probability - a.probability);
  for (const item of sorted) {
    const tag = document.createElement("span");
    const sampled = item.id === data.sampled.id;
    tag.style.cssText =
      "border-radius:10px;padding:2px 10px;font-size:12px;" +
      (sampled ? "background:#2fb36f;color:#fff;"
               : "background:#23232e;color:#bbc;");
    tag.textContent =
      `${JSON.stringify(item.token)}: ` +
      `${(item.probability * 100).toFixed(2)}%` + (sampled ? " 🎯" : "");
    list.appendChild(tag);
  }
  el.appendChild(list);
  return el;
}
