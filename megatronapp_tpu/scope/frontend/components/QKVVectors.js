// Counterpart of transformer-visualize/src/components/QKVVectors.vue:
// a flex row of per-token QKVVector strips. The reference hardcodes its
// model's 96-dim projection; here the dimension comes from the payload.
import { QKVVector } from "./QKVVector.js";

export function QKVVectors({ colors, values, dim }) {
  const el = document.createElement("div");
  el.style.cssText = "display:flex;flex-wrap:wrap;gap:4px;";
  if (!values || !values.length || !dim) return el;
  const nTokens = Math.floor(values.length / dim);
  for (let i = 0; i < nTokens; i++) {
    el.appendChild(QKVVector({
      length: dim,
      colors,
      values: values.slice(i * dim, (i + 1) * dim),
    }));
  }
  return el;
}
