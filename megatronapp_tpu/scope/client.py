"""MegaScope Python client: drives the training WS server programmatically.

Pins the wire contract from the CLIENT side (the other side of
scope/ws_server.py): a headless counterpart of the web UI
(scope/frontend/index.html), usable for scripted probing, contract tests,
and notebook analysis.

  client = ScopeClient("ws://localhost:5656/ws")
  payloads = client.run_step(
      visualization={"QKV_mat_mul": [0, 1]},
      compressor={"pixels": 16, "method": "mean"})
"""

from __future__ import annotations

import asyncio
import json
from typing import Dict, List, Optional


class ScopeClient:
    """Blocking wrapper over one aiohttp WS connection."""

    def __init__(self, url: str = "ws://127.0.0.1:5656/ws",
                 timeout: float = 300.0):
        self.url = url
        self.timeout = timeout

    def run_step(self, visualization: Optional[Dict] = None,
                 disturbance: Optional[Dict] = None,
                 compressor: Optional[Dict] = None) -> List[dict]:
        """Run one training step; returns all payloads up to and including
        the step_done summary (raises on server-side error payloads)."""
        return asyncio.run(self._run_step_async(
            visualization, disturbance, compressor))

    async def _run_step_async(self, visualization, disturbance, compressor,
                              session=None):
        import aiohttp
        own = session is None
        if own:
            session = aiohttp.ClientSession()
        try:
            async with session.ws_connect(self.url,
                                          timeout=self.timeout) as ws:
                req = {"type": "run_training_step"}
                if visualization is not None:
                    req["visualization"] = visualization
                if disturbance is not None:
                    req["disturbance"] = disturbance
                if compressor is not None:
                    req["compressor"] = compressor
                await ws.send_json(req)
                payloads: List[dict] = []
                while True:
                    msg = await asyncio.wait_for(ws.receive(),
                                                 timeout=self.timeout)
                    if msg.type != aiohttp.WSMsgType.TEXT:
                        raise ConnectionError(
                            f"ws closed mid-step: {msg.type}")
                    data = json.loads(msg.data)
                    if data.get("type") == "error":
                        raise RuntimeError(
                            f"server error: {data.get('message')}")
                    payloads.append(data)
                    if data.get("type") == "step_done":
                        return payloads
        finally:
            if own:
                await session.close()


def validate_payloads(payloads: List[dict],
                      visualization: Optional[Dict] = None) -> None:
    """Contract assertions both sides rely on (golden-payload shape).

    - every capture carries update_type/site/layer_id/result;
    - exactly one trailing step_done with iteration/loss/grad_norm;
    - every requested FlagType produced at least one capture.
    """
    from megatronapp_tpu.scope.hooks import FlagType

    assert payloads, "no payloads"
    *captures, done = payloads
    assert done.get("type") == "step_done", done
    for key in ("iteration", "loss", "grad_norm"):
        assert key in done, (key, done)
    for c in captures:
        for key in ("update_type", "site", "layer_id", "result"):
            assert key in c, (key, c)
        assert isinstance(c["result"], list)
    if visualization:
        got = {c["update_type"] for c in captures}
        for name in visualization:
            want = int(FlagType[name])
            assert want in got, (
                f"flag {name} requested but no capture arrived "
                f"(got update_types {sorted(got)})")
