"""MegaScope tensor tracer: capture → compress → report pipeline.

Parity with /root/reference/megatron/core/tensor_tracer.py:
- FlagType per-layer on/off flags (:66-74, wire contract in scope/hooks.py);
- Compressor (:76-122): bucket the feature dim to `pixels` means (or a named
  reduction) before shipping to the frontend;
- TensorTracers.report (:125-183): dimension-correct re-concat is
  unnecessary here — captures see the full logical tensor (XLA materializes
  it on host via the callback), so the TP-gather step of the reference
  disappears by construction;
- tik_result (:189-209): per-token softmax + sampled token + top-20
  candidates with decoded text;
- tik_end PCA (:212-223): 2-component PCA of accumulated MLP records
  (sklearn, with a numpy-SVD fallback).
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Callable, Dict, List, Optional

import numpy as np

from megatronapp_tpu.scope.hooks import FlagType, _SITE_TO_FLAG, configure


class Compressor:
    """Reference Compressor: chunk the last dim into `pixels` buckets and
    reduce each with a named method."""

    METHODS = {
        "mean": lambda x: x.mean(-1),
        "max": lambda x: x.max(-1),
        "min": lambda x: x.min(-1),
        "norm": lambda x: np.linalg.norm(x, axis=-1),
        "first": lambda x: x[..., 0],
    }

    def __init__(self, pixels: int = 64, method: str = "mean"):
        self.pixels = pixels
        if method not in self.METHODS:
            raise ValueError(
                f"compressor method must be one of {sorted(self.METHODS)}, "
                f"got {method!r}")
        self.method = method

    def __call__(self, data: np.ndarray) -> np.ndarray:
        feat = data.shape[-1]
        if self.pixels <= 0 or feat <= self.pixels:
            return np.asarray(data, np.float32)
        chunk = feat // self.pixels
        trimmed = np.asarray(data[..., : self.pixels * chunk], np.float32)
        buckets = trimmed.reshape(*data.shape[:-1], self.pixels, chunk)
        return self.METHODS[self.method](buckets)


class TensorTracer:
    """Singleton-style per-process tracer (reference TensorTracers).

    configure_sites() wires scope.hooks so model-side scope_capture calls
    stream compressed tensors into `report_func(site, layer_id, array)`.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.flags: Dict[int, set] = defaultdict(set)  # layer -> FlagTypes
        self.compressor = Compressor()
        self.report_func: Optional[Callable] = None
        self.mlp2_records: List[np.ndarray] = []
        self.enabled = False

    # -- flag control (reference tt_flags set/unset :225-266) --------------
    def set_flag(self, layer_id: int, flag: FlagType):
        self.flags[layer_id].add(flag)

    def unset_flag(self, layer_id: int, flag: FlagType):
        self.flags[layer_id].discard(flag)

    def set_flags_from_config(self, config: Dict[str, List[int]]):
        """config: {flag name: [layer ids]} — the WS wire format."""
        self.flags.clear()
        for name, layers in config.items():
            flag = FlagType[name]
            for lid in layers:
                self.flags[int(lid)].add(flag)

    def _site_enabled(self, site: str, layer_id) -> bool:
        flag = _SITE_TO_FLAG.get(site)
        if flag is None:
            return False
        if layer_id is None or layer_id < 0:
            return any(flag in s for s in self.flags.values())
        return flag in self.flags.get(int(layer_id), ())

    # -- activation --------------------------------------------------------
    def activate(self, report_func: Callable, pixels: int = 64,
                 method: str = "mean"):
        self.report_func = report_func
        self.compressor = Compressor(pixels, method)
        self.enabled = True
        sites = {site: True for site in _SITE_TO_FLAG}
        # 'mean' compresses on device (hooks._compress) so the host callback
        # ships pixels-sized data, not the full activation; other methods
        # need the raw tensor host-side.
        device_pixels = pixels if method == "mean" else 0
        configure(enabled=True, sites=sites, sink=self._sink,
                  compress_pixels=device_pixels)

    def deactivate(self):
        self.enabled = False
        configure(enabled=False)

    def _sink(self, site: str, layer_id, array):
        if not self.enabled or not self._site_enabled(site, layer_id):
            return
        arr = np.asarray(array)
        compressed = self.compressor(arr)
        if site == "mlp2":
            with self._lock:
                self.mlp2_records.append(
                    compressed.reshape(-1, compressed.shape[-1]))
        if self.report_func is not None:
            self.report_func(site, layer_id, compressed)

    # -- token/logit reporting (tik_result :189-209) -----------------------
    def report_result(self, logits: np.ndarray, sampled_token: int,
                      tokenizer=None, top_n: int = 20) -> dict:
        logits = np.asarray(logits, np.float64).ravel()
        probs = np.exp(logits - logits.max())
        probs /= probs.sum()
        top_idx = np.argsort(probs)[::-1][:top_n]
        cands = []
        for i in top_idx:
            text = (tokenizer.detokenize([int(i)]) if tokenizer else str(i))
            cands.append({"token": int(i), "prob": float(probs[i]),
                          "text": text})
        return {
            "token": int(sampled_token),
            "text": (tokenizer.detokenize([int(sampled_token)])
                     if tokenizer else str(sampled_token)),
            "candidates": cands,
        }

    # -- PCA (tik_end :212-223) -------------------------------------------
    def pca_mlp2(self, n_components: int = 2) -> Optional[np.ndarray]:
        with self._lock:
            if not self.mlp2_records:
                return None
            data = np.concatenate(self.mlp2_records, axis=0)
        if data.shape[0] < 2 or data.shape[1] < n_components:
            # Too few samples/features for a 2-component plane (sklearn
            # raises; the SVD fallback would emit degenerate points).
            return None
        # StandardScaler + PCA (sklearn when present, numpy SVD otherwise).
        mean = data.mean(0)
        std = data.std(0)
        std[std == 0] = 1.0
        scaled = (data - mean) / std
        try:
            from sklearn.decomposition import PCA
            return PCA(n_components=n_components).fit_transform(scaled)
        except ImportError:
            u, s, _ = np.linalg.svd(scaled, full_matrices=False)
            return u[:, :n_components] * s[:n_components]

    def clear_records(self):
        with self._lock:
            self.mlp2_records.clear()


_TT = TensorTracer()


def get_tensor_tracer() -> TensorTracer:
    return _TT
