"""Slow-chip detection heuristic.

Behavioral parity with /root/reference/scripts/aggregate.py:399 (try_detect)
and :366 (detect_in_data_parallelism_group):

Stage 1 — across data-parallel peers, compare the k-th occurrence of each
schedule event per iteration:
  * a 'loss' or 'allreduce' event *finishing early* (< 0.9 x the mean of the
    other ranks) marks the rank suspect — a slow rank reaches the sync op
    last and therefore waits *less* inside it;
  * a 'backward' event *taking long* (> 1.1 x the mean of the others) marks
    the rank suspect.
A rank suspected more than `stage1_threshold` (5) times is escalated.

Stage 2 — for an escalated rank, compare each of its collective events
('all-reduce'/'reduce-scatter'/'all-gather' — the TP '_reduce' analogues)
against the related_sync_op peers; if it is the earliest-finishing member in
> 40% of them, report it as abnormal.

On TPU, 'rank' granularity is the trace producer (one process per host; the
reference has one process per GPU). The math is identical.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Set

SYNC_EARLY_EVENTS = ("loss", "allreduce", "grad-sync", "optimizer")
SLOW_EVENTS = ("backward", "forward-backward")
COLLECTIVE_PREFIXES = ("all-reduce", "reduce-scatter", "all-gather",
                       "collective-permute", "all-to-all")

EARLY_FACTOR = 0.9
SLOW_FACTOR = 1.1
STAGE1_THRESHOLD = 5
STAGE2_FRACTION = 0.4


def _end(e):
    return e["ts"] + e.get("dur", 0.0)


def detect_stage1(events: List[dict]) -> Dict[int, int]:
    """Suspect counts per pid (reference try_detect stage 1)."""
    # Bucket by (iteration, name, occurrence index) across pids.
    buckets: Dict[tuple, Dict[int, List[dict]]] = defaultdict(
        lambda: defaultdict(list))
    for e in events:
        if e["ph"] != "X":
            continue
        if e["name"] in SYNC_EARLY_EVENTS or e["name"] in SLOW_EVENTS:
            key = (e["args"].get("iteration", -1), e["name"])
            buckets[key][e["pid"]].append(e)

    suspects: Dict[int, int] = defaultdict(int)
    for (it, name), per_pid in buckets.items():
        if len(per_pid) < 2:
            continue
        depth = min(len(v) for v in per_pid.values())
        for i in range(depth):
            if name in SYNC_EARLY_EVENTS:
                # Use wait time inside the op ≈ duration: a slow rank
                # arrives late and waits less.
                durs = {pid: v[i].get("dur", 0.0)
                        for pid, v in per_pid.items()}
                for pid, d in durs.items():
                    others = [durs[q] for q in durs if q != pid]
                    avg = sum(others) / len(others)
                    if avg > 0 and d < EARLY_FACTOR * avg:
                        suspects[pid] += 1
            else:  # slow events: longer duration ⇒ suspect
                durs = {pid: v[i].get("dur", 0.0)
                        for pid, v in per_pid.items()}
                for pid, d in durs.items():
                    others = [durs[q] for q in durs if q != pid]
                    avg = sum(others) / len(others)
                    if avg > 0 and d > SLOW_FACTOR * avg:
                        suspects[pid] += 1
    return dict(suspects)


def _owner(e: dict):
    """Process a collective event belongs to: profiler-derived per-device
    records carry args['process'] (trace/profiler_collectives.py); plain
    tracer records are owned by their pid."""
    return e.get("args", {}).get("process", e["pid"])


def detect_stage2(events: List[dict], related: Dict[int, Set[int]],
                  pid: int) -> bool:
    """Within collectives, is `pid` the earliest finisher in >40% of its
    related-op sets (reference detect_in_data_parallelism_group)?

    Membership is by owning PROCESS: profiler-derived collective events
    have per-device pids, so a set's events attribute back to the
    process stage 1 escalated."""
    by_id = {e["args"]["id"]: e for e in events
             if "id" in e.get("args", {})}
    total = 0
    slow_cnt = 0
    seen = set()
    for eid, ids in related.items():
        if eid in seen or len(ids) < 2:
            continue
        seen.update(ids)
        evs = [by_id[i] for i in ids if i in by_id]
        if not any(_owner(e) == pid for e in evs):
            continue
        # Events in a related set share a name by construction
        # (dependency matching key), but tolerate heterogeneous sets from
        # hand-built traces: require at least one collective member.
        if not any(e["name"].startswith(p) for e in evs
                   for p in COLLECTIVE_PREFIXES):
            continue
        mine = [e for e in evs if _owner(e) == pid]
        others = [e for e in evs if _owner(e) != pid]
        if not others:
            continue
        total += 1
        if min(_end(m) for m in mine) < min(_end(o) for o in others):
            slow_cnt += 1
    return total > 0 and slow_cnt > STAGE2_FRACTION * total


def stage_step_gaps(events: List[dict],
                    name: str = "pp-overlap-permute") -> Dict[int, list]:
    """Per-stage compute-time samples mined from the pipeline's ring-hop
    spans — the bridge from MegaScan detection to MegaDPP scheduling
    (ISSUE 15): between hop E(step t) and hop B(step t+1) on one stage
    timeline the rank runs its stage body, so those gaps ARE the
    per-stage step times the pipeline planner
    (parallel/schedule.Planner.ingest_trace_events) consumes.

    Returns {stage (args.rank): [gap_seconds, ...]}. Spans from other
    ring domains (op != 'pp-*') are ignored; timelines are keyed
    (pid, tid, op) so dp/cp shards of one stage never interleave AND
    the forward scan's hops ('pp-schedule') never pair with the
    zero-bubble backward scan's ('pp-zb-bwd') — a cross-scan gap spans
    the LM head + loss + head backward, not a stage body."""
    by_tid: Dict[tuple, List[dict]] = defaultdict(list)
    for e in events:
        if e.get("name") != name:
            continue
        op = str(e.get("args", {}).get("op", ""))
        if not op.startswith("pp"):
            continue
        by_tid[(e.get("pid"), e.get("tid"), op)].append(e)
    gaps: Dict[int, list] = defaultdict(list)
    for evs in by_tid.values():
        evs.sort(key=lambda e: e["ts"])
        last_end = None
        for e in evs:
            rank = e.get("args", {}).get("rank")
            if e["ph"] == "B" and last_end is not None and rank is not None:
                gap_us = e["ts"] - last_end
                if gap_us > 0:
                    gaps[int(rank)].append(gap_us / 1e6)
            elif e["ph"] == "E":
                last_end = e["ts"]
    return dict(gaps)


def try_detect(events: List[dict], related: Dict[int, Set[int]],
               stage1_threshold: int = STAGE1_THRESHOLD) -> List[int]:
    """Full two-stage detection; returns abnormal pids (reference
    try_detect → abnormal.txt)."""
    counts = detect_stage1(events)
    escalated = [pid for pid, c in counts.items() if c > stage1_threshold]
    abnormal = []
    for pid in escalated:
        # Stage 2 only filters when collective events with groups exist;
        # otherwise stage-1 escalation stands (the reference requires
        # _reduce events, which exist in its traces by construction).
        has_collectives = any(
            e["name"].startswith(p) for e in events
            for p in COLLECTIVE_PREFIXES)
        if not has_collectives or detect_stage2(events, related, pid):
            abnormal.append(pid)
    return abnormal
