"""Per-collective trace events synthesized from the XLA profiler.

Parity with the reference's hand-instrumented TP collectives
(/root/reference/megatron/core/tensor_parallel/mappings.py:27-60 records
group + bytes per op; /root/reference/megatron/training/trace.py:371-380
derives per-op Gbps) — but TPU-first: XLA inserts the collectives during
SPMD partitioning, so host code never sees them. Instead we

1. statically read the compiled HLO for every collective instruction
   (kind, output bytes, replica groups → mesh axes), and
2. capture one profiled execution (``jax.profiler.trace`` emits a Chrome
   trace with per-device X events carrying ``args.hlo_op``), then

join the two on the HLO op name into tracer-contract event dicts
({pid, name, ts, dur, args:{id, group, bytes, bandwidth_gbps,
iteration}}) that flow through trace/dependency.py ``build_dependencies``
and trace/detect.py stage 2 unchanged. This also restores collective
visibility on backends without host callbacks (the tunneled axon chip —
trace/tracer.py ``callbacks_supported``): the profiler path needs no
in-graph instrumentation at all.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import tempfile
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

_DTYPE_BYTES = {"f64": 8, "f32": 4, "f16": 2, "bf16": 2, "s32": 4,
                "u32": 4, "s64": 8, "u64": 8, "s16": 2, "u16": 2,
                "s8": 1, "u8": 1, "pred": 1, "f8e4m3fn": 1, "f8e5m2": 1}

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_INSTR_RE = re.compile(
    r"%?([\w.-]+)\s*=\s*(\([^)]*\)|\S+)\s+"
    r"(all-reduce-start|all-gather-start|reduce-scatter|all-reduce|"
    r"all-gather|collective-permute-start|collective-permute|all-to-all)"
    r"\(")
_GROUPS_RE = re.compile(r"replica_groups=(\{\{[\d,{}\s]*\}\}|\[[^\]]*\]"
                        r"<=\[[^\]]*\](?:T\([\d,]*\))?)")
_SRC_TGT_RE = re.compile(r"source_target_pairs=\{([\d,{}\s]*)\}")


def _shape_bytes(shape_text: str, result_only: bool = False) -> int:
    """'f32[32,64]{1,0}' or '(f32[8], f32[8])' → payload bytes.

    result_only: async '-start' ops have tuple shapes holding (operands,
    results); count only the result half so bytes are not double-counted
    (e.g. all-reduce-start's (in, out) pair)."""
    shapes = _SHAPE_RE.findall(shape_text)
    if result_only and len(shapes) > 1:
        shapes = shapes[len(shapes) // 2:]
    total = 0
    for dtype, dims in shapes:
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def _parse_groups(text: str) -> List[List[int]]:
    """Decode replica_groups: explicit '{{0,1},{2,3}}' or iota
    '[2,2]<=[4]' / '[2,2]<=[2,2]T(1,0)'."""
    if text.startswith("{{"):
        return [[int(x) for x in g.split(",") if x.strip()]
                for g in re.findall(r"\{([\d,\s]*)\}", text[1:-1])]
    m = re.match(r"\[([\d,]*)\]<=\[([\d,]*)\](?:T\(([\d,]*)\))?", text)
    if not m:
        return []
    gshape = [int(x) for x in m.group(1).split(",")]
    dims = [int(x) for x in m.group(2).split(",")]
    ids = np.arange(int(np.prod(dims))).reshape(dims)
    if m.group(3):
        ids = ids.transpose([int(x) for x in m.group(3).split(",")])
    return ids.reshape(gshape).tolist()


def _axes_of_groups(groups: List[List[int]], mesh) -> str:
    """Mesh axes a collective spans: axes whose coordinate varies within
    a participant group (e.g. tp for the TP all-reduce)."""
    if mesh is None or not groups or len(groups[0]) < 2:
        return ""
    coord_of = {}
    it = np.nditer(np.asarray(mesh.devices, dtype=object),
                   flags=["multi_index", "refs_ok"])
    for dev in it:
        coord_of[dev.item().id] = it.multi_index
    g = [coord_of.get(d) for d in groups[0]]
    if any(c is None for c in g):
        return ""
    varying = [mesh.axis_names[i] for i in range(len(mesh.axis_names))
               if len({c[i] for c in g}) > 1]
    return "x".join(varying)


def extract_hlo_collectives(hlo_text: str, mesh=None) -> Dict[str, dict]:
    """Map HLO op name → {kind, bytes, groups, axes} for every collective
    in a compiled module (the static half of the join)."""
    out: Dict[str, dict] = {}
    for line in hlo_text.splitlines():
        m = _INSTR_RE.search(line)
        if not m:
            continue
        name, shape_text, kind = m.groups()
        is_async = kind.endswith("-start")
        kind = kind.replace("-start", "")
        info = {"kind": kind,
                "bytes": _shape_bytes(shape_text, result_only=is_async)}
        gm = _GROUPS_RE.search(line)
        groups = _parse_groups(gm.group(1)) if gm else []
        if not groups and kind == "collective-permute":
            pm = _SRC_TGT_RE.search(line)
            if pm:
                pairs = re.findall(r"\{(\d+),(\d+)\}", "{" + pm.group(1) + "}")
                members = sorted({int(a) for p in pairs for a in p})
                groups = [members]
        info["groups"] = groups
        info["axes"] = _axes_of_groups(groups, mesh)
        out[name] = info
    return out


def _attach_thread_ordinals(payload_events: List[dict],
                            events: List[dict]) -> None:
    """Synthesize ``args.device_ordinal`` on profiler builds that report
    all devices under ONE host plane.

    Newer jax profilers emit one Chrome-trace pid per device plane and a
    ``device_ordinal`` arg; the 0.4.x CPU profiler instead reports a
    single '/host:CPU' pid whose per-device EXECUTION THREADS carry the
    HLO X events (thread_name 'tf_XLATfrtCpuClient/...'). Map each thread
    that executed HLO ops to a device ordinal by thread_sort_index order
    (the profiler assigns them in device order) so the per-device pid
    attribution downstream keeps working."""
    missing = [e for e in events
               if "device_ordinal" not in e.get("args", {})]
    if not missing:
        return
    sort_index: Dict[tuple, int] = {}
    for e in payload_events:
        if e.get("ph") == "M" and e.get("name") == "thread_sort_index":
            sort_index[(e.get("pid"), e.get("tid"))] = int(
                e["args"]["sort_index"])
    # Only UNannotated threads get synthesized ordinals, numbered after
    # any real annotated ordinals so a mixed trace (device planes
    # annotated, host-plane HLO events not) never aliases a host thread
    # onto an existing device.
    annotated = {int(e["args"]["device_ordinal"]) for e in events
                 if "device_ordinal" in e.get("args", {})}
    base = max(annotated) + 1 if annotated else 0
    exec_threads = sorted(
        {(e.get("pid"), e.get("tid")) for e in missing},
        key=lambda k: (sort_index.get(k, 1 << 30), k))
    ordinal_of = {k: base + i for i, k in enumerate(exec_threads)}
    for e in missing:
        e.setdefault("args", {})["device_ordinal"] = \
            ordinal_of[(e.get("pid"), e.get("tid"))]


def parse_profile_dir(trace_dir: str, cleanup: bool = False) -> List[dict]:
    """Read a jax.profiler output directory → the raw per-device
    Chrome-trace X events that carry an hlo_op."""
    paths = sorted(glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True))
    events: List[dict] = []
    if paths:
        with gzip.open(paths[-1]) as f:
            payload = json.load(f)
        all_events = payload.get("traceEvents", [])
        events = [e for e in all_events
                  if e.get("ph") == "X" and "hlo_op" in e.get("args", {})]
        _attach_thread_ordinals(all_events, events)
    if cleanup:
        import shutil
        shutil.rmtree(trace_dir, ignore_errors=True)
    return events


def profile_run(run: Callable[[], Any],
                trace_dir: Optional[str] = None) -> List[dict]:
    """Execute ``run`` under jax.profiler and return the raw per-device
    Chrome-trace X events that carry an hlo_op.

    The fence is a device_get of the smallest output leaf, not
    block_until_ready: on the tunneled axon backend block_until_ready
    does not wait, and the profiler would stop before the step ran."""
    import jax

    own = trace_dir is None
    trace_dir = trace_dir or tempfile.mkdtemp(prefix="jax_prof_")
    with jax.profiler.trace(trace_dir):
        out = run()
        leaves = [l for l in jax.tree.leaves(out) if hasattr(l, "size")]
        if leaves:
            jax.device_get(min(leaves, key=lambda l: l.size))
        jax.block_until_ready(out)
    return parse_profile_dir(trace_dir, cleanup=own)


def collective_events(raw_events: Sequence[dict],
                      hlo_info: Dict[str, dict],
                      iteration: int = 0,
                      id_base: int = 0,
                      process_index: Optional[int] = None,
                      local_device_count: Optional[int] = None
                      ) -> List[dict]:
    """Join profiler events with HLO metadata into tracer-contract
    records (trace/dependency.py: args carries id/group/bytes;
    trace/detect.py stage 2 keys on the collective name prefixes).

    Each local device gets its own timeline (the reference's per-GPU
    process granularity): pid = 1000*(process+1) + local ordinal — a
    range disjoint from process pids so device rows never collide with
    the host-side schedule records. The profiler reports LOCAL ordinals;
    replica groups contain GLOBAL device ids, so membership is checked
    against process*local_count + ordinal. args carries 'process' (owner,
    for detector stage-2 attribution) and 'device' (global id)."""
    import jax

    if process_index is None:
        process_index = jax.process_index()
    if local_device_count is None:
        local_device_count = jax.local_device_count()
    out: List[dict] = []
    next_id = id_base
    for e in sorted(raw_events, key=lambda x: (x.get("ts", 0.0))):
        op = e["args"]["hlo_op"]
        base = op.split(".")[0]
        info = hlo_info.get(op) or hlo_info.get(base)
        if info is None or info["kind"] not in COLLECTIVE_KINDS:
            continue
        ordinal = int(e["args"].get("device_ordinal", e.get("pid", 0)))
        dev = process_index * local_device_count + ordinal
        group = next((g for g in info["groups"] if dev in g),
                     info["groups"][0] if info["groups"] else [])
        dur_us = float(e.get("dur", 0.0))
        gbps = (info["bytes"] * 8e-3 / dur_us) if dur_us > 0 else 0.0
        out.append({
            "ph": "X", "pid": 1000 * (process_index + 1) + ordinal,
            "tid": e.get("tid", 0),
            "name": info["kind"], "ts": float(e["ts"]), "dur": dur_us,
            "args": {"id": next_id, "hlo_op": op, "group": group,
                     "bytes": info["bytes"], "axes": info["axes"],
                     "bandwidth_gbps": round(gbps, 3),
                     "process": process_index, "device": dev,
                     "iteration": iteration},
        })
        next_id += 1
    return out


def profile_step_collectives(compiled, run: Callable[[], Any], mesh=None,
                             iteration: int = 0) -> List[dict]:
    """One-call convenience: HLO metadata from ``compiled`` (a
    jax.stages.Compiled) + one profiled execution of ``run`` → joined
    collective event records."""
    info = extract_hlo_collectives(compiled.as_text(), mesh)
    raw = profile_run(run)
    return collective_events(raw, info, iteration=iteration)
