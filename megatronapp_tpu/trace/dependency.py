"""Dependency reconstruction + P2P amendment for merged traces.

Behavioral parity with /root/reference/scripts/dependency.py:
- :26 dependency(): events whose (name, sorted participant group) coincide
  are the same logical collective → grouped into a related_sync_op set;
- :54 amendP2P(): for matched send/recv pairs, both sides are shrunk to the
  overlap (the actual transfer) — the long side was waiting, not moving
  bytes — and annotated with the max of the two measured bandwidths.

Events carry the participant list in args['group'] (tracer.set_attr /
set_group parity) and byte counts in args['bytes'].
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Set


def build_dependencies(events: List[dict]) -> Dict[int, Set[int]]:
    """Map event id → set of related event ids (same collective instance).

    Same (name, sorted group, iteration, occurrence-index-within-iteration)
    across processes = one logical op, exactly the reference's matching key.
    """
    buckets: Dict[tuple, List[dict]] = defaultdict(list)
    for e in events:
        group = e.get("args", {}).get("group")
        if not group:
            continue
        key_base = (e["name"], tuple(sorted(group)),
                    e["args"].get("iteration", -1))
        buckets[key_base].append(e)

    related: Dict[int, Set[int]] = {}
    for key, evs in buckets.items():
        # Within a bucket, the n-th occurrence on each pid matches the n-th
        # occurrence on every other pid.
        per_pid: Dict[int, List[dict]] = defaultdict(list)
        for e in sorted(evs, key=lambda x: x["ts"]):
            per_pid[e["pid"]].append(e)
        depth = max(len(v) for v in per_pid.values())
        for i in range(depth):
            ids = {v[i]["args"]["id"] for v in per_pid.values()
                   if len(v) > i}
            for v in per_pid.values():
                if len(v) > i:
                    v[i]["args"]["related_sync_op"] = sorted(ids)
                    related[v[i]["args"]["id"]] = ids
    return related


def amend_p2p(events: List[dict], related: Dict[int, Set[int]]) -> None:
    """Shrink matched send/recv pairs to the actual transfer window
    (reference amendP2P): new duration = min of the pair; both get the max
    bandwidth; start aligned to the later start."""
    by_id = {e["args"]["id"]: e for e in events if "id" in e.get("args", {})}
    done = set()
    for eid, ids in related.items():
        if eid in done or len(ids) != 2:
            continue
        a_id, b_id = sorted(ids)
        a, b = by_id.get(a_id), by_id.get(b_id)
        if not a or not b or a["ph"] != "X" or b["ph"] != "X":
            continue
        name = a["name"]
        if not (name.startswith("send") or name.startswith("recv") or
                name.startswith("exchange") or "p2p" in name):
            continue
        start = max(a["ts"], b["ts"])
        dur = min(a["dur"], b["dur"])
        bw = max(a["args"].get("bandwidth", 0.0),
                 b["args"].get("bandwidth", 0.0))
        for e in (a, b):
            e["args"]["orig_ts"] = e["ts"]
            e["args"]["orig_dur"] = e["dur"]
            e["ts"] = start
            e["dur"] = dur
            if bw:
                e["args"]["bandwidth"] = bw
        done.update(ids)
