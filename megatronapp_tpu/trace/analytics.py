"""Offline trace analytics: iteration times, compute/communication ratio,
phase windows.

Parity with /root/reference/profiling/process_*.py (process_data.py,
process_send_compute.py, process_memory.py: iteration-time stats,
compute-vs-send ratio and windows, peak memory across pp/dpp runs) —
computed from our aggregated Chrome-trace events (trace/aggregate.py
transform_to_complete_events 'X' records).

Usage:
  python -m megatronapp_tpu.trace.analytics --trace-dir trace/ [--json out]
"""

from __future__ import annotations

import argparse
import json
from collections import defaultdict
from typing import Dict, List, Optional

# Event names that are communication (collectives/transfers) — matches the
# tracer's collective scope names + schedule-phase comm spans.
_COMM_MARKERS = ("all-reduce", "all-gather", "reduce-scatter", "allreduce",
                 "ppermute", "all-to-all", "send", "recv", "exchange",
                 "grad-sync")


def is_comm_event(name: str) -> bool:
    low = name.lower()
    return any(m in low for m in _COMM_MARKERS)


def iteration_time_stats(events: List[dict]) -> Dict:
    """Per-iteration wall time stats from 'iteration' X events (µs)."""
    durs = sorted(e["dur"] for e in events
                  if e.get("name") == "iteration" and e.get("ph") == "X")
    if not durs:
        return {"iterations": 0}
    n = len(durs)
    return {
        "iterations": n,
        "mean_us": sum(durs) / n,
        "p50_us": durs[n // 2],
        "max_us": durs[-1],
        "min_us": durs[0],
    }


def compute_comm_ratio(events: List[dict]) -> Dict:
    """Total compute vs communication span time per process (reference
    process_send_compute.py ratio)."""
    per_pid = defaultdict(lambda: {"compute_us": 0.0, "comm_us": 0.0})
    # Wrapper spans contain the phase spans — counting both would double
    # every microsecond (train-step wraps forward/backward/grad-sync).
    wrappers = {"iteration", "train-step"}
    for e in events:
        if e.get("ph") != "X" or e.get("name") in wrappers:
            continue
        bucket = "comm_us" if is_comm_event(e["name"]) else "compute_us"
        per_pid[e.get("pid", 0)][bucket] += e["dur"]
    out = {}
    for pid, d in sorted(per_pid.items()):
        total = d["compute_us"] + d["comm_us"]
        out[pid] = {**d,
                    "comm_fraction": (d["comm_us"] / total if total
                                      else 0.0)}
    return out


def phase_windows(events: List[dict]) -> Dict[str, Dict]:
    """Per-phase (forward/backward/loss/allreduce/optimizer) totals +
    counts — the schedule-phase breakdown the reference's detector keys on
    (scripts/aggregate.py try_detect inputs)."""
    agg = defaultdict(lambda: {"total_us": 0.0, "count": 0})
    for e in events:
        if e.get("ph") != "X":
            continue
        name = e["name"]
        if name in ("forward", "backward", "loss", "allreduce",
                    "optimizer", "grad-sync", "train-step"):
            agg[name]["total_us"] += e["dur"]
            agg[name]["count"] += 1
    return dict(agg)


def collective_stats(events: List[dict]) -> Dict[str, Dict]:
    """Per-kind collective summary from profiler-derived records
    (trace/profiler_collectives.py): count, total bytes, duration, and
    mean/max bandwidth — the reference's per-op Gbps reporting
    (training/trace.py:371-380) aggregated per collective kind."""
    agg = defaultdict(lambda: {"count": 0, "bytes_total": 0,
                               "time_us": 0.0, "gbps": []})
    # Convention: totals are per LOGICAL collective (the reference's
    # per-op accounting), not per participant. Each device in a group
    # contributes its own copy of the same event, so copies are deduped
    # ACROSS pids by matching the n-th occurrence of
    # (name, hlo_op, iteration, group) per pid — the same logical-op
    # identity trace/dependency.py uses. This is robust to aggregated
    # and raw per-rank traces alike (a 1/len(group) weighting would
    # undercount the latter) while still counting repeated executions of
    # one HLO op within an iteration (per-microbatch loop collectives)
    # separately. bytes count once per occurrence; time_us takes the
    # slowest participant (the collective's critical path); per-copy
    # bandwidths all feed the mean/max.
    #
    # Dropped-event guard (ADVICE round 5): when a pid dropped copies,
    # its occurrence numbering lags the other pids', so its n-th event
    # would pair with a DIFFERENT logical op and corrupt the
    # slowest-participant merge. The cross-pid matching window is
    # therefore CLAMPED to the minimum per-pid occurrence count of the
    # ident; occurrences beyond it keep per-pid identities (each counts
    # as its own logical op — a conservative overcount of at most the
    # dropped tail). An EARLY drop can still misalign pairings inside the
    # common window (occurrence indices carry no timing); the clamp
    # bounds the damage to that window instead of letting the tail
    # inflate counts too — a span-overlap tie-breaker would be the full
    # fix if early drops show up in practice.
    def _is_copy(ev):
        return ev.get("ph") == "X" and "bandwidth_gbps" in ev.get(
            "args", {})

    def _ident_of(ev):
        args = ev.get("args", {})
        if not args.get("hlo_op"):
            return None
        return (ev["name"], args["hlo_op"], args.get("iteration"),
                tuple(args.get("group") or ()))

    ident_pid_totals: Dict[tuple, Dict] = defaultdict(
        lambda: defaultdict(int))
    for e in events:
        if _is_copy(e):
            ident = _ident_of(e)
            if ident is not None:
                ident_pid_totals[ident][e.get("pid")] += 1
    n_common = {ident: min(by_pid.values())
                for ident, by_pid in ident_pid_totals.items()}

    seen: Dict[tuple, str] = {}
    per_pid_n: Dict[tuple, int] = {}
    for e in sorted(events, key=lambda ev: (str(ev.get("pid")),
                                            ev.get("ts", 0.0))):
        args = e.get("args", {})
        if not _is_copy(e):
            continue
        a = agg[e["name"]]
        # Occurrence identity needs hlo_op (+iteration+group); events
        # without it (hand-built or foreign traces) can't be deduped and
        # each counts as its own occurrence.
        ident = _ident_of(e)
        if ident is not None:
            pkey = (e.get("pid"),) + ident
            n = per_pid_n.get(pkey, 0)
            per_pid_n[pkey] = n + 1
            if n < n_common[ident]:
                occ = ident + (n,)
            else:
                # Beyond the common window: some pid dropped copies of
                # this ident — keep per-pid identity (longer key shape,
                # so it can never collide with a merged occurrence).
                occ = ident + (e.get("pid"), n)
        else:
            occ = (id(e),)
        dur = float(e.get("dur", 0.0))
        if occ not in seen:
            seen[occ] = e["name"]
            a["count"] += 1
            a["bytes_total"] += int(args.get("bytes", 0))
            a["time_us"] += dur
            a.setdefault("max_dur", {})[occ] = dur
        else:
            prev = a.setdefault("max_dur", {}).get(occ, 0.0)
            if dur > prev:
                a["time_us"] += dur - prev
                a["max_dur"][occ] = dur
        if args["bandwidth_gbps"] > 0:
            a["gbps"].append(args["bandwidth_gbps"])
    out = {}
    for kind, a in sorted(agg.items()):
        gb = a.pop("gbps")
        a.pop("max_dur", None)
        a["count"] = int(a["count"])
        a["bytes_total"] = int(a["bytes_total"])
        a["time_us"] = round(a["time_us"], 3)
        out[kind] = {**a,
                     "gbps_mean": (round(sum(gb) / len(gb), 3)
                                   if gb else 0.0),
                     "gbps_max": max(gb) if gb else 0.0}
    return out


def analyze(trace_dir: str) -> Dict:
    """Full report over an aggregated (or raw per-rank) trace dir."""
    from megatronapp_tpu.trace.aggregate import aggregate_dir
    trace = aggregate_dir(trace_dir, output=None)
    events = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    return {
        "iteration_time": iteration_time_stats(events),
        "compute_comm": compute_comm_ratio(events),
        "phases": phase_windows(events),
        "collectives": collective_stats(events),
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace-dir", required=True)
    ap.add_argument("--json", default=None, help="write report here")
    args = ap.parse_args(argv)
    report = analyze(args.trace_dir)
    text = json.dumps(report, indent=1)
    if args.json:
        with open(args.json, "w") as f:
            f.write(text)
    print(text)


if __name__ == "__main__":
    main()
