"""MegaScan-TPU tracer: operator/phase-granularity event collection.

Parity with /root/reference/megatron/training/trace.py:242-617 (Tracer:
scoped B/E/i records, iteration windows, bandwidth attrs, rank gather) —
re-designed for TPU/XLA:

- CUDA events don't exist on TPU; instead we combine
  (a) host wall-clock scopes around dispatched work (schedule phases:
      forward/backward per microbatch, optimizer, data),
  (b) in-graph markers via ``io_callback(ordered=True)`` that timestamp the
      moment the running XLA program reaches a point — the TPU analogue of a
      CUDA event record, and
  (c) a per-iteration ``block_until_ready`` calibration fence, mirroring the
      reference's torch.cuda.synchronize at iteration_end
      (trace.py:385-411).
- Interval windows: trace only iterations where
  (iter - 1) % interval < continuous_iterations (trace.py:594-614).
- Records are Chrome-trace-style dicts {name, ph, ts(ns), pid, tid, args};
  per-process JSON files are merged by trace/aggregate.py exactly like the
  reference's per-rank files (scripts/aggregate.py).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

# Granularity sets (reference trace.py:75-132): 'full' records everything,
# 'schedule' only phase events, 'collective' adds comm ops.
GRANULARITY_EVENTS = {
    "schedule": {
        "train-step", "forward", "backward", "optimizer", "loss",
        "allreduce", "grad-sync", "data", "recv-warmup", "send-forward",
        "recv-forward", "send-backward", "recv-backward", "exchange-next",
        "exchange-prev", "checkpoint",
    },
    "collective": {
        "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
        "all-to-all", "tp-overlap-compute", "tp-overlap-permute",
        "cp-overlap-compute", "cp-overlap-permute",
        "moe-a2a-compute", "moe-a2a-permute", "pp-overlap-permute",
    },
}


def _now_ns() -> int:
    return time.perf_counter_ns()


_CALLBACKS_SUPPORTED: Optional[bool] = None


def callbacks_supported() -> bool:
    """Whether the backend supports host callbacks (io_callback).

    Standard PJRT TPU/CPU backends do; the tunneled 'axon' dev backend does
    not (UNIMPLEMENTED: host send/recv callbacks). Without callbacks the
    tracer degrades to host-side scopes (train-step/iteration spans) — the
    schedule-phase spans need callbacks.
    """
    global _CALLBACKS_SUPPORTED
    if _CALLBACKS_SUPPORTED is None:
        from jax.experimental import io_callback

        def probe(x):
            tok = io_callback(lambda _: np.zeros((), np.int32),
                              jax.ShapeDtypeStruct((), np.int32), x)
            return x + tok
        try:
            jax.device_get(jax.jit(probe)(np.int32(0)))
            _CALLBACKS_SUPPORTED = True
        except Exception:
            _CALLBACKS_SUPPORTED = False
    return _CALLBACKS_SUPPORTED


class Tracer:
    """Singleton tracer (reference get_tracer via global_vars.py)."""

    def __init__(self):
        self.enabled = False
        self.interval = 5
        self.continuous_iterations = 2
        self.trace_dir = "trace"
        self.granularity = "full"
        self.process_index = 0
        self.mesh_ctx = None
        self._records: List[Dict[str, Any]] = []
        self._iteration = -1
        self._iter_t0 = 0
        self.active = False
        self._lock = threading.Lock()
        self._scope_stack: List[str] = []
        self._save_lock = threading.Lock()
        self._saver_threads: List[threading.Thread] = []
        self._overhead_ns = 0

    # -- configuration ----------------------------------------------------
    def configure(self, enabled: bool = True, trace_dir: str = "trace",
                  interval: int = 5, continuous_iterations: int = 2,
                  granularity: str = "full", mesh_ctx=None):
        self.enabled = enabled
        self.trace_dir = trace_dir
        self.interval = max(interval, 1)
        self.continuous_iterations = max(continuous_iterations, 1)
        self.granularity = granularity
        self.mesh_ctx = mesh_ctx
        self.process_index = jax.process_index()
        if enabled:
            os.makedirs(trace_dir, exist_ok=True)

    def _window_active(self, iteration: int) -> bool:
        # Reference interval predicate (trace.py:594-614), 0-indexed iters.
        return iteration % self.interval < self.continuous_iterations

    # -- iteration lifecycle ----------------------------------------------
    def iteration_begin(self, iteration: int):
        if not self.enabled:
            return
        self.active = self._window_active(iteration)
        if not self.active:
            return
        self._iteration = iteration
        self._iter_t0 = _now_ns()
        self._emit("iteration", "B", 0, {"iteration": iteration})

    def iteration_end(self, iteration: int, fence: Any = None):
        if not self.enabled or not self.active:
            return
        # Calibration fence — analogous to torch.cuda.synchronize before
        # resolving events (reference trace.py iteration_end).
        if fence is not None:
            jax.block_until_ready(fence)
        self._emit("iteration", "E", _now_ns() - self._iter_t0, {})
        self.active = False

    # -- scopes ------------------------------------------------------------
    def _allowed(self, name: str) -> bool:
        if self.granularity == "full":
            return True
        allowed = GRANULARITY_EVENTS.get(self.granularity, set())
        return name in allowed or name in GRANULARITY_EVENTS["schedule"]

    @contextlib.contextmanager
    def scope(self, name: str, **attrs):
        if not (self.enabled and self.active and self._allowed(name)):
            yield self
            return
        t0 = _now_ns()
        self._emit(name, "B", t0 - self._iter_t0, attrs)
        self._scope_stack.append(name)
        try:
            yield self
        finally:
            self._scope_stack.pop()
            self._emit(name, "E", _now_ns() - self._iter_t0, attrs)

    def instant(self, name: str, **attrs):
        if self.enabled and self.active and self._allowed(name):
            self._emit(name, "i", _now_ns() - self._iter_t0, attrs)

    def set_attr(self, **attrs):
        """Attach attrs to the innermost open scope's B record (reference
        tracers.set / set_group, trace.py:499-526)."""
        if not (self.enabled and self.active and self._scope_stack):
            return
        target = self._scope_stack[-1]
        with self._lock:
            for rec in reversed(self._records):
                if rec["name"] == target and rec["ph"] == "B":
                    rec["args"].update(attrs)
                    break

    # -- in-graph phase spans ----------------------------------------------
    def phase_event(self, name: str, ph: str, tid: int = 0, **attrs):
        """Host-side record emission used by in-graph callbacks.

        tid: per-process timeline; 0 is the host-scope timeline, the
        tp-overlap ring spans use tid = tp_rank + 1 (parallel/overlap.py)
        so per-rank B/E pairs nest cleanly in the merged trace."""
        if self.enabled and self.active:
            self._emit(name, ph, _now_ns() - self._iter_t0, attrs, tid=tid)

    # -- in-graph markers ---------------------------------------------------
    def marker(self, name: str, x, **attrs):
        """In-graph event marker: identity on x, records host time when the
        XLA program reaches this point (ordered io_callback) — the TPU
        analogue of torch.cuda.Event. Safe under jit; no-op python-side when
        tracing disabled at trace time."""
        if not self.enabled:
            return x
        from jax.experimental import io_callback

        def _cb(_):
            if self.active:
                self._emit(name, "i", _now_ns() - self._iter_t0,
                           dict(attrs, marker=True))
            return np.zeros((), np.int32)

        token = io_callback(_cb, jax.ShapeDtypeStruct((), np.int32),
                            np.zeros((), np.int32), ordered=True)
        # Tie the callback into the data flow so XLA cannot reorder it away.
        first = jax.tree.leaves(x)[0]
        anchored = first + token.astype(first.dtype) * 0
        leaves = jax.tree.leaves(x)
        leaves[0] = anchored
        return jax.tree.unflatten(jax.tree.structure(x), leaves)

    # -- record handling -----------------------------------------------------
    def _emit(self, name: str, ph: str, ts_ns: int, args: Dict[str, Any],
              tid: int = 0):
        rec = {
            "name": name, "ph": ph, "ts": ts_ns / 1e3,  # Chrome trace: µs
            "pid": self.process_index,
            "tid": tid,
            "iteration": self._iteration,
            "args": dict(args),
        }
        if "data" in args:
            rec["args"]["bytes"] = int(args["data"])
        with self._lock:
            self._records.append(rec)

    def now_in_iteration_us(self) -> float:
        """Current offset inside the open iteration window (µs)."""
        return (_now_ns() - self._iter_t0) / 1e3

    def add_collective_records(self, events: List[Dict[str, Any]],
                               offset_us: Optional[float] = None):
        """Merge profiler-derived collective events
        (trace/profiler_collectives.py; per-device pids already disjoint
        from process pids) into this iteration's records.

        offset_us anchors the capture inside the iteration window — pass
        the value of now_in_iteration_us() taken BEFORE the profiled
        execution, so events land where the collectives ran rather than
        after the (per-process, variable) profile parsing delay that
        would skew cross-process stage-2 comparisons."""
        if not (self.enabled and self.active and events):
            return
        base = min(e["ts"] for e in events)
        if offset_us is None:
            offset_us = self.now_in_iteration_us()
        recs = []
        for e in events:
            recs.append({
                "name": e["name"], "ph": "X",
                "ts": e["ts"] - base + offset_us,
                "dur": e.get("dur", 0.0),
                "pid": e["pid"],
                "tid": e.get("tid", 0),
                "iteration": self._iteration,
                "args": dict(e.get("args", {}),
                             iteration=self._iteration),
            })
        with self._lock:
            self._records.extend(recs)

    def drain(self) -> List[Dict[str, Any]]:
        with self._lock:
            recs, self._records = self._records, []
        return recs

    def peek(self) -> List[Dict[str, Any]]:
        """Non-destructive snapshot of the buffered records: the
        trace-driven pipeline planner (parallel/schedule.Planner) reads
        the per-stage ring-hop spans of a traced iteration BEFORE save()
        drains them to disk."""
        with self._lock:
            return list(self._records)

    def save(self, path: Optional[str] = None):
        """Append records to the per-process trace file (reference background
        saver thread, trace.py:136-193; file naming parity with
        benchmark-data-*.json)."""
        recs = self.drain()
        if not recs:
            return
        ctx = self.mesh_ctx
        if ctx is not None:
            fname = (f"benchmark-data-{ctx.dp}-pipeline-{ctx.pp}"
                     f"-tensor-{ctx.tp}-process-{self.process_index}.json")
        else:
            fname = f"benchmark-data-process-{self.process_index}.json"
        path = path or os.path.join(self.trace_dir, fname)

        def _write():
            # _save_lock serializes concurrent save() calls so the
            # read-modify-write below cannot drop or corrupt records.
            with self._save_lock:
                existing = []
                if os.path.exists(path):
                    with open(path) as f:
                        try:
                            existing = json.load(f)
                        except json.JSONDecodeError:
                            existing = []
                existing.extend(recs)
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(existing, f)
                os.replace(tmp, path)

        t = threading.Thread(target=_write, daemon=True)
        t.start()
        self._saver_threads.append(t)

    def finalize(self):
        self.save()
        for t in self._saver_threads:
            t.join()
        self._saver_threads.clear()


_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


# ---------------------------------------------------------------------------
# In-graph schedule-phase spans (SURVEY §2.4: the schedule-phase events —
# forward/backward/loss/optimizer — whose emit sites the reference lost in
# its rebase and the detector depends on). A span is a custom-VJP identity:
# its forward emits the forward-phase record, and because cotangents traverse
# the graph in reverse, the SAME pair of spans around a forward region
# automatically emits a correctly-oriented 'backward' span during the
# backward pass — the TPU-native analogue of wrapping both fwd and bwd
# schedule phases with CUDA events.
# ---------------------------------------------------------------------------

def _phase_cb(name: str, ph: str):
    def cb(_):
        _TRACER.phase_event(name, ph)
        return np.zeros((), np.int32)
    return cb


def _emit_in_graph(x_anchor, name: str, ph: str):
    from jax.experimental import io_callback
    from jax.sharding import SingleDeviceSharding
    # Under SPMD partitioning a side-effecting callback may not be
    # replicated — pin it to one device (this process records one timeline,
    # like the reference's one-tracer-per-rank). ordered=True is not
    # SPMD-compatible (its ordering token stays replicated → partitioner
    # RET_CHECK); execution order is enforced by the data dependency on
    # x_anchor instead.
    token = io_callback(_phase_cb(name, ph),
                        jax.ShapeDtypeStruct((), np.int32),
                        x_anchor, ordered=False,
                        sharding=SingleDeviceSharding(jax.local_devices()[0]))
    return token


def _anchor_scalar(tree):
    leaf = jax.tree.leaves(tree)[0]
    return (jax.lax.stop_gradient(leaf).ravel()[0] * 0).astype(np.float32)


def _tie(tree, token):
    leaves = jax.tree.leaves(tree)
    first = leaves[0]
    leaves[0] = first + token.astype(first.dtype) * 0
    return jax.tree.unflatten(jax.tree.structure(tree), leaves)


def _make_span(fwd_ph: str, bwd_ph: str):
    def span(tree, fwd_name: str, bwd_name: Optional[str] = None):
        def _primal(t):
            # The primal body must ALSO emit: JAX uses the primal (not the
            # fwd rule) when the span is not on a differentiation path
            # (e.g. spans around the optimizer update).
            tok = _emit_in_graph(_anchor_scalar(t), fwd_name, fwd_ph)
            return _tie(t, tok)

        @jax.custom_vjp
        def f(t):
            return _primal(t)

        def fwd(t):
            return _primal(t), None

        def bwd(_, g):
            if bwd_name is not None:
                # Cotangent leaves can be float0 (int inputs); anchor on a
                # constant — ordering comes from surrounding data deps.
                tok = _emit_in_graph(jnp.zeros((), jnp.float32),
                                     bwd_name, bwd_ph)
                floats = [l for l in jax.tree.leaves(g)
                          if hasattr(l, "dtype") and
                          jnp.issubdtype(l.dtype, jnp.floating)]
                if floats:
                    g = _tie_first_float(g, tok)
            return (g,)

        f.defvjp(fwd, bwd)
        return f(tree)

    return span


def _tie_first_float(tree, token):
    leaves = jax.tree.leaves(tree)
    for i, l in enumerate(leaves):
        if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.floating):
            leaves[i] = l + token.astype(l.dtype) * 0
            break
    return jax.tree.unflatten(jax.tree.structure(tree), leaves)


# Open fwd_name in the forward pass; close bwd_name in the backward pass.
phase_span_begin = _make_span("B", "E")
# Close fwd_name in the forward pass; open bwd_name in the backward pass.
phase_span_end = _make_span("E", "B")
