"""MegaScan aggregation: per-process trace files → merged Chrome trace.

Behavioral parity with /root/reference/scripts/aggregate.py (:56
collect_benchmark_files, :92 read_benchmark_file, :142
aggregate_benchmark_data, :273 transform B/E→X, :337
benchmark_to_chrome_trace) — reimplemented for our record schema (tracer.py
emits Chrome-style dicts with ts in µs relative to each iteration start).

Timeline stitching: iterations are aligned across processes by padding each
iteration's events to a shared global timeline (the reference's pad_before +
per-iteration max-duration logic): global_offset(iter) = sum over previous
iterations of max-across-ranks(iteration duration).
"""

from __future__ import annotations

import glob
import json
import os
from collections import defaultdict
from typing import Dict, List, Optional

# Stable color assignment per event name (Chrome trace 'cname' is limited;
# we use the reference's approach of cycling a palette per name).
_COLORS = [
    "thread_state_running", "thread_state_runnable", "rail_response",
    "rail_animation", "rail_idle", "rail_load", "good", "bad", "terrible",
    "cq_build_passed", "cq_build_failed", "cq_build_running",
]


def collect_benchmark_files(trace_dir: str) -> List[str]:
    return sorted(glob.glob(os.path.join(trace_dir, "benchmark-data-*.json")))


def read_benchmark_file(path: str) -> List[dict]:
    with open(path) as f:
        return json.load(f)


def _iteration_spans(records: List[dict]) -> Dict[int, float]:
    """Per-iteration duration (µs) = ts of the iteration E record."""
    spans = {}
    for r in records:
        if r["name"] == "iteration" and r["ph"] == "E":
            spans[r["iteration"]] = max(spans.get(r["iteration"], 0.0),
                                        r["ts"])
    return spans


def aggregate_benchmark_data(per_process: Dict[int, List[dict]]
                             ) -> List[dict]:
    """Stitch per-process records onto one global timeline.

    Returns records with absolute 'ts' (µs); iteration k starts at the same
    global offset on every process (reference aggregate_benchmark_data)."""
    # Global per-iteration duration = max across processes.
    global_spans: Dict[int, float] = defaultdict(float)
    for recs in per_process.values():
        for it, dur in _iteration_spans(recs).items():
            global_spans[it] = max(global_spans[it], dur)
    iters = sorted(global_spans)
    offsets = {}
    cursor = 0.0
    for it in iters:
        offsets[it] = cursor
        cursor += global_spans[it] + 1.0  # 1µs gap

    out = []
    for pid, recs in per_process.items():
        for r in recs:
            it = r.get("iteration", -1)
            if it not in offsets:
                continue
            rr = dict(r)
            rr["ts"] = r["ts"] + offsets[it]
            # Records carry their own pid (host records: the process
            # index; profiler-derived collectives: process*1000+device,
            # tracer.add_collective_records); fall back to the file's
            # process id for legacy traces.
            rr["pid"] = r.get("pid", pid)
            out.append(rr)
    out.sort(key=lambda r: (r["ts"], r["pid"]))
    return out


def transform_to_complete_events(records: List[dict]) -> List[dict]:
    """B/E pairs → X (complete) events; i stays instant (reference
    transform, aggregate.py:273)."""
    out = []
    # Keyed by (pid, tid, name): spans of different phases interleave
    # (e.g. 'backward' opens while 'forward' of the next microbatch is
    # pending), so pairing must match names, not just nesting order.
    open_stacks: Dict[tuple, List[dict]] = defaultdict(list)
    color_map: Dict[str, str] = {}
    eid = 0
    for r in records:
        key = (r["pid"], r.get("tid", 0), r["name"])
        if r["ph"] == "B":
            open_stacks[key].append(r)
        elif r["ph"] == "E":
            if not open_stacks[key]:
                continue
            b = open_stacks[key].pop()
            name = b["name"]
            if name not in color_map:
                color_map[name] = _COLORS[len(color_map) % len(_COLORS)]
            eid += 1
            out.append({
                "name": name, "ph": "X", "ts": b["ts"],
                "dur": max(r["ts"] - b["ts"], 0.001),
                "pid": b["pid"], "tid": b.get("tid", 0),
                "cname": color_map[name],
                "args": {**b.get("args", {}),
                         "iteration": b.get("iteration", -1),
                         "id": eid},
            })
        elif r["ph"] == "i":
            eid += 1
            out.append({
                "name": r["name"], "ph": "i", "ts": r["ts"],
                "pid": r["pid"], "tid": r.get("tid", 0), "s": "t",
                "args": {**r.get("args", {}),
                         "iteration": r.get("iteration", -1), "id": eid},
            })
        elif r["ph"] == "X":
            # Pre-formed complete events (profiler-derived collectives,
            # trace/profiler_collectives.py) pass through. Ids are ALWAYS
            # reassigned here: producer ids restart per capture window
            # and per process, so keeping them would collide with span
            # ids and with each other, corrupting every id-keyed lookup
            # (dependency related-sets, detect stage 2, amend_p2p).
            eid += 1
            args = {**r.get("args", {})}
            args.setdefault("iteration", r.get("iteration", -1))
            args["id"] = eid
            out.append({
                "name": r["name"], "ph": "X", "ts": r["ts"],
                "dur": r.get("dur", 0.001), "pid": r["pid"],
                "tid": r.get("tid", 0), "args": args,
            })
    out.sort(key=lambda r: (r["ts"], r["pid"]))
    return out


def chrome_trace(events: List[dict], process_names: Optional[Dict[int, str]]
                 = None) -> dict:
    """Final Chrome trace JSON (with process_name/sort metadata like the
    reference's benchmark_to_chrome_trace)."""
    meta = []
    pids = sorted({e["pid"] for e in events})
    for pid in pids:
        name = (process_names or {}).get(pid, f"process {pid}")
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "args": {"name": name}})
        meta.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                     "args": {"sort_index": pid}})
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def aggregate_dir(trace_dir: str, output: Optional[str] = None,
                  detect: bool = False) -> dict:
    """Full offline pipeline (reference scripts/aggregate.py __main__):
    read per-process files → stitch → X events → dependency → [detect] →
    Chrome trace file."""
    from megatronapp_tpu.trace.dependency import (
        amend_p2p, build_dependencies,
    )

    files = collect_benchmark_files(trace_dir)
    if not files:
        raise FileNotFoundError(f"no benchmark-data-*.json in {trace_dir}")
    per_process = {}
    for path in files:
        recs = read_benchmark_file(path)
        pid = recs[0]["pid"] if recs else len(per_process)
        per_process[pid] = recs
    merged = aggregate_benchmark_data(per_process)
    events = transform_to_complete_events(merged)
    related = build_dependencies(events)
    amend_p2p(events, related)

    if detect:
        from megatronapp_tpu.trace.detect import try_detect
        suspects = try_detect(events, related)
        if suspects:
            with open(os.path.join(trace_dir, "abnormal.txt"), "w") as f:
                for s in suspects:
                    f.write(f"Abnormal chip: process {s}\n")

    trace = chrome_trace(events)
    if output:
        with open(output, "w") as f:
            json.dump(trace, f)
    return trace


def main(argv=None):
    """CLI parity with /root/reference/scripts/aggregate.py:
    python -m megatronapp_tpu.trace.aggregate -b DIR [-o OUT] [-d]"""
    import argparse
    ap = argparse.ArgumentParser(description="MegaScan trace aggregation")
    ap.add_argument("-b", "--benchmark-dir", required=True)
    ap.add_argument("-o", "--output", default=None)
    ap.add_argument("-d", "--detect", action="store_true")
    args = ap.parse_args(argv)
    out = args.output or os.path.join(args.benchmark_dir, "aggregated.json")
    aggregate_dir(args.benchmark_dir, out, detect=args.detect)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
