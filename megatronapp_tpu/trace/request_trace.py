"""Per-request lifecycle tracing: an always-on bounded ring of B/E spans.

The MegaScan tracer (trace/tracer.py) is iteration-window-gated — right
for training, useless for serving, where the interesting timeline is a
REQUEST's: admit → queue wait → prefill chunks → parked/handoff → adopt
→ decode steps → spec rounds → retire/expire/abort/preempt. This module
is the serving-side counterpart (ISSUE 12): a singleton ring-buffer
tracer that the engines emit Chrome-trace-style B/E/i records into,
bounded by ``capacity`` (old records fall off — tracing can stay ON in
production), with the SAME record schema as tracer.py so the existing
aggregation machinery (trace/aggregate.py: B/E→X pairing, Chrome trace
metadata) renders it.

Timeline layout:

- ``pid`` is the LOGICAL mesh/component: ``DECODE_PID`` (0) for the
  engine/decode side, ``PREFILL_PID`` (1) for the disaggregated prefill
  worker — a disagg request's prefill chunks and its decode lifetime
  merge into ONE Chrome trace with one process row per mesh.
- ``tid`` is the request id + 1 for per-request spans (each request gets
  its own timeline row; B/E pairing in aggregate.py keys on
  (pid, tid, name), so concurrent requests never mis-pair), and 0 for
  step-granularity spans (decode-step, spec-round).

Pairing is guaranteed by construction: ``end()`` is a no-op unless that
span is open (no orphan E), and ``finish()`` closes every span a
request still has open (retire/expire/abort paths all funnel through
it — no orphan B). tests/test_metrics.py pins every-B-has-a-matching-E
across the full lifecycle including expire and preempt.

The disabled path is one attribute truthiness check per call site.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

DECODE_PID = 0      # engine / decode sub-mesh timeline
PREFILL_PID = 1     # disaggregated prefill sub-mesh timeline

_PROCESS_NAMES = {DECODE_PID: "decode-mesh", PREFILL_PID: "prefill-mesh"}


class RequestTracer:
    """Bounded always-on request-lifecycle tracer (singleton via
    get_request_tracer)."""

    def __init__(self, capacity: int = 16384):
        self.enabled = False
        self.capacity = capacity
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        # rid -> [(pid, name), ...] open spans, innermost last.
        self._open: Dict[int, List[tuple]] = {}
        self._t0 = time.perf_counter_ns()
        # pid -> Chrome-trace process-row label. Extensible at runtime:
        # the fleet router labels replica rows ("replica-N decode") so a
        # migrated request's spans read across replicas in one trace
        # (ISSUE 14 — migration spans join the per-request timeline).
        self._pid_names: Dict[int, str] = dict(_PROCESS_NAMES)

    # -- configuration -----------------------------------------------------
    def configure(self, enabled: bool = True,
                  capacity: Optional[int] = None):
        with self._lock:
            self.enabled = enabled
            if capacity is not None and capacity != self.capacity:
                self.capacity = capacity
                self._ring = deque(self._ring, maxlen=capacity)

    def set_process_name(self, pid: int, name: str):
        """Label a process row (fleet replicas; custom meshes).
        reset() restores the default labels — custom names are part of
        the trace epoch, not global state."""
        with self._lock:
            self._pid_names[pid] = name

    def reset(self):
        """Drop all records, open-span state, and custom process
        labels (tests; fresh epochs)."""
        with self._lock:
            self._ring.clear()
            self._open.clear()
            self._pid_names = dict(_PROCESS_NAMES)
            self._t0 = time.perf_counter_ns()

    def _ts_us(self) -> float:
        return (time.perf_counter_ns() - self._t0) / 1e3

    # -- emission ----------------------------------------------------------
    def _emit(self, name: str, ph: str, rid: Optional[int], pid: int,
              attrs: Dict[str, Any]):
        rec = {
            "name": name, "ph": ph, "ts": self._ts_us(),
            "pid": pid,
            "tid": 0 if rid is None else rid + 1,
            "iteration": 0,
            "args": dict(attrs, rid=rid) if rid is not None else dict(attrs),
        }
        with self._lock:
            self._ring.append(rec)

    def begin(self, name: str, rid: Optional[int],
              pid: int = DECODE_PID, **attrs):
        if not self.enabled:
            return
        with self._lock:
            self._open.setdefault(rid, []).append((pid, name))
        self._emit(name, "B", rid, pid, attrs)

    def end(self, name: str, rid: Optional[int],
            pid: int = DECODE_PID, **attrs):
        """Close an open span. Tolerant: a no-op when `name` is not open
        for `rid` — the lifecycle paths overlap (abort during prefill,
        expire while parked) and an orphan E would corrupt B/E pairing
        downstream."""
        if not self.enabled:
            return
        with self._lock:
            spans = self._open.get(rid)
            if not spans or (pid, name) not in spans:
                return
            # Remove the innermost matching occurrence.
            for i in range(len(spans) - 1, -1, -1):
                if spans[i] == (pid, name):
                    del spans[i]
                    break
            if not spans:
                self._open.pop(rid, None)
        self._emit(name, "E", rid, pid, attrs)

    def instant(self, name: str, rid: Optional[int] = None,
                pid: int = DECODE_PID, **attrs):
        if not self.enabled:
            return
        self._emit(name, "i", rid, pid, attrs)

    def finish(self, rid: int, reason: Optional[str] = None, **attrs):
        """Terminal event for a request: optional instant `reason`
        (retire/expire/abort) then close EVERY span it still has open,
        innermost first — the one funnel that guarantees no orphan B on
        any exit path."""
        if not self.enabled:
            return
        if reason is not None:
            self._emit(reason, "i", rid, DECODE_PID, attrs)
        with self._lock:
            spans = self._open.pop(rid, [])
        for pid, name in reversed(spans):
            self._emit(name, "E", rid, pid, {})

    # -- export ------------------------------------------------------------
    def dump(self) -> List[dict]:
        """Ring contents, oldest first (records stay in the ring)."""
        with self._lock:
            return list(self._ring)

    def _windowed_records(self) -> List[dict]:
        """Records wrapped in a synthetic single-iteration window per
        pid, so trace/aggregate.py's iteration-stitching machinery
        (which keys offsets on 'iteration' B/E spans) accepts a serving
        trace as one window."""
        recs = self.dump()
        if not recs:
            return []
        t_end = max(r["ts"] for r in recs) + 1.0
        out = []
        for pid in sorted({r["pid"] for r in recs}):
            out.append({"name": "iteration", "ph": "B", "ts": 0.0,
                        "pid": pid, "tid": 0, "iteration": 0, "args": {}})
        out.extend(recs)
        for pid in sorted({r["pid"] for r in recs}):
            out.append({"name": "iteration", "ph": "E", "ts": t_end,
                        "pid": pid, "tid": 0, "iteration": 0, "args": {}})
        return out

    def chrome_trace(self, process_names: Optional[Dict[int, str]] = None
                     ) -> dict:
        """Render the ring as one merged Chrome trace through the
        existing aggregation machinery (B/E→X pairing + process
        metadata) — prefill-mesh and decode-mesh events land as separate
        process rows of the SAME trace."""
        from megatronapp_tpu.trace.aggregate import (
            chrome_trace as _chrome, transform_to_complete_events,
        )
        recs = sorted(self._windowed_records(),
                      key=lambda r: (r["ts"], r["pid"]))
        events = transform_to_complete_events(recs)
        return _chrome(events, process_names or dict(self._pid_names))

    def save(self, path: Optional[str] = None, trace_dir: str = "trace"
             ) -> str:
        """Write the ring as a benchmark-data-*.json file compatible
        with `python -m megatronapp_tpu.trace.aggregate -b DIR`, so
        serving request traces stitch offline next to training traces."""
        if path is None:
            os.makedirs(trace_dir, exist_ok=True)
            path = os.path.join(trace_dir, "benchmark-data-requests.json")
        with open(path, "w") as f:
            json.dump(self._windowed_records(), f)
        return path


def merge_process_traces(procs: List[tuple]) -> dict:
    """Merge per-PROCESS request-trace rings into ONE Chrome trace —
    the MegaScan per-rank-merge story applied to serving (ISSUE 18):
    each replica worker dumps its ring over RPC and the router renders
    one timeline with a process row per (worker, logical mesh).

    `procs` is ``[(label, records, pid_names), ...]`` where `records`
    is a ring dump (RequestTracer.dump()) and `pid_names` that
    process's pid→row-label map. Each process's ring has its OWN
    perf_counter epoch, so timestamps are normalized per ring (min →
    0); pids are offset by 100·i so rows never collide, and labels
    compose as "label name" ("replica-1 decode-mesh"). Empty rings are
    skipped. B/E pairing is per-(pid, tid, name), and the pid offset
    keeps every process's spans in their own rows, so pairing never
    crosses a process boundary."""
    from megatronapp_tpu.trace.aggregate import (
        chrome_trace as _chrome, transform_to_complete_events,
    )
    merged: List[dict] = []
    names: Dict[int, str] = {}
    for i, (label, records, pid_names) in enumerate(procs):
        if not records:
            continue
        base = 100 * i
        t_min = min(r["ts"] for r in records)
        t_end = max(r["ts"] for r in records) - t_min + 1.0
        pids = sorted({r["pid"] for r in records})
        for pid in pids:
            row = (pid_names or {}).get(pid, f"pid-{pid}")
            names[base + pid] = f"{label} {row}"
            merged.append({"name": "iteration", "ph": "B", "ts": 0.0,
                           "pid": base + pid, "tid": 0, "iteration": 0,
                           "args": {}})
        for r in records:
            merged.append(dict(r, ts=r["ts"] - t_min,
                               pid=base + r["pid"]))
        for pid in pids:
            merged.append({"name": "iteration", "ph": "E", "ts": t_end,
                           "pid": base + pid, "tid": 0, "iteration": 0,
                           "args": {}})
    merged.sort(key=lambda r: (r["ts"], r["pid"]))
    return _chrome(transform_to_complete_events(merged), names)


_TRACER = RequestTracer()


def get_request_tracer() -> RequestTracer:
    return _TRACER


if os.environ.get("MEGATRON_REQUEST_TRACE"):
    _TRACER.configure(enabled=True)
