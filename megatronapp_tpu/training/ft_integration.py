"""Fault-tolerance integration: heartbeat monitor with section timeouts.

Parity with /root/reference/megatron/training/ft_integration.py (the
NVIDIA resiliency-ext "rank monitor" bridge): the training process emits
heartbeats tagged with the current SECTION (setup / step / checkpointing);
a watchdog thread flags the run as hung when the active section exceeds its
timeout, and `maybe_setup_simulated_fault` injects a delayed hang/crash for
drills (reference maybe_setup_simulated_fault).

TPU-native notes: heartbeats also land in a small JSON file
(`<dir>/heartbeat.json`, atomic rename) so an EXTERNAL supervisor — the
analogue of the reference's separate rank-monitor process — can detect a
dead/hung training process from outside even when the in-process watchdog
is itself wedged by the same hang.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Callable, Dict, Optional


@dataclasses.dataclass
class FTConfig:
    """Section timeouts in seconds (reference --calc-ft-timeouts
    defaults)."""
    setup_timeout: float = 600.0
    step_timeout: float = 180.0
    checkpointing_timeout: float = 600.0
    check_interval: float = 5.0
    heartbeat_dir: Optional[str] = None
    # Floor between heartbeat FILE writes: beat() fires every training
    # iteration, but sub-second steps must not hammer the (often
    # shared) filesystem with a write+rename per step — the in-memory
    # watchdog timestamp still updates on every beat, and supervisors
    # read staleness at tens-of-seconds granularity. Section changes
    # always write (they are rare and meaningful).
    heartbeat_write_interval: float = 1.0


class HeartbeatMonitor:
    """In-process watchdog + on-disk heartbeat file."""

    def __init__(self, cfg: FTConfig,
                 on_timeout: Optional[Callable[[str, float], None]] = None):
        self.cfg = cfg
        self.on_timeout = on_timeout or self._default_on_timeout
        self._section = "setup"
        self._last_beat = time.monotonic()
        self._last_write = 0.0   # monotonic time of the last file write
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.timed_out_sections: list = []

    # -- section lifecycle -------------------------------------------------
    def start_section(self, section: str):
        assert section in ("setup", "step", "checkpointing"), section
        with self._lock:
            self._section = section
            self._last_beat = time.monotonic()
        self._write_heartbeat()

    def beat(self):
        with self._lock:
            self._last_beat = time.monotonic()
            throttled = (time.monotonic() - self._last_write
                         < self.cfg.heartbeat_write_interval)
        if not throttled:
            self._write_heartbeat()

    def _timeout_for(self, section: str) -> float:
        return {"setup": self.cfg.setup_timeout,
                "step": self.cfg.step_timeout,
                "checkpointing": self.cfg.checkpointing_timeout}[section]

    # -- watchdog ----------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.cfg.check_interval * 2)

    def _run(self):
        while not self._stop.wait(self.cfg.check_interval):
            with self._lock:
                section = self._section
                idle = time.monotonic() - self._last_beat
            limit = self._timeout_for(section)
            if idle > limit:
                self.timed_out_sections.append(section)
                self.on_timeout(section, idle)

    def _default_on_timeout(self, section: str, idle: float):
        print(f"ft: section {section!r} exceeded its timeout "
              f"({idle:.0f}s > {self._timeout_for(section):.0f}s) — "
              f"rank appears hung", flush=True)

    def _write_heartbeat(self):
        if not self.cfg.heartbeat_dir:
            return
        os.makedirs(self.cfg.heartbeat_dir, exist_ok=True)
        path = os.path.join(self.cfg.heartbeat_dir, "heartbeat.json")
        tmp = path + ".tmp"
        with self._lock:
            payload = {"section": self._section, "ts": time.time(),
                       "pid": os.getpid()}
            self._last_write = time.monotonic()
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)


def read_heartbeat(heartbeat_dir: str,
                   stale_after: float = 60.0) -> Dict:
    """External-supervisor view: {'alive': bool, 'section', 'age'} from the
    heartbeat file (the out-of-process detection path)."""
    path = os.path.join(heartbeat_dir, "heartbeat.json")
    if not os.path.exists(path):
        return {"alive": False, "section": None, "age": None}
    with open(path) as f:
        hb = json.load(f)
    age = time.time() - hb["ts"]
    return {"alive": age < stale_after, "section": hb["section"],
            "age": age}


def maybe_setup_simulated_fault(kind: Optional[str], delay_s: float,
                                target: Optional[Callable] = None):
    """Schedule a fault for FT drills (reference
    maybe_setup_simulated_fault): kind 'hang' blocks the caller-provided
    target hook; 'exit' hard-exits the process after `delay_s`."""
    if not kind:
        return None
    assert kind in ("hang", "exit"), kind

    def fire():
        time.sleep(delay_s)
        if kind == "exit":
            print(f"ft: simulated fault 'exit' firing after {delay_s}s",
                  flush=True)
            os._exit(42)
        if target is not None:
            target()

    t = threading.Thread(target=fire, daemon=True)
    t.start()
    return t
