"""Knowledge distillation loss (post-training).

Parity with /root/reference/megatron/post_training/algos/distillation.py
(ModelOpt logits-distillation: student trains against softened teacher
distributions mixed with the hard-label CE). The reference delegates to the
modelopt package; the math is small and backend-agnostic, so it lives here
natively: loss = alpha * T² * KL(teacher_T ‖ student_T)
              + (1 - alpha) * CE(student, labels).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from megatronapp_tpu.ops.cross_entropy import cross_entropy_loss


def soft_kl_loss(student_logits: jnp.ndarray, teacher_logits: jnp.ndarray,
                 temperature: float = 1.0,
                 loss_mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Token-mean KL(teacher ‖ student) at temperature T (fp32), scaled by
    T² (the standard Hinton correction so gradients are T-invariant)."""
    t = float(temperature)
    s = jax.nn.log_softmax(student_logits.astype(jnp.float32) / t, axis=-1)
    te = jax.nn.log_softmax(teacher_logits.astype(jnp.float32) / t, axis=-1)
    per_token = jnp.sum(jnp.exp(te) * (te - s), axis=-1)
    if loss_mask is None:
        return t * t * jnp.mean(per_token)
    loss_mask = loss_mask.astype(jnp.float32)
    return t * t * jnp.sum(per_token * loss_mask) / jnp.maximum(
        jnp.sum(loss_mask), 1.0)


def distillation_loss(student_logits: jnp.ndarray,
                      teacher_logits: jnp.ndarray,
                      labels: jnp.ndarray,
                      loss_mask: Optional[jnp.ndarray] = None,
                      temperature: float = 2.0,
                      alpha: float = 0.5
                      ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Combined KD objective (reference logits-distillation recipe)."""
    kd = soft_kl_loss(student_logits, teacher_logits, temperature,
                      loss_mask)
    ce, _ = cross_entropy_loss(student_logits, labels, loss_mask)
    total = alpha * kd + (1.0 - alpha) * ce
    return total, {"kd_loss": kd, "lm_loss": ce}


def make_distillation_loss_fn(student_loss_cfg, teacher_params,
                              teacher_cfg, temperature: float = 2.0,
                              alpha: float = 0.5, ctx=None):
    """loss_fn(student_params, micro) for make_train_step: the frozen
    teacher forward runs inside the same jit (stop_gradient), so XLA
    overlaps teacher and student compute."""
    from megatronapp_tpu.models.gpt import gpt_forward

    def loss_fn(params, micro):
        s_logits, aux = gpt_forward(params, micro["tokens"],
                                    student_loss_cfg, ctx=ctx)
        t_logits, _ = gpt_forward(teacher_params, micro["tokens"],
                                  teacher_cfg, ctx=ctx)
        t_logits = jax.lax.stop_gradient(t_logits)
        total, metrics = distillation_loss(
            s_logits, t_logits, micro["labels"], micro.get("loss_mask"),
            temperature=temperature, alpha=alpha)
        return total + aux, {**metrics, "moe_aux_loss": aux}

    return loss_fn
