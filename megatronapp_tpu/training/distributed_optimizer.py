"""ZeRO-1 distributed optimizer: dp-sharded weight update + state.

Parity with the reference DistributedOptimizer
(/root/reference/megatron/core/optimizer/distrib_optimizer.py:80): the
optimizer state — Adam moments and, for low-precision params, an fp32
master-weight copy — is sharded across data-parallel replicas, gradients
flow into the update reduce-scattered and updated params return via
all-gather, so per-rank optimizer memory scales ~1/dp and the HBM-bound
Adam update (PERF.md: ~4.3 ms/step on replicated fp32 state) touches only
a 1/dp slice per chip.

Done the XLA way (PAPERS.md: *Automatic Cross-Replica Sharding of Weight
Update in Data-Parallel Training*, arXiv 2004.13336) rather than by
hand-bucketing grads: the wrapper below is a pure optax-compatible
``GradientTransformation`` whose state LAYOUT carries the sharding —
``zero1_state_shardings`` produces a dp-sharded partition pytree for the
m/v/master leaves (a ``match_partition_rules``-style regex spec map,
SNIPPETS.md [3]), ``setup_train_state`` pins it as the state's
NamedShardings, and the jitted train step's in/out shardings then make
XLA partition the elementwise update over dp, slice the (already
dp-reduced) grads into shards, and all-gather the updated params. Two
explicit manual modes (``dist_opt_comm`` = 'ring' | 'bulk') run the same
math inside a full-manual shard_map instead, returning the updated
params through the latency-hiding ring all-gather in
``parallel/overlap.py`` (or its bulk fallback) — the A/B legs of
``tools/dist_opt_benchmark.py``.

Mixed precision (reference Float16OptimizerWithFloat16Params /
--use-precision-aware-optimizer knobs): ``exp_avg_dtype`` /
``exp_avg_sq_dtype`` store the Adam moments in bf16 while the update
math stays fp32, and ``main_params_dtype`` keeps an fp32 master-weight
shard whenever the model params are lower precision — the master is the
accumulation domain, params are its rounded image.

Arithmetic note: every stage delegates to the SAME optax transforms the
replicated chain (training/optimizer.py get_optimizer) is built from —
clip_by_global_norm, scale_by_adam / trace, add_decayed_weights,
scale_by_learning_rate — called with reconstructed inner states, so the
fp32 mode is bit-identical to the replicated baseline and the benchmark's
sharded-vs-replicated loss parity holds at 0.0.
"""

from __future__ import annotations

import re
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from megatronapp_tpu.config.parallel_config import DP_AXIS, EP_AXIS
from megatronapp_tpu.config.training_config import OptimizerConfig
from megatronapp_tpu.training.optimizer import (
    _weight_decay_mask, lr_schedule,
)

# ---------------------------------------------------------------------------
# Mixed-precision dtype knobs (--main-params-dtype / --exp-avg-dtype /
# --exp-avg-sq-dtype).
# ---------------------------------------------------------------------------

STATE_DTYPES = {
    "fp32": jnp.float32, "float32": jnp.float32,
    "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
}


def resolve_state_dtype(name: str):
    """'fp32'/'float32'/'bf16'/'bfloat16' → jnp dtype (ValueError
    otherwise — config/arguments.py validates at parse time with the
    same table)."""
    try:
        return STATE_DTYPES[str(name).lower()]
    except KeyError:
        raise ValueError(
            f"unknown optimizer-state dtype {name!r}; expected one of "
            f"{sorted(set(STATE_DTYPES))}") from None


# ---------------------------------------------------------------------------
# The opt-state partition spec map (match_partition_rules style,
# SNIPPETS.md [3]): regex over the slash-joined leaf path selects WHICH
# dim of an m/v/master leaf takes the dp shard; unmatched leaves fall back
# to the first spec-free dim that divides evenly. A rule mapping to None
# pins the leaf replicated.
# ---------------------------------------------------------------------------

# (path regex, dim index | None). Paths look like
# 'mu/block/attn_qkv_kernel' — the state-group key (mu/nu/master) leads.
ZERO1_RULES: Tuple[Tuple[str, Optional[int]], ...] = (
    # Embeddings [V|P, H]: prefer the hidden dim — the vocab dim is
    # tp-sharded ('vocab' rule) and row-contiguous hidden shards gather
    # cheapest.
    (r"embedding/", 1),
)


def _spec_entries(spec: P, ndim: int) -> list:
    entries = list(spec)
    entries += [None] * (ndim - len(entries))
    return entries


def _used_axes(entries) -> set:
    used = set()
    for e in entries:
        if e is None:
            continue
        used.update(e if isinstance(e, (tuple, list)) else (e,))
    return used


def zero1_partition_spec(path: str, spec: P, shape: Tuple[int, ...],
                         dp: int, ep: int,
                         rules=ZERO1_RULES) -> P:
    """dp-shard one optimizer-state leaf's PartitionSpec.

    Scalars / single-element leaves stay replicated (the snippet's
    "don't partition scalar values"). The chosen dim must be spec-free
    and divide evenly by the dp group — (dp, ep) jointly when the leaf
    does not already use ep (non-expert params' grads reduce over both
    batch axes), dp alone otherwise. Leaves with no eligible dim keep
    their spec (replicated update, correct just not sharded)."""
    if len(shape) == 0 or int(np.prod(shape)) == 1 or dp * ep <= 1:
        return spec
    entries = _spec_entries(spec, len(shape))
    used = _used_axes(entries)
    if DP_AXIS in used:          # already dp-sharded (fsdp rules)
        return spec
    group = [DP_AXIS]
    if EP_AXIS not in used and ep > 1:
        group.append(EP_AXIS)
    gsize = dp * (ep if len(group) > 1 else 1)

    explicit = None
    for pat, dim in rules:
        if re.search(pat, path):
            if dim is None:
                return spec
            explicit = dim
            break
    candidates = ([explicit] if explicit is not None
                  else list(range(len(shape))))
    for i in candidates:
        if i >= len(shape) or entries[i] is not None:
            continue
        if shape[i] % gsize == 0:
            entries[i] = tuple(group) if len(group) > 1 else group[0]
            return P(*entries)
        if len(group) > 1 and shape[i] % dp == 0:
            entries[i] = DP_AXIS
            return P(*entries)
    return spec


def zero1_state_shardings(opt_shardings, opt_struct, ctx,
                          rules=ZERO1_RULES):
    """Rewrite an opt-state sharding pytree so the params-like leaves
    (mu/nu/master) shard over dp. `opt_shardings` comes from the base
    logical rules (so tp/pp/ep placements are already right);
    `opt_struct` supplies the global shapes."""
    def upd(path, sh, st):
        if not isinstance(sh, NamedSharding) or not hasattr(st, "shape"):
            return sh
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        spec = zero1_partition_spec(name, sh.spec, tuple(st.shape),
                                    ctx.dp, ctx.ep, rules)
        # manual-ok: host-side layout construction (setup_train_state),
        # never traced inside a manual region.
        return NamedSharding(sh.mesh, spec)
    return jtu.tree_map_with_path(upd, opt_shardings, opt_struct)


class LeafPlan:
    """Opaque (non-pytree) per-leaf shard plan: `dim` is the leaf dim the
    dp group shards (None = leaf stays replicated), `axes` the mesh axis
    names of that group. Deliberately NOT a tuple/dataclass-pytree so a
    plan tree can ride through jax.tree.map next to an array tree."""
    __slots__ = ("dim", "axes")

    def __init__(self, dim=None, axes=()):
        self.dim, self.axes = dim, axes

    def __repr__(self):
        return f"LeafPlan(dim={self.dim}, axes={self.axes})"


def shard_plan(param_shardings, opt_shardings):
    """Per-param-leaf LeafPlan derived from the spec map: the dim index
    where the mu sharding carries a dp group the param sharding does
    not, and the mesh axes of that group. Used by the manual (ring/bulk)
    update path to slice grads/params into their dp shards."""
    mu_sh = opt_shardings["mu"]

    def leaf_plan(p_sh, m_sh):
        if not isinstance(m_sh, NamedSharding):
            return LeafPlan()
        p_entries = list(getattr(p_sh, "spec", P()) or ())
        for i, e in enumerate(m_sh.spec):
            if e is None:
                continue
            pe = p_entries[i] if i < len(p_entries) else None
            if e != pe:
                axes = tuple(e) if isinstance(e, (tuple, list)) else (e,)
                return LeafPlan(i, axes)
        return LeafPlan()

    return jax.tree.map(leaf_plan, param_shardings, mu_sh,
                        is_leaf=lambda x: isinstance(x, NamedSharding))


# ---------------------------------------------------------------------------
# The wrapper.
# ---------------------------------------------------------------------------

class DistributedOptimizer:
    """ZeRO-1 wrapper with the optax GradientTransformation interface.

    State is a plain dict — orbax-friendly, and `state_logical_axes`
    (train_state.py) maps its params-like subtrees to the params' logical
    axes unchanged:

        {"count": int32 scalar,
         "mu":    params-like (exp_avg_dtype),
         "nu":    params-like (exp_avg_sq_dtype; adam only),
         "master": params-like fp32 shard (only when params are
                   lower-precision than main_params_dtype)}

    ``update`` is a PURE transform: it contains no collectives and no
    mesh references — the dp sharding comes entirely from the state
    layout (zero1_state_shardings) pinned by the enclosing jit's in/out
    shardings, so every existing call site (train_step, the DPP runtime's
    optimizer half, FBD) works unchanged. The manual ring/bulk path lives
    in :func:`manual_apply` and is selected by the train step.
    """

    zero1 = True

    def __init__(self, cfg: OptimizerConfig, train_iters: int,
                 schedule=None, shard_state: bool = True):
        # shard_state=False keeps the wrapper's arithmetic and state
        # container but a REPLICATED layout (setup_train_state skips the
        # dp spec map) — the like-for-like baseline leg of the
        # dist_opt benchmark's bf16-moments A/B.
        self.shard_state = shard_state
        self.cfg = cfg
        self.sched = schedule or lr_schedule(cfg, train_iters)
        self.mu_dtype = resolve_state_dtype(cfg.exp_avg_dtype)
        self.nu_dtype = resolve_state_dtype(cfg.exp_avg_sq_dtype)
        self.master_dtype = resolve_state_dtype(cfg.main_params_dtype)
        if self.master_dtype != jnp.float32:
            # A low-precision "master" would ROUND the params through it
            # every step (apply_updates sets params = cast(master)) —
            # the master is the fp32 accumulation domain by contract.
            # The CLI validates this too; guard programmatic construction.
            raise ValueError(
                f"main_params_dtype={cfg.main_params_dtype!r}: only fp32 "
                "master weights are supported (the master shard is the "
                "accumulation domain; low-precision params get an fp32 "
                "master automatically)")
        self._clip = (optax.clip_by_global_norm(cfg.clip_grad)
                      if cfg.clip_grad else None)
        if cfg.optimizer == "adam":
            self._inner = optax.scale_by_adam(
                b1=cfg.adam_beta1, b2=cfg.adam_beta2, eps=cfg.adam_eps,
                mu_dtype=self.mu_dtype)
        elif cfg.optimizer == "sgd":
            self._inner = optax.trace(decay=cfg.sgd_momentum)
        else:
            raise ValueError(f"unknown optimizer {cfg.optimizer}")
        self._adw = (optax.add_decayed_weights(
            cfg.weight_decay, mask=_weight_decay_mask)
            if (cfg.optimizer == "adam" and cfg.weight_decay) else None)
        self._lr = optax.scale_by_learning_rate(self.sched)

    # -- optax interface ----------------------------------------------------
    def init(self, params) -> dict:
        state = {"count": jnp.zeros((), jnp.int32)}
        if self.cfg.optimizer == "adam":
            inner = self._inner.init(params)
            state["mu"] = inner.mu
            state["nu"] = jax.tree.map(
                lambda v: v.astype(self.nu_dtype), inner.nu)
        else:
            # SGD momentum honors exp_avg_dtype like Adam's first moment
            # (the config must never claim a precision the state lacks).
            state["mu"] = jax.tree.map(
                lambda t: t.astype(self.mu_dtype),
                self._inner.init(params).trace)
        if self._wants_master(params):
            state["master"] = jax.tree.map(
                lambda p: p.astype(self.master_dtype), params)
        return state

    def update(self, grads, state, params=None):
        u = self._clip_stage(grads)
        return self._shard_stage(u, state, params)

    # -- stages (shared by the GSPMD and manual paths) ----------------------
    def _wants_master(self, params) -> bool:
        """Keep a master copy only when it would differ from the params
        themselves (fp32 params + fp32 main_params_dtype needs none —
        params ARE the accumulation domain)."""
        return any(l.dtype != self.master_dtype
                   for l in jax.tree.leaves(params))

    def _clip_stage(self, grads):
        """Global-norm clip on the FULL grad tree. Runs outside the
        sharded domain: the norm is global, and grads arrive dp-replicated
        (already dp-reduced by the backward's psum) so the replicated
        compute costs what the baseline chain paid."""
        if self._clip is None:
            return grads
        u, _ = self._clip.update(grads, optax.EmptyState())
        return u

    def _shard_stage(self, u, state, params):
        """Moments + decay + lr + master accumulate — elementwise per
        leaf, so the same code runs on full arrays (GSPMD partitions it
        along the state shardings) and on explicit shards (manual_apply).
        Returns (updates, new_state); updates are in the master domain
        (fp32) when a master shard exists."""
        p_ref = state.get("master", params)
        new = {}
        if self.cfg.optimizer == "adam":
            inner_state = optax.ScaleByAdamState(
                count=state["count"], mu=state["mu"], nu=state["nu"])
            u, new_inner = self._inner.update(u, inner_state)
            new["count"] = new_inner.count
            new["mu"] = new_inner.mu
            new["nu"] = jax.tree.map(
                lambda v: v.astype(self.nu_dtype), new_inner.nu)
            if self._adw is not None:
                u, _ = self._adw.update(u, self._adw.init(p_ref), p_ref)
        else:
            u, new_inner = self._inner.update(
                u, optax.TraceState(trace=state["mu"]))
            new["count"] = optax.safe_int32_increment(state["count"])
            new["mu"] = jax.tree.map(
                lambda t: t.astype(self.mu_dtype), new_inner.trace)
        u, _ = self._lr.update(
            u, optax.ScaleByScheduleState(count=state["count"]))
        if "master" in state:
            new["master"] = jax.tree.map(
                lambda m, du: m + du.astype(m.dtype), state["master"], u)
        return u, new

    def apply_updates(self, params, updates, new_state):
        """params ← updates, master-aware: with a master shard the new
        params are the ROUNDED IMAGE of the fp32 master (params never
        accumulate in low precision); otherwise the standard p + u."""
        if "master" in new_state:
            return jax.tree.map(
                lambda p, m: m.astype(p.dtype), params,
                new_state["master"])
        return jax.tree.map(lambda p, du: p + du.astype(p.dtype),
                            params, updates)


def get_distributed_optimizer(cfg: OptimizerConfig, train_iters: int,
                              schedule=None) -> DistributedOptimizer:
    return DistributedOptimizer(cfg, train_iters, schedule=schedule)


# ---------------------------------------------------------------------------
# Manual (ring / bulk) update path: the same math inside one FULL-MANUAL
# shard_map, with the param return through parallel/overlap.py rings.
# ---------------------------------------------------------------------------

def _shard_index(axes: Tuple[str, ...]):
    """Linearized rank over a dp group, axis-major in group order —
    matches both the lax.all_gather concat order and the spec map's
    (dp, ep) grouping."""
    from jax import lax
    from megatronapp_tpu.parallel.collectives import axis_size
    idx = lax.axis_index(axes[0])
    for a in axes[1:]:
        idx = idx * axis_size(a) + lax.axis_index(a)
    return idx


def _slice_leaf(x, plan: LeafPlan):
    from jax import lax
    from megatronapp_tpu.parallel.collectives import axis_size
    if plan.dim is None:
        return x
    n = 1
    for a in plan.axes:
        n *= axis_size(a)
    chunk = x.shape[plan.dim] // n
    return lax.dynamic_slice_in_dim(
        x, _shard_index(plan.axes) * chunk, chunk, axis=plan.dim)


def _gather_leaf(x, plan: LeafPlan, overlap: bool):
    """Return a rank's updated param shard to every dp rank: the ring
    all-gather (overlap.py) over a single-axis group, the tiled bulk
    gather otherwise (ppermute cannot ring over a joint (dp, ep) group)."""
    from jax import lax
    from megatronapp_tpu.parallel.collectives import axis_size
    from megatronapp_tpu.parallel.overlap import ring_all_gather
    if plan.dim is None:
        return x
    dim, axes = plan.dim, plan.axes
    if overlap and len(axes) == 1:
        return ring_all_gather(x, axes[0], axis_size(axes[0]), axis=dim,
                               op_name="zero1-allgather")
    return lax.all_gather(x, axes if len(axes) > 1 else axes[0],
                          axis=dim, tiled=True)


def manual_apply(optimizer: DistributedOptimizer, grads, opt_state,
                 params, state_shardings, mesh, plan, overlap=True):
    """The ZeRO-1 weight update as one full-manual shard_map.

    Grads arrive dp-REPLICATED and already dp-reduced (the enclosing
    step's backward psums them — XLA owns that collective), so the
    reduce-scatter leg degenerates to a static shard slice; the comm this
    path owns is the param RETURN, where each rank updates only its 1/dp
    shard and the new params travel back through the latency-hiding ring
    all-gather (``overlap=True``) or the bulk tiled gather
    (``overlap=False``, the A/B baseline). m/v/master shards stay
    resident — they are never gathered.

    Returns (new_params, new_opt_state) with layouts identical to the
    GSPMD path, so the lax.cond NaN-skip and the donated state buffers
    are mode-agnostic.
    """
    from megatronapp_tpu.parallel.collectives import shard_map_compat

    # Clip needs the GLOBAL grad norm — run it on the full (replicated)
    # grads before the sharded domain, exactly where the GSPMD path and
    # the replicated baseline run it.
    grads = optimizer._clip_stage(grads)

    spec_of = lambda tree: jax.tree.map(  # noqa: E731
        lambda s: s.spec, tree,
        is_leaf=lambda x: isinstance(x, NamedSharding))
    param_specs = spec_of(state_shardings["params"])
    opt_specs = spec_of(state_shardings["opt_state"])

    def body(grads, opt_state, params):
        g = jax.tree.map(_slice_leaf, grads, plan)
        p = jax.tree.map(_slice_leaf, params, plan)
        u, new_state = optimizer._shard_stage(g, opt_state, p)
        if "master" in new_state:
            new_p = jax.tree.map(lambda pl, m: m.astype(pl.dtype), p,
                                 new_state["master"])
        else:
            new_p = jax.tree.map(lambda pl, du: pl + du.astype(pl.dtype),
                                 p, u)
        new_p = jax.tree.map(
            lambda x, pl: _gather_leaf(x, pl, overlap), new_p, plan)
        return new_p, new_state

    # manual-ok: REGION-CREATING call at the train step's top level —
    # train_step invokes manual_apply outside any manual region (the
    # pipeline loss's shard_map has already closed), so this is never a
    # nested shard_map.
    return shard_map_compat(
        body, mesh,
        in_specs=(param_specs, opt_specs, param_specs),
        out_specs=(param_specs, opt_specs))(grads, opt_state, params)
