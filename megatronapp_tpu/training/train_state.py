"""Train state container + sharded initialization.

The state pytree is {'step', 'params', 'opt_state'}; optimizer state leaves
inherit the corresponding parameter's sharding. ZeRO-1 semantics of the
reference DistributedOptimizer (/root/reference/megatron/core/optimizer/
distrib_optimizer.py:80) fall out of the rules: with
ParallelConfig.distributed_optimizer the 'embed' axis of params and adam
moments is sharded over dp — "shard optimizer state over DP" with XLA doing
the reduce-scatter/all-gather the reference implements by hand
(distrib_optimizer.py grad reduce-scatter + param all-gather).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

from megatronapp_tpu.parallel.mesh import MeshContext
from megatronapp_tpu.parallel.sharding import (
    DEFAULT_RULES, FSDP_RULES, tree_logical_to_sharding,
)


from megatronapp_tpu.parallel.sharding import is_logical_axes as _is_axes


def _param_like(leaf, params_axes) -> bool:
    """True if `leaf` is a pytree with the same structure as params."""
    try:
        return (jax.tree.structure(leaf) ==
                jax.tree.structure(params_axes, is_leaf=_is_axes))
    except Exception:
        return False


def state_logical_axes(params_axes, opt_state_struct) -> Any:
    """Logical-axes pytree matching {'step','params','opt_state'}: optimizer
    substates shaped like params reuse the params axes; scalars get ()."""
    opt_axes = jax.tree.map(
        lambda node: params_axes if _param_like(node, params_axes) else (),
        opt_state_struct,
        is_leaf=lambda n: _param_like(n, params_axes) or not isinstance(
            n, (tuple, list, dict)) or not jax.tree.leaves(n),
    )
    return {"step": (), "params": params_axes, "opt_state": opt_axes}


def pick_rules(ctx: MeshContext):
    return (FSDP_RULES if (ctx.parallel.fsdp or
                           ctx.parallel.distributed_optimizer)
            else DEFAULT_RULES)


def setup_train_state(rng, params_and_axes_fn: Callable, optimizer,
                      ctx: MeshContext, rules=None) -> Tuple[Any, Any, Any]:
    """jit-init the full state directly into its shardings (params never
    materialize unsharded — parity with the reference's per-rank init).

    params_and_axes_fn(rng) -> (params, logical_axes). Returns
    (state, state_shardings, params_axes).
    """
    rules = rules or pick_rules(ctx)
    # Logical axes are config-static python data; capture them during an
    # abstract trace (no device arrays are materialized).
    captured = {}

    def _shapes_only(rng):
        params, axes = params_and_axes_fn(rng)
        captured["axes"] = axes
        return params

    jax.eval_shape(_shapes_only, rng)
    params_axes = captured["axes"]

    def _init(rng):
        params, _ = params_and_axes_fn(rng)
        opt_state = optimizer.init(params)
        return {"step": jnp.zeros((), jnp.int32), "params": params,
                "opt_state": opt_state}

    state_struct = jax.eval_shape(_init, rng)
    axes = state_logical_axes(params_axes, state_struct["opt_state"])
    shardings = tree_logical_to_sharding(axes, ctx.mesh, rules)
    with ctx.mesh:
        state = jax.jit(_init, out_shardings=shardings)(rng)
    return state, shardings, params_axes
