"""Train state container + sharded initialization.

The state pytree is {'step', 'params', 'opt_state'}; optimizer state leaves
inherit the corresponding parameter's sharding as a BASE layout. ZeRO-1
semantics of the reference DistributedOptimizer
(/root/reference/megatron/core/optimizer/distrib_optimizer.py:80) come from
the DistributedOptimizer wrapper (training/distributed_optimizer.py): params
stay dp-replicated while the m/v/master state leaves get an extra dp shard
dim from its regex spec map — XLA then emits the grad reduce-scatter /
param all-gather the reference implements by hand. FSDP_RULES remain the
param-sharding variant ('embed' over dp for params AND state).
"""

from __future__ import annotations

import warnings
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

from megatronapp_tpu.parallel.mesh import MeshContext
from megatronapp_tpu.parallel.sharding import (
    DEFAULT_RULES, FSDP_RULES, tree_logical_to_sharding,
)


from megatronapp_tpu.parallel.sharding import is_logical_axes as _is_axes


def _param_like(leaf, params_axes) -> bool:
    """True if `leaf` is a pytree with the same structure as params."""
    try:
        return (jax.tree.structure(leaf) ==
                jax.tree.structure(params_axes, is_leaf=_is_axes))
    except Exception:
        return False


def state_logical_axes(params_axes, opt_state_struct) -> Any:
    """Logical-axes pytree matching {'step','params','opt_state'}: optimizer
    substates shaped like params reuse the params axes; scalars get ()."""
    opt_axes = jax.tree.map(
        lambda node: params_axes if _param_like(node, params_axes) else (),
        opt_state_struct,
        is_leaf=lambda n: _param_like(n, params_axes) or not isinstance(
            n, (tuple, list, dict)) or not jax.tree.leaves(n),
    )
    return {"step": (), "params": params_axes, "opt_state": opt_axes}


def pick_rules(ctx: MeshContext, optimizer=None):
    """Param sharding rules for the run.

    fsdp: FSDP_RULES — params (and state) shard their 'embed' axis over
    dp. ZeRO-1 (a DistributedOptimizer instance): params stay replicated
    over dp (DEFAULT_RULES) and only the optimizer STATE shards — the
    dp placement comes from zero1_state_shardings below, not the logical
    rules. Legacy: ParallelConfig.distributed_optimizer with a plain
    optax chain keeps the old FSDP_RULES interpretation so direct
    setup_train_state callers that never wire the wrapper (FBD, tools,
    model families) behave exactly as before."""
    if ctx.parallel.fsdp:
        return FSDP_RULES
    if getattr(optimizer, "zero1", False):
        return DEFAULT_RULES
    return (FSDP_RULES if ctx.parallel.distributed_optimizer
            else DEFAULT_RULES)


def setup_train_state(rng, params_and_axes_fn: Callable, optimizer,
                      ctx: MeshContext, rules=None,
                      sharded_init: bool = False,
                      fp8_state=None) -> Tuple[Any, Any, Any]:
    """Initialize the full train state into its shardings.

    params_and_axes_fn(rng) -> (params, logical_axes). Returns
    (state, state_shardings, params_axes).

    fp8_state (ISSUE 13, training/fp8.init_fp8_state): when given, the
    delayed-scaling amax histories join the state pytree under "fp8"
    (replicated — a few KB of fp32) so checkpoint save/restore and
    resharding carry them with everything else and resume is bitwise.

    sharded_init=False (default): two-stage init — jit with fully
    REPLICATED out_shardings (every device runs the identical init
    program, so seeded values are provably mesh-independent), then a
    jitted identity resharding into the target shardings. Root cause
    (cp×pp parity work): with sharded out_shardings, GSPMD partitions the
    stacked threefry draws of the layer-stack init, and on this jax
    0.4.x/XLA:CPU build the cp×pp mesh then produced DIFFERENT param
    values than a single device (~0.09 max leaf diff, the cp2×pp2
    train-loss drift) while every other tested mesh matched. Both stages
    are computation-based (no host transfers), so multi-process meshes
    work unchanged.

    sharded_init=True: the old direct-to-shards init (params never
    materialize unsharded — the reference's per-rank init analogue) for
    memory-constrained giant-model runs; values are then only guaranteed
    mesh-independent on meshes validated by the init-parity tests.
    """
    rules = rules or pick_rules(ctx, optimizer)
    # Logical axes are config-static python data; capture them during an
    # abstract trace (no device arrays are materialized).
    captured = {}

    def _shapes_only(rng):
        params, axes = params_and_axes_fn(rng)
        captured["axes"] = axes
        return params

    jax.eval_shape(_shapes_only, rng)
    params_axes = captured["axes"]

    def _init(rng):
        params, _ = params_and_axes_fn(rng)
        opt_state = optimizer.init(params)
        state = {"step": jnp.zeros((), jnp.int32), "params": params,
                 "opt_state": opt_state}
        if fp8_state is not None:
            state["fp8"] = jax.tree.map(jnp.asarray, fp8_state)
        return state

    state_struct = jax.eval_shape(_init, rng)
    axes = state_logical_axes(params_axes, state_struct["opt_state"])
    shardings = tree_logical_to_sharding(axes, ctx.mesh, rules)
    if fp8_state is not None:
        from jax.sharding import NamedSharding, PartitionSpec
        shardings["fp8"] = jax.tree.map(
            lambda _: NamedSharding(ctx.mesh, PartitionSpec()),
            fp8_state)
    if getattr(optimizer, "zero1", False) and \
            getattr(optimizer, "shard_state", True):
        # ZeRO-1: the m/v/master leaves additionally shard over the dp
        # group — the regex spec map owns the per-leaf dim choice
        # (training/distributed_optimizer.py). Params keep their
        # dp-replicated rules; the jitted step's in/out shardings then
        # make XLA slice grads into the update and all-gather the
        # updated params (arXiv 2004.13336 semantics).
        from megatronapp_tpu.training.distributed_optimizer import (
            zero1_state_shardings,
        )
        shardings["opt_state"] = zero1_state_shardings(
            shardings["opt_state"], state_struct["opt_state"], ctx)
    with ctx.mesh:
        if sharded_init:
            state = jax.jit(_init, out_shardings=shardings)(rng)
        else:
            from jax.sharding import NamedSharding, PartitionSpec
            rep = jax.tree.map(
                lambda _: NamedSharding(ctx.mesh, PartitionSpec()),
                shardings)
            state = jax.jit(_init, out_shardings=rep)(rng)
            # Donate the replicated copy so backends with donation
            # support free its buffers as the reshard consumes them
            # (peak init memory ~1x sharded state instead of
            # replicated + sharded). CPU lacks donation and warns;
            # expected, so silence just that warning.
            with warnings.catch_warnings():
                warnings.filterwarnings(
                    "ignore",
                    message="Some donated buffers were not usable")
                state = jax.jit(lambda s: s, out_shardings=shardings,
                                donate_argnums=0)(state)
    return state, shardings, params_axes
