"""fp8 (e4m3) training with delayed-scaling amax history (ISSUE 13).

The tp-overlap ring matmuls (parallel/overlap.py ``_ag_mm``/``_mm_rs``)
own every training GEMM call site under ``--tp-comm-overlap``; this
module owns the DELAYED-SCALING machinery around their fp8 variants:

- **State.** One fp32 amax history row per (layer, site, tensor):
  ``hist [n_tensors, H]`` where slot 0 is the most recent step's amax
  and H = ``cfg.fp8_amax_history_len`` — stacked over layers exactly
  like the block params so it rides the same ``lax.scan``. Per-site
  ``sat [n_tensors]`` carries the step's count of saturated elements
  (the overflow observability satellite). Sites per layer:
  attention ``qkv`` (x + 2 weights + 2 cotangents = 5 tensors),
  attention ``out`` / mlp ``fc1`` / mlp ``fc2`` (3 each: input, weight,
  cotangent).

- **Scales.** Derived from the history at every use —
  ``scale = FP8_MAX / (max(hist) * 2**margin)`` (TE-style delayed
  scaling; 1.0 while the history is empty) — so there is no separate
  scale leaf whose update order could drift from the history's; the
  documented "current scale" in /metrics is this same derivation.

- **Transport.** The new history never touches the optimizer: the fp8
  ring custom_vjps define the COTANGENT of the hist input to BE the
  rolled history with the step's observed amaxes in slot 0 (forward
  tensors observed in fwd, the cotangent tensor in bwd). The train step
  differentiates the (params, fp8_state) pair, accumulates the fp8
  half with elementwise max across microbatches (each microbatch rolls
  the SAME old history, so max combines exactly the amax slots), and
  installs ``state["fp8"] = fp8_grads`` directly. Because the state is
  a first-class member of the train-state pytree it checkpoints,
  restores, and reshards with everything else — resume is bitwise.

Scope: the fp8 path lives where the rings live — tp > 1 with
``--tp-comm-overlap`` on, pp == 1 (the ambient-manual tp-sharded stage
rings keep bf16), dense non-MLA/non-MoE/non-hetero layers.
``fp8_ineligible_reason`` names the first failed predicate (the house
loud-fallback contract).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from megatronapp_tpu.utils import metrics as telemetry

FP8_DTYPE = jnp.float8_e4m3fn
FP8_MAX = 448.0          # e4m3fn finfo max (overflow is NaN, not inf)

# Per-layer fp8 sites and their tensor counts — index order inside each
# site's hist/sat rows: [input, weight_0..weight_{n-1}, grad_0..grad_{n-1}]
# for the all-gather-matmul sites (fused QKV has two weights) and
# [input, weight, grad] for the matmul-reduce-scatter sites.
SITE_TENSORS = {
    ("attention", "qkv"): 5,
    ("attention", "out"): 3,
    ("mlp", "fc1"): 3,
    ("mlp", "fc2"): 3,
}


def fp8_scale_from_hist(hist: jnp.ndarray, margin: int) -> jnp.ndarray:
    """Delayed scale per tensor: hist [..., H] → scale [...]
    (FP8_MAX / (amax * 2**margin); 1.0 while the history is empty)."""
    amax = jnp.max(hist, axis=-1)
    return jnp.where(amax > 0.0,
                     FP8_MAX / (amax * (2.0 ** margin)),
                     jnp.ones_like(amax))


def fp8_quantize(x: jnp.ndarray, scale) -> tuple:
    """Saturating e4m3 cast of ``x * scale``.

    Returns (x_fp8, amax fp32 scalar, saturated-element count fp32
    scalar). The clip is load-bearing: e4m3fn overflows to NaN."""
    x32 = x.astype(jnp.float32) * scale
    amax = jnp.max(jnp.abs(x)).astype(jnp.float32)
    sat = jnp.sum(jnp.abs(x32) > FP8_MAX).astype(jnp.float32)
    q = jnp.clip(x32, -FP8_MAX, FP8_MAX).astype(FP8_DTYPE)
    return q, amax, sat


def rolled_hist(hist: jnp.ndarray, amaxes: jnp.ndarray) -> jnp.ndarray:
    """New delayed-scaling history: shift every row right by one and
    install this step's observed amaxes in slot 0.

    hist [n, H], amaxes [n] → [n, H]."""
    return jnp.concatenate(
        [amaxes[:, None], hist[:, :-1]], axis=1)


def _site(num_layers: int, n_tensors: int, hist_len: int) -> Dict:
    return {
        "hist": jnp.zeros((num_layers, n_tensors, hist_len), jnp.float32),
        "sat": jnp.zeros((num_layers, n_tensors), jnp.float32),
    }


def init_fp8_state(cfg) -> Dict:
    """The per-run fp8 state pytree (threaded through train_state /
    checkpointing): amax histories + per-step saturation counts for
    every (layer, site, tensor), stacked over layers for the block
    scan."""
    l = cfg.num_layers
    h = int(getattr(cfg, "fp8_amax_history_len", 16))
    out: Dict = {"block": {}}
    for (mod, site), n in SITE_TENSORS.items():
        out["block"].setdefault(mod, {})[site] = _site(l, n, h)
    return out


def fp8_ineligible_reason(cfg, parallel) -> Optional[str]:
    """Why --fp8 may NOT run — None when eligible, otherwise the FIRST
    failed predicate by name (tp_paged_ineligible_reason contract).
    Checked at parse time (config/arguments.py) AND at train wiring."""
    if not getattr(cfg, "fp8", False):
        return "cfg.fp8 off"
    if not getattr(cfg, "tp_comm_overlap", False):
        return ("--fp8 requires --tp-comm-overlap: the fp8 GEMMs live "
                "inside the ring all-gather / reduce-scatter matmul "
                "bodies (parallel/overlap.py)")
    tp = getattr(parallel, "tensor_parallel", 1)
    if tp <= 1:
        return (f"--fp8 requires --tensor-model-parallel-size > 1 "
                f"(got {tp}): with tp == 1 no ring matmul ever runs, "
                "so fp8 would silently be a no-op")
    if getattr(parallel, "pipeline_parallel", 1) > 1:
        return ("--fp8 does not support pipeline parallelism yet: the "
                "ambient-manual tp-sharded stage rings keep bf16 "
                "(amax state threading through the pp scan is the "
                "recorded follow-up)")
    if getattr(parallel, "context_parallel", 1) > 1:
        return ("--fp8 requires context_parallel == 1 (the GSPMD "
                "overlap rings are cp==1-only — tp_overlap_eligible)")
    if cfg.is_moe:
        return ("--fp8 does not support MoE layers: expert GEMMs "
                "dispatch outside the tp rings")
    if cfg.multi_latent_attention:
        return ("--fp8 does not support MLA: the dense MLA projections "
                "only ring inside the pp stage body, which keeps bf16")
    if getattr(cfg, "hetero_block_specs", None):
        return "--fp8 does not support heterogeneous per-layer configs"
    if cfg.mtp_num_layers:
        return ("--fp8 does not support MTP depth modules yet (their "
                "layer bodies run outside the fp8-threaded block scan)")
    if getattr(parallel, "forward_backward_disaggregating", False):
        return ("--fp8 is not supported with "
                "--forward-backward-disaggregating (the FBD executor "
                "path does not thread the fp8 state)")
    if getattr(parallel, "use_dpp", False):
        return ("--fp8 is not wired into the host-driven DPP runtime "
                "(--use-dpp)")
    return None


# ---------------------------------------------------------------------------
# Train-step integration helpers
# ---------------------------------------------------------------------------


def fp8_zeros_like(fp8_state):
    return jax.tree.map(jnp.zeros_like, fp8_state)


def fp8_accumulate(acc, new):
    """Combine two microbatches' fp8 observations: histories combine
    with elementwise max (both are roll(old) with the microbatch amax in
    slot 0, so max keeps the rolled tail and takes the larger amax);
    saturation counts ADD (each microbatch counts its own elements)."""
    def comb(path, a, b):
        if path[-1].key == "sat":
            return a + b
        return jnp.maximum(a, b)
    return jax.tree_util.tree_map_with_path(comb, acc, new)


def fp8_carry_sat(old_state, new_obs):
    """Promote the step's saturation observations to CUMULATIVE totals:
    the state's sat leaves count every saturated element since step 0
    (they checkpoint with the histories), while the hist leaves take the
    step's rolled value as-is. Applied once per step in train_step,
    where both the old state and the step's observations are in hand."""
    def comb(path, old, new):
        if path[-1].key == "sat":
            return old + new
        return new
    return jax.tree_util.tree_map_with_path(comb, old_state, new_obs)


def export_fp8_metrics(fp8_state, cfg):
    """Host-side /metrics export (ISSUE 13 satellite): per-site current
    scale + worst amax gauges (aggregated over layers/tensors — scale
    drift is a per-site signal), the history depth, and the CUMULATIVE
    saturation totals (the state's sat leaves accumulate every step via
    fp8_carry_sat, so a gauge set at log time is exact regardless of
    log_interval). One device_get per logged step, all math in numpy on
    the fetched host arrays; callers gate on telemetry.enabled()."""
    import numpy as np
    if not telemetry.enabled():
        return
    margin = int(getattr(cfg, "fp8_margin", 0))
    telemetry.set_gauge("fp8_amax_history_len",
                        int(getattr(cfg, "fp8_amax_history_len", 16)))
    host = jax.device_get(fp8_state)
    for mod, sites in host["block"].items():
        for site, leaves in sites.items():
            hist = np.asarray(leaves["hist"])        # [L, n, H]
            amax = np.max(hist, axis=-1)             # [L, n]
            scale = np.where(amax > 0.0,
                             FP8_MAX / np.maximum(amax, 1e-30)
                             / (2.0 ** margin), 1.0)
            telemetry.set_gauge(f"fp8_amax_{mod}_{site}",
                                float(hist.max()))
            telemetry.set_gauge(f"fp8_scale_{mod}_{site}",
                                float(scale.min()))
            telemetry.set_gauge(f"fp8_saturated_{mod}_{site}",
                                float(np.sum(np.asarray(leaves["sat"]))))
