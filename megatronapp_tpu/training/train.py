"""Training driver — the ``pretrain()`` analogue.

Parity with /root/reference/megatron/training/training.py:894 (pretrain) /
:668 (pretrain_body) / :1967 (train loop) / :1488 (training_log): mesh+state
setup, microbatched train loop, throughput/loss logging, checkpoint
save/resume, MegaScan tracing hooks, NaN-skip accounting.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from megatronapp_tpu.config.parallel_config import ParallelConfig
from megatronapp_tpu.config.training_config import (
    OptimizerConfig, TrainingConfig,
)
from megatronapp_tpu.config.transformer_config import TransformerConfig
from megatronapp_tpu.data.mock import mock_batches
from megatronapp_tpu.models.gpt import (
    gpt_loss, gpt_pipeline_loss, init_gpt_params,
)
from megatronapp_tpu.parallel.mesh import MeshContext, build_mesh
from megatronapp_tpu.training.checkpointing import (
    CheckpointManager, LocalCheckpointManager, read_side_state,
    write_side_state,
)
from megatronapp_tpu.training.optimizer import get_optimizer
from megatronapp_tpu.training.train_state import setup_train_state
from megatronapp_tpu.training.train_step import (
    globalize_batch, make_train_step,
)
from megatronapp_tpu.trace.tracer import get_tracer
from megatronapp_tpu.utils import metrics as telemetry
from megatronapp_tpu.utils.flops import flops_per_token


@dataclasses.dataclass
class TrainResult:
    state: Any
    losses: list
    tokens_per_sec: float
    step_time_ms: float
    # Graceful signal exit fired (SIGTERM drained via emergency save).
    interrupted: bool = False
    # Data-stream position at exit (samples consumed incl. any resume).
    consumed_samples: int = 0


@contextlib.contextmanager
def _signal_exit_context(train_cfg: TrainingConfig, log_fn):
    """Install the graceful-exit signal handler for the duration of the
    train loop (--exit-signal-handler). Python restricts signal.signal
    to the main thread — off-main callers (e.g. a driver thread in
    tests) run without it rather than crashing."""
    if not train_cfg.exit_signal_handler:
        yield None
        return
    if threading.current_thread() is not threading.main_thread():
        log_fn("signals: --exit-signal-handler requires the main "
               "thread; running without a signal handler")
        yield None
        return
    from megatronapp_tpu.training.signals import DistSignalHandler
    with DistSignalHandler.for_config(
            sigint=train_cfg.exit_signal_handler_sigint) as handler:
        yield handler


def _emergency_side_state(step: int, consumed: int, rerun
                          ) -> Dict[str, Any]:
    """Resumable host-side bookkeeping persisted with every checkpoint:
    `consumed` is the exact data-stream position (the _RowBuffer's
    carry-over rows were fetched but NOT consumed, so recreating the
    stream at `consumed` via batch_iter_factory replays them — no
    samples dropped or double-consumed); `rerun` pins the fault-
    classification statistics (EMA, step/injection counters)."""
    return {"step": int(step), "consumed": int(consumed),
            "rerun": rerun.state_dict()}


class _CheckpointScribe:
    """ONE home for the train loop's four checkpoint moments (ROADMAP
    cleanup item): interval durable, interval local, emergency (signal
    exit), and final. Every path shares the same plumbing — the heartbeat
    'checkpointing' section bracketing, device_get + layout on the
    durable save, the side-state payload (exact stream position incl.
    _RowBuffer carry-over + rerun statistics), and best-effort semantics
    for the local copy — so threading new state layouts (the dp-sharded
    ZeRO-1 optimizer state) through checkpointing touches one place."""

    def __init__(self, ckpt, local_ckpt, train_cfg: TrainingConfig,
                 layout, ft, rerun, log_fn):
        self.ckpt = ckpt
        self.local_ckpt = local_ckpt
        self.cfg = train_cfg
        self.layout = layout
        self.ft = ft
        self.rerun = rerun
        self.log_fn = log_fn

    @contextlib.contextmanager
    def section(self):
        """Bracket a save in the heartbeat 'checkpointing' section so the
        watchdog applies the checkpoint timeout, then return to 'step'."""
        if self.ft is not None:
            self.ft.start_section("checkpointing")
        try:
            yield
        finally:
            if self.ft is not None:
                self.ft.start_section("step")

    def _side(self, step: int, consumed: int) -> Dict[str, Any]:
        return _emergency_side_state(step, consumed, self.rerun)

    def save_durable(self, step: int, state, consumed: int,
                     force: bool = False,
                     skip_if_current: bool = False) -> None:
        """Durable Orbax save + side-state sidecar. skip_if_current: a
        step already on disk is left alone — orbax rewrites same-step
        saves by delete-then-write, which inside a preemption grace
        window would drop the just-written good checkpoint. The side
        state is (re)written either way: it is an atomic sidecar."""
        if self.ckpt is None:
            return
        if not (skip_if_current and self.ckpt.latest_step == step):
            self.ckpt.save(step, jax.device_get(state), force=force,
                           layout=self.layout)
        write_side_state(self.cfg.save_dir, step,
                         self._side(step, consumed))

    def save_local(self, step: int, state, consumed: int,
                   what: str = "local checkpoint") -> None:
        """Best-effort local .npz with the side state riding as extra —
        warn-and-continue on failure (local checkpoints are an
        optimization, never worth killing the run)."""
        if self.local_ckpt is None:
            return
        try:
            self.local_ckpt.save(step, jax.device_get(state),
                                 extra=self._side(step, consumed))
        except Exception as e:  # noqa: BLE001 — best-effort path
            self.log_fn(f"{what} save failed at step {step} "
                        f"({type(e).__name__}: {e}); continuing — "
                        "local checkpoints are best-effort")


def reshape_global_batch(batch: Dict[str, np.ndarray], num_micro: int
                         ) -> Dict[str, np.ndarray]:
    """[global_batch, seq] → [num_micro, global_batch/num_micro, seq]."""
    def r(x):
        gb = x.shape[0]
        return x.reshape(num_micro, gb // num_micro, *x.shape[1:])
    return {k: r(v) for k, v in batch.items()}


def _validate_schedule_stages(batch_calc, pp: int, vpp: int,
                              order_policy: str) -> None:
    """Fail at startup (not hours into a ramp) when any batch-size stage
    produces a microbatch count the interleaved pipeline can't schedule
    (spmd_pipeline requires M % pp == 0 for vpp>1 'dfc')."""
    if pp > 1 and vpp > 1 and order_policy == "dfc":
        for gbs_i, m_i in batch_calc.stages():
            if m_i % pp:
                raise ValueError(
                    f"batch size {gbs_i} in the schedule gives {m_i} "
                    f"microbatches, not divisible by pipeline_parallel="
                    f"{pp} as the interleaved (dfc) pipeline requires; "
                    "adjust the rampup schedule or use order_policy "
                    "'bfc'")


class _RowBuffer:
    """Takes exactly-n sample rows from a fixed-size batch stream without
    dropping any (batch-size rampup consumes fewer rows than the stream's
    batch size; leftovers carry into the next step so consumed-samples
    bookkeeping matches the stream position exactly)."""

    def __init__(self, batch_iter):
        self._iter = batch_iter
        self._buf: Optional[Dict[str, np.ndarray]] = None

    def take(self, n: int) -> Dict[str, np.ndarray]:
        while self._buf is None or                 next(iter(self._buf.values())).shape[0] < n:
            nxt = next(self._iter)
            if self._buf is None:
                self._buf = dict(nxt)
            else:
                self._buf = {k: np.concatenate([self._buf[k], nxt[k]])
                             for k in self._buf}
        out = {k: v[:n] for k, v in self._buf.items()}
        rest = {k: v[n:] for k, v in self._buf.items()}
        self._buf = (rest if next(iter(rest.values())).shape[0] else None)
        return out


def gpt_microbatch_loss(cfg: TransformerConfig, ctx=None):
    def loss_fn(params, micro, fp8=None):
        loss, metrics = gpt_loss(params, micro["tokens"], micro["labels"],
                                 micro["loss_mask"], cfg, ctx=ctx,
                                 segment_ids=micro.get("segment_ids"),
                                 fp8=fp8)
        return loss, metrics
    return loss_fn


def pretrain_gpt(
    model_cfg: TransformerConfig,
    parallel_cfg: ParallelConfig,
    train_cfg: TrainingConfig,
    opt_cfg: OptimizerConfig,
    batch_iter: Optional[Iterator[Dict[str, np.ndarray]]] = None,
    ctx: Optional[MeshContext] = None,
    log_fn: Callable[[str], None] = print,
    batch_iter_factory: Optional[Callable] = None,
    eval_batch_iter: Optional[Iterator[Dict[str, np.ndarray]]] = None,
) -> TrainResult:
    """End-to-end GPT pretraining loop. Returns final state + stats."""
    # fp8 delayed-scaling training (ISSUE 13, --fp8): reject ineligible
    # layouts HERE too (programmatic callers bypass the parse-time
    # check; fp8_ineligible_reason covers the FBD/DPP exclusions) —
    # checked before the FBD early-return so a silent no-op fp8 run is
    # impossible on any path.
    fp8_on = bool(getattr(model_cfg, "fp8", False))
    if fp8_on:
        from megatronapp_tpu.training.fp8 import fp8_ineligible_reason
        reason = fp8_ineligible_reason(model_cfg, parallel_cfg)
        if reason is not None:
            raise ValueError(reason)

    if parallel_cfg.forward_backward_disaggregating:
        # The FBD executor runs its own legacy schedule — a non-default
        # schedule program or the planner would be silently ignored,
        # which is worse than an error (same policy as the --use-dpp
        # parse-time check; this covers programmatic callers too).
        if getattr(parallel_cfg, "pp_schedule", "1f1b") != "1f1b" or \
                getattr(parallel_cfg, "pp_plan_from_trace", False):
            raise ValueError(
                "--pp-schedule/--pp-plan-from-trace do not compose "
                "with forward_backward_disaggregating (the FBD "
                "executor runs its own schedule); drop one")
        # The FBD executor path has no resilience wiring yet (ROADMAP
        # follow-up) — say so loudly instead of silently dropping the
        # protection the operator asked for.
        if (train_cfg.exit_signal_handler or train_cfg.heartbeat_dir
                or train_cfg.ft_timeouts
                or train_cfg.non_persistent_save_interval
                or train_cfg.simulated_fault):
            log_fn("WARNING: fault-tolerance flags (--exit-signal-"
                   "handler/--heartbeat-dir/--ft-timeouts/--non-"
                   "persistent-save-interval/--simulated-fault) are "
                   "NOT wired into the forward_backward_disaggregating "
                   "path yet — running without them")
        return _pretrain_gpt_fbd(model_cfg, parallel_cfg, train_cfg,
                                 opt_cfg, batch_iter, log_fn,
                                 batch_iter_factory=batch_iter_factory)

    # --- resilience wiring (ISSUE 6) ----------------------------------
    # Heartbeat monitor with section timeouts (training/ft_integration):
    # sections setup → step → checkpointing around the loop below; the
    # on-disk heartbeat lets an external supervisor (read_heartbeat)
    # catch a wedged process even when the in-process watchdog is hung
    # with it.
    ft = None
    if train_cfg.heartbeat_dir or train_cfg.ft_timeouts:
        from megatronapp_tpu.training.ft_integration import (
            FTConfig, HeartbeatMonitor,
        )
        ft_cfg = FTConfig(heartbeat_dir=train_cfg.heartbeat_dir)
        if train_cfg.ft_timeouts:
            (ft_cfg.setup_timeout, ft_cfg.step_timeout,
             ft_cfg.checkpointing_timeout) = train_cfg.ft_timeouts
            ft_cfg.check_interval = min(5.0,
                                        min(train_cfg.ft_timeouts) / 2)

        def _on_timeout(section, idle):
            log_fn(f"ft: section {section!r} hung for {idle:.0f}s "
                   "(timeout exceeded) — rank appears wedged")

        ft = HeartbeatMonitor(ft_cfg, on_timeout=_on_timeout).start()
        ft.start_section("setup")
    # Simulated fault for FT drills (--simulated-fault KIND:DELAY):
    # 'exit' hard-kills the process after DELAY (inside ft_integration);
    # 'hang' sets this event and the loop wedges on it — the watchdog /
    # external supervisor must catch and recover.
    sim_hang = threading.Event()
    if train_cfg.simulated_fault:
        from megatronapp_tpu.training.ft_integration import (
            maybe_setup_simulated_fault,
        )
        kind, delay = train_cfg.simulated_fault
        maybe_setup_simulated_fault(kind, delay, target=sim_hang.set)
        log_fn(f"ft: simulated fault {kind!r} armed (fires in {delay}s)")

    if ctx is None:
        ctx = build_mesh(parallel_cfg)
    dp_total = ctx.dp * ctx.ep
    num_micro = train_cfg.num_microbatches(dp_total)
    from megatronapp_tpu.training.num_microbatches_calculator import (
        build_calculator,
    )
    batch_calc = build_calculator(
        train_cfg.global_batch_size, train_cfg.micro_batch_size, dp_total,
        train_cfg.rampup_batch_size)
    vpp = parallel_cfg.virtual_pipeline_parallel
    _validate_schedule_stages(batch_calc, ctx.pp, vpp,
                              parallel_cfg.pipeline_order_policy)

    # ZeRO-1 distributed optimizer (--use-distributed-optimizer): the
    # wrapper dp-shards m/v/master state; fsdp keeps the param-sharding
    # rules instead (the two compose poorly — fsdp already owns dp).
    optimizer = get_optimizer(
        opt_cfg, train_cfg.train_iters,
        distributed=(parallel_cfg.distributed_optimizer
                     and not parallel_cfg.fsdp))
    rng = jax.random.PRNGKey(train_cfg.seed)

    # fp8 amax-history state (validated above) threads through the
    # train state so checkpoints carry it and resume is bitwise.
    fp8_state = None
    if fp8_on:
        from megatronapp_tpu.training.fp8 import init_fp8_state
        fp8_state = init_fp8_state(model_cfg)

    def params_and_axes(rng):
        return init_gpt_params(rng, model_cfg, pp=ctx.pp, vpp=vpp)

    state, shardings, params_axes = setup_train_state(
        rng, params_and_axes, optimizer, ctx,
        sharded_init=train_cfg.sharded_init, fp8_state=fp8_state)

    # Checkpointing: restore from load_dir (or save_dir when resuming the
    # same run), save only to save_dir — reference --load/--save semantics
    # (training/checkpointing.py).
    ckpt = None
    start_step = 0
    # Pipeline layout metadata saved with (and consulted by) checkpoints
    # so cross-layout restores derive the stacked-leaf split instead of
    # shape-guessing (reference resharding.py source-parallelism record).
    ckpt_layout = {"pp": ctx.pp, "vpp": vpp,
                   "num_layers": model_cfg.num_layers}
    if train_cfg.save_dir:
        ckpt = CheckpointManager(train_cfg.save_dir,
                                 save_interval=train_cfg.save_interval)
    # Fast non-persistent local checkpoints (LocalCheckpointManager,
    # --non-persistent-save-interval): latest-only .npz saved every few
    # steps for quick preemption restarts, independent of the durable
    # Orbax saves.
    local_ckpt = None
    if (train_cfg.non_persistent_save_interval
            or train_cfg.non_persistent_ckpt_dir):
        np_dir = train_cfg.resolved_non_persistent_dir()
        if np_dir is None:
            log_fn("local checkpoints disabled: pass "
                   "--non-persistent-ckpt-dir or --save")
        else:
            local_ckpt = LocalCheckpointManager(np_dir)
    restore_dir = train_cfg.load_dir or train_cfg.save_dir
    loader = None
    if restore_dir:
        if train_cfg.load_dir and train_cfg.load_dir != train_cfg.save_dir:
            loader = CheckpointManager(train_cfg.load_dir)
        else:
            loader = ckpt
    # Restore prefers the FRESHEST of (local, durable); a tie goes to
    # the local copy (one flat read vs a tensorstore restore). A
    # corrupt/partial local file degrades to the durable path, and a
    # corrupt durable step walks back to the previous saved step
    # (CheckpointManager.restore fallback).
    side_state = None
    restored = None
    local_step = local_ckpt.latest_step if local_ckpt is not None else None
    durable_step = loader.latest_step if loader is not None else None
    # The restore paths are collectives under multi-host: every rank
    # must take the SAME one (one rank entering the durable restore
    # alone wedges the job — same invariant as the emergency-save
    # agreement). Local wins only when EVERY rank prefers it, and a
    # local-restore failure on ANY rank sends every rank to the durable
    # path together. (Ranks whose local files sit at different steps
    # would still diverge — per-rank local saves happen at the same
    # iterations, so differing steps imply a torn save, which shows up
    # as a corrupt/missing file and fails this agreement.)
    from megatronapp_tpu.training.signals import any_process_flag
    want_local = (local_step is not None
                  and (durable_step is None or local_step >= durable_step))
    if not any_process_flag(not want_local):
        out = local_ckpt.restore(state, return_extra=True)
        usable = out is not None
        if jax.process_count() > 1:
            # Bool agreement alone is step-BLIND: a rank whose earlier
            # local save failed (best-effort warn-and-continue) holds a
            # valid-but-STALE file, and with no durable checkpoint to
            # outvote it the ranks would restore divergent steps.
            # Gather the actual restored step (every rank participates
            # — -1 for a failed local restore) and require unanimity.
            from jax.experimental import multihost_utils
            mine = (int(jax.device_get(out[0]["step"])) if usable
                    else -1)
            steps_all = np.asarray(multihost_utils.process_allgather(
                np.asarray([mine])))
            usable = bool((steps_all == steps_all.flat[0]).all()
                          and steps_all.flat[0] >= 0)
        if any_process_flag(not usable):
            if out is not None:
                log_fn("local checkpoint unusable or stale on another "
                       "process; using the durable path")
        else:
            restored, side_state = out
            log_fn(f"restoring from local checkpoint (step {local_step})")
    if restored is None and loader is not None:
        restored = loader.restore(state, layout=ckpt_layout)
        if restored is not None:
            side_state = read_side_state(
                restore_dir, int(jax.device_get(restored["step"])))
    if restored is not None:
        state = restored
        start_step = int(jax.device_get(state["step"]))
        log_fn(f"resumed from checkpoint at step {start_step}")
    if loader is not None and loader is not ckpt:
        loader.close()
    if side_state is not None and \
            int(side_state.get("step", -1)) != start_step:
        side_state = None    # sidecar from a different step: stale

    # Consumed-samples bookkeeping honors the rampup schedule on resume
    # (reference consumed_train_samples accumulates ACTUAL batch sizes).
    # The checkpoint's side-state is authoritative when present (exact
    # stream position incl. _RowBuffer carry-over); the O(start_step)
    # schedule replay only runs for pre-side-state checkpoints.
    if side_state is not None and "consumed" in side_state:
        consumed = int(side_state["consumed"])
    else:
        consumed = 0
        for _ in range(start_step):
            consumed += batch_calc.get(consumed)[0]
    if batch_iter is None:
        # Fast-forward the data stream past already-consumed samples on
        # resume (reference consumed_train_samples bookkeeping) — via the
        # caller's factory for real datasets, the mock stream otherwise.
        if batch_iter_factory is not None:
            batch_iter = batch_iter_factory(consumed)
        else:
            batch_iter = mock_batches(
                train_cfg.seq_length, model_cfg.vocab_size,
                train_cfg.global_batch_size, seed=train_cfg.seed,
                start_idx=consumed)

    pp_schedule = getattr(parallel_cfg, "pp_schedule", "1f1b")
    if ctx.pp > 1:
        def make_pp_loss_fn(schedule):
            """Pipelined loss bound to one schedule program — the
            planner re-plan path rebuilds through this (ISSUE 15)."""
            def loss_fn(params, batch_mb):
                return gpt_pipeline_loss(
                    params, batch_mb["tokens"], batch_mb["labels"],
                    batch_mb["loss_mask"], model_cfg, ctx, vpp=vpp,
                    order_policy=parallel_cfg.pipeline_order_policy,
                    segment_ids_mb=batch_mb.get("segment_ids"),
                    schedule=schedule)
            return loss_fn

        loss_fn = make_pp_loss_fn(pp_schedule)
        if pp_schedule != "1f1b":
            log_fn(f"pipeline schedule: {pp_schedule} (instruction "
                   "program executor, parallel/schedule.py)")
    else:
        loss_fn = gpt_microbatch_loss(model_cfg, ctx=ctx)
    eval_step_fn = None
    if train_cfg.eval_interval:
        # Held-out evaluation (reference evaluate_and_print_results,
        # training.py eval loop): the caller-provided eval stream when
        # given (real validation data), else a distinct mock stream
        # (different seed). Works under pp>1 via the pipelined eval step.
        from megatronapp_tpu.training.train_step import make_eval_step
        eval_step_fn = make_eval_step(loss_fn, ctx, shardings,
                                      pipeline=ctx.pp > 1, fp8=fp8_on)
        if eval_batch_iter is None:
            eval_batch_iter = mock_batches(
                train_cfg.seq_length, model_cfg.vocab_size,
                train_cfg.global_batch_size, seed=train_cfg.seed + 1)

    # MegaDPP dynamic runtime in the training path (reference transport
    # init inside pretrain_body, training.py:746-783): with --use-dpp and
    # a pure-pp layout the step runs host-driven through the
    # DppPipelineRunner (fwd+bwd dynamic scheduling, runtime/dpp_train.py)
    # instead of the jitted SPMD schedule (one host pipeline per dp
    # replica). Layouts the host runner cannot place (tp/cp/ep > 1)
    # fall back to the static bfc SPMD order.
    use_dpp_runtime = False
    if getattr(parallel_cfg, "use_dpp", False) and ctx.pp > 1:
        if (ctx.tp == ctx.cp == ctx.ep == 1
                and not model_cfg.mtp_num_layers):
            use_dpp_runtime = True
        else:
            log_fn("dpp: layout has tp/cp/ep > 1 (or MTP) — host "
                   "runner needs one stage per device per replica; "
                   "falling back to static bfc SPMD ordering")
    if use_dpp_runtime:
        from megatronapp_tpu.runtime.dpp_train import make_dpp_train_step
        # Mesh axis order (pp, dp, ep, cp, tp): with ep=cp=tp=1 the
        # device array reshapes to a [pp][dp] grid — each dp column is
        # one replica's stage chain.
        device_grid = ctx.mesh.devices.reshape(ctx.pp, ctx.dp)
        step_fn = make_dpp_train_step(
            optimizer, opt_cfg, model_cfg, device_grid,
            train_cfg.train_iters, vpp=vpp,
            policy=parallel_cfg.pipeline_order_policy,
            check_nan=train_cfg.check_for_nan_in_loss,
            state_shardings=shardings)
        log_fn(f"dpp: dynamic runtime active (pp={ctx.pp}, dp={ctx.dp}, "
               f"vpp={vpp}, "
               f"policy={parallel_cfg.pipeline_order_policy})")
        if getattr(opt_cfg, "dist_opt_comm", "gspmd") in ("ring", "bulk") \
                and getattr(optimizer, "zero1", False):
            # The host-driven step has no manual-update hook; say so
            # instead of letting an A/B silently measure the wrong mode
            # (same loud-fallback policy as the FBD path).
            log_fn(f"dpp: --dist-opt-comm {opt_cfg.dist_opt_comm} is not "
                   "wired into the host-driven runtime — the ZeRO-1 "
                   "update runs in gspmd mode here")
    def _build_step(loss_fn_, trace_phases=False, donate=True):
        """The ONE build site for the jitted SPMD step — startup, the
        phase-traced variant, and the planner's _apply_schedule rebuild
        all go through it so they can never drift apart."""
        return make_train_step(
            loss_fn_, optimizer, opt_cfg, ctx, shardings,
            train_cfg.train_iters,
            check_nan=train_cfg.check_for_nan_in_loss,
            pipeline=ctx.pp > 1, trace_phases=trace_phases,
            donate=donate, fp8=fp8_on)

    if not use_dpp_runtime:
        step_fn = _build_step(loss_fn)
    # Non-donating variant for rerun replay (compiles only if a failure is
    # ever classified; the donating step would delete the live state's
    # buffers on replay). The DPP step never donates, so it replays as-is.
    replay_step_fn = step_fn if use_dpp_runtime else \
        _build_step(loss_fn, donate=False)

    # Trace-driven dynamic pipeline planning (ISSUE 15 — closing the
    # MegaScan → MegaDPP loop): per-(stage, vstage) step-time EWMAs fed
    # by the pipeline's ring-hop trace spans and the whole-step
    # straggler signal drive a planner that models every candidate
    # schedule's bubble and re-plans with hysteresis; a re-plan rebuilds
    # the jitted step family below (loudly).
    planner = None
    saw_packed = False  # one packed batch freezes planning for the run
    if (getattr(parallel_cfg, "pp_plan_from_trace", False) and ctx.pp > 1
            and not use_dpp_runtime):
        import dataclasses as _dc_plan

        from megatronapp_tpu.parallel.overlap import tp_stage_eligible
        from megatronapp_tpu.parallel.schedule import Planner

        # Mirror pipeline.py's zb_switch: the planner may auto-apply
        # zero-bubble only where the executor realizes it with the
        # per-slot switch backward. On masked-dispatch meshes
        # (tp-sharded / cp-ring / moe-ep stage bodies) both vjps run
        # every slot — the modeled bubble win is paid back ~2x in
        # redundant backward compute, so switching there would make
        # real steps slower while the model claims improvement.
        zb_realizable = (ctx.cp == 1 and ctx.ep == 1 and not (
            ctx.tp > 1
            and tp_stage_eligible(model_cfg, ctx,
                                  train_cfg.seq_length)))
        planner = Planner(ctx.pp, vpp=vpp, model_cfg=model_cfg,
                          allow_zero_bubble=zb_realizable)
        if not zb_realizable:
            log_fn("pp-planner: zero-bubble candidate DISABLED on this "
                   "mesh — the stage body carries collectives "
                   "(tp-sharded rings / cp ring / moe ep), so the "
                   "executor runs zero-bubble as masked dual-vjp "
                   "compute that costs more than the bubble saves; "
                   "planning stays among the remaining schedules")
        _plan0 = planner.plan(num_micro)
        # Pin "current" to the CONFIGURED schedule so re-plans measure
        # improvement against what is actually running (plan() alone
        # would seed with the modeled winner before any signal exists).
        # Under vpp > 1 the candidate is named 'vpp' and '1f1b' is the
        # same interleaved schedule — seed with the alias so the
        # planner never "switches" between two names for one program.
        _seed = ("vpp" if (vpp > 1 and pp_schedule == "1f1b")
                 else pp_schedule)
        planner.current = _dc_plan.replace(
            _plan0, schedule=_seed,
            bubble_fraction=_plan0.candidates.get(
                _seed, _plan0.bubble_fraction))
        log_fn(f"pp-planner: active (schedule {pp_schedule!r}, modeled "
               f"bubble {planner.current.bubble_fraction:.4f}, "
               "candidates "
               f"{ {k: round(v, 4) for k, v in _plan0.candidates.items()} }"
               f", stage costs "
               f"{[round(c, 3) for c in _plan0.stage_costs]})")

    tracer = get_tracer()
    traced_step_fn = step_fn
    fenced_trace = False
    phase_traced = False
    if train_cfg.trace:
        tracer.configure(
            enabled=True, trace_dir=train_cfg.trace_dir,
            interval=train_cfg.trace_interval,
            continuous_iterations=train_cfg.continuous_trace_iterations,
            granularity=train_cfg.trace_granularity, mesh_ctx=ctx)
        # Separate compiled step with in-graph phase markers — selected only
        # on traced iterations so untraced steps carry zero overhead (the
        # reference's per-window tracing achieves this by skipping event
        # creation; under jit the instrumentation must be traced in).
        from megatronapp_tpu.trace.tracer import callbacks_supported
        if use_dpp_runtime:
            # The host-driven step has its own per-phase observability
            # (runner transfer/stall metrics in the step metrics dict);
            # in-graph phase markers only apply to the SPMD step.
            log_fn("trace: dpp runtime active — schedule-phase spans come "
                   "from the runner's per-phase metrics")
        elif callbacks_supported():
            phase_traced = True
            traced_step_fn = _build_step(loss_fn, trace_phases=True)
        else:
            # Host-timestamped dispatch windows (round-4 verdict task 6
            # fallback): backends without host callbacks (the tunneled
            # axon chip — tracer.callbacks_supported) cannot carry
            # in-graph phase markers, so traced iterations run as FENCED
            # dispatches instead: (1) a forward-only loss, fenced by
            # device_get — the 'forward' span; (2) the full step, fenced
            # — the 'backward' span, whose attrs carry the honest
            # arithmetic (it re-runs the forward and includes the
            # optimizer; backward_est_ms = span - forward). Cost (one
            # extra forward + two fences) is confined to traced
            # iterations — the reference's per-window tracing perturbs
            # its traced iterations the same way.
            log_fn("trace: backend lacks host callbacks; using fenced "
                   "dispatch windows for schedule-phase spans")
            fenced_trace = True
            if planner is not None:
                # Committing a re-plan the loop below cannot apply would
                # desync the planner's state/metrics from the schedule
                # actually running — planning stays observational here
                # (EWMAs + gauges only; maybe_replan is never called).
                log_fn("pp-planner: fenced-dispatch trace mode pins the "
                       "compiled step — planning is OBSERVATIONAL (no "
                       "re-plans); restart with --pp-schedule to change "
                       "schedules")
            if ctx.pp > 1:
                _fwd_only = jax.jit(lambda p, b: loss_fn(p, b)[0])
            else:
                def _fwd_loss(p, b):
                    def body(acc, micro):
                        l, _ = loss_fn(p, micro)
                        return acc + l, None
                    tot, _ = jax.lax.scan(
                        body, jnp.zeros((), jnp.float32), b)
                    return tot / jax.tree.leaves(b)[0].shape[0]
                _fwd_only = jax.jit(_fwd_loss)

            def fenced_step(state, batch):
                import time as _time
                t0 = _time.perf_counter()
                with tracer.scope("forward", fenced=True):
                    jax.device_get(_fwd_only(state["params"], batch))
                fwd_ms = (_time.perf_counter() - t0) * 1e3
                with tracer.scope("backward", fenced=True,
                                  includes="fwd_rerun+optimizer",
                                  forward_ms=round(fwd_ms, 3)) as tr:
                    new_state, metrics = step_fn(state, batch)
                    jax.device_get(metrics["loss"])
                    tr.set_attr(backward_est_ms=round(
                        (_time.perf_counter() - t0) * 1e3 - 2 * fwd_ms,
                        3))
                return new_state, metrics

            # The profiler-collectives join still needs compiled HLO;
            # the fenced wrapper exposes the underlying jitted step.
            fenced_step._hlo_source = step_fn
            traced_step_fn = fenced_step

    def _apply_schedule(new_schedule: str) -> bool:
        """Planner re-plan: swap the pipeline schedule program and
        rebuild the jitted step family (one recompile, loudly logged).
        Returns True when applied. Grads are schedule-invariant
        (zero-bubble parity pinned ≤1e-6), so switching mid-run never
        perturbs the optimizer trajectory beyond accumulation order."""
        nonlocal loss_fn, step_fn, replay_step_fn, traced_step_fn
        nonlocal pp_schedule
        if fenced_trace:
            log_fn("pp-planner: re-plan NOT applied — fenced-dispatch "
                   "trace mode pins the compiled step (backend without "
                   "host callbacks); restart with --pp-schedule "
                   f"{new_schedule} to take it")
            return False
        log_fn(f"pp-planner: APPLYING schedule {new_schedule!r} "
               f"(was {pp_schedule!r}) — rebuilding the train step "
               "(one-time recompile)")
        pp_schedule = new_schedule
        loss_fn = make_pp_loss_fn(new_schedule)
        step_fn = _build_step(loss_fn)
        replay_step_fn = _build_step(loss_fn, donate=False)
        traced_step_fn = step_fn
        if phase_traced:
            traced_step_fn = _build_step(loss_fn, trace_phases=True)
        return True

    # Per-collective events via the XLA profiler (reference
    # mappings.py:27-60 group+bytes instrumentation; here synthesized
    # post-hoc since SPMD inserts the collectives — see
    # trace/profiler_collectives.py). One profiled iteration per trace
    # window keeps the profiler overhead off the steady state.
    _coll = {"hlo": {}, "window": -1}

    def run_step_maybe_profiled(active_fn, state, batch, it):
        # Fenced traced steps expose their inner jitted step for the HLO
        # join; host-driven (DPP) steps have no single lowered HLO at
        # all — the runner's metrics cover them.
        hlo_source = getattr(active_fn, "_hlo_source", active_fn)
        if (not tracer.active or not hasattr(hlo_source, "lower") or
                train_cfg.trace_granularity not in ("full", "collective")):
            return active_fn(state, batch)
        window = it // tracer.interval
        if window == _coll["window"]:
            return active_fn(state, batch)
        _coll["window"] = window
        from megatronapp_tpu.trace.profiler_collectives import (
            collective_events, extract_hlo_collectives, profile_run,
        )
        # Keyed on batch leaf shapes as well as the fn: under batch-size
        # rampup a later window recompiles the step, and joining profiler
        # events against the first shape's HLO table would silently
        # misattribute bytes/bandwidth per collective.
        shape_key = tuple(
            (getattr(l, "shape", ()), str(getattr(l, "dtype", "")))
            for l in jax.tree_util.tree_leaves(batch))
        key = (id(active_fn), shape_key)
        if key not in _coll["hlo"]:
            try:
                compiled = hlo_source.lower(state, batch).compile()
                _coll["hlo"][key] = extract_hlo_collectives(
                    compiled.as_text(), ctx.mesh)
            except Exception as e:  # pragma: no cover — backend-specific
                log_fn(f"trace: collective HLO extraction failed ({e}); "
                       "profiler collectives disabled")
                _coll["hlo"][key] = None
        info = _coll["hlo"][key]
        if not info:
            return active_fn(state, batch)
        result = {}

        def run():
            result["out"] = active_fn(state, batch)
            return result["out"]

        # Anchor BEFORE the capture so events land where the collectives
        # ran, not after the profile-parse delay (which varies per host
        # and would skew cross-process stage-2 comparisons).
        offset_us = tracer.now_in_iteration_us()
        try:
            raw = profile_run(run)
            tracer.add_collective_records(
                collective_events(raw, info, iteration=it),
                offset_us=offset_us)
        except Exception as e:  # pragma: no cover — profiler optional
            log_fn(f"trace: profiler capture failed ({e})")
            if "out" not in result:  # failed before the step ran
                result["out"] = active_fn(state, batch)
        return result["out"]

    from megatronapp_tpu.training.rerun_state_machine import (
        get_rerun_state_machine,
    )
    from megatronapp_tpu.utils.straggler import get_straggler_detector

    from megatronapp_tpu.training.metrics import MetricsLogger
    metrics_logger = MetricsLogger()
    if jax.process_index() == 0:  # rank-0 writer (reference tb gating)
        if train_cfg.metrics_jsonl:
            metrics_logger.add_jsonl(train_cfg.metrics_jsonl)
        if train_cfg.tensorboard_dir:
            metrics_logger.add_tensorboard(train_cfg.tensorboard_dir,
                                           warn=log_fn)

    rerun = get_rerun_state_machine()
    rerun.mode = train_cfg.rerun_mode
    rerun.loss_spike_factor = train_cfg.loss_spike_factor
    rerun.error_injection_rate = train_cfg.error_injection_rate
    if side_state is not None and side_state.get("rerun"):
        # Resume the fault-classification statistics exactly (EMA, step
        # and injection counters); mode stays with THIS run's config.
        sd = dict(side_state["rerun"])
        sd.pop("mode", None)
        rerun.load_state_dict(sd)
    straggler = get_straggler_detector()
    if train_cfg.log_straggler:
        straggler.enable()
    inspector = None
    if train_cfg.run_workload_inspector_server and jax.process_index() == 0:
        from megatronapp_tpu.utils.inspector import get_inspector
        inspector = get_inspector()
        port = inspector.start(train_cfg.workload_inspector_port)
        log_fn(f"workload inspector: http://127.0.0.1:{port}/status")

    scribe = _CheckpointScribe(ckpt, local_ckpt, train_cfg, ckpt_layout,
                               ft, rerun, log_fn)
    losses = []
    window_tokens = 0
    window_start = time.perf_counter()
    step_time_ms = 0.0
    tokens_per_sec = 0.0

    # E2E run-health metrics (reference one_logger_utils.py parity —
    # utils/one_logger.py flushes through the standard metrics sinks).
    from megatronapp_tpu.utils.one_logger import get_e2e_tracker
    e2e = get_e2e_tracker()
    e2e.reset()
    e2e.on_train_start(start_step, consumed, train_cfg.train_iters,
                       train_cfg.seq_length)
    window_start_iter = start_step   # first iteration of the open window

    last_sync_iter = start_step
    rows = _RowBuffer(batch_iter)
    interrupted = False
    # Exit-signal sync cadence: should_exit() is a host-level collective
    # under multi-host (process_allgather) — running it every iteration
    # would put a blocking sync point in the hot loop for an event that
    # happens at most once. All ranks share the same schedule, so the
    # agreement still holds; a preemption notice drains within 8 steps.
    # Single-process keeps the cheap every-step local check.
    exit_sync_every = 1 if jax.process_count() <= 1 else 8
    if ft is not None:
        ft.start_section("step")
    with _signal_exit_context(train_cfg, log_fn) as sig, ctx.mesh:
        for it in range(start_step, train_cfg.train_iters):
            if ft is not None:
                ft.beat()
            if sim_hang.is_set():
                # FT drill: wedge the step section — heartbeats stop,
                # the watchdog flags the hang, and the external
                # supervisor (read_heartbeat) sees a stale file.
                log_fn(f"ft: simulated hang at iteration {it + 1} — "
                       "wedging the step section")
                while True:          # pragma: no cover — drill only
                    time.sleep(3600)
            tracer.iteration_begin(it)
            cur_gbs, cur_micro = batch_calc.get(consumed)
            # Rampup consumes exactly cur_gbs rows from the stream (each
            # distinct size is its own compiled step shape; leftovers
            # carry over — no samples dropped).
            batch = globalize_batch(
                reshape_global_batch(rows.take(cur_gbs), cur_micro), ctx)
            consumed += cur_gbs
            if (ctx.pp > 1 and not use_dpp_runtime
                    and "segment_ids" in batch):
                # Packed batches cannot run the zero-bubble program
                # (per-microbatch aux inputs). The stream may MIX packed
                # and unpacked batches, so one packed batch freezes
                # planning for the rest of the run, and a zero-bubble
                # schedule — planner-applied OR statically configured —
                # reverts to 1f1b BEFORE the step instead of crashing
                # mid-stream (grads are schedule-invariant, so the
                # revert is a perf-only change; a crash hours in is
                # not).
                if planner is not None and not saw_packed:
                    saw_packed = True
                    log_fn("pp-planner: packed batch in the stream — "
                           "planning frozen (zero-bubble does not "
                           "compose with packed sequences)")
                if pp_schedule == "zero-bubble":
                    log_fn("zero-bubble does not compose with packed "
                           "sequences (segment_ids in batch) — "
                           "reverting to 1f1b (grads are schedule-"
                           "invariant; perf-only change)")
                    if not _apply_schedule("1f1b"):
                        # Fenced-dispatch trace mode pins the compiled
                        # zero-bubble step — the packed batch WOULD
                        # crash on retrace with a confusing
                        # NotImplementedError; name the conflict now.
                        raise ValueError(
                            "packed batch (segment_ids) in the stream "
                            "while the zero-bubble step is pinned by "
                            "fenced-dispatch trace mode — restart with "
                            "--pp-schedule 1f1b for packed data")
                    if planner is not None and \
                            planner.current is not None:
                        planner.current = _dc_plan.replace(
                            planner.current, schedule="1f1b",
                            bubble_fraction=planner.current.candidates
                            .get("1f1b",
                                 planner.current.bubble_fraction))
            tokens_per_step = cur_gbs * train_cfg.seq_length
            straggler.start()
            with tracer.scope("train-step"):
                active_fn = traced_step_fn if tracer.active else step_fn
                state, metrics = run_step_maybe_profiled(
                    active_fn, state, batch, it)
                # Block for accurate per-step timing only when tracing or
                # logging this step; otherwise let steps pipeline.
                should_log = ((it + 1) % train_cfg.log_interval == 0 or
                              it + 1 == train_cfg.train_iters)
                if tracer.active or should_log:
                    metrics = jax.device_get(metrics)
                    # Straggler sampling: normalize the sync-to-sync window
                    # by the number of pipelined steps it covers, so traced
                    # (1-step) and logged (log_interval-step) samples share
                    # a baseline.
                    steps_in_span = max(it + 1 - last_sync_iter, 1)
                    outlier = straggler.stop(steps=steps_in_span)
                    last_sync_iter = it + 1
                    if outlier is not None:
                        log_fn(f"straggler: step {it+1} averaged "
                               f"{outlier.elapsed_s*1e3:.0f} ms/step "
                               f"(>{straggler.z_threshold} sigma)")
                    # Result validation runs at sync points; the in-graph
                    # NaN guard (lax.cond skip) protects params on EVERY
                    # step regardless — only the host-side classification
                    # is sampled (vs the reference's per-step check).
                    loss_val = float(metrics["loss"])
                    ok, eff_loss = rerun.validate(loss_val)
                    if not ok:
                        # The step's lax.cond already skipped the param
                        # update on non-finite losses, so `state` still
                        # holds the pre-update params — replaying the same
                        # (state, batch) via the NON-donating step
                        # classifies transient vs persistent (reference
                        # rerun-to-classify; spikes with finite loss did
                        # update, so those are report-only).
                        import math as _math
                        if not _math.isfinite(eff_loss):
                            diag = rerun.classify_failure(
                                replay_step_fn, state, batch, eff_loss)
                            log_fn(f"rerun: invalid loss {eff_loss} at step "
                                   f"{it+1} — {diag.value}")
                        else:
                            log_fn(f"rerun: loss spike {eff_loss:.4f} at "
                                   f"step {it+1} (report-only)")
            was_traced = tracer.active
            # Fence on the updated params so in-flight phase callbacks
            # (e.g. the optimizer span) land inside this iteration window.
            tracer.iteration_end(
                it, fence=state["params"] if was_traced else None)
            if was_traced:
                if planner is not None:
                    # MegaScan → planner: mine the traced iteration's
                    # ring-hop spans for per-stage compute gaps BEFORE
                    # save() drains the buffer to disk.
                    planner.ingest_trace_events(tracer.peek())
                tracer.save()
            window_tokens += tokens_per_step

            if should_log:
                loss = float(metrics["loss"])
                losses.append(loss)
                now = time.perf_counter()
                dt = now - window_start
                # Iteration-indexed window length (a modulo formula
                # overcounts the first window after a mid-interval
                # checkpoint resume).
                steps_in_window = it + 1 - window_start_iter
                tokens_per_sec = window_tokens / dt
                step_time_ms = dt / max(steps_in_window, 1) * 1e3
                tflops = (tokens_per_sec *
                          flops_per_token(model_cfg, train_cfg.seq_length)
                          / ctx.num_devices / 1e12)
                log_fn(
                    f"iter {it+1:6d}/{train_cfg.train_iters} | "
                    f"loss {loss:.4f} | grad_norm "
                    f"{float(metrics['grad_norm']):.3f} | "
                    f"lr {float(metrics['lr']):.2e} | "
                    f"skipped {int(metrics['skipped'])} | "
                    f"{step_time_ms:.1f} ms/step | "
                    f"{tokens_per_sec:,.0f} tok/s | "
                    f"{tflops:.1f} TFLOP/s/dev")
                if inspector is not None:
                    inspector.update(
                        step=it + 1, loss=loss,
                        tokens_per_sec=round(tokens_per_sec, 1),
                        step_time_ms=round(step_time_ms, 2),
                        tflops_per_device=round(tflops, 2),
                        consumed_samples=consumed)
                metrics_logger.log(it + 1, {
                    **metrics,
                    "tokens_per_sec": tokens_per_sec,
                    "step_time_ms": step_time_ms,
                    "tflops_per_device": tflops,
                })
                # Telemetry registry (ISSUE 12): step-time histogram +
                # throughput gauge land in the SAME registry the serving
                # stack exports at /metrics — one signal substrate.
                telemetry.observe("train_step_time_ms", step_time_ms,
                                  lo=1e-2, hi=1e7)
                telemetry.set_gauge("train_tokens_per_sec",
                                    round(tokens_per_sec, 1))
                if fp8_on and telemetry.enabled():
                    # fp8 scale-drift observability (ISSUE 13): per-site
                    # current scale / worst amax gauges + saturation
                    # counters, one small device_get per logged step.
                    from megatronapp_tpu.training.fp8 import (
                        export_fp8_metrics,
                    )
                    export_fp8_metrics(state["fp8"], model_cfg)
                if planner is not None:
                    # Whole-step sample keeps the per-stage EWMAs alive
                    # between traced iterations; the gauges make the
                    # planner's input signal observable at /metrics
                    # (ISSUE 15 satellite). Re-plan with hysteresis —
                    # frozen once ANY packed batch has been seen
                    # (zero-bubble does not compose with per-microbatch
                    # aux inputs, and the stream may mix).
                    planner.observe_step(step_time_ms / 1e3)
                    planner.export_metrics()
                    if not saw_packed and not fenced_trace:
                        newp = planner.maybe_replan(cur_micro)
                        if newp is not None:
                            _apply_schedule(newp.schedule)
                e2e.track_iterations(
                    steps_in_window, dt,
                    window_tokens // train_cfg.seq_length)
                window_tokens = 0
                window_start = now
                window_start_iter = it + 1

            if eval_step_fn is not None and \
                    (it + 1) % train_cfg.eval_interval == 0:
                t_eval = time.perf_counter()
                totals = []
                for _ in range(train_cfg.eval_iters):
                    ebatch = globalize_batch(
                        reshape_global_batch(next(eval_batch_iter),
                                             num_micro), ctx)
                    totals.append(eval_step_fn(state, ebatch))
                eval_loss = float(jax.device_get(
                    jnp.mean(jnp.stack(totals))))
                eval_dt = time.perf_counter() - t_eval
                e2e.track_validation(eval_dt)
                # Keep eval time out of the next train window (it is
                # reported under validation_* instead).
                window_start += eval_dt
                log_fn(f"eval @ iter {it+1}: loss {eval_loss:.4f} over "
                       f"{train_cfg.eval_iters} batches")

            if ckpt is not None and train_cfg.save_interval and \
                    (it + 1) % train_cfg.save_interval == 0:
                with scribe.section():
                    t_save = time.perf_counter()
                    scribe.save_durable(it + 1, state, consumed)
                    save_dt = time.perf_counter() - t_save
                e2e.on_save_checkpoint(save_dt)
                # Save dispatch time is reported under save_checkpoint_*,
                # not the next train window.
                window_start += save_dt

            if local_ckpt is not None and \
                    train_cfg.non_persistent_save_interval and \
                    (it + 1) % train_cfg.non_persistent_save_interval == 0:
                with scribe.section():
                    scribe.save_local(it + 1, state, consumed)

            # Graceful signal exit (--exit-signal-handler): the in-
            # flight step above already finished; agree the decision
            # across processes (one rank must never enter the collective
            # emergency save alone), force-save durable + local
            # checkpoints with resumable side state, and exit cleanly.
            if sig is not None and (it + 1) % exit_sync_every == 0 \
                    and sig.should_exit():
                log_fn(f"signal: exit requested — emergency checkpoint "
                       f"at iteration {it + 1}")
                with scribe.section():
                    t_save = time.perf_counter()
                    # A SIGTERM landing on a save-interval boundary
                    # already has this step on disk (skip_if_current).
                    scribe.save_durable(it + 1, state, consumed,
                                        force=True, skip_if_current=True)
                    scribe.save_local(it + 1, state, consumed,
                                      what="local emergency")
                    if ckpt is not None:
                        ckpt.wait()   # durability before exit
                log_fn(f"signal: emergency save done in "
                       f"{time.perf_counter() - t_save:.2f}s; exiting "
                       "cleanly")
                interrupted = True
                break

            if train_cfg.exit_interval and \
                    (it + 1) % train_cfg.exit_interval == 0:
                break

    if ckpt is not None:
        final_step = int(jax.device_get(state["step"]))
        if train_cfg.save_interval and ckpt.latest_step != final_step:
            with scribe.section():
                scribe.save_durable(final_step, state, consumed,
                                    force=True)
        ckpt.wait()
        ckpt.close()
    if ft is not None:
        ft.stop()
    if train_cfg.trace:
        tracer.finalize()
    if inspector is not None:
        inspector.stop()
    # Flush a partial window (exit_interval or final iterations not
    # aligned to log_interval) so the summary covers every step run.
    final_iter = int(jax.device_get(state["step"]))
    if final_iter > window_start_iter:
        e2e.track_iterations(final_iter - window_start_iter,
                             time.perf_counter() - window_start,
                             window_tokens // train_cfg.seq_length)
    e2e.finish(metrics_logger, log_fn=log_fn, step=final_iter)
    metrics_logger.close()

    return TrainResult(state=state, losses=losses,
                       tokens_per_sec=tokens_per_sec,
                       step_time_ms=step_time_ms,
                       interrupted=interrupted,
                       consumed_samples=consumed)


def _pretrain_gpt_fbd(model_cfg, parallel_cfg, train_cfg, opt_cfg,
                      batch_iter=None, log_fn=print,
                      batch_iter_factory=None) -> TrainResult:
    """MegaFBD training path: forward and backward on disjoint sub-meshes
    (parallel/fbd.py). DP is halved on each mesh; per microbatch the
    forward mesh runs the vjp forward pass and ships the residuals to the
    backward mesh, which applies the transposed pass and the optimizer
    update — dispatches overlap across the two meshes. Composes with
    tp/pp/cp (each half-mesh runs the same loss_fn as the main path,
    including the SPMD pipeline)."""
    from megatronapp_tpu.parallel.fbd import FBDExecutor, split_fbd_meshes
    from megatronapp_tpu.training.num_microbatches_calculator import (
        build_calculator,
    )

    fwd_ctx, bwd_ctx = split_fbd_meshes(parallel_cfg)
    log_fn(f"FBD: forward mesh {dict(fwd_ctx.mesh.shape)} | backward mesh "
           f"{dict(bwd_ctx.mesh.shape)}")
    if parallel_cfg.distributed_optimizer:
        # The executor ships state between half-meshes with its own
        # shardings; the ZeRO-1 wrapper is not validated there yet
        # (ROADMAP follow-up) — the legacy dp-sharded-param rules apply.
        log_fn("FBD: ZeRO-1 distributed optimizer is not wired into the "
               "forward_backward_disaggregating path; using the legacy "
               "dp-sharded-param (fsdp-style) state rules")
    # Batch-size rampup composes: the executor's microbatch loop takes any
    # M (non-pipelined — no recompiles; pipelined — one compile per ramp
    # stage, same bound as the main path).
    batch_calc = build_calculator(
        train_cfg.global_batch_size, train_cfg.micro_batch_size,
        bwd_ctx.dp * bwd_ctx.ep, train_cfg.rampup_batch_size)
    vpp = parallel_cfg.virtual_pipeline_parallel
    _validate_schedule_stages(batch_calc, bwd_ctx.pp, vpp,
                              parallel_cfg.pipeline_order_policy)

    optimizer = get_optimizer(opt_cfg, train_cfg.train_iters)
    rng = jax.random.PRNGKey(train_cfg.seed)
    with bwd_ctx.mesh:
        state, shardings, _ = setup_train_state(
            rng,
            lambda k: init_gpt_params(k, model_cfg, pp=bwd_ctx.pp, vpp=vpp),
            optimizer, bwd_ctx, sharded_init=train_cfg.sharded_init)

    if bwd_ctx.pp > 1:
        # Pipelined loss on each half-mesh: the executor feeds the WHOLE
        # microbatched batch per fwd call (the pipeline schedules
        # microbatches internally), so grad accumulation degenerates to a
        # single fwd/bwd pair per step.
        def loss_fn(params, batch_whole, _ctx):
            return gpt_pipeline_loss(
                params, batch_whole["tokens"], batch_whole["labels"],
                batch_whole["loss_mask"], model_cfg, _ctx, vpp=vpp,
                order_policy=parallel_cfg.pipeline_order_policy)
    else:
        def loss_fn(params, micro, _ctx):
            loss, metrics = gpt_loss(params, micro["tokens"],
                                     micro["labels"], micro["loss_mask"],
                                     model_cfg, ctx=_ctx)
            return loss, metrics
    executor = FBDExecutor(loss_fn, optimizer, fwd_ctx, bwd_ctx, state,
                           shardings, pipeline=bwd_ctx.pp > 1)

    # Checkpointing on the backward-mesh master state (reference FBD's
    # save_checkpoint_legacy analogue — ours reuses the standard manager).
    ckpt = None
    start_step = 0
    ckpt_layout = {"pp": bwd_ctx.pp, "vpp": 1,
                   "num_layers": model_cfg.num_layers}
    if train_cfg.save_dir:
        ckpt = CheckpointManager(train_cfg.save_dir,
                                 save_interval=train_cfg.save_interval)
    restore_dir = train_cfg.load_dir or train_cfg.save_dir
    if restore_dir:
        loader = (CheckpointManager(train_cfg.load_dir)
                  if train_cfg.load_dir and
                  train_cfg.load_dir != train_cfg.save_dir else ckpt)
        restored = (loader.restore(executor.state, layout=ckpt_layout)
                    if loader else None)
        if restored is not None:
            executor.set_state(restored)
            start_step = int(jax.device_get(restored["step"]))
            log_fn(f"resumed from checkpoint at step {start_step}")
        if loader is not None and loader is not ckpt:
            loader.close()

    # Fast-forward the data stream past consumed samples on resume (same
    # bookkeeping as the main path — rampup makes consumed step-nonlinear,
    # so replay the schedule).
    consumed = 0
    for _ in range(start_step):
        consumed += batch_calc.get(consumed)[0]
    if batch_iter is None:
        if batch_iter_factory is not None:
            batch_iter = batch_iter_factory(consumed)
        else:
            batch_iter = mock_batches(
                train_cfg.seq_length, model_cfg.vocab_size,
                train_cfg.global_batch_size, seed=train_cfg.seed,
                start_idx=consumed)

    from megatronapp_tpu.training.metrics import MetricsLogger
    metrics_logger = MetricsLogger()
    if jax.process_index() == 0:
        if train_cfg.metrics_jsonl:
            metrics_logger.add_jsonl(train_cfg.metrics_jsonl)
        if train_cfg.tensorboard_dir:
            metrics_logger.add_tensorboard(train_cfg.tensorboard_dir,
                                           warn=log_fn)
    tracer = get_tracer()
    if train_cfg.trace:
        # Host-side scopes only: FBD spans two meshes; in-graph phase
        # markers are a per-mesh concept (the bwd mesh carries the
        # schedule), so trace covers dispatch-level timing.
        tracer.configure(
            enabled=True, trace_dir=train_cfg.trace_dir,
            interval=train_cfg.trace_interval,
            continuous_iterations=train_cfg.continuous_trace_iterations,
            granularity=train_cfg.trace_granularity, mesh_ctx=bwd_ctx)

    losses = []
    t0 = time.perf_counter()
    rows = _RowBuffer(batch_iter)
    start_consumed = consumed
    for it in range(start_step, train_cfg.train_iters):
        tracer.iteration_begin(it)
        cur_gbs, cur_micro = batch_calc.get(consumed)
        batch = reshape_global_batch(rows.take(cur_gbs), cur_micro)
        consumed += cur_gbs
        with tracer.scope("train-step"):
            out = executor.step(batch)
        if (it + 1) % train_cfg.log_interval == 0 or \
                it + 1 == train_cfg.train_iters:
            loss = float(jax.device_get(out["loss"]))
            fwd_loss = float(jax.device_get(out["fwd_loss"]))
            grad_norm = float(jax.device_get(out["grad_norm"]))
            losses.append(loss)
            log_fn(f"iter {it+1:6d}/{train_cfg.train_iters} | "
                   f"loss {loss:.4f} | fwd-mesh loss {fwd_loss:.4f} | "
                   f"grad_norm {grad_norm:.3f}")
            metrics_logger.log(it + 1, {"loss": loss, "fwd_loss": fwd_loss,
                                        "grad_norm": grad_norm})
        tracer.iteration_end(it)
        if tracer.active:
            tracer.save()
        if ckpt is not None and train_cfg.save_interval and \
                (it + 1) % train_cfg.save_interval == 0:
            ckpt.save(it + 1, jax.device_get(executor.state),
                      layout=ckpt_layout)
    dt = time.perf_counter() - t0
    if ckpt is not None:
        final_step = int(jax.device_get(executor.state["step"]))
        if train_cfg.save_interval and ckpt.latest_step != final_step:
            ckpt.save(final_step, jax.device_get(executor.state),
                      force=True, layout=ckpt_layout)
        ckpt.wait()
        ckpt.close()
    if train_cfg.trace:
        tracer.finalize()
    metrics_logger.close()
    tokens = (consumed - start_consumed) * train_cfg.seq_length
    return TrainResult(state=executor.state, losses=losses,
                       tokens_per_sec=tokens / max(dt, 1e-9),
                       step_time_ms=dt / max(
                           train_cfg.train_iters - start_step, 1) * 1e3,
                       consumed_samples=consumed)
