"""Optimizer construction (optax).

Parity with /root/reference/megatron/core/optimizer/__init__.py:431
(get_megatron_optimizer) + optimizer.py (Float16Optimizer etc.) +
optimizer_param_scheduler.py (warmup + cosine/linear decay) + clip_grads.py.

TPU-native notes: fp16 loss-scaling machinery is unnecessary (bf16 training
is the norm on TPU — master params fp32, compute bf16, no dynamic scaler);
ZeRO-1 state sharding is obtained by sharding optimizer-state pytrees with
the same logical rules as params plus dp over the 'embed' axis (reference
distrib_optimizer.py:80 semantics) — see training/train.py.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax

from megatronapp_tpu.config.training_config import OptimizerConfig


def lr_schedule(cfg: OptimizerConfig, train_iters: int) -> optax.Schedule:
    decay_iters = cfg.lr_decay_iters or train_iters
    warmup = cfg.lr_warmup_iters

    def sched(step):
        step = jnp.asarray(step, jnp.float32)
        warm = cfg.lr * step / jnp.maximum(warmup, 1)
        frac = jnp.clip((step - warmup) / jnp.maximum(decay_iters - warmup, 1),
                        0.0, 1.0)
        if cfg.lr_decay_style == "cosine":
            decay = cfg.min_lr + 0.5 * (cfg.lr - cfg.min_lr) * (
                1.0 + jnp.cos(jnp.pi * frac))
        elif cfg.lr_decay_style == "linear":
            decay = cfg.lr + (cfg.min_lr - cfg.lr) * frac
        else:
            decay = jnp.asarray(cfg.lr)
        return jnp.where(step < warmup, warm, decay)

    return sched


# Leaf-name suffixes/names exempt from weight decay: biases, norm scales,
# and Mamba's per-channel state params.  Matching by NAME, not ndim: block
# params are stacked with leading layers/stage axes (init_block_params), so
# semantically-1-D leaves (ln scales, biases) can have ndim > 1.
_NO_DECAY_SUFFIXES = ("_bias", "_scale")
_NO_DECAY_NAMES = frozenset({"A_log", "D"})


def _weight_decay_mask(params):
    """No decay for biases and norm params — reference get_param_groups
    (optimizer/__init__.py) no_weight_decay_cond default."""
    import jax.tree_util as jtu

    def decay(path, p):
        name = next((k.key for k in reversed(path)
                     if isinstance(k, jtu.DictKey)), "")
        if name.endswith(_NO_DECAY_SUFFIXES) or name in _NO_DECAY_NAMES:
            return False
        return p.ndim > 1

    return jtu.tree_map_with_path(decay, params)


def get_optimizer(cfg: OptimizerConfig, train_iters: int,
                  schedule: Optional[optax.Schedule] = None,
                  distributed: bool = False):
    """distributed=True returns the ZeRO-1 DistributedOptimizer wrapper
    (training/distributed_optimizer.py): same optax-transform arithmetic,
    dict-shaped state whose m/v/master leaves setup_train_state shards
    over dp, mixed-precision state dtypes from cfg. The plain chain below
    is the replicated baseline (and what non-ZeRO paths — FBD, tools,
    model families — keep using)."""
    if distributed:
        from megatronapp_tpu.training.distributed_optimizer import (
            DistributedOptimizer,
        )
        return DistributedOptimizer(cfg, train_iters, schedule=schedule)
    # The mixed-precision state knobs only exist on the ZeRO-1 layout;
    # the plain chain stores fp32 unconditionally. Refuse rather than
    # silently train with a different precision than the config claims
    # (the CLI validates the same constraint at parse time — this guard
    # covers programmatic OptimizerConfig construction).
    low = [n for n, v in (("exp_avg_dtype", cfg.exp_avg_dtype),
                          ("exp_avg_sq_dtype", cfg.exp_avg_sq_dtype),
                          ("main_params_dtype", cfg.main_params_dtype))
           if str(v).lower() not in ("fp32", "float32")]
    if low:
        raise ValueError(
            f"OptimizerConfig {', '.join(low)} != fp32 requires the "
            "ZeRO-1 distributed-optimizer wrapper, but this code path "
            "builds the replicated optax chain (plain DP, FSDP, FBD, or "
            "a direct get_optimizer(distributed=False) call), which "
            "stores fp32 state only — use fp32 state dtypes here")
    sched = schedule or lr_schedule(cfg, train_iters)
    chain = []
    if cfg.clip_grad:
        chain.append(optax.clip_by_global_norm(cfg.clip_grad))
    if cfg.optimizer == "adam":
        chain.append(optax.scale_by_adam(
            b1=cfg.adam_beta1, b2=cfg.adam_beta2, eps=cfg.adam_eps))
        if cfg.weight_decay:
            chain.append(optax.add_decayed_weights(
                cfg.weight_decay, mask=_weight_decay_mask))
    elif cfg.optimizer == "sgd":
        chain.append(optax.trace(decay=cfg.sgd_momentum))
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer}")
    chain.append(optax.scale_by_learning_rate(sched))
    return optax.chain(*chain)


def global_grad_norm(grads) -> jnp.ndarray:
    return optax.global_norm(grads)
