"""Checkpoint save/load (Orbax/tensorstore-backed, async, reshardable).

Parity with /root/reference/megatron/training/checkpointing.py:315
(save_checkpoint) / :1247 (load_checkpoint) and core/dist_checkpointing/
(sharded state dicts, async save via strategies/async_utils.py, tensorstore
strategy). On TPU, Orbax provides the same capability set natively: arrays
are saved with their shardings, restore reshards to the *current* mesh (the
reference's strategies/resharding.py TP/PP-change path), and AsyncCheckpointer
overlaps writes with training (reference --async-save).
"""

from __future__ import annotations

import json
import logging
import os
import time
import zipfile
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from megatronapp_tpu.utils import chaos

logger = logging.getLogger("megatronapp_tpu.checkpointing")


def _any_process_failed(local_fail: bool) -> bool:
    """Cluster-agreed failure flag (True when ANY process failed).

    Orbax save/restore are collectives under multi-host: a rank that
    retries (or walks back to a previous step) ALONE enters a barrier
    no other rank will join and wedges the job — the same invariant as
    the layout consistency check below and DistSignalHandler.should_exit.
    Every retry/walk-back decision therefore all-gathers the local
    failure flag first, so the ranks move to the next attempt together
    (a rank whose own attempt succeeded discards it and rejoins).
    Thin module-level wrapper over signals.any_process_flag (one shared
    all-gather primitive) so tests can fake the agreement here."""
    from megatronapp_tpu.training.signals import any_process_flag
    return any_process_flag(local_fail)


def _relayout_leaf(x: np.ndarray, target_shape: tuple,
                   saved_layout: Optional[dict] = None,
                   target_layout: Optional[dict] = None) -> np.ndarray:
    """Re-layout one stacked-layer leaf between pipeline layouts.

    Layouts are [L, *rest] (pp=1) or [pp, vpp, L/(pp*vpp), *rest]
    (parallel/pipeline.py reshape_params_for_pipeline: chunk-major
    reshape + stage/chunk swap). Mirrors the reference's resharding.py
    PP-change path.

    With explicit layouts ({'pp', 'vpp'} — checkpoint metadata on the
    saved side, the restoring run's config on the target side) the lead
    split is DERIVED, never guessed, and inconsistencies raise. Without
    them (pre-metadata checkpoints) the split falls back to shape
    enumeration — which resolves a pathological ambiguity (a rest dim
    that equals Lc) by enumeration order."""
    if tuple(x.shape) == target_shape:
        return x

    def lead_ndim(layout):
        return 1 if layout["pp"] * layout.get("vpp", 1) == 1 else 3

    if saved_layout is not None and target_layout is not None:
        ls, lt = lead_ndim(saved_layout), lead_ndim(target_layout)
        lead_s, rest_s = x.shape[:ls], x.shape[ls:]
        lead_t, rest_t = target_shape[:lt], target_shape[lt:]
        if ls == 3 and tuple(lead_s[:2]) != (saved_layout["pp"],
                                             saved_layout.get("vpp", 1)):
            raise ValueError(
                f"checkpoint leaf {x.shape} does not lead with the saved "
                f"layout (pp={saved_layout['pp']}, "
                f"vpp={saved_layout.get('vpp', 1)})")
        if lt == 3 and tuple(lead_t[:2]) != (target_layout["pp"],
                                             target_layout.get("vpp", 1)):
            raise ValueError(
                f"target leaf {target_shape} does not lead with the "
                f"current layout (pp={target_layout['pp']}, "
                f"vpp={target_layout.get('vpp', 1)})")
        if (tuple(rest_s) != tuple(rest_t) or
                int(np.prod(lead_s)) != int(np.prod(lead_t))):
            raise ValueError(
                f"cannot relayout checkpoint leaf {x.shape} -> "
                f"{target_shape} under layouts {saved_layout} -> "
                f"{target_layout}: model geometry differs")
        L = int(np.prod(lead_s))
        if ls == 3:                   # [pp, vpp, Lc] → [L]
            x = np.swapaxes(x, 0, 1).reshape((L,) + tuple(rest_s))
        if lt == 3:                   # [L] → [pp, vpp, Lc]
            pp, vpp, lc = lead_t
            x = np.swapaxes(
                x.reshape((vpp, pp, lc) + tuple(rest_s)), 0, 1)
        return np.ascontiguousarray(x)

    # Shape-driven fallback for checkpoints saved before layout metadata
    # existed: a layer-stack leaf leads with [L] or [pp, vpp, Lc];
    # enumerate the split (a greedy common-suffix match would eat an
    # equal Lc).
    for ls in (1, 3):
        for lt in (1, 3):
            lead_s, rest_s = x.shape[:ls], x.shape[ls:]
            lead_t, rest_t = target_shape[:lt], target_shape[lt:]
            if (x.ndim - ls == len(target_shape) - lt and
                    tuple(rest_s) == tuple(rest_t) and
                    len(lead_s) == ls and len(lead_t) == lt and
                    int(np.prod(lead_s)) == int(np.prod(lead_t))):
                L = int(np.prod(lead_s))
                if ls == 3:                   # [pp, vpp, Lc] → [L]
                    x = np.swapaxes(x, 0, 1).reshape((L,) + tuple(rest_s))
                if lt == 3:                   # [L] → [pp, vpp, Lc]
                    pp, vpp, lc = lead_t
                    x = np.swapaxes(
                        x.reshape((vpp, pp, lc) + tuple(rest_s)), 0, 1)
                return np.ascontiguousarray(x)
    raise ValueError(
        f"cannot relayout checkpoint leaf {x.shape} -> {target_shape}: "
        "not a pipeline layout change (model geometry differs?)")


class CheckpointManager:
    """Thin wrapper over ocp.CheckpointManager.

    save() is async by default (writes overlap next steps); wait() finalizes
    — the analogue of maybe_finalize_async_save (training.py:884).
    """

    def __init__(self, directory: str, save_interval: Optional[int] = None,
                 max_to_keep: int = 3, async_save: bool = True,
                 save_retries: int = 2, retry_backoff_s: float = 0.5):
        directory = os.path.abspath(directory)
        os.makedirs(directory, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            save_interval_steps=save_interval or 1,
            max_to_keep=max_to_keep,
            enable_async_checkpointing=async_save,
        )
        self._mngr = ocp.CheckpointManager(directory, options=options)
        self._layout_path = os.path.join(directory, "layout.json")
        self.save_retries = save_retries
        self.retry_backoff_s = retry_backoff_s

    def save(self, step: int, state: Any, force: bool = False,
             layout: Optional[dict] = None) -> bool:
        """layout: the run's pipeline layout ({'pp', 'vpp'}, optionally
        'num_layers') — persisted once per run directory so cross-layout
        restores derive the stacked-leaf split from metadata instead of
        shape guessing (reference resharding.py records the source
        parallelism the same way). A run directory holds one layout."""
        if layout is not None:
            # The consistency check runs on EVERY process: if only rank 0
            # raised, the other ranks would enter the collective save and
            # hang waiting for it (multi-host checkpoint dirs are shared
            # filesystems, so each rank can read layout.json itself). Only
            # the layout.json WRITE stays on process 0.
            existing = self._read_layout()
            if existing is not None and existing != dict(layout):
                raise ValueError(
                    f"checkpoint dir {self._mngr.directory} was saved "
                    f"with layout {existing}; refusing to mix in "
                    f"{dict(layout)} — use a fresh --save-dir per layout")
            if existing is None and jax.process_index() == 0:
                tmp = self._layout_path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(dict(layout), f)
                os.replace(tmp, self._layout_path)
        # Bounded retry with backoff: a transient write failure (flaky
        # shared filesystem, a surfaced async-save error from a previous
        # step) must not kill a multi-hour run when the next attempt
        # would succeed. Persistent failures still raise after the last
        # attempt — silently dropping checkpoints would be worse. The
        # retry decision is agreed across processes (_any_process_failed)
        # and an agreed retry overwrites (force=True): a rank whose own
        # attempt succeeded still holds a possibly-partial collective
        # step and must rewrite it with the others.
        last_err = None
        retrying = False
        for attempt in range(self.save_retries + 1):
            try:
                chaos.fire("checkpoint-save")
                if retrying and step in self._mngr.all_steps():
                    # This rank's previous attempt landed (another
                    # rank's failed): the collective step is suspect —
                    # drop it so the rewrite isn't refused (orbax
                    # force=True does not overwrite on 0.7.x). Settle
                    # the async finalize first: deleting a step whose
                    # save is still in flight kills the finalize thread
                    # and poisons the next wait().
                    try:
                        self._mngr.wait_until_finished()
                    except Exception:  # noqa: BLE001 — it failed anyway
                        pass
                    self._mngr.delete(step)
                result = self._mngr.save(
                    step, args=ocp.args.StandardSave(state),
                    force=force or retrying)
                err = None
            except Exception as e:  # noqa: BLE001 — retried, then re-raised
                result, err = None, e
            if not _any_process_failed(err is not None):
                return result
            last_err = err or last_err
            if attempt >= self.save_retries:
                break
            retrying = True
            delay = self.retry_backoff_s * (2 ** attempt)
            logger.warning(
                "checkpoint save at step %d failed%s; retry %d/%d in "
                "%.2fs", step,
                (f" ({type(err).__name__}: {err})" if err is not None
                 else " on another process"),
                attempt + 1, self.save_retries, delay)
            time.sleep(delay)
        if last_err is not None:
            raise last_err
        raise RuntimeError(
            f"checkpoint save at step {step} failed on another process "
            f"after {self.save_retries + 1} attempts")

    def _read_layout(self) -> Optional[dict]:
        if not os.path.exists(self._layout_path):
            return None
        with open(self._layout_path) as f:
            return json.load(f)

    def restore(self, state_struct: Any, step: Optional[int] = None,
                layout: Optional[dict] = None, fallback: bool = True) -> Any:
        """Restore into the shardings of `state_struct`.

        Mesh-only layout changes (tp/dp/fsdp degree) reshard natively:
        arrays keep their shapes and Orbax redistributes into the new
        shardings. Pipeline layout changes (pp/vpp degree) additionally
        change the stacked-layer leaf SHAPES ([L, ...] ↔ [pp, vpp, Lc,
        ...], models/gpt.py init layout) — the reference's
        dist_checkpointing/strategies/resharding.py TP/PP-change path.
        When shapes mismatch, leaves are restored in their saved shapes,
        relayouted host-side (metadata-driven when the saved dir has a
        layout.json and the caller passes its own `layout`; shape-driven
        fallback otherwise — see _relayout_leaf), and device_put into
        the target shardings.

        Corrupt/partial-step fallback (ISSUE 6): with `step=None` and
        `fallback=True`, a step that fails to restore (truncated array
        files from a crash mid-write, a half-deleted dir) is logged and
        skipped, walking BACK to the previous saved step instead of
        killing the resume — a preempted run restarts from the freshest
        intact checkpoint. An explicit `step` restores exactly that step
        (no walk-back). Raises the last error only when every saved step
        fails."""
        if step is not None:
            return self._restore_at(step, state_struct, layout)
        steps = sorted(self._mngr.all_steps(), reverse=True)
        if not steps:
            return None
        last_err: Optional[Exception] = None
        for s in steps:
            try:
                out = self._restore_at(s, state_struct, layout)
                err = None
            except Exception as e:  # noqa: BLE001 — log + walk back
                if not fallback:
                    raise
                out, err = None, e
            # Walk-back is agreed across processes: restore is a
            # collective, so when ANY rank fails the step, every rank
            # discards it and moves to the previous step together (one
            # rank walking back alone would deadlock the others).
            if not _any_process_failed(err is not None):
                return out
            last_err = err or last_err
            logger.warning(
                "checkpoint step %d failed to restore%s; falling back "
                "to the previous saved step", s,
                (f" ({type(err).__name__}: {err})" if err is not None
                 else " on another process"))
        if last_err is not None:
            raise last_err
        raise RuntimeError(
            "every saved checkpoint step failed to restore on some "
            "process")

    def _restore_at(self, step: int, state_struct: Any,
                    layout: Optional[dict] = None) -> Any:
        abstract = jax.tree.map(
            lambda x: (ocp.utils.to_shape_dtype_struct(x)
                       if hasattr(x, "dtype") else x),
            state_struct)
        meta = self._mngr.item_metadata(step)
        if meta is None:
            # A manager that has not saved in this process does not know
            # the item handler yet; read the tree metadata directly.
            with ocp.StandardCheckpointer() as ck:
                meta = ck.metadata(os.path.join(
                    self._mngr.directory, str(step), "default"))
            # Newer orbax wraps the tree (CheckpointMetadata
            # .item_metadata); 0.7.x returns the tree itself.
            meta = getattr(meta, "item_metadata", meta)
        # Same version split for the manager path: newer orbax returns
        # an object carrying .tree, 0.7.x the metadata tree directly.
        saved_tree = getattr(meta, "tree", meta)
        # The metadata tree flattens containers differently (optax
        # namedtuples become lists), but leaf ORDER is isomorphic to the
        # target structure — compare/rebuild leaf-wise on the target
        # treedef.
        target_leaves, treedef = jax.tree.flatten(abstract)
        saved_leaves = jax.tree.leaves(saved_tree)
        if len(saved_leaves) != len(target_leaves):
            # Structural change (different model/optimizer): let the
            # plain restore try, but wrap its failure with the one
            # migration a user is likely to hit — the ZeRO-1 optimizer
            # state layout (dict {count, mu, nu[, master]}) differs from
            # the optax chain tuples older checkpoints hold.
            try:
                return self._mngr.restore(
                    step, args=ocp.args.StandardRestore(abstract))
            except Exception as e:
                raise RuntimeError(
                    f"checkpoint step {step} holds a different state "
                    f"STRUCTURE ({len(saved_leaves)} leaves saved, "
                    f"{len(target_leaves)} expected). If this run dir "
                    "predates the ZeRO-1 distributed optimizer, the "
                    "opt_state layout changed — resume with "
                    "--no-use-distributed-optimizer to match the old "
                    "layout, or start a fresh --save dir") from e
        mismatch = any(
            hasattr(t, "shape") and tuple(s.shape) != tuple(t.shape)
            for s, t in zip(saved_leaves, target_leaves))
        if not mismatch:
            return self._mngr.restore(
                step, args=ocp.args.StandardRestore(abstract))
        # Cross-pipeline-layout restore. Shape-matching leaves restore
        # straight into their target shardings (native parallel
        # resharding); only mismatched leaves take the host relayout
        # path, restored REPLICATED on the target mesh (explicit
        # sharding — file-derived shardings are unsafe on a different
        # topology, and replicated arrays stay fully addressable under
        # multi-host).
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = next(t.sharding.mesh for t in target_leaves
                    if isinstance(getattr(t, "sharding", None),
                                  NamedSharding))
        replicated = NamedSharding(mesh, PartitionSpec())

        def _mismatched(s, t):
            return (hasattr(t, "shape") and
                    tuple(s.shape) != tuple(t.shape))

        saved_abstract = jax.tree.unflatten(treedef, [
            (jax.ShapeDtypeStruct(tuple(s.shape), t.dtype,
                                  sharding=replicated)
             if _mismatched(s, t) else t)
            for s, t in zip(saved_leaves, target_leaves)])
        restored = self._mngr.restore(
            step, args=ocp.args.StandardRestore(saved_abstract))
        saved_layout = self._read_layout()
        out_leaves = []
        for s, t, r in zip(saved_leaves, target_leaves,
                           jax.tree.leaves(restored)):
            if _mismatched(s, t):
                r = jax.device_put(
                    _relayout_leaf(np.asarray(jax.device_get(r)),
                                   tuple(t.shape),
                                   saved_layout=saved_layout,
                                   target_layout=layout),
                    t.sharding)
            out_leaves.append(r)
        return jax.tree.unflatten(treedef, out_leaves)

    @property
    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def wait(self):
        self._mngr.wait_until_finished()

    def close(self):
        self._mngr.close()


class LocalCheckpointManager:
    """Fast non-persistent local checkpoints (reference
    --non-persistent-ckpt-type local, training.py:700-727:
    LocalCheckpointManager + CliqueReplicationStrategy).

    Latest-only flat .npz with atomic rename: cheap enough to save every
    few steps for fast node-failure restarts, independent of the durable
    Orbax checkpoints. Multi-host replication (the clique strategy) maps to
    each process writing its own file; a restarted process can read any
    clique member's copy over the shared/local filesystem.
    """

    # npz read failures a truncated/partial file can produce (a crash
    # mid-save leaves a short zip; a crash mid-rename can leave either).
    _CORRUPT_ERRS = (OSError, ValueError, KeyError, EOFError,
                     zipfile.BadZipFile)

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._path = os.path.join(
            self.directory, f"local_ckpt_p{jax.process_index()}.npz")
        # A crash between np.savez and os.replace leaves a .tmp behind;
        # it is by definition incomplete — drop it so it can never be
        # mistaken for a checkpoint.
        for leftover in (self._path + ".tmp", self._path + ".tmp.npz"):
            if os.path.exists(leftover):
                logger.warning(
                    "local checkpoint: dropping leftover partial file %s",
                    leftover)
                os.unlink(leftover)

    @staticmethod
    def _to_serializable(x: np.ndarray) -> Tuple[np.ndarray, Optional[str]]:
        """np.savez silently degrades extension dtypes (ml_dtypes
        bfloat16 & friends, numpy kind 'V') to raw void on load — the
        bytes survive but the dtype is lost and jax.device_put rejects
        the result. Store such leaves as a same-width uint VIEW plus the
        dtype name in a sidecar (applied back on restore)."""
        if x.dtype.kind != "V":
            return x, None
        uint = np.dtype(f"u{x.dtype.itemsize}")
        return x.view(uint), x.dtype.name

    def save(self, step: int, state: Any, extra: Optional[Dict] = None):
        """extra: small JSON-able side state (consumed samples, rerun
        state machine) persisted inside the npz alongside the leaves."""
        chaos.fire("local-checkpoint-save")
        leaves, treedef = jax.tree.flatten(jax.device_get(state))
        payload, dtypes = {}, {}
        for i, x in enumerate(leaves):
            arr, name = self._to_serializable(np.asarray(x))
            payload[f"leaf_{i}"] = arr
            if name is not None:
                dtypes[str(i)] = name
        payload["__step__"] = np.asarray(step)
        if dtypes:
            payload["__dtypes__"] = np.frombuffer(
                json.dumps(dtypes).encode(), np.uint8)
        if extra is not None:
            payload["__extra__"] = np.frombuffer(
                json.dumps(extra).encode(), np.uint8)
        tmp = self._path + ".tmp"
        np.savez(tmp, **payload)
        # np.savez appends .npz to names without it.
        os.replace(tmp + ".npz" if os.path.exists(tmp + ".npz") else tmp,
                   self._path)

    @property
    def latest_step(self) -> Optional[int]:
        if not os.path.exists(self._path):
            return None
        try:
            with np.load(self._path) as z:
                return int(z["__step__"])
        except self._CORRUPT_ERRS as e:
            logger.warning(
                "local checkpoint %s is corrupt/partial (%s: %s); "
                "ignoring it", self._path, type(e).__name__, e)
            return None

    def restore(self, state_struct: Any,
                return_extra: bool = False) -> Optional[Any]:
        """Restore into the structure (and shardings) of state_struct.
        A corrupt/partial file (truncated write, interrupted rename) is
        logged and treated as missing — the caller falls back to the
        durable checkpoint instead of crashing the restart path."""
        if not os.path.exists(self._path):
            return None
        leaves, treedef = jax.tree.flatten(state_struct)
        try:
            with np.load(self._path) as z:
                new_leaves = [z[f"leaf_{i}"] for i in range(len(leaves))]
                dtypes = (json.loads(bytes(z["__dtypes__"]))
                          if "__dtypes__" in z else {})
                extra = (json.loads(bytes(z["__extra__"]))
                         if "__extra__" in z else None)
        except self._CORRUPT_ERRS as e:
            logger.warning(
                "local checkpoint %s failed to load (%s: %s); "
                "ignoring it", self._path, type(e).__name__, e)
            return None
        try:
            for i, name in dtypes.items():
                new_leaves[int(i)] = new_leaves[int(i)].view(np.dtype(name))
            restored = jax.tree.unflatten(treedef, new_leaves)
            leaf_shardings = [getattr(x, "sharding", None) for x in leaves]
            if all(s is not None for s in leaf_shardings):
                restored = jax.device_put(
                    restored, jax.tree.unflatten(treedef, leaf_shardings))
        except Exception as e:  # noqa: BLE001 — stale layout → durable path
            # A local checkpoint from a different parallel layout (leaf
            # shapes/shardings no longer match state_struct) is STALE,
            # not fatal: the durable restore path relayouts natively —
            # degrade to it instead of killing the restart.
            logger.warning(
                "local checkpoint %s incompatible with the current "
                "state layout (%s: %s); ignoring it", self._path,
                type(e).__name__, e)
            return None
        return (restored, extra) if return_extra else restored


# ---- resumable side-state (consumed samples, rerun state machine) --------

def write_side_state(directory: str, step: int, payload: Dict) -> None:
    """Persist JSON side-state next to a durable checkpoint step (the
    model/optimizer pytree lives in Orbax; the HOST-side training
    bookkeeping — consumed samples = the data-stream position including
    any _RowBuffer carry-over, rerun-state-machine state_dict — rides in
    a per-step sidecar so a resume replays the exact stream position and
    fault-classification statistics). Rank-0 write, atomic rename."""
    if jax.process_index() != 0:
        return
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"side_state_{step}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"step": step, **payload}, f)
    os.replace(tmp, path)
    # GC sidecars whose checkpoint step is gone (Orbax prunes step dirs
    # to max_to_keep; without this a long run leaks one JSON per save).
    # The just-written step is always kept — its (async) step dir may
    # not exist yet.
    import glob
    import re
    for old in glob.glob(os.path.join(directory, "side_state_*.json")):
        m = re.fullmatch(r"side_state_(\d+)\.json", os.path.basename(old))
        if m and int(m.group(1)) != step and \
                not os.path.isdir(os.path.join(directory, m.group(1))):
            try:
                os.unlink(old)
            except OSError:
                pass


def read_side_state(directory: str, step: int) -> Optional[Dict]:
    """Side-state for a checkpoint step; None when absent or unreadable
    (pre-side-state checkpoints resume through the derivation fallback
    in training/train.py)."""
    path = os.path.join(directory, f"side_state_{step}.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        logger.warning("side state %s unreadable (%s: %s); ignoring",
                       path, type(e).__name__, e)
        return None
