"""Checkpoint save/load (Orbax/tensorstore-backed, async, reshardable).

Parity with /root/reference/megatron/training/checkpointing.py:315
(save_checkpoint) / :1247 (load_checkpoint) and core/dist_checkpointing/
(sharded state dicts, async save via strategies/async_utils.py, tensorstore
strategy). On TPU, Orbax provides the same capability set natively: arrays
are saved with their shardings, restore reshards to the *current* mesh (the
reference's strategies/resharding.py TP/PP-change path), and AsyncCheckpointer
overlaps writes with training (reference --async-save).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp


def _relayout_leaf(x: np.ndarray, target_shape: tuple,
                   saved_layout: Optional[dict] = None,
                   target_layout: Optional[dict] = None) -> np.ndarray:
    """Re-layout one stacked-layer leaf between pipeline layouts.

    Layouts are [L, *rest] (pp=1) or [pp, vpp, L/(pp*vpp), *rest]
    (parallel/pipeline.py reshape_params_for_pipeline: chunk-major
    reshape + stage/chunk swap). Mirrors the reference's resharding.py
    PP-change path.

    With explicit layouts ({'pp', 'vpp'} — checkpoint metadata on the
    saved side, the restoring run's config on the target side) the lead
    split is DERIVED, never guessed, and inconsistencies raise. Without
    them (pre-metadata checkpoints) the split falls back to shape
    enumeration — which resolves a pathological ambiguity (a rest dim
    that equals Lc) by enumeration order."""
    if tuple(x.shape) == target_shape:
        return x

    def lead_ndim(layout):
        return 1 if layout["pp"] * layout.get("vpp", 1) == 1 else 3

    if saved_layout is not None and target_layout is not None:
        ls, lt = lead_ndim(saved_layout), lead_ndim(target_layout)
        lead_s, rest_s = x.shape[:ls], x.shape[ls:]
        lead_t, rest_t = target_shape[:lt], target_shape[lt:]
        if ls == 3 and tuple(lead_s[:2]) != (saved_layout["pp"],
                                             saved_layout.get("vpp", 1)):
            raise ValueError(
                f"checkpoint leaf {x.shape} does not lead with the saved "
                f"layout (pp={saved_layout['pp']}, "
                f"vpp={saved_layout.get('vpp', 1)})")
        if lt == 3 and tuple(lead_t[:2]) != (target_layout["pp"],
                                             target_layout.get("vpp", 1)):
            raise ValueError(
                f"target leaf {target_shape} does not lead with the "
                f"current layout (pp={target_layout['pp']}, "
                f"vpp={target_layout.get('vpp', 1)})")
        if (tuple(rest_s) != tuple(rest_t) or
                int(np.prod(lead_s)) != int(np.prod(lead_t))):
            raise ValueError(
                f"cannot relayout checkpoint leaf {x.shape} -> "
                f"{target_shape} under layouts {saved_layout} -> "
                f"{target_layout}: model geometry differs")
        L = int(np.prod(lead_s))
        if ls == 3:                   # [pp, vpp, Lc] → [L]
            x = np.swapaxes(x, 0, 1).reshape((L,) + tuple(rest_s))
        if lt == 3:                   # [L] → [pp, vpp, Lc]
            pp, vpp, lc = lead_t
            x = np.swapaxes(
                x.reshape((vpp, pp, lc) + tuple(rest_s)), 0, 1)
        return np.ascontiguousarray(x)

    # Shape-driven fallback for checkpoints saved before layout metadata
    # existed: a layer-stack leaf leads with [L] or [pp, vpp, Lc];
    # enumerate the split (a greedy common-suffix match would eat an
    # equal Lc).
    for ls in (1, 3):
        for lt in (1, 3):
            lead_s, rest_s = x.shape[:ls], x.shape[ls:]
            lead_t, rest_t = target_shape[:lt], target_shape[lt:]
            if (x.ndim - ls == len(target_shape) - lt and
                    tuple(rest_s) == tuple(rest_t) and
                    len(lead_s) == ls and len(lead_t) == lt and
                    int(np.prod(lead_s)) == int(np.prod(lead_t))):
                L = int(np.prod(lead_s))
                if ls == 3:                   # [pp, vpp, Lc] → [L]
                    x = np.swapaxes(x, 0, 1).reshape((L,) + tuple(rest_s))
                if lt == 3:                   # [L] → [pp, vpp, Lc]
                    pp, vpp, lc = lead_t
                    x = np.swapaxes(
                        x.reshape((vpp, pp, lc) + tuple(rest_s)), 0, 1)
                return np.ascontiguousarray(x)
    raise ValueError(
        f"cannot relayout checkpoint leaf {x.shape} -> {target_shape}: "
        "not a pipeline layout change (model geometry differs?)")


class CheckpointManager:
    """Thin wrapper over ocp.CheckpointManager.

    save() is async by default (writes overlap next steps); wait() finalizes
    — the analogue of maybe_finalize_async_save (training.py:884).
    """

    def __init__(self, directory: str, save_interval: Optional[int] = None,
                 max_to_keep: int = 3, async_save: bool = True):
        directory = os.path.abspath(directory)
        os.makedirs(directory, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            save_interval_steps=save_interval or 1,
            max_to_keep=max_to_keep,
            enable_async_checkpointing=async_save,
        )
        self._mngr = ocp.CheckpointManager(directory, options=options)
        self._layout_path = os.path.join(directory, "layout.json")

    def save(self, step: int, state: Any, force: bool = False,
             layout: Optional[dict] = None) -> bool:
        """layout: the run's pipeline layout ({'pp', 'vpp'}, optionally
        'num_layers') — persisted once per run directory so cross-layout
        restores derive the stacked-leaf split from metadata instead of
        shape guessing (reference resharding.py records the source
        parallelism the same way). A run directory holds one layout."""
        if layout is not None:
            import json
            # The consistency check runs on EVERY process: if only rank 0
            # raised, the other ranks would enter the collective save and
            # hang waiting for it (multi-host checkpoint dirs are shared
            # filesystems, so each rank can read layout.json itself). Only
            # the layout.json WRITE stays on process 0.
            existing = self._read_layout()
            if existing is not None and existing != dict(layout):
                raise ValueError(
                    f"checkpoint dir {self._mngr.directory} was saved "
                    f"with layout {existing}; refusing to mix in "
                    f"{dict(layout)} — use a fresh --save-dir per layout")
            if existing is None and jax.process_index() == 0:
                tmp = self._layout_path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(dict(layout), f)
                os.replace(tmp, self._layout_path)
        return self._mngr.save(
            step, args=ocp.args.StandardSave(state), force=force)

    def _read_layout(self) -> Optional[dict]:
        if not os.path.exists(self._layout_path):
            return None
        import json
        with open(self._layout_path) as f:
            return json.load(f)

    def restore(self, state_struct: Any, step: Optional[int] = None,
                layout: Optional[dict] = None) -> Any:
        """Restore into the shardings of `state_struct`.

        Mesh-only layout changes (tp/dp/fsdp degree) reshard natively:
        arrays keep their shapes and Orbax redistributes into the new
        shardings. Pipeline layout changes (pp/vpp degree) additionally
        change the stacked-layer leaf SHAPES ([L, ...] ↔ [pp, vpp, Lc,
        ...], models/gpt.py init layout) — the reference's
        dist_checkpointing/strategies/resharding.py TP/PP-change path.
        When shapes mismatch, leaves are restored in their saved shapes,
        relayouted host-side (metadata-driven when the saved dir has a
        layout.json and the caller passes its own `layout`; shape-driven
        fallback otherwise — see _relayout_leaf), and device_put into
        the target shardings."""
        if step is None:
            step = self._mngr.latest_step()
        if step is None:
            return None
        abstract = jax.tree.map(
            lambda x: (ocp.utils.to_shape_dtype_struct(x)
                       if hasattr(x, "dtype") else x),
            state_struct)
        meta = self._mngr.item_metadata(step)
        if meta is None:
            # A manager that has not saved in this process does not know
            # the item handler yet; read the tree metadata directly.
            with ocp.StandardCheckpointer() as ck:
                meta = ck.metadata(os.path.join(
                    self._mngr.directory, str(step), "default"
                )).item_metadata
        # The metadata tree flattens containers differently (optax
        # namedtuples become lists), but leaf ORDER is isomorphic to the
        # target structure — compare/rebuild leaf-wise on the target
        # treedef.
        target_leaves, treedef = jax.tree.flatten(abstract)
        saved_leaves = jax.tree.leaves(meta.tree)
        if len(saved_leaves) != len(target_leaves):
            # Structural change (different model/optimizer): let the
            # plain restore produce its descriptive error.
            return self._mngr.restore(
                step, args=ocp.args.StandardRestore(abstract))
        mismatch = any(
            hasattr(t, "shape") and tuple(s.shape) != tuple(t.shape)
            for s, t in zip(saved_leaves, target_leaves))
        if not mismatch:
            return self._mngr.restore(
                step, args=ocp.args.StandardRestore(abstract))
        # Cross-pipeline-layout restore. Shape-matching leaves restore
        # straight into their target shardings (native parallel
        # resharding); only mismatched leaves take the host relayout
        # path, restored REPLICATED on the target mesh (explicit
        # sharding — file-derived shardings are unsafe on a different
        # topology, and replicated arrays stay fully addressable under
        # multi-host).
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = next(t.sharding.mesh for t in target_leaves
                    if isinstance(getattr(t, "sharding", None),
                                  NamedSharding))
        replicated = NamedSharding(mesh, PartitionSpec())

        def _mismatched(s, t):
            return (hasattr(t, "shape") and
                    tuple(s.shape) != tuple(t.shape))

        saved_abstract = jax.tree.unflatten(treedef, [
            (jax.ShapeDtypeStruct(tuple(s.shape), t.dtype,
                                  sharding=replicated)
             if _mismatched(s, t) else t)
            for s, t in zip(saved_leaves, target_leaves)])
        restored = self._mngr.restore(
            step, args=ocp.args.StandardRestore(saved_abstract))
        saved_layout = self._read_layout()
        out_leaves = []
        for s, t, r in zip(saved_leaves, target_leaves,
                           jax.tree.leaves(restored)):
            if _mismatched(s, t):
                r = jax.device_put(
                    _relayout_leaf(np.asarray(jax.device_get(r)),
                                   tuple(t.shape),
                                   saved_layout=saved_layout,
                                   target_layout=layout),
                    t.sharding)
            out_leaves.append(r)
        return jax.tree.unflatten(treedef, out_leaves)

    @property
    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def wait(self):
        self._mngr.wait_until_finished()

    def close(self):
        self._mngr.close()


class LocalCheckpointManager:
    """Fast non-persistent local checkpoints (reference
    --non-persistent-ckpt-type local, training.py:700-727:
    LocalCheckpointManager + CliqueReplicationStrategy).

    Latest-only flat .npz with atomic rename: cheap enough to save every
    few steps for fast node-failure restarts, independent of the durable
    Orbax checkpoints. Multi-host replication (the clique strategy) maps to
    each process writing its own file; a restarted process can read any
    clique member's copy over the shared/local filesystem.
    """

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._path = os.path.join(
            self.directory, f"local_ckpt_p{jax.process_index()}.npz")

    def save(self, step: int, state: Any):
        leaves, treedef = jax.tree.flatten(jax.device_get(state))
        payload = {f"leaf_{i}": np.asarray(x)
                   for i, x in enumerate(leaves)}
        payload["__step__"] = np.asarray(step)
        tmp = self._path + ".tmp"
        np.savez(tmp, **payload)
        # np.savez appends .npz to names without it.
        os.replace(tmp + ".npz" if os.path.exists(tmp + ".npz") else tmp,
                   self._path)

    @property
    def latest_step(self) -> Optional[int]:
        if not os.path.exists(self._path):
            return None
        with np.load(self._path) as z:
            return int(z["__step__"])

    def restore(self, state_struct: Any) -> Optional[Any]:
        """Restore into the structure (and shardings) of state_struct."""
        if not os.path.exists(self._path):
            return None
        leaves, treedef = jax.tree.flatten(state_struct)
        with np.load(self._path) as z:
            new_leaves = [z[f"leaf_{i}"] for i in range(len(leaves))]
        restored = jax.tree.unflatten(treedef, new_leaves)
        leaf_shardings = [getattr(x, "sharding", None) for x in leaves]
        if all(s is not None for s in leaf_shardings):
            restored = jax.device_put(
                restored, jax.tree.unflatten(treedef, leaf_shardings))
        return restored
