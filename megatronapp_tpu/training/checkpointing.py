"""Checkpoint save/load (Orbax/tensorstore-backed, async, reshardable).

Parity with /root/reference/megatron/training/checkpointing.py:315
(save_checkpoint) / :1247 (load_checkpoint) and core/dist_checkpointing/
(sharded state dicts, async save via strategies/async_utils.py, tensorstore
strategy). On TPU, Orbax provides the same capability set natively: arrays
are saved with their shardings, restore reshards to the *current* mesh (the
reference's strategies/resharding.py TP/PP-change path), and AsyncCheckpointer
overlaps writes with training (reference --async-save).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp


class CheckpointManager:
    """Thin wrapper over ocp.CheckpointManager.

    save() is async by default (writes overlap next steps); wait() finalizes
    — the analogue of maybe_finalize_async_save (training.py:884).
    """

    def __init__(self, directory: str, save_interval: Optional[int] = None,
                 max_to_keep: int = 3, async_save: bool = True):
        directory = os.path.abspath(directory)
        os.makedirs(directory, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            save_interval_steps=save_interval or 1,
            max_to_keep=max_to_keep,
            enable_async_checkpointing=async_save,
        )
        self._mngr = ocp.CheckpointManager(directory, options=options)

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        return self._mngr.save(
            step, args=ocp.args.StandardSave(state), force=force)

    def restore(self, state_struct: Any, step: Optional[int] = None) -> Any:
        """Restore into the shardings of `state_struct` (abstract arrays with
        shardings → resharding on layout change comes free)."""
        if step is None:
            step = self._mngr.latest_step()
        if step is None:
            return None
        abstract = jax.tree.map(
            lambda x: (ocp.utils.to_shape_dtype_struct(x)
                       if hasattr(x, "dtype") else x),
            state_struct)
        return self._mngr.restore(
            step, args=ocp.args.StandardRestore(abstract))

    @property
    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def wait(self):
        self._mngr.wait_until_finished()

    def close(self):
        self._mngr.close()


class LocalCheckpointManager:
    """Fast non-persistent local checkpoints (reference
    --non-persistent-ckpt-type local, training.py:700-727:
    LocalCheckpointManager + CliqueReplicationStrategy).

    Latest-only flat .npz with atomic rename: cheap enough to save every
    few steps for fast node-failure restarts, independent of the durable
    Orbax checkpoints. Multi-host replication (the clique strategy) maps to
    each process writing its own file; a restarted process can read any
    clique member's copy over the shared/local filesystem.
    """

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._path = os.path.join(
            self.directory, f"local_ckpt_p{jax.process_index()}.npz")

    def save(self, step: int, state: Any):
        leaves, treedef = jax.tree.flatten(jax.device_get(state))
        payload = {f"leaf_{i}": np.asarray(x)
                   for i, x in enumerate(leaves)}
        payload["__step__"] = np.asarray(step)
        tmp = self._path + ".tmp"
        np.savez(tmp, **payload)
        # np.savez appends .npz to names without it.
        os.replace(tmp + ".npz" if os.path.exists(tmp + ".npz") else tmp,
                   self._path)

    @property
    def latest_step(self) -> Optional[int]:
        if not os.path.exists(self._path):
            return None
        with np.load(self._path) as z:
            return int(z["__step__"])

    def restore(self, state_struct: Any) -> Optional[Any]:
        """Restore into the structure (and shardings) of state_struct."""
        if not os.path.exists(self._path):
            return None
        leaves, treedef = jax.tree.flatten(state_struct)
        with np.load(self._path) as z:
            new_leaves = [z[f"leaf_{i}"] for i in range(len(leaves))]
        restored = jax.tree.unflatten(treedef, new_leaves)
        leaf_shardings = [getattr(x, "sharding", None) for x in leaves]
        if all(s is not None for s in leaf_shardings):
            restored = jax.device_put(
                restored, jax.tree.unflatten(treedef, leaf_shardings))
        return restored
