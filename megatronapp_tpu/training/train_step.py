"""The jitted train step: microbatch grad accumulation + optimizer update.

Parity with /root/reference/megatron/training/training.py:1367 (train_step:
forward_backward_func over microbatches → finalize grads → clip → optimizer
step → skipped-iter bookkeeping). TPU-first: one jit containing a lax.scan
over microbatches; XLA overlaps the dp grad all-reduce with backward compute
(the hand-written bucketing of param_and_grad_buffer.py:93 is subsumed by the
compiler), and the NaN-skip is a lax.cond instead of the fp16 scaler path
(optimizer.py:322).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from megatronapp_tpu.config.training_config import OptimizerConfig
from megatronapp_tpu.parallel.mesh import MeshContext
from megatronapp_tpu.training.optimizer import global_grad_norm, lr_schedule


def batch_shardings(ctx: MeshContext) -> Any:
    """Sharding for batch dicts of [num_micro, global_batch, ...] arrays.

    Returned as a pytree PREFIX (one sharding for the whole dict) so batches
    with model-specific extra fields (BERT's tokentype_ids/is_random, T5's
    enc/dec pairs) shard uniformly over the batch axis. With cp > 1 the
    sequence axis must also shard, which requires rank-3 leaves — the GPT
    field set.
    """
    spec = ctx.batch_spec()
    if ctx.cp > 1:
        sh = NamedSharding(ctx.mesh, P(None, *spec))
        return {"tokens": sh, "labels": sh, "loss_mask": sh,
                "position_ids": sh}
    return NamedSharding(ctx.mesh, P(None, *spec))


def globalize_batch(batch: Any, ctx: MeshContext, shardings=None) -> Any:
    """Host numpy batches → global jax.Arrays for multi-process runs.

    Single-process jit accepts numpy directly; across hosts each process
    holds the SAME deterministic global batch (the mock/data streams are
    seed-identical per rank — reference per-rank loaders yield aligned
    samples), so every device slices its shard out of the local copy
    (jax.make_array_from_callback). No-op when one process."""
    if jax.process_count() == 1:
        return batch
    shardings = shardings if shardings is not None else batch_shardings(ctx)
    is_prefix = not isinstance(shardings, dict)

    def conv(x, sh):
        x = np.asarray(x)   # one host conversion; shards slice from it
        return jax.make_array_from_callback(
            x.shape, sh, lambda idx: x[idx])

    if is_prefix:
        return jax.tree.map(lambda x: conv(x, shardings), batch)
    unmatched = set(batch) - set(shardings)
    if unmatched:
        # Host numpy mixed with global arrays fails far from the cause;
        # refuse loudly (extend batch_shardings' cp>1 field set instead).
        raise ValueError(
            f"globalize_batch: no sharding for batch fields "
            f"{sorted(unmatched)} under cp>1")
    return {k: conv(v, shardings[k]) for k, v in batch.items()}


def make_train_step(
    loss_fn: Callable[[Any, Dict[str, jnp.ndarray]], Tuple[jnp.ndarray, Dict]],
    optimizer,
    opt_cfg: OptimizerConfig,
    ctx: MeshContext,
    state_shardings,
    train_iters: int,
    check_nan: bool = True,
    pipeline: bool = False,
    trace_phases: bool = False,
    donate: bool = True,
    fp8: bool = False,
):
    """loss_fn(params, microbatch_dict) -> (loss, metrics_dict).

    Returns jitted step(state, batch) -> (state, metrics); batch arrays are
    [num_micro, global_batch, seq]. In pipeline mode, loss_fn consumes the
    whole microbatched batch at once (the pipeline schedules microbatches
    internally — parallel/pipeline.py); otherwise a lax.scan accumulates
    grads microbatch by microbatch (reference
    forward_backward_no_pipelining, schedules.py:618).

    fp8 (ISSUE 13): loss_fn additionally accepts fp8= (the delayed-
    scaling amax state, state["fp8"]) and the step differentiates the
    (params, fp8) PAIR — the fp8 half's "gradient" is the updated
    history (parallel/overlap.py fp8 custom_vjps), which accumulates
    with elementwise max / saturation-count sum across microbatches
    (training/fp8.fp8_accumulate), bypasses grad scaling, the grad
    norm, and the optimizer entirely, and lands in state["fp8"]
    directly. A NaN-skipped step keeps the old history (nothing
    observed)."""
    sched = lr_schedule(opt_cfg, train_iters)
    # ZeRO-1 manual update path (--dist-opt-comm ring|bulk): the weight
    # update runs inside one full-manual shard_map with the updated
    # params returned through the overlap.py ring all-gather (ring) or a
    # tiled bulk gather. Default 'gspmd' leaves the collectives to XLA's
    # sharding propagation over the dp-sharded state layout.
    zero1_manual = (getattr(optimizer, "zero1", False)
                    and getattr(optimizer, "shard_state", True)
                    and getattr(opt_cfg, "dist_opt_comm", "gspmd")
                    in ("ring", "bulk")
                    and ctx.dp * ctx.ep > 1
                    and not getattr(ctx, "abstract_collectives", False))
    zero1_plan = None
    if zero1_manual:
        from megatronapp_tpu.training.distributed_optimizer import (
            shard_plan,
        )
        zero1_plan = shard_plan(state_shardings["params"],
                                state_shardings["opt_state"])
    if trace_phases:
        # MegaScan schedule-phase spans (trace/tracer.py): 'forward' spans
        # the loss computation; its custom-VJP mirrors emit the 'backward'
        # span during the gradient pass; 'loss' marks the loss value.
        from megatronapp_tpu.trace.tracer import (
            phase_span_begin, phase_span_end,
        )
        inner_loss = loss_fn

        def loss_fn(params, micro, **kw):  # noqa: F811 — traced wrapper
            # Spans must sit on the params→loss differentiation path so the
            # custom-VJP backward mirrors fire: B 'forward' on params entry
            # (its bwd emits E 'backward' when the last param cotangent
            # leaves), E 'forward' + B 'backward' mirror on the loss.
            params = phase_span_begin(params, "forward", "backward")
            loss, metrics = inner_loss(params, micro, **kw)
            loss = phase_span_end(loss, "forward", "backward")
            loss = phase_span_begin(loss, "loss")
            loss = phase_span_end(loss, "loss")
            return loss, metrics

    if fp8 and pipeline:
        raise ValueError("fp8 does not support the pipeline loss path "
                         "(fp8_ineligible_reason gates this off)")
    if fp8:
        def _fp8_target(pair, micro):
            params, fstate = pair
            return loss_fn(params, micro, fp8=fstate)
        grad_fn = jax.value_and_grad(_fp8_target, has_aux=True)
    else:
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def step(state, batch):
        params = state["params"]
        num_micro = jax.tree.leaves(batch)[0].shape[0]
        fp8_new = None

        if pipeline:
            (loss, aux), grads = grad_fn(params, batch)
        else:
            from megatronapp_tpu.training.fp8 import (
                fp8_accumulate, fp8_zeros_like,
            )

            def accum(carry, micro):
                g_acc, loss_acc, aux_acc = carry
                if fp8:
                    (loss, metrics), (g, g8) = grad_fn(
                        (params, state["fp8"]), micro)
                    gp_acc, f8_acc = g_acc
                    g_acc = (jax.tree.map(
                        lambda a, b: a + b.astype(a.dtype), gp_acc, g),
                        fp8_accumulate(f8_acc, g8))
                else:
                    (loss, metrics), g = grad_fn(params, micro)
                    g_acc = jax.tree.map(
                        lambda a, b: a + b.astype(a.dtype), g_acc, g)
                return (g_acc, loss_acc + loss,
                        jax.tree.map(lambda a, b: a + b, aux_acc,
                                     metrics)), None

            zeros = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
            if fp8:
                zeros = (zeros, fp8_zeros_like(state["fp8"]))
                metrics_struct = jax.eval_shape(
                    lambda: loss_fn(
                        params, jax.tree.map(lambda x: x[0], batch),
                        fp8=state["fp8"])[1])
            else:
                metrics_struct = jax.eval_shape(
                    lambda: loss_fn(params,
                                    jax.tree.map(lambda x: x[0],
                                                 batch))[1])
            aux_zeros = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), metrics_struct)
            (g_sum, loss_sum, aux_sum), _ = jax.lax.scan(
                accum, (zeros, jnp.zeros((), jnp.float32), aux_zeros), batch)

            if fp8:
                g_sum, fp8_new = g_sum
                # Saturation totals are CUMULATIVE in the state (the
                # observations are per-step counts); histories take the
                # step's rolled value.
                from megatronapp_tpu.training.fp8 import fp8_carry_sat
                fp8_new = fp8_carry_sat(state["fp8"], fp8_new)
            inv = 1.0 / num_micro
            grads = jax.tree.map(lambda g: g * inv, g_sum)
            loss = loss_sum * inv
            aux = jax.tree.map(lambda a: a * inv, aux_sum)

        if trace_phases:
            from megatronapp_tpu.trace.tracer import (
                phase_span_begin, phase_span_end,
            )
            grads = phase_span_begin(grads, "allreduce")
        grad_norm = global_grad_norm(grads)
        if trace_phases:
            grad_norm = phase_span_end(grad_norm, "allreduce")
            grads = phase_span_begin(grads, "optimizer")
        finite = jnp.isfinite(loss) & jnp.isfinite(grad_norm)

        def do_update(_):
            if zero1_manual:
                from megatronapp_tpu.training.distributed_optimizer \
                    import manual_apply
                new_params, new_opt = manual_apply(
                    optimizer, grads, state["opt_state"], params,
                    state_shardings, ctx.mesh, zero1_plan,
                    overlap=(opt_cfg.dist_opt_comm == "ring"))
            else:
                updates, new_opt = optimizer.update(
                    grads, state["opt_state"], params)
                if hasattr(optimizer, "apply_updates"):
                    # Master-weight aware (ZeRO-1 mixed precision):
                    # params become the rounded image of the fp32
                    # master shard.
                    new_params = optimizer.apply_updates(params, updates,
                                                         new_opt)
                else:
                    new_params = jax.tree.map(
                        lambda p, u: (p + u.astype(p.dtype)), params,
                        updates)
            if fp8:
                # The accumulated fp8 "gradient" IS the next history
                # (rolled, amaxes in slot 0) — installed directly,
                # never via the optimizer.
                return new_params, new_opt, fp8_new
            return new_params, new_opt

        def skip(_):
            if fp8:
                return params, state["opt_state"], state["fp8"]
            return params, state["opt_state"]

        if check_nan:
            updated = jax.lax.cond(finite, do_update, skip, operand=None)
            skipped = jnp.where(finite, 0, 1).astype(jnp.int32)
        else:
            updated = do_update(None)
            skipped = jnp.zeros((), jnp.int32)
        if fp8:
            new_params, new_opt, new_fp8 = updated
        else:
            new_params, new_opt = updated

        if trace_phases:
            new_params = phase_span_end(new_params, "optimizer")
        new_state = {
            "step": state["step"] + 1,
            "params": new_params,
            "opt_state": new_opt,
        }
        if fp8:
            new_state["fp8"] = new_fp8
        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
            "lr": sched(state["step"]),
            "skipped": skipped,
            **aux,
        }
        return new_state, metrics

    b_sh = batch_shardings(ctx)
    return jax.jit(
        step,
        in_shardings=(state_shardings, b_sh),
        out_shardings=(state_shardings, None),
        donate_argnums=(0,) if donate else (),
    )


def make_eval_step(loss_fn, ctx: MeshContext, state_shardings,
                   pipeline: bool = False, fp8: bool = False):
    """Forward-only loss (reference evaluate(), training.py eval loop).

    pipeline=True: loss_fn consumes the whole microbatched batch (the SPMD
    pipeline schedules internally), matching make_train_step.

    fp8: evaluate through the same fp8 forward as training (the amax
    state is read, never updated — no backward runs here)."""
    b_sh = batch_shardings(ctx)

    def step(state, batch):
        kw = {"fp8": state["fp8"]} if fp8 else {}
        if pipeline:
            loss, _ = loss_fn(state["params"], batch)
            return loss

        def body(acc, micro):
            loss, _ = loss_fn(state["params"], micro, **kw)
            return acc + loss, None
        total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), batch)
        return total / jax.tree.leaves(batch)[0].shape[0]

    return jax.jit(step, in_shardings=(state_shardings, b_sh))
