"""Metrics sinks: JSONL, CSV, TensorBoard.

Parity with the reference's logging fan-out (training_log, training.py:1488
→ tensorboard writers in global_vars.py, wandb_utils.py, one_logger_utils.py
and the throughput progress log :1757): one `MetricsLogger` dispatches each
step's scalars to every configured sink.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Dict, List, Optional


class JsonlSink:
    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", buffering=1)

    def log(self, step: int, metrics: Dict[str, float]):
        # Strict JSON: NaN/Inf are not valid tokens; stringify them so the
        # exact lines that matter for fault diagnosis stay parseable.
        clean = {k: (v if not (isinstance(v, float)
                               and not math.isfinite(v)) else str(v))
                 for k, v in metrics.items()}
        self._f.write(json.dumps(
            {"step": step, "ts": time.time(), **clean}) + "\n")

    def close(self):
        self._f.close()


class TensorBoardSink:
    """Optional (reference --tensorboard-dir)."""

    def __init__(self, log_dir: str, warn=None):
        try:
            from torch.utils.tensorboard import SummaryWriter
            self._w = SummaryWriter(log_dir=log_dir)
        except Exception as e:
            if warn is not None:
                warn(f"tensorboard sink disabled: {type(e).__name__}: {e}")
            self._w = None

    def log(self, step: int, metrics: Dict[str, float]):
        if self._w is None:
            return
        for k, v in metrics.items():
            try:
                self._w.add_scalar(k, float(v), step)
            except (TypeError, ValueError):
                pass

    def close(self):
        if self._w is not None:
            self._w.close()


class WandbSink:
    """Optional (reference training/wandb_utils.py): degrades to a no-op
    with a warning when the wandb package is absent (this image ships
    without it — the sink exists for deployments that have it)."""

    def __init__(self, project: str, name: Optional[str] = None,
                 config: Optional[dict] = None, warn=None):
        try:
            import wandb
            self._run = wandb.init(project=project, name=name,
                                   config=config or {})
            self._wandb = wandb
        except Exception as e:
            if warn is not None:
                warn(f"wandb sink disabled: {type(e).__name__}: {e}")
            self._run = None

    def log(self, step: int, metrics: Dict[str, float]):
        if self._run is None:
            return
        self._wandb.log(dict(metrics), step=step)

    def close(self):
        if self._run is not None:
            self._run.finish()


class MetricsLogger:
    def __init__(self):
        self._sinks: List = []

    def add_jsonl(self, path: str):
        self._sinks.append(JsonlSink(path))
        return self

    def add_tensorboard(self, log_dir: str, warn=None):
        self._sinks.append(TensorBoardSink(log_dir, warn=warn))
        return self

    def add_wandb(self, project: str, name: Optional[str] = None,
                  config: Optional[dict] = None, warn=None):
        self._sinks.append(WandbSink(project, name, config, warn=warn))
        return self

    def log(self, step: int, metrics: Dict[str, float]):
        clean = {k: (float(v) if hasattr(v, "__float__") else v)
                 for k, v in metrics.items()}
        for s in self._sinks:
            s.log(step, clean)

    def close(self):
        for s in self._sinks:
            s.close()
