"""Rerun state machine: result validation + step replay fault classification.

Parity with /root/reference/megatron/core/rerun_state_machine.py (1307 LoC):
- validates training results per step (NaN/Inf loss, loss spikes vs a
  running statistic — the reference's result validation);
- on a validation failure, REPLAYS the exact same step (same batch, same
  state) and compares: a different result on identical inputs ⇒ transient
  hardware fault (the chip mis-executed); an identical bad result ⇒
  deterministic cause (data/numerics/model) — the reference's
  rerun-to-classify logic;
- supports error injection for testing (reference RerunErrorInjector :1147,
  --error-injection-rate);
- its state (step counters, EMA) is checkpointable (state_dict parity).

The JAX replay is simpler than the reference's RNG/data capture: train steps
are pure functions of (state, batch), so replay = call again with the saved
inputs — determinism is the default on TPU/XLA.
"""

from __future__ import annotations

import dataclasses
import enum
import math
from typing import Any, Callable, Dict, Optional

from megatronapp_tpu.utils import chaos


class RerunDiagnostic(enum.Enum):
    """Classification of a validation failure (reference diagnostics)."""
    OK = "ok"
    TRANSIENT_FAULT = "transient_hardware_fault"
    PERSISTENT = "persistent_error"


@dataclasses.dataclass
class RerunStateMachine:
    """Wraps step execution with validation + replay classification."""

    # 'disabled' | 'validate_results' | 'report_stats' (reference
    # --rerun-mode, arguments.py:1795-1812).
    mode: str = "validate_results"
    loss_spike_factor: float = 10.0
    ema_decay: float = 0.95
    error_injection_rate: float = 0.0
    _ema_loss: Optional[float] = None
    _step: int = 0
    _injected: int = 0
    reports: list = dataclasses.field(default_factory=list)

    def validate(self, loss: float):
        """Returns (ok, effective_loss). effective_loss differs from the
        input only under error injection (the injected NaN must reach the
        caller's classification path, not just this check)."""
        self._step += 1
        if self.error_injection_rate > 0 and \
                self._step * self.error_injection_rate >= self._injected + 1:
            self._injected += 1
            loss = float("nan")  # injected fault for pipeline testing
        if chaos.should_fire("step-nan"):
            # Chaos-harness variant of the same injection point: armable
            # deterministically (nth validation) instead of rate-based.
            self._injected += 1
            loss = float("nan")
        if self.mode == "disabled":
            return True, loss
        if not math.isfinite(loss):
            return False, loss
        if self._ema_loss is not None and \
                loss > self.loss_spike_factor * self._ema_loss:
            return False, loss
        self._ema_loss = (loss if self._ema_loss is None else
                          self.ema_decay * self._ema_loss +
                          (1 - self.ema_decay) * loss)
        return True, loss

    def classify_failure(self, step_fn: Callable, state, batch,
                         bad_loss: float,
                         atol: float = 0.0) -> RerunDiagnostic:
        """Replay the failing step on identical inputs and compare
        (reference should_run_forward_backward rerun logic)."""
        import jax
        _, metrics = step_fn(state, batch)
        replay_loss = float(jax.device_get(metrics["loss"]))
        both_nan = (not math.isfinite(bad_loss)
                    and not math.isfinite(replay_loss))
        if both_nan or abs(replay_loss - bad_loss) <= atol:
            diag = RerunDiagnostic.PERSISTENT
        else:
            diag = RerunDiagnostic.TRANSIENT_FAULT
        self.reports.append({
            "step": self._step, "first_loss": bad_loss,
            "replay_loss": replay_loss, "diagnostic": diag.value,
        })
        return diag

    # -- checkpointable state (reference state_dict into common ckpt) ------
    def state_dict(self) -> Dict[str, Any]:
        return {"mode": self.mode, "ema_loss": self._ema_loss,
                "step": self._step, "injected": self._injected}

    def load_state_dict(self, sd: Dict[str, Any]):
        self.mode = sd.get("mode", self.mode)
        self._ema_loss = sd.get("ema_loss")
        self._step = sd.get("step", 0)
        self._injected = sd.get("injected", 0)


_RERUN = RerunStateMachine()


def get_rerun_state_machine() -> RerunStateMachine:
    return _RERUN
