"""Global-batch-size schedule: constant and linear-rampup calculators.

Parity with /root/reference/megatron/core/num_microbatches_calculator.py:
`--rampup-batch-size <start> <increment> <samples>` grows the global batch
from `start` to the configured global_batch_size in `increment` steps
spread evenly over `samples` consumed samples; every intermediate size must
divide by micro_batch_size * dp.

TPU note: each distinct global batch size is a distinct jitted step shape —
the schedule compiles num_increments+1 step variants over the ramp (bounded
and amortized; the reference pays the same in re-bucketed grad buffers).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass
class ConstantCalculator:
    global_batch_size: int
    micro_batch_size: int
    data_parallel: int

    def get(self, consumed_samples: int) -> Tuple[int, int]:
        """(current_global_batch_size, num_microbatches)."""
        denom = self.micro_batch_size * self.data_parallel
        return self.global_batch_size, self.global_batch_size // denom

    def stages(self):
        """All distinct (global_batch_size, num_microbatches) pairs the
        schedule will ever produce — for fail-fast validation against
        schedule constraints (e.g. interleaved pipeline M % pp)."""
        return [self.get(0)]


@dataclasses.dataclass
class RampupCalculator:
    """Linear batch-size rampup (reference
    RampupBatchsizeNumMicroBatchesCalculator)."""

    start_batch_size: int
    batch_size_increment: int
    rampup_samples: int
    global_batch_size: int
    micro_batch_size: int
    data_parallel: int

    def __post_init__(self):
        denom = self.micro_batch_size * self.data_parallel
        diff = self.global_batch_size - self.start_batch_size
        if diff < 0 or self.batch_size_increment <= 0 or \
                diff % self.batch_size_increment != 0:
            raise ValueError(
                f"rampup: global({self.global_batch_size}) - "
                f"start({self.start_batch_size}) must be a non-negative "
                f"multiple of increment({self.batch_size_increment})")
        for bs in range(self.start_batch_size, self.global_batch_size + 1,
                        self.batch_size_increment):
            if bs % denom != 0:
                raise ValueError(
                    f"rampup: intermediate batch size {bs} not divisible "
                    f"by micro_batch_size*dp={denom}")
        self._num_increments = max(diff // self.batch_size_increment, 1)
        self._samples_per_increment = (self.rampup_samples /
                                       self._num_increments)

    def get(self, consumed_samples: int) -> Tuple[int, int]:
        """(current_global_batch_size, num_microbatches) at this point in
        the sample stream (reference update())."""
        if consumed_samples >= self.rampup_samples:
            bs = self.global_batch_size
        else:
            steps = int(consumed_samples / self._samples_per_increment)
            bs = min(self.start_batch_size +
                     steps * self.batch_size_increment,
                     self.global_batch_size)
        denom = self.micro_batch_size * self.data_parallel
        return bs, bs // denom

    def stages(self):
        """All distinct (global_batch_size, num_microbatches) pairs over
        the ramp, start → final (see ConstantCalculator.stages)."""
        denom = self.micro_batch_size * self.data_parallel
        return [(bs, bs // denom)
                for bs in range(self.start_batch_size,
                                self.global_batch_size + 1,
                                self.batch_size_increment)]


def build_calculator(global_batch_size: int, micro_batch_size: int,
                     data_parallel: int,
                     rampup: Optional[Tuple[int, int, int]] = None):
    """rampup = (start, increment, samples) or None (reference
    --rampup-batch-size triplet)."""
    if rampup is None:
        return ConstantCalculator(global_batch_size, micro_batch_size,
                                  data_parallel)
    start, inc, samples = rampup
    return RampupCalculator(start, inc, samples, global_batch_size,
                            micro_batch_size, data_parallel)
