"""Signal-based graceful exit.

Parity with /root/reference/megatron/training/dist_signal_handler.py
(--exit-signal-handler): install a SIGTERM/SIGINT handler that flips a flag;
the train loop checks it each iteration, checkpoints, and exits cleanly.
"""

from __future__ import annotations

import signal
import threading
from typing import Iterable


class DistSignalHandler:
    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,)):
        self._signals = tuple(signals)
        self._received = threading.Event()
        self._prev = {}

    def __enter__(self):
        for sig in self._signals:
            self._prev[sig] = signal.signal(sig, self._handle)
        return self

    def __exit__(self, *exc):
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        return False

    def _handle(self, signum, frame):
        self._received.set()

    def signals_received(self) -> bool:
        return self._received.is_set()
