"""Signal-based graceful exit.

Parity with /root/reference/megatron/training/dist_signal_handler.py
(--exit-signal-handler): install a SIGTERM (optionally SIGINT) handler
that flips a flag; the train loop checks it each iteration, finishes the
in-flight step, force-saves an emergency checkpoint, and exits cleanly.

Multi-host safety (reference DistSignalHandler.signals_received does an
all_gather of the flag): the EXIT DECISION must be agreed across
processes — the emergency save is a collective, so one rank entering it
while the others keep training deadlocks the job. `should_exit()`
all-gathers the local flag and exits when ANY rank received the signal
(max-reduce semantics), so a preemption notice delivered to a single
host still drains the whole job.
"""

from __future__ import annotations

import signal
import threading
from typing import Iterable

import numpy as np


def any_process_flag(local: bool) -> bool:
    """Cluster-agreed boolean: True when ANY process's local flag is
    set (all-gather MAX). The shared primitive behind every collective
    go/no-go decision — graceful exit (should_exit), checkpoint save
    retry and restore walk-back (training/checkpointing.py) — where one
    rank acting alone on local information would enter (or skip) a
    collective the others don't, deadlocking the job. Collective under
    multi-host: every rank must call it at the same point. Plain local
    check on a single process."""
    import jax
    if jax.process_count() <= 1:
        return local
    from jax.experimental import multihost_utils
    flags = np.asarray(multihost_utils.process_allgather(
        np.asarray([local])))
    return bool(flags.any())


class DistSignalHandler:
    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,)):
        self._signals = tuple(signals)
        self._received = threading.Event()
        self._prev = {}

    @classmethod
    def for_config(cls, sigint: bool = False) -> "DistSignalHandler":
        """Handler for the train loop: SIGTERM always (the preemption
        notice), SIGINT opt-in (--exit-signal-handler-sigint — lets an
        interactive ^C drain through the same emergency-save path)."""
        sigs = [signal.SIGTERM]
        if sigint:
            sigs.append(signal.SIGINT)
        return cls(sigs)

    def __enter__(self):
        for sig in self._signals:
            self._prev[sig] = signal.signal(sig, self._handle)
        return self

    def __exit__(self, *exc):
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        return False

    def _handle(self, signum, frame):
        self._received.set()

    def signals_received(self) -> bool:
        """This process's local flag (no collective)."""
        return self._received.is_set()

    def should_exit(self) -> bool:
        """Cluster-agreed exit decision: True when ANY process received
        an exit signal. Collective under multi-host (every rank must
        call it at the same point each iteration — the train loop does);
        plain local check on a single process."""
        return any_process_flag(self._received.is_set())
